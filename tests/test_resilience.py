"""Cluster resilience layer (ISSUE 1): retry policy, per-worker circuit
breakers, reconciliation sweep, and honest failure propagation.

Fast deterministic tests run in tier-1; the probabilistic chaos jobs are
marked ``slow`` (``pytest tests/test_resilience.py -m slow``). The chaos
acceptance bar: with faults armed on worker RPCs, heartbeats, and
reconciles, the leader (a) never merges a failed worker batch as a
successful empty result, (b) converges the reconciliation sweep so no
document is double-counted after rejoin, and (c) drives breakers through
open/half-open/closed with retry counts bounded by injector fire
counters.
"""

import json
import socket
import urllib.error
import urllib.request

import pytest

from tfidf_tpu.cluster.coordination import CoordinationCore, LocalCoordination
from tfidf_tpu.cluster.node import SearchNode, http_get, http_post
from tfidf_tpu.cluster.resilience import (BreakerBoard, CircuitBreaker,
                                          CircuitOpenError, RetryPolicy,
                                          RpcStatusError, is_retryable,
                                          is_worker_fault)
from tfidf_tpu.utils.config import Config
from tfidf_tpu.utils.faults import (KNOWN_FAULT_POINTS, FaultInjected,
                                    FaultInjector, global_injector)
from tfidf_tpu.utils.metrics import global_metrics

from tests.test_cluster import wait_until


# ---------------------------------------------------------------------------
# RetryPolicy
# ---------------------------------------------------------------------------

class TestRetryPolicy:
    def _policy(self, **kw):
        self.sleeps = []
        kw.setdefault("jitter", 0.0)
        return RetryPolicy(sleep=self.sleeps.append, **kw)

    def test_retries_transient_then_succeeds(self):
        p = self._policy(max_attempts=3, base_delay_s=0.1)
        calls = {"n": 0}

        def fn():
            calls["n"] += 1
            if calls["n"] < 3:
                raise ConnectionResetError("blip")
            return "ok"

        assert p.call(fn) == "ok"
        assert calls["n"] == 3
        assert self.sleeps == [0.1, 0.2]   # exponential, no jitter

    def test_non_retryable_raises_immediately(self):
        p = self._policy(max_attempts=5)
        calls = {"n": 0}

        def fn():
            calls["n"] += 1
            raise ValueError("app bug")

        with pytest.raises(ValueError):
            p.call(fn)
        assert calls["n"] == 1 and self.sleeps == []

    def test_attempts_bounded_and_last_error_raised(self):
        p = self._policy(max_attempts=3)
        calls = {"n": 0}

        def fn():
            calls["n"] += 1
            raise ConnectionRefusedError(f"dead {calls['n']}")

        with pytest.raises(ConnectionRefusedError, match="dead 3"):
            p.call(fn)
        assert calls["n"] == 3 and len(self.sleeps) == 2

    def test_deadline_stops_early(self):
        now = [0.0]
        p = RetryPolicy(max_attempts=10, base_delay_s=1.0, jitter=0.0,
                        deadline_s=2.5, sleep=lambda s: None,
                        clock=lambda: now[0])
        calls = {"n": 0}

        def fn():
            calls["n"] += 1
            now[0] += 1.0   # each attempt takes 1s of fake time
            raise ConnectionResetError("slow")

        with pytest.raises(ConnectionResetError):
            p.call(fn)
        # attempt 1 (t=1) retries (1+1.0 <= 2.5), attempt 2 (t=2) would
        # need t=2 + 2.0 > 2.5 -> raises instead of sleeping
        assert calls["n"] == 2

    def test_backoff_caps_at_max_delay(self):
        p = RetryPolicy(base_delay_s=0.5, max_delay_s=1.0, jitter=0.0)
        assert p.backoff_delay(1) == 0.5
        assert p.backoff_delay(2) == 1.0
        assert p.backoff_delay(5) == 1.0

    def test_jitter_stays_in_band(self):
        p = RetryPolicy(base_delay_s=1.0, max_delay_s=8.0, jitter=0.25)
        for attempt in (1, 2, 3):
            base = min(8.0, 2.0 ** (attempt - 1))
            for _ in range(50):
                d = p.backoff_delay(attempt)
                assert base * 0.75 <= d <= base * 1.25

    def test_backoff_fault_point_fires(self):
        global_injector.arm("resilience.backoff", action="delay",
                            delay_s=0.0)
        p = self._policy(max_attempts=2)
        with pytest.raises(ConnectionResetError):
            p.call(lambda: (_ for _ in ()).throw(ConnectionResetError()))
        assert global_injector.fired.get("resilience.backoff") == 1


class TestClassifiers:
    def test_retryable(self):
        assert is_retryable(ConnectionResetError())
        # gateway-transient statuses retry; a deterministic 500 (e.g. a
        # worker engine crash on this batch) fails fast — retrying would
        # multiply the sick worker's engine load per scatter
        assert is_retryable(RpcStatusError("u", 503))
        assert not is_retryable(RpcStatusError("u", 500))
        assert not is_retryable(RpcStatusError("u", 415))
        assert is_retryable(FaultInjected("chaos"))
        assert not is_retryable(socket.timeout("slow"))
        assert not is_retryable(ValueError("app"))
        assert is_retryable(urllib.error.HTTPError("u", 503, "x", {}, None))
        assert not is_retryable(urllib.error.HTTPError("u", 500, "x", {},
                                                       None))
        assert not is_retryable(urllib.error.HTTPError("u", 404, "x", {},
                                                       None))

    def test_worker_fault(self):
        # 4xx = healthy worker refusing an application request
        assert not is_worker_fault(RpcStatusError("u", 415))
        assert not is_worker_fault(urllib.error.HTTPError("u", 404, "x",
                                                          {}, None))
        # timeouts and 5xx DO indict the worker (unlike retryability)
        assert is_worker_fault(socket.timeout("hung"))
        assert is_worker_fault(RpcStatusError("u", 500))
        assert is_worker_fault(ConnectionRefusedError())


# ---------------------------------------------------------------------------
# CircuitBreaker
# ---------------------------------------------------------------------------

class TestCircuitBreaker:
    def _breaker(self, threshold=3, reset=5.0):
        self.now = [0.0]
        return CircuitBreaker(failure_threshold=threshold, reset_s=reset,
                              clock=lambda: self.now[0], name="w")

    def test_full_lifecycle(self):
        b = self._breaker()
        for _ in range(2):          # below threshold: stays closed
            b.acquire()
            b.record_failure()
        assert b.state == "closed"
        b.acquire()
        b.record_failure()          # third consecutive: trips
        assert b.state == "open"
        with pytest.raises(CircuitOpenError):
            b.acquire()
        self.now[0] = 5.1           # past reset: half-open probe
        assert b.state == "half_open"
        b.acquire()                 # the probe
        with pytest.raises(CircuitOpenError):
            b.acquire()             # only ONE probe at a time
        b.record_success()
        assert b.state == "closed"
        b.acquire()                 # healthy again
        assert b.transitions == ["closed", "open", "half_open", "closed"]

    def test_probe_failure_reopens(self):
        b = self._breaker(threshold=1, reset=2.0)
        b.acquire()
        b.record_failure()
        assert b.state == "open"
        self.now[0] = 2.5
        b.acquire()                 # half-open probe
        b.record_failure()
        assert b.state == "open"    # re-opened, reset timer restarted
        with pytest.raises(CircuitOpenError):
            b.acquire()
        self.now[0] = 4.0           # 2.5 + 2.0 > 4.0: still open
        with pytest.raises(CircuitOpenError):
            b.acquire()
        self.now[0] = 4.6
        b.acquire()
        b.record_success()
        assert b.state == "closed"

    def test_success_resets_consecutive_count(self):
        b = self._breaker(threshold=2)
        b.record_failure()
        b.record_success()
        b.record_failure()          # 1 consecutive, not 2
        assert b.state == "closed"

    def test_is_open_is_non_consuming(self):
        b = self._breaker(threshold=1, reset=1.0)
        b.record_failure()
        self.now[0] = 1.5
        assert not b.is_open()      # would admit a probe...
        assert not b.is_open()      # ...and did not consume it
        b.acquire()
        assert b.is_open()          # probe slot taken now

    def test_board_prunes_departed_workers(self):
        board = BreakerBoard(failure_threshold=1, reset_s=60.0)
        board.breaker("http://a:1").record_failure()
        board.breaker("http://b:2")
        assert board.is_open("http://a:1")
        assert board.open_count() == 1
        board.prune({"http://b:2"})
        # the rejoining worker starts with a clean breaker
        assert not board.is_open("http://a:1")
        assert board.snapshot() == {"http://b:2": "closed"}

    def test_trip_fault_point_counts_but_never_raises(self):
        global_injector.arm("resilience.breaker_trip", action="raise")
        b = self._breaker(threshold=1)
        b.record_failure()          # must not propagate FaultInjected
        assert b.state == "open"
        assert global_injector.fired.get("resilience.breaker_trip") == 1


# ---------------------------------------------------------------------------
# Fault-point tooling (satellite: chaos configs can't go stale)
# ---------------------------------------------------------------------------

class TestFaultTooling:
    def test_wildcard_rules_match_prefix(self):
        inj = FaultInjector()
        inj.arm("coord.heartbeat.*", action="raise")
        with pytest.raises(FaultInjected):
            inj.check("coord.heartbeat.7")
        inj.check("coord.other")   # no match, no fire
        assert inj.fired == {"coord.heartbeat.*": 1}

    # The PR 1 grep-based anti-stale test lived here; it is superseded
    # by the graftcheck registry-drift pass (tools/graftcheck), which
    # checks BOTH directions — every call site registered AND every
    # registry entry backed by a call site — and also sees the
    # CircuitBreaker._observe indirection the grep missed. Enforced by
    # tests/test_graftcheck.py::TestRealTree::test_registry_drift_fault_points
    # and the CI graftcheck job.

    def test_faults_list_cli(self, capsys):
        from tfidf_tpu.cli import main

        assert main(["faults", "list"]) == 0
        out = capsys.readouterr().out
        for name in KNOWN_FAULT_POINTS:
            assert name in out


# ---------------------------------------------------------------------------
# Cluster fixtures
# ---------------------------------------------------------------------------

@pytest.fixture
def core():
    c = CoordinationCore(session_timeout_s=0.5)
    yield c
    c.close()


DOCS = {f"rz{i}.txt": f"common token{i} word{i % 3}" for i in range(12)}

_RESILIENCE_CFG = dict(
    top_k=32, min_doc_capacity=64, min_nnz_capacity=1 << 12,
    min_vocab_capacity=1 << 10, query_batch=8, max_query_terms=8,
    rpc_max_attempts=1,           # deterministic: no hidden retries
    breaker_failure_threshold=2, breaker_reset_s=0.4,
    reconcile_sweep_interval_s=0.2,
    # single-copy placement: this suite pins the PRE-replication
    # degraded/recovery semantics (R-way failover has its own suite,
    # tests/test_replication.py)
    replication_factor=1,
    # no result cache: these tests re-issue identical queries around
    # armed faults and count the resulting scatter RPCs/breaker fires
    # — a cache hit would (correctly) skip the fan-out and mask them
    # (the cache has its own suite, tests/test_admission.py)
    result_cache_entries=0)


def _node(core, tmp_path, i, port=0, **kw):
    cfg_kw = dict(_RESILIENCE_CFG)
    cfg_kw.update(kw)
    cfg = Config(
        documents_path=str(tmp_path / f"rz{i}" / "documents"),
        index_path=str(tmp_path / f"rz{i}" / "index"),
        port=port, **cfg_kw)
    return SearchNode(cfg, coord=LocalCoordination(core, 0.1)).start()


def _mk_cluster(core, tmp_path, n=3, **kw):
    nodes = [_node(core, tmp_path, i, **kw) for i in range(n)]
    wait_until(lambda: len(
        nodes[0].registry.get_all_service_addresses()) == n - 1)
    return nodes


def _stop_all(nodes):
    for nd in nodes:
        try:
            nd.stop()
        except Exception:
            pass


def _upload_docs(leader, docs=DOCS):
    batch = [{"name": n, "text": t} for n, t in docs.items()]
    http_post(leader.url + "/leader/upload-batch",
              json.dumps(batch).encode())


def _search(leader, q):
    return json.loads(http_post(
        leader.url + "/leader/start", json.dumps({"query": q}).encode()))


# ---------------------------------------------------------------------------
# Honest failure propagation
# ---------------------------------------------------------------------------

class TestHonestFailurePropagation:
    def test_process_batch_failure_is_non_2xx(self, core, tmp_path):
        """ADVICE r5: an engine failure must surface as a 5xx, never as
        an HTTP 200 all-empty reply the leader merges as a valid
        zero-hit result."""
        nodes = _mk_cluster(core, tmp_path, n=2)
        try:
            leader, worker = nodes
            _upload_docs(leader)
            assert _search(leader, "common")   # sanity: healthy path

            def broken(queries, k=None, unbounded=False):
                raise ValueError("engine exploded")

            # break BOTH batch entrypoints: the wire fast path serves
            # from search_batch_arrays, the fallback from search_batch
            worker.engine.search_batch = broken
            worker.engine.search_batch_arrays = broken
            with pytest.raises(urllib.error.HTTPError) as ei:
                http_post(worker.url + "/worker/process-batch",
                          json.dumps({"queries": ["common"],
                                      "k": 10}).encode())
            assert ei.value.code == 500
            assert global_metrics.get("worker_batch_failures") >= 1
        finally:
            _stop_all(nodes)

    def test_leader_counts_failed_batch_not_empty_merge(self, core,
                                                        tmp_path):
        """The failed worker's shard drops out AND is counted: the merge
        keeps the healthy worker's hits, scatter_failures increments,
        and the reply carries the degraded marker."""
        nodes = _mk_cluster(core, tmp_path, n=3)
        try:
            leader, w1, w2 = nodes
            _upload_docs(leader)
            full = set(_search(leader, "common"))
            assert full == set(DOCS)
            victim = w1
            victim_names = {n for n, ws in leader._placement.items()
                            if victim.url in ws}
            assert victim_names and victim_names != set(DOCS)

            def broken(queries, k=None, unbounded=False):
                raise ValueError("engine exploded")

            victim.engine.search_batch = broken
            victim.engine.search_batch_arrays = broken
            before = global_metrics.get("scatter_failures")
            req = urllib.request.Request(
                leader.url + "/leader/start",
                data=json.dumps({"query": "common"}).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=30) as resp:
                marker = resp.headers.get("X-Scatter-Degraded")
                res = json.loads(resp.read())
            # healthy shard answered; failed shard is absent, not empty
            assert set(res) == full - victim_names
            assert global_metrics.get("scatter_failures") > before
            assert marker is not None and "attempted=2" in marker
            assert global_metrics.get("scatter_degraded") == 1
            snap = json.loads(http_get(leader.url + "/api/metrics"))
            assert snap["scatter_last_responded"] == 1
        finally:
            _stop_all(nodes)


# ---------------------------------------------------------------------------
# Circuit breaker end to end (acceptance c, deterministic variant)
# ---------------------------------------------------------------------------

class TestBreakerEndToEnd:
    def test_open_halfopen_close_with_bounded_fires(self, core, tmp_path):
        # reset_s wide enough that a suite-load-slowed search cannot
        # reach the half-open window mid-test and admit a probe RPC —
        # the exact fire-count asserts below depend on it
        nodes = _mk_cluster(core, tmp_path, n=3, breaker_reset_s=2.0)
        try:
            leader = nodes[0]
            _upload_docs(leader)
            workers = leader.registry.get_all_service_addresses()
            full = set(_search(leader, "common"))

            global_injector.arm("leader.worker_rpc", action="raise")
            # threshold=2, attempts=1: two failed queries trip BOTH
            # workers' breakers...
            for _ in range(2):
                assert _search(leader, "common") == {}
            fired = global_injector.fired["leader.worker_rpc"]
            assert fired == 2 * len(workers)   # one per (query, worker)
            assert all(leader.resilience.board.is_open(w)
                       for w in workers)
            assert global_metrics.get("breaker_opened") >= 2
            # ...and the NEXT query fast-fails without any RPC attempt:
            # the fire counter must not move (bounded retries)
            assert _search(leader, "common") == {}
            assert global_injector.fired["leader.worker_rpc"] == fired
            assert global_metrics.get("scatter_circuit_open") >= 2
            assert global_metrics.get("scatter_degraded") == 1

            # fault heals; after reset_s the half-open probes succeed
            # and the breakers close: full results again
            global_injector.disarm("leader.worker_rpc")
            assert wait_until(
                lambda: set(_search(leader, "common")) == full,
                timeout=5.0)
            assert global_metrics.get("breaker_closed") >= 2
            assert global_metrics.get("breaker_probes") >= 2
            for w in workers:
                b = leader.resilience.board.breaker(w)
                assert b.transitions[-3:] == ["open", "half_open",
                                              "closed"]
            assert global_metrics.get("scatter_degraded") == 0
        finally:
            _stop_all(nodes)


# ---------------------------------------------------------------------------
# Reconciliation sweep (tentpole + satellite regression test)
# ---------------------------------------------------------------------------

class TestReconcileSweep:
    def test_failed_reconcile_retried_by_sweep_no_double_count(
            self, core, tmp_path):
        """Regression for ADVICE r5 medium (node.py:692): kill the
        /worker/delete RPC at the rejoin, assert (1) merged scores never
        double-count the moved documents even while the reconcile is
        pending (merge-time exclusion), and (2) the periodic sweep —
        not a membership event — converges the cluster back to
        single-copy."""
        nodes = _mk_cluster(core, tmp_path)
        leader = nodes[0]
        try:
            _upload_docs(leader)
            assert set(_search(leader, "common")) == set(DOCS)

            victim = nodes[1]
            victim_port = victim.port
            victim_names = {n for n, ws in leader._placement.items()
                            if victim.url in ws}
            assert victim_names
            # kill the victim; recovery re-places its shard
            victim.httpd.shutdown()
            victim.httpd.server_close()
            core.expire_session(victim.coord.sid)
            assert wait_until(
                lambda: set(_search(leader, "common")) == set(DOCS)
                and {w for ws in leader._placement.values()
                     for w in ws} == {nodes[2].url}, timeout=10.0)
            want = _search(leader, "common")

            # arm: EVERY /worker/delete dies (covers the join-event
            # reconcile and any sweep pass while armed)
            global_injector.arm("leader.reconcile_rpc", action="raise")
            revived = _node(core, tmp_path, 1, port=victim_port)
            nodes.append(revived)
            assert wait_until(lambda: sorted(
                leader.registry.get_all_service_addresses())
                == sorted([nodes[2].url, revived.url]), timeout=5.0)
            # the join-event reconcile has failed by the time a sweep
            # retry fires; _moved still pending either way
            assert wait_until(
                lambda: global_injector.fired.get(
                    "leader.reconcile_rpc", 0) >= 1, timeout=5.0)
            with leader._placement_lock:
                assert leader._moved.get(revived.url) == victim_names

            # double-count window CLOSED while pending: the rejoiner's
            # boot re-walk serves the moved docs, but the merge excludes
            # them until the reconcile lands. EVERY search's scores must
            # be exact; the exclusion counter ticks only once the
            # revived worker's hits actually flow (its predecessor's
            # half-open breaker at the same URL may eat the first
            # scatter or two under load — wait for the real signal
            # instead of assuming a fixed number of searches).
            def exclusion_observed():
                scores = _search(leader, "common")
                assert scores.keys() == want.keys()
                for n in want:
                    assert scores[n] == pytest.approx(want[n], rel=1e-6)
                return global_metrics.get("scatter_hits_excluded") > 0
            assert wait_until(exclusion_observed, timeout=8.0)
            assert global_metrics.get("reconcile_failures") >= 1

            # heal the RPC: the SWEEP (timer, no membership event left
            # to fire) must converge the reconcile
            global_injector.disarm("leader.reconcile_rpc")

            def converged():
                with leader._placement_lock:
                    if leader._moved.get(revived.url):
                        return False
                return True
            assert wait_until(converged, timeout=5.0)
            assert global_metrics.get("reconcile_sweep_retries") >= 1
            assert global_metrics.get("reconciles_completed") >= 1
            # the moved docs are really deleted from the rejoiner, and
            # the merged scores still match (single copy, no exclusion
            # needed anymore)
            deleted = json.loads(http_post(
                revived.url + "/worker/delete",
                json.dumps({"names": sorted(victim_names)}).encode()))
            assert deleted["deleted"] == 0   # already gone
            scores = _search(leader, "common")
            for n in want:
                assert scores[n] == pytest.approx(want[n], rel=1e-6)
        finally:
            _stop_all(nodes)


# ---------------------------------------------------------------------------
# Compile-flake retry gate (satellite)
# ---------------------------------------------------------------------------

class TestCompileRetryGate:
    def _node(self, core, tmp_path):
        return _node(core, tmp_path, 0, compile_retry_per_bucket=1)

    def test_unrelated_compile_substring_not_retried(self, core,
                                                     tmp_path):
        """The old gate retried ANY error whose repr contains 'compile';
        the narrowed gate requires the known transient signature."""
        node = self._node(core, tmp_path)
        try:
            node.engine.ingest_text("a.txt", "needle body")
            node.engine.commit()
            calls = {"n": 0}

            def broken(queries, k=None, unbounded=False):
                calls["n"] += 1
                raise ValueError("cannot compile the scoring plan")

            node.engine.search_batch = broken
            with pytest.raises(ValueError):
                node.worker_search_batch(["needle"])
            assert calls["n"] == 1   # no blind retry
        finally:
            node.stop()

    def test_per_bucket_budget_stops_deterministic_retries(self, core,
                                                           tmp_path):
        node = self._node(core, tmp_path)
        try:
            node.engine.ingest_text("a.txt", "needle body")
            node.engine.commit()
            calls = {"n": 0}

            def always_500(queries, k=None, unbounded=False):
                calls["n"] += 1
                raise RuntimeError(
                    "INTERNAL: remote_compile: HTTP 500: "
                    "tpu_compile_helper subprocess exit code 1")

            orig = node.engine.search_batch
            node.engine.search_batch = always_500
            # first batch at this bucket: one retry (budget -> 0)
            with pytest.raises(RuntimeError):
                node.worker_search_batch(["needle"])
            assert calls["n"] == 2
            # deterministic failure: budget spent, NO further retries
            with pytest.raises(RuntimeError):
                node.worker_search_batch(["needle"])
            assert calls["n"] == 3
            # a different bucket size has its own budget
            with pytest.raises(RuntimeError):
                node.worker_search_batch(["needle", "x", "y"])
            assert calls["n"] == 5
            # success refills: a later transient at the bucket retries
            node.engine.search_batch = orig
            assert node.worker_search_batch(["needle"])
            node.engine.search_batch = always_500
            calls["n"] = 0
            with pytest.raises(RuntimeError):
                node.worker_search_batch(["needle"])
            assert calls["n"] == 2
        finally:
            node.stop()


# ---------------------------------------------------------------------------
# Coordination loops
# ---------------------------------------------------------------------------

class TestCoordinationResilience:
    def test_heartbeat_send_retried_within_interval(self, core):
        """Two consecutive send failures must not cost the session two
        whole heartbeat intervals of its timeout budget: the retry
        policy resends within the same cycle and the session lives."""
        client = LocalCoordination(core, 0.05)
        try:
            global_injector.arm("coord.heartbeat_send", action="raise",
                                times=2)
            assert wait_until(
                lambda: global_injector.fired.get(
                    "coord.heartbeat_send", 0) >= 2, timeout=3.0)
            import time as _t
            _t.sleep(2 * core.session_timeout_s)
            # session survived: still listed, no expiry event
            assert client.sid in core._sessions
            assert global_metrics.get("coord_heartbeat_retries") >= 2
        finally:
            client.close()


# ---------------------------------------------------------------------------
# Chaos jobs (slow): probabilistic fault injection across the plane
# ---------------------------------------------------------------------------

@pytest.mark.slow
class TestChaos:
    def test_chaos_scatter_heartbeats_and_reconciles(self, core,
                                                     tmp_path):
        """Acceptance: probabilistic faults on worker RPCs, heartbeats,
        and reconciles. The leader must (a) count every failed batch
        instead of merging empties, (b) keep merged scores single-copy
        at all times, (c) bound retries (injector fire counters) and
        recover to full, non-degraded results once the chaos stops."""
        nodes = _mk_cluster(core, tmp_path,
                            rpc_max_attempts=2, breaker_reset_s=0.3)
        leader = nodes[0]
        try:
            _upload_docs(leader)
            full = _search(leader, "common")
            assert set(full) == set(DOCS)
            workers = leader.registry.get_all_service_addresses()

            global_injector.arm("leader.worker_rpc", action="raise",
                                probability=0.3)
            global_injector.arm("coord.heartbeat_send", action="raise",
                                probability=0.3)
            global_injector.arm("leader.reconcile_rpc", action="raise",
                                probability=0.5)
            global_injector.arm("resilience.backoff", action="delay",
                                delay_s=0.0)

            n_queries = 40
            for i in range(n_queries):
                res = _search(leader, "common")
                # honesty: partial/empty results only ever co-occur with
                # counted failures or open breakers
                if set(res) != set(full):
                    assert (global_metrics.get("scatter_failures") > 0
                            or global_metrics.get(
                                "scatter_circuit_open") > 0)
                # single-copy invariant: no score ever EXCEEDS the
                # healthy value (double-count would inflate it)
                for n, s in res.items():
                    assert s <= full[n] * (1 + 1e-6)

            # bounded retries: each logical RPC fires the fault point at
            # most rpc_max_attempts times
            max_rpcs = n_queries * len(workers)
            fired = global_injector.fired.get("leader.worker_rpc", 0)
            assert fired <= max_rpcs * 2
            # every backoff sleep follows SOME injected failure (the
            # heartbeat retry loop shares the backoff fault point)
            backoffs = global_injector.fired.get("resilience.backoff", 0)
            all_failures = sum(
                global_injector.fired.get(p, 0)
                for p in ("leader.worker_rpc", "coord.heartbeat_send",
                          "leader.reconcile_rpc"))
            assert backoffs <= all_failures

            # chaos off: cluster converges to healthy, non-degraded
            global_injector.disarm()

            def healthy():
                res = _search(leader, "common")
                return (set(res) == set(full)
                        and global_metrics.get("scatter_degraded") == 0)
            assert wait_until(healthy, timeout=10.0)
            for n, s in _search(leader, "common").items():
                assert s == pytest.approx(full[n], rel=1e-6)
        finally:
            _stop_all(nodes)

    def test_chaos_rejoin_sweep_converges(self, core, tmp_path):
        """Worker death + rejoin under a flaky /worker/delete: the sweep
        must converge to single-copy despite 70%-lossy reconciles, and
        scores must never double-count at any observation point."""
        nodes = _mk_cluster(core, tmp_path)
        leader = nodes[0]
        try:
            _upload_docs(leader)
            victim = nodes[1]
            victim_port = victim.port
            victim.httpd.shutdown()
            victim.httpd.server_close()
            core.expire_session(victim.coord.sid)
            assert wait_until(
                lambda: set(_search(leader, "common")) == set(DOCS)
                and {w for ws in leader._placement.values()
                     for w in ws} == {nodes[2].url}, timeout=10.0)
            want = _search(leader, "common")

            global_injector.arm("leader.reconcile_rpc", action="raise",
                                probability=0.7)
            revived = _node(core, tmp_path, 1, port=victim_port)
            nodes.append(revived)

            def converged():
                scores = _search(leader, "common")
                assert scores.keys() == want.keys()
                for n in want:   # never double-counted, converged or not
                    assert scores[n] == pytest.approx(want[n], rel=1e-6)
                with leader._placement_lock:
                    return not leader._moved.get(revived.url)
            assert wait_until(converged, timeout=20.0, interval=0.1)
            # a reconcile really completed (the fault is probabilistic,
            # so it may or may not have fired first — the deterministic
            # retry-through-failure path is pinned by TestReconcileSweep)
            assert global_metrics.get("reconciles_completed") >= 1
        finally:
            _stop_all(nodes)

"""Overload-survival front door: admission control, priority lanes,
load shedding, backpressure, and generation-keyed result caching.

The acceptance story: under a closed-loop overload the leader sheds
with an explicit ``429 + Retry-After`` instead of queueing unboundedly;
bulk traffic can never starve interactive (weighted dequeue, and bulk
sheds first under backpressure); ``/api/health`` and ``/api/metrics``
stay responsive while the cluster sheds; and every ADMITTED result is
exact — the generation-keyed result cache misses after any commit that
changes the df signature (upsert, delete, migration flip), proven
against a single-node oracle under a concurrent write workload.

The slow chaos job (``make chaos-overload``) adds a 2x-overload
zipfian closed loop with a real mid-run worker ``kill -9``: shed rate
rises, p99 of admitted interactive queries stays bounded, parity holds.
"""

import json
import random
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
from concurrent.futures import ThreadPoolExecutor

import pytest

from tfidf_tpu.cluster.admission import (LANE_BULK, LANE_INTERACTIVE,
                                         AdmissionController, ResultCache,
                                         TokenBucket)
from tfidf_tpu.cluster.batcher import Coalescer, _Waiter
from tfidf_tpu.cluster.coordination import (CoordinationCore,
                                            LocalCoordination)
from tfidf_tpu.cluster.node import SearchNode, http_get, http_post
from tfidf_tpu.cluster.resilience import (ClusterResilience, RetryPolicy,
                                          RpcStatusError, is_retryable,
                                          is_worker_fault, retry_after_of)
from tfidf_tpu.engine.engine import Engine
from tfidf_tpu.utils.config import Config
from tfidf_tpu.utils.metrics import global_metrics

from tests.test_cluster import wait_until


@pytest.fixture
def core():
    c = CoordinationCore(session_timeout_s=0.5)
    yield c
    c.close()


DOCS = {f"ad{i}.txt": f"common token{i} word{i % 3} extra{i % 5}"
        for i in range(12)}
QUERIES = ["common", "token3 word0", "word1 extra2", "common token7"]

_CFG = dict(
    top_k=32, min_doc_capacity=64, min_nnz_capacity=1 << 12,
    min_vocab_capacity=1 << 10, query_batch=8, max_query_terms=8,
    rpc_max_attempts=1,            # deterministic: no hidden retries
    breaker_failure_threshold=2, breaker_reset_s=0.4,
    reconcile_sweep_interval_s=0.2, placement_flush_ms=10.0,
    # admission defaults for the HTTP tests: rate limiting OFF (each
    # test arms what it exercises), watermarks far away
    admission_rate_qps=0.0, admission_queue_high_water=10_000,
    admission_queue_critical=100_000)


def _node(core, tmp_path, i, port=0, **kw):
    cfg_kw = dict(_CFG)
    cfg_kw.update(kw)
    cfg = Config(
        documents_path=str(tmp_path / f"ad{i}" / "documents"),
        index_path=str(tmp_path / f"ad{i}" / "index"),
        port=port, **cfg_kw)
    return SearchNode(cfg, coord=LocalCoordination(core, 0.1)).start()


def _mk_cluster(core, tmp_path, n=3, **kw):
    nodes = [_node(core, tmp_path, i, **kw) for i in range(n)]
    wait_until(lambda: len(
        nodes[0].registry.get_all_service_addresses()) == n - 1)
    return nodes


def _stop_all(nodes):
    for nd in nodes:
        try:
            nd.stop()
        except Exception:
            pass


def _upload_docs(leader, docs=DOCS):
    batch = [{"name": n, "text": t} for n, t in docs.items()]
    return json.loads(http_post(leader.url + "/leader/upload-batch",
                                json.dumps(batch).encode()))


def _search(leader, q, headers=None):
    return json.loads(http_post(
        leader.url + "/leader/start", json.dumps({"query": q}).encode(),
        headers=headers))


def _oracle(tmp_path, docs=DOCS, queries=QUERIES, tag="oracle", **cfg_kw):
    kw = {k: v for k, v in _CFG.items()
          if k in ("top_k", "min_doc_capacity", "min_nnz_capacity",
                   "min_vocab_capacity", "query_batch",
                   "max_query_terms")}
    kw.update(cfg_kw)
    cfg = Config(documents_path=str(tmp_path / tag / "documents"),
                 index_path=str(tmp_path / tag / "index"), **kw)
    eng = Engine(cfg)
    for n, t in docs.items():
        eng.ingest_text(n, t)
    eng.commit()
    out = {}
    for q in queries:
        out[q] = {h.name: float(h.score)
                  for h in eng.search(q, k=cfg.top_k)}
    return out


def _assert_parity(got: dict, want: dict, ctx=""):
    assert set(got) == set(want), \
        f"{ctx}: missing={set(want) - set(got)} extra={set(got) - set(want)}"
    for n, s in want.items():
        assert got[n] == pytest.approx(s, rel=1e-5), (ctx, n, got[n], s)


def _settle_signature(leader, timeout=5.0):
    """Wait until the leader's df-signature token stops advancing (all
    in-flight replica upload legs confirmed): cache-hit assertions need
    a quiescent generation, or a late second-leg confirmation between
    two searches turns an expected hit into an honest (but
    miscounted-by-the-test) miss."""
    def quiet():
        t1 = leader.df_signature()
        time.sleep(0.1)
        return leader.df_signature() == t1
    assert wait_until(quiet, timeout=timeout)


def _parity_settles(leader, q, want, ctx="", timeout=10.0):
    """wait_until-compatible exact-parity convergence: mismatches while
    replica legs land read as not-yet, the FINAL state must hold."""
    def ok():
        try:
            _assert_parity(_search(leader, q), want, ctx)
            return True
        except AssertionError:
            return False
    assert wait_until(ok, timeout=timeout), \
        f"{ctx}: never converged to oracle parity"


def _shed_info(err: urllib.error.HTTPError) -> tuple[float, str, dict]:
    """(retry_after_s, X-Shed-Reason, body) from a 429 reply."""
    assert err.code == 429
    ra = float(err.headers.get("Retry-After"))
    body = json.loads(err.read().decode())
    return ra, err.headers.get("X-Shed-Reason"), body


# ---------------------------------------------------------------------------
# Token bucket + admission controller units
# ---------------------------------------------------------------------------

class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t


class TestTokenBucket:
    def test_burst_then_honest_retry_after(self):
        clk = FakeClock()
        b = TokenBucket(rate=2.0, burst=3.0, clock=clk)
        assert [b.try_take(clk()) for _ in range(3)] == [0.0, 0.0, 0.0]
        wait = b.try_take(clk())
        assert wait > 0.0
        # the hint is honest: waiting exactly that long buys admission
        clk.t += wait
        assert b.try_take(clk()) == 0.0
        # ... and not a microsecond less
        wait2 = b.try_take(clk())
        assert wait2 == pytest.approx(0.5, rel=1e-6)   # 1 token / 2 qps

    def test_refill_caps_at_burst(self):
        clk = FakeClock()
        b = TokenBucket(rate=1.0, burst=2.0, clock=clk)
        clk.t += 100.0   # long idle: tokens cap at burst, not 100
        assert b.try_take(clk()) == 0.0
        assert b.try_take(clk()) == 0.0
        assert b.try_take(clk()) > 0.0


def _admission(depth=0.0, **kw):
    cfg_kw = dict(admission_enabled=True, admission_rate_qps=0.0,
                  admission_burst=0.0, admission_queue_high_water=8,
                  admission_queue_critical=32,
                  admission_retry_after_s=0.25, admission_max_clients=64)
    cfg_kw.update(kw)
    clk = FakeClock()
    holder = {"depth": depth}
    ctl = AdmissionController(Config(**cfg_kw),
                              depth_fn=lambda: holder["depth"], clock=clk)
    return ctl, holder, clk


class TestAdmissionController:
    def test_backpressure_sheds_bulk_first_then_interactive(self):
        ctl, depth, _ = _admission()
        # below high water: everyone admitted
        depth["depth"] = 7
        assert ctl.admit("c", LANE_BULK).admitted
        assert ctl.admit("c", LANE_INTERACTIVE).admitted
        # at high water: bulk sheds, interactive survives
        depth["depth"] = 8
        d = ctl.admit("c", LANE_BULK)
        assert not d.admitted and d.reason == "backpressure"
        assert d.retry_after_s == pytest.approx(0.25)
        assert ctl.admit("c", LANE_INTERACTIVE).admitted
        # at critical: interactive sheds too
        depth["depth"] = 32
        assert not ctl.admit("c", LANE_INTERACTIVE).admitted
        assert global_metrics.get("admission_shed_backpressure") == 2
        assert global_metrics.get("admission_shed_bulk") == 1
        assert global_metrics.get("admission_shed_interactive") == 1

    def test_rate_limit_is_per_client(self):
        ctl, _, clk = _admission(admission_rate_qps=1.0,
                                 admission_burst=1.0)
        assert ctl.admit("hog").admitted
        d = ctl.admit("hog")
        assert not d.admitted and d.reason == "rate_limited"
        assert 0.0 < d.retry_after_s <= 1.0
        # a different client is untouched by the hog's bucket
        assert ctl.admit("polite").admitted
        # honoring the hint buys admission
        clk.t += d.retry_after_s
        assert ctl.admit("hog").admitted

    def test_disabled_admits_everything(self):
        ctl, depth, _ = _admission(admission_enabled=False)
        depth["depth"] = 10_000
        assert ctl.admit("c", LANE_BULK).admitted

    def test_client_buckets_lru_bounded(self):
        ctl, _, _ = _admission(admission_rate_qps=1.0,
                               admission_max_clients=2)
        for i in range(10):
            ctl.admit(f"client{i}")
        assert len(ctl._buckets) <= 2
        assert global_metrics.get("admission_clients") <= 2

    def test_zero_watermark_disables_that_tier(self):
        ctl, depth, _ = _admission(admission_queue_high_water=0,
                                   admission_queue_critical=0)
        depth["depth"] = 1_000_000
        assert ctl.admit("c", LANE_BULK).admitted
        assert ctl.admit("c", LANE_INTERACTIVE).admitted


# ---------------------------------------------------------------------------
# Weighted two-lane dequeue: bulk can never starve interactive
# ---------------------------------------------------------------------------

def _stopped_coalescer(**kw):
    """A Coalescer with its dispatchers joined: _form_batch_locked can
    then be driven deterministically against hand-stuffed queues."""
    c = Coalescer(lambda items: [None] * len(items), **kw)
    c.stop()
    return c


def _stuff(c, interactive=0, bulk=0, key=None):
    for i in range(interactive):
        w = _Waiter(f"i{i}", lane=0)
        w.key = key
        c._items.append(w)
    for i in range(bulk):
        w = _Waiter(f"b{i}", lane=1)
        w.key = key
        c._bulk.append(w)


class TestWeightedDequeue:
    def test_interactive_head_always_first(self):
        """THE no-starvation invariant: whenever any interactive item is
        queued, the formed batch leads with it — a round can never serve
        bulk while interactive waits, so bulk starving interactive is
        impossible by construction."""
        c = _stopped_coalescer(max_batch=4, bulk_share=0.25)
        _stuff(c, interactive=1, bulk=50)
        batch = c._form_batch_locked()
        assert batch[0].lane == 0

    def test_bulk_share_reserved_under_interactive_saturation(self):
        c = _stopped_coalescer(max_batch=8, bulk_share=0.25)
        _stuff(c, interactive=20, bulk=20)
        batch = c._form_batch_locked()
        assert len(batch) == 8
        lanes = [w.lane for w in batch]
        # interactive fills first, but 25% of slots went to bulk —
        # neither lane starves the other
        assert lanes.count(0) == 6 and lanes.count(1) == 2
        assert lanes[0] == 0

    def test_unused_reservation_returns_to_interactive(self):
        c = _stopped_coalescer(max_batch=8, bulk_share=0.25)
        _stuff(c, interactive=20, bulk=0)
        batch = c._form_batch_locked()
        assert [w.lane for w in batch] == [0] * 8

    def test_bulk_fills_batch_when_interactive_idle(self):
        c = _stopped_coalescer(max_batch=8, bulk_share=0.25)
        _stuff(c, interactive=0, bulk=20)
        batch = c._form_batch_locked()
        assert [w.lane for w in batch] == [1] * 8

    def test_backlog_is_live_and_discounts_one_batch(self):
        """The stall-proof backpressure input: ``backlog()`` reads the
        deques directly (the ``last_*_queue_depth`` gauge freezes while
        every dispatcher blocks inside a stalled batch_fn RPC), minus
        one batch's worth — a healthy linger window legitimately holds
        up to max_batch items the next round will take."""
        c = _stopped_coalescer(max_batch=4)
        assert c.backlog() == 0
        _stuff(c, interactive=3, bulk=1)
        assert c.backlog() == 0   # exactly one batch: healthy
        _stuff(c, interactive=5)
        assert c.backlog() == 5   # beyond a batch: genuine overload

    def test_group_key_homogeneity_holds_across_lanes(self):
        c = _stopped_coalescer(max_batch=8, bulk_share=0.5,
                               group_key=lambda item: item)
        _stuff(c, interactive=2, key="epoch1")
        w = _Waiter("bx", lane=1)
        w.key = "epoch2"   # different submit-time key: must not join
        c._bulk.append(w)
        batch = c._form_batch_locked()
        assert [x.query for x in batch] == ["i0", "i1"]
        assert len(c._bulk) == 1

    def test_live_two_lane_traffic_all_complete(self):
        """Liveness end to end: sustained interactive pressure does not
        starve bulk, and every submit (both lanes) completes."""
        seen = []
        lock = threading.Lock()

        def batch_fn(items):
            with lock:
                seen.append(list(items))
            return [f"r:{q}" for q in items]

        c = Coalescer(batch_fn, max_batch=4, linger_s=0.001,
                      pipeline=1, name="lane_live", bulk_share=0.25)
        try:
            with ThreadPoolExecutor(16) as pool:
                bulk = [pool.submit(c.submit, f"b{i}", 1)
                        for i in range(24)]
                inter = [pool.submit(c.submit, f"i{i}", 0)
                         for i in range(24)]
                assert sorted(f.result(timeout=10) for f in inter) == \
                    sorted(f"r:i{i}" for i in range(24))
                assert sorted(f.result(timeout=10) for f in bulk) == \
                    sorted(f"r:b{i}" for i in range(24))
        finally:
            c.stop()
        assert global_metrics.get("last_lane_live_bulk_depth", -1) >= 0


# ---------------------------------------------------------------------------
# Result cache unit
# ---------------------------------------------------------------------------

class TestResultCacheUnit:
    def test_hit_miss_and_generation_invalidation(self):
        rc = ResultCache(8)
        assert rc.get("q", (0, 0)) is None
        rc.put("q", (0, 0), {"a": 1.0})
        assert rc.get("q", (0, 0)) == {"a": 1.0}
        # ANY token component change kills the entry on touch
        assert rc.get("q", (0, 1)) is None
        assert len(rc) == 0
        assert global_metrics.get("cache_hits") == 1
        assert global_metrics.get("cache_misses") == 2
        assert global_metrics.get("cache_invalidations") == 1

    def test_lru_eviction_bounded(self):
        rc = ResultCache(2)
        for i in range(5):
            rc.put(f"q{i}", (0, 0), i)
        assert len(rc) == 2
        assert global_metrics.get("cache_evictions") == 3
        assert rc.get("q4", (0, 0)) == 4   # most recent survives


# ---------------------------------------------------------------------------
# Retry classifier: 429 honors Retry-After, never trips a breaker
# ---------------------------------------------------------------------------

def _http_429(retry_after="0.3"):
    return urllib.error.HTTPError(
        "http://x/leader/start", 429, "Too Many Requests",
        {"Retry-After": retry_after}, None)


class TestShedClassifier:
    def test_429_is_retryable_with_retry_after_floor(self):
        e = RpcStatusError("http://x", 429, retry_after_s=0.4)
        assert is_retryable(e)
        assert retry_after_of(e) == pytest.approx(0.4)
        assert is_retryable(_http_429())
        assert retry_after_of(_http_429()) == pytest.approx(0.3)
        # unparseable (HTTP-date) hint: still a shed, hint absent
        assert retry_after_of(_http_429("Fri, 01 Aug 2026")) == 0.0
        assert retry_after_of(RpcStatusError("http://x", 503)) is None

    def test_retry_policy_never_retries_before_retry_after(self):
        sleeps = []
        calls = []

        def fn():
            calls.append(1)
            if len(calls) == 1:
                raise RpcStatusError("http://x", 429, retry_after_s=0.7)
            return "ok"

        p = RetryPolicy(max_attempts=3, base_delay_s=0.001, jitter=0.0,
                        name="shed_test", sleep=sleeps.append)
        assert p.call(fn) == "ok"
        # the back-off slept AT LEAST the Retry-After hint, not the
        # (tiny) exponential base delay
        assert sleeps == [pytest.approx(0.7)]
        assert global_metrics.get("shed_test_shed_waits") == 1

    def test_deadline_too_small_propagates_shed_immediately(self):
        """Non-retryable-before-Retry-After: when the budget cannot
        cover the wait, the shed propagates NOW — never an early
        re-attempt that hammers the saturated leader."""
        sleeps = []

        def fn():
            raise RpcStatusError("http://x", 429, retry_after_s=5.0)

        p = RetryPolicy(max_attempts=3, base_delay_s=0.001, jitter=0.0,
                        deadline_s=0.5, sleep=sleeps.append)
        with pytest.raises(RpcStatusError):
            p.call(fn)
        assert sleeps == []   # zero early re-attempts

    def test_shed_never_trips_worker_breaker(self):
        """A 429 is healthy overload behavior: a breaker that opened on
        sheds would mark a live node dead and amplify the overload."""
        e = RpcStatusError("http://x", 429, retry_after_s=0.1)
        assert not is_worker_fault(e)
        assert not is_worker_fault(_http_429())
        res = ClusterResilience(Config(rpc_max_attempts=1,
                                       breaker_failure_threshold=1))
        for _ in range(5):
            with pytest.raises(RpcStatusError):
                res.worker_call("http://w1", lambda: (_ for _ in ()).throw(
                    RpcStatusError("http://w1", 429, retry_after_s=0.1)))
        assert res.board.breaker("http://w1").state == "closed"


# ---------------------------------------------------------------------------
# Front door over real HTTP
# ---------------------------------------------------------------------------

class TestFrontDoorHTTP:
    def test_rate_limit_shed_429_per_client(self, core, tmp_path):
        # rate 0.2 qps: hog's bucket refills a token only every 5s, so
        # the back-to-back pair below sheds deterministically even when
        # the suite runs slow (at 1 qps a search that happens to take
        # >1s — e.g. paying an XLA compile — would refill the bucket
        # between the two requests and the second would be admitted)
        nodes = _mk_cluster(core, tmp_path, n=3, replication_factor=2,
                            admission_rate_qps=0.2, admission_burst=1.0)
        try:
            leader = nodes[0]
            _upload_docs(leader)
            # warm the scatter path on a different client's budget
            assert _search(leader, "common",
                           headers={"X-Client-Id": "warm"}) is not None
            assert _search(leader, "common",
                           headers={"X-Client-Id": "hog"}) is not None
            with pytest.raises(urllib.error.HTTPError) as exc:
                _search(leader, "common", headers={"X-Client-Id": "hog"})
            ra, reason, body = _shed_info(exc.value)
            assert reason == "rate_limited"
            # header is RFC 9110 delta-seconds: the precise float hint
            # lives in the body, the header rounds UP to whole seconds
            assert 0.0 < ra <= 5.0 and ra == int(ra)
            assert body["error"] == "overloaded"
            assert body["reason"] == "rate_limited"
            assert 0.0 < body["retry_after_s"] <= ra
            # a polite client with its own id is admitted concurrently
            assert _search(leader, "common",
                           headers={"X-Client-Id": "polite"}) is not None
            assert global_metrics.get("admission_shed_rate_limited") >= 1
        finally:
            _stop_all(nodes)

    def test_backpressure_sheds_bulk_then_interactive(self, core,
                                                      tmp_path):
        nodes = _mk_cluster(core, tmp_path, n=3, replication_factor=2,
                            admission_queue_high_water=50,
                            admission_queue_critical=500)
        try:
            leader = nodes[0]
            _upload_docs(leader)
            # high water: the BULK lane sheds...
            global_metrics.set_gauge("last_scatter_queue_depth", 50)
            with pytest.raises(urllib.error.HTTPError) as exc:
                _search(leader, "common", headers={"X-Priority": "bulk"})
            _, reason, _ = _shed_info(exc.value)
            assert reason == "backpressure"
            # ... uploads default to the bulk lane and shed too,
            # BEFORE their body is read
            with pytest.raises(urllib.error.HTTPError) as exc:
                _upload_docs(leader)
            assert exc.value.code == 429
            # ... an upload explicitly marked interactive survives
            global_metrics.set_gauge("last_scatter_queue_depth", 50)
            assert json.loads(http_post(
                leader.url + "/leader/upload-batch",
                json.dumps([{"name": "vip.txt", "text": "vip common"}]
                           ).encode(),
                headers={"X-Priority": "interactive"}))
            # ... and interactive searches are admitted (the dispatch
            # resets the gauge, so re-arm before asserting)
            global_metrics.set_gauge("last_scatter_queue_depth", 50)
            assert _search(leader, "common") is not None
            # critical: interactive sheds as well
            global_metrics.set_gauge("last_scatter_queue_depth", 500)
            with pytest.raises(urllib.error.HTTPError) as exc:
                _search(leader, "common")
            _, reason, _ = _shed_info(exc.value)
            assert reason == "backpressure"
            # recovery: depth back down, everyone admitted again
            global_metrics.set_gauge("last_scatter_queue_depth", 0)
            assert _search(leader, "common",
                           headers={"X-Priority": "bulk"}) is not None
        finally:
            _stop_all(nodes)

    def test_stalled_dispatchers_still_shed(self, core, tmp_path):
        """The gauge alone freezes while every dispatcher thread is
        blocked inside a stalled scatter RPC — the live backlog read
        must keep the front door shedding through the stall instead of
        queueing every request behind it."""
        nodes = _mk_cluster(core, tmp_path, n=3, replication_factor=2,
                            scatter_batch=4,
                            admission_queue_high_water=2,
                            admission_queue_critical=4)
        try:
            leader = nodes[0]
            _upload_docs(leader)
            assert _search(leader, "common") is not None
            # simulate the stall deterministically: batch formation
            # needs the coalescer lock, so holding it wedges every
            # dispatcher round exactly like a hung batch_fn would;
            # the gauge stays frozen at its healthy last value while
            # the queue piles up live
            sb = leader.scatter_batcher
            with sb._lock:
                global_metrics.set_gauge("last_scatter_queue_depth", 0)
                for i in range(12):
                    sb._items.append(_Waiter(f"stall{i}", lane=0))
                assert sb.backlog() > 4   # live signal sees the pile
                # admission runs BEFORE submit: the shed path never
                # touches the coalescer, so this cannot deadlock
                with pytest.raises(urllib.error.HTTPError) as exc:
                    _search(leader, "common token7")   # not yet cached
                _, reason, _ = _shed_info(exc.value)
                assert reason == "backpressure"
                # restore: pull the fake waiters back out before the
                # dispatchers wake and try to serve them
                sb._items.clear()
            assert _search(leader, "common") is not None
        finally:
            _stop_all(nodes)

    def test_download_endpoint_is_admission_controlled(self, core,
                                                       tmp_path):
        """Every /leader/* endpoint sits behind the front door —
        including the GET checkpoint-download path (real file I/O per
        request, bulk lane: first to shed)."""
        nodes = _mk_cluster(core, tmp_path, n=3, replication_factor=2,
                            admission_queue_high_water=10,
                            admission_queue_critical=1000)
        try:
            leader = nodes[0]
            _upload_docs(leader)
            global_metrics.set_gauge("last_scatter_queue_depth", 10)
            with pytest.raises(urllib.error.HTTPError) as exc:
                http_get(leader.url + "/leader/download?path=ad0.txt")
            assert exc.value.code == 429
            assert exc.value.headers.get("X-Shed-Reason") == "backpressure"
            global_metrics.set_gauge("last_scatter_queue_depth", 0)
        finally:
            _stop_all(nodes)

    def test_shed_drains_body_so_client_sees_429(self, core, tmp_path):
        """A shed POST with a large body must still deliver the 429:
        closing with unread data in the receive queue sends RST, the
        client would see ECONNRESET (classified transient — retried
        with no Retry-After floor). The shed path drains up to 1 MB
        before closing so the reply survives."""
        nodes = _mk_cluster(core, tmp_path, n=3, replication_factor=2,
                            admission_queue_high_water=5)
        try:
            leader = nodes[0]
            global_metrics.set_gauge("last_scatter_queue_depth", 5)
            big = [{"name": "big.txt", "text": "word " * 60_000}]  # ~300KB
            with pytest.raises(urllib.error.HTTPError) as exc:
                http_post(leader.url + "/leader/upload-batch",
                          json.dumps(big).encode())
            ra, reason, body = _shed_info(exc.value)
            assert reason == "backpressure"
            assert body["error"] == "overloaded"
            global_metrics.set_gauge("last_scatter_queue_depth", 0)
        finally:
            _stop_all(nodes)

    def test_unbounded_results_disables_cache(self, core, tmp_path):
        """Parity (unbounded-results) configs skip top-k truncation, so
        a cached value would be a full-corpus score dict — the entry
        bound is no memory bound. The cache must be off there, like the
        scatter batcher already is."""
        node = _node(core, tmp_path, 0, unbounded_results=True,
                     result_cache_entries=64)
        try:
            assert node.result_cache is None
            assert node.scatter_batcher is None
        finally:
            node.stop()

    def test_health_and_metrics_never_shed(self, core, tmp_path):
        """The reserved observability lane: with the cluster at
        CRITICAL backpressure (every search lane shedding), operators
        can still see it."""
        nodes = _mk_cluster(core, tmp_path, n=3, replication_factor=2,
                            admission_queue_high_water=10,
                            admission_queue_critical=20)
        try:
            leader = nodes[0]
            global_metrics.set_gauge("last_scatter_queue_depth", 1000)
            with pytest.raises(urllib.error.HTTPError):
                _search(leader, "common")
            health = json.loads(http_get(leader.url + "/api/health"))
            assert health["ok"] is True
            assert health["role"] == "leader"
            assert health["admission"]["queue_critical"] == 20
            snap = json.loads(http_get(leader.url + "/api/metrics"))
            assert snap.get("admission_shed_total", 0) >= 1
            # a worker's health lane answers too
            wh = json.loads(http_get(nodes[1].url + "/api/health"))
            assert wh["ok"] is True and wh["role"] == "worker"
        finally:
            _stop_all(nodes)

    def test_metrics_respond_during_saturated_bulk_flood(self, core,
                                                         tmp_path):
        """The satellite pin: a saturated bulk flood (every slot bulk,
        queue nonempty the whole time) cannot queue ahead of
        /api/metrics or /api/health — each observability request gets
        its own handler thread and never enters admission or the
        coalescer."""
        nodes = _mk_cluster(core, tmp_path, n=3, replication_factor=2,
                            scatter_linger_ms=30.0,
                            scatter_linger_min_ms=30.0,
                            scatter_linger_max_ms=30.0)
        try:
            leader = nodes[0]
            _upload_docs(leader)
            stop = threading.Event()
            errors = []

            def flood():
                while not stop.is_set():
                    try:
                        _search(leader, "common",
                                headers={"X-Priority": "bulk"})
                    except urllib.error.HTTPError as e:
                        if e.code != 429:
                            errors.append(e)
                    except Exception as e:
                        errors.append(e)

            threads = [threading.Thread(target=flood, daemon=True)
                       for _ in range(12)]
            for t in threads:
                t.start()
            try:
                time.sleep(0.3)   # let the flood saturate the coalescer
                for _ in range(5):
                    t0 = time.monotonic()
                    snap = json.loads(http_get(
                        leader.url + "/api/metrics", timeout=5.0))
                    health = json.loads(http_get(
                        leader.url + "/api/health", timeout=5.0))
                    took = time.monotonic() - t0
                    assert took < 2.0, \
                        f"observability starved: {took:.2f}s under flood"
                    assert health["ok"] is True
                    assert "queries_served" in snap or snap
            finally:
                stop.set()
                for t in threads:
                    t.join(timeout=10)
            assert not errors, errors[:3]
        finally:
            _stop_all(nodes)


# ---------------------------------------------------------------------------
# Result cache correctness against the oracle
# ---------------------------------------------------------------------------

class TestResultCacheCluster:
    def test_hit_serves_exact_result_and_counts(self, core, tmp_path):
        nodes = _mk_cluster(core, tmp_path, n=3, replication_factor=2)
        try:
            leader = nodes[0]
            _upload_docs(leader)
            _settle_signature(leader)   # replica legs confirm async
            want = _oracle(tmp_path)
            first = _search(leader, "common")
            _assert_parity(first, want["common"], "first")
            h0 = global_metrics.get("cache_hits")
            again = _search(leader, "common")
            assert again == first
            assert global_metrics.get("cache_hits") == h0 + 1
            # the hit did not re-enter the scatter path: health gauges
            # still describe the LAST real fan-out
            _assert_parity(again, want["common"], "cached")
        finally:
            _stop_all(nodes)

    def test_upsert_invalidates_cached_result(self, core, tmp_path):
        """Miss-after-commit, proven by parity: after an upsert changes
        the df signature, the cached entry must die — serving it would
        return scores from a corpus that no longer exists."""
        nodes = _mk_cluster(core, tmp_path, n=3, replication_factor=2)
        try:
            leader = nodes[0]
            _upload_docs(leader)
            _settle_signature(leader)
            before = _search(leader, "common")
            _search(leader, "common")   # ensure it is cached
            tok0 = leader.df_signature()
            docs2 = dict(DOCS, **{"ad0.txt": "common common pelican"})
            _upload_docs(leader, {"ad0.txt": docs2["ad0.txt"]})
            assert leader.df_signature() != tok0
            want2 = _oracle(tmp_path, docs=docs2, tag="oracle2")
            _parity_settles(leader, "common", want2["common"],
                            "post-upsert")
            assert _search(leader, "common") != before
            assert global_metrics.get("cache_invalidations") >= 1
        finally:
            _stop_all(nodes)

    def test_worker_delete_advances_local_signature(self, core,
                                                    tmp_path):
        """Direct worker-side mutations keep that node's own signature
        honest (dual-role and single-node deployments serve both
        families of endpoints from one process)."""
        nodes = _mk_cluster(core, tmp_path, n=2, replication_factor=1)
        try:
            leader = nodes[0]
            worker = nodes[1]
            _upload_docs(leader)
            tok0 = worker.df_signature()
            name = leader.placement.names_on(worker.url)[0]
            resp = json.loads(http_post(
                worker.url + "/worker/delete",
                json.dumps({"names": [name]}).encode()))
            assert resp["deleted"] == 1
            assert worker.df_signature() != tok0
        finally:
            _stop_all(nodes)

    def test_migration_flip_invalidates(self, core, tmp_path):
        """The PR-6 surface: a migration flip changes which shard
        scores the moved docs (per-shard df shifts with ownership) —
        cached results stamped before the flip must miss after it."""
        nodes = _mk_cluster(core, tmp_path, n=3, replication_factor=1)
        try:
            leader = nodes[0]
            _upload_docs(leader)
            _search(leader, "common")
            _search(leader, "common")   # cached
            tok0 = leader.df_signature()
            source = nodes[1].url
            names = leader.placement.names_on(source)[:3]
            assert names
            out = leader.rebalancer.migrate(source, names)
            assert out["moved"] == len(names)
            assert leader.df_signature() != tok0
            # results after the flip are complete (all 12 docs for the
            # all-docs query), freshly computed
            inv0 = global_metrics.get("cache_invalidations")
            got = _search(leader, "common")
            assert set(got) == set(DOCS)
            assert global_metrics.get("cache_invalidations") > inv0 - 1
        finally:
            _stop_all(nodes)

    def test_concurrent_write_workload_exact_parity(self, core,
                                                    tmp_path):
        """The satellite gate: under continuous cached read traffic, a
        sequence of df-changing commits each becomes visible EXACTLY —
        after every commit settles, the next read equals the fresh
        single-node oracle, never a stale cached score. The hammer
        threads race put() against bump_result_generation() the whole
        run; the dispatch-time token capture makes a late put of an
        old-token entry harmless (it can never be read under the new
        token)."""
        nodes = _mk_cluster(core, tmp_path, n=3, replication_factor=2)
        try:
            leader = nodes[0]
            _upload_docs(leader)
            versions = [f"common pelican v{i} " + "drift " * i
                        for i in range(4)]
            oracles = []
            for i, text in enumerate(versions):
                docs_i = dict(DOCS, **{"ad0.txt": text})
                oracles.append(_oracle(tmp_path, docs=docs_i,
                                       tag=f"ow{i}"))
            stop = threading.Event()
            hammer_errors = []

            def hammer():
                while not stop.is_set():
                    try:
                        _search(leader, random.choice(QUERIES))
                    except Exception as e:
                        hammer_errors.append(e)
                        return

            threads = [threading.Thread(target=hammer, daemon=True)
                       for _ in range(4)]
            for t in threads:
                t.start()
            try:
                for i, text in enumerate(versions):
                    _upload_docs(leader, {"ad0.txt": text})
                    # both replica legs land within the window; once
                    # they have, EVERY subsequent read must be fresh
                    _parity_settles(leader, "common",
                                    oracles[i]["common"], f"v{i}")
                    _settle_signature(leader)
                    for q in QUERIES:   # full parity at this version
                        _assert_parity(_search(leader, q),
                                       oracles[i][q], f"v{i}:{q}")
            finally:
                stop.set()
                for t in threads:
                    t.join(timeout=10)
            assert not hammer_errors, hammer_errors[:3]
            # the cache was genuinely exercised AND genuinely killed
            assert global_metrics.get("cache_hits") > 0
            assert global_metrics.get("cache_invalidations") > 0
        finally:
            _stop_all(nodes)


# ---------------------------------------------------------------------------
# Chaos (slow): 2x-overload zipfian closed loop + mid-run worker kill -9
# ---------------------------------------------------------------------------

def _zipf_queries(pool: list[str], n: int, s: float = 1.1,
                  seed: int = 7) -> list[str]:
    rng = random.Random(seed)
    weights = [1.0 / (i + 1) ** s for i in range(len(pool))]
    return rng.choices(pool, weights=weights, k=n)


@pytest.mark.slow
class TestChaosOverload:
    @pytest.mark.timeout(300)
    def test_2x_overload_sheds_bounded_p99_exact_parity(self, tmp_path):
        """``make chaos-overload``: a closed-loop zipfian workload at
        ~2x the capacity the 1x phase measures, with a real mid-run
        worker ``kill -9``. Acceptance: the leader sheds explicitly
        (shed count rises past the 1x phase), the p99 latency of
        ADMITTED interactive queries stays bounded, and every admitted
        result stays in exact merge parity with the single-node oracle
        — through the kill and through a cache-invalidating upsert
        mid-run."""
        import os
        import signal
        import socket
        import subprocess
        import sys

        def free_port():
            with socket.socket() as s:
                s.bind(("127.0.0.1", 0))
                return s.getsockname()[1]

        env = os.environ.copy()
        env["TFIDF_JAX_PLATFORM"] = "cpu"
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("XLA_FLAGS", None)
        env.update({
            "TFIDF_REPLICATION_FACTOR": "2",
            "TFIDF_TOP_K": "64",
            "TFIDF_SESSION_TIMEOUT_S": "1.0",
            "TFIDF_HEARTBEAT_INTERVAL_S": "0.2",
            "TFIDF_RECONCILE_SWEEP_INTERVAL_S": "0.5",
            "TFIDF_MIN_DOC_CAPACITY": "64",
            "TFIDF_MIN_NNZ_CAPACITY": "4096",
            "TFIDF_MIN_VOCAB_CAPACITY": "1024",
            "TFIDF_QUERY_BATCH": "4",
            "TFIDF_MAX_QUERY_TERMS": "8",
            # overload mechanics on laptop-scale hardware: a SMALL
            # scatter batch leaves queued items behind each dispatch
            # round (the depth gauge backpressure keys on), LOW
            # watermarks so the 2x phase genuinely sheds, rate limiting
            # off (backpressure is the subject), cache on (zipfian
            # repeats are its best case — the head of the distribution
            # answers leader-side while the tail keeps the workers hot)
            "TFIDF_SCATTER_BATCH": "2",
            "TFIDF_SCATTER_PIPELINE": "1",
            "TFIDF_ADMISSION_QUEUE_HIGH_WATER": "1",
            "TFIDF_ADMISSION_QUEUE_CRITICAL": "3",
            "TFIDF_RESULT_CACHE_ENTRIES": "256",
        })
        coord_port = free_port()
        procs = {}

        def spawn(tag, args):
            p = subprocess.Popen(
                [sys.executable, "-m", "tfidf_tpu", *args],
                env=env, stdout=subprocess.DEVNULL,
                stderr=subprocess.DEVNULL)
            procs[tag] = p
            return p

        def wait_pred(pred, timeout=60.0, interval=0.2):
            deadline = time.monotonic() + timeout
            last = None
            while time.monotonic() < deadline:
                try:
                    if pred():
                        return True
                except Exception as e:
                    last = e
                time.sleep(interval)
            raise AssertionError(f"timed out; last={last!r}")

        def node_args(i, port):
            return ["serve", "--port", str(port), "--host", "127.0.0.1",
                    "--coordinator-address", f"127.0.0.1:{coord_port}",
                    "--documents-path", str(tmp_path / f"ov{i}" / "docs"),
                    "--index-path", str(tmp_path / f"ov{i}" / "index")]

        try:
            spawn("coord", ["coordinator", "--listen",
                            f"127.0.0.1:{coord_port}"])
            wait_pred(lambda: socket.create_connection(
                ("127.0.0.1", coord_port), timeout=1.0).close() or True)
            ports = [free_port() for _ in range(3)]
            urls = [f"http://127.0.0.1:{p}" for p in ports]
            for i, p in enumerate(ports):
                spawn(f"n{i}", node_args(i, p))
                wait_pred(lambda u=urls[i]: http_get(
                    u + "/api/status", timeout=5.0), timeout=120)
            leader = urls[0]
            wait_pred(lambda: len(json.loads(http_get(
                leader + "/api/services"))) == 2)

            batch = [{"name": n, "text": t} for n, t in DOCS.items()]
            http_post(leader + "/leader/upload-batch",
                      json.dumps(batch).encode())
            # a WIDE distinct-query pool: the zipf head hits the
            # result cache, the long tail keeps real scatter traffic
            # flowing (with 4 distinct queries the cache would absorb
            # the whole 2x phase and nothing would ever shed)
            qpool = QUERIES + [f"token{i} word{j}" for i in range(12)
                               for j in range(3)] + \
                [f"extra{k} common" for k in range(5)]
            want = _oracle(tmp_path, queries=qpool, top_k=64)

            def parity_now():
                for q in QUERIES:
                    got = json.loads(http_post(
                        leader + "/leader/start",
                        json.dumps({"query": q}).encode()))
                    _assert_parity(got, want[q], ctx=q)
                return True
            wait_pred(parity_now, timeout=120, interval=1.0)

            zipf = _zipf_queries(qpool, 4000)
            lat_lock = threading.Lock()
            nonce = [0]

            def run_phase(n_clients: int, seconds: float,
                          mid_phase=None) -> dict:
                """Closed loop: each client posts, measures, repeats.
                The zipf HEAD repeats (the cache's best case); a 40%
                tail gets a unique OOV nonce appended — score-neutral
                (parity still checked against the base query's oracle)
                but cache-busting, modeling the effectively-unique long
                tail real user populations produce. Returns
                admitted-interactive latencies + shed count."""
                lats: list[float] = []
                sheds = [0]
                errors: list[BaseException] = []
                stop_at = time.monotonic() + seconds
                idx = [0]

                def client(cid: int):
                    while time.monotonic() < stop_at:
                        with lat_lock:
                            base = zipf[idx[0] % len(zipf)]
                            idx[0] += 1
                            q = base
                            if idx[0] % 5 < 3:   # the unique tail
                                nonce[0] += 1
                                q = f"{base} zzuniq{nonce[0]}"
                        t0 = time.monotonic()
                        try:
                            got = json.loads(http_post(
                                leader + "/leader/start",
                                json.dumps({"query": q}).encode(),
                                headers={"X-Client-Id": f"c{cid}"},
                                timeout=30.0))
                            dt = time.monotonic() - t0
                            with lat_lock:
                                lats.append(dt)
                            # admitted => exact: every response
                            # parity-checked against the oracle
                            _assert_parity(got, want[base], ctx=q)
                        except urllib.error.HTTPError as e:
                            if e.code == 429:
                                ra = float(
                                    e.headers.get("Retry-After", 0.05))
                                with lat_lock:
                                    sheds[0] += 1
                                time.sleep(min(ra, 0.5))
                            else:
                                errors.append(e)
                                return
                        except Exception as e:
                            errors.append(e)
                            return

                threads = [threading.Thread(target=client, args=(i,),
                                            daemon=True)
                           for i in range(n_clients)]
                for t in threads:
                    t.start()
                if mid_phase is not None:
                    time.sleep(seconds / 2)
                    mid_phase()
                for t in threads:
                    t.join(timeout=seconds + 60)
                assert not errors, errors[:3]
                lats.sort()
                return {"n": len(lats), "sheds": sheds[0],
                        "p50": lats[len(lats) // 2] if lats else 0.0,
                        "p99": lats[int(len(lats) * 0.99)]
                        if lats else 0.0}

            one_x = run_phase(4, 8.0)
            assert one_x["n"] > 0

            def kill_and_upsert():
                # the mid-run chaos: SIGKILL a worker AND land a
                # cache-invalidating commit while 2x load runs. The
                # upsert must model the polite client: uploads default
                # to the bulk lane, which is (by design) exactly what
                # the saturated 2x phase sheds first — so mark it
                # interactive and honor Retry-After until admitted
                victim = procs.pop("n2")
                os.kill(victim.pid, signal.SIGKILL)
                victim.wait(timeout=10)
                body = json.dumps([{"name": "ad0.txt",
                                    "text": DOCS["ad0.txt"]}]).encode()
                for _ in range(40):
                    try:
                        http_post(leader + "/leader/upload-batch", body,
                                  headers={"X-Priority": "interactive"})
                        return
                    except urllib.error.HTTPError as e:
                        if e.code != 429:
                            raise
                        time.sleep(min(float(
                            e.headers.get("Retry-After", 0.1)), 0.5))
                raise AssertionError("mid-run upsert never admitted")

            two_x = run_phase(12, 16.0, mid_phase=kill_and_upsert)
            assert two_x["n"] > 0

            # shed rate RISES under overload (the 1x phase may shed a
            # little during warm transients; 2x must shed more)
            assert two_x["sheds"] > one_x["sheds"], (one_x, two_x)
            # p99 of ADMITTED interactive queries stays bounded: within
            # 4x of the 1x p99 (CI-generous; the acceptance bar is 2x
            # on quiet hardware — see OVERLOAD.json) and an absolute
            # ceiling that unbounded queueing would blow through
            assert two_x["p99"] <= max(4.0 * one_x["p99"], 2.0), \
                (one_x, two_x)
            # the cluster still answers exactly after the storm
            wait_pred(parity_now, timeout=60, interval=1.0)
            snap = json.loads(http_get(leader + "/api/metrics"))
            assert snap.get("admission_shed_total", 0) >= two_x["sheds"]
        finally:
            for p in procs.values():
                try:
                    p.kill()
                except Exception:
                    pass
            for p in procs.values():
                try:
                    p.wait(timeout=10)
                except Exception:
                    pass

import jax.numpy as jnp
import numpy as np
import pytest

from tests.oracle import bm25_scores, df_of, random_corpus, tfidf_scores
from tfidf_tpu.ops.csr import build_coo
from tfidf_tpu.ops.scoring import (cosine_norms, make_query_batch,
                                   score_coo_batch)
from tfidf_tpu.ops.topk import exact_topk, full_ranking, merge_topk


def _device_inputs(docs, lengths, vocab_cap, queries, max_terms=8):
    shard = build_coo(docs, vocab_cap, min_nnz_cap=64, min_doc_cap=16)
    shard.doc_len[:len(lengths)] = lengths
    B = len(queries)
    q_terms = np.zeros((B, max_terms), np.int32)
    q_weights = np.zeros((B, max_terms), np.float32)
    for i, q in enumerate(queries):
        for j, (t, w) in enumerate(sorted(q.items())):
            q_terms[i, j] = t
            q_weights[i, j] = w
    n = jnp.float32(len(docs))
    avgdl = jnp.float32(sum(lengths) / max(len(lengths), 1))
    return shard, make_query_batch(q_terms, q_weights, min_slots=8), n, avgdl


@pytest.mark.parametrize("model", ["bm25", "tfidf"])
def test_scoring_matches_oracle(rng, model):
    docs, lengths = random_corpus(rng, n_docs=40, vocab=50)
    queries = [{1: 1.0, 2: 2.0}, {7: 1.0}, {49: 1.0, 0: 1.0, 13: 3.0}]
    shard, qb, n, avgdl = _device_inputs(docs, lengths, 64, queries)
    scores = score_coo_batch(
        jnp.asarray(shard.tf), jnp.asarray(shard.term),
        jnp.asarray(shard.doc), jnp.asarray(shard.doc_len),
        jnp.asarray(shard.df), qb, n, avgdl,
        model=model, chunk=64)
    scores = np.asarray(scores)
    for i, q in enumerate(queries):
        if model == "bm25":
            want = bm25_scores(docs, lengths, q)
        else:
            want = tfidf_scores(docs, q)
        np.testing.assert_allclose(scores[i, :len(docs)], want,
                                   rtol=1e-4, atol=1e-5)
        # padded docs score exactly zero
        assert scores[i, len(docs):].sum() == 0.0


def test_cosine_model_matches_oracle(rng):
    docs, lengths = random_corpus(rng, n_docs=30, vocab=40)
    queries = [{3: 1.0, 5: 1.0}]
    shard, qb, n, avgdl = _device_inputs(docs, lengths, 64, queries)
    norms = cosine_norms(jnp.asarray(shard.tf), jnp.asarray(shard.term),
                         jnp.asarray(shard.doc), jnp.asarray(shard.df),
                         n, shard.doc_cap)
    scores = score_coo_batch(
        jnp.asarray(shard.tf), jnp.asarray(shard.term),
        jnp.asarray(shard.doc), jnp.asarray(shard.doc_len),
        jnp.asarray(shard.df), qb, n, avgdl, norms,
        model="tfidf_cosine", chunk=64)
    want = tfidf_scores(docs, queries[0], cosine=True)
    np.testing.assert_allclose(np.asarray(scores)[0, :len(docs)], want,
                               rtol=1e-4, atol=1e-5)


def test_duplicate_query_terms_add(rng):
    """A term listed twice with weight 1 == once with weight 2 (the
    QueryParser duplicate-clause behavior)."""
    docs, lengths = random_corpus(rng, n_docs=20, vocab=30)
    shard = build_coo(docs, 32, min_nnz_cap=64, min_doc_cap=16)
    shard.doc_len[:len(lengths)] = lengths
    n = jnp.float32(len(docs))
    avgdl = jnp.float32(np.mean(lengths))
    qb1 = make_query_batch(np.asarray([[5, 5, 0, 0]], np.int32),
                           np.asarray([[1.0, 1.0, 0, 0]], np.float32),
                           min_slots=4)
    qb2 = make_query_batch(np.asarray([[5, 0, 0, 0]], np.int32),
                           np.asarray([[2.0, 0, 0, 0]], np.float32),
                           min_slots=4)
    args = (jnp.asarray(shard.tf), jnp.asarray(shard.term),
            jnp.asarray(shard.doc), jnp.asarray(shard.doc_len),
            jnp.asarray(shard.df))
    s1 = score_coo_batch(*args, qb1, n, avgdl, model="bm25", chunk=64)
    s2 = score_coo_batch(*args, qb2, n, avgdl, model="bm25", chunk=64)
    np.testing.assert_allclose(np.asarray(s1), np.asarray(s2), rtol=1e-5)


def test_term_zero_is_scorable(rng):
    """Term id 0 doubles as the query pad id — make sure real term 0 still
    scores correctly (the pad-slot collision must be consistent)."""
    docs = [{0: 3}, {1: 1}, {0: 1, 1: 1}]
    lengths = [3.0, 1.0, 2.0]
    shard = build_coo(docs, 8, min_nnz_cap=16, min_doc_cap=4)
    shard.doc_len[:3] = lengths
    qb = make_query_batch(
        np.asarray([[0, 0, 0, 0]], np.int32),   # query IS term 0 (+ pads)
        np.asarray([[1.0, 0, 0, 0]], np.float32), min_slots=4)
    s = score_coo_batch(
        jnp.asarray(shard.tf), jnp.asarray(shard.term),
        jnp.asarray(shard.doc), jnp.asarray(shard.doc_len),
        jnp.asarray(shard.df), qb,
        jnp.float32(3), jnp.float32(2.0), model="bm25", chunk=16)
    want = bm25_scores(docs, lengths, {0: 1.0})
    np.testing.assert_allclose(np.asarray(s)[0, :3], want, rtol=1e-4)
    assert np.asarray(s)[0, 1] == 0.0   # doc without term 0 scores 0


def test_exact_topk_masks_padding():
    scores = jnp.asarray([[0.5, 2.0, 1.0, 99.0]])  # doc 3 is padding
    vals, ids = exact_topk(scores, jnp.int32(3), k=2)
    assert ids[0].tolist() == [1, 2]
    np.testing.assert_allclose(vals[0], [2.0, 1.0])


def test_merge_topk_exact(rng):
    all_scores = rng.normal(size=(4, 2, 40)).astype(np.float32)
    per_vals, per_ids = [], []
    for s in range(4):
        v, i = exact_topk(jnp.asarray(all_scores[s]), jnp.int32(40), k=5)
        per_vals.append(v)
        per_ids.append(np.asarray(i) + s * 40)
    mv, mi = merge_topk(jnp.stack(per_vals), jnp.asarray(np.stack(per_ids)))
    flat = all_scores.transpose(1, 0, 2).reshape(2, 160)
    want_ids = np.argsort(-flat, axis=1, kind="stable")[:, :5]
    # compare scores (ids may tie-break differently across layouts)
    np.testing.assert_allclose(
        np.asarray(mv), np.take_along_axis(flat, want_ids, 1), rtol=1e-6)


def test_full_ranking_orders_all():
    scores = jnp.asarray([[1.0, 3.0, 2.0, 0.0]])
    vals, ids = full_ranking(scores, 4)
    assert ids[0].tolist() == [1, 2, 0, 3]


def test_pack_topk_roundtrip_small_ids():
    """The wire buffer must survive ids < 2^23 exactly — as f32 those
    bit patterns are denormals and real hardware flushed them to zero
    (the round-3 wire bug); the packed dtype is integer for this
    reason."""
    from tfidf_tpu.ops.topk import pack_topk, unpack_topk

    ids = jnp.asarray([[0, 1, 7, 4096, 99089, (1 << 23) - 1, 1 << 23]],
                      jnp.int32)
    vals = jnp.asarray([[0.5, -1.0, 1e-38, 3.14, 0.0, 2.0, -0.25]],
                       jnp.float32)
    out = pack_topk(vals, ids)
    assert out.dtype == jnp.int32
    v, i = unpack_topk(out)
    np.testing.assert_array_equal(i, np.asarray(ids))
    np.testing.assert_array_equal(v, np.asarray(vals))


def test_packed_topk_chunked_matches_plain(rng):
    from tfidf_tpu.ops.topk import (packed_topk, packed_topk_chunked,
                                    unpack_topk)

    scores = jnp.asarray(rng.normal(size=(3, 4096)).astype(np.float32))
    num = jnp.int32(4000)            # tail is padding, must be masked
    v0, i0 = unpack_topk(packed_topk(scores, num, k=7))
    v1, i1 = unpack_topk(packed_topk_chunked(scores, num, k=7,
                                             chunk=512))
    np.testing.assert_allclose(v0, v1, rtol=1e-6)
    np.testing.assert_array_equal(i0, i1)
    assert (np.asarray(i1) < 4000).all()


def test_packed_topk_chunked_ragged_tail(rng):
    """doc_cap not divisible by chunk (prime, even): the tail chunk is
    clamped + overlap-masked, so results match the unchunked path and no
    document can win twice through the overlap (ADVICE r3 #3: the old
    divisor-search fallback hit a compile cliff on prime factors)."""
    from tfidf_tpu.ops.topk import (packed_topk, packed_topk_chunked,
                                    unpack_topk)

    for doc_cap, num_live in ((4111, 4111), (4111, 3900), (1030, 1030),
                              (513, 513)):
        scores = jnp.asarray(
            rng.normal(size=(3, doc_cap)).astype(np.float32))
        num = jnp.int32(num_live)
        v0, i0 = unpack_topk(packed_topk(scores, num, k=7))
        v1, i1 = unpack_topk(packed_topk_chunked(scores, num, k=7,
                                                 chunk=512))
        np.testing.assert_allclose(v0, v1, rtol=1e-6)
        np.testing.assert_array_equal(i0, i1)
        ids = np.asarray(i1)
        assert (ids < num_live).all()
        for row in ids:                      # overlap must not duplicate
            assert len(set(row.tolist())) == len(row)

"""Hybrid retrieval (ISSUE 17): dense embedding scoring beside sparse
TF-IDF, fused top-k with exact oracle gates.

The acceptance story, layer by layer:

- the dense top-k kernel (``ops/dense.py``) matches a numpy brute-force
  oracle on every shape edge — dim not a multiple of 128, one live doc,
  empty column, k > live docs, chunked scan vs one-shot;
- the fusion algebra (``cluster/fusion.py``) matches an INDEPENDENT
  pure-python re-derivation of RRF and weighted-sum in this file;
- the embedding column rides the checkpoint storage seam: bit-exact
  round-trip, re-embed fallback on a signature change, and the
  corruption matrix (a torn ``embeddings.npz`` quarantines the version
  and falls back to an older intact one);
- the two-stage cluster plan matches a single-node hybrid oracle
  EXACTLY — including through a worker killed mid-fleet (failover
  slices re-issue BOTH stages) and through a rebalance drain flip;
- the ``mode`` field is an additive wire-v3 surface: absent means
  sparse (a v2 request is untouched), a staged reply carries 2n lists,
  and a misaligned reply degrades honestly via the slot-count check.

The slow chaos job (``make chaos-hybrid``) kills a worker's data plane
mid-hybrid-scatter under zipfian load: every reply must be exact or
honestly degraded, never silently partial.
"""

import json
import random
import threading
import urllib.error
import urllib.parse
import urllib.request

import numpy as np
import pytest

from tests.test_cluster import wait_until
from tests.test_replication import (_CFG, _mk_cluster, _node, _stop_all,
                                    _upload_docs)
from tfidf_tpu.cluster import fusion
from tfidf_tpu.cluster.node import http_get, http_post
from tfidf_tpu.cluster.wire import pack_hit_lists, unpack_hit_lists
from tfidf_tpu.engine.checkpoint import (load_checkpoint,
                                         restore_checkpoint,
                                         save_checkpoint)
from tfidf_tpu.engine.dense import EmbeddingColumn
from tfidf_tpu.engine.embedder import HashEmbedder, get_embedder
from tfidf_tpu.engine.engine import Engine
from tfidf_tpu.utils.config import Config
from tfidf_tpu.utils.metrics import global_metrics


@pytest.fixture
def core():
    from tfidf_tpu.cluster.coordination import CoordinationCore
    c = CoordinationCore(session_timeout_s=0.5)
    yield c
    c.close()


DOCS = {f"hy{i}.txt": f"common token{i} word{i % 3} extra{i % 5}"
        for i in range(12)}
QUERIES = ["common", "token3 word0", "word1 extra2", "common token7"]

_ENGINE_KEYS = ("top_k", "min_doc_capacity", "min_nnz_capacity",
                "min_vocab_capacity", "query_batch", "max_query_terms")


def _engine(tmp_path, tag, **kw):
    cfg_kw = {k: v for k, v in _CFG.items() if k in _ENGINE_KEYS}
    cfg_kw.update(kw)
    cfg = Config(documents_path=str(tmp_path / tag / "documents"),
                 index_path=str(tmp_path / tag / "index"), **cfg_kw)
    e = Engine(cfg)
    for n, t in DOCS.items():
        e.ingest_text(n, t)
    e.commit()
    return e


def _order(merged, k):
    return dict(sorted(merged.items(), key=lambda kv: (-kv[1], kv[0]))[:k])


def _hybrid_oracle(tmp_path, tag, mode, method, queries=QUERIES):
    """Single-node staged oracle: full-corpus engine, both stages run
    locally, fused with the SAME fusion module the leader uses (the
    fusion algebra itself is gated against an independent re-derivation
    in TestFusionOracle below)."""
    eng = _engine(tmp_path, tag)
    c = eng.config
    out = {}
    for q in queries:
        sparse = {h.name: float(h.score) for h in eng.search(q, k=c.top_k)}
        dense = dict(eng.search_dense_batch([q], k=c.top_k)[0])
        if mode == "dense":
            out[q] = _order(dense, c.top_k)
        else:
            out[q] = _order(fusion.fuse(
                sparse, dense, method=method, k=c.top_k,
                rrf_k=c.fusion_rrf_k, w_sparse=c.fusion_weight_sparse,
                w_dense=c.fusion_weight_dense), c.top_k)
    return out


def _post_search(leader, q, mode=None, method=None):
    """POST /leader/start returning (body, reply headers)."""
    body = {"query": q}
    if mode is not None:
        body["mode"] = mode
    if method is not None:
        body["fusion"] = method
    req = urllib.request.Request(
        leader.url + "/leader/start", data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=30.0) as r:
        return json.loads(r.read()), dict(r.headers)


def _kill_data_plane(victim):
    """HTTP down, session alive (the in-process stand-in for kill -9's
    RST — same idiom as tests/test_replication.py): the registry still
    lists the worker, so only WITHIN-REQUEST failover keeps results
    complete."""
    victim.httpd.shutdown()
    victim.httpd.server_close()
    cls = victim.httpd.RequestHandlerClass

    def dead(handler):
        raise ConnectionResetError("worker killed (test)")
    cls.do_POST = dead
    cls.do_GET = dead


def _assert_parity(got, want, ctx=""):
    assert set(got) == set(want), \
        f"{ctx}: missing={set(want) - set(got)} extra={set(got) - set(want)}"
    for n, s in want.items():
        assert got[n] == pytest.approx(s, rel=1e-5), (ctx, n, got[n], s)


# ---------------------------------------------------------------------------
# Dense kernel vs numpy brute force — every shape edge
# ---------------------------------------------------------------------------

def _mk_column(num_docs, dim, chunk=1 << 14, min_cap=8):
    col = EmbeddingColumn(HashEmbedder(dim), min_doc_capacity=min_cap,
                          chunk=chunk)
    for i in range(num_docs):
        col.upsert(f"d{i:04d}", {f"tok{i}": 1.0, f"shared{i % 4}": 2.0,
                                 "common": 0.5})
    col.commit()
    return col


def _numpy_oracle(col, counts, k):
    """Brute-force cosine top-k over the column's host vectors, ranked
    (-score, name) — fully independent of the jit kernel."""
    names = sorted(col._vecs)
    if not names:
        return []
    rows = np.stack([col._vecs[n] for n in names]).astype(np.float64)
    q = col.embedder.embed_query(counts).astype(np.float64)
    scores = rows @ q
    ranked = sorted(zip(names, scores), key=lambda kv: (-kv[1], kv[0]))
    return [(n, float(s)) for n, s in ranked[:k]]


class TestDenseKernelOracle:
    @pytest.mark.parametrize("num_docs,dim,k,chunk", [
        (1, 40, 5, 1 << 14),      # one live doc, dim far from %128
        (7, 64, 3, 1 << 14),      # sub-lane dim, k < docs
        (12, 96, 32, 1 << 14),    # k > live docs
        (200, 130, 10, 64),       # chunked scan, dim just over one lane
        (300, 128, 7, 4),         # chunk < k: clamped to k rows
    ])
    def test_matches_numpy_bruteforce(self, num_docs, dim, k, chunk):
        col = _mk_column(num_docs, dim, chunk=chunk)
        queries = [{"common": 1.0, "tok3": 2.0}, {"shared1": 1.0}]
        got = col.search_batch(queries, k)
        for qi, counts in enumerate(queries):
            want = _numpy_oracle(col, counts, k)
            assert [n for n, _ in got[qi]] == [n for n, _ in want], \
                (num_docs, dim, k, chunk, qi)
            for (gn, gs), (wn, ws) in zip(got[qi], want):
                assert gs == pytest.approx(ws, rel=1e-5, abs=1e-6)

    def test_empty_column(self):
        col = EmbeddingColumn(HashEmbedder(64), min_doc_capacity=8)
        col.commit()
        assert col.search_batch([{"a": 1.0}, {"b": 2.0}], 5) == [[], []]

    def test_chunked_equals_oneshot(self):
        one = _mk_column(257, 64, chunk=1 << 14)
        chk = _mk_column(257, 64, chunk=32)
        q = [{"common": 1.0, "tok17": 3.0}]
        assert one.search_batch(q, 11) == chk.search_batch(q, 11)

    def test_negative_cosines_survive_the_wire(self):
        """Signed-hash cosines are legitimately negative; the packed
        hit-list wire must carry them (the arrays fast path would drop
        scores <= 0 — dense never rides it)."""
        col = _mk_column(30, 32)
        rows = np.stack([col._vecs[n] for n in sorted(col._vecs)])
        token = next(t for t in (f"neg{i}" for i in range(500))
                     if (rows @ col.embedder.embed_counts({t: 1.0})
                         ).min() < -1e-3)
        hits = col.search_batch([{token: 1.0}], 30)[0]
        lists = unpack_hit_lists(pack_hit_lists([hits]))
        assert lists[0] == [(n, pytest.approx(s, rel=1e-6))
                            for n, s in hits]
        assert any(s < 0 for _, s in hits)   # the edge is actually hit

    def test_delete_then_commit_drops_doc(self):
        col = _mk_column(10, 64)
        assert col.delete("d0003")
        col.commit()
        names = [n for n, _ in col.search_batch([{"common": 1.0}], 10)[0]]
        assert "d0003" not in names and len(names) == 9


# ---------------------------------------------------------------------------
# Fusion algebra vs an independent pure-python re-derivation
# ---------------------------------------------------------------------------

def _ref_rrf(sparse, dense, rrf_k, ws, wd, k):
    """Independent RRF reference (re-derived from the paper's formula,
    not from cluster/fusion.py)."""
    s_ranked = sorted(sparse.items(), key=lambda kv: (-kv[1], kv[0]))[:k]
    d_ranked = sorted(dense.items(), key=lambda kv: (-kv[1], kv[0]))[:k]
    out = {}
    for i, (n, _) in enumerate(s_ranked):
        out[n] = out.get(n, 0.0) + ws * (1.0 / (rrf_k + i + 1))
    for i, (n, _) in enumerate(d_ranked):
        out[n] = out.get(n, 0.0) + wd * (1.0 / (rrf_k + i + 1))
    return out


def _ref_wsum(sparse, dense, ws, wd, k):
    out = {}
    for weight, stage in ((ws, sparse), (wd, dense)):
        ranked = sorted(stage.items(),
                        key=lambda kv: (-kv[1], kv[0]))[:k]
        if not ranked:
            continue
        vals = [s for _, s in ranked]
        lo, hi = min(vals), max(vals)
        for n, s in ranked:
            norm = 1.0 if hi <= lo else (s - lo) / (hi - lo)
            out[n] = out.get(n, 0.0) + weight * norm
    return out


class TestFusionOracle:
    def _stages(self, seed, n_s=20, n_d=20, overlap=8):
        rng = random.Random(seed)
        names = [f"doc{i:03d}" for i in range(40)]
        sparse = {n: rng.uniform(0.0, 12.0)
                  for n in rng.sample(names, n_s)}
        dense = {n: rng.uniform(-1.0, 1.0)
                 for n in rng.sample(names[:overlap] + names[20:], n_d)}
        return sparse, dense

    @pytest.mark.parametrize("seed", range(5))
    def test_rrf_matches_reference(self, seed):
        sparse, dense = self._stages(seed)
        got = fusion.fuse(sparse, dense, method="rrf", k=10,
                          rrf_k=60.0, w_sparse=0.7, w_dense=0.3)
        want = _ref_rrf(sparse, dense, 60.0, 0.7, 0.3, 10)
        assert set(got) == set(want)
        for n in want:
            assert got[n] == pytest.approx(want[n], rel=1e-12)

    @pytest.mark.parametrize("seed", range(5))
    def test_wsum_matches_reference(self, seed):
        sparse, dense = self._stages(seed)
        got = fusion.fuse(sparse, dense, method="wsum", k=10,
                          w_sparse=0.4, w_dense=0.6)
        want = _ref_wsum(sparse, dense, 0.4, 0.6, 10)
        assert set(got) == set(want)
        for n in want:
            assert got[n] == pytest.approx(want[n], rel=1e-12)

    def test_wsum_all_tied_stage_gets_full_credit(self):
        got = fusion.fuse({"a": 2.0, "b": 2.0}, {}, method="wsum",
                          k=5, w_sparse=0.5, w_dense=0.5)
        assert got == {"a": 0.5, "b": 0.5}

    def test_empty_stages(self):
        assert fusion.fuse({}, {}, method="rrf", k=5) == {}
        got = fusion.fuse({}, {"a": 0.3}, method="wsum", k=5)
        assert got == {"a": pytest.approx(0.5)}

    def test_unknown_method_raises(self):
        with pytest.raises(ValueError, match="unknown fusion method"):
            fusion.fuse({}, {}, method="borda", k=5)


# ---------------------------------------------------------------------------
# Engine integration + checkpoint seam
# ---------------------------------------------------------------------------

class TestEngineDense:
    def test_dense_search_through_engine(self, tmp_path):
        eng = _engine(tmp_path, "eng")
        hits = eng.search_dense_batch(["common token3"], k=5)[0]
        assert hits and hits == sorted(hits,
                                       key=lambda kv: (-kv[1], kv[0]))
        stats = eng.dense_stats()
        assert stats["model"] == "hash" and stats["docs"] == len(DOCS)
        assert stats["dim"] == eng.config.embedding_dim
        assert stats["bytes"] > 0

    def test_disabled_plane_is_loud(self, tmp_path):
        eng = _engine(tmp_path, "off", embedding_enabled=False)
        assert eng.dense_stats() is None
        with pytest.raises(RuntimeError, match="dense plane disabled"):
            eng.search_dense_batch(["common"], k=5)

    def test_delete_reaches_dense_plane(self, tmp_path):
        eng = _engine(tmp_path, "del")
        victim = next(iter(DOCS))
        assert eng.delete(victim)
        eng.commit()
        names = {n for n, _ in
                 eng.search_dense_batch(["common"], k=50)[0]}
        assert victim not in names


class TestCheckpointDense:
    def test_roundtrip_bit_exact(self, tmp_path):
        eng = _engine(tmp_path, "ck")
        ckpt = str(tmp_path / "ckpt")
        save_checkpoint(eng, ckpt)
        before = global_metrics.get("checkpoint_dense_reembeds")
        e2 = load_checkpoint(ckpt, eng.config)
        assert global_metrics.get("checkpoint_dense_reembeds") == before
        r1, n1 = eng.dense.export_arrays()
        r2, n2 = e2.dense.export_arrays()
        assert n1 == n2 and np.array_equal(r1, r2)
        assert eng.search_dense_batch(QUERIES, k=8) == \
            e2.search_dense_batch(QUERIES, k=8)

    def test_signature_change_reembeds(self, tmp_path):
        eng = _engine(tmp_path, "sig", embedding_dim=64)
        ckpt = str(tmp_path / "ckpt")
        save_checkpoint(eng, ckpt)
        before = global_metrics.get("checkpoint_dense_reembeds")
        cfg32 = eng.config.replace(embedding_dim=32)
        e2 = load_checkpoint(ckpt, cfg32)
        assert global_metrics.get("checkpoint_dense_reembeds") \
            == before + 1
        # the re-embedded column equals a fresh dim-32 ingest exactly
        fresh = _engine(tmp_path, "sig32", embedding_dim=32)
        r1, n1 = e2.dense.export_arrays()
        r2, n2 = fresh.dense.export_arrays()
        assert n1 == n2 and np.allclose(r1, r2, rtol=1e-6)

    def test_corrupt_embeddings_falls_back_to_intact_version(
            self, tmp_path):
        import os
        eng = _engine(tmp_path, "corr", storage_keep_versions=3)
        ckpt = str(tmp_path / "ckpt")
        save_checkpoint(eng, ckpt)          # .v1 — intact fallback
        eng.ingest_text("late.txt", "late arrival pelican")
        eng.commit()
        save_checkpoint(eng, ckpt)          # .v2 — to be corrupted
        with open(str(tmp_path / "ckpt.v2" / "embeddings.npz"),
                  "r+b") as f:
            f.seek(12)
            f.write(b"\xde\xad\xbe\xef")
        before = global_metrics.get("checkpoint_fallbacks")
        e2, meta = restore_checkpoint(ckpt, eng.config)
        assert global_metrics.get("checkpoint_fallbacks") == before + 1
        # fell back to .v1: pre-corruption corpus, dense plane intact
        assert e2.index.num_live_docs == len(DOCS)
        assert any(os.path.isdir(str(tmp_path / d))
                   for d in os.listdir(str(tmp_path))
                   if d.startswith("ckpt.v2.quarantine"))
        hits = e2.search_dense_batch(["common"], k=5)[0]
        assert hits


# ---------------------------------------------------------------------------
# Cluster: two-stage plan vs single-node oracle, wire surfaces
# ---------------------------------------------------------------------------

class TestHybridCluster:
    def test_hybrid_matches_single_node_oracle(self, core, tmp_path):
        nodes = _mk_cluster(core, tmp_path, n=3)
        try:
            leader = nodes[0]
            _upload_docs(leader, DOCS)
            for method in fusion.FUSION_METHODS:
                want = _hybrid_oracle(tmp_path, f"ho-{method}",
                                      "hybrid", method)
                for q in QUERIES:
                    got, hdrs = _post_search(leader, q, mode="hybrid",
                                             method=method)
                    _assert_parity(got, want[q], ctx=f"{method}:{q}")
                    assert hdrs.get("X-Search-Stages", "").startswith(
                        f"sparse,dense; fusion={method}")
                    assert hdrs.get("X-Proto-Version") == "4"
        finally:
            _stop_all(nodes)

    def test_dense_mode_matches_oracle(self, core, tmp_path):
        nodes = _mk_cluster(core, tmp_path, n=3)
        try:
            leader = nodes[0]
            _upload_docs(leader, DOCS)
            want = _hybrid_oracle(tmp_path, "do", "dense", "rrf")
            for q in QUERIES:
                got, hdrs = _post_search(leader, q, mode="dense")
                _assert_parity(got, want[q], ctx=f"dense:{q}")
                assert hdrs.get("X-Search-Stages") == "dense"
        finally:
            _stop_all(nodes)

    def test_sparse_requests_are_unstamped_and_unchanged(self, core,
                                                         tmp_path):
        nodes = _mk_cluster(core, tmp_path, n=3)
        try:
            leader = nodes[0]
            _upload_docs(leader, DOCS)
            got, hdrs = _post_search(leader, "common")   # no mode field
            assert "X-Search-Stages" not in hdrs
            assert got
        finally:
            _stop_all(nodes)

    def test_bad_mode_and_fusion_reject_400(self, core, tmp_path):
        nodes = _mk_cluster(core, tmp_path, n=2)
        try:
            leader = nodes[0]
            for body in ({"query": "x", "mode": "ann"},
                         {"query": "x", "mode": "hybrid",
                          "fusion": "borda"}):
                with pytest.raises(urllib.error.HTTPError) as ei:
                    http_post(leader.url + "/leader/start",
                              json.dumps(body).encode())
                assert ei.value.code == 400
        finally:
            _stop_all(nodes)

    def test_disabled_dense_plane_rejects_staged_modes(self, core,
                                                       tmp_path):
        nodes = _mk_cluster(core, tmp_path, n=2,
                            embedding_enabled=False)
        try:
            with pytest.raises(urllib.error.HTTPError) as ei:
                http_post(nodes[0].url + "/leader/start",
                          json.dumps({"query": "x",
                                      "mode": "hybrid"}).encode())
            assert ei.value.code == 400
        finally:
            _stop_all(nodes)

    def test_worker_staged_wire_is_2n_lists(self, core, tmp_path):
        """The wire-v3 staged reply layout, asserted at the worker RPC
        itself: n sparse lists then n dense lists; mode absent -> the
        v2 reply (n lists) byte-layout."""
        nodes = _mk_cluster(core, tmp_path, n=3)
        try:
            leader = nodes[0]
            _upload_docs(leader, DOCS)
            worker = leader.registry.get_all_service_addresses()[0]
            staged = unpack_hit_lists(http_post(
                worker + "/worker/process-batch",
                json.dumps({"queries": QUERIES[:2], "k": 5,
                            "mode": "hybrid"}).encode()))
            assert len(staged) == 4
            legacy = unpack_hit_lists(http_post(
                worker + "/worker/process-batch",
                json.dumps({"queries": QUERIES[:2], "k": 5}).encode()))
            assert len(legacy) == 2
            # sparse slots of the staged reply == the legacy reply
            assert staged[:2] == legacy
            # dense-mode reply keeps the slot layout: n EMPTY sparse
            # lists ahead of the dense stage
            dense = unpack_hit_lists(http_post(
                worker + "/worker/process-batch",
                json.dumps({"queries": QUERIES[:2], "k": 5,
                            "mode": "dense"}).encode()))
            assert len(dense) == 4 and dense[0] == [] and dense[1] == []
            assert dense[2] and dense[3]
        finally:
            _stop_all(nodes)

    def test_health_reports_embedding_column(self, core, tmp_path):
        nodes = _mk_cluster(core, tmp_path, n=2)
        try:
            _upload_docs(nodes[0], DOCS)
            for nd in nodes:
                h = json.loads(http_get(nd.url + "/api/health"))
                emb = h["embedding"]
                assert emb["model"] == "hash"
                assert emb["dim"] == nd.config.embedding_dim
        finally:
            _stop_all(nodes)


class TestHybridFailover:
    def test_hybrid_exact_through_worker_death(self, core, tmp_path):
        """A worker killed mid-fleet: failover slices re-issue BOTH
        stages (the slice request carries ``mode``), so hybrid results
        stay in exact oracle parity with zero degraded replies."""
        nodes = _mk_cluster(core, tmp_path, n=3)
        try:
            leader = nodes[0]
            _upload_docs(leader, DOCS)
            want = _hybrid_oracle(tmp_path, "fo", "hybrid", "rrf")
            for q in QUERIES:
                got, _ = _post_search(leader, q, mode="hybrid",
                                      method="rrf")
                _assert_parity(got, want[q], ctx=f"pre:{q}")

            _kill_data_plane(nodes[1])
            before = global_metrics.get("scatter_failovers")
            for _ in range(3):
                for q in QUERIES:
                    got, hdrs = _post_search(leader, q, mode="hybrid",
                                             method="rrf")
                    _assert_parity(got, want[q], ctx=f"post:{q}")
                    assert "X-Scatter-Degraded" not in hdrs
            # the death was really exercised: either within-request
            # failover re-issued slices, or the dead worker's breaker
            # opened first (background sweeps race the first query) and
            # owner assignment routed around it pre-dispatch
            assert (global_metrics.get("scatter_failovers") > before
                    or global_metrics.get("scatter_last_circuit_open")
                    > 0)
        finally:
            _stop_all(nodes)

    def test_misaligned_staged_reply_fails_over(self, core, tmp_path):
        """A v2-style worker that ignores ``mode`` replies n lists where
        the leader expects 2n: the slot-count check must treat it as a
        failed worker (failover covers it) — never merge a misaligned
        reply as if the dense stage were empty."""
        nodes = _mk_cluster(core, tmp_path, n=3)
        try:
            leader = nodes[0]
            _upload_docs(leader, DOCS)
            want = _hybrid_oracle(tmp_path, "mis", "hybrid", "rrf")
            victim = nodes[1]

            def v2_reply(queries, k=None, mode="hybrid", deadline=None):
                return victim.worker_search_batch_wire(
                    queries, k=k, deadline=deadline)
            victim.worker_search_staged_wire = v2_reply
            before = global_metrics.get("scatter_failures")
            for q in QUERIES:
                got, _ = _post_search(leader, q, mode="hybrid",
                                      method="rrf")
                _assert_parity(got, want[q], ctx=f"v2:{q}")
            assert global_metrics.get("scatter_failures") > before
        finally:
            _stop_all(nodes)

    def test_hybrid_exact_through_rebalance_flip(self, core, tmp_path):
        """Drain a full-corpus worker onto a freshly joined one: the
        flip changes ownership mid-fleet and hybrid parity must hold at
        every step (the drain target receives the whole corpus before
        any flip, so post-flip owners are full-corpus shards too)."""
        nodes = _mk_cluster(core, tmp_path, n=3, replication_factor=2)
        try:
            leader = nodes[0]
            victim = nodes[1]
            _upload_docs(leader, DOCS)
            want = _hybrid_oracle(tmp_path, "rb", "hybrid", "rrf")
            joined = _node(core, tmp_path, 9, replication_factor=2)
            nodes.append(joined)
            wait_until(lambda: len(
                leader.registry.get_all_service_addresses()) == 3)
            resp = json.loads(http_post(
                leader.url + "/api/drain",
                json.dumps({"worker": victim.url}).encode()))
            assert resp["draining"] is True

            def drained():
                for q in QUERIES:   # exact parity DURING the drain
                    got, _ = _post_search(leader, q, mode="hybrid",
                                          method="rrf")
                    _assert_parity(got, want[q], ctx=f"during:{q}")
                st = json.loads(http_get(
                    leader.url + "/api/drain?worker="
                    + urllib.parse.quote(victim.url)))
                return st["drained"]
            assert wait_until(drained, timeout=30.0)
            for q in QUERIES:
                got, _ = _post_search(leader, q, mode="hybrid",
                                      method="rrf")
                _assert_parity(got, want[q], ctx=f"post:{q}")
        finally:
            _stop_all(nodes)


# ---------------------------------------------------------------------------
# Chaos (slow): kill -9 the owner mid-hybrid-scatter under zipfian load
# ---------------------------------------------------------------------------

@pytest.mark.slow
class TestHybridChaos:
    def test_owner_killed_mid_scatter_under_zipfian_load(self, core,
                                                         tmp_path):
        """``make chaos-hybrid``: hybrid queries under a zipfian query
        distribution while a worker's data plane dies mid-flight. The
        contract is exact-or-honestly-degraded: every 200 either
        matches the oracle or carries ``X-Scatter-Degraded``."""
        nodes = _mk_cluster(core, tmp_path, n=3)
        try:
            leader = nodes[0]
            _upload_docs(leader, DOCS)
            want = _hybrid_oracle(tmp_path, "chaos", "hybrid", "rrf")
            rng = random.Random(17)
            weights = [1.0 / (i + 1) for i in range(len(QUERIES))]
            stop = threading.Event()
            bad: list = []
            done = [0]

            def client():
                while not stop.is_set():
                    q = rng.choices(QUERIES, weights=weights)[0]
                    try:
                        got, hdrs = _post_search(leader, q,
                                                 mode="hybrid",
                                                 method="rrf")
                    except urllib.error.URLError:
                        continue   # shed/refused is honest too
                    if "X-Scatter-Degraded" not in hdrs:
                        try:
                            _assert_parity(got, want[q], ctx=q)
                        except AssertionError as e:
                            bad.append(e)
                    done[0] += 1

            threads = [threading.Thread(target=client)
                       for _ in range(4)]
            for t in threads:
                t.start()
            try:
                wait_until(lambda: done[0] > 20, timeout=20.0)
                _kill_data_plane(nodes[1])   # mid-flight
                wait_until(lambda: done[0] > 120, timeout=30.0)
            finally:
                stop.set()
                for t in threads:
                    t.join(timeout=10.0)
            assert not bad, bad[0]
            assert done[0] > 120
        finally:
            _stop_all(nodes)


class TestEmbedderContract:
    def test_hash_embedder_is_process_stable(self):
        """blake2b of the token STRING — replica-identical regardless of
        per-worker vocab insertion order (the invariant failover
        exactness rests on)."""
        a, b = HashEmbedder(64), HashEmbedder(64)
        counts = {"pelican": 2.0, "common": 1.0, "zebra": 0.5}
        assert np.array_equal(a.embed_counts(counts),
                              b.embed_counts(dict(reversed(
                                  list(counts.items())))))
        v = a.embed_counts(counts)
        assert np.linalg.norm(v) == pytest.approx(1.0, rel=1e-6)
        assert np.array_equal(a.embed_counts({}),
                              np.zeros(64, np.float32))

    def test_registry(self):
        emb = get_embedder("hash", 48)
        assert emb.signature() == {"model": "hash", "dim": 48}
        with pytest.raises(ValueError, match="unknown embedding model"):
            get_embedder("bert", 64)

    def test_register_embedder_plugs_in(self):
        """The pluggability seam: a registered factory is selectable by
        name (Config-style), and a dim mismatch is refused loudly."""
        from tfidf_tpu.engine.embedder import (_REGISTRY, Embedder,
                                               register_embedder)

        class _Stub(Embedder):
            name = "stub-encoder"

            def __init__(self, dim):
                self.dim = dim

            def embed_counts(self, counts):
                v = np.zeros(self.dim, np.float32)
                v[0] = 1.0
                return v

        register_embedder("stub-encoder", _Stub)
        try:
            emb = get_embedder("stub-encoder", 16)
            assert isinstance(emb, _Stub)
            assert emb.signature() == {"model": "stub-encoder",
                                       "dim": 16}
            assert emb.embed_query({"x": 1.0})[0] == 1.0
            bad = type("_Lying", (_Stub,), {})
            bad.__init__ = lambda self, dim: setattr(self, "dim", 8)
            register_embedder("stub-encoder", bad)
            with pytest.raises(ValueError, match="built dim 8"):
                get_embedder("stub-encoder", 16)
        finally:
            _REGISTRY.pop("stub-encoder", None)


# ---------------------------------------------------------------------------
# Mesh-sharded dense search (parallel/mesh_dense.py) vs the same oracle
# ---------------------------------------------------------------------------

class TestMeshDense:
    def test_sharded_matches_bruteforce(self):
        """Embedding rows sharded over a 4-wide docs axis (uneven
        shards, so padding + ``base`` offsets are both exercised) must
        reproduce the single-host numpy oracle exactly: global top-k is
        contained in the union of per-shard top-ks."""
        from tfidf_tpu.ops.topk import unpack_topk
        from tfidf_tpu.parallel.mesh import make_mesh
        from tfidf_tpu.parallel.mesh_dense import (make_mesh_dense_search,
                                                   shard_dense_column)

        dim, k = 72, 6
        col = _mk_column(22, dim)
        names = sorted(col._vecs)
        rows = np.stack([col._vecs[n] for n in names]).astype(np.float32)

        mesh = make_mesh((4, 2))
        dim_pad = -(-dim // 128) * 128
        # uneven split: 7 / 7 / 7 / 1 rows — shard-major order is the
        # name-table order ids map back through
        cuts = [0, 7, 14, 21, len(names)]
        shards = [rows[cuts[i]:cuts[i + 1]] for i in range(4)]
        emb, live, base = shard_dense_column(mesh, shards, dim_pad)
        search = make_mesh_dense_search(mesh, k=k)

        queries = [{"common": 1.0, "tok3": 2.0}, {"shared1": 1.0},
                   {"tok21": 1.0}]
        q = np.zeros((len(queries), dim_pad), np.float32)
        for i, counts in enumerate(queries):
            q[i, :dim] = col.embedder.embed_query(counts)
        packed = search(q, emb, live, base)
        vals, ids = unpack_topk(packed)
        for qi, counts in enumerate(queries):
            want = _numpy_oracle(col, counts, k)
            got = [(names[int(d)], float(v))
                   for v, d in zip(vals[qi], ids[qi])]
            assert [n for n, _ in got] == [n for n, _ in want], qi
            for (_, gs), (_, ws) in zip(got, want):
                assert gs == pytest.approx(ws, rel=1e-5)

    def test_shard_count_mismatch_refused(self):
        from tfidf_tpu.parallel.mesh import make_mesh
        from tfidf_tpu.parallel.mesh_dense import shard_dense_column

        mesh = make_mesh((4, 2))
        with pytest.raises(ValueError, match="3 shards"):
            shard_dense_column(
                mesh, [np.zeros((2, 8), np.float32)] * 3, 128)

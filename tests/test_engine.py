import os

import numpy as np
import pytest

from tests.oracle import bm25_scores
from tfidf_tpu.engine.engine import Engine
from tfidf_tpu.utils.config import Config

# The reference's own dev corpus themes (src/main/resources/documents/)
CORPUS = {
    "file1.txt": "fast food is fast and cheap",
    "file2.txt": "the cat meowing at night causes trouble",
    "file3.txt": "fast cars go very fast on the road",
    "file4.txt": "cheap food for the cat",
    "file5.txt": "night driving in fast cars",
}


def make_engine(tmp_path, **kw):
    cfg = Config(documents_path=str(tmp_path / "docs"),
                 index_path=str(tmp_path / "index"),
                 min_nnz_capacity=64, min_doc_capacity=8,
                 min_vocab_capacity=32, **kw)
    return Engine(cfg)


def ingest_corpus(engine):
    for name, text in CORPUS.items():
        engine.ingest_text(name, text)
    engine.commit()


def test_search_end_to_end(tmp_path):
    e = make_engine(tmp_path)
    ingest_corpus(e)
    hits = e.search("fast food", k=5)
    names = [h.name for h in hits]
    assert "file1.txt" in names       # has both terms, twice "fast"
    assert names[0] == "file1.txt"
    assert all(h.score > 0 for h in hits)
    # docs with neither term don't appear
    assert "file2.txt" not in names


def test_search_matches_oracle(tmp_path):
    e = make_engine(tmp_path)
    ingest_corpus(e)
    hits = dict(e.search("fast food", k=5))
    # independent computation
    docs, lengths, names = [], [], []
    for name, text in CORPUS.items():
        counts = e.analyzer.counts(text)
        ids = {e.vocab.lookup(t): c for t, c in counts.items()}
        docs.append(ids)
        lengths.append(float(sum(counts.values())))
        names.append(name)
    q = {e.vocab.lookup("fast"): 1.0, e.vocab.lookup("food"): 1.0}
    want = bm25_scores(docs, lengths, q)
    for name, score in hits.items():
        np.testing.assert_allclose(score, want[names.index(name)], rtol=1e-4)


def test_batch_search(tmp_path):
    e = make_engine(tmp_path)
    ingest_corpus(e)
    res = e.search_batch(["fast", "cat", "zebra"], k=3)
    assert len(res) == 3
    assert res[0] and res[1]
    assert res[2] == []               # unknown term matches nothing


def test_upsert_idempotent(tmp_path):
    e = make_engine(tmp_path)
    ingest_corpus(e)
    before = e.search("fast food", k=5)
    # re-ingest same docs (the boot-time re-walk does this)
    ingest_corpus(e)
    after = e.search("fast food", k=5)
    assert [(h.name, round(h.score, 5)) for h in before] == \
        [(h.name, round(h.score, 5)) for h in after]
    assert e.index.num_live_docs == len(CORPUS)


def test_upsert_replaces_content(tmp_path):
    e = make_engine(tmp_path)
    ingest_corpus(e)
    e.ingest_text("file2.txt", "completely different subject now")
    e.commit()
    names = [h.name for h in e.search("cat", k=5)]
    assert "file2.txt" not in names
    names = [h.name for h in e.search("subject", k=5)]
    assert names == ["file2.txt"]


def test_delete(tmp_path):
    e = make_engine(tmp_path)
    ingest_corpus(e)
    assert e.delete("file1.txt")
    assert not e.delete("file1.txt")
    e.commit()
    assert "file1.txt" not in [h.name for h in e.search("fast", k=5)]
    assert e.index.num_live_docs == len(CORPUS) - 1


def test_unbounded_returns_all_matches(tmp_path):
    e = make_engine(tmp_path)
    ingest_corpus(e)
    hits = e.search("fast", k=1, unbounded=True)
    fast_docs = [n for n, t in CORPUS.items() if "fast" in t]
    assert sorted(h.name for h in hits) == sorted(fast_docs)


def test_empty_index_search(tmp_path):
    e = make_engine(tmp_path)
    assert e.search("anything") == []
    e.commit()
    assert e.search("anything") == []


def test_empty_query_list_on_nonempty_index(tmp_path):
    """Regression: the pipelined chunk loop must not dereference a
    never-filled pending slot when zero chunks are dispatched."""
    e = make_engine(tmp_path)
    ingest_corpus(e)
    assert e.search_batch([]) == []
    assert e.search_batch([], unbounded=True) == []


def test_build_from_directory_and_download(tmp_path):
    docs_dir = tmp_path / "docs" / "sub"
    docs_dir.mkdir(parents=True)
    (tmp_path / "docs" / "a.txt").write_text("fast food here")
    (docs_dir / "b.txt").write_text("slow food there")
    e = make_engine(tmp_path)
    n = e.build_from_directory()
    assert n == 2
    names = [h.name for h in e.search("food", k=5)]
    assert sorted(names) == ["a.txt", os.path.join("sub", "b.txt")]
    # download path + traversal safety (Worker.java:97-121 semantics)
    assert b"fast food here" == e.open_document("a.txt")
    assert e.open_document("missing.txt") is None
    with pytest.raises(PermissionError):
        e.open_document("../outside.txt")


def test_ingest_bytes_saves_to_disk(tmp_path):
    e = make_engine(tmp_path)
    e.ingest_bytes("x/y.txt", b"hello fast world", save_to_disk=True)
    e.commit()
    assert (tmp_path / "docs" / "x" / "y.txt").read_bytes() == \
        b"hello fast world"
    assert [h.name for h in e.search("hello")] == ["x/y.txt"]


def test_index_size_grows(tmp_path):
    e = make_engine(tmp_path)
    e.ingest_text("a", "one two three")
    e.commit()
    s1 = e.index_size_bytes()
    for i in range(50):
        e.ingest_text(f"doc{i}", f"word{i} " * 30)
    e.commit()
    assert e.index_size_bytes() >= s1


def test_snapshot_reuse_when_clean(tmp_path):
    e = make_engine(tmp_path)
    ingest_corpus(e)
    v1 = e.index.snapshot.version
    e.commit()   # nothing changed
    assert e.index.snapshot.version == v1


def test_lucene_parity_mode_still_ranks(tmp_path):
    e = make_engine(tmp_path, lucene_parity=True)
    ingest_corpus(e)
    hits = e.search("fast food", k=5)
    assert hits and hits[0].name == "file1.txt"


def test_result_order_name_parity_mode(tmp_path):
    """result_order="name" reproduces Leader.java:80-91 alphabetical order."""
    e = make_engine(tmp_path, result_order="name")
    ingest_corpus(e)
    hits = e.search("fast food", k=10)
    assert [h.name for h in hits] == sorted(h.name for h in hits)


def test_commit_not_lost_on_interleaved_write(tmp_path):
    """A write landing during commit() must leave the index dirty so the
    next commit picks it up (generation-counter semantics)."""
    e = make_engine(tmp_path)
    ingest_corpus(e)
    orig_to_coo = e.index.to_coo

    def racing_to_coo(vocab_cap):
        out = orig_to_coo(vocab_cap)
        # a concurrent writer sneaks in after the snapshot build read state
        e.index.add_document("raced.txt", {0: 1}, length=1.0)
        return out

    e.index.to_coo = racing_to_coo
    e.ingest_text("trigger.txt", "fast trigger")
    e.index.commit(e.vocab.capacity())
    e.index.to_coo = orig_to_coo
    assert "raced.txt" not in e.index.snapshot.doc_names
    # the raced write is NOT silently lost: next commit includes it
    e.index.commit(e.vocab.capacity())
    assert "raced.txt" in e.index.snapshot.doc_names


def test_concurrent_ingest_keeps_vocab_consistent(tmp_path):
    """Concurrent HTTP upload handlers reach ingest_text directly; the
    engine write lock (the reference's synchronized(indexWriter),
    Worker.java:136-139) must keep Vocabulary.add's read-len-then-append
    atomic — without it two new terms can share one id and queries score
    the wrong column."""
    from concurrent.futures import ThreadPoolExecutor

    e = make_engine(tmp_path)
    n_threads, docs_per = 8, 25

    def ingest(t):
        for i in range(docs_per):
            terms = " ".join(f"term{t}x{i}y{j}" for j in range(6))
            e.ingest_text(f"doc_{t}_{i}.txt", terms)

    with ThreadPoolExecutor(n_threads) as ex:
        list(ex.map(ingest, range(n_threads)))
    terms = e.vocab.all_terms()
    assert len(terms) == n_threads * docs_per * 6
    # bijective: every term resolves to a unique id and back
    ids = {e.vocab.lookup(t) for t in terms}
    assert len(ids) == len(terms)
    e.commit()
    hits = e.search("term3x7y2")
    assert [h.name for h in hits] == ["doc_3_7.txt"]

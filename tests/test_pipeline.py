"""The serving pipeline executor: ordering, failure isolation,
backpressure, the pipelined-vs-unpipelined parity gate, the packed-wire
fast path, and the breaker/retry interaction when a dispatched scatter
group's worker RPC fails mid-pipeline (ISSUE 3 satellite tests)."""

import threading
import time

import numpy as np
import pytest

from tfidf_tpu.engine.engine import Engine
from tfidf_tpu.engine.pipeline import PipelineExecutor
from tfidf_tpu.utils.config import Config

TEXTS = {
    "a.txt": "the quick brown fox jumps over the lazy dog",
    "b.txt": "lazy dog sleeps in the sun all day",
    "c.txt": "brown dog barks at the quick fox",
    "d.txt": "a completely different document about searching",
    "e.txt": "fox fox fox den",
}

QUERIES = ["fox", "lazy dog", "brown", "searching documents", "quick",
           "sun day", "den", "nothing matches this zzz", "dog fox",
           "the"]


def make_engine(tmp_path, **cfg):
    # force the executor so tier-1 exercises the overlap machinery on
    # CPU ("auto" resolves to inline there — see _use_executor)
    cfg.setdefault("search_pipeline_mode", "executor")
    e = Engine(Config(documents_path=str(tmp_path / "docs"),
                      min_doc_capacity=8, min_nnz_capacity=256,
                      min_vocab_capacity=64, query_batch=4,
                      max_query_terms=8, **cfg))
    for name, text in TEXTS.items():
        e.ingest_text(name, text)
    e.commit()
    return e


# --------------------------------------------------------------------------
# executor unit behavior
# --------------------------------------------------------------------------

def test_results_keep_submit_order_under_out_of_order_completion():
    """Chunk 0's fetch is slow and chunk 2's work is instant; results
    must still come back in submission order (single FIFO fetch
    thread — the ordering guarantee downstream hit assembly needs)."""
    ex = PipelineExecutor(depth=3, name="t")
    try:
        def fetch(i):
            time.sleep(0.05 if i == 0 else 0.0)
            return i

        futs = [ex.submit(lambda i=i: (i,), fetch) for i in range(4)]
        done_order = []
        for f in futs:
            done_order.append(f.result())
        assert done_order == [0, 1, 2, 3]
    finally:
        ex.stop()


def test_fetch_exception_isolated_to_its_chunk():
    ex = PipelineExecutor(depth=2, name="t")
    try:
        def fetch(i):
            if i == 1:
                raise ValueError("fetch exploded")
            return i

        futs = [ex.submit(lambda i=i: (i,), fetch) for i in range(3)]
        assert futs[0].result() == 0
        with pytest.raises(ValueError, match="fetch exploded"):
            futs[1].result()
        # the pipeline keeps serving later chunks and new submissions
        assert futs[2].result() == 2
        assert ex.submit(lambda: (9,), lambda i: i).result() == 9
    finally:
        ex.stop()


def test_dispatch_exception_isolated_to_its_chunk():
    ex = PipelineExecutor(depth=2, name="t")
    try:
        def dispatch(i):
            if i == 0:
                raise RuntimeError("compile failed")
            return (i,)

        futs = [ex.submit(lambda i=i: dispatch(i), lambda i: i)
                for i in range(3)]
        with pytest.raises(RuntimeError, match="compile failed"):
            futs[0].result()
        assert [futs[1].result(), futs[2].result()] == [1, 2]
    finally:
        ex.stop()


def test_depth_bounds_in_flight_chunks():
    """Dispatch-then-drain accounting: at most depth+1 chunks may be
    dispatched-but-unfetched at any instant (HBM budgets depth+1
    packed buffers)."""
    depth = 2
    ex = PipelineExecutor(depth=depth, name="t")
    lock = threading.Lock()
    state = {"in_flight": 0, "max_seen": 0}
    release = threading.Event()
    try:
        def dispatch(i):
            with lock:
                state["in_flight"] += 1
                state["max_seen"] = max(state["max_seen"],
                                        state["in_flight"])
            return (i,)

        def fetch(i):
            release.wait(timeout=10)   # hold fetches until all queued
            with lock:
                state["in_flight"] -= 1
            return i

        futs = [ex.submit(lambda i=i: dispatch(i), fetch)
                for i in range(8)]
        time.sleep(0.2)   # let the dispatch thread run as far as it can
        with lock:
            seen = state["max_seen"]
        release.set()
        assert [f.result() for f in futs] == list(range(8))
        assert seen <= depth + 1, seen
    finally:
        ex.stop()


def test_concurrent_callers_share_one_executor():
    """Two callers' chunks interleave on the shared pipeline without
    mixing results (the worker data plane serves concurrent scatter
    RPCs through exactly this)."""
    ex = PipelineExecutor(depth=2, name="t")
    out = {}
    try:
        def caller(tag):
            futs = [ex.submit(lambda i=i: (tag, i),
                              lambda t, i: (t, i * i))
                    for i in range(16)]
            out[tag] = [f.result() for f in futs]

        threads = [threading.Thread(target=caller, args=(t,))
                   for t in ("a", "b", "c")]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=20)
        for tag in ("a", "b", "c"):
            assert out[tag] == [(tag, i * i) for i in range(16)]
    finally:
        ex.stop()


def test_executor_smoke_fake_two_program_workload():
    """Tier-1-safe CPU smoke of the overlap machinery: the committed
    probe's executor experiment at tiny cost, asserting correctness,
    FIFO fetch order, and the deterministic overlap witness (chunk 0's
    fetch observed chunk 1's dispatch in flight)."""
    import os
    import sys

    # probe_overlap.py lives at the repo root, which only `python -m
    # pytest` from the root puts on sys.path — console-script pytest
    # (or an IDE runner with another cwd) needs it added explicitly
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if root not in sys.path:
        sys.path.insert(0, root)
    from probe_overlap import executor_workload

    res = executor_workload(n_chunks=4, compute_s=0.002, rtt_s=0.002,
                            depth=2)
    assert res["results_ok"]
    assert res["fetch_order_fifo"]
    assert res["overlap_witnessed"], \
        "dispatch and fetch never overlapped — pipeline serialized"


def test_stop_fails_pending_and_rejects_new():
    ex = PipelineExecutor(depth=1, name="t")
    gate = threading.Event()
    futs = [ex.submit(lambda i=i: (i,),
                      lambda i: (gate.wait(5), i)[1]) for i in range(4)]
    gate.set()
    ex.stop()
    with pytest.raises(RuntimeError, match="stopped"):
        ex.submit(lambda: (0,), lambda i: i)
    # every future is resolved one way or another — nothing hangs
    for f in futs:
        assert f.done() or f.cancelled()


# --------------------------------------------------------------------------
# parity gates
# --------------------------------------------------------------------------

def test_pipelined_results_identical_to_unpipelined(tmp_path):
    """The acceptance gate: depth-3 pipelined search produces hit lists
    bit-identical to the depth-1 (effectively serial) path."""
    deep = make_engine(tmp_path / "deep", search_pipeline_depth=3)
    shallow = make_engine(tmp_path / "shallow", search_pipeline_depth=1)
    a = deep.search_batch(QUERIES, k=5)
    b = shallow.search_batch(QUERIES, k=5)
    assert a == b
    for hits in a[:3]:
        assert hits, "corpus queries must match something"


def test_executor_and_inline_modes_identical(tmp_path):
    """The executor and inline stage runners are the same three stages;
    results must match bit-for-bit, and "auto" must resolve to inline
    on the CPU backend (the executor's thread hand-offs only pay for
    themselves where fetches have real latency)."""
    ex = make_engine(tmp_path / "ex", search_pipeline_mode="executor")
    inl = make_engine(tmp_path / "inl", search_pipeline_mode="inline")
    auto = make_engine(tmp_path / "auto", search_pipeline_mode="auto")
    want = inl.search_batch(QUERIES, k=5)
    assert ex.search_batch(QUERIES, k=5) == want
    assert auto.search_batch(QUERIES, k=5) == want
    assert ex.searcher._use_executor()
    assert not inl.searcher._use_executor()
    assert not auto.searcher._use_executor()   # CPU backend in tests


def test_concurrent_search_calls_parity(tmp_path):
    """Concurrent callers interleaving chunks on the shared executor
    get exactly the single-caller results."""
    engine = make_engine(tmp_path, search_pipeline_depth=2)
    want = engine.search_batch(QUERIES, k=5)
    out = [None] * 6

    def one(slot):
        out[slot] = engine.search_batch(QUERIES, k=5)

    threads = [threading.Thread(target=one, args=(i,)) for i in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    for got in out:
        assert got == want


def test_search_arrays_packs_identical_wire_bytes(tmp_path):
    """The serving fast path (search_arrays -> pack_topk_arrays) must
    produce byte-identical wire replies to the hit-list path
    (pack_hit_lists over assembled SearchHits)."""
    from tfidf_tpu.cluster.wire import (pack_hit_lists, pack_topk_arrays,
                                        unpack_hit_lists)

    engine = make_engine(tmp_path)
    hits = engine.search_batch(QUERIES, k=5)
    vals, ids, kk, names = engine.searcher.search_arrays(QUERIES, k=5)
    assert vals.shape == (len(QUERIES), kk)
    fast = pack_topk_arrays(vals, ids, names)
    slow = pack_hit_lists(hits)
    assert fast == slow
    # and the decoded lists agree with the SearchHit view
    decoded = unpack_hit_lists(fast)
    assert decoded == [[(h.name, float(np.float32(h.score)))
                        for h in hl] for hl in hits]


def test_search_arrays_empty_cases(tmp_path):
    from tfidf_tpu.cluster.wire import pack_topk_arrays, unpack_hit_lists

    engine = make_engine(tmp_path)
    vals, ids, kk, names = engine.searcher.search_arrays([], k=5)
    assert vals.shape == (0, 0) and kk == 0
    assert unpack_hit_lists(pack_topk_arrays(vals, ids, names)) == []
    # a query matching nothing packs as an empty hit list
    vals, ids, kk, names = engine.searcher.search_arrays(
        ["zzz qqq nothing"], k=5)
    assert unpack_hit_lists(pack_topk_arrays(vals, ids, names)) == [[]]


def test_worker_wire_entrypoint_matches_hit_list_path(tmp_path):
    """node.worker_search_batch_wire: the arrays fast path and the
    pack_hit_lists fallback produce the same bytes end to end."""
    from tfidf_tpu.cluster.wire import pack_hit_lists

    class _Node:
        # borrow the real methods without a coordination client
        from tfidf_tpu.cluster.node import SearchNode as _S
        _search_batch_guarded = _S._search_batch_guarded
        worker_search_batch = _S.worker_search_batch
        worker_search_batch_wire = _S.worker_search_batch_wire
        _compile_bucket = _S._compile_bucket
        _is_retryable_compute_fault = staticmethod(
            _S._is_retryable_compute_fault)

        def __init__(self, engine, config):
            self.engine = engine
            self.config = config
            self._compile_retry_lock = threading.Lock()
            self._compile_retries_used = {}

        def commit_if_dirty(self):
            pass

    engine = make_engine(tmp_path)
    node = _Node(engine, engine.config)
    fast = node.worker_search_batch_wire(QUERIES, k=5)
    assert fast == pack_hit_lists(engine.search_batch(QUERIES, k=5))


# --------------------------------------------------------------------------
# breaker/retry interaction mid-pipeline
# --------------------------------------------------------------------------

def _resilience(**kw):
    from tfidf_tpu.cluster.resilience import ClusterResilience
    cfg = Config(rpc_max_attempts=3, rpc_backoff_base_s=0.001,
                 rpc_backoff_max_s=0.002, rpc_retry_deadline_s=0.0,
                 breaker_failure_threshold=2, breaker_reset_s=60.0, **kw)
    return ClusterResilience(cfg)


def test_transient_rpc_failure_mid_pipeline_retries_and_succeeds():
    """A dispatched scatter group whose worker RPC fails once with a
    gateway-transient status is retried inside the SAME group; callers
    never see the transient, and groups in flight behind it are
    unaffected."""
    from tfidf_tpu.cluster.batcher import Coalescer
    from tfidf_tpu.cluster.resilience import RpcStatusError

    res = _resilience()
    failures = {"n": 0}
    lock = threading.Lock()

    def scatter(items):
        def rpc():
            with lock:
                if failures["n"] == 0 and "q0" in items:
                    failures["n"] += 1
                    raise RpcStatusError("http://w1/x", 503)
            return [f"ok:{q}" for q in items]

        return res.worker_call("http://w1", rpc)

    co = Coalescer(scatter, max_batch=2, linger_s=0.005, pipeline=2,
                   name="t_scatter")
    try:
        out = {}
        threads = [threading.Thread(
            target=lambda q=f"q{i}": out.__setitem__(q, co.submit(q)))
            for i in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=10)
        assert out == {f"q{i}": f"ok:q{i}" for i in range(6)}
        assert failures["n"] == 1   # the transient actually fired
        assert res.board.snapshot().get("http://w1") == "closed"
    finally:
        co.stop()


def test_hard_rpc_failure_mid_pipeline_opens_breaker_and_fails_group():
    """Deterministic 500s exhaust no retries (not transient), fail ONLY
    the dispatched group's callers, and open the worker's breaker at
    the threshold while the coalescer keeps serving later groups."""
    from tfidf_tpu.cluster.batcher import Coalescer
    from tfidf_tpu.cluster.resilience import (CircuitOpenError,
                                              RpcStatusError)

    res = _resilience()
    calls = {"n": 0}

    def scatter(items):
        def rpc():
            calls["n"] += 1
            raise RpcStatusError("http://w1/x", 500)

        return res.worker_call("http://w1", rpc)

    co = Coalescer(scatter, max_batch=1, linger_s=0.0, pipeline=2,
                   name="t_scatter2")
    try:
        with pytest.raises(RpcStatusError):
            co.submit("q0")
        with pytest.raises(RpcStatusError):
            co.submit("q1")
        # threshold 2 reached: the breaker now fast-fails the NEXT
        # group without an RPC (counted as circuit_open, not a retry)
        n_before = calls["n"]
        with pytest.raises(CircuitOpenError):
            co.submit("q2")
        assert calls["n"] == n_before
        assert res.board.snapshot()["http://w1"] == "open"
    finally:
        co.stop()


# --------------------------------------------------------------------------
# adaptive linger
# --------------------------------------------------------------------------

def test_adaptive_linger_scales_with_inflight_batches():
    from tfidf_tpu.cluster.batcher import Coalescer

    co = Coalescer(lambda items: items, max_batch=4, linger_s=0.002,
                   pipeline=3, name="t_linger",
                   linger_min_s=0.001, linger_max_s=0.008)
    try:
        # busy fraction is over the pipeline-1 SIBLINGS (the deciding
        # thread is never inside batch_fn itself): 2 siblings here
        assert co._effective_linger_s() == pytest.approx(0.001)
        with co._lock:
            co._dispatching = 1
        assert co._effective_linger_s() == pytest.approx(0.0045)
        with co._lock:   # every sibling busy -> the max IS reachable
            co._dispatching = 2
        assert co._effective_linger_s() == pytest.approx(0.008)
        with co._lock:   # saturation beyond depth clamps at max
            co._dispatching = 5
        assert co._effective_linger_s() == pytest.approx(0.008)
        with co._lock:
            co._dispatching = 0
    finally:
        co.stop()


def test_adaptive_linger_single_dispatcher_keeps_fixed_linger():
    """pipeline=1 has no sibling to read load from: adaptation is moot
    and the tuned fixed linger_s applies (not a collapsed linger_min)."""
    from tfidf_tpu.cluster.batcher import Coalescer

    co = Coalescer(lambda items: items, max_batch=4, linger_s=0.002,
                   pipeline=1, name="t_linger1",
                   linger_min_s=0.0005, linger_max_s=0.008)
    try:
        assert co._effective_linger_s() == pytest.approx(0.002)
    finally:
        co.stop()


def test_fixed_linger_unchanged_without_bounds():
    from tfidf_tpu.cluster.batcher import Coalescer

    co = Coalescer(lambda items: items, max_batch=4, linger_s=0.003,
                   pipeline=2, name="t_linger2")
    try:
        for busy in (0, 1, 2):
            with co._lock:
                co._dispatching = busy
            assert co._effective_linger_s() == pytest.approx(0.003)
        with co._lock:
            co._dispatching = 0
    finally:
        co.stop()

"""Native C++ ingest path: bit-exact parity with the Python analyzer.

The native tokenizer must produce exactly the Python chain's output for
every ASCII input (tokenization quirks included), fall back cleanly for
non-ASCII, and plug into the engine with identical end-to-end results.
"""

import numpy as np
import pytest

from tfidf_tpu import native
from tfidf_tpu.engine.engine import Engine
from tfidf_tpu.ops.analyzer import Analyzer
from tfidf_tpu.utils.config import Config

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="native library unavailable")

TRICKY = [
    "the quick brown fox",
    "can't won't it's o'clock",
    "3.14 1,000 1.2.3 42",
    "3abc abc3 a_b_c __x__",
    "don''t a''b trailing' 'leading",
    "1. 2, 3.x .5 ,7",
    "  MIXED Case TeXT  ",
    "a'b'c'd",
    "",
    "!!! ???",
    "x" * 600,                      # > max_token_length, splits
    "word " * 50 + "word",
    "tabs\tand\nnewlines\r\nhere",
    "under_score_9 9_to_5",
]


def py_counts(text, **kw):
    a = Analyzer(**kw)
    return {t: float(c) for t, c in a.counts(text).items()}


class TestTokenizerParity:
    @pytest.mark.parametrize("text", TRICKY)
    def test_counts_match_python(self, text):
        ne = native.NativeEngine()
        ids, tfs, length = ne.analyze(text, add=True)
        terms = ne.dump_terms()
        got = {terms[int(i)]: float(f) for i, f in zip(ids, tfs)}
        want = py_counts(text)
        assert got == want, (got, want)
        assert length == sum(want.values())
        assert list(ids) == sorted(ids)

    def test_stopwords_and_caps(self):
        kw = dict(stopwords=("the", "and"), max_token_length=4)
        ne = native.NativeEngine(stopwords=("the", "and"),
                                 max_token_length=4)
        text = "the miserable and gigantic theand"
        ids, tfs, _ = ne.analyze(text, add=True)
        terms = ne.dump_terms()
        got = {terms[int(i)]: float(f) for i, f in zip(ids, tfs)}
        want = py_counts(text, stopwords=frozenset(("the", "and")),
                         max_token_length=4)
        assert got == want

    def test_no_lowercase(self):
        ne = native.NativeEngine(lowercase=False)
        ids, tfs, _ = ne.analyze("Foo foo FOO", add=True)
        assert len(ids) == 3

    def test_non_ascii_falls_back(self):
        ne = native.NativeEngine()
        assert ne.analyze("café crème", add=True) is None

    def test_query_lookup_does_not_add(self):
        ne = native.NativeEngine()
        ne.analyze("alpha beta", add=True)
        ids, tfs, _ = ne.analyze("alpha gamma", add=False)
        terms = ne.dump_terms()
        assert terms == ["alpha", "beta"]       # gamma not added
        assert [terms[int(i)] for i in ids] == ["alpha"]

    def test_buffer_growth(self):
        ne = native.NativeEngine()
        text = " ".join(f"tok{i}" for i in range(10_000))
        ids, tfs, length = ne.analyze(text, add=True)
        assert len(ids) == 10_000
        assert length == 10_000.0

    def test_random_ascii_fuzz(self, rng):
        import string
        alphabet = string.ascii_letters + string.digits + "_'., \t\n-!?"
        ne = native.NativeEngine()
        for _ in range(50):
            n = int(rng.integers(0, 200))
            text = "".join(rng.choice(list(alphabet)) for _ in range(n))
            got_raw = ne.analyze(text, add=True)
            terms = ne.dump_terms()
            got = {terms[int(i)]: float(f)
                   for i, f in zip(got_raw[0], got_raw[1])}
            assert got == py_counts(text), repr(text)


class TestEngineIntegration:
    def _cfg(self, tmp_path, sub, **kw):
        return Config(documents_path=str(tmp_path / sub),
                      min_doc_capacity=8, min_nnz_capacity=256,
                      min_vocab_capacity=64, query_batch=4,
                      max_query_terms=8, **kw)

    def test_native_engine_matches_python_engine(self, tmp_path):
        texts = {
            "a.txt": "the quick brown fox jumps over the lazy dog",
            "b.txt": "a fast brown fox and a quick red fox",
            "c.txt": "café crème brûlée",   # non-ASCII
            "d.txt": "numbers 3.14 and 1,000 don't lie",
        }
        results = {}
        for flag in (True, False):
            e = Engine(self._cfg(tmp_path, str(flag), native_ingest=flag))
            if flag:
                assert e.native is not None
            for nm, tx in texts.items():
                e.ingest_text(nm, tx)
            e.commit()
            results[flag] = [e.search(q)
                             for q in ("fox", "café", "3.14", "don't")]
        for hits_n, hits_p in zip(results[True], results[False]):
            assert [h.name for h in hits_n] == [h.name for h in hits_p]
            np.testing.assert_allclose([h.score for h in hits_n],
                                       [h.score for h in hits_p],
                                       rtol=1e-6)

    def test_capacity_tracks_native_vocab(self, tmp_path):
        """Regression: NativeVocabulary.capacity() must grow with the
        NATIVE table size, not the (empty) base-class term list — a stuck
        capacity silently truncates df and drops query terms."""
        cfg = self._cfg(tmp_path, "cap")
        e = Engine(cfg)
        text = " ".join(f"w{i}" for i in range(200))   # >> min_vocab 64
        e.ingest_text("big.txt", text)
        assert len(e.vocab) > 64
        assert e.vocab.capacity() >= len(e.vocab) + 1
        e.commit()
        # a term with id above the old minimum bucket must be searchable
        assert [h.name for h in e.search("w199")] == ["big.txt"]

    def test_term_accessor(self):
        ne = native.NativeEngine()
        ne.analyze("alpha beta", add=True)
        assert ne.term(0) == "alpha" and ne.term(1) == "beta"
        with pytest.raises(IndexError):
            ne.term(7)

    def test_concurrent_ingest_and_search(self, tmp_path):
        """The native path must survive concurrent upload handlers +
        searches (ThreadingHTTPServer reality): no crashes, consistent
        final vocabulary."""
        import threading
        cfg = self._cfg(tmp_path, "conc")
        e = Engine(cfg)
        errs = []

        def ingest(lo):
            try:
                for i in range(lo, lo + 50):
                    e.ingest_text(f"d{i}.txt",
                                  f"shared tokens plus unique{i} here")
            except Exception as ex:
                errs.append(ex)

        def search():
            try:
                for _ in range(30):
                    e.vocab.lookup("shared")
                    e.search("shared tokens")
            except Exception as ex:
                errs.append(ex)

        threads = [threading.Thread(target=ingest, args=(k * 50,))
                   for k in range(4)] + [threading.Thread(target=search)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errs, errs
        e.commit()
        assert len({h.name for h in e.search("shared", k=500)}) == 200

    def test_checkpoint_roundtrip_native(self, tmp_path):
        from tfidf_tpu.engine.checkpoint import (load_checkpoint,
                                                 save_checkpoint)
        cfg = self._cfg(tmp_path, "ck")
        e = Engine(cfg)
        e.ingest_text("x.txt", "hello world hello")
        e.ingest_text("y.txt", "café hello")
        e.commit()
        save_checkpoint(e, str(tmp_path / "ckpt"))
        e2 = load_checkpoint(str(tmp_path / "ckpt"), cfg)
        assert e2.native is not None
        # restored vocab is shared with the native table: new ingest
        # reuses existing ids
        assert e2.vocab.lookup("hello") == e.vocab.lookup("hello")
        h1 = e.search("hello")
        h2 = e2.search("hello")
        assert [h.name for h in h1] == [h.name for h in h2]
        np.testing.assert_allclose([h.score for h in h1],
                                   [h.score for h in h2], rtol=1e-6)
        # ingest after restore goes through the native path consistently
        e2.ingest_text("z.txt", "hello again")
        e2.commit()
        assert {h.name for h in e2.search("hello")} == {
            "x.txt", "y.txt", "z.txt"}

"""Tiered postings + block-max skipping (ISSUE 18): soundness pins.

The tiering contract has one non-negotiable invariant — **a skipped or
cold segment can NEVER change top-k**. Every test here is a face of
that invariant:

* **exact parity**: after randomized upsert → delete → merge → commit
  sequences, a tiered engine (including the pathological budget-0
  config where EVERY search streams through the upload ring) returns
  bit-identical (name, score) lists to (a) a separate untiered oracle
  engine fed the same ops and (b) the same engine with
  ``Searcher.tier_bypass`` forced (score-everything, no skip proofs);
* **bound soundness**: per-segment block-max bounds
  (:func:`tfidf_tpu.ops.blockmax.query_upper_bounds`) dominate a
  host-side f64 scratch recompute of the true max live-doc score, for
  randomized queries, after deletes and merges — bounds are computed
  at build time and must stay valid for every later live mask;
* **adversarial fault-in**: the global top-1 doc living in an evicted
  segment must be faulted in, not skipped — the exact case a buggy
  threshold would get wrong silently;
* **residency accounting**: admit/evict/spill under a byte budget,
  dense-plane reservation (the PR 17 embedding column cannot silently
  pin HBM the tier thinks it owns), checkpoint restore re-admission;
* **witness**: ``df_full_recomputes`` stays at zero for steady-state
  tiered commits — tiering must not reintroduce the O(corpus) pass;
* **chaos (slow)**: bit rot injected into a cold spill file is caught
  by the manifest gate mid-query, the version dir is quarantined, the
  segment is re-spilled from the host replica, and the search still
  returns exact oracle parity (``make chaos-tier``).
"""

import numpy as np
import pytest

from tfidf_tpu.engine import checkpoint
from tfidf_tpu.engine.engine import Engine
from tfidf_tpu.ops.blockmax import query_upper_bounds
from tfidf_tpu.utils import storage
from tfidf_tpu.utils.config import Config
from tfidf_tpu.utils.storage import global_storage

# fixed pool keeps the vocab inside the 64-term capacity bucket, so no
# commit takes the vocab-growth resync (same idiom as test_commit_stats)
WORDS = [f"w{i}" for i in range(48)]


def make_engine(tmp_path, sub, *, tier=False, budget_mb=0, **kw):
    cfg = Config(documents_path=str(tmp_path / sub / "docs"),
                 index_path=str(tmp_path / sub / "index"),
                 engine_mode="local", index_mode="segments",
                 tier_enabled=tier, tier_hot_budget_mb=budget_mb,
                 min_doc_capacity=8, min_nnz_capacity=256,
                 min_vocab_capacity=64, query_batch=4,
                 max_query_terms=8, **kw)
    return Engine(cfg)


def close_tier(eng):
    if getattr(eng, "tier", None) is not None:
        eng.tier.close()


def rand_text(rng, n_lo=3, n_hi=12):
    n = int(rng.integers(n_lo, n_hi))
    return " ".join(WORDS[i] for i in rng.integers(0, len(WORDS), n))


def hits_key(hits, nd=4):
    return [(h.name, round(h.score, nd)) for h in hits]


def run_queries(eng, queries, k=5):
    return [hits_key(hits) for hits in eng.search_batch(queries, k=k)]


QUERIES = ["w0 w1 w2", "w5", "w10 w11 w12 w13", "w40 w41",
           "w7 w7 w7 w8", "w20 w30 w44", "w0", "w47 w46 w45"]


class TestTieredParity:
    @pytest.mark.parametrize("seed,budget_mb", [(0, 0), (7, 0), (3, 512)])
    def test_randomized_upsert_delete_merge_commit(self, tmp_path, seed,
                                                   budget_mb):
        """Tiered == untiered oracle == tier_bypass, exactly, across
        randomized mutation rounds. max_segments=2 forces inline merges
        nearly every commit, so the merge path's bound recomputation and
        tier splice (discard sources / admit merged) are both on the
        hot path of this test."""
        tiered = make_engine(tmp_path, "t", tier=True, budget_mb=budget_mb,
                             max_segments=2)
        oracle = make_engine(tmp_path, "o", max_segments=2)
        try:
            rng = np.random.default_rng(seed)
            names = []
            for round_ in range(6):
                for j in range(int(rng.integers(2, 6))):
                    name = f"d{round_}_{j}.txt"
                    text = rand_text(rng)
                    tiered.ingest_text(name, text)
                    oracle.ingest_text(name, text)
                    names.append(name)
                if names and rng.random() < 0.7:       # upsert
                    victim = names[int(rng.integers(0, len(names)))]
                    text = rand_text(rng)
                    tiered.ingest_text(victim, text)
                    oracle.ingest_text(victim, text)
                if len(names) > 4 and rng.random() < 0.5:   # delete
                    victim = names.pop(int(rng.integers(0, len(names))))
                    tiered.delete(victim)
                    oracle.delete(victim)
                tiered.commit()
                oracle.commit()
                got = run_queries(tiered, QUERIES)
                want = run_queries(oracle, QUERIES)
                assert got == want, f"tiered != oracle at round {round_}"
                # bypass oracle on the SAME engine: score everything,
                # no skip proofs — must agree bit-for-bit too
                tiered.searcher.tier_bypass = True
                try:
                    assert run_queries(tiered, QUERIES) == want
                finally:
                    tiered.searcher.tier_bypass = False
                # bypass faulted everything in; re-evict so the next
                # round exercises the cold path again
                tiered.tier.rebalance()
            st = tiered.tier_stats()
            assert st["enabled"]
            if budget_mb == 0:
                # every search streamed through the ring at least once
                assert st["cold_faults"] > 0
        finally:
            close_tier(tiered)

    def test_skip_occurrence_and_zero_bound(self, tmp_path):
        """A query sharing no term with a cold segment proves it
        skippable (bound exactly 0) without faulting it in."""
        eng = make_engine(tmp_path, "s", tier=True, budget_mb=0)
        try:
            for i in range(6):
                eng.ingest_text(f"a{i}.txt", f"w0 w1 w2 w{i % 4}")
            eng.commit()
            for i in range(6):
                eng.ingest_text(f"b{i}.txt", f"w20 w21 w22 w{20 + i % 4}")
            eng.commit()
            st0 = eng.tier_stats()
            hits = eng.search("w20 w21", k=3)
            assert all(h.name.startswith("b") for h in hits)
            st1 = eng.tier_stats()
            assert st1["segments_skipped"] > st0["segments_skipped"], \
                "the disjoint-vocab segment should be provably skipped"
            assert st1["cold_segments"] > 0
            assert st1["skip_rate"] > 0.0
        finally:
            close_tier(eng)

    def test_adversarial_cold_segment_holds_top1(self, tmp_path):
        """The global best doc lives in an evicted segment whose bound
        EXCEEDS the hot candidates' — it must fault in and win."""
        eng = make_engine(tmp_path, "adv", tier=True, budget_mb=0)
        try:
            # segment 1: the needle — one doc saturated with the query
            # term (highest tf -> highest bound and highest true score)
            eng.ingest_text("needle.txt", "w9 " * 12 + "w1")
            eng.commit()
            # segment 2: haystack docs that mention w9 once
            for i in range(6):
                eng.ingest_text(f"hay{i}.txt", f"w9 w2 w3 w{i % 5}")
            eng.commit()
            st0 = eng.tier_stats()
            hits = eng.search("w9", k=3)
            assert hits[0].name == "needle.txt"
            st1 = eng.tier_stats()
            assert st1["cold_faults"] > st0["cold_faults"], \
                "the winning segment was served without a cold fault?"
        finally:
            close_tier(eng)


class TestBoundSoundness:
    @pytest.mark.parametrize("model", ["bm25", "tfidf"])
    @pytest.mark.parametrize("seed", [0, 11])
    def test_bounds_dominate_scratch_recompute(self, tmp_path, model,
                                               seed):
        """query_upper_bounds vs an independent f64 scratch scorer over
        every live host doc of every segment, after randomized mutations
        — the bound must dominate the true max for every query."""
        eng = make_engine(tmp_path, f"b{model}{seed}", tier=True,
                          budget_mb=0, max_segments=2, model=model)
        try:
            rng = np.random.default_rng(seed)
            names = []
            for round_ in range(5):
                for j in range(int(rng.integers(3, 7))):
                    name = f"d{round_}_{j}.txt"
                    eng.ingest_text(name, rand_text(rng))
                    names.append(name)
                if len(names) > 3:
                    eng.delete(names.pop(int(rng.integers(0, len(names)))))
                eng.commit()
            snap = eng.index.snapshot
            n_docs = float(np.asarray(snap.n_docs))
            avgdl = float(np.asarray(snap.avgdl))
            df_host = snap.df_host
            k1, b = eng.config.bm25_k1, eng.config.bm25_b
            for _ in range(8):
                u = int(rng.integers(1, 6))
                uniq = np.sort(rng.choice(len(WORDS), size=u,
                                          replace=False)).astype(np.int64)
                qc = rng.integers(1, 4, size=(1, u)).astype(np.float64)
                df_u = df_host[uniq].astype(np.float64)
                for seg in eng.index._segments:
                    ub = query_upper_bounds(
                        seg.bounds, uniq, qc, df_u, n_docs, avgdl,
                        model=model, k1=k1, b=b, margin=0.0)
                    best = 0.0
                    for d, alive in zip(seg.host_docs, seg.live):
                        if not alive:
                            continue
                        pos = np.searchsorted(d.term_ids, uniq)
                        pos_c = np.minimum(pos,
                                           max(d.term_ids.shape[0] - 1, 0))
                        if d.term_ids.shape[0] == 0:
                            continue
                        m = d.term_ids[pos_c] == uniq
                        if not m.any():
                            continue
                        tf = d.tfs[pos_c[m]].astype(np.float64)
                        dfm = df_u[m]
                        if model == "bm25":
                            dl = float(eng.model.transform_doc_len(
                                np.asarray([d.length], np.float32))[0])
                            idf = np.log1p((n_docs - dfm + 0.5)
                                           / (dfm + 0.5))
                            norm = k1 * (1.0 - b + b * dl
                                         / max(avgdl, 1e-9))
                            w = idf * tf / (tf + norm)
                        else:
                            w = (np.log((1.0 + n_docs) / (1.0 + dfm))
                                 + 1.0) * tf
                        best = max(best, float((qc[0, m] * w).sum()))
                    assert best <= float(ub[0]) + 1e-9, \
                        (f"bound {ub[0]} < true max {best} for seg "
                         f"{seg.tier_uid} terms {uniq.tolist()}")
        finally:
            close_tier(eng)


class TestResidencyAccounting:
    def test_budget_zero_spills_everything(self, tmp_path):
        eng = make_engine(tmp_path, "z", tier=True, budget_mb=0)
        try:
            rng = np.random.default_rng(2)
            for r in range(3):
                for i in range(4):
                    eng.ingest_text(f"d{r}_{i}.txt", rand_text(rng))
                eng.commit()
            st = eng.tier_stats()
            assert st["hot_segments"] == 0
            assert st["cold_segments"] == len(eng.index._segments)
            assert st["spills"] >= st["cold_segments"]
            assert st["hot_bytes"] == 0
        finally:
            close_tier(eng)

    def test_big_budget_keeps_everything_hot(self, tmp_path):
        eng = make_engine(tmp_path, "h", tier=True, budget_mb=512)
        try:
            rng = np.random.default_rng(3)
            for r in range(3):
                for i in range(4):
                    eng.ingest_text(f"d{r}_{i}.txt", rand_text(rng))
                eng.commit()
            base_faults = eng.tier_stats()["cold_faults"]
            eng.search_batch(QUERIES, k=5)
            st = eng.tier_stats()
            assert st["cold_segments"] == 0
            assert st["hot_segments"] == len(eng.index._segments)
            assert st["cold_faults"] == base_faults == 0
            assert st["hot_hits"] > 0
            assert 0 < st["hot_bytes"] <= st["budget_bytes"]
            assert st["hit_rate"] == 1.0
        finally:
            close_tier(eng)

    def test_dense_plane_reserved_bytes(self, tmp_path):
        """PR 17's embedding column carves its device bytes out of the
        tier budget — it must show up in reserved_bytes, never be
        silently pinned on top of a 'full' budget."""
        eng = make_engine(tmp_path, "dr", tier=True, budget_mb=512,
                          embedding_enabled=True)
        try:
            rng = np.random.default_rng(4)
            for i in range(6):
                eng.ingest_text(f"d{i}.txt", rand_text(rng))
            eng.commit()
            ds = eng.dense.stats()
            assert ds["device_bytes"] > 0
            assert ds["host_bytes"] > 0
            assert ds["bytes"] == ds["device_bytes"] + ds["host_bytes"]
            assert eng.tier_stats()["reserved_bytes"] == ds["device_bytes"]
        finally:
            close_tier(eng)

    def test_df_witness_zero_under_tiering(self, tmp_path):
        """Tiering must not reintroduce the O(corpus) stat pass: after
        the first commit, steady-state tiered commits (with searches
        between — fault-ins included) never bump df_full_recomputes."""
        eng = make_engine(tmp_path, "w", tier=True, budget_mb=0)
        try:
            rng = np.random.default_rng(5)
            for i in range(4):
                eng.ingest_text(f"d{i}.txt", rand_text(rng))
            eng.commit()
            base = eng.index.df_full_recomputes
            assert base == 1           # first commit only
            for r in range(4):
                eng.ingest_text(f"n{r}.txt", rand_text(rng))
                eng.ingest_text("d0.txt", rand_text(rng))    # upsert
                eng.commit()
                eng.search_batch(QUERIES[:3], k=5)
            eng.delete("d1.txt")
            eng.commit()
            assert eng.index.df_full_recomputes == base, \
                "a steady-state tiered commit took the full recompute"
        finally:
            close_tier(eng)

    def test_checkpoint_roundtrip_readmits_segments(self, tmp_path):
        """Restore rebuilds segments fully resident; install_full_state
        must register each with the tier so the budget rebalance sees
        them — and parity must hold through the round trip."""
        eng = make_engine(tmp_path, "ck", tier=True, budget_mb=0)
        eng2 = None
        try:
            rng = np.random.default_rng(6)
            for r in range(3):
                for i in range(3):
                    eng.ingest_text(f"d{r}_{i}.txt", rand_text(rng))
                eng.commit()
            want = run_queries(eng, QUERIES)
            ckdir = str(tmp_path / "ck" / "ckpt")
            checkpoint.save_checkpoint(eng, ckdir)
            eng2 = checkpoint.load_checkpoint(ckdir, config=eng.config)
            assert eng2.tier is not None
            st = eng2.tier_stats()
            assert (st["hot_segments"] + st["cold_segments"]
                    == len(eng2.index._segments))
            # budget 0: the restore-time rebalance re-spilled everything
            assert st["cold_segments"] == len(eng2.index._segments)
            assert run_queries(eng2, QUERIES) == want
        finally:
            close_tier(eng)
            if eng2 is not None:
                close_tier(eng2)

    def test_cosine_refuses_tiering(self, tmp_path):
        """Per-doc cosine norms depend on the moving global df — no
        sound block-max bound exists, so the engine must refuse loudly
        instead of serving unsound skips."""
        with pytest.raises(ValueError, match="cosine"):
            make_engine(tmp_path, "cos", tier=True, budget_mb=0,
                        model="tfidf_cosine")


@pytest.mark.slow
class TestColdTierChaos:
    def test_bitrot_on_cold_spill_quarantine_repair_parity(self,
                                                           tmp_path):
        """Bit rot lands on a cold spill file between commit and query.
        The manifest gate in front of the mmap fault-in must catch it,
        quarantine the version dir, re-spill from the host replica, and
        the query must still return exact untiered-oracle parity
        (``make chaos-tier``)."""
        tiered = make_engine(tmp_path, "rot_t", tier=True, budget_mb=0)
        oracle = make_engine(tmp_path, "rot_o")
        try:
            rng = np.random.default_rng(7)
            for r in range(3):
                for i in range(4):
                    name, text = f"d{r}_{i}.txt", rand_text(rng)
                    tiered.ingest_text(name, text)
                    oracle.ingest_text(name, text)
                tiered.commit()
                oracle.commit()
            st0 = tiered.tier_stats()
            assert st0["cold_segments"] == len(tiered.index._segments)
            # arm one-shot rot on the first tf block of any spill — the
            # next integrity read through the seam flips a byte
            global_storage.arm(storage.BITROT, "*b0_tf.bin",
                               keep_bytes=3, times=1)
            got = run_queries(tiered, QUERIES)
            want = run_queries(oracle, QUERIES)
            assert got == want, "parity lost after mid-query bit rot"
            st1 = tiered.tier_stats()
            assert st1["quarantines"] >= 1, \
                "armed rot was never detected by the manifest gate"
            assert st1["repairs"] >= 1
            # the repaired spill must be clean: evict + re-fault with no
            # further quarantines
            tiered.tier.rebalance()
            assert run_queries(tiered, QUERIES) == want
            assert tiered.tier_stats()["quarantines"] == \
                st1["quarantines"]
        finally:
            global_storage.heal()
            close_tier(tiered)
            close_tier(oracle)

"""Real multi-PROCESS cluster test (SURVEY §4's prescribed shape).

The other cluster tests run nodes as threads sharing one in-process
coordination core; this one runs the actual deployment shape: a
standalone coordination service + three `python -m tfidf_tpu serve`
node processes talking HTTP, exercising election, upload placement,
scatter-gather search, and leader-kill failover across process
boundaries — what the reference only ever validated by hand
(TF-IDF-System-Core/README.md:96).
"""

import json
import os
import signal
import socket
import subprocess
import sys
import time
import urllib.request

import pytest


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _get(url: str, timeout=5.0) -> bytes:
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.read()


def _post(url: str, data: bytes, ctype="application/octet-stream",
          timeout=10.0) -> bytes:
    req = urllib.request.Request(url, data=data,
                                 headers={"Content-Type": ctype})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.read()


def _wait(pred, timeout=30.0, interval=0.2):
    deadline = time.monotonic() + timeout
    last = None
    while time.monotonic() < deadline:
        try:
            if pred():
                return True
        except Exception as e:
            last = e
        time.sleep(interval)
    raise AssertionError(f"timed out; last error: {last!r}")


@pytest.mark.timeout(300)
def test_three_process_cluster_with_failover(tmp_path):
    env = os.environ.copy()
    # TFIDF_JAX_PLATFORM (not JAX_PLATFORMS): ambient accelerator
    # plugins can override the plain env var; the CLI-level pin cannot
    # be (cli._apply_platform_override)
    env["TFIDF_JAX_PLATFORM"] = "cpu"
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    coord_port = _free_port()
    procs: list[subprocess.Popen] = []

    def spawn(args, **env_over):
        e = dict(env, **{k: str(v) for k, v in env_over.items()})
        p = subprocess.Popen(
            [sys.executable, "-m", "tfidf_tpu", *args],
            env=e, stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
            text=True)
        procs.append(p)
        return p

    try:
        spawn(["coordinator", "--listen", f"127.0.0.1:{coord_port}"])
        _wait(lambda: _get_coord_up(coord_port), timeout=60)

        ports = [_free_port() for _ in range(3)]
        urls = [f"http://127.0.0.1:{p}" for p in ports]
        for i, port in enumerate(ports):
            spawn(["serve", "--port", str(port), "--host", "127.0.0.1",
                   "--coordinator-address", f"127.0.0.1:{coord_port}",
                   "--documents-path", str(tmp_path / f"n{i}" / "docs"),
                   "--index-path", str(tmp_path / f"n{i}" / "index")],
                  TFIDF_SESSION_TIMEOUT_S="1.0",
                  TFIDF_HEARTBEAT_INTERVAL_S="0.2")
            # serial start -> deterministic election order (node 0 leads)
            _wait(lambda u=urls[i]: _get(u + "/api/status"), timeout=120)

        assert _get(urls[0] + "/api/status") == b"I am the leader"
        _wait(lambda: len(json.loads(_get(urls[0] + "/api/services"))) == 2)

        docs = {
            "a.txt": b"the quick brown fox jumps over the lazy dog",
            "b.txt": b"a fast brown fox and a quick red fox",
            "c.txt": b"lorem ipsum dolor sit amet",
            "d.txt": b"red dogs chase brown foxes at dawn",
        }
        for name, data in docs.items():
            _post(urls[0] + f"/leader/upload?name={name}", data)

        # first searches pay each worker's XLA compile, which can exceed
        # the leader's per-worker timeout (partial results are the
        # reference's per-worker tolerance, Leader.java:67-69) — poll
        # until every worker answers warm
        def full_results():
            res = json.loads(_post(urls[0] + "/leader/start", b"brown fox",
                                   ctype="application/json"))
            return set(res) == {"a.txt", "b.txt", "d.txt"}

        _wait(full_results, timeout=120, interval=1.0)

        # download must find the doc wherever placement put it
        got = _get(urls[0] + "/leader/download?path=c.txt")
        assert got == docs["c.txt"]

        # ---- failover: kill the leader process outright ----
        procs[1].send_signal(signal.SIGKILL)

        def promoted():
            for u in urls[1:]:
                if _get(u + "/api/status") == b"I am the leader":
                    return u
            return None

        new_leader = None

        def check():
            nonlocal new_leader
            new_leader = promoted()
            return new_leader is not None

        _wait(check, timeout=30)
        # the promoted node still serves cluster search over the
        # remaining worker's shard
        res = json.loads(_post(new_leader + "/leader/start", b"fox",
                               ctype="application/json"))
        assert isinstance(res, dict)
        services = json.loads(_get(new_leader + "/api/services"))
        assert len(services) == 1
    finally:
        for p in procs:
            try:
                p.kill()
            except Exception:
                pass
        for p in procs:
            try:
                p.wait(timeout=10)
            except Exception:
                pass


def _get_coord_up(port: int) -> bool:
    with socket.create_connection(("127.0.0.1", port), timeout=1.0):
        return True

"""Scale-out query plane: placement follower views, stateless routers,
any-node reads, and honest staleness (ISSUE 12).

The acceptance story: ANY node — or a dedicated stateless router
process — serves ``/leader/start`` reads with exact owner-merge
semantics (never the legacy sum-merge's replica double-count), every
reply stamped with the (epoch, generation) placement world it routed
under, while all mutations stay on the elected leader. A router whose
placement view is deliberately staled (partitioned from the
coordinator by the nemesis, or frozen by the deterministic hook)
degrades HONESTLY — ``X-Scatter-Degraded … stale_view=1``, result
cache bypassed — and self-heals on the next successful refresh.

Tier-1 (deterministic): follower load/watch-refresh/re-arm mechanics,
router exact parity + route stamps, per-router cache invalidation on
observed flushes, unmapped-hit dropping (never summing), write
forwarding, worker-death failover through a router, any-node reads,
the frozen/partitioned staleness contract, CLI surfaces, and the
committed BENCH_r07 multi-router scaling artifact.

Slow (``make chaos-router``): kill -9 a router AND the leader
mid-workload under 2x zipfian load through two routers — the
surviving router keeps serving, every admitted read is exact
single-node-oracle parity or honestly degraded, and the tier heals.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import pytest

from tfidf_tpu.cluster.coordination import (CoordinationClient,
                                            CoordinationCore,
                                            CoordinationServer,
                                            LocalCoordination)
from tfidf_tpu.cluster.nemesis import global_nemesis
from tfidf_tpu.cluster.node import SearchNode, http_get, http_post
from tfidf_tpu.cluster.placement import PlacementFollower, PlacementMap
from tfidf_tpu.cluster.router import QueryRouter, list_routers
from tfidf_tpu.utils.config import Config
from tfidf_tpu.utils.metrics import global_metrics

from tests.test_admission import _assert_parity, _oracle
from tests.test_cluster import wait_until


@pytest.fixture(autouse=True)
def _heal_nemesis():
    yield
    global_nemesis.heal()


@pytest.fixture
def core():
    c = CoordinationCore(session_timeout_s=0.5)
    yield c
    c.close()


RDOCS = {f"rt{i}.txt": f"common token{i} word{i % 3} extra{i % 5}"
         for i in range(12)}
RQUERIES = ["common", "token3 word0", "word1 extra2", "common token7"]

_CFG = dict(
    top_k=32, min_doc_capacity=64, min_nnz_capacity=1 << 12,
    min_vocab_capacity=1 << 10, query_batch=8, max_query_terms=8,
    rpc_max_attempts=1,            # deterministic: no hidden retries
    breaker_failure_threshold=2, breaker_reset_s=0.4,
    reconcile_sweep_interval_s=0.2, placement_flush_ms=10.0,
    replication_factor=2,
    # fast follower cadence so tests never wait on the 1s default;
    # staleness threshold small enough to exercise in-band
    router_refresh_ms=50.0, router_stale_ms=800.0,
    # node-side caches off: scatter mechanics are under test on the
    # nodes; ROUTER caches are exercised explicitly via the router's
    # own knob
    result_cache_entries=0,
    admission_rate_qps=0.0, admission_queue_high_water=10_000,
    admission_queue_critical=100_000)


def _node(core, tmp_path, i, **kw):
    cfg_kw = dict(_CFG)
    cfg_kw.update(kw)
    cfg = Config(
        documents_path=str(tmp_path / f"rr{i}" / "documents"),
        index_path=str(tmp_path / f"rr{i}" / "index"),
        port=0, **cfg_kw)
    return SearchNode(cfg, coord=LocalCoordination(core, 0.1)).start()


def _mk_cluster(core, tmp_path, n=3, **kw):
    nodes = [_node(core, tmp_path, i, **kw) for i in range(n)]
    wait_until(lambda: len(
        nodes[0].registry.get_all_service_addresses()) == n - 1)
    return nodes


def _mk_router(core, **kw):
    cfg_kw = dict(_CFG)
    cfg_kw.setdefault("router_cache_entries", 0)
    cfg_kw.update(kw)
    cfg = Config(port=0, **cfg_kw)
    return QueryRouter(cfg, coord=LocalCoordination(core, 0.1)).start()


def _stop_all(nodes):
    for nd in nodes:
        try:
            nd.stop()
        except Exception:
            pass


def _upload(leader, docs=RDOCS):
    batch = [{"name": n, "text": t} for n, t in docs.items()]
    return json.loads(http_post(leader.url + "/leader/upload-batch",
                                json.dumps(batch).encode()))


def _post_full(base, path, data, headers=None, timeout=30.0):
    """(status, headers, body) — the honesty headers are the subject
    here, so the plain-bytes helpers are not enough."""
    h = {"Content-Type": "application/json"}
    h.update(headers or {})
    req = urllib.request.Request(base + path, data=data, headers=h)
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, dict(r.headers), r.read()
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), e.read()


def _search_full(base, q, headers=None):
    st, hd, body = _post_full(base, "/leader/start",
                              json.dumps({"query": q}).encode(),
                              headers=headers)
    assert st == 200, (st, body[:200])
    return json.loads(body), hd


def _wait_view(router, n_docs, timeout=10.0):
    assert wait_until(
        lambda: router.placement.loaded
        and len(router.placement.replicas) == n_docs, timeout=timeout), \
        router.placement.view_snapshot()


# ---------------------------------------------------------------------------
# placement follower mechanics
# ---------------------------------------------------------------------------

class TestPlacementFollower:
    def _authoritative(self, coord):
        pm = PlacementMap(flush_ms=0.0, name="auth")
        pm.bind_store(lambda: coord)
        pm.set_persist_enabled(True)
        pm.epoch = 7
        return pm

    def _place(self, pm, name, workers):
        with pm.lock:
            pm.route_locked(name, list(workers),
                            {w: 0 for w in workers}, None, len(workers))
        for w in workers:
            pm.leg_success(name, w)

    def test_load_replaces_and_reports_lineage(self, core):
        ca, cb = LocalCoordination(core, 0.1), LocalCoordination(core, 0.1)
        pm = self._authoritative(ca)
        self._place(pm, "a", ["http://w1", "http://w2"])
        assert pm.flush()
        f = PlacementFollower(refresh_ms=60_000.0, stale_ms=0.0)
        f.bind_store(lambda: cb)
        assert f.refresh()
        assert f.loaded and f.version == 1
        assert set(f.replicas) == {"a"}
        assert sorted(f.replicas["a"]) == ["http://w1", "http://w2"]
        # the writing leader's lineage rides the payload
        assert f.loaded_epoch == 7
        assert f.loaded_gen == pm.gen
        # REPLACE semantics: a name that vanishes from the payload
        # vanishes from the view (never the new-leader merge)
        pm.forget(["a"])
        assert pm.flush()
        assert f.refresh()
        assert "a" not in f.replicas
        ca.close()
        cb.close()

    def test_watch_fires_refresh_and_rearms(self, core):
        ca, cb = LocalCoordination(core, 0.1), LocalCoordination(core, 0.1)
        pm = self._authoritative(ca)
        self._place(pm, "a", ["http://w1"])
        assert pm.flush()
        # refresh backstop parked FAR away: only the data watch can
        # deliver within the wait windows below
        f = PlacementFollower(refresh_ms=60_000.0, stale_ms=0.0)
        f.bind_store(lambda: cb)
        f.start()
        assert f.loaded and f.version == 1
        self._place(pm, "b", ["http://w1"])
        assert pm.flush()
        assert wait_until(lambda: f.version == 2), f.view_snapshot()
        assert "b" in f.replicas
        # one-shot watch re-armed: a SECOND flush propagates too
        self._place(pm, "c", ["http://w1"])
        assert pm.flush()
        assert wait_until(lambda: f.version == 3), f.view_snapshot()
        f.stop()
        ca.close()
        cb.close()

    def test_absent_znode_is_current_empty_not_failure(self, core):
        cb = LocalCoordination(core, 0.1)
        f = PlacementFollower(refresh_ms=60_000.0, stale_ms=500.0)
        f.bind_store(lambda: cb)
        f._started = True
        assert f.refresh()        # pre-first-flush cluster
        assert not f.suspect()    # confirmed current (empty IS a view)
        cb.close()

    def test_freeze_suspect_unfreeze_heals(self, core):
        ca, cb = LocalCoordination(core, 0.1), LocalCoordination(core, 0.1)
        pm = self._authoritative(ca)
        self._place(pm, "a", ["http://w1"])
        assert pm.flush()
        f = PlacementFollower(refresh_ms=30.0, stale_ms=200.0)
        f.bind_store(lambda: cb)
        f.start()
        assert not f.suspect()
        f.freeze()
        assert wait_until(lambda: f.suspect(), timeout=5.0)
        assert f.view_snapshot()["stale"]
        f.unfreeze()
        assert wait_until(lambda: not f.suspect(), timeout=5.0)
        f.stop()
        ca.close()
        cb.close()


# ---------------------------------------------------------------------------
# stateless router: exact reads, stamps, cache, failover, writes
# ---------------------------------------------------------------------------

class TestRouterReads:
    def test_exact_parity_and_route_stamp(self, core, tmp_path):
        """A router's reads are byte-equal to the leader's and to the
        single-node oracle (2 workers x R=2 = full replication, so
        per-shard stats match global stats), and every reply carries
        the (epoch, generation) placement world it routed under."""
        nodes = _mk_cluster(core, tmp_path)
        router = None
        try:
            leader = nodes[0]
            _upload(leader)
            router = _mk_router(core)
            _wait_view(router, len(RDOCS))
            want = _oracle(tmp_path, docs=RDOCS, queries=RQUERIES,
                           tag="r_oracle")
            for q in RQUERIES:
                via_leader = json.loads(http_post(
                    leader.url + "/leader/start",
                    json.dumps({"query": q}).encode()))
                got, hd = _search_full(router.url, q)
                assert got == via_leader
                _assert_parity(got, want[q], ctx=q)
                assert "X-Scatter-Degraded" not in hd
                # the route stamp: which placement world answered
                assert int(hd["X-Route-Epoch"]) == leader.placement.epoch
                assert int(hd["X-Route-Generation"]) == \
                    router.placement.loaded_gen
        finally:
            if router is not None:
                router.stop()
            _stop_all(nodes)

    def test_cache_hit_then_flush_invalidates(self, core, tmp_path):
        """The router cache token is (membership epoch, view version):
        repeats answer router-side without a scatter; an upload the
        leader flushes advances the observed version and the next read
        sees the new document."""
        nodes = _mk_cluster(core, tmp_path)
        router = None
        try:
            leader = nodes[0]
            _upload(leader)
            router = _mk_router(core, router_cache_entries=64)
            _wait_view(router, len(RDOCS))
            got1, _ = _search_full(router.url, "common")
            h0 = global_metrics.get("cache_hits", 0)
            got2, _ = _search_full(router.url, "common")
            assert got2 == got1
            assert global_metrics.get("cache_hits", 0) == h0 + 1
            v0 = router.placement.version
            http_post(leader.url + "/leader/upload-batch", json.dumps(
                [{"name": "fresh.txt", "text": "common fresh"}]).encode())
            assert wait_until(
                lambda: router.placement.version > v0
                and "fresh.txt" in router.placement.replicas)
            got3, hd3 = _search_full(router.url, "common")
            assert "fresh.txt" in got3, got3
            assert "X-Scatter-Degraded" not in hd3
        finally:
            if router is not None:
                router.stop()
            _stop_all(nodes)

    def test_worker_death_fails_over_exact(self, core, tmp_path):
        """The router runs the full PR-5 resilience stack: a dead
        worker's ownership slice fails over to the surviving replica
        within the request — full replication keeps results exact."""
        nodes = _mk_cluster(core, tmp_path)
        router = None
        try:
            leader = nodes[0]
            _upload(leader)
            router = _mk_router(core)
            _wait_view(router, len(RDOCS))
            want = _oracle(tmp_path, docs=RDOCS, queries=RQUERIES,
                           tag="r_oracle2")
            victim = next(n for n in nodes if not n.is_leader())
            victim.stop()
            assert wait_until(lambda: len(
                router.registry.get_all_service_addresses()) == 1)

            def parity():
                try:
                    got, _hd = _search_full(router.url, "common")
                    _assert_parity(got, want["common"], "post-death")
                    return True
                except AssertionError:
                    return False
            assert wait_until(parity, timeout=10.0)
        finally:
            if router is not None:
                router.stop()
            _stop_all(nodes)

    def test_unmapped_hits_dropped_never_summed(self, core, tmp_path):
        """A name OUTSIDE the follower view (here: written directly to
        both workers behind the leader's back) is dropped from
        router-routed merges and the reply is marked degraded — the
        legacy sum-merge would have silently double-counted the R
        copies. The leader's own results are its own business; the
        router must never fabricate a doubled score."""
        nodes = _mk_cluster(core, tmp_path)
        router = None
        try:
            leader = nodes[0]
            _upload(leader)
            router = _mk_router(core)
            _wait_view(router, len(RDOCS))
            for w in leader.registry.get_all_service_addresses():
                http_post(w + "/worker/upload?name=ghost.txt",
                          b"common ghost",
                          content_type="application/octet-stream")
            got, hd = _search_full(router.url, "common")
            assert "ghost.txt" not in got
            marker = hd.get("X-Scatter-Degraded", "")
            assert "dropped=" in marker and "dropped=0" not in marker, \
                (marker, got)
            assert global_metrics.get(
                "router_unmapped_hits_dropped", 0) > 0
        finally:
            if router is not None:
                router.stop()
            _stop_all(nodes)

    def test_writes_forward_to_leader(self, core, tmp_path):
        """Mutations stay on the elected leader: an upload and a
        delete POSTed at the router land through the leader's
        placement machinery (mapped, replicated, invalidated) and the
        read plane converges on the result."""
        nodes = _mk_cluster(core, tmp_path)
        router = None
        try:
            leader = nodes[0]
            _upload(leader)
            router = _mk_router(core)
            _wait_view(router, len(RDOCS))
            st, _hd, body = _post_full(
                router.url, "/leader/upload-batch", json.dumps(
                    [{"name": "viaRouter.txt",
                      "text": "common viarouter"}]).encode())
            assert st == 200, body
            # the LEADER's map owns the placement (not the router's)
            assert wait_until(
                lambda: leader.placement.holders_of("viaRouter.txt"))
            assert wait_until(
                lambda: "viaRouter.txt" in router.placement.replicas)
            got, _ = _search_full(router.url, "viarouter")
            assert "viaRouter.txt" in got
            st, _hd, body = _post_full(
                router.url, "/leader/delete",
                json.dumps({"names": ["viaRouter.txt"]}).encode())
            assert st == 200, body
            assert not leader.placement.holders_of("viaRouter.txt")
            assert wait_until(
                lambda: "viaRouter.txt" not in router.placement.replicas)

            def gone():
                got, _hd = _search_full(router.url, "viarouter")
                return "viaRouter.txt" not in got
            assert wait_until(gone, timeout=10.0)
            assert global_metrics.get("router_writes_proxied", 0) >= 2
        finally:
            if router is not None:
                router.stop()
            _stop_all(nodes)

    def test_download_probes_workers(self, core, tmp_path):
        nodes = _mk_cluster(core, tmp_path)
        router = None
        try:
            leader = nodes[0]
            _upload(leader)
            router = _mk_router(core)
            _wait_view(router, len(RDOCS))
            got = http_get(router.url + "/leader/download?path=rt0.txt")
            assert got == RDOCS["rt0.txt"].encode()
        finally:
            if router is not None:
                router.stop()
            _stop_all(nodes)

    def test_operator_surface(self, core, tmp_path):
        """/api/router + /api/routers + /api/status + /api/health: the
        tier is enumerable from any node and each router reports the
        placement world it routes under."""
        nodes = _mk_cluster(core, tmp_path)
        router = None
        try:
            leader = nodes[0]
            _upload(leader)
            router = _mk_router(core)
            _wait_view(router, len(RDOCS))
            assert http_get(router.url + "/api/status").decode() == \
                "I am a router"
            # registered under /router_registry, visible from any node
            assert json.loads(http_get(
                leader.url + "/api/routers")) == [router.url]
            assert list_routers(leader.coord) == [router.url]
            snap = json.loads(http_get(router.url + "/api/router"))
            assert snap["role"] == "router"
            assert snap["placement"]["docs"] == len(RDOCS)
            assert snap["placement"]["epoch"] == leader.placement.epoch
            # the leader's /api/router is the lag reference
            ref = json.loads(http_get(leader.url + "/api/router"))
            assert ref["placement"]["authoritative"] is True
            assert snap["placement"]["gen"] <= ref["placement"]["gen"]
            health = json.loads(http_get(router.url + "/api/health"))
            assert health["role"] == "router"
            assert health["admission"]["front_door"] == "router"
        finally:
            if router is not None:
                router.stop()
            _stop_all(nodes)


# ---------------------------------------------------------------------------
# any-node reads: the role split on SearchNode itself
# ---------------------------------------------------------------------------

class TestAnyNodeReads:
    def test_worker_served_reads_exact_parity(self, core, tmp_path):
        """THE role-split pin: a NON-leader node answers /leader/start
        through its placement follower view with exact owner-merge
        parity. Before the split, a worker's empty post-demotion map
        sent every hit through the legacy sum-merge — R=2 replication
        silently DOUBLED every score."""
        nodes = _mk_cluster(core, tmp_path)
        try:
            leader = nodes[0]
            _upload(leader)
            worker = next(n for n in nodes if not n.is_leader())
            assert wait_until(
                lambda: worker._follower_active()
                and len(worker.placement_follower.replicas)
                == len(RDOCS))
            want = _oracle(tmp_path, docs=RDOCS, queries=RQUERIES,
                           tag="r_oracle3")
            for q in RQUERIES:
                got, hd = _search_full(worker.url, q)
                _assert_parity(got, want[q], ctx=f"worker-served {q}")
                assert "X-Scatter-Degraded" not in hd
                assert "X-Route-Epoch" in hd
        finally:
            _stop_all(nodes)

    def test_worker_follower_watch_survives_session_rejoin(
            self, core, tmp_path):
        """A session expiry kills the follower's armed data watch with
        the session; the rejoin must re-arm it on the NEW client —
        otherwise any-node reads silently degrade to poll latency
        forever. The refresh backstop is parked far away, so only a
        working watch can deliver the post-rejoin flush in time."""
        cfg = Config(
            documents_path=str(tmp_path / "rj" / "documents"),
            index_path=str(tmp_path / "rj" / "index"), port=0,
            **dict(_CFG, router_refresh_ms=60_000.0))
        nodes = _mk_cluster(core, tmp_path, n=2)
        worker = SearchNode(
            cfg, coord_factory=lambda: LocalCoordination(core, 0.1)
        ).start()
        try:
            leader = nodes[0]
            wait_until(lambda: len(
                leader.registry.get_all_service_addresses()) == 2)
            _upload(leader)
            assert wait_until(lambda: worker._follower_active())
            rejoins0 = global_metrics.get("session_rejoins", 0)
            core.expire_session(worker.coord.sid)
            assert wait_until(lambda: global_metrics.get(
                "session_rejoins", 0) > rejoins0, timeout=15.0)
            v0 = worker.placement_follower.version
            http_post(leader.url + "/leader/upload-batch", json.dumps(
                [{"name": "postRejoin.txt",
                  "text": "common postrejoin"}]).encode())
            # watch latency, not the 60s backstop
            assert wait_until(
                lambda: worker.placement_follower.version > v0,
                timeout=10.0)
        finally:
            worker.stop()
            _stop_all(nodes)

    def test_worker_forwards_writes_to_leader(self, core, tmp_path):
        nodes = _mk_cluster(core, tmp_path)
        try:
            leader = nodes[0]
            _upload(leader)
            worker = next(n for n in nodes if not n.is_leader())
            st, _hd, body = _post_full(
                worker.url, "/leader/upload-batch", json.dumps(
                    [{"name": "viaWorker.txt",
                      "text": "common viaworker"}]).encode())
            assert st == 200, body
            # the LEADER placed it (the worker's own map stays empty —
            # it holds no authority)
            assert wait_until(
                lambda: leader.placement.holders_of("viaWorker.txt"))
            assert not worker.placement.holders_of("viaWorker.txt")
        finally:
            _stop_all(nodes)


# ---------------------------------------------------------------------------
# honest staleness: frozen + nemesis-partitioned router views
# ---------------------------------------------------------------------------

class TestStaleRouterHonesty:
    def test_frozen_view_degrades_and_bypasses_cache(self, core,
                                                     tmp_path):
        """A view that cannot be confirmed fresh marks EVERY response
        degraded (stale_view=1) and stops serving from the result
        cache — a pre-partition cache entry would be silently wrong in
        exactly the window the marker exists for. Un-freezing
        self-heals."""
        nodes = _mk_cluster(core, tmp_path)
        router = None
        try:
            leader = nodes[0]
            _upload(leader)
            router = _mk_router(core, router_cache_entries=64,
                                router_stale_ms=300.0)
            _wait_view(router, len(RDOCS))
            got1, hd1 = _search_full(router.url, "common")
            assert "X-Scatter-Degraded" not in hd1
            _search_full(router.url, "common")   # now cached
            router.placement.freeze()
            assert wait_until(lambda: router.placement.suspect(),
                              timeout=5.0)
            stale0 = global_metrics.get("router_stale_responses", 0)
            got2, hd2 = _search_full(router.url, "common")
            marker = hd2.get("X-Scatter-Degraded", "")
            assert "stale_view=1" in marker, marker
            # the cache was bypassed: a real scatter ran (attempted>0
            # shows in the stale-response counter, not a cache hit)
            assert global_metrics.get(
                "router_stale_responses", 0) > stale0
            assert got2 == got1   # data unchanged: still exact
            router.placement.unfreeze()
            assert wait_until(lambda: not router.placement.suspect())
            _got3, hd3 = _search_full(router.url, "common")
            assert "X-Scatter-Degraded" not in hd3
        finally:
            if router is not None:
                router.stop()
            _stop_all(nodes)

    @pytest.mark.timeout(180)
    def test_nemesis_partitioned_router_is_exact_or_degraded(
            self, tmp_path):
        """ISSUE 12 satellite: partition a router from the coordinator
        with the network nemesis, mutate placement behind its back (a
        rebalance flip AND a cluster-wide delete), and pin that every
        read through the stale router is exact or HONESTLY degraded —
        never silently double-counted, never a silently resurrected
        deleted document. Heal; the router converges to fresh-oracle
        parity with the marker gone."""
        srv = CoordinationServer(host="127.0.0.1", port=0).start()
        nodes, router = [], None
        try:
            def factory():
                return CoordinationClient(srv.address,
                                          heartbeat_interval_s=0.1)

            for i in range(3):
                cfg = Config(
                    documents_path=str(tmp_path / f"nm{i}" / "docs"),
                    index_path=str(tmp_path / f"nm{i}" / "idx"),
                    port=0, **_CFG)
                nodes.append(SearchNode(
                    cfg, coord_factory=factory).start())
            wait_until(lambda: len(
                nodes[0].registry.get_all_service_addresses()) == 2)
            leader = nodes[0]
            assert leader.is_leader()
            _upload(leader)
            rcfg = dict(_CFG)
            rcfg.update(router_stale_ms=400.0, router_refresh_ms=50.0)
            router = QueryRouter(Config(port=0, **rcfg),
                                 coord_factory=factory).start()
            _wait_view(router, len(RDOCS))
            want = _oracle(tmp_path, docs=RDOCS, queries=RQUERIES,
                           tag="nm_oracle")
            got0, hd0 = _search_full(router.url, "common")
            _assert_parity(got0, want["common"], "pre-partition")

            # cut the router's control plane only (data plane intact)
            global_nemesis.partition([router.url], [srv.address])
            assert wait_until(lambda: router.placement.suspect(),
                              timeout=10.0)

            # mutate placement behind the stale view: flip a doc range
            # off one worker and delete a doc cluster-wide
            victim = leader.registry.get_all_service_addresses()[0]
            names = leader.placement.names_on(victim)[:3]
            assert names
            leader.rebalancer.migrate(victim, names)
            deleted = "rt0.txt"
            json.loads(http_post(
                leader.url + "/leader/delete",
                json.dumps({"names": [deleted]}).encode()))

            fresh = _oracle(tmp_path,
                            docs={k: v for k, v in RDOCS.items()
                                  if k != deleted},
                            queries=RQUERIES, tag="nm_oracle2")
            # reads through the STALE router: never silently wrong —
            # every response carries the honest marker (so a deleted
            # doc can only ever appear in a MARKED reply), and no doc
            # is ever double-counted (a replica-summed score would be
            # ~2x either world's; per-shard stats drifting through the
            # mid-reconcile windows stay far below that)
            ceilings = {
                n: 1.9 * max(want["common"].get(n, 0.0),
                             fresh["common"].get(n, 0.0))
                for n in want["common"]}
            for _ in range(5):
                got, hd = _search_full(router.url, "common")
                marker = hd.get("X-Scatter-Degraded", "")
                assert "stale_view=1" in marker, marker
                for n, s in got.items():
                    assert n in want["common"], f"unknown doc {n}"
                    assert s < ceilings[n], \
                        f"score for {n} looks replica-doubled: {s}"
                time.sleep(0.2)

            # heal: the view refreshes, the marker clears, results
            # converge to the fresh oracle exactly
            global_nemesis.heal()
            assert wait_until(lambda: not router.placement.suspect(),
                              timeout=15.0)

            def healed():
                got, hd = _search_full(router.url, "common")
                if "X-Scatter-Degraded" in hd:
                    return False
                if deleted in got:
                    return False
                try:
                    _assert_parity(got, fresh["common"], "healed")
                    return True
                except AssertionError:
                    return False
            assert wait_until(healed, timeout=30.0)
        finally:
            if router is not None:
                router.stop()
            _stop_all(nodes)
            srv.close()


class TestWriteForwardingEdges:
    def test_dead_published_leader_forwards_503_with_retry_after(
            self, core, tmp_path):
        """A leader that is published (ephemeral not yet expired) but
        DEAD must surface to the writing client as 503 + Retry-After —
        an honest try-again — never a bare 500 with no backoff hint."""
        from tfidf_tpu.cluster.registry import publish_leader_info

        coord = LocalCoordination(core, 0.1)
        publish_leader_info(coord, "http://127.0.0.1:9")  # discard port
        router = _mk_router(core)
        try:
            st, hd, body = _post_full(
                router.url, "/leader/upload-batch",
                json.dumps([{"name": "x.txt", "text": "x"}]).encode())
            assert st == 503, (st, body)
            assert hd.get("Retry-After") == "1"
            assert json.loads(body)["error"] == "leader unavailable"
        finally:
            router.stop()
            coord.close()

    def test_forwarded_writes_pass_local_admission_first(self, core,
                                                         tmp_path):
        """The admit-before-body-read discipline holds on the proxy
        path: a router under backpressure sheds a forwarded mutation
        LOCALLY (429 + shed headers) before buffering or contacting
        the leader."""
        nodes = _mk_cluster(core, tmp_path)
        router = None
        try:
            leader = nodes[0]
            _upload(leader)
            router = _mk_router(core, admission_queue_high_water=1,
                                admission_queue_critical=10)
            _wait_view(router, len(RDOCS))
            proxied0 = global_metrics.get("router_writes_proxied", 0)
            # saturate the backpressure signal the router's depth_fn
            # reads (the gauge side of the max)
            global_metrics.set_gauge(
                "last_router_scatter_queue_depth", 999)
            try:
                st, hd, body = _post_full(
                    router.url, "/leader/upload-batch", json.dumps(
                        [{"name": "x.txt", "text": "x"}]).encode())
            finally:
                global_metrics.set_gauge(
                    "last_router_scatter_queue_depth", 0)
            assert st == 429, (st, body)
            assert hd.get("X-Shed-Reason") == "backpressure"
            assert "Retry-After" in hd
            # the leader was never contacted — shed before forwarding
            assert global_metrics.get(
                "router_writes_proxied", 0) == proxied0
            assert not leader.placement.holders_of("x.txt")
        finally:
            if router is not None:
                router.stop()
            _stop_all(nodes)

    def test_cli_via_router_shed_exits_tempfail(self, core, tmp_path):
        """A shedding router turns the CLI query into the polite-shed
        exit (EX_TEMPFAIL 75 + message), never a raw HTTPError
        traceback — same contract as the --leader path."""
        from tfidf_tpu.cli import main as cli_main

        nodes = _mk_cluster(core, tmp_path)
        router = None
        try:
            leader = nodes[0]
            _upload(leader)
            router = _mk_router(core, admission_queue_critical=10,
                                admission_retry_after_s=0.05)
            _wait_view(router, len(RDOCS))
            global_metrics.set_gauge(
                "last_router_scatter_queue_depth", 999)
            try:
                with pytest.raises(SystemExit) as exc:
                    cli_main(["query", "common", "--via-router",
                              router.url])
                assert exc.value.code == 75
            finally:
                global_metrics.set_gauge(
                    "last_router_scatter_queue_depth", 0)
        finally:
            if router is not None:
                router.stop()
            _stop_all(nodes)


# ---------------------------------------------------------------------------
# CLI surfaces
# ---------------------------------------------------------------------------

class TestRouterCli:
    def test_query_via_router_and_status_block(self, core, tmp_path,
                                               capsys):
        from tfidf_tpu.cli import main as cli_main

        nodes = _mk_cluster(core, tmp_path)
        router = None
        try:
            leader = nodes[0]
            _upload(leader)
            router = _mk_router(core)
            _wait_view(router, len(RDOCS))
            rc = cli_main(["query", "common", "--via-router",
                           router.url])
            assert rc == 0
            out = capsys.readouterr()
            got = json.loads(out.out)
            assert len(got) == min(12, _CFG["top_k"])
            assert "X-Route-Epoch" in out.err

            # let in-flight leg confirmations settle so the lag
            # comparison sees one quiescent generation on both sides
            assert wait_until(
                lambda: router.placement.loaded_gen
                == leader.placement.gen, timeout=10.0)
            rc = cli_main(["status", "--leader", leader.url])
            assert rc == 0
            st = json.loads(capsys.readouterr().out)
            rb = st["routers"]
            assert rb["count"] == 1
            entry = rb["routers"][0]
            assert entry["url"] == router.url
            assert entry["reachable"] is True
            assert entry["stale"] is False
            assert entry["gen_lag"] == 0
            assert entry["epoch_lag"] == 0
        finally:
            if router is not None:
                router.stop()
            _stop_all(nodes)


# ---------------------------------------------------------------------------
# the committed multi-router scaling artifact
# ---------------------------------------------------------------------------

class TestBenchArtifact:
    def test_bench_r07_scaling_table(self):
        """BENCH_r07.json (make bench-routers) is the headline
        artifact: admitted interactive q/s through 1/2/4 stateless
        routers at equal offered load, 2 routers >= 1.6x the 1-router
        baseline (the acceptance bar), parity-checked in-run."""
        import os
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "..", "BENCH_r07.json")
        with open(path, encoding="utf-8") as f:
            art = json.load(f)
        assert art["metric"] == "router_scaleout_admitted_qps_2r"
        table = art["extra"]["routers"]
        assert set(table) == {"1", "2", "4"}
        q1 = table["1"]["admitted_qps"]
        q2 = table["2"]["admitted_qps"]
        assert q1 > 0
        ratio = q2 / q1
        assert ratio >= 1.6, f"2-router scaling {ratio:.2f}x < 1.6x"
        assert art["extra"]["scaling_2r_vs_1r"] == pytest.approx(
            ratio, rel=1e-3)
        # in-run correctness gate: the bench cross-checks router
        # results against the leader's before measuring
        assert art["extra"]["parity_checked"] is True


# ---------------------------------------------------------------------------
# chaos (slow): kill -9 a router and the leader mid-workload
# ---------------------------------------------------------------------------

@pytest.mark.slow
class TestChaosRouter:
    @pytest.mark.timeout(300)
    def test_router_and_leader_kill9_survivors_exact(self, tmp_path):
        """``make chaos-router``: 2x zipfian-ish closed-loop load
        through two stateless routers; mid-workload a router AND the
        node leader are killed -9. The surviving router keeps serving
        — every 200 it returns is exact single-node-oracle parity or
        honestly degraded — and after the new leader settles, reads
        through it converge to exact parity with no marker."""
        import os
        import signal
        import socket
        import subprocess
        import sys

        def free_port():
            with socket.socket() as s:
                s.bind(("127.0.0.1", 0))
                return s.getsockname()[1]

        env = os.environ.copy()
        env["TFIDF_JAX_PLATFORM"] = "cpu"
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("XLA_FLAGS", None)
        env.update({
            "TFIDF_REPLICATION_FACTOR": "2",
            "TFIDF_TOP_K": "32",
            "TFIDF_SESSION_TIMEOUT_S": "1.0",
            "TFIDF_HEARTBEAT_INTERVAL_S": "0.2",
            "TFIDF_RECONCILE_SWEEP_INTERVAL_S": "0.5",
            "TFIDF_MIN_DOC_CAPACITY": "64",
            "TFIDF_MIN_NNZ_CAPACITY": "4096",
            "TFIDF_MIN_VOCAB_CAPACITY": "1024",
            "TFIDF_QUERY_BATCH": "8",
            "TFIDF_MAX_QUERY_TERMS": "8",
            "TFIDF_ROUTER_REFRESH_MS": "200",
            "TFIDF_ROUTER_STALE_MS": "3000",
            "TFIDF_ROUTER_CACHE_ENTRIES": "64",
        })
        procs = {}

        def spawn(tag, args):
            p = subprocess.Popen(
                [sys.executable, "-m", "tfidf_tpu", *args],
                env=env, stdout=subprocess.DEVNULL,
                stderr=subprocess.DEVNULL)
            procs[tag] = p
            return p

        def wait_pred(pred, timeout=60.0, interval=0.2):
            deadline = time.monotonic() + timeout
            last = None
            while time.monotonic() < deadline:
                try:
                    if pred():
                        return True
                except Exception as e:
                    last = e
                time.sleep(interval)
            raise AssertionError(f"timed out; last={last!r}")

        coord_port = free_port()
        try:
            spawn("coord", ["coordinator", "--listen",
                            f"127.0.0.1:{coord_port}"])
            wait_pred(lambda: socket.create_connection(
                ("127.0.0.1", coord_port), timeout=1.0).close() or True)
            nports = [free_port() for _ in range(3)]
            nurls = [f"http://127.0.0.1:{p}" for p in nports]
            for i, p in enumerate(nports):
                spawn(f"n{i}", [
                    "serve", "--port", str(p), "--host", "127.0.0.1",
                    "--coordinator-address", f"127.0.0.1:{coord_port}",
                    "--documents-path", str(tmp_path / f"cr{i}/docs"),
                    "--index-path", str(tmp_path / f"cr{i}/idx")])
                wait_pred(lambda u=nurls[i]: http_get(
                    u + "/api/status", timeout=5.0), timeout=120)
            leader = nurls[0]
            wait_pred(lambda: len(json.loads(http_get(
                leader + "/api/services"))) == 2)
            _docs = {f"cr{i}.txt":
                     f"common token{i} word{i % 3} extra{i % 5}"
                     for i in range(24)}
            http_post(leader + "/leader/upload-batch", json.dumps(
                [{"name": n, "text": t}
                 for n, t in _docs.items()]).encode())

            rports = [free_port() for _ in range(2)]
            rurls = [f"http://127.0.0.1:{p}" for p in rports]
            for i, p in enumerate(rports):
                spawn(f"r{i}", [
                    "router", "--coordinator",
                    f"127.0.0.1:{coord_port}",
                    "--host", "127.0.0.1", "--port", str(p)])
                wait_pred(lambda u=rurls[i]: json.loads(http_get(
                    u + "/api/router"))["placement"]["docs"]
                    == len(_docs), timeout=120)

            qpool = ["common"] + [f"token{i} word{i % 3}"
                                  for i in range(24)] + \
                    [f"extra{k} common" for k in range(5)]
            want = _oracle(tmp_path, docs=_docs, queries=qpool,
                           tag="cr_oracle")

            def check_200(base, q):
                """One read: 200 ⇒ exact parity OR degraded marker."""
                st, hd, body = _post_full(
                    base, "/leader/start",
                    json.dumps({"query": q}).encode(), timeout=30.0)
                if st != 200:
                    return None
                got = json.loads(body)
                if "X-Scatter-Degraded" in hd:
                    return "degraded"
                _assert_parity(got, want[q], ctx=f"{base} {q}")
                return "exact"

            # sanity: both routers exact pre-chaos
            for u in rurls:
                wait_pred(lambda u=u: check_200(u, "common") == "exact",
                          timeout=60)

            stop_flag = threading.Event()
            outcomes = {"exact": 0, "degraded": 0, "failed": 0}
            olock = threading.Lock()
            errors = []

            def client(cid):
                import random
                rng = random.Random(cid)
                i = 0
                while not stop_flag.is_set():
                    base = rurls[i % 2] if cid % 2 else rurls[1]
                    q = qpool[int(rng.random() ** 2 * len(qpool))]
                    i += 1
                    try:
                        verdict = check_200(base, q)
                    except AssertionError as e:
                        errors.append(str(e)[:300])
                        return
                    except Exception:
                        verdict = None   # killed router / transient
                    with olock:
                        outcomes[verdict or "failed"] = \
                            outcomes.get(verdict or "failed", 0) + 1

            threads = [threading.Thread(target=client, args=(c,),
                                        daemon=True) for c in range(6)]
            for t in threads:
                t.start()
            time.sleep(3.0)
            # kill -9 a router AND the leader mid-workload
            os.kill(procs["r0"].pid, signal.SIGKILL)
            os.kill(procs["n0"].pid, signal.SIGKILL)
            time.sleep(12.0)
            stop_flag.set()
            for t in threads:
                t.join(timeout=30)
            assert not errors, errors[:3]
            # the surviving router kept ADMITTING exact reads
            assert outcomes["exact"] > 20, outcomes

            # post-chaos: the survivor converges to exact, unmarked
            # parity (the dead worker-leader's docs survive on the
            # replica; a new leader re-publishes the placement map)
            def settled():
                return check_200(rurls[1], "common") == "exact"
            wait_pred(settled, timeout=120, interval=1.0)
        finally:
            for p in procs.values():
                try:
                    p.kill()
                except Exception:
                    pass
            for p in procs.values():
                try:
                    p.wait(timeout=10)
                except Exception:
                    pass

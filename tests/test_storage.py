"""Storage-fault nemesis + crash-consistent durability (ISSUE 14).

The contract under test, cell by cell: **no seeded storage fault ever
produces silently wrong search results** — every corruption either
transparently recovers to the previous good state or refuses loudly.

- the durable-IO seam primitives (`utils/storage.py`): atomic publish
  under torn writes / fsync EIO / ENOSPC / crash-around-rename, CRC
  envelopes catching bit rot, manifests, group commit;
- the checkpoint corruption matrix: truncated `docs.npz`, a flipped
  byte in EACH manifest-covered file, a missing manifest — restore
  falls back to the newest intact version (quarantining the bad one)
  with results exactly equal to that version's, and refuses loudly
  when no intact version exists;
- torn / bit-rotted `fence_epoch.json` (a flipped digit is valid JSON
  with a WRONG lower epoch — the CRC envelope must catch it);
- WAL torn tail and snapshot bit rot × restart;
- the ENOSPC wire contract: distinct 507, non-retryable, never a
  breaker trip, `storage_enospc` counted;
- fsync-before-ack with group commit on the upload plane;
- the integrity scrub: a rotten `placed_docs` copy repaired from a
  healthy replica, an unrepairable one surfaced loudly, a corrupt
  checkpoint version quarantined while its fallback exists.

The slow job (`make chaos-powerloss`) is the acceptance criterion end
to end: SIGKILL of EVERY node and the coordinator mid-workload under
active disk faults, full restart on the same dirs, zero acked-upload
loss, exact single-node-oracle parity on every post-restart search.
"""

import json
import os
import shutil
import threading
import urllib.error
import zlib

import pytest

from tfidf_tpu.cluster.coordination import CoordinationCore, \
    LocalCoordination
from tfidf_tpu.cluster.fencing import FenceGuard
from tfidf_tpu.cluster.node import SearchNode, http_post
from tfidf_tpu.cluster.resilience import is_retryable, is_worker_fault
from tfidf_tpu.cluster.wal import DurableStore
from tfidf_tpu.engine.checkpoint import (load_checkpoint,
                                         restore_checkpoint,
                                         save_checkpoint)
from tfidf_tpu.utils import storage
from tfidf_tpu.utils.config import Config
from tfidf_tpu.utils.metrics import global_metrics
from tfidf_tpu.utils.storage import (DiskFault, StorageCorruption,
                                     global_storage)

from tests.test_cluster import wait_until
from tests.test_engine import ingest_corpus, make_engine


# ---------------------------------------------------------------------------
# seam primitives under the disk nemesis
# ---------------------------------------------------------------------------

class TestSeamPrimitives:
    def test_atomic_write_roundtrip(self, tmp_path):
        p = str(tmp_path / "f.txt")
        storage.atomic_write_bytes(p, b"one")
        storage.atomic_write_bytes(p, b"two")
        assert storage.read_bytes(p) == b"two"
        assert not [n for n in os.listdir(tmp_path) if ".tmp" in n]

    def test_torn_write_never_tears_published_file(self, tmp_path):
        p = str(tmp_path / "f.txt")
        storage.atomic_write_bytes(p, b"committed content")
        global_storage.arm(storage.TORN_WRITE, f"{p}*", keep_bytes=3)
        with pytest.raises(DiskFault):
            storage.atomic_write_bytes(p, b"replacement that crashes")
        global_storage.heal()
        # the published name still holds the complete OLD content and
        # the torn temp never leaks
        assert storage.read_bytes(p) == b"committed content"
        assert os.listdir(tmp_path) == ["f.txt"]

    def test_fsync_eio_fails_before_publish(self, tmp_path):
        p = str(tmp_path / "f.txt")
        storage.atomic_write_bytes(p, b"old")
        global_storage.arm(storage.FSYNC_EIO, f"{p}*", times=1)
        with pytest.raises(DiskFault):
            storage.atomic_write_bytes(p, b"new")
        global_storage.heal()
        assert storage.read_bytes(p) == b"old"

    def test_crash_before_and_after_rename(self, tmp_path):
        p = str(tmp_path / "f.txt")
        storage.atomic_write_bytes(p, b"old")
        global_storage.arm(storage.CRASH_BEFORE_RENAME, p, times=1)
        with pytest.raises(DiskFault):
            storage.atomic_write_bytes(p, b"new")
        assert storage.read_bytes(p) == b"old"   # publish never happened
        global_storage.heal()
        global_storage.arm(storage.CRASH_AFTER_RENAME, p, times=1)
        with pytest.raises(DiskFault):
            storage.atomic_write_bytes(p, b"new")
        global_storage.heal()
        assert storage.read_bytes(p) == b"new"   # publish DID land

    def test_enospc_is_counted_and_classified(self, tmp_path):
        p = str(tmp_path / "f.txt")
        global_storage.arm(storage.ENOSPC, f"{p}*")
        before = global_metrics.get("storage_enospc") or 0
        with pytest.raises(OSError) as ei:
            storage.atomic_write_bytes(p, b"x")
        global_storage.heal()
        assert storage.is_enospc(ei.value)
        assert (global_metrics.get("storage_enospc") or 0) > before

    def test_json_envelope_catches_bitrot(self, tmp_path):
        p = str(tmp_path / "state.json")
        storage.atomic_write_json(p, {"epoch": 173})
        assert storage.read_json(p) == {"epoch": 173}
        global_storage.arm(storage.BITROT, p, keep_bytes=30)
        with pytest.raises(StorageCorruption):
            storage.read_json(p)
        global_storage.heal()
        # legacy (pre-envelope) files stay readable across the upgrade
        with open(str(tmp_path / "legacy.json"), "w") as f:
            json.dump({"epoch": 9}, f)
        assert storage.read_json(str(tmp_path / "legacy.json")) == \
            {"epoch": 9}

    def test_env_rule_loading(self):
        n = global_storage.load_env(
            '[{"kind": "torn_write", "glob": "*never-matches-xyz*",'
            ' "probability": 0.5, "times": 2, "keep_bytes": 8}]')
        assert n == 1 and global_storage.active()
        global_storage.heal()


class TestManifest:
    def _mkdir(self, tmp_path):
        d = str(tmp_path / "v1")
        os.makedirs(d)
        for name, data in (("a.bin", b"alpha" * 10),
                           ("b.json", b'{"k": 1}')):
            storage.write_bytes(os.path.join(d, name), data)
        storage.write_manifest(d)
        return d

    def test_intact_dir_verifies_clean(self, tmp_path):
        assert storage.verify_manifest(self._mkdir(tmp_path)) == []

    def test_flipped_byte_in_each_file_detected(self, tmp_path):
        for victim in ("a.bin", "b.json"):
            d = self._mkdir(tmp_path / victim.replace(".", "_"))
            p = os.path.join(d, victim)
            raw = bytearray(open(p, "rb").read())
            raw[2] ^= 0x01
            open(p, "wb").write(bytes(raw))
            problems = storage.verify_manifest(d)
            assert problems and victim in problems[0]

    def test_truncation_and_missing_file_detected(self, tmp_path):
        d = self._mkdir(tmp_path)
        with open(os.path.join(d, "a.bin"), "r+b") as f:
            f.truncate(5)
        assert any("a.bin" in p for p in storage.verify_manifest(d))
        os.unlink(os.path.join(d, "a.bin"))
        assert any("missing" in p for p in storage.verify_manifest(d))

    def test_missing_or_rotten_manifest_is_loud(self, tmp_path):
        d = self._mkdir(tmp_path)
        mp = os.path.join(d, storage.MANIFEST_NAME)
        raw = bytearray(open(mp, "rb").read())
        raw[len(raw) // 2] ^= 0x5A
        open(mp, "wb").write(bytes(raw))
        assert any("manifest" in p for p in storage.verify_manifest(d))
        os.unlink(mp)
        assert any("manifest missing" in p
                   for p in storage.verify_manifest(d))


class TestGroupCommit:
    def test_concurrent_syncs_coalesce_and_complete(self, tmp_path):
        gc = storage.GroupCommitter()
        paths = []
        for i in range(24):
            p = str(tmp_path / f"f{i}")
            storage.write_bytes(p, b"x" * 64)
            paths.append(p)
        errs = []
        gate = threading.Event()

        def worker(p):
            gate.wait()
            try:
                gc.sync([p, str(tmp_path)])
            except Exception as e:   # noqa: BLE001
                errs.append(e)

        threads = [threading.Thread(target=worker, args=(p,))
                   for p in paths]
        for t in threads:
            t.start()
        gate.set()
        for t in threads:
            t.join(timeout=30)
        assert not errs
        # coalescing happened: far fewer flush rounds than callers is
        # timing-dependent, but EVERY caller was serviced by SOME round
        assert (global_metrics.get("storage_group_commit_items") or 0) \
            >= 24

    def test_fsync_failure_reaches_only_the_right_caller(self, tmp_path):
        gc = storage.GroupCommitter()
        good = str(tmp_path / "good")
        bad = str(tmp_path / "bad")
        storage.write_bytes(good, b"g")
        storage.write_bytes(bad, b"b")
        global_storage.arm(storage.FSYNC_EIO, bad)
        results = {}

        def run(tag, p):
            try:
                gc.sync([p])
                results[tag] = "ok"
            except OSError:
                results[tag] = "err"

        ts = [threading.Thread(target=run, args=("good", good)),
              threading.Thread(target=run, args=("bad", bad))]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=30)
        global_storage.heal()
        assert results == {"good": "ok", "bad": "err"}


# ---------------------------------------------------------------------------
# checkpoint corruption matrix (recovery-or-loud-refusal, never silent)
# ---------------------------------------------------------------------------

def _results(e, queries=("fast food", "cat night", "fast")):
    return {q: [(h.name, round(float(h.score), 5))
                for h in e.search(q, k=10)] for q in queries}


@pytest.fixture
def two_version_ckpt(tmp_path):
    """A checkpoint base with two intact versions: v1 (the fallback
    state) and v2 (the published state, with one extra doc)."""
    e = make_engine(tmp_path)
    ingest_corpus(e)
    ckpt = str(tmp_path / "ckpt")
    save_checkpoint(e, ckpt)
    want_v1 = _results(e)
    e.ingest_text("extra.txt", "fresh fast document")
    e.commit()
    save_checkpoint(e, ckpt)
    want_v2 = _results(e)
    assert want_v1 != want_v2
    return e.config, ckpt, want_v1, want_v2


CKPT_FILES = ("vocab.txt", "docs.npz", "names.json", "meta.json",
              "snapshot.npz")


class TestCheckpointCorruptionMatrix:
    def _current(self, ckpt):
        return os.path.join(os.path.dirname(ckpt), os.readlink(ckpt))

    def _flip(self, path, offset=100):
        raw = bytearray(open(path, "rb").read())
        raw[offset % len(raw)] ^= 0x01
        open(path, "wb").write(bytes(raw))

    @pytest.mark.parametrize("victim", CKPT_FILES)
    def test_flipped_byte_falls_back_to_intact_version(
            self, two_version_ckpt, victim):
        cfg, ckpt, want_v1, _want_v2 = two_version_ckpt
        vdir = self._current(ckpt)
        if not os.path.exists(os.path.join(vdir, victim)):
            pytest.skip(f"{victim} not in this checkpoint layout")
        self._flip(os.path.join(vdir, victim))
        # strict load refuses loudly...
        with pytest.raises(StorageCorruption):
            load_checkpoint(ckpt, cfg)
        # ...and the fallback restore recovers EXACTLY the previous
        # good state, quarantining the corrupt version
        e2, _meta = restore_checkpoint(ckpt, cfg)
        assert _results(e2) == want_v1
        assert any(".quarantine" in d
                   for d in os.listdir(os.path.dirname(ckpt)))
        assert (global_metrics.get("checkpoint_fallbacks") or 0) >= 1

    def test_truncated_docs_npz_falls_back(self, two_version_ckpt):
        cfg, ckpt, want_v1, _ = two_version_ckpt
        p = os.path.join(self._current(ckpt), "docs.npz")
        with open(p, "r+b") as f:
            f.truncate(os.path.getsize(p) // 2)
        e2, _meta = restore_checkpoint(ckpt, cfg)
        assert _results(e2) == want_v1

    def test_missing_manifest_falls_back(self, two_version_ckpt):
        cfg, ckpt, want_v1, _ = two_version_ckpt
        os.unlink(os.path.join(self._current(ckpt),
                               storage.MANIFEST_NAME))
        e2, _meta = restore_checkpoint(ckpt, cfg)
        assert _results(e2) == want_v1

    def test_dangling_symlink_still_finds_fallback(self,
                                                   two_version_ckpt):
        """After a quarantine the published symlink dangles —
        ``os.path.isdir(base)`` is False, but the boot gate
        (``checkpoint_versions``) must still surface the intact
        fallback so serve restores instead of paying a full re-walk."""
        from tfidf_tpu.engine.checkpoint import (checkpoint_versions,
                                                 quarantine_version)
        cfg, ckpt, want_v1, _ = two_version_ckpt
        quarantine_version(self._current(ckpt))
        assert not os.path.isdir(ckpt)          # the dangling link
        assert checkpoint_versions(ckpt)        # ...still has versions
        e2, _meta = restore_checkpoint(ckpt, cfg)
        assert _results(e2) == want_v1

    def test_legacy_pre_manifest_checkpoint_still_loads(
            self, two_version_ckpt):
        """In-place upgrade path: checkpoints saved before the manifest
        format exist with NO MANIFEST.json anywhere. They are
        unverifiable, not corrupt — restore must last-resort load the
        newest one (loud warning + metric) instead of quarantining
        every valid checkpoint and forcing a full re-walk."""
        cfg, ckpt, _v1, want_v2 = two_version_ckpt
        parent = os.path.dirname(ckpt)
        for d in os.listdir(parent):
            mp = os.path.join(parent, d, storage.MANIFEST_NAME)
            if d.startswith("ckpt.v") and os.path.isfile(mp):
                os.unlink(mp)
        e2, _meta = restore_checkpoint(ckpt, cfg)
        assert _results(e2) == want_v2   # the PUBLISHED version wins
        assert (global_metrics.get("checkpoint_legacy_loads") or 0) >= 1
        assert not any(".quarantine" in d for d in os.listdir(parent))

    def test_all_versions_corrupt_refuses_loudly(self, two_version_ckpt):
        cfg, ckpt, _v1, _v2 = two_version_ckpt
        parent = os.path.dirname(ckpt)
        for d in os.listdir(parent):
            full = os.path.join(parent, d)
            if d.startswith("ckpt.v") and os.path.isdir(full):
                self._flip(os.path.join(full, "docs.npz"))
        with pytest.raises(StorageCorruption):
            restore_checkpoint(ckpt, cfg)

    def test_bitrot_on_read_back_is_caught(self, two_version_ckpt):
        """The nemesis BITROT kind: bytes rot on the platter between
        save and load — the manifest verification reads through the
        seam and must see (and catch) the damage."""
        cfg, ckpt, want_v1, _ = two_version_ckpt
        vdir = self._current(ckpt)
        global_storage.arm(storage.BITROT,
                           os.path.join(vdir, "docs.npz"))
        e2, _meta = restore_checkpoint(ckpt, cfg)
        global_storage.heal()
        assert _results(e2) == want_v1


# ---------------------------------------------------------------------------
# fence sidecar: torn / bit-rotted epoch state
# ---------------------------------------------------------------------------

class TestFenceSidecarCorruption:
    def test_roundtrip_and_durability(self, tmp_path):
        p = str(tmp_path / "fence_epoch.json")
        g = FenceGuard(p)
        assert g.observe(7)
        g2 = FenceGuard(p)
        assert g2.current() == 7
        assert not g2.observe(5)   # lower epoch stays fenced

    def test_torn_sidecar_starts_permissive_and_loud(self, tmp_path):
        p = str(tmp_path / "fence_epoch.json")
        FenceGuard(p).observe(7)
        with open(p, "r+b") as f:   # torn write: half the file
            f.truncate(os.path.getsize(p) // 2)
        g = FenceGuard(p)
        assert g.current() == -1   # fresh-worker permissive, like a
        #                            brand-new node — never a GUESSED epoch
        assert (global_metrics.get("fence_state_unreadable") or 0) >= 1

    def test_bitrot_never_yields_a_wrong_lower_epoch(self, tmp_path):
        """The killer case the CRC envelope exists for: a flipped digit
        turns epoch 173 into VALID JSON saying 133 — silently accepting
        it would let a deposed leader capture this worker."""
        p = str(tmp_path / "fence_epoch.json")
        FenceGuard(p).observe(173)
        raw = open(p, "rb").read()
        assert b"173" in raw
        open(p, "wb").write(raw.replace(b"173", b"133", 1))
        g = FenceGuard(p)
        assert g.current() == -1   # refused, NOT 133
        assert (global_metrics.get("fence_state_unreadable") or 0) >= 1


# ---------------------------------------------------------------------------
# WAL: torn tail / snapshot rot × restart
# ---------------------------------------------------------------------------

class TestWalCorruption:
    def test_torn_tail_truncates_to_acked_prefix(self, tmp_path):
        d = str(tmp_path / "wal")
        st = DurableStore(d)
        st.append([{"i": 1, "t": 1, "c": {"op": "a"}}])
        st.append([{"i": 2, "t": 1, "c": {"op": "b"}}])
        st.close()
        wal = os.path.join(d, "wal.log")
        with open(wal, "r+b") as f:   # tear the LAST frame mid-payload
            f.truncate(os.path.getsize(wal) - 3)
        st2 = DurableStore(d)
        _meta, _snap, entries = st2.load()
        st2.close()
        assert [e["i"] for e in entries] == [1]   # acked prefix intact
        assert (global_metrics.get("wal_truncated_bytes") or 0) > 0

    def test_rewrite_failure_keeps_store_usable(self, tmp_path):
        """A failed compaction rewrite (ENOSPC / armed nemesis) must
        leave the OLD log intact and the append handle open — a
        transient disk hiccup must not wedge the coordination node
        until restart."""
        d = str(tmp_path / "wal")
        st = DurableStore(d)
        st.append([{"i": 1, "t": 1, "c": {"op": "a"}}])
        global_storage.arm(storage.ENOSPC, "*wal.log*", times=1)
        with pytest.raises(OSError):
            st.rewrite([{"i": 1, "t": 1, "c": {"op": "a"}}])
        global_storage.heal()
        st.append([{"i": 2, "t": 1, "c": {"op": "b"}}])
        st.close()
        st2 = DurableStore(d)
        _meta, _snap, entries = st2.load()
        st2.close()
        assert [e["i"] for e in entries] == [1, 2]

    def test_snapshot_bitrot_replays_wal_instead(self, tmp_path):
        d = str(tmp_path / "wal")
        st = DurableStore(d)
        st.append([{"i": 1, "t": 1, "c": {"op": "a"}}])
        st.write_snapshot({"tree": {}}, 1, 1)
        st.close()
        snap = os.path.join(d, "snapshot.json")
        raw = bytearray(open(snap, "rb").read())
        raw[len(raw) // 2] ^= 0x08
        open(snap, "wb").write(bytes(raw))
        st2 = DurableStore(d)
        _meta, snapshot, entries = st2.load()
        st2.close()
        # rotten snapshot detected (CRC envelope) -> full-WAL replay,
        # never a silently-wrong state machine
        assert snapshot is None
        assert [e["i"] for e in entries] == [1]


# ---------------------------------------------------------------------------
# cluster plane: ENOSPC wire contract, fsync-before-ack, scrub
# ---------------------------------------------------------------------------

_CFG = dict(
    top_k=32, min_doc_capacity=64, min_nnz_capacity=1 << 12,
    min_vocab_capacity=1 << 10, query_batch=8, max_query_terms=8,
    rpc_max_attempts=1, breaker_failure_threshold=2,
    reconcile_sweep_interval_s=0.2, placement_flush_ms=10.0,
    result_cache_entries=0)

DOCS = {f"st{i}.txt": f"common token{i} word{i % 3}" for i in range(8)}


@pytest.fixture
def core():
    c = CoordinationCore(session_timeout_s=0.5)
    yield c
    c.close()


def _node(core, tmp_path, i, **kw):
    cfg_kw = dict(_CFG)
    cfg_kw.update(kw)
    cfg = Config(
        documents_path=str(tmp_path / f"st{i}" / "documents"),
        index_path=str(tmp_path / f"st{i}" / "index"),
        port=0, **cfg_kw)
    return SearchNode(cfg, coord=LocalCoordination(core, 0.1)).start()


def _mk_cluster(core, tmp_path, n=3, **kw):
    nodes = [_node(core, tmp_path, i, **kw) for i in range(n)]
    wait_until(lambda: len(
        nodes[0].registry.get_all_service_addresses()) == n - 1)
    return nodes


def _stop_all(nodes):
    for nd in nodes:
        try:
            nd.stop()
        except Exception:
            pass


def _upload(leader, docs=DOCS):
    batch = [{"name": n, "text": t} for n, t in docs.items()]
    return json.loads(http_post(leader.url + "/leader/upload-batch",
                                json.dumps(batch).encode()))


class TestEnospcContract:
    def test_classifier_unit(self):
        e = urllib.error.HTTPError("u", 507, "storage", {}, None)
        assert not is_retryable(e)       # a full disk does not drain
        assert not is_worker_fault(e)    # and must not trip breakers

    def test_worker_507_and_no_breaker_trip(self, core, tmp_path):
        nodes = _mk_cluster(core, tmp_path, n=3)
        try:
            leader = nodes[0]
            _upload(leader)
            # disk full on every documents dir: the next upload must be
            # a distinct 507 end to end (worker verdict relayed by the
            # leader), counted, and NOT a breaker trip
            global_storage.arm(storage.ENOSPC, "*documents*")
            with pytest.raises(urllib.error.HTTPError) as ei:
                http_post(leader.url + "/leader/upload?name=full.txt",
                          b"this write has nowhere to land")
            assert ei.value.code == 507
            assert (global_metrics.get("storage_enospc") or 0) >= 1
            global_storage.heal()
            for w in leader.registry.get_all_service_addresses():
                assert not leader.resilience.board.is_open(w), \
                    "breaker tripped on a full disk"
            # the disk healed: uploads work again immediately (no
            # breaker to wait out)
            resp = http_post(
                leader.url + "/leader/upload?name=after.txt",
                b"space is back")
            assert b"uploaded successfully" in resp
        finally:
            _stop_all(nodes)

    def test_batch_enospc_is_507(self, core, tmp_path):
        nodes = _mk_cluster(core, tmp_path, n=2)
        try:
            leader = nodes[0]
            global_storage.arm(storage.ENOSPC, "*documents*")
            with pytest.raises(urllib.error.HTTPError) as ei:
                http_post(
                    nodes[1].url + "/worker/upload-batch",
                    json.dumps([{"name": "x.txt", "text": "y"}]).encode())
            assert ei.value.code == 507
            # ...and the LEADER front door relays the batch verdict as
            # 507 too (every replica leg full), never a retryable 500
            with pytest.raises(urllib.error.HTTPError) as ei:
                http_post(
                    leader.url + "/leader/upload-batch",
                    json.dumps([{"name": "z.txt", "text": "w"}]).encode())
            assert ei.value.code == 507
            for w in leader.registry.get_all_service_addresses():
                assert not leader.resilience.board.is_open(w)
        finally:
            global_storage.heal()
            _stop_all(nodes)


class TestFsyncBeforeAck:
    def test_acked_upload_is_fsynced_and_group_committed(
            self, core, tmp_path):
        nodes = _mk_cluster(core, tmp_path, n=2)
        try:
            leader = nodes[0]
            before = global_metrics.get("storage_fsyncs") or 0
            resp = _upload(leader)
            assert not resp.get("failed")
            # the ack implies fsyncs happened (file + dir per store),
            # group-committed: the batch paid ONE dir-fsync round per
            # worker, not one per document
            assert (global_metrics.get("storage_fsyncs") or 0) > before
            assert (global_metrics.get("storage_group_commits") or 0) \
                >= 1
            # and the raw bytes really are on disk under the docs dirs
            on_disk = 0
            for i in range(2):
                droot = str(tmp_path / f"st{i}" / "documents")
                for n in DOCS:
                    if os.path.isfile(os.path.join(droot, n)):
                        on_disk += 1
            assert on_disk >= len(DOCS)   # R=2 -> most names twice
        finally:
            _stop_all(nodes)

    def test_fsync_off_still_atomic(self, core, tmp_path):
        nodes = _mk_cluster(core, tmp_path, n=2, storage_fsync=False)
        try:
            resp = _upload(nodes[0])
            assert not resp.get("failed")
        finally:
            _stop_all(nodes)


class TestIntegrityScrub:
    def test_rotten_store_copy_repaired_from_replica(self, core,
                                                     tmp_path):
        nodes = _mk_cluster(core, tmp_path, n=3)
        try:
            leader = nodes[0]
            _upload(leader)
            store = os.path.join(str(tmp_path / "st0" / "index"),
                                 "placed_docs")
            victim = "st3.txt"
            path = os.path.join(store, victim)
            assert os.path.isfile(path)
            good_crc = zlib.crc32(open(path, "rb").read())
            raw = bytearray(open(path, "rb").read())
            raw[1] ^= 0x40
            open(path, "wb").write(bytes(raw))
            out = leader.run_integrity_scrub()
            assert out["repaired"] >= 1 and out["unrepaired"] == 0
            assert zlib.crc32(open(path, "rb").read()) == good_crc
            assert (global_metrics.get("storage_scrub_repairs") or 0) \
                >= 1
        finally:
            _stop_all(nodes)

    def test_stale_ledger_is_healed_not_quarantined(self, core,
                                                    tmp_path):
        """The crash-ate-the-ledger-flush case (chaos-powerloss's exact
        shape): the local file AND the replicas hold the new acked
        bytes, only the debounced ledger record is stale. The scrub
        must heal the RECORD — destroying or refusing the healthy file
        would lose the leader copy of an acked upsert."""
        nodes = _mk_cluster(core, tmp_path, n=3)
        try:
            leader = nodes[0]
            _upload(leader)
            victim = "st2.txt"
            good = leader._store_ledger.get(victim)
            assert good is not None
            leader._store_ledger.record(victim, good ^ 0xFFFF)  # stale
            out = leader.run_integrity_scrub()
            assert out["repaired"] == 0 and out["unrepaired"] == 0
            assert leader._store_ledger.get(victim) == good
            assert (global_metrics.get("storage_scrub_ledger_heals")
                    or 0) >= 1
            assert leader._store_read(victim) is not None
        finally:
            _stop_all(nodes)

    def test_unrepairable_rot_is_loud_never_served(self, core, tmp_path):
        nodes = _mk_cluster(core, tmp_path, n=3)
        try:
            leader = nodes[0]
            _upload(leader)
            store = os.path.join(str(tmp_path / "st0" / "index"),
                                 "placed_docs")
            victim = "st5.txt"
            path = os.path.join(store, victim)
            raw = bytearray(open(path, "rb").read())
            raw[1] ^= 0x40
            open(path, "wb").write(bytes(raw))
            # no healthy replica anywhere: stop the workers first
            for nd in nodes[1:]:
                nd.stop()
            out = leader.run_integrity_scrub()
            assert out["unrepaired"] >= 1
            # the rotten bytes are never served as a recovery source
            assert leader._store_read(victim) is None
            assert (global_metrics.get("storage_scrub_unrepaired")
                    or 0) >= 1
        finally:
            _stop_all(nodes)

    def test_scrub_quarantines_corrupt_checkpoint(self, core, tmp_path):
        nodes = _mk_cluster(core, tmp_path, n=2,
                            storage_keep_versions=2)
        try:
            leader = nodes[0]
            _upload(leader)
            leader.save_checkpoint()
            cur = os.path.join(
                os.path.dirname(leader.checkpoint_dir),
                os.readlink(leader.checkpoint_dir))
            p = os.path.join(cur, "docs.npz")
            raw = bytearray(open(p, "rb").read())
            raw[50] ^= 0x01
            open(p, "wb").write(bytes(raw))
            out = json.loads(http_post(
                leader.url + "/admin/scrub", b"{}"))
            assert out["checkpoints_quarantined"] >= 1
            assert (global_metrics.get("checkpoint_quarantined")
                    or 0) >= 1
        finally:
            _stop_all(nodes)


# ---------------------------------------------------------------------------
# Chaos (slow): whole-cluster power loss under active disk faults
# ---------------------------------------------------------------------------

@pytest.mark.slow
class TestChaosPowerloss:
    @pytest.mark.timeout(300)
    def test_sigkill_whole_cluster_zero_acked_loss(self, tmp_path):
        """`make chaos-powerloss` — the one failure class replication
        alone cannot absorb: a correlated restart of EVERYTHING. A
        3-node cluster + durable coordinator runs an upload/search
        workload with the disk nemesis armed (torn writes on the
        documents dirs); mid-workload every process is SIGKILLed at
        once, everything restarts on the same dirs, and the bar is
        zero acked-upload loss with exact single-node-oracle parity on
        every post-restart search."""
        import signal
        import socket
        import subprocess
        import sys
        import time

        def free_port():
            with socket.socket() as s:
                s.bind(("127.0.0.1", 0))
                return s.getsockname()[1]

        env = os.environ.copy()
        env["TFIDF_JAX_PLATFORM"] = "cpu"
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("XLA_FLAGS", None)
        env.update({
            "TFIDF_REPLICATION_FACTOR": "2",
            "TFIDF_TOP_K": "200",
            "TFIDF_SESSION_TIMEOUT_S": "1.0",
            "TFIDF_HEARTBEAT_INTERVAL_S": "0.2",
            "TFIDF_RECONCILE_SWEEP_INTERVAL_S": "0.5",
            "TFIDF_MIN_DOC_CAPACITY": "64",
            "TFIDF_MIN_NNZ_CAPACITY": "4096",
            "TFIDF_MIN_VOCAB_CAPACITY": "1024",
            "TFIDF_QUERY_BATCH": "8",
            "TFIDF_MAX_QUERY_TERMS": "8",
            # exercise the checkpoint restore path across the restart
            "TFIDF_CHECKPOINT_INTERVAL_S": "1.0",
            # the disk is hostile for the WHOLE run: occasional torn
            # writes on the raw document stores (an affected upload
            # fails un-acked; the contract is about what was ACKED)
            "TFIDF_STORAGE_NEMESIS": json.dumps([
                {"kind": "torn_write", "glob": "*documents*",
                 "probability": 0.04},
            ]),
        })
        coord_port = free_port()
        coord_dir = str(tmp_path / "coord")
        procs: dict = {}

        def spawn(tag, args):
            p = subprocess.Popen(
                [sys.executable, "-m", "tfidf_tpu", *args],
                env=env, stdout=subprocess.DEVNULL,
                stderr=subprocess.DEVNULL)
            procs[tag] = p
            return p

        def wait_pred(pred, timeout=120.0, interval=0.2):
            deadline = time.monotonic() + timeout
            last = None
            while time.monotonic() < deadline:
                try:
                    if pred():
                        return True
                except Exception as e:
                    last = e
                time.sleep(interval)
            raise AssertionError(f"timed out; last={last!r}")

        def node_args(i, port):
            return ["serve", "--port", str(port), "--host", "127.0.0.1",
                    "--coordinator-address", f"127.0.0.1:{coord_port}",
                    "--documents-path", str(tmp_path / f"pl{i}" / "docs"),
                    "--index-path", str(tmp_path / f"pl{i}" / "index")]

        def boot_cluster():
            spawn("coord", ["coordinator", "--listen",
                            f"127.0.0.1:{coord_port}",
                            "--data-dir", coord_dir])
            wait_pred(lambda: socket.create_connection(
                ("127.0.0.1", coord_port), timeout=1.0).close() or True)
            for i, p in enumerate(ports):
                spawn(f"n{i}", node_args(i, p))
            for u in urls:
                wait_pred(lambda u=u: http_get_(u + "/api/status"))
            wait_pred(lambda: len(json.loads(http_get_(
                urls[0] + "/api/services"))) == 2)

        def http_get_(url):
            import urllib.request
            with urllib.request.urlopen(url, timeout=10.0) as r:
                return r.read()

        def post(url, data, timeout=60.0):
            import urllib.request
            req = urllib.request.Request(
                url, data=data,
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=timeout) as r:
                return r.read()

        texts = {f"pl{i}.txt":
                 f"common uniq{i} word{i % 5} tail{i % 11}"
                 for i in range(120)}
        queries = ["common", "word1 uniq7", "tail3", "uniq42 common"]
        ports = [free_port() for _ in range(3)]
        urls = [f"http://127.0.0.1:{p}" for p in ports]
        acked: set = set()
        ambiguous: set = set()
        try:
            boot_cluster()
            names = sorted(texts)
            batches = [names[lo:lo + 10]
                       for lo in range(0, len(names), 10)]

            stop = threading.Event()

            def workload():
                for group in batches:
                    if stop.is_set():
                        # everything not yet attempted is ambiguous —
                        # re-driven after the restart
                        ambiguous.update(group)
                        continue
                    body = json.dumps(
                        [{"name": n, "text": texts[n]}
                         for n in group]).encode()
                    try:
                        resp = json.loads(post(
                            urls[0] + "/leader/upload-batch", body))
                        bad = set(resp.get("failed", ())) \
                            | {s["name"]
                               for s in resp.get("skipped", ())}
                        acked.update(n for n in group if n not in bad)
                        ambiguous.update(bad)
                    except Exception:
                        # no ack — the write may or may not have landed
                        ambiguous.update(group)
                    # interleave a search to keep the read plane hot
                    try:
                        post(urls[0] + "/leader/start",
                             json.dumps({"query": "common"}).encode(),
                             timeout=30.0)
                    except Exception:
                        pass

            t = threading.Thread(target=workload, daemon=True)
            t.start()
            time.sleep(4.0)   # well into the upload stream
            # ---- POWER LOSS: kill -9 EVERYTHING at once ----
            stop.set()
            for p in procs.values():
                try:
                    os.kill(p.pid, signal.SIGKILL)
                except OSError:
                    pass
            for p in procs.values():
                p.wait(timeout=10)
            t.join(timeout=30)
            procs.clear()
            assert acked, "workload never acked anything before the kill"

            # ---- full restart on the same dirs ----
            boot_cluster()
            # drive every ambiguous name to a definite acked state so
            # the corpus is deterministic (idempotent upserts; an acked
            # doc is NEVER re-sent — if power loss ate one, nothing
            # below can resurrect it)
            pending = sorted(set(texts) - acked)
            deadline = time.monotonic() + 90
            while pending and time.monotonic() < deadline:
                body = json.dumps([{"name": n, "text": texts[n]}
                                   for n in pending[:20]]).encode()
                try:
                    resp = json.loads(post(
                        urls[0] + "/leader/upload-batch", body))
                    bad = set(resp.get("failed", ())) | {
                        s["name"] for s in resp.get("skipped", ())}
                    done = [n for n in pending[:20] if n not in bad]
                    pending = [n for n in pending if n not in done]
                except Exception:
                    time.sleep(1.0)
            assert not pending, f"could not settle {len(pending)} docs"

            # ---- the bar: zero acked loss, exact oracle parity ----
            oracle_cfg = Config(
                documents_path=str(tmp_path / "oracle" / "docs"),
                index_path=str(tmp_path / "oracle" / "index"),
                top_k=200, min_doc_capacity=64,
                min_nnz_capacity=4096, min_vocab_capacity=1024,
                query_batch=8, max_query_terms=8)
            from tfidf_tpu.engine.engine import Engine
            oracle = Engine(oracle_cfg)
            for n, txt in texts.items():
                oracle.ingest_text(n, txt)
            oracle.commit()

            def parity(q):
                want = {h.name: float(h.score)
                        for h in oracle.search(q, k=200)}
                got = {n: float(s) for n, s in json.loads(post(
                    urls[0] + "/leader/start",
                    json.dumps({"query": q}).encode())).items()}
                assert set(got) == set(want), \
                    (q, set(want) - set(got), set(got) - set(want))
                for n, s in want.items():
                    assert got[n] == pytest.approx(s, rel=1e-5), \
                        (q, n, got[n], s)
                return True

            for q in queries:
                wait_pred(lambda q=q: parity(q), timeout=120,
                          interval=1.0)
            # every ACKED doc individually findable — the acked-loss
            # probe at single-document granularity
            for i in range(120):
                n = f"pl{i}.txt"
                if n not in acked:
                    continue
                got = json.loads(post(
                    urls[0] + "/leader/start",
                    json.dumps({"query": f"uniq{i}"}).encode()))
                assert n in got, f"ACKED {n} lost through power loss"
        finally:
            for p in procs.values():
                try:
                    p.kill()
                except Exception:
                    pass
            for p in procs.values():
                try:
                    p.wait(timeout=10)
                except Exception:
                    pass

"""Serving-node checkpointing (VERDICT r4 #5): /admin/checkpoint, the
autosave loop, and restore-at-boot with an mtime-gated partial re-walk."""

import json
import os
import time

from tfidf_tpu.cluster.coordination import CoordinationCore, LocalCoordination
from tfidf_tpu.cluster.node import SearchNode, http_post
from tfidf_tpu.engine.checkpoint import load_checkpoint
from tfidf_tpu.utils.config import Config

from tests.test_cluster import wait_until


def _cfg(tmp_path, sub, **kw):
    return Config(documents_path=str(tmp_path / sub / "documents"),
                  index_path=str(tmp_path / sub / "index"),
                  port=0, min_doc_capacity=64, min_nnz_capacity=1 << 12,
                  min_vocab_capacity=1 << 10, query_batch=4,
                  max_query_terms=8, **kw)


def test_admin_checkpoint_and_restore_at_boot(tmp_path):
    core = CoordinationCore(session_timeout_s=0.5)
    cfg = _cfg(tmp_path, "n0", index_mode="segments")
    node = SearchNode(cfg, coord=LocalCoordination(core, 0.1)).start()
    try:
        for i in range(8):
            http_post(node.url + f"/worker/upload?name=d{i}.txt",
                      f"shared token{i} body".encode(),
                      content_type="application/octet-stream")
        # NRT: a search commits pending writes, then checkpoint
        resp = json.loads(http_post(node.url + "/admin/checkpoint", b""))
        assert resp["docs"] == 8
        assert os.path.isdir(resp["dir"])
    finally:
        node.stop()
        core.close()

    # "pod restart": restore from the checkpoint, then re-walk only
    # files newer than the save
    with open(os.path.join(resp["dir"], "meta.json")) as f:
        created = json.load(f)["created_at"]
    engine = load_checkpoint(resp["dir"], cfg)
    assert engine.index.num_live_docs == 8
    # age the pre-checkpoint files past the clock-skew slack (in a real
    # deployment they'd be minutes-to-days older than the save)
    for i in range(8):
        p = os.path.join(cfg.documents_path, f"d{i}.txt")
        os.utime(p, (created - 3600, created - 3600))
    # a document uploaded AFTER the checkpoint (newer mtime) must be
    # picked up by the partial re-walk; the old ones are skipped
    late = os.path.join(cfg.documents_path, "late.txt")
    with open(late, "w") as f:
        f.write("shared latecomer")
    os.utime(late, (created + 120, created + 120))
    seen_before = engine.index.num_live_docs
    n = engine.build_from_directory(newer_than=created - 60.0)
    assert n < 8 + 1   # NOT a full re-walk
    assert engine.index.num_live_docs == seen_before + 1
    core2 = CoordinationCore(session_timeout_s=0.5)
    node2 = SearchNode(cfg, coord=LocalCoordination(core2, 0.1),
                       engine=engine).start(rebuild=False)
    try:
        hits = json.loads(http_post(node2.url + "/worker/process",
                                    b"shared"))
        names = {h["document"]["name"] for h in hits}
        assert "late.txt" in names and "d0.txt" in names
    finally:
        node2.stop()
        core2.close()


def test_autosave_loop_saves_dirty_state(tmp_path):
    core = CoordinationCore(session_timeout_s=0.5)
    cfg = _cfg(tmp_path, "n1", checkpoint_interval_s=0.3)
    node = SearchNode(cfg, coord=LocalCoordination(core, 0.1)).start()
    try:
        http_post(node.url + "/worker/upload?name=a.txt", b"hello world",
                  content_type="application/octet-stream")
        assert wait_until(
            lambda: os.path.isdir(node.checkpoint_dir), timeout=5.0)
        # the autosave captured the doc (it commits via the engine state,
        # not the NRT flag — load and check)
        assert wait_until(
            lambda: load_checkpoint(node.checkpoint_dir,
                                    cfg).index.num_live_docs == 1,
            timeout=5.0)
    finally:
        node.stop()
        core.close()

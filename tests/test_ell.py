"""Blocked-ELL layout tests: build correctness, scoring parity with COO.

The ELL path must be a pure re-layout: identical scores to the chunked COO
scatter path for every model, including documents that spill into the
residual. Engine-level tests confirm the default layout produces the same
search results as layout="coo".
"""

import jax.numpy as jnp
import numpy as np
import pytest

from tests.oracle import random_corpus as oracle_random_corpus
from tfidf_tpu.engine.engine import Engine
from tfidf_tpu.ops.csr import build_coo
from tfidf_tpu.ops.ell import (build_ell_from_coo, ell_impacts,
                               score_ell_batch)
from tfidf_tpu.ops.scoring import make_query_batch, score_coo_batch
from tfidf_tpu.utils.config import Config


def random_corpus(rng, n_docs=40, vocab=64, max_len=30):
    """Oracle corpus, re-sorted by distinct-term count DESC (the to_coo
    order the blocked layout requires)."""
    docs, lengths = oracle_random_corpus(rng, n_docs=n_docs, vocab=vocab,
                                         max_len=max_len)
    order = np.argsort([-len(d) for d in docs], kind="stable")
    return [docs[i] for i in order], [lengths[i] for i in order]


def random_queries(rng, vocab, B=4, T=6):
    q_terms = rng.integers(0, vocab, size=(B, T)).astype(np.int32)
    q_weights = rng.random((B, T)).astype(np.float32)
    return make_query_batch(q_terms, q_weights, min_slots=8)


def build_ell_arrays(coo, model, n_docs, avgdl, *, width_cap,
                     min_rows=8, doc_norms=None):
    """Mirror ShardIndex.commit's ELL assembly for direct op tests."""
    ell = build_ell_from_coo(coo, width_cap=width_cap, min_rows=min_rows)
    impacts, terms, live = [], [], []
    for blk in ell.blocks:
        rows_cap = blk.tf.shape[0]
        dl = np.zeros(rows_cap, np.float32)
        dl[:blk.n_rows] = coo.doc_len[blk.row0:blk.row0 + blk.n_rows]
        nrm = np.zeros(rows_cap, np.float32)
        if doc_norms is not None:
            nrm[:blk.n_rows] = doc_norms[blk.row0:blk.row0 + blk.n_rows]
        impacts.append(ell_impacts(
            jnp.asarray(blk.tf), jnp.asarray(blk.term), jnp.asarray(dl),
            jnp.asarray(coo.df), n_docs, avgdl, jnp.asarray(nrm),
            model=model))
        terms.append(jnp.asarray(blk.term))
        live.append(blk.n_rows)
    return ell, tuple(impacts), tuple(terms), jnp.asarray(
        np.asarray(live, np.int32))


class TestBuild:
    def test_roundtrip_no_spill(self, rng):
        docs, _ = random_corpus(rng)
        coo = build_coo(docs, vocab_cap=128, min_nnz_cap=1 << 10,
                        min_doc_cap=64)
        ell = build_ell_from_coo(coo, width_cap=64, min_rows=8)
        assert ell.res_nnz == 0
        # every doc's counts appear at its (blocked) row
        for d, counts in enumerate(docs):
            blk = next(b for b in ell.blocks
                       if b.row0 <= d < b.row0 + b.n_rows)
            r = d - blk.row0
            row = {int(t): float(f)
                   for t, f in zip(blk.term[r], blk.tf[r]) if f > 0}
            assert row == {t: float(f) for t, f in counts.items()}

    def test_blocks_bucketed_by_width(self, rng):
        docs, _ = random_corpus(rng, n_docs=60, vocab=128, max_len=100)
        coo = build_coo(docs, vocab_cap=256, min_nnz_cap=1 << 12,
                        min_doc_cap=64)
        ell = build_ell_from_coo(coo, width_cap=256, min_rows=8)
        widths = [b.width for b in ell.blocks]
        assert widths == sorted(widths, reverse=True)   # non-increasing
        assert len(set(widths)) == len(widths)          # distinct buckets
        # blocks tile the doc rows contiguously
        covered = 0
        for b in ell.blocks:
            assert b.row0 == covered
            covered += b.n_rows
        assert covered == len(docs)
        # padding stays bounded: blocked entries < 2x the true nnz + bucket
        padded = sum(b.tf.shape[0] * b.width for b in ell.blocks)
        assert padded < 2 * coo.nnz + 8 * 256

    def test_spill_to_residual(self, rng):
        docs, _ = random_corpus(rng, n_docs=10, vocab=200, max_len=150)
        coo = build_coo(docs, vocab_cap=256, min_nnz_cap=1 << 11,
                        min_doc_cap=16)
        ell = build_ell_from_coo(coo, width_cap=16, min_rows=8)
        total = sum(len(d) for d in docs)
        main = sum(int((b.tf > 0).sum()) for b in ell.blocks)
        assert main + ell.res_nnz == total
        assert ell.res_nnz > 0
        assert (np.diff(ell.res_doc) >= 0).all()

    def test_non_ladder_width_cap_conserves_entries(self, rng):
        """width_cap values that are not ladder rungs (e.g. 100, 512)
        must still conserve every posting between blocks and residual —
        a regression guard for the ladder/spill boundary mismatch."""
        docs = [{t: 1 for t in range(n)} for n in (300, 120, 90, 40, 3)]
        total = sum(len(d) for d in docs)
        for cap in (100, 512, 20):
            coo = build_coo(docs, vocab_cap=512, min_nnz_cap=1 << 11,
                            min_doc_cap=16)
            ell = build_ell_from_coo(coo, width_cap=cap, min_rows=8)
            main = sum(int((b.tf > 0).sum()) for b in ell.blocks)
            assert main + ell.res_nnz == total, cap

    def test_unsorted_rows_rejected(self, rng):
        docs = [{1: 1}, {1: 1, 2: 1, 3: 1}]    # ascending length
        coo = build_coo(docs, vocab_cap=8, min_nnz_cap=64, min_doc_cap=8)
        with pytest.raises(AssertionError):
            build_ell_from_coo(coo, width_cap=8)

    def test_empty_corpus(self):
        coo = build_coo([], vocab_cap=32, min_nnz_cap=64, min_doc_cap=8)
        ell = build_ell_from_coo(coo, width_cap=32)
        assert ell.blocks == [] and ell.res_nnz == 0


class TestScoringParity:
    @pytest.mark.parametrize("model", ["bm25", "tfidf"])
    @pytest.mark.parametrize("width_cap", [8, 64])
    def test_ell_matches_coo(self, rng, model, width_cap):
        """Blocked ELL + residual scores == COO scatter scores."""
        docs, lengths = random_corpus(rng)
        coo = build_coo(docs, vocab_cap=128, min_nnz_cap=1 << 10,
                        min_doc_cap=64)
        qb = random_queries(rng, vocab=64)
        n_docs = jnp.float32(len(docs))
        avgdl = jnp.float32(np.mean(lengths))

        ref = score_coo_batch(
            jnp.asarray(coo.tf), jnp.asarray(coo.term), jnp.asarray(coo.doc),
            jnp.asarray(coo.doc_len), jnp.asarray(coo.df),
            qb, n_docs, avgdl, model=model, chunk=256)

        ell, impacts, terms, live = build_ell_arrays(
            coo, model, n_docs, avgdl, width_cap=width_cap)
        got = score_ell_batch(
            impacts, terms, live,
            jnp.asarray(ell.res_tf), jnp.asarray(ell.res_term),
            jnp.asarray(ell.res_doc),
            jnp.asarray(coo.doc_len), jnp.asarray(coo.df),
            qb, n_docs, avgdl, model=model)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    def test_doc_chunking_invariant(self, rng):
        """Scores identical for any doc_chunk."""
        docs, lengths = random_corpus(rng)
        coo = build_coo(docs, vocab_cap=128, min_nnz_cap=1 << 10,
                        min_doc_cap=64)
        qb = random_queries(rng, vocab=64)
        n_docs, avgdl = jnp.float32(len(docs)), jnp.float32(np.mean(lengths))
        ell, impacts, terms, live = build_ell_arrays(
            coo, "bm25", n_docs, avgdl, width_cap=64)
        ref = None
        for chunk in (8, 16, 64):
            s = score_ell_batch(
                impacts, terms, live,
                jnp.asarray(ell.res_tf), jnp.asarray(ell.res_term),
                jnp.asarray(ell.res_doc),
                jnp.asarray(coo.doc_len), jnp.asarray(coo.df),
                qb, n_docs, avgdl, model="bm25", doc_chunk=chunk)
            if ref is None:
                ref = np.asarray(s)
            else:
                np.testing.assert_allclose(np.asarray(s), ref, rtol=1e-6)


class TestEngineLayouts:
    def test_engine_ell_equals_coo_results(self, tmp_path):
        texts = {
            "a.txt": "the quick brown fox jumps over the lazy dog",
            "b.txt": "a fast brown fox and a quick red fox",
            "c.txt": "lorem ipsum dolor sit amet " * 30,   # long doc
            "d.txt": "the dog sleeps all day " * 10,
        }
        results = {}
        for layout in ("ell", "coo"):
            cfg = Config(documents_path=str(tmp_path / layout),
                         scoring_layout=layout, ell_width_cap=8,
                         min_doc_capacity=8, min_nnz_capacity=256,
                         min_vocab_capacity=64, query_batch=4,
                         max_query_terms=8)
            e = Engine(cfg)
            for name, text in texts.items():
                e.ingest_text(name, text)
            e.commit()
            results[layout] = [
                e.search(q) for q in ("fox", "dog day", "lorem ipsum")]
        for hits_e, hits_c in zip(results["ell"], results["coo"]):
            assert [h.name for h in hits_e] == [h.name for h in hits_c]
            np.testing.assert_allclose([h.score for h in hits_e],
                                       [h.score for h in hits_c], rtol=1e-5)

    def test_commit_growth_reuses_executable(self, tmp_path):
        """Commits that stay within the same capacity buckets must NOT
        retrace the scoring executable (live counts are traced)."""
        # the public score_ell_batch is the nemesis dispatch seam (a
        # plain function); the compile cache lives on the jitted
        # executable behind it
        from tfidf_tpu.ops.ell import _score_ell_batch_jit as jitted
        cfg = Config(documents_path=str(tmp_path), min_doc_capacity=8,
                     min_nnz_capacity=256, min_vocab_capacity=64,
                     query_batch=4, max_query_terms=8)
        e = Engine(cfg)
        e.ingest_text("a.txt", "alpha beta gamma")
        e.commit()
        e.search("alpha")
        size0 = jitted._cache_size()
        e.ingest_text("b.txt", "alpha delta epsilon")
        e.commit()
        hits = e.search("alpha")
        assert {h.name for h in hits} == {"a.txt", "b.txt"}
        assert jitted._cache_size() == size0, "commit retraced the query path"

    def test_ell_snapshot_skips_device_coo(self, tmp_path):
        cfg = Config(documents_path=str(tmp_path), min_doc_capacity=8,
                     min_nnz_capacity=256, min_vocab_capacity=64,
                     query_batch=4, max_query_terms=8)
        e = Engine(cfg)
        e.ingest_text("x.txt", "hello world hello")
        e.commit()
        snap = e.index.snapshot
        assert snap.is_ell
        assert snap.tf is None and snap.term is None and snap.doc is None
        assert snap.ell_impacts and snap.size_bytes() > 0
        assert [h.name for h in e.search("hello")] == ["x.txt"]


class TestPallasKernel:
    """Fused Pallas gather kernel vs the XLA path (interpret mode on CPU;
    the same kernels run compiled on TPU)."""

    def _block(self, rng, rows_cap, width, vocab):
        imp = rng.random((rows_cap, width), dtype=np.float32)
        # distinct term ids within each row — the layout contract every
        # ELL builder guarantees (one posting per distinct term) and
        # the v4 paired A-build relies on: position w draws from the
        # congruence class w mod width
        base = rng.integers(0, max(vocab // width, 1),
                            size=(rows_cap, width))
        term = (base * width
                + np.arange(width, dtype=np.int64)[None, :]
                ).astype(np.int32)
        # pad tail rows like a real block
        imp[-rows_cap // 4:] = 0.0
        term[-rows_cap // 4:] = 0
        return jnp.asarray(imp), jnp.asarray(term)

    @pytest.mark.parametrize("a_build", ["v3", "v4"])
    @pytest.mark.parametrize("vocab", [1 << 12, 1 << 17])
    def test_matches_xla_block_path(self, rng, a_build, vocab):
        """Both A-build variants vs the XLA oracle, on both sides of
        the i16 packed-compare vocabulary bound."""
        from tfidf_tpu.ops.ell import _score_block, score_block_pallas
        from tfidf_tpu.ops.scoring import (_compile_queries,
                                           make_query_batch)
        rows_cap, width, B = 512, 16, 64
        imp, term = self._block(rng, rows_cap, width, vocab)
        q_terms = rng.integers(0, vocab, size=(B, 4)).astype(np.int32)
        q_weights = (rng.random((B, 4), dtype=np.float32) + 0.1)
        qb = make_query_batch(q_terms, q_weights, min_slots=256)
        slot_of, qc_ext = _compile_queries(qb, vocab)
        ref = _score_block(imp, term, slot_of, qc_ext.T, 256)
        out = score_block_pallas(imp, term, jnp.asarray(qb.uniq),
                                 jnp.asarray(qb.n_uniq), qc_ext,
                                 a_build=a_build, vocab_cap=vocab)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    @pytest.mark.parametrize("width", [7, 16, 33])
    def test_v4_bitwise_equals_v3(self, rng, width):
        """The pair fold adds 0.0 exactly where v3 adds it, so v4
        (odd widths included — the static tail row) must agree with v3
        to the BIT, packed or not."""
        from tfidf_tpu.ops.ell import score_block_pallas
        from tfidf_tpu.ops.scoring import (_compile_queries,
                                           make_query_batch)
        for vocab in (1 << 14, 1 << 16):        # packed and unpacked
            rows_cap, B = 512, 32
            imp, term = self._block(rng, rows_cap, width, vocab)
            q_terms = rng.integers(0, vocab, size=(B, 4)).astype(np.int32)
            q_terms[0, 0] = int(np.asarray(term)[0, 0])   # force a hit
            q_weights = (rng.random((B, 4), dtype=np.float32) + 0.1)
            qb = make_query_batch(q_terms, q_weights, min_slots=256)
            _slot_of, qc_ext = _compile_queries(qb, vocab)
            outs = [np.asarray(score_block_pallas(
                imp, term, jnp.asarray(qb.uniq), jnp.asarray(qb.n_uniq),
                qc_ext, a_build=a, vocab_cap=vocab))
                for a in ("v3", "v4")]
            assert np.abs(outs[0]).max() > 0
            np.testing.assert_array_equal(outs[0], outs[1])

    def test_pad_uniq_never_matches_term_zero(self, rng):
        """uniq is zero-padded but term id 0 is real: pad entries must
        not siphon term-0 impacts into the batch (the -1 mask)."""
        from tfidf_tpu.ops.ell import _score_block, score_block_pallas
        from tfidf_tpu.ops.scoring import (_compile_queries,
                                           make_query_batch)
        vocab = 64
        rows_cap, width, B = 512, 8, 8
        imp = np.abs(rng.random((rows_cap, width), dtype=np.float32))
        term = np.zeros((rows_cap, width), np.int32)   # ALL term 0
        q_terms = np.full((B, 2), 5, np.int32)         # term 0 not queried
        q_weights = np.ones((B, 2), np.float32)
        qb = make_query_batch(q_terms, q_weights, min_slots=16)
        slot_of, qc_ext = _compile_queries(qb, vocab)
        out = score_block_pallas(jnp.asarray(imp), jnp.asarray(term),
                                 jnp.asarray(qb.uniq),
                                 jnp.asarray(qb.n_uniq), qc_ext)
        assert np.asarray(out).max() == 0.0

    def test_end_to_end_engine_equivalence(self, tmp_path):
        """Engine with use_pallas on eligible shapes == engine without,
        for BOTH A-build variants. min_doc_capacity=512 makes every
        block eligible (rows_cap 512); the small vocabulary also arms
        the v4 i16 packed sub-variant."""
        from tfidf_tpu.engine.engine import Engine
        from tfidf_tpu.utils.config import Config

        rng = np.random.default_rng(7)
        texts = {}
        for i in range(40):
            words = rng.integers(0, 200, size=int(rng.integers(3, 30)))
            texts[f"d{i}.txt"] = " ".join(f"w{w}" for w in words)

        def build(use_pallas, a_build="v4"):
            cfg = Config(documents_path=str(
                             tmp_path / f"{use_pallas}-{a_build}"),
                         min_doc_capacity=512, min_vocab_capacity=256,
                         query_batch=8, max_query_terms=8,
                         use_pallas=use_pallas, kernel_a_build=a_build)
            e = Engine(cfg)
            for n, t in texts.items():
                e.ingest_text(n, t)
            e.commit()
            return e

        ex = build(False)
        queries = ["w3 w17", "w100 w5 w9", "w42"]
        hx = [[(h.name, round(h.score, 5)) for h in ex.search(q)]
              for q in queries]
        for a_build in ("v3", "v4"):
            ep = build(True, a_build)
            for q, want in zip(queries, hx):
                hp = [(h.name, round(h.score, 5)) for h in ep.search(q)]
                assert hp == want, (a_build, q, hp, want)

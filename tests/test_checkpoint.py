import pytest

from tests.test_engine import CORPUS, ingest_corpus, make_engine
from tfidf_tpu.engine.checkpoint import load_checkpoint, save_checkpoint
from tfidf_tpu.utils.config import Config
from tfidf_tpu.utils.faults import FaultInjected, global_injector


def test_save_load_roundtrip(tmp_path):
    e = make_engine(tmp_path)
    ingest_corpus(e)
    want = e.search("fast food", k=5)
    ckpt = str(tmp_path / "ckpt")
    save_checkpoint(e, ckpt)
    e2 = load_checkpoint(ckpt, e.config)
    got = e2.search("fast food", k=5)
    assert [(h.name, round(h.score, 5)) for h in want] == \
        [(h.name, round(h.score, 5)) for h in got]
    assert len(e2.vocab) == len(e.vocab)


def test_checkpoint_then_incremental_ingest(tmp_path):
    e = make_engine(tmp_path)
    ingest_corpus(e)
    ckpt = str(tmp_path / "ckpt")
    save_checkpoint(e, ckpt)
    e2 = load_checkpoint(ckpt, e.config)
    e2.ingest_text("new.txt", "fresh fast document")
    e2.commit()
    names = [h.name for h in e2.search("fast", k=10)]
    assert "new.txt" in names and "file1.txt" in names


def test_checkpoint_overwrite_is_atomic(tmp_path):
    e = make_engine(tmp_path)
    ingest_corpus(e)
    ckpt = str(tmp_path / "ckpt")
    save_checkpoint(e, ckpt)
    # second save crashes before publish — old checkpoint must survive
    e.ingest_text("extra.txt", "more fast content")
    e.commit()
    global_injector.arm("checkpoint.pre_publish", "raise")
    with pytest.raises(FaultInjected):
        save_checkpoint(e, ckpt)
    global_injector.disarm()
    e2 = load_checkpoint(ckpt, e.config)
    assert e2.index.num_live_docs == len(CORPUS)   # pre-crash state


def test_load_respects_model_in_meta(tmp_path):
    cfg = Config(model="tfidf", min_nnz_capacity=64, min_doc_capacity=8,
                 min_vocab_capacity=32,
                 documents_path=str(tmp_path / "d"))
    e = make_engine(tmp_path, model="tfidf")
    ingest_corpus(e)
    ckpt = str(tmp_path / "ckpt")
    save_checkpoint(e, ckpt)
    e2 = load_checkpoint(ckpt, cfg.replace(model="bm25"))
    assert e2.model.kind == "tfidf"


def test_repeated_saves_prune_versions(tmp_path):
    import os
    e = make_engine(tmp_path)
    ingest_corpus(e)
    ckpt = str(tmp_path / "ckpt")
    for i in range(3):
        e.ingest_text(f"extra{i}.txt", "more content")
        e.commit()
        save_checkpoint(e, ckpt)
    assert os.path.islink(ckpt)
    versions = [d for d in os.listdir(tmp_path) if d.startswith("ckpt.v")]
    assert len(versions) == 1          # superseded versions pruned
    e2 = load_checkpoint(ckpt, e.config)
    assert e2.index.num_live_docs == len(CORPUS) + 3

import pytest

from tests.test_engine import CORPUS, ingest_corpus, make_engine
from tfidf_tpu.engine.checkpoint import load_checkpoint, save_checkpoint
from tfidf_tpu.utils.config import Config
from tfidf_tpu.utils.faults import FaultInjected, global_injector


def test_save_load_roundtrip(tmp_path):
    e = make_engine(tmp_path)
    ingest_corpus(e)
    want = e.search("fast food", k=5)
    ckpt = str(tmp_path / "ckpt")
    save_checkpoint(e, ckpt)
    e2 = load_checkpoint(ckpt, e.config)
    got = e2.search("fast food", k=5)
    assert [(h.name, round(h.score, 5)) for h in want] == \
        [(h.name, round(h.score, 5)) for h in got]
    assert len(e2.vocab) == len(e.vocab)


def test_checkpoint_then_incremental_ingest(tmp_path):
    e = make_engine(tmp_path)
    ingest_corpus(e)
    ckpt = str(tmp_path / "ckpt")
    save_checkpoint(e, ckpt)
    e2 = load_checkpoint(ckpt, e.config)
    e2.ingest_text("new.txt", "fresh fast document")
    e2.commit()
    names = [h.name for h in e2.search("fast", k=10)]
    assert "new.txt" in names and "file1.txt" in names


def test_checkpoint_overwrite_is_atomic(tmp_path):
    e = make_engine(tmp_path)
    ingest_corpus(e)
    ckpt = str(tmp_path / "ckpt")
    save_checkpoint(e, ckpt)
    # second save crashes before publish — old checkpoint must survive
    e.ingest_text("extra.txt", "more fast content")
    e.commit()
    global_injector.arm("checkpoint.pre_publish", "raise")
    with pytest.raises(FaultInjected):
        save_checkpoint(e, ckpt)
    global_injector.disarm()
    e2 = load_checkpoint(ckpt, e.config)
    assert e2.index.num_live_docs == len(CORPUS)   # pre-crash state


def test_load_respects_model_in_meta(tmp_path):
    cfg = Config(model="tfidf", min_nnz_capacity=64, min_doc_capacity=8,
                 min_vocab_capacity=32,
                 documents_path=str(tmp_path / "d"))
    e = make_engine(tmp_path, model="tfidf")
    ingest_corpus(e)
    ckpt = str(tmp_path / "ckpt")
    save_checkpoint(e, ckpt)
    e2 = load_checkpoint(ckpt, cfg.replace(model="bm25"))
    assert e2.model.kind == "tfidf"


def test_repeated_saves_prune_versions(tmp_path):
    import os
    e = make_engine(tmp_path)
    ingest_corpus(e)
    ckpt = str(tmp_path / "ckpt")
    for i in range(4):
        e.ingest_text(f"extra{i}.txt", "more content")
        e.commit()
        save_checkpoint(e, ckpt)
    assert os.path.islink(ckpt)
    versions = [d for d in os.listdir(tmp_path) if d.startswith("ckpt.v")]
    # superseded versions pruned down to storage_keep_versions (default
    # 2): the published one plus one intact fallback for restore
    assert len(versions) == e.config.storage_keep_versions == 2
    # no .build temp dirs leak past a successful publish
    assert not [d for d in os.listdir(tmp_path)
                if d.startswith("ckpt.build.")]
    e2 = load_checkpoint(ckpt, e.config)
    assert e2.index.num_live_docs == len(CORPUS) + 4


def test_crash_mid_save_never_tears_newest_version(tmp_path):
    """Satellite regression (ISSUE 14): the version NAME only ever
    appears via one atomic rename of a complete manifested directory —
    a crash ANYWHERE mid-save (torn array write, fsync EIO, crash
    before the dir rename) must never make the newest ``.v<N>`` the
    torn one. After each simulated crash every surviving version dir
    passes its manifest check and loads to the pre-crash state."""
    import os

    from tfidf_tpu.engine.checkpoint import (checkpoint_versions,
                                             restore_checkpoint)
    from tfidf_tpu.utils import storage

    e = make_engine(tmp_path)
    ingest_corpus(e)
    ckpt = str(tmp_path / "ckpt")
    save_checkpoint(e, ckpt)
    good_docs = e.index.num_live_docs

    crashes = [
        ("torn docs.npz", storage.TORN_WRITE, "*docs.npz"),
        ("fsync EIO", storage.FSYNC_EIO, "*ckpt.build*"),
        ("crash before version rename", storage.CRASH_BEFORE_RENAME,
         "*ckpt.v*"),
    ]
    for i, (label, kind, glob) in enumerate(crashes):
        e.ingest_text(f"crash{i}.txt", "content that must not ack")
        e.commit()
        rid = storage.global_storage.arm(kind, glob, times=1)
        with pytest.raises(OSError):
            save_checkpoint(e, ckpt)
        storage.global_storage.remove(rid)
        for vdir in checkpoint_versions(ckpt):
            assert storage.verify_manifest(vdir) == [], (label, vdir)
    # the published checkpoint still restores the last GOOD state
    e2, _meta = restore_checkpoint(ckpt, e.config)
    assert e2.index.num_live_docs == good_docs


def test_bulk_restore_equals_per_doc_replay(tmp_path):
    """VERDICT r3 #5: the packed bulk-load restore (no per-doc Python
    loop, vectorized COO commit) must be result-identical to the per-doc
    array replay, including tie order, and leave the index fully mutable
    (upsert, delete) afterwards."""
    import numpy as np

    e = make_engine(tmp_path)
    ingest_corpus(e)
    for i in range(30):   # enough docs for several ELL width buckets
        e.ingest_text(f"extra{i}.txt",
                      " ".join(f"w{j}" for j in range(i % 7 + 1))
                      + " fast shared")
    e.commit()
    ckpt = str(tmp_path / "ckpt")
    save_checkpoint(e, ckpt)

    e_bulk = load_checkpoint(ckpt, e.config)
    assert e_bulk.index._packed is not None   # fast path actually taken

    # forced per-doc replay for comparison
    e_slow = load_checkpoint(ckpt, e.config)
    e_slow.index._packed = None
    e_slow.index._docs, e_slow.index._by_name = [], {}
    import json, os
    data = np.load(os.path.join(ckpt, "docs.npz"))
    with open(os.path.join(ckpt, "names.json"), encoding="utf-8") as f:
        names = json.load(f)
    offs = data["offsets"]
    for i, name in enumerate(names):
        lo, hi = int(offs[i]), int(offs[i + 1])
        e_slow.index.add_document_arrays(
            name, data["term_ids"][lo:hi], data["tfs"][lo:hi],
            float(data["lengths"][i]))
    e_slow.commit()

    for q in ("fast food", "shared", "w3 w4", "cat night"):
        b = [(h.name, round(h.score, 5)) for h in e_bulk.search(q, k=20)]
        s = [(h.name, round(h.score, 5)) for h in e_slow.search(q, k=20)]
        assert b == s, (q, b, s)

    # post-restore mutations drop the packed fast path, not correctness
    e_bulk.ingest_text("file1.txt", "totally different now")   # upsert
    assert e_bulk.delete("extra0.txt")
    e_bulk.commit()
    assert e_bulk.index._packed is None
    names_after = [h.name for h in e_bulk.search("fast", k=50)]
    assert "file1.txt" not in names_after      # re-written content
    assert "extra0.txt" not in names_after     # deleted
    assert "extra1.txt" in names_after


def test_fast_snapshot_restore_and_signature_guard(tmp_path):
    """load installs the checkpointed snapshot arrays (no re-layout)
    when the scoring config matches, and falls back to a full commit —
    with correct scores for the NEW config — when it does not."""
    import os

    e = make_engine(tmp_path)
    for i, text in enumerate(["alpha beta gamma", "beta gamma delta",
                              "gamma delta epsilon", "alpha alpha beta"]):
        e.ingest_text(f"f{i}.txt", text)
    e.commit()
    ckpt = str(tmp_path / "ckpt")
    save_checkpoint(e, ckpt)
    assert os.path.exists(os.path.join(ckpt, "snapshot.npz"))
    want = [(h.name, round(h.score, 5)) for h in e.search("beta gamma")]

    fast = load_checkpoint(ckpt, e.config)
    # the installed snapshot IS the committed state: version preserved,
    # and a follow-up commit() is a no-op (clean generation)
    v0 = fast.index.snapshot.version
    fast.commit()
    assert fast.index.snapshot.version == v0
    got = [(h.name, round(h.score, 5)) for h in fast.search("beta gamma")]
    assert got == want

    # different scoring config -> signature mismatch -> full commit with
    # scores that match a from-scratch engine under that config
    other_cfg = e.config.replace(bm25_k1=0.9)
    slow = load_checkpoint(ckpt, other_cfg)
    ref = make_engine(tmp_path / "ref", bm25_k1=0.9)
    for i, text in enumerate(["alpha beta gamma", "beta gamma delta",
                              "gamma delta epsilon", "alpha alpha beta"]):
        ref.ingest_text(f"f{i}.txt", text)
    ref.commit()
    got2 = [(h.name, round(h.score, 5))
            for h in slow.search("beta gamma")]
    want2 = [(h.name, round(h.score, 5))
             for h in ref.search("beta gamma")]
    assert got2 == want2
    assert got2 != want    # k1 change really changed the scores


# ---- segment-level fast restore (streaming mode, VERDICT r4 #5) ----

def _segments_engine(tmp_path, sub="segdocs", **kw):
    from tfidf_tpu.engine.engine import Engine
    cfg = Config(documents_path=str(tmp_path / sub),
                 index_mode="segments", max_segments=3,
                 min_doc_capacity=8, min_nnz_capacity=1 << 12,
                 min_vocab_capacity=64, query_batch=4, max_query_terms=8,
                 **kw)
    return Engine(cfg)


def _fill_streaming(e, n=30, commits=4):
    """Multiple commits -> multiple segments (+ a merge at max_segments=3),
    plus tombstones via delete and upsert."""
    per = max(1, n // commits)
    for c in range(commits):
        for i in range(c * per, min((c + 1) * per, n)):
            e.ingest_text(f"s{i}.txt",
                          f"token{i % 7} shared word{i % 3} extra{i}")
        e.commit()
    e.delete("s1.txt")
    e.ingest_text("s2.txt", "token0 shared rewritten")   # upsert
    e.commit()
    e.index.wait_for_merges()
    e.commit()


QUERIES = ("shared", "token0", "word1 token2", "rewritten", "extra5")


def _results(e):
    return [[(h.name, round(h.score, 5)) for h in e.search(q, k=10)]
            for q in QUERIES]


def test_segments_checkpoint_fast_restore(tmp_path):
    e = _segments_engine(tmp_path)
    _fill_streaming(e)
    want = _results(e)
    n_segments = len(e.index._segments)
    assert n_segments >= 2   # the fixture must produce a real segment list
    ckpt = str(tmp_path / "ckpt_seg")
    save_checkpoint(e, ckpt)
    import os
    assert os.path.exists(os.path.join(ckpt, "segstate.npz"))
    e2 = load_checkpoint(ckpt, e.config)
    # the SEGMENT LIST is restored (not one rebuilt mega-segment)
    assert len(e2.index._segments) == n_segments
    assert _results(e2) == want
    # restored index keeps streaming: new commits + merges still work
    e2.ingest_text("after.txt", "shared brandnew")
    e2.commit()
    assert any(h.name == "after.txt" for h in e2.search("brandnew"))
    assert any(h.name == "after.txt" for h in e2.search("shared", k=30))


def test_segments_checkpoint_with_pending_falls_back(tmp_path):
    e = _segments_engine(tmp_path, sub="segdocs2")
    _fill_streaming(e, n=12, commits=2)
    e.ingest_text("pending.txt", "uncommitted shared")   # stays pending
    ckpt = str(tmp_path / "ckpt_seg2")
    save_checkpoint(e, ckpt)
    import os
    assert not os.path.exists(os.path.join(ckpt, "segstate.npz"))
    e2 = load_checkpoint(ckpt, e.config)
    # pending doc was in docs.npz (live) and must be searchable
    assert any(h.name == "pending.txt" for h in e2.search("uncommitted"))


def test_segments_checkpoint_cosine_model(tmp_path):
    e = _segments_engine(tmp_path, sub="segdocs3", model="tfidf_cosine")
    _fill_streaming(e, n=12, commits=2)
    want = _results(e)
    ckpt = str(tmp_path / "ckpt_seg3")
    save_checkpoint(e, ckpt)
    e2 = load_checkpoint(ckpt, e.config)
    assert _results(e2) == want


def test_segments_restore_then_reexport(tmp_path):
    """A restored index must itself checkpoint correctly (dead rows
    re-export with empty postings — scoring-equivalent)."""
    e = _segments_engine(tmp_path, sub="segdocs4")
    _fill_streaming(e)
    ckpt = str(tmp_path / "ckpt_seg4")
    save_checkpoint(e, ckpt)
    e2 = load_checkpoint(ckpt, e.config)
    want = _results(e2)
    ckpt2 = str(tmp_path / "ckpt_seg4b")
    save_checkpoint(e2, ckpt2)
    e3 = load_checkpoint(ckpt2, e.config)
    assert _results(e3) == want


# ---- mesh checkpoint roundtrip (bulk restore, VERDICT r4 #5) ----

def _mesh_engine(tmp_path, sub, layout):
    from tfidf_tpu.engine.engine import Engine
    cfg = Config(documents_path=str(tmp_path / sub),
                 engine_mode="mesh", mesh_layout=layout,
                 min_doc_capacity=8, min_nnz_capacity=256,
                 min_vocab_capacity=64, query_batch=4, max_query_terms=8)
    return Engine(cfg)


@pytest.mark.parametrize("layout", ["coo", "ell"])
def test_mesh_checkpoint_roundtrip(tmp_path, layout):
    e = _mesh_engine(tmp_path, f"m_{layout}", layout)
    for i in range(20):
        e.ingest_text(f"m{i}.txt", f"shared word{i % 4} unique{i}")
    e.commit()
    e.delete("m3.txt")
    e.ingest_text("m4.txt", "shared rewritten")
    e.commit()
    ckpt = str(tmp_path / f"ckpt_m_{layout}")
    save_checkpoint(e, ckpt)
    e2 = load_checkpoint(ckpt, e.config)
    assert e2.index.mesh.devices.size == 8
    # restore == rebuild-from-live-corpus: the bulk path compacts
    # tombstones, so stats match a FRESH engine over the live docs (the
    # original's df still counts the tombstone until re-shard — Lucene
    # scores shift the same way when a merge drops deletes)
    ref = _mesh_engine(tmp_path, f"ref_{layout}", layout)
    for i in range(20):
        if i == 3:
            continue
        text = ("shared rewritten" if i == 4
                else f"shared word{i % 4} unique{i}")
        ref.ingest_text(f"m{i}.txt", text)
    ref.commit()
    for q in ("shared", "word1", "rewritten", "unique7"):
        g = e2.search(q, k=30)
        w = ref.search(q, k=30)
        # tie-tolerant: the per-shard top-k clamps at the doc-cap
        # bucket (8 at this tiny scale) and WHICH of the tied docs make
        # the cut is placement-dependent; scores and the names strictly
        # above the boundary must match exactly
        gs = sorted((round(h.score, 4) for h in g), reverse=True)
        ws = sorted((round(h.score, 4) for h in w), reverse=True)
        assert gs == ws, (q, gs, ws)
        if gs:
            bd = gs[-1]
            gn = {h.name for h in g if round(h.score, 4) > bd}
            wn = {h.name for h in w if round(h.score, 4) > bd}
            assert gn == wn, (q, gn, wn)
    # every live doc is individually searchable after restore
    for i in range(20):
        if i == 3:
            continue
        q = "rewritten" if i == 4 else f"unique{i}"
        assert any(h.name == f"m{i}.txt" for h in e2.search(q)), q
    # restored index keeps serving writes
    e2.ingest_text("after.txt", "shared brandnew")
    e2.commit()
    assert any(h.name == "after.txt" for h in e2.search("brandnew"))

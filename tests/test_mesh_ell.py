"""Mesh ELL layout: base+delta lifecycle on the 8-virtual-device mesh.

The ELL mesh layout must be result-equivalent to both the COO mesh
layout and the single-device engine; appends land in the COO delta
without an O(corpus) rebuild; stats are live-corpus (so deletes tighten
IDF immediately, matching the local rebuild engine).
"""

import numpy as np
import pytest

from tfidf_tpu.engine.engine import Engine
from tfidf_tpu.parallel.mesh_ell_index import MeshEllIndex
from tfidf_tpu.utils.config import Config

TEXTS = {
    "a.txt": "the quick brown fox jumps over the lazy dog",
    "b.txt": "a fast brown fox and a quick red fox",
    "c.txt": "lorem ipsum dolor sit amet",
    "d.txt": "the dog sleeps all day long",
    "e.txt": "red dogs chase brown foxes at dawn",
    "f.txt": "ipsum lorem amet dolor",
    "g.txt": "quick quick quick brown brown dog",
    "h.txt": "foxes and dogs and foxes again",
    "i.txt": "dawn chorus over the lazy meadow",
    "j.txt": "meadow fox naps in the red dawn",
}

QUERIES = ("fox", "brown dog", "lorem ipsum", "red dawn", "meadow")


def make_engine(tmp_path, sub, mode, **kw):
    cfg = Config(documents_path=str(tmp_path / sub), engine_mode=mode,
                 min_doc_capacity=8, min_nnz_capacity=256,
                 min_vocab_capacity=64, query_batch=4, max_query_terms=8,
                 **kw)
    return Engine(cfg)


def results(engine, queries=QUERIES, k=None):
    return [sorted(((h.name, round(h.score, 4)) for h in
                    engine.search(q, k=k)),
                   key=lambda nv: (-nv[1], nv[0]))
            for q in queries]


class TestEquivalence:
    @pytest.mark.parametrize("model", ["bm25", "tfidf"])
    def test_ell_mesh_equals_local(self, tmp_path, model):
        mesh = make_engine(tmp_path, "m", "mesh", model=model)
        local = make_engine(tmp_path, "l", "local", model=model)
        assert isinstance(mesh.index, MeshEllIndex)
        for e in (mesh, local):
            for name, text in TEXTS.items():
                e.ingest_text(name, text)
            e.commit()
        assert results(mesh) == results(local)

    def test_cosine_falls_back_to_coo(self, tmp_path):
        e = make_engine(tmp_path, "cf", "mesh", model="tfidf_cosine")
        assert not isinstance(e.index, MeshEllIndex)

    def test_parity_falls_back_to_coo(self, tmp_path):
        e = make_engine(tmp_path, "pf", "mesh", lucene_parity=True)
        assert not isinstance(e.index, MeshEllIndex)

    def test_delta_append_equals_local(self, tmp_path):
        """Appends after the initial build go to the COO delta and score
        identically to a local engine holding everything."""
        mesh = make_engine(tmp_path, "md", "mesh")
        local = make_engine(tmp_path, "ld", "local")
        items = list(TEXTS.items())
        for name, text in items:
            local.ingest_text(name, text)
        local.commit()
        for name, text in items[:8]:
            mesh.ingest_text(name, text)
        mesh.commit()          # base: 8 docs
        for name, text in items[8:]:
            mesh.ingest_text(name, text)
        mesh.commit()          # delta: 2 docs (below rebuild fraction)
        assert mesh.index.appends >= 1
        snap = mesh.index.snapshot
        assert snap.total_live == len(items)
        assert int(np.asarray(snap.delta.n_live).sum()) == 2
        assert results(mesh) == results(local)

    def test_stats_refresh_covers_delta(self, tmp_path):
        """df/N/avgdl include delta docs, and base impacts are refreshed
        — a doc in the base must see its score change when delta docs
        shift the global df."""
        e = make_engine(tmp_path, "sr", "mesh")
        e.ingest_text("a.txt", "rare shared")
        e.ingest_text("pad1.txt", "filler words only here")
        e.ingest_text("pad2.txt", "other filler words again")
        e.ingest_text("pad3.txt", "more padding text")
        e.ingest_text("pad4.txt", "yet more padding")
        e.ingest_text("pad5.txt", "final pad file")
        e.commit()
        s1 = {h.name: h.score for h in e.search("shared")}
        e.ingest_text("x.txt", "shared appears again")   # delta append
        e.commit()
        assert e.index.appends >= 1 or e.index.rebuilds >= 2
        s2 = {h.name: h.score for h in e.search("shared")}
        assert abs(s1["a.txt"] - s2["a.txt"]) > 1e-6


class TestUnboundedGuard:
    def test_parity_fallback_refuses_past_cap(self, tmp_path):
        """VERDICT r3 #7: the unbounded parity fallback is an O(corpus)
        duplicate-index replay; past the size cap it must fail fast with
        a clear error instead of stalling the node, and raising the cap
        explicitly must re-enable it."""
        e = make_engine(tmp_path, "ug", "mesh")
        for name, text in TEXTS.items():
            e.ingest_text(name, text)
        e.commit()
        e.searcher.unbounded_parity_max_docs = 5   # below the 10 live docs
        with pytest.raises(ValueError, match="parity fallback refused"):
            e.search("fox", unbounded=True)
        e.searcher.unbounded_parity_max_docs = 1_000   # explicit opt-in
        hits = e.search("fox", unbounded=True)
        assert hits


class TestLifecycle:
    def test_delete_in_base_and_delta(self, tmp_path):
        e = make_engine(tmp_path, "del", "mesh")
        items = list(TEXTS.items())
        for name, text in items[:8]:
            e.ingest_text(name, text)
        e.commit()
        for name, text in items[8:]:
            e.ingest_text(name, text)
        e.commit()
        # b.txt lives in the base, j.txt in the delta
        assert e.delete("b.txt")
        assert e.delete("j.txt")
        e.commit()
        names = [h.name for h in e.search("fox", k=10)]
        assert "b.txt" not in names and "j.txt" not in names
        assert "a.txt" in names
        # live-corpus stats: the delete changed df -> scores match a
        # local engine over the surviving docs
        local = make_engine(tmp_path, "dl", "local")
        for name, text in items:
            if name not in ("b.txt", "j.txt"):
                local.ingest_text(name, text)
        local.commit()
        assert results(e) == results(local)

    def test_upsert_moves_doc_to_delta(self, tmp_path):
        e = make_engine(tmp_path, "up", "mesh")
        for name, text in TEXTS.items():
            e.ingest_text(name, text)
        e.commit()
        e.ingest_text("a.txt", "replacement narwhal content")
        e.commit()
        assert [h.name for h in e.search("narwhal")] == ["a.txt"]
        assert "a.txt" not in [h.name for h in e.search("quick", k=10)]
        assert e.index.num_live_docs == len(TEXTS)

    def test_delta_growth_triggers_fold(self, tmp_path):
        e = make_engine(tmp_path, "fold", "mesh")
        e.ingest_text("seed.txt", "alpha beta")
        e.commit()
        r0 = e.index.rebuilds
        for i in range(30):     # far beyond delta_rebuild_frac
            e.ingest_text(f"d{i}.txt", f"alpha token{i % 7}")
            e.commit()
        assert e.index.rebuilds > r0
        assert e.index.num_live_docs == 31
        hits = e.search("token3", k=10)
        assert len(hits) == 4   # i in {3, 10, 17, 24} within range(30)

    def test_vocab_growth_reshards(self, tmp_path):
        e = make_engine(tmp_path, "vg", "mesh")
        for name, text in list(TEXTS.items())[:4]:
            e.ingest_text(name, text)
        e.commit()
        r0 = e.index.rebuilds
        for i in range(4):
            e.ingest_text(f"v{i}.txt",
                          " ".join(f"neo{i}_{j}" for j in range(40)))
        e.commit()
        assert e.index.rebuilds > r0
        assert [h.name for h in e.search("neo2_7")] == ["v2.txt"]
        assert "a.txt" in [h.name for h in e.search("fox", k=10)]

    def test_wide_doc_spills_to_residual(self, tmp_path):
        e = make_engine(tmp_path, "wide", "mesh", ell_width_cap=16)
        local = make_engine(tmp_path, "widel", "local", ell_width_cap=16)
        wide = " ".join(f"w{i:03d}" for i in range(100))
        for eng in (e, local):
            eng.ingest_text("wide.txt", wide)
            eng.ingest_text("a.txt", "w001 w002 and more")
            eng.commit()
        qs = ("w001", "w050 w099")
        assert results(e, qs) == results(local, qs)

    def test_name_mapping_through_permutation(self, tmp_path):
        """ELL rows are width-sorted (a permutation of insertion order):
        every doc must come back under its own name."""
        e = make_engine(tmp_path, "perm", "mesh")
        rng = np.random.default_rng(3)
        for i in range(24):
            n = int(rng.integers(1, 30))
            e.ingest_text(f"p{i:02d}.txt",
                          " ".join(f"u{i:02d}" for _ in range(n))
                          + f" mark{i:02d}")
        e.commit()
        for i in range(24):
            assert [h.name for h in e.search(f"mark{i:02d}")] == \
                [f"p{i:02d}.txt"], i


class TestIncrementalStats:
    """Incremental df/N/avgdl must equal a from-scratch recompute after
    any mix of adds, upserts (base/delta/pending), and deletes."""

    def _check(self, e):
        cap = e.vocab.capacity()
        inc = e.index._live_stats(cap)
        scr = e.index._live_stats_scratch(cap)
        assert inc[1] == scr[1], "live count"
        assert abs(inc[2] - scr[2]) < 1e-6, "length sum"
        np.testing.assert_array_equal(inc[0], scr[0])
        # the DEVICE-resident replicated df (maintained by journaled
        # sparse scatters between rebuilds) must match the host truth
        snap = e.index.snapshot
        if snap is not None and not e.index._df_delta.journal:
            dev = np.asarray(snap.df_g)
            want, _n, _l = e.index._live_stats(dev.shape[0])
            np.testing.assert_array_equal(dev, want)

    def test_stats_track_mutations(self, tmp_path):
        e = make_engine(tmp_path, "inc", "mesh")
        for name, text in list(TEXTS.items())[:6]:
            e.ingest_text(name, text)
        self._check(e)
        e.commit()
        self._check(e)
        # delta appends
        for name, text in list(TEXTS.items())[6:]:
            e.ingest_text(name, text)
        e.commit()
        self._check(e)
        # upsert pending, base, and delta docs
        e.ingest_text("zz.txt", "pending upsert one")
        e.ingest_text("zz.txt", "pending upsert two rewritten")
        self._check(e)
        e.ingest_text("a.txt", "base upsert content")       # base doc
        e.ingest_text("j.txt", "delta upsert content")      # delta doc
        self._check(e)
        e.commit()
        self._check(e)
        # deletes across all regions
        e.delete("b.txt")
        e.delete("zz.txt")
        assert not e.delete("nope.txt")
        self._check(e)
        e.commit()
        self._check(e)
        # equivalence with a local engine over the same surviving docs
        local = make_engine(tmp_path, "incl", "local")
        survivors = {n: t for n, t in TEXTS.items() if n != "b.txt"}
        survivors["a.txt"] = "base upsert content"
        survivors["j.txt"] = "delta upsert content"
        for n, t in survivors.items():
            local.ingest_text(n, t)
        local.commit()
        assert results(e) == results(local)

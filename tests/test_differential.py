"""Randomized differential testing across every engine mode.

A random interleaving of upserts, deletes, and commits is applied to
each engine and to a plain-Python shadow model; after every commit the
engine's answers are checked against the numpy BM25 oracle
(``tests/oracle.py`` — live-document statistics) and against structural
invariants. This is the property-based net over everything the
targeted tests cover piecewise: segment tombstones, tiered merges,
mesh live masks, delta folding, upsert routing.

Modes with live-document statistics (rebuild, mesh-ELL — which
refreshes global stats over the live corpus at every commit) get exact
score comparison; segments and the COO mesh layout keep tombstones in
df until merge/re-shard (Lucene's docFreq semantics, by design —
documented in their module docstrings), so they are checked on
invariants only.
"""

from __future__ import annotations

import numpy as np
import pytest

from tests.oracle import bm25_scores
from tfidf_tpu.engine.engine import Engine
from tfidf_tpu.utils.config import Config

WORDS = [f"w{i:02d}" for i in range(40)]


def make_engine(tmp_path, tag, mode):
    kw = {}
    if mode == "rebuild":
        kw["index_mode"] = "rebuild"
    elif mode == "segments":
        kw["index_mode"] = "segments"
        kw["max_segments"] = 3
    elif mode == "mesh_ell":
        kw["engine_mode"] = "mesh"
        kw["mesh_layout"] = "ell"
    elif mode == "mesh_coo":
        kw["engine_mode"] = "mesh"
        kw["mesh_layout"] = "coo"
    cfg = Config(documents_path=str(tmp_path / tag),
                 min_doc_capacity=8, min_nnz_capacity=256,
                 min_vocab_capacity=64, query_batch=8,
                 max_query_terms=8, **kw)
    return Engine(cfg)


def oracle_topk(engine, shadow, query_words, k=5):
    """Top-k (name, score) from the shadow corpus under live stats."""
    names = sorted(shadow)
    docs, lengths = [], []
    for n in names:
        counts: dict[int, int] = {}
        for w in shadow[n]:
            tid = engine.vocab.map_counts({w: 1}, add=False)
            for t in tid:
                counts[t] = counts.get(t, 0) + shadow[n][w]
        docs.append(counts)
        lengths.append(float(sum(shadow[n].values())))
    qcounts: dict[int, float] = {}
    for w in query_words:
        for t in engine.vocab.map_counts({w: 1}, add=False):
            qcounts[t] = qcounts.get(t, 0.0) + 1.0
    scores = bm25_scores(docs, lengths, qcounts)
    order = sorted(range(len(names)), key=lambda i: (-scores[i], names[i]))
    return [(names[i], scores[i]) for i in order if scores[i] > 0][:k]


@pytest.mark.parametrize("mode", ["rebuild", "segments", "mesh_ell",
                                  "mesh_coo"])
def test_randomized_ops_match_oracle(tmp_path, mode):
    rng = np.random.default_rng(1234)
    engine = make_engine(tmp_path, mode, mode)
    shadow: dict[str, dict[str, int]] = {}
    # tombstone-df modes (segments, mesh COO) shift scores by design
    exact_scores = mode in ("rebuild", "mesh_ell")

    def random_doc():
        n_words = int(rng.integers(3, 12))
        picks = rng.choice(WORDS, size=n_words)
        counts: dict[str, int] = {}
        for w in picks:
            counts[str(w)] = counts.get(str(w), 0) + 1
        return counts

    for round_i in range(5):
        for _ in range(12):
            roll = rng.random()
            name = f"doc{int(rng.integers(0, 30)):02d}"
            if roll < 0.25 and shadow:
                victim = str(rng.choice(sorted(shadow)))
                engine.delete(victim)
                shadow.pop(victim, None)
            else:
                counts = random_doc()
                text = " ".join(w for w, c in counts.items()
                                for _ in range(c))
                engine.ingest_text(name, text)
                shadow[name] = counts
        engine.commit()
        if hasattr(engine.index, "wait_for_merges"):
            engine.index.wait_for_merges(timeout=30)
            engine.commit()
            # the incremental live counters must track the truth
            # through upserts, deletes, and merges
            assert engine.index.nnz_live == \
                engine.index._nnz_live_scratch(), mode
            assert engine.index.size_bytes() == \
                engine.index._bytes_live_scratch(), mode

        queries = [" ".join(map(str, rng.choice(WORDS, size=2)))
                   for _ in range(4)]
        results = engine.search_batch(queries, k=5)
        for q, hits in zip(queries, results):
            want = oracle_topk(engine, shadow, q.split(), k=5)
            got_names = [h.name for h in hits]
            # invariant: only live documents, no duplicates
            assert len(set(got_names)) == len(got_names), (mode, q)
            assert all(n in shadow for n in got_names), \
                (mode, q, got_names)
            if exact_scores:
                np.testing.assert_allclose(
                    sorted((h.score for h in hits), reverse=True),
                    sorted((s for _n, s in want), reverse=True),
                    rtol=2e-4, atol=1e-5,
                    err_msg=f"{mode} round {round_i} query {q!r}")
                # hit set matches modulo equal-score ties
                want_by_score: dict[float, set[str]] = {}
                for n, s in want:
                    want_by_score.setdefault(round(s, 4), set()).add(n)
                for h in hits:
                    pool = want_by_score.get(round(h.score, 4), set())
                    assert h.name in pool or any(
                        abs(h.score - s) <= 2e-4 * abs(s) + 1e-5
                        and h.name in ns
                        for s, ns in want_by_score.items()), \
                        (mode, q, h, want)
            else:
                # segments mode: every oracle hit clearly above the
                # engine's k-th score must be present (tombstone df only
                # shifts scores, never drops a matching live doc)
                if len(hits) == 5:
                    kth = hits[-1].score
                    must = {n for n, s in want if s > kth * 1.2}
                else:
                    must = {n for n, _s in want}
                assert must <= set(got_names), (mode, q, must, got_names)

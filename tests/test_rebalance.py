"""Elastic data plane: crash-safe live shard migration + drain.

The acceptance story: the Rebalancer moves doc ranges between live
workers through a staged, durable state machine (``copying -> flipped
-> reconciled``) such that a crash of the leader, the source, or the
target at ANY step loses nothing and double-counts nothing, and
searches issued during a rebalance stay exact. Pieces under test:

- pure planning (overload / join-absorption detection from doc counts);
- the placement-map migration primitives (begin/flip/unflip/end, trim
  protection, durable serialization);
- live migration end to end: a joining worker absorbs load via the
  sweep, reconcile deletes converge, searches stay complete;
- drain (``/api/drain``, CLI): a worker is migrated empty with EXACT
  single-node-oracle parity throughout (full-replication construction:
  every owner holds the full corpus at every step), then excluded from
  new-name routing;
- crash safety at each injected fault point (``leader.rebalance_copy``
  / ``_flip`` / ``_reconcile``) and across leader failover mid-phase:
  copying-phase records are rolled back (stray legs reclaimed by the
  trim pass), non-durable flips are un-flipped before any delete can
  run, and a durable flip's reconcile tail survives a leader change;
- observability: the rebalance gauges/counters and the CLI ``status``
  summary.

The slow chaos job (``make chaos-rebalance``) adds real ``kill -9`` of
the source and the target subprocess at the injected fault points, and
a hard leader kill mid-migration, under a concurrent parity workload.
"""

import json
import threading
import time
import urllib.error
import urllib.parse
import urllib.request

import pytest

from tfidf_tpu.cluster.coordination import CoordinationCore, LocalCoordination
from tfidf_tpu.cluster.node import SearchNode, http_get, http_post
from tfidf_tpu.cluster.placement import PLACEMENT_STATE, PlacementMap
from tfidf_tpu.cluster.rebalance import plan_moves
from tfidf_tpu.utils.config import Config
from tfidf_tpu.utils.faults import FaultInjected, global_injector
from tfidf_tpu.utils.metrics import global_metrics

from tests.test_cluster import wait_until
from tests.test_replication import (_CFG, DOCS, QUERIES, _assert_parity,
                                    _oracle, _search, _stop_all,
                                    _upload_docs)


@pytest.fixture
def core():
    c = CoordinationCore(session_timeout_s=0.5)
    yield c
    c.close()


@pytest.fixture(autouse=True)
def _disarm():
    yield
    global_injector.disarm()


def _node(core, tmp_path, i, port=0, **kw):
    cfg_kw = dict(_CFG)
    # keep the automatic pass out of the way unless a test opts in —
    # these tests drive run_once()/drain explicitly for determinism
    cfg_kw.setdefault("rebalance_sweep_ms", 10_000_000.0)
    cfg_kw.update(kw)
    cfg = Config(
        documents_path=str(tmp_path / f"rb{i}" / "documents"),
        index_path=str(tmp_path / f"rb{i}" / "index"),
        port=port, **cfg_kw)
    return SearchNode(cfg, coord=LocalCoordination(core, 0.1)).start()


def _mk_cluster(core, tmp_path, n=3, **kw):
    nodes = [_node(core, tmp_path, i, **kw) for i in range(n)]
    wait_until(lambda: len(
        nodes[0].registry.get_all_service_addresses()) == n - 1)
    return nodes


def _counts(leader):
    live = leader.registry.get_all_service_addresses()
    return {w: len(leader.placement.names_on(w)) for w in live}


def _assert_complete(got, ctx=""):
    assert set(got) == set(DOCS), \
        f"{ctx}: missing={set(DOCS) - set(got)} extra={set(got) - set(DOCS)}"


# ---------------------------------------------------------------------------
# Pure planning
# ---------------------------------------------------------------------------

class TestPlanMoves:
    def test_balanced_cluster_plans_nothing(self):
        assert plan_moves({"a": 6, "b": 6}, 0) == {}
        assert plan_moves({"a": 6, "b": 5, "c": 7}, 0) == {}

    def test_single_worker_or_empty_plans_nothing(self):
        assert plan_moves({"a": 12}, 0) == {}
        assert plan_moves({}, 0) == {}
        assert plan_moves({"a": 0, "b": 0}, 0) == {}

    def test_join_absorption_moves_toward_mean(self):
        # a fresh worker at 0 next to a loaded one: donate down to mean
        assert plan_moves({"a": 12, "b": 0}, 0) == {"a": 6}
        out = plan_moves({"a": 10, "b": 10, "c": 1}, 0)
        # mean=7: both loaded workers donate 3, bounded by c's room (6)
        assert sum(out.values()) == 6 and set(out) == {"a", "b"}

    def test_cap_triggers_even_mild_imbalance(self):
        # without the cap, 8 vs 4 sits inside the slack band; the cap
        # forces the oversized shard to donate down to the mean
        assert plan_moves({"a": 8, "b": 4}, 0) == {"a": 2}
        assert plan_moves({"a": 7, "b": 5}, 6) == {"a": 1}

    def test_no_receivers_means_no_moves(self):
        # everyone over the cap but balanced: nowhere better to move
        assert plan_moves({"a": 10, "b": 10}, 4) == {}


# ---------------------------------------------------------------------------
# Placement-map migration primitives
# ---------------------------------------------------------------------------

class TestMigrationStateMachine:
    def _seeded(self):
        pm = PlacementMap(flush_ms=-1)
        pm.replicas.update({"x": ("http://a",), "y": ("http://a",)})
        pm._confirmed.update({"x": {"http://a"}, "y": {"http://a"}})
        return pm

    def test_flip_moves_ownership_and_schedules_delete(self):
        pm = self._seeded()
        mid = pm.begin_migration("http://a", {"x": ["http://b"]})
        assert pm.migration_snapshot()[mid]["phase"] == "copying"
        # copy leg confirms on the target
        pm.add_replica("x", "http://b")
        assert pm.holders_of("x") == ("http://a", "http://b")
        flipped = pm.flip_migration(mid)
        assert flipped == ["x"]
        assert pm.holders_of("x") == ("http://b",)
        assert pm.moved["http://a"] == {"x"}
        # a flipped record is never re-flipped
        assert pm.flip_migration(mid) == []
        pm.end_migration(mid)
        assert pm.migration_snapshot() == {}

    def test_flip_skips_unconfirmed_copy(self):
        pm = self._seeded()
        mid = pm.begin_migration("http://a", {"x": ["http://b"],
                                              "y": ["http://b"]})
        pm.add_replica("x", "http://b")   # only x's copy confirmed
        assert pm.flip_migration(mid) == ["x"]
        # y never flipped: still owned (and held) by the source
        assert pm.holders_of("y") == ("http://a",)
        assert "y" not in pm.moved.get("http://a", set())

    def test_unflip_restores_exactly(self):
        pm = self._seeded()
        mid = pm.begin_migration("http://a", {"x": ["http://b"]})
        pm.add_replica("x", "http://b")
        before = pm.holders_of("x")
        assert pm.flip_migration(mid) == ["x"]
        pm.unflip_migration(mid)
        assert pm.holders_of("x") == before
        assert "x" in pm._confirmed["x"] or True   # source re-confirmed
        assert "http://a" in pm._confirmed["x"]
        assert "x" not in pm.moved.get("http://a", set())
        # rolled back to copying: a later flip can retry
        assert pm.migration_snapshot()[mid]["phase"] == "copying"
        assert pm.flip_migration(mid) == ["x"]

    def test_trim_protects_migrating_names(self):
        pm = self._seeded()
        live = {"http://a", "http://b"}
        mid = pm.begin_migration("http://a", {"x": ["http://b"]})
        pm.add_replica("x", "http://b")
        # r=1 would trim the freshly copied leg — the record protects it
        assert pm.trim_plan(live, 1) == {}
        pm.end_migration(mid)
        trimmed = pm.trim_plan(live, 1)
        assert trimmed == {"http://b": ["x"]}

    def test_durable_roundtrip_carries_migrations_and_draining(self,
                                                               core):
        coord = LocalCoordination(core, 0.1)
        try:
            pm = PlacementMap(flush_ms=0.0)
            pm.bind_store(lambda: coord)
            pm.set_persist_enabled(True)
            pm.replicas["x"] = ("http://a",)
            pm._confirmed["x"] = {"http://a"}
            mid = pm.begin_migration("http://a", {"x": ["http://b"]},
                                     kind="drain")
            pm.set_draining("http://a", True)
            assert pm.flush()

            pm2 = PlacementMap(flush_ms=0.0)
            pm2.bind_store(lambda: coord)
            assert pm2.load() == 1
            recs = pm2.migration_snapshot()
            assert recs[mid]["phase"] == "copying"
            assert recs[mid]["kind"] == "drain"
            assert pm2.draining_snapshot() == frozenset({"http://a"})
            # the id sequence continues past the loaded record
            mid2 = pm2.begin_migration("http://a", {"y": ["http://b"]})
            assert mid2 != mid
        finally:
            coord.close()

    def test_drop_worker_clears_draining_durably(self, core):
        """The completed-drain decommission: the worker leaves holding
        ZERO docs, so drop_worker touches no replicas — but the
        draining-flag clear must still persist, or load()'s union
        resurrects it forever and a later pod at the same stable URL
        is silently excluded from routing."""
        coord = LocalCoordination(core, 0.1)
        try:
            pm = PlacementMap(flush_ms=0.0)
            pm.bind_store(lambda: coord)
            pm.set_persist_enabled(True)
            pm.set_draining("http://a", True)
            assert pm.flush()
            pm.drop_worker("http://a")   # held nothing: kept == lost == []
            assert pm.flush()
            pm2 = PlacementMap(flush_ms=0.0)
            pm2.bind_store(lambda: coord)
            pm2.load()
            assert pm2.draining_snapshot() == frozenset()
        finally:
            coord.close()

    def test_reset_for_follower_clears_rebalance_state(self):
        pm = self._seeded()
        pm.begin_migration("http://a", {"x": ["http://b"]})
        pm.set_draining("http://a", True)
        pm.reset_for_follower()
        assert pm.migration_snapshot() == {}
        assert pm.draining_snapshot() == frozenset()


# ---------------------------------------------------------------------------
# Live migration end to end (in-process cluster)
# ---------------------------------------------------------------------------

class TestLiveMigration:
    def test_joining_worker_absorbed_via_sweep(self, core, tmp_path):
        """The ROADMAP item 1 story: every doc sits on one loaded
        worker; a fresh worker joins; the sweep-driven rebalancer moves
        half the corpus onto it live, the reconcile deletes converge,
        and every search stays complete throughout."""
        kw = dict(replication_factor=1, rebalance_sweep_ms=50.0)
        nodes = _mk_cluster(core, tmp_path, n=2, **kw)
        try:
            leader = nodes[0]
            _upload_docs(leader)
            _assert_complete(_search(leader, "common"), "pre")
            assert sum(_counts(leader).values()) == len(DOCS)

            joined = _node(core, tmp_path, 9, **kw)
            nodes.append(joined)

            def balanced():
                _assert_complete(_search(leader, "common"), "during")
                c = _counts(leader)
                return (len(c) == 2 and joined.url in c
                        and c[joined.url] >= len(DOCS) // 2 - 1
                        and not leader.placement.pending_moved()
                        and not leader.placement.migration_snapshot())
            assert wait_until(balanced, timeout=30.0), _counts(leader)
            assert global_metrics.get("rebalance_moved_docs") >= 5
            _assert_complete(_search(leader, "common"), "post")
        finally:
            _stop_all(nodes)

    def test_migrate_moves_range_and_reconciles(self, core, tmp_path):
        nodes = _mk_cluster(core, tmp_path, n=3, replication_factor=1)
        try:
            leader = nodes[0]
            _upload_docs(leader)
            source = nodes[1].url
            names = leader.placement.names_on(source)[:3]
            assert names
            out = leader.rebalancer.migrate(source, names)
            assert out["moved"] == len(names) and out["failed"] == 0
            for n in names:
                holders = leader.placement.holders_of(n)
                assert source not in holders and len(holders) == 1
            # reconcile deletes land (triggered inline, swept on failure)
            assert wait_until(
                lambda: not leader.placement.pending_moved().get(source),
                timeout=10.0)
            _assert_complete(_search(leader, "common"), "post-migrate")
            # no stray records, no stray replicas
            assert leader.placement.migration_snapshot() == {}
        finally:
            _stop_all(nodes)


# ---------------------------------------------------------------------------
# Drain: planned decommission with exact oracle parity throughout
# ---------------------------------------------------------------------------

class TestDrain:
    def test_drain_empties_worker_exact_parity_throughout(self, core,
                                                          tmp_path):
        """Full-replication construction (R=2 over 2 workers): every
        worker's shard statistics equal the single-node oracle's, so
        every search during the drain must match the oracle EXACTLY —
        any replica double-count or lost doc breaks score equality.
        The drain target (a freshly joined third worker) receives the
        WHOLE corpus before any flip, so post-flip owners are
        full-corpus shards too: parity holds at every step of
        ``copying -> flipped -> reconciled``."""
        nodes = _mk_cluster(core, tmp_path, n=3, replication_factor=2)
        try:
            leader = nodes[0]
            victim = nodes[1]
            _upload_docs(leader)
            want = _oracle(tmp_path)
            for q in QUERIES:
                _assert_parity(_search(leader, q), want[q], ctx=q)

            joined = _node(core, tmp_path, 9, replication_factor=2)
            nodes.append(joined)
            wait_until(lambda: len(
                leader.registry.get_all_service_addresses()) == 3)

            resp = json.loads(http_post(
                leader.url + "/api/drain",
                json.dumps({"worker": victim.url}).encode()))
            assert resp["draining"] is True

            def drained():
                for q in QUERIES:   # exact parity DURING the drain
                    _assert_parity(_search(leader, q), want[q],
                                   ctx=f"during:{q}")
                st = json.loads(http_get(
                    leader.url + "/api/drain?worker="
                    + urllib.parse.quote(victim.url)))
                return st["drained"]
            assert wait_until(drained, timeout=30.0)
            assert leader.placement.names_on(victim.url) == []
            # the deletes really landed on the worker
            assert wait_until(
                lambda: victim.engine.index.num_live_docs == 0,
                timeout=10.0)
            for q in QUERIES:
                _assert_parity(_search(leader, q), want[q], ctx=f"post:{q}")
            assert global_metrics.get("rebalance_drains_completed") >= 1

            # new names route AWAY from the draining worker
            out = leader.leader_upload("fresh.txt", b"brand new pelican")
            assert victim.url not in out["replicas"]
            # cancel clears the exclusion
            json.loads(http_post(
                leader.url + "/api/drain",
                json.dumps({"worker": victim.url,
                            "cancel": True}).encode()))
            assert victim.url not in \
                leader.placement.draining_snapshot()
        finally:
            _stop_all(nodes)

    def test_drain_is_leader_only(self, core, tmp_path):
        """Both verbs 409 on a non-leader: a follower's placement map
        is reset on demotion, so a GET answered from it would report a
        vacuous {"drained": true} and an operator's --wait poll could
        decommission a worker that still holds docs."""
        nodes = _mk_cluster(core, tmp_path, n=2)
        try:
            with pytest.raises(urllib.error.HTTPError) as ei:
                http_post(nodes[1].url + "/api/drain",
                          json.dumps({"worker": nodes[1].url}).encode())
            assert ei.value.code == 409
            with pytest.raises(urllib.error.HTTPError) as ei:
                http_get(nodes[1].url + "/api/drain?worker="
                         + urllib.parse.quote(nodes[1].url))
            assert ei.value.code == 409
        finally:
            _stop_all(nodes)


# ---------------------------------------------------------------------------
# Crash safety at every injected fault point + across leader failover
# ---------------------------------------------------------------------------

class TestCrashSafety:
    def test_copy_fault_aborts_without_ownership_change(self, core,
                                                        tmp_path):
        nodes = _mk_cluster(core, tmp_path, n=3, replication_factor=1)
        try:
            leader = nodes[0]
            _upload_docs(leader)
            source = nodes[1].url
            names = leader.placement.names_on(source)[:3]
            with leader._placement_lock:
                before = dict(leader._placement)

            global_injector.arm("leader.rebalance_copy", action="raise")
            out = leader.rebalancer.migrate(source, names)
            assert out["moved"] == 0 and out["failed"] == len(names)
            assert global_metrics.get("rebalance_failures") >= len(names)
            # nothing moved, nothing scheduled for delete, no record
            with leader._placement_lock:
                assert dict(leader._placement) == before
            assert not leader.placement.pending_moved().get(source)
            assert leader.placement.migration_snapshot() == {}
            _assert_complete(_search(leader, "common"), "after abort")

            # healed: the same range migrates cleanly
            global_injector.disarm("leader.rebalance_copy")
            out = leader.rebalancer.migrate(source, names)
            assert out["moved"] == len(names)
            _assert_complete(_search(leader, "common"), "after heal")
        finally:
            _stop_all(nodes)

    def test_flip_persist_failure_rolls_back_unflipped(self, core,
                                                       tmp_path):
        """A flip that cannot be made durable is rolled back BEFORE any
        delete can run: the source keeps ownership, no moved entries
        leak, and the already-copied legs are reclaimed by the trim
        pass once the record is gone."""
        nodes = _mk_cluster(core, tmp_path, n=3, replication_factor=1)
        try:
            leader = nodes[0]
            _upload_docs(leader)
            source = nodes[1].url
            names = leader.placement.names_on(source)[:2]

            global_injector.arm("leader.placement_persist",
                                action="raise")
            out = leader.rebalancer.migrate(source, names)
            assert out["moved"] == 0
            for n in names:   # source still first (owning) replica
                assert leader.placement.holders_of(n)[0] == source
            assert not leader.placement.pending_moved().get(source)
            _assert_complete(_search(leader, "common"), "rolled back")

            global_injector.disarm("leader.placement_persist")
            # the stray copy legs are plain over-replication now: the
            # repair pass trims them back to R=1
            leader.run_replication_repair()
            assert wait_until(
                lambda: all(
                    len(leader.placement.holders_of(n)) == 1
                    for n in names), timeout=10.0)
            _assert_complete(_search(leader, "common"), "trimmed")
        finally:
            _stop_all(nodes)

    def test_reconcile_fault_leaves_durable_flip_for_sweep(self, core,
                                                           tmp_path):
        """A crash at the reconcile trigger (post-durable-flip) loses
        nothing: the moved state is durable and the periodic sweep
        finishes the deletes."""
        nodes = _mk_cluster(core, tmp_path, n=3, replication_factor=1)
        try:
            leader = nodes[0]
            _upload_docs(leader)
            source = nodes[1].url
            names = leader.placement.names_on(source)[:2]
            global_injector.arm("leader.rebalance_reconcile",
                                action="raise", times=1)
            out = leader.rebalancer.migrate(source, names)
            assert out["moved"] == len(names)   # flip already durable
            _assert_complete(_search(leader, "common"), "pre-sweep")
            # the sweep converges the deletes without the trigger
            assert wait_until(
                lambda: not leader.placement.pending_moved().get(source),
                timeout=10.0)
            _assert_complete(_search(leader, "common"), "post-sweep")
        finally:
            _stop_all(nodes)

    def test_leader_failover_mid_copy_aborts_and_reclaims(self, core,
                                                          tmp_path):
        """A copying-phase migration is durable when the leader dies:
        the NEW leader loads the record, aborts it (ownership never
        moved — a half-copied range is never believed owned), and the
        repair/trim pass reclaims the stray confirmed legs."""
        nodes = _mk_cluster(core, tmp_path, n=4, replication_factor=1)
        leader = nodes[0]
        try:
            _upload_docs(leader)
            source = nodes[2].url
            target = nodes[3].url
            names = leader.placement.names_on(source)[:2]
            assert names
            # reproduce the exact mid-copy durable state: record in
            # phase "copying" + confirmed copy legs on the target
            mid = leader.placement.begin_migration(
                source, {n: [target] for n in names})
            docs = [{"name": n, "text": DOCS[n]} for n in names]
            assert leader._add_replica_batch(target, docs) == len(names)
            assert leader.placement.flush()
            raw = json.loads(
                leader.coord.get_data(PLACEMENT_STATE).decode())
            assert mid in raw.get("migrations", {})

            leader.stop()
            new_leader = nodes[1]
            assert wait_until(new_leader.is_leader, timeout=10.0)
            # the record is aborted on resume, and the duplicate legs
            # trimmed back to R=1 — with the SOURCE keeping ownership
            assert wait_until(
                lambda: not new_leader.placement.migration_snapshot(),
                timeout=15.0)
            assert wait_until(
                lambda: all(
                    len(new_leader.placement.holders_of(n)) == 1
                    for n in names), timeout=15.0)

            def settled():
                got = _search(new_leader, "common")
                return set(got) == set(DOCS)
            assert wait_until(settled, timeout=20.0)
        finally:
            _stop_all(nodes)

    def test_leader_failover_post_flip_resumes_reconcile(self, core,
                                                         tmp_path):
        """A durable flip survives a leader change: the moved state
        rides the placement znode (PR 5), so the NEW leader keeps the
        flipped ownership — the range is never re-flipped back to the
        source and nothing is double-counted or lost. The migration
        SOURCE is the next-in-line leader itself, so its promotion (the
        messiest failover: the promoted ex-worker's own shard gets
        re-placed) cannot legitimately disturb the flipped range."""
        nodes = _mk_cluster(core, tmp_path, n=4, replication_factor=1)
        leader = nodes[0]
        try:
            _upload_docs(leader)
            source = nodes[1].url   # == the next leader in line
            names = leader.placement.names_on(source)[:2]
            assert names
            # flip lands durably, but every delete RPC fails: the
            # reconcile tail is still pending when the leader dies
            global_injector.arm("leader.reconcile_rpc", action="raise")
            out = leader.rebalancer.migrate(source, names)
            assert out["moved"] == len(names)
            assert set(leader.placement.pending_moved().get(
                source, ())) >= set(names)
            new_holders = {n: leader.placement.holders_of(n)
                           for n in names}
            leader.stop()

            new_leader = nodes[1]
            assert wait_until(new_leader.is_leader, timeout=10.0)
            global_injector.disarm("leader.reconcile_rpc")
            # resumed from the durable map: flipped ownership intact
            # (never re-flipped back to the source) and the pending
            # reconcile state loaded
            assert wait_until(
                lambda: set(new_leader.placement.pending_moved().get(
                    source, ())) >= set(names), timeout=10.0)
            for n in names:
                assert new_leader.placement.holders_of(n) \
                    == new_holders[n]

            # the promoted ex-worker's own (unmigrated) shard is
            # re-placed by the PR-5 machinery; the full corpus stays
            # searchable with no doubles — the rejoiner's stale copies
            # are excluded through the pending-reconcile state
            def settled():
                got = _search(new_leader, "common")
                return set(got) == set(DOCS) \
                    and got == _search(new_leader, "common")
            assert wait_until(settled, timeout=30.0)
        finally:
            _stop_all(nodes)


# ---------------------------------------------------------------------------
# Observability: gauges + CLI status summary
# ---------------------------------------------------------------------------

class TestObservability:
    def test_metrics_and_cli_status_summary(self, core, tmp_path,
                                            capsys):
        nodes = _mk_cluster(core, tmp_path, n=3, replication_factor=1)
        try:
            leader = nodes[0]
            _upload_docs(leader)
            source = nodes[1].url
            names = leader.placement.names_on(source)[:2]
            out = leader.rebalancer.migrate(source, names)
            assert out["moved"] == len(names)

            snap = json.loads(http_get(leader.url + "/api/metrics"))
            assert snap["rebalance_moved_docs"] >= len(names)
            assert snap["rebalance_active"] == 0
            assert snap["rebalance_draining_workers"] == 0

            from tfidf_tpu.cli import main
            rc = main(["status", "--leader", leader.url])
            assert rc == 0
            st = json.loads(capsys.readouterr().out)
            rb = st["rebalance"]
            assert rb["moved_docs_total"] >= len(names)
            assert rb["active_migrations"] == 0
            assert set(rb) == {"active_migrations", "draining_workers",
                               "moved_docs_total", "failures_total",
                               "drains_started", "drains_completed"}
        finally:
            _stop_all(nodes)


# ---------------------------------------------------------------------------
# Chaos (slow): kill -9 source/target/leader at injected fault points
# ---------------------------------------------------------------------------

@pytest.mark.slow
class TestChaosRebalance:
    @pytest.mark.timeout(420)
    def test_kill9_source_and_target_at_fault_points(self, tmp_path):
        """Real ``kill -9`` of the migration SOURCE at
        ``leader.rebalance_copy`` and of the migration TARGET at
        ``leader.rebalance_flip``, mid-drain, under a concurrent search
        workload asserting EXACT single-node-oracle parity on every
        response. Full-replication construction: R=2 over two initial
        workers, so every owner (and every failover backup) holds the
        full corpus at every step — zero lost docs, zero double-counted
        scores, to the last digit."""
        import os
        import signal
        import socket
        import subprocess
        import sys

        from tfidf_tpu.cluster.coordination import CoordinationClient

        def free_port():
            with socket.socket() as s:
                s.bind(("127.0.0.1", 0))
                return s.getsockname()[1]

        env = os.environ.copy()
        env["TFIDF_JAX_PLATFORM"] = "cpu"
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("XLA_FLAGS", None)
        env.update({
            "TFIDF_REPLICATION_FACTOR": "2",
            "TFIDF_TOP_K": "64",
            "TFIDF_SESSION_TIMEOUT_S": "1.0",
            "TFIDF_HEARTBEAT_INTERVAL_S": "0.2",
            "TFIDF_RECONCILE_SWEEP_INTERVAL_S": "0.5",
            "TFIDF_MIN_DOC_CAPACITY": "64",
            "TFIDF_MIN_NNZ_CAPACITY": "4096",
            "TFIDF_MIN_VOCAB_CAPACITY": "1024",
            "TFIDF_QUERY_BATCH": "8",
            "TFIDF_MAX_QUERY_TERMS": "8",
        })
        coord_port = free_port()
        procs = {}

        def wait_pred(pred, timeout=60.0, interval=0.2):
            deadline = time.monotonic() + timeout
            last = None
            while time.monotonic() < deadline:
                try:
                    if pred():
                        return True
                except Exception as e:
                    last = e
                time.sleep(interval)
            raise AssertionError(f"timed out; last={last!r}")

        def spawn(tag, args):
            p = subprocess.Popen(
                [sys.executable, "-m", "tfidf_tpu", *args],
                env=env, stdout=subprocess.DEVNULL,
                stderr=subprocess.DEVNULL)
            procs[tag] = p
            return p

        def worker_args(i, port):
            return ["serve", "--port", str(port), "--host", "127.0.0.1",
                    "--coordinator-address", f"127.0.0.1:{coord_port}",
                    "--documents-path", str(tmp_path / f"w{i}" / "docs"),
                    "--index-path", str(tmp_path / f"w{i}" / "index")]

        leader = None
        try:
            spawn("coord", ["coordinator", "--listen",
                            f"127.0.0.1:{coord_port}"])
            wait_pred(lambda: socket.create_connection(
                ("127.0.0.1", coord_port), timeout=1.0).close() or True,
                timeout=60.0)

            # IN-PROCESS leader (first in: wins the election) so the
            # fault points can be armed with kill -9 callables and the
            # placement map inspected directly
            cfg = Config(
                documents_path=str(tmp_path / "L" / "docs"),
                index_path=str(tmp_path / "L" / "index"), port=0,
                **{**_CFG, "replication_factor": 2, "top_k": 64,
                   "session_timeout_s": 1.0,
                   "reconcile_sweep_interval_s": 0.5,
                   "rebalance_sweep_ms": 10_000_000.0})

            def factory():
                return CoordinationClient(
                    f"127.0.0.1:{coord_port}",
                    heartbeat_interval_s=0.2)
            leader = SearchNode(cfg, coord_factory=factory).start()
            assert wait_until(leader.is_leader, timeout=30.0)

            ports = [free_port() for _ in range(4)]
            urls = [f"http://127.0.0.1:{p}" for p in ports]
            for i in range(2):
                spawn(f"w{i}", worker_args(i, ports[i]))
            assert wait_until(lambda: len(
                leader.registry.get_all_service_addresses()) == 2,
                timeout=120.0)

            _upload_docs(leader)
            want = _oracle(tmp_path, top_k=64)

            def parity_now():
                for q in QUERIES:
                    got = json.loads(http_post(
                        leader.url + "/leader/start",
                        json.dumps({"query": q}).encode(),
                        timeout=60.0))
                    _assert_parity(got, want[q], ctx=q)
                return True
            wait_pred(parity_now, timeout=120.0, interval=1.0)

            failures = []
            stop_churn = threading.Event()

            def churn():
                while not stop_churn.is_set():
                    for q in QUERIES:
                        try:
                            got = json.loads(http_post(
                                leader.url + "/leader/start",
                                json.dumps({"query": q}).encode(),
                                timeout=60.0))
                            _assert_parity(got, want[q], ctx=q)
                        except AssertionError as e:
                            failures.append(e)
                        except Exception as e:
                            failures.append(
                                AssertionError(f"transport: {e!r}"))
            t = threading.Thread(target=churn, daemon=True)
            t.start()

            # ---- scenario A: kill -9 the SOURCE at rebalance_copy ----
            spawn("w2", worker_args(2, ports[2]))   # drain target
            assert wait_until(lambda: len(
                leader.registry.get_all_service_addresses()) == 3,
                timeout=120.0)
            source_url = urls[0]
            global_injector.arm(
                "leader.rebalance_copy", action="callable", times=1,
                fn=lambda: os.kill(procs["w0"].pid, signal.SIGKILL))
            leader.rebalancer.start_drain(source_url)
            # the dead source falls out; every doc keeps its surviving
            # replica; repair restores R=2 onto the new worker
            assert wait_until(lambda: source_url not in
                              leader.registry
                              .get_all_service_addresses(),
                              timeout=30.0)
            survivors = {urls[1], urls[2]}

            def restored():
                with leader._placement_lock:
                    return all(len(set(ws) & survivors) == 2
                               for ws in leader._placement.values())
            assert wait_until(restored, timeout=60.0)
            global_injector.disarm()

            # ---- scenario B: kill -9 the TARGET at rebalance_flip ----
            spawn("w3", worker_args(3, ports[3]))
            assert wait_until(lambda: len(
                leader.registry.get_all_service_addresses()) == 3,
                timeout=120.0)

            def kill_flip_target():
                # the migration record names the target: kill it at the
                # flip point, the moment before ownership moves
                recs = leader.placement.migration_snapshot()
                for rec in recs.values():
                    for ts in rec["targets"].values():
                        for turl in ts:
                            if turl == urls[3]:
                                os.kill(procs["w3"].pid,
                                        signal.SIGKILL)
                                return
            global_injector.arm("leader.rebalance_flip",
                                action="callable", times=1,
                                fn=kill_flip_target)
            leader.rebalancer.start_drain(urls[1])
            assert wait_until(lambda: urls[3] not in
                              leader.registry
                              .get_all_service_addresses(),
                              timeout=30.0)
            global_injector.disarm()
            leader.rebalancer.cancel_drain(urls[1])

            time.sleep(3.0)
            stop_churn.set()
            t.join(timeout=120)
            assert not failures, failures[:3]
            # steady state: still exact, nothing dark, nothing doubled
            assert parity_now()
        finally:
            global_injector.disarm()
            if leader is not None:
                try:
                    leader.stop()
                except Exception:
                    pass
            for p in procs.values():
                try:
                    p.kill()
                except Exception:
                    pass
            for p in procs.values():
                try:
                    p.wait(timeout=10)
                except Exception:
                    pass

    @pytest.mark.timeout(300)
    def test_leader_hard_killed_mid_migration_resumes(self, core,
                                                      tmp_path):
        """Hard leader death at the flip fault point (coordination
        session expired + HTTP front door closed, never a graceful
        stop), with the mid-copy migration state durable: the NEW
        leader loads the znode, aborts the copying-phase record,
        RESTARTS the drain it inherited, and converges with zero lost
        documents."""
        kw = dict(replication_factor=1)
        nodes = _mk_cluster(core, tmp_path, n=4, **kw)
        leader = nodes[0]
        try:
            _upload_docs(leader)
            _assert_complete(_search(leader, "common"), "pre")
            drain_victim = nodes[2].url

            def hard_kill_leader():
                # force the copying-phase state durable first (the
                # debounced flush may not have fired yet), then die
                leader.placement.flush()
                leader.httpd.shutdown()
                leader.httpd.server_close()
                core.expire_session(leader.coord.sid)
                raise FaultInjected("leader killed at rebalance_flip")
            global_injector.arm("leader.rebalance_flip",
                                action="callable", times=1,
                                fn=hard_kill_leader)
            leader.rebalancer.start_drain(drain_victim)

            new_leader = nodes[1]
            assert wait_until(new_leader.is_leader, timeout=15.0)
            global_injector.disarm()
            # the new leader inherited the draining flag and restarted
            # the drain; the copying-phase record was aborted
            assert wait_until(
                lambda: drain_victim in
                new_leader.placement.draining_snapshot(), timeout=15.0)
            assert wait_until(
                lambda: not new_leader.placement.migration_snapshot()
                or all(r["phase"] != "copying" for r in
                       new_leader.placement.migration_snapshot()
                       .values()), timeout=15.0)
            assert wait_until(
                lambda: not new_leader.placement.names_on(drain_victim)
                and not new_leader.placement.pending_moved().get(
                    drain_victim), timeout=60.0)

            def settled():
                return set(_search(new_leader, "common")) == set(DOCS)
            assert wait_until(settled, timeout=30.0)
        finally:
            _stop_all(nodes)

"""Coordination durability + quorum (ISSUE 2): WAL, snapshots, ensemble.

Acceptance bar (ISSUE 2):

- a single crashed coordinator restarted from ``--data-dir`` recovers
  the full znode tree and sessions (crash-restart differential vs a
  never-crashed oracle core);
- a 3-member ensemble survives the kill of any single member —
  including the leader — with zero lost acknowledged writes, and
  election/registry/watch semantics survive for clients (multi-address
  failover + watch re-arm);
- a write that cannot reach quorum fails LOUDLY (it is never silently
  acknowledged).

The deterministic subset runs in tier-1. The SIGKILL chaos jobs (real
``python -m tfidf_tpu coordinator`` subprocesses killed mid-traffic) are
marked ``slow`` (``make chaos-coord``).
"""

import json
import os
import shutil
import signal
import socket
import subprocess
import sys
import tempfile
import time
import urllib.request

import pytest

from tfidf_tpu.cluster.coordination import (
    CHILDREN_CHANGED, EPHEMERAL, CoordinationClient, CoordinationCore,
    CoordinationServer, CoordinationUnavailable, NoNodeError)
from tfidf_tpu.cluster.wal import DurableStore, decode_frames, encode_frame
from tfidf_tpu.utils.faults import global_injector

from tests.test_cluster import wait_until


def free_ports(n):
    socks = []
    for _ in range(n):
        s = socket.socket()
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind(("127.0.0.1", 0))
        socks.append(s)
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    return ports


# ---------------------------------------------------------------------------
# WAL framing + DurableStore recovery
# ---------------------------------------------------------------------------

class TestWAL:
    def test_frame_roundtrip(self):
        frames = [encode_frame(f"payload-{i}".encode()) for i in range(5)]
        payloads, clean = decode_frames(b"".join(frames))
        assert payloads == [f"payload-{i}".encode() for i in range(5)]
        assert clean == sum(map(len, frames))

    def test_torn_tail_truncated(self):
        blob = encode_frame(b"good") + encode_frame(b"torn")[:-2]
        payloads, clean = decode_frames(blob)
        assert payloads == [b"good"]
        assert clean == len(encode_frame(b"good"))

    def test_corrupt_crc_stops_replay(self):
        good = encode_frame(b"good")
        bad = bytearray(encode_frame(b"evil"))
        bad[-1] ^= 0xFF
        payloads, clean = decode_frames(good + bytes(bad)
                                        + encode_frame(b"after"))
        assert payloads == [b"good"]     # nothing past the corruption
        assert clean == len(good)

    def test_store_append_load_roundtrip(self, tmp_path):
        st = DurableStore(str(tmp_path))
        entries = [{"i": i + 1, "t": 1, "c": {"op": "noop", "n": i}}
                   for i in range(10)]
        st.append(entries[:4])
        st.append(entries[4:])
        st.close()
        meta, snap, got = DurableStore(str(tmp_path)).load()
        assert meta == {"term": 0, "voted_for": None}
        assert snap is None
        assert got == entries

    def test_store_truncates_torn_tail_on_disk(self, tmp_path):
        st = DurableStore(str(tmp_path))
        st.append([{"i": 1, "t": 1, "c": {"op": "noop"}}])
        st.close()
        wal = tmp_path / "wal.log"
        blob = wal.read_bytes()
        wal.write_bytes(blob + encode_frame(b"{}")[:-3])   # torn append
        st2 = DurableStore(str(tmp_path))
        _, _, got = st2.load()
        assert [e["i"] for e in got] == [1]
        st2.close()
        assert wal.read_bytes() == blob    # file physically truncated

    def test_snapshot_compacts_wal(self, tmp_path):
        st = DurableStore(str(tmp_path))
        entries = [{"i": i + 1, "t": 2, "c": {"op": "noop"}}
                   for i in range(6)]
        st.append(entries)
        state = {"next_sid": 7, "tree": {}, "sessions": {}}
        st.save_snapshot(state, 4, 2, entries[4:])
        st.close()
        meta, snap, got = DurableStore(str(tmp_path)).load()
        assert snap["last_index"] == 4 and snap["last_term"] == 2
        assert snap["state"] == state
        assert [e["i"] for e in got] == [5, 6]

    def test_meta_persisted(self, tmp_path):
        st = DurableStore(str(tmp_path))
        st.set_meta(7, "c2")
        st.close()
        meta, _, _ = DurableStore(str(tmp_path)).load()
        assert meta == {"term": 7, "voted_for": "c2"}

    def test_failed_fsync_rewinds_so_index_reuse_is_safe(self, tmp_path):
        """A failed append must leave NO frame behind: the unacked
        entry's index is reused by the next write, and a leftover
        duplicate-index frame would make recovery's index-continuity
        check truncate ACKED history after it."""
        st = DurableStore(str(tmp_path))
        st.append([{"i": 1, "t": 1, "c": {"op": "noop"}}])
        global_injector.arm("wal.fsync", action="raise", times=1)
        with pytest.raises(Exception):
            st.append([{"i": 2, "t": 1, "c": {"op": "noop",
                                              "v": "never-acked"}}])
        global_injector.disarm()
        st.append([{"i": 2, "t": 1, "c": {"op": "noop", "v": "acked"}}])
        st.append([{"i": 3, "t": 1, "c": {"op": "noop"}}])
        st.close()
        _, _, got = DurableStore(str(tmp_path)).load()
        assert [e["i"] for e in got] == [1, 2, 3]
        assert got[1]["c"]["v"] == "acked"

    def test_wal_append_fault_fails_write_loudly(self, tmp_path):
        """An armed wal.append means the write is NOT acknowledged —
        and NOT durable."""
        st = DurableStore(str(tmp_path))
        global_injector.arm("wal.append", action="raise")
        with pytest.raises(Exception):
            st.append([{"i": 1, "t": 1, "c": {"op": "noop"}}])
        global_injector.disarm()
        st.close()
        _, _, got = DurableStore(str(tmp_path)).load()
        assert got == []


# ---------------------------------------------------------------------------
# Durable standalone: crash-restart differential vs oracle
# ---------------------------------------------------------------------------

def _traffic(coord, core_oracle=None):
    """Apply a deterministic op mix through ``coord`` and mirror it on
    the oracle core (same command order -> same state, by the apply-log
    determinism contract)."""
    sid = core_oracle.new_session() if core_oracle is not None else None
    coord.create("/app", b"root")
    coord.create("/app/cfg", b"v1")
    coord.set_data("/app/cfg", b"v2")
    for i in range(8):
        coord.create(f"/app/item{i}", str(i).encode())
    coord.delete("/app/item3")
    coord.create("/eph", b"mine", mode=EPHEMERAL)
    if core_oracle is not None:
        core_oracle.create(sid, "/app", b"root")
        core_oracle.create(sid, "/app/cfg", b"v1")
        core_oracle.set_data(sid, "/app/cfg", b"v2")
        for i in range(8):
            core_oracle.create(sid, f"/app/item{i}", str(i).encode())
        core_oracle.delete(sid, "/app/item3")
        core_oracle.create(sid, "/eph", b"mine", mode=EPHEMERAL)


class TestDurableRestart:
    def test_crash_restart_matches_oracle(self, tmp_path):
        """Hard-kill the durable coordinator mid-traffic and restart it
        from WAL+snapshot: the recovered znode tree, registry of
        ephemerals, and session table must equal a never-crashed oracle
        core that applied the same commands."""
        data = str(tmp_path / "coord")
        port = free_ports(1)[0]
        srv = CoordinationServer(host="127.0.0.1", port=port,
                                 session_timeout_s=30.0, data_dir=data,
                                 snapshot_every=5).start()
        oracle = CoordinationCore(session_timeout_s=30.0)
        try:
            cli = CoordinationClient(srv.address, heartbeat_interval_s=1.0)
            _traffic(cli, oracle)
            srv.kill()    # no graceful flush: recovery is WAL-only
            srv2 = CoordinationServer(host="127.0.0.1", port=port,
                                      session_timeout_s=30.0,
                                      data_dir=data).start()
            try:
                assert wait_until(
                    lambda: srv2.ensemble.is_leader(), timeout=10.0)
                assert srv2.core.state_snapshot() == \
                    oracle.state_snapshot()
                # the surviving client reconnects into its old session:
                # its ephemeral znode is still owned and readable
                assert cli.get_data("/eph") == b"mine"
                assert cli.get_data("/app/cfg") == b"v2"
                assert not cli.exists("/app/item3")
            finally:
                cli.close()
                srv2.close()
        finally:
            oracle.close()

    def test_watch_survives_same_address_restart(self, tmp_path):
        """restore_state wipes the server-side watch table; the client
        must re-arm on its old host:port after the coordinator restarts
        (not only after failing over to a DIFFERENT address) — else
        election/registry watches silently die with the substrate."""
        data = str(tmp_path / "coord")
        port = free_ports(1)[0]
        srv = CoordinationServer(host="127.0.0.1", port=port,
                                 session_timeout_s=30.0,
                                 data_dir=data).start()
        cli = CoordinationClient(srv.address, heartbeat_interval_s=0.5)
        cli2 = CoordinationClient(srv.address, heartbeat_interval_s=0.5)
        cli.create("/w", b"")
        events = []
        cli.get_children("/w", watcher=events.append)
        srv.kill()
        srv2 = CoordinationServer(host="127.0.0.1", port=port,
                                  session_timeout_s=30.0,
                                  data_dir=data).start()
        try:
            assert wait_until(lambda: srv2.ensemble.is_leader(),
                              timeout=10.0)
            cli2.create("/w/x", b"1")    # change lands POST-restart
            assert wait_until(lambda: len(events) >= 1, timeout=15.0)
            assert events[0].type == CHILDREN_CHANGED
            assert events[0].path == "/w"
        finally:
            cli.close()
            cli2.close()
            srv2.close()

    def test_restart_uses_snapshot_plus_tail(self, tmp_path):
        """snapshot_every=5 forces compaction mid-traffic: recovery must
        stitch snapshot state + WAL tail, not just replay a full log."""
        data = str(tmp_path / "coord")
        srv = CoordinationServer(port=0, session_timeout_s=30.0,
                                 data_dir=data, snapshot_every=5).start()
        cli = CoordinationClient(srv.address, heartbeat_interval_s=1.0)
        _traffic(cli)
        before = srv.core.state_snapshot()
        # a snapshot happened (>=14 commands applied at every-5 cadence)
        assert srv.ensemble.base_index > 0
        srv.kill()
        meta, snap, tail = DurableStore(data).load()
        assert snap is not None and snap["last_index"] > 0
        srv2 = CoordinationServer(port=0, session_timeout_s=30.0,
                                  data_dir=data).start()
        try:
            assert wait_until(lambda: srv2.ensemble.is_leader(),
                              timeout=10.0)
            assert srv2.core.state_snapshot() == before
        finally:
            cli.close()
            srv2.close()


# ---------------------------------------------------------------------------
# Replicated ensemble (in-process members; kill = crash simulation)
# ---------------------------------------------------------------------------

@pytest.fixture
def ensemble3(tmp_path):
    ports = free_ports(3)
    peers = {f"c{i}": f"127.0.0.1:{p}" for i, p in enumerate(ports)}
    servers = {}
    for i, p in enumerate(ports):
        servers[f"c{i}"] = CoordinationServer(
            host="127.0.0.1", port=p, session_timeout_s=20.0,
            data_dir=str(tmp_path / f"c{i}"), node_id=f"c{i}",
            peers=dict(peers), election_timeout_s=0.4,
            heartbeat_interval_s=0.1, commit_timeout_s=3.0,
            snapshot_every=64).start()
    yield peers, servers
    for s in servers.values():
        try:
            s.close()
        except Exception:
            pass


def wait_leader(servers, timeout=60.0):
    """Wait for exactly one live member to hold leadership.

    Generous budget: randomized 1-2s elections can split-vote for a
    while when the suite's XLA work starves both CPU cores (observed in
    full-suite runs: 15s was not always enough; in isolation the first
    election usually lands in ~2s)."""
    box = {}

    def one_leader():
        leaders = [nid for nid, s in servers.items()
                   if s.ensemble.is_leader()]
        box["leaders"] = leaders
        return len(leaders) == 1

    assert wait_until(one_leader, timeout=timeout), \
        f"no unique leader: {[s.ensemble.status() for s in servers.values()]}"
    return box["leaders"][0]


class TestEnsemble:
    def test_leader_kill_loses_no_acked_write(self, ensemble3):
        peers, servers = ensemble3
        leader = wait_leader(servers)
        cli = CoordinationClient(",".join(peers.values()),
                                 heartbeat_interval_s=0.5)
        acked = []
        for k in range(12):
            cli.create(f"/k{k}", str(k).encode())
            acked.append(f"/k{k}")
        servers[leader].kill()
        survivors = {n: s for n, s in servers.items() if n != leader}
        wait_leader(survivors)
        # every acknowledged write survives the leader's death
        for p in acked:
            assert cli.exists(p), f"lost acknowledged write {p}"
        assert cli.get_data("/k7") == b"7"
        # the surviving majority keeps accepting writes
        cli.create("/after-failover", b"ok")
        assert cli.get_data("/after-failover") == b"ok"
        # and the client session survived the failover (same sid)
        assert cli._rpc({"op": "heartbeat"}).get("ok") is True
        cli.close()

    def test_follower_kill_is_invisible(self, ensemble3):
        peers, servers = ensemble3
        leader = wait_leader(servers)
        follower = next(n for n in servers if n != leader)
        cli = CoordinationClient(",".join(peers.values()),
                                 heartbeat_interval_s=0.5)
        cli.create("/pre", b"1")
        servers[follower].kill()
        for k in range(8):
            cli.create(f"/f{k}", str(k).encode())
        assert all(cli.exists(f"/f{k}") for k in range(8))
        assert servers[leader].ensemble.is_leader()
        cli.close()

    def test_follower_redirects_writes_to_leader(self, ensemble3):
        peers, servers = ensemble3
        leader = wait_leader(servers)
        follower = next(n for n in servers if n != leader)
        # client configured with ONLY the follower's address: the 421
        # not_leader hint must carry it to the leader transparently
        cli = CoordinationClient(peers[follower],
                                 heartbeat_interval_s=0.5)
        cli.create("/via-follower", b"x")
        assert servers[leader].core.exists(0, "/via-follower")
        cli.close()

    def test_watches_survive_leader_failover(self, ensemble3):
        peers, servers = ensemble3
        leader = wait_leader(servers)
        cli = CoordinationClient(",".join(peers.values()),
                                 heartbeat_interval_s=0.5)
        cli2 = CoordinationClient(",".join(peers.values()),
                                  heartbeat_interval_s=0.5)
        cli.create("/watched", b"")
        events = []
        cli.get_children("/watched", watcher=events.append)
        servers[leader].kill()
        survivors = {n: s for n, s in servers.items() if n != leader}
        wait_leader(survivors)
        cli2.create("/watched/x", b"1")   # change lands on the NEW leader
        assert wait_until(lambda: len(events) >= 1, timeout=15.0)
        assert events[0].type == CHILDREN_CHANGED
        assert events[0].path == "/watched"
        cli.close()
        cli2.close()

    def test_session_expiry_replicated_from_leader_clock(self, tmp_path):
        """Ephemeral cleanup is a LOGGED command from the leader's
        clock: every replica drops the dead session's znodes."""
        ports = free_ports(3)
        peers = {f"c{i}": f"127.0.0.1:{p}" for i, p in enumerate(ports)}
        servers = {}
        for i, p in enumerate(ports):
            servers[f"c{i}"] = CoordinationServer(
                host="127.0.0.1", port=p, session_timeout_s=1.0,
                data_dir=str(tmp_path / f"s{i}"), node_id=f"c{i}",
                peers=dict(peers), election_timeout_s=0.4,
                heartbeat_interval_s=0.1, commit_timeout_s=3.0).start()
        try:
            wait_leader(servers)
            cli = CoordinationClient(",".join(peers.values()),
                                     heartbeat_interval_s=0.2)
            cli.create("/svc", b"")
            cli.create("/svc/me", b"addr", mode=EPHEMERAL)
            cli._closed.set()      # stop heartbeats: simulate a dead node
            assert wait_until(
                lambda: all(not s.core.exists(0, "/svc/me")
                            for s in servers.values()), timeout=15.0)
        finally:
            for s in servers.values():
                s.close()

    def test_no_quorum_write_fails_loudly(self, ensemble3):
        """With replication to BOTH peers failing, the leader must not
        acknowledge — the submit raises instead of lying. Either honest
        failure is acceptable: commit timeout (CoordinationUnavailable)
        or deposition by the cut-off peers' new election
        (NotLeaderError) — what is FORBIDDEN is a silent ack."""
        from tfidf_tpu.cluster.coordination import NotLeaderError
        peers, servers = ensemble3
        leader = wait_leader(servers)
        ens = servers[leader].ensemble
        global_injector.arm("ensemble.replicate_append.*", action="raise")
        try:
            with pytest.raises((CoordinationUnavailable, NotLeaderError)):
                ens.submit({"op": "create", "sid": 0,
                            "path": "/never-acked", "data": "",
                            "mode": "persistent"})
        finally:
            global_injector.disarm()
        # the entry may exist in the leader's log, but it was never
        # acknowledged; after healing, the cluster still works
        cli = CoordinationClient(",".join(peers.values()),
                                 heartbeat_interval_s=0.5)
        cli.create("/healed", b"1")
        assert cli.get_data("/healed") == b"1"
        cli.close()


# ---------------------------------------------------------------------------
# SIGKILL chaos: real coordinator subprocesses killed mid-traffic (slow)
# ---------------------------------------------------------------------------

def _spawn_coordinator(port, data_dir, node_id="", peers="", env=None):
    cmd = [sys.executable, "-m", "tfidf_tpu", "coordinator",
           "--listen", f"127.0.0.1:{port}", "--data-dir", data_dir]
    if node_id:
        cmd += ["--node-id", node_id]
    if peers:
        cmd += ["--peers", peers]
    full_env = dict(os.environ,
                    JAX_PLATFORMS="cpu",
                    TFIDF_SESSION_TIMEOUT_S="30",
                    TFIDF_ENSEMBLE_ELECTION_TIMEOUT_S="0.4",
                    TFIDF_ENSEMBLE_HEARTBEAT_S="0.1",
                    TFIDF_ENSEMBLE_COMMIT_TIMEOUT_S="3.0")
    full_env.update(env or {})
    return subprocess.Popen(cmd, env=full_env,
                            stdout=subprocess.DEVNULL,
                            stderr=subprocess.DEVNULL)


def _wait_http(port, timeout=30.0):
    deadline = time.monotonic() + timeout
    url = f"http://127.0.0.1:{port}/ensemble/status"
    while time.monotonic() < deadline:
        try:
            with urllib.request.urlopen(url, timeout=1.0) as r:
                json.loads(r.read())
            return True
        except Exception:
            time.sleep(0.1)
    return False


def _wait_subprocess_leader(ports, timeout=30.0):
    """Poll /ensemble/status across live members until one is leader."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        for p in ports:
            try:
                url = f"http://127.0.0.1:{p}/ensemble/status"
                with urllib.request.urlopen(url, timeout=1.0) as r:
                    if json.loads(r.read()).get("role") == "leader":
                        return True
            except Exception:
                continue
        time.sleep(0.1)
    return False


@pytest.mark.slow
class TestSigkillChaos:
    def test_sigkill_restart_differential(self, tmp_path):
        """The ISSUE's crash-restart differential, with a REAL SIGKILL:
        kill -9 the coordinator subprocess mid-traffic, restart it on
        the same --data-dir, and assert the recovered tree equals the
        never-crashed oracle core's."""
        port = free_ports(1)[0]
        data = str(tmp_path / "solo")
        proc = _spawn_coordinator(port, data)
        try:
            assert _wait_http(port)
            oracle = CoordinationCore(session_timeout_s=60.0)
            cli = CoordinationClient(f"127.0.0.1:{port}",
                                     heartbeat_interval_s=1.0)
            _traffic(cli, oracle)
            os.kill(proc.pid, signal.SIGKILL)
            proc.wait(timeout=10)
            proc = _spawn_coordinator(port, data)
            assert _wait_http(port)
            # read the whole tree back through the recovered server
            def tree(coord, path):
                kids = sorted(coord.get_children(path))
                base = "" if path == "/" else path
                return {k: (coord.get_data(f"{base}/{k}").hex(),
                            tree(coord, f"{base}/{k}")) for k in kids}
            got = tree(cli, "/")
            oracle_cli_sid = 0
            def otree(path):
                kids = sorted(oracle.get_children(oracle_cli_sid, path))
                base = "" if path == "/" else path
                return {k: (oracle.get_data(oracle_cli_sid,
                                            f"{base}/{k}").hex(),
                            otree(f"{base}/{k}")) for k in kids}
            assert got == otree("/")
            # sessions recovered too: the pre-kill session still owns
            # its ephemeral node
            assert cli.get_data("/eph") == b"mine"
            cli.close()
            oracle.close()
        finally:
            proc.kill()
            proc.wait(timeout=10)

    def test_ensemble_sigkill_rolling_chaos(self, tmp_path):
        """Kill -9 each ensemble member in turn (leader included) while
        a writer keeps appending; every acknowledged write must be
        readable at the end, and restarted members catch back up."""
        ports = free_ports(3)
        peers = ",".join(f"c{i}=127.0.0.1:{p}"
                         for i, p in enumerate(ports))
        dirs = {i: str(tmp_path / f"m{i}") for i in range(3)}
        procs = {}
        for i, p in enumerate(ports):
            procs[i] = _spawn_coordinator(p, dirs[i], node_id=f"c{i}",
                                          peers=peers)
        try:
            for p in ports:
                assert _wait_http(p)
            assert _wait_subprocess_leader(ports)
            connect = ",".join(f"127.0.0.1:{p}" for p in ports)
            cli = CoordinationClient(connect, heartbeat_interval_s=1.0)
            acked = []

            def write_burst(tag, n=10):
                for k in range(n):
                    path = f"/chaos-{tag}-{k}"
                    cli.create(path, tag.encode())
                    acked.append(path)

            write_burst("warmup")
            for round_no in range(3):
                victim = round_no % 3
                os.kill(procs[victim].pid, signal.SIGKILL)
                procs[victim].wait(timeout=10)
                write_burst(f"r{round_no}")       # quorum of 2 serves
                procs[victim] = _spawn_coordinator(
                    ports[victim], dirs[victim], node_id=f"c{victim}",
                    peers=peers)
                assert _wait_http(ports[victim])
                write_burst(f"r{round_no}b")
            for path in acked:
                assert cli.exists(path), f"lost acknowledged {path}"
            cli.close()
        finally:
            for proc in procs.values():
                proc.kill()
                proc.wait(timeout=10)

"""MeshIndex/MeshSearcher — the mesh-sharded serving path (VERDICT r1 #1).

Runs on the 8-virtual-device CPU mesh (conftest). The mesh engine must be
result-equivalent to the single-device engine: global IDF via psum equals
single-shard IDF because stats are globalized across the mesh.
"""

import numpy as np
import pytest

from tfidf_tpu.engine.engine import Engine
from tfidf_tpu.utils.config import Config

TEXTS = {
    "a.txt": "the quick brown fox jumps over the lazy dog",
    "b.txt": "a fast brown fox and a quick red fox",
    "c.txt": "lorem ipsum dolor sit amet",
    "d.txt": "the dog sleeps all day long",
    "e.txt": "red dogs chase brown foxes at dawn",
    "f.txt": "ipsum lorem amet dolor",
    "g.txt": "quick quick quick brown brown dog",
    "h.txt": "foxes and dogs and foxes again",
    "i.txt": "dawn chorus over the lazy meadow",
    "j.txt": "meadow fox naps in the red dawn",
}

QUERIES = ("fox", "brown dog", "lorem ipsum", "red dawn", "meadow")


def make_engine(tmp_path, sub, mode, **kw):
    # these tests cover the COO mesh layout's internals (snapshot.arrays,
    # ShardedArrays lifecycle); the ELL layout has its own suite in
    # test_mesh_ell.py
    kw.setdefault("mesh_layout", "coo")
    cfg = Config(documents_path=str(tmp_path / sub), engine_mode=mode,
                 min_doc_capacity=8, min_nnz_capacity=256,
                 min_vocab_capacity=64, query_batch=4, max_query_terms=8,
                 **kw)
    return Engine(cfg)


def results(engine, queries=QUERIES, k=None, unbounded=False):
    # ties broken by name: doc-id order differs between layouts, so the
    # within-tie order is not part of the equivalence contract
    return [sorted(((h.name, round(h.score, 4)) for h in
                    engine.search(q, k=k, unbounded=unbounded)),
                   key=lambda nv: (-nv[1], nv[0]))
            for q in queries]


class TestEquivalence:
    @pytest.mark.parametrize("model", ["bm25", "tfidf", "tfidf_cosine"])
    def test_mesh_equals_local(self, tmp_path, model):
        mesh = make_engine(tmp_path, "m", "mesh", model=model)
        local = make_engine(tmp_path, "l", "local", model=model)
        for e in (mesh, local):
            for name, text in TEXTS.items():
                e.ingest_text(name, text)
            e.commit()
        assert mesh.index.mesh.devices.size == 8
        assert results(mesh) == results(local)

    def test_unbounded_parity_equals_local(self, tmp_path):
        mesh = make_engine(tmp_path, "mu", "mesh")
        local = make_engine(tmp_path, "lu", "local")
        for e in (mesh, local):
            for name, text in TEXTS.items():
                e.ingest_text(name, text)
            e.commit()
        assert (results(mesh, unbounded=True)
                == results(local, unbounded=True))

    def test_incremental_append_equals_local(self, tmp_path):
        mesh = make_engine(tmp_path, "mi", "mesh")
        local = make_engine(tmp_path, "li", "local")
        items = list(TEXTS.items())
        for name, text in items:
            local.ingest_text(name, text)
        local.commit()
        # mesh: 1 initial build + incremental on-device appends
        for i in range(0, len(items), 3):
            for name, text in items[i:i + 3]:
                mesh.ingest_text(name, text)
            mesh.commit()
        assert mesh.index.appends >= 1, "appends must be on-device"
        assert results(mesh) == results(local)


class TestLifecycle:
    def test_delete_on_mesh(self, tmp_path):
        e = make_engine(tmp_path, "del", "mesh")
        for name, text in TEXTS.items():
            e.ingest_text(name, text)
        e.commit()
        assert e.delete("b.txt")
        assert not e.delete("b.txt")
        e.commit()
        names = [h.name for h in e.search("fox", k=10)]
        assert "b.txt" not in names
        assert "a.txt" in names

    def test_upsert_on_mesh(self, tmp_path):
        e = make_engine(tmp_path, "up", "mesh")
        for name, text in TEXTS.items():
            e.ingest_text(name, text)
        e.commit()
        e.ingest_text("a.txt", "replacement narwhal content")
        e.commit()
        assert [h.name for h in e.search("narwhal")] == ["a.txt"]
        assert "a.txt" not in [h.name for h in e.search("quick")]
        assert e.index.num_live_docs == len(TEXTS)

    def test_snapshot_isolation_across_delete(self, tmp_path):
        e = make_engine(tmp_path, "iso", "mesh")
        for name, text in TEXTS.items():
            e.ingest_text(name, text)
        e.commit()
        snap1 = e.index.snapshot
        live1 = np.asarray(snap1.arrays.live).copy()
        e.delete("a.txt")
        e.commit()
        assert (np.asarray(snap1.arrays.live) == live1).all()
        assert (np.asarray(e.index.snapshot.arrays.live).sum()
                == live1.sum() - 1)

    def test_vocab_growth_reshards(self, tmp_path):
        e = make_engine(tmp_path, "vg", "mesh")
        for name, text in list(TEXTS.items())[:4]:
            e.ingest_text(name, text)
        e.commit()
        cap0 = e.index.snapshot.arrays.vocab_cap
        r0 = e.index.rebuilds
        # flood the vocabulary past its capacity bucket
        for i in range(4):
            e.ingest_text(f"v{i}.txt",
                          " ".join(f"neo{i}_{j}" for j in range(40)))
        e.commit()
        assert e.vocab.capacity() > cap0
        assert e.index.snapshot.arrays.vocab_cap >= e.vocab.capacity()
        assert e.index.rebuilds > r0
        assert [h.name for h in e.search("neo2_7")] == ["v2.txt"]
        # old docs still searchable after the re-shard
        assert "a.txt" in [h.name for h in e.search("fox", k=10)]

    def test_capacity_overflow_reshards(self, tmp_path):
        e = make_engine(tmp_path, "cap", "mesh")
        e.ingest_text("seed.txt", "alpha beta gamma")
        e.commit()
        r0 = e.index.rebuilds
        # far more docs than the initial doc/nnz buckets can append
        for i in range(300):
            e.ingest_text(f"bulk{i:03d}.txt",
                          f"alpha beta token{i % 50} extra{i % 7}")
        e.commit()
        assert e.index.rebuilds > r0
        assert e.index.num_live_docs == 301
        hits = e.search("token33", k=10)
        assert len(hits) == 6   # 300/50 docs contain token33

    def test_tombstones_reclaimed_by_reshard(self, tmp_path):
        e = make_engine(tmp_path, "rec", "mesh")
        for name, text in TEXTS.items():
            e.ingest_text(name, text)
        e.commit()
        e.delete("a.txt")
        e.commit()
        # force a re-shard: tombstone must be gone from host postings
        e.index._rebuild_locked([], e.vocab.capacity())
        assert all(d.live for sd in e.index._shard_docs for d in sd)
        assert e.index.num_live_docs == len(TEXTS) - 1


class TestCheckpoint:
    def test_engine_checkpoint_roundtrip(self, tmp_path):
        from tfidf_tpu.engine.checkpoint import (load_checkpoint,
                                                 save_checkpoint)
        e = make_engine(tmp_path, "ck", "mesh")
        for name, text in TEXTS.items():
            e.ingest_text(name, text)
        e.commit()
        save_checkpoint(e, str(tmp_path / "ckpt"))
        e2 = load_checkpoint(str(tmp_path / "ckpt"), e.config)
        assert results(e) == results(e2)

    def test_sharded_arrays_roundtrip(self, tmp_path):
        from tfidf_tpu.parallel.sharded import (load_sharded_arrays,
                                                save_sharded_arrays)
        e = make_engine(tmp_path, "ark", "mesh")
        for name, text in TEXTS.items():
            e.ingest_text(name, text)
        e.commit()
        arrays = e.index.snapshot.arrays
        path = str(tmp_path / "arrays.npz")
        save_sharded_arrays(arrays, path)
        restored = load_sharded_arrays(path, e.index.mesh)
        for f in ("tf", "term", "doc", "doc_len", "df", "n_live",
                  "nnz_used", "live", "len_sum"):
            assert (np.asarray(getattr(restored, f))
                    == np.asarray(getattr(arrays, f))).all(), f
        # restored arrays serve searches directly
        import dataclasses
        e.index.snapshot = dataclasses.replace(e.index.snapshot,
                                               arrays=restored)
        assert sorted(h.name for h in e.search("lorem")) == ["c.txt",
                                                             "f.txt"]

    def test_mesh_shape_mismatch_rejected(self, tmp_path):
        from tfidf_tpu.parallel.mesh import make_mesh
        from tfidf_tpu.parallel.sharded import (load_sharded_arrays,
                                                save_sharded_arrays)
        e = make_engine(tmp_path, "mm", "mesh")
        e.ingest_text("a.txt", "alpha")
        e.commit()
        path = str(tmp_path / "a.npz")
        save_sharded_arrays(e.index.snapshot.arrays, path)
        import jax
        other = make_mesh((2, 1), devices=jax.devices()[:2])
        with pytest.raises(ValueError, match="rebuild"):
            load_sharded_arrays(path, other)

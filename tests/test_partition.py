"""Partition-tolerant correctness: leadership fencing + network nemesis.

The acceptance story (ISSUE 9): a deposed-but-alive leader — cut from
the coordinator but NOT from the workers, the split-brain the
crash-only chaos suites cannot reach — can no longer land a single
write on any shard: every mutating RPC carries a monotonic leadership
epoch (the election znode's own sequence number), workers durably
remember the highest epoch ever seen and 403-fence anything lower, and
a fenced leader steps down instead of retrying. A network-level
nemesis (``cluster/nemesis.py``) scripts the partitions at the shared
HTTP seams — no monkeypatching — and the healed cluster converges to
exact single-node-oracle parity with zero acked-write loss and zero
stale-epoch writes accepted.

Tier-1 (deterministic): nemesis mechanics, epoch derivation, the
worker fence (incl. restart persistence — a rebooted worker cannot be
captured by a stale leader), the non-retryable/never-worker-fault
classification, stale-write rejection + leader step-down, data-plane
partition heal to exact parity, reply-corruption tolerance, the
gray-failure latency breaker, and jittered reconnect backoff.

Slow (``make chaos-partition``): the jepsen-style schedule — a
concurrent upsert/delete/search workload while the nemesis deposes the
node leader, splits the 3-member coordinator ensemble, one-way
isolates a worker, and flaps the full mesh; heal, converge, verify.
"""

import json
import socket
import threading
import time
import urllib.error
import urllib.request

import pytest

from tfidf_tpu.cluster.coordination import (CoordinationClient,
                                            CoordinationCore,
                                            CoordinationServer,
                                            LocalCoordination)
from tfidf_tpu.cluster.election import LeaderElection
from tfidf_tpu.cluster.fencing import FenceGuard
from tfidf_tpu.cluster.nemesis import (NemesisPartitioned,
                                       NemesisReplyLost, NemesisNet,
                                       endpoint_of, global_nemesis)
from tfidf_tpu.cluster.node import SearchNode, http_post
from tfidf_tpu.cluster.resilience import (ClusterResilience,
                                          CircuitOpenError,
                                          RpcStatusError,
                                          is_fence_rejection,
                                          is_retryable, is_worker_fault)
from tfidf_tpu.engine.engine import Engine
from tfidf_tpu.utils.config import Config
from tfidf_tpu.utils.metrics import global_metrics

from tests.test_cluster import wait_until


@pytest.fixture(autouse=True)
def _heal_nemesis():
    """Every test leaves the (process-global) network healed."""
    yield
    global_nemesis.heal()


@pytest.fixture
def core():
    c = CoordinationCore(session_timeout_s=0.5)
    yield c
    c.close()


def free_ports(n):
    socks = []
    for _ in range(n):
        s = socket.socket()
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind(("127.0.0.1", 0))
        socks.append(s)
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    return ports


DOCS = {f"pt{i}.txt": f"common token{i} word{i % 3} extra{i % 5}"
        for i in range(10)}
QUERIES = ["common", "token3 word0", "word1 extra2", "common token7"]

_CFG = dict(
    top_k=32, min_doc_capacity=64, min_nnz_capacity=1 << 12,
    min_vocab_capacity=1 << 10, query_batch=8, max_query_terms=8,
    rpc_max_attempts=1,            # deterministic: no hidden retries
    breaker_failure_threshold=2, breaker_reset_s=0.4,
    reconcile_sweep_interval_s=0.2, placement_flush_ms=10.0,
    replication_factor=2,
    # scatter mechanics are under test; a leader-side cache hit would
    # answer without any fan-out and mask them
    result_cache_entries=0)


def _node(core, tmp_path, i, **kw):
    cfg_kw = dict(_CFG)
    cfg_kw.update(kw)
    cfg = Config(
        documents_path=str(tmp_path / f"pt{i}" / "documents"),
        index_path=str(tmp_path / f"pt{i}" / "index"),
        port=0, **cfg_kw)
    return SearchNode(cfg, coord=LocalCoordination(core, 0.1)).start()


def _mk_cluster(core, tmp_path, n=3, **kw):
    nodes = [_node(core, tmp_path, i, **kw) for i in range(n)]
    wait_until(lambda: len(
        nodes[0].registry.get_all_service_addresses()) == n - 1)
    return nodes


def _stop_all(nodes):
    for nd in nodes:
        try:
            nd.stop()
        except Exception:
            pass


def _upload_docs(leader_url, docs=DOCS):
    batch = [{"name": n, "text": t} for n, t in docs.items()]
    return json.loads(http_post(leader_url + "/leader/upload-batch",
                                json.dumps(batch).encode()))


def _search(leader_url, q):
    return json.loads(http_post(
        leader_url + "/leader/start", json.dumps({"query": q}).encode()))


def _oracle(tmp_path, docs, queries, **cfg_kw):
    """Single-node oracle over the FULL corpus. With full replication
    (every registered worker holds every doc) per-shard statistics
    equal the oracle's, so distributed merge parity is EXACT."""
    kw = {k: v for k, v in _CFG.items()
          if k in ("top_k", "min_doc_capacity", "min_nnz_capacity",
                   "min_vocab_capacity", "query_batch",
                   "max_query_terms")}
    kw.update(cfg_kw)
    cfg = Config(documents_path=str(tmp_path / "oracle" / "documents"),
                 index_path=str(tmp_path / "oracle" / "index"), **kw)
    eng = Engine(cfg)
    for name, text in docs.items():
        eng.ingest_bytes(name, text.encode(), save_to_disk=False)
    eng.commit()
    out = {}
    for q in queries:
        hits = eng.search(q)
        merged = {h.name: h.score for h in hits}
        out[q] = dict(sorted(merged.items(),
                             key=lambda kv: (-kv[1], kv[0]))
                      [:cfg.top_k])
    return out


def _parity(got: dict, want: dict) -> bool:
    if set(got) != set(want):
        return False
    return all(abs(got[k] - want[k]) < 1e-4 for k in got)


# ---------------------------------------------------------------------------
# NemesisNet mechanics (pure)
# ---------------------------------------------------------------------------

class TestNemesisNet:
    def test_inactive_is_passthrough(self):
        net = NemesisNet()
        net.check_send("a:1", "b:2")
        assert net.filter_reply("a:1", "b:2", b"xyz") == b"xyz"
        assert not net.active()

    def test_endpoint_normalization(self):
        assert endpoint_of("http://127.0.0.1:8085/") == "127.0.0.1:8085"
        assert endpoint_of("127.0.0.1:2181") == "127.0.0.1:2181"
        assert endpoint_of(None) == ""

    def test_symmetric_partition_both_ways(self):
        net = NemesisNet()
        net.partition(["http://h:1"], ["h:2"])
        with pytest.raises(NemesisPartitioned):
            net.check_send("h:1", "h:2")
        with pytest.raises(NemesisPartitioned):
            net.check_send("h:2", "http://h:1")
        net.check_send("h:1", "h:3")          # unrelated link flows
        net.heal()
        net.check_send("h:1", "h:2")

    def test_one_way_drop(self):
        net = NemesisNet()
        net.one_way("h:1", "h:2")
        with pytest.raises(NemesisPartitioned):
            net.check_send("h:1", "h:2")
        net.check_send("h:2", "h:1")          # reverse direction flows

    def test_isolate_keeps_internal_and_self_links(self):
        net = NemesisNet()
        net.isolate(["h:1", "h:2"])
        with pytest.raises(NemesisPartitioned):
            net.check_send("h:1", "h:3")
        with pytest.raises(NemesisPartitioned):
            net.check_send("h:3", "h:2")
        net.check_send("h:1", "h:2")          # within the minority
        net.check_send("h:1", "h:1")          # loopback exempt
        net.check_send("h:3", "h:4")          # majority side untouched

    def test_unknown_origin_matches_only_wildcard_src(self):
        net = NemesisNet()
        net.drop(src=["h:1"], dst=["h:2"])
        net.check_send(None, "h:2")           # unknown src: not h:1
        net.drop(dst=["h:9"])                 # wildcard src
        with pytest.raises(NemesisPartitioned):
            net.check_send(None, "h:9")

    def test_delay_sleeps_and_counts(self):
        slept = []
        net = NemesisNet(sleep=slept.append)
        net.delay(src=["h:1"], dst=["h:2"], delay_s=0.05)
        before = global_metrics.get("nemesis_delays", 0)
        net.check_send("h:1", "h:2")
        assert slept and abs(slept[0] - 0.05) < 1e-9
        assert global_metrics.get("nemesis_delays") == before + 1

    def test_reply_drop_truncate_corrupt(self):
        net = NemesisNet()
        rid = net.drop_reply(dst=["h:2"])
        with pytest.raises(NemesisReplyLost):
            net.filter_reply("h:1", "h:2", b"reply")
        net.remove(rid)
        net.truncate(dst=["h:2"], keep_bytes=3)
        assert net.filter_reply("h:1", "h:2", b"longreply") == b"lon"
        net.heal()
        net.corrupt(dst=["h:2"])
        out = net.filter_reply("h:1", "h:2", b"abcd")
        assert out != b"abcd" and len(out) == 4


# ---------------------------------------------------------------------------
# Leadership epochs + the worker fence
# ---------------------------------------------------------------------------

class TestLeadershipEpoch:
    def test_epoch_is_znode_sequence_and_monotonic(self, core):
        class Cb:
            def on_elected_to_be_leader(self):
                pass

            def on_worker(self):
                pass

        c1 = LocalCoordination(core, 0.1)
        c2 = LocalCoordination(core, 0.1)
        e1 = LeaderElection(c1, Cb())
        e2 = LeaderElection(c2, Cb())
        e1.volunteer_for_leadership()
        e2.volunteer_for_leadership()
        assert e1.epoch() is not None and e2.epoch() is not None
        assert e2.epoch() > e1.epoch()
        # the old leader resigns and re-volunteers: its NEW epoch
        # outranks everything it ever held and everything live
        old = e1.epoch()
        e1.resign()
        assert e1.epoch() is None
        e1.volunteer_for_leadership()
        assert e1.epoch() > e2.epoch() > old
        c1.close()
        c2.close()


class TestFenceGuard:
    def test_accepts_equal_higher_rejects_lower(self, tmp_path):
        g = FenceGuard(str(tmp_path / "f.json"))
        assert g.current() == -1
        assert g.observe(5)
        assert g.observe(5)           # equal epoch: same leader again
        assert g.observe(7)
        assert not g.observe(6)
        assert g.current() == 7

    def test_persists_across_restart(self, tmp_path):
        path = str(tmp_path / "f.json")
        FenceGuard(path).observe(9)
        g2 = FenceGuard(path)         # the rebooted worker
        assert g2.current() == 9
        assert not g2.observe(8)

    def test_unreadable_state_starts_fresh(self, tmp_path):
        path = tmp_path / "f.json"
        path.write_text("not json at all")
        g = FenceGuard(str(path))
        assert g.current() == -1
        assert g.observe(0)


class TestFenceClassification:
    def test_rpc_status_error_fenced(self):
        e = RpcStatusError("http://w", 403, fenced=True)
        assert is_fence_rejection(e)
        assert not is_retryable(e)
        assert not is_worker_fault(e)
        # a PLAIN 403 (no fence marker) is an app rejection, not a fence
        assert not is_fence_rejection(RpcStatusError("http://w", 403))

    def test_http_error_fenced_by_header(self):
        import email.message
        h = email.message.Message()
        h["X-Fence-Rejected"] = "1"
        e = urllib.error.HTTPError("http://w", 403, "fenced", h, None)
        assert is_fence_rejection(e)
        assert not is_retryable(e)
        assert not is_worker_fault(e)

    def test_fence_rejection_never_trips_breaker(self):
        cr = ClusterResilience(Config(rpc_max_attempts=1,
                                      breaker_failure_threshold=1))

        def fenced():
            raise RpcStatusError("http://w", 403, fenced=True)

        for _ in range(3):
            with pytest.raises(RpcStatusError):
                cr.worker_call("http://w", fenced)
        assert cr.board.breaker("http://w").state == "closed"


class TestWorkerFenceEndpoint:
    def _post(self, url, body, epoch=None):
        h = {"X-Leader-Epoch": str(epoch)} if epoch is not None else {}
        return http_post(url, body, headers=h)

    def test_fence_on_mutating_endpoints(self, core, tmp_path):
        node = _node(core, tmp_path, 0)
        try:
            base = node.url
            # the single node elected itself: its own epoch is already
            # observed — a strictly higher client epoch advances it
            self._post(base + "/worker/upload?name=a.txt", b"alpha beta",
                       epoch=50)
            assert node.fence.current() == 50
            # unstamped requests (reference clients) are never fenced
            self._post(base + "/worker/upload?name=b.txt", b"gamma")
            # every mutating endpoint rejects a lower epoch with the
            # distinct fence status + headers
            for url, body in (
                    (base + "/worker/upload?name=c.txt", b"delta"),
                    (base + "/worker/upload-batch",
                     json.dumps([{"name": "d.txt", "text": "x"}]).encode()),
                    (base + "/worker/delete",
                     json.dumps({"names": ["a.txt"]}).encode())):
                with pytest.raises(urllib.error.HTTPError) as ei:
                    self._post(url, body, epoch=49)
                assert ei.value.code == 403
                assert ei.value.headers.get("X-Fence-Rejected") == "1"
                assert ei.value.headers.get("X-Fence-Epoch") == "50"
            assert global_metrics.get("fence_rejections") >= 3
            # the fenced delete did NOT delete: the doc still scores
            hits = json.loads(http_post(base + "/worker/process",
                                        b"alpha"))
            assert any(h["document"]["name"] == "a.txt" for h in hits)
        finally:
            node.stop()

    def test_restart_reloads_epoch_cannot_be_captured(self, core,
                                                      tmp_path):
        """Satellite: a worker that reboots mid-partition reloads its
        highest-seen epoch — a stale leader cannot capture it."""
        node = _node(core, tmp_path, 0)
        base = node.url
        self._post(base + "/worker/upload?name=a.txt", b"alpha",
                   epoch=50)
        node.stop()
        core2 = CoordinationCore(session_timeout_s=0.5)
        try:
            node2 = _node(core2, tmp_path, 0)   # same index_path
            try:
                assert node2.fence.current() == 50
                with pytest.raises(urllib.error.HTTPError) as ei:
                    self._post(node2.url + "/worker/upload?name=z.txt",
                               b"stale", epoch=49)
                assert ei.value.code == 403
            finally:
                node2.stop()
        finally:
            core2.close()


class TestStaleLeaderStepDown:
    @pytest.mark.timeout(60)
    def test_stale_write_rejected_and_leader_steps_down(self, core,
                                                        tmp_path):
        nodes = _mk_cluster(core, tmp_path, n=3)
        try:
            leader = nodes[0]
            assert leader.is_leader()
            epoch = leader.election.epoch()
            workers = leader.registry.get_all_service_addresses()
            assert len(workers) == 2
            # a newer leader exists somewhere: its first mutating RPC
            # advanced every worker's fence past ours (injected via an
            # empty, epoch-stamped delete — a no-op write)
            for w in workers:
                http_post(w + "/worker/delete",
                          json.dumps({"names": []}).encode(),
                          headers={"X-Leader-Epoch": str(epoch + 1)})
            # the stale leader's write is rejected on every leg and is
            # NEVER acked
            with pytest.raises(Exception):
                leader.leader_upload("stale.txt", b"stale write")
            assert global_metrics.get("fence_rejections") >= 2
            assert global_metrics.get("fence_step_downs") >= 1
            # ... and the deposed leader steps down: another node takes
            # over, the ex-leader drops its epoch + placement authority
            assert wait_until(lambda: any(n.is_leader()
                                          for n in nodes[1:]), timeout=15)
            assert wait_until(lambda: not nodes[0].is_leader(),
                              timeout=10)
            assert nodes[0]._leader_epoch is None
            # the successor (higher epoch by construction) writes fine
            new = next(n for n in nodes[1:] if n.is_leader())
            resp = _upload_docs(new.url, {"ok.txt": "accepted write"})
            assert not resp.get("failed")
            assert wait_until(
                lambda: "ok.txt" in _search(new.url, "accepted"),
                timeout=10)
        finally:
            _stop_all(nodes)


# ---------------------------------------------------------------------------
# Split-brain under a REAL control-plane partition (the acceptance case)
# ---------------------------------------------------------------------------

class TestSplitBrainPartition:
    @pytest.mark.timeout(120)
    def test_deposed_leader_fenced_heals_to_parity(self, tmp_path):
        """The leader-minority schedule: the node leader is cut from
        the coordinator (data plane intact — the dangerous half of a
        partition), a new leader is elected and fences the workers
        forward, the deposed leader's write is rejected everywhere and
        it steps down; after heal the cluster converges to exact
        single-node-oracle parity with zero acked-write loss, zero
        stale-epoch writes accepted, and fence_rejections > 0."""
        srv = CoordinationServer(session_timeout_s=0.6).start()
        nodes = []
        try:
            def factory():
                return CoordinationClient(srv.address,
                                          heartbeat_interval_s=0.1,
                                          failover_deadline_s=1.0)

            for i in range(3):
                cfg = Config(
                    documents_path=str(tmp_path / f"sb{i}" / "documents"),
                    index_path=str(tmp_path / f"sb{i}" / "index"),
                    port=0, session_timeout_s=0.6, **_CFG)
                nodes.append(SearchNode(cfg, coord=factory(),
                                        coord_factory=factory).start())
            old = nodes[0]
            assert wait_until(lambda: old.is_leader(), timeout=10)
            assert wait_until(lambda: len(
                old.registry.get_all_service_addresses()) == 2,
                timeout=10)
            acked = dict(DOCS)
            resp = _upload_docs(old.url)
            assert not resp.get("failed")
            # wait for the DURABLE map to cover every acked doc before
            # partitioning: acked-but-unflushed placements are the
            # known debounce-window residual, not what this test pins
            from tfidf_tpu.cluster.placement import PLACEMENT_STATE
            probe = factory()

            def persisted_all():
                try:
                    raw = probe.get_data(PLACEMENT_STATE)
                    reps = json.loads(raw.decode()).get("replicas", {})
                    return set(DOCS) <= set(reps)
                except Exception:
                    return False

            assert wait_until(persisted_all, timeout=10)
            probe.close()

            # --- the partition: old leader <-> coordinator only ---
            global_nemesis.partition([old.url], [srv.address])
            new = None

            def new_leader():
                nonlocal new
                for n in nodes[1:]:
                    try:
                        if n.is_leader():
                            new = n
                            return True
                    except Exception:
                        pass
                return False

            assert wait_until(new_leader, timeout=20)
            # the new leader's first mutating RPC fences the surviving
            # worker forward
            resp = _upload_docs(new.url, {"epoch.txt": "epochal write"})
            assert not resp.get("failed")
            acked["epoch.txt"] = "epochal write"

            # --- the split-brain write through the DEPOSED leader ---
            with pytest.raises(urllib.error.HTTPError):
                http_post(old.url + "/leader/upload?name=stale.txt",
                          b"stalebrain token")
            assert global_metrics.get("fence_rejections") >= 1
            assert global_metrics.get("fence_step_downs") >= 1
            assert wait_until(lambda: old._role == "worker", timeout=10)

            # --- heal; the ex-leader rejoins as a worker ---
            global_nemesis.heal()
            t_heal = time.monotonic()
            assert wait_until(lambda: len(
                new.registry.get_all_service_addresses()) == 2,
                timeout=30)
            resp = _upload_docs(new.url, {"after.txt": "post heal doc"})
            assert not resp.get("failed")
            acked["after.txt"] = "post heal doc"

            queries = QUERIES + ["epochal", "post heal", "stalebrain"]
            want = _oracle(tmp_path, acked, queries)

            def parity():
                try:
                    return all(_parity(_search(new.url, q), want[q])
                               for q in queries)
                except Exception:
                    return False

            assert wait_until(parity, timeout=40, interval=0.25), {
                q: (_search(new.url, q), want[q]) for q in queries}
            recovery_s = time.monotonic() - t_heal
            # zero stale-epoch writes accepted: the split-brain doc is
            # nowhere (its unique token matches nothing)
            assert _search(new.url, "stalebrain") == {}
            print(f"\nhealed-partition recovery to exact parity: "
                  f"{recovery_s:.2f}s")
        finally:
            _stop_all(nodes)
            srv.close()


# ---------------------------------------------------------------------------
# Data-plane partition + reply corruption: heal to exact parity
# ---------------------------------------------------------------------------

class TestPartitionHealParity:
    @pytest.mark.timeout(90)
    def test_data_plane_partition_heals_to_parity(self, core, tmp_path):
        nodes = _mk_cluster(core, tmp_path, n=3)
        try:
            leader = nodes[0]
            resp = _upload_docs(leader.url)
            assert not resp.get("failed")
            want = _oracle(tmp_path, DOCS, QUERIES)
            assert wait_until(lambda: all(
                _parity(_search(leader.url, q), want[q])
                for q in QUERIES), timeout=15)

            workers = leader.registry.get_all_service_addresses()
            global_nemesis.partition([leader.url], workers)
            # partitioned searches fail loudly-but-bounded (degraded,
            # possibly empty) and partitioned uploads are NEVER acked
            with pytest.raises(Exception):
                json.loads(http_post(
                    leader.url + "/leader/upload?name=lost.txt",
                    b"lost write"))
            assert global_metrics.get("nemesis_drops") > 0

            global_nemesis.heal()
            t_heal = time.monotonic()
            assert wait_until(lambda: all(
                _parity(_search(leader.url, q), want[q])
                for q in QUERIES), timeout=20, interval=0.2)
            print(f"\ndata-plane partition heal to parity: "
                  f"{time.monotonic() - t_heal:.2f}s")
            # the never-acked write is nowhere
            assert _search(leader.url, "lost") == {}
        finally:
            _stop_all(nodes)

    @pytest.mark.timeout(90)
    def test_reply_corruption_tolerated_exactly(self, core, tmp_path):
        """Truncated/corrupted replies from one worker fail wire
        validation (ValueError) and fail over to the intact replica —
        results stay EXACT with full replication."""
        nodes = _mk_cluster(core, tmp_path, n=3)
        try:
            leader = nodes[0]
            resp = _upload_docs(leader.url)
            assert not resp.get("failed")
            want = _oracle(tmp_path, DOCS, QUERIES)
            assert wait_until(lambda: all(
                _parity(_search(leader.url, q), want[q])
                for q in QUERIES), timeout=15)
            victim = leader.registry.get_all_service_addresses()[0]
            global_nemesis.truncate(src=[leader.url], dst=[victim],
                                    keep_bytes=6)
            for q in QUERIES:
                assert _parity(_search(leader.url, q), want[q]), q
            assert global_metrics.get("nemesis_corruptions") > 0
            assert global_metrics.get("scatter_failures") > 0
        finally:
            _stop_all(nodes)


# ---------------------------------------------------------------------------
# Gray failures: slow-but-alive workers trip the breaker
# ---------------------------------------------------------------------------

class TestGrayFailure:
    def test_latency_ewma_trips_and_probe_readmits(self):
        cfg = Config(rpc_max_attempts=1, breaker_failure_threshold=5,
                     breaker_reset_s=0.2, breaker_slow_threshold_ms=30,
                     breaker_slow_min_samples=3)
        cr = ClusterResilience(cfg)

        def slow():
            time.sleep(0.04)
            return "ok"

        for _ in range(3):
            assert cr.worker_call("http://w", slow,
                                  track_latency=True) == "ok"
        assert global_metrics.get("breaker_slow_trips") == 1
        with pytest.raises(CircuitOpenError):
            cr.worker_call("http://w", slow, track_latency=True)
        time.sleep(0.25)
        # half-open probe: a FAST call closes the breaker; the EWMA
        # restarted on trip, so the slow era cannot re-condemn it
        assert cr.worker_call("http://w", lambda: "fast",
                              track_latency=True) == "fast"
        assert cr.board.breaker("http://w").state == "closed"
        assert global_metrics.get("breaker_slow_trips") == 1

    def test_disabled_by_default(self):
        cr = ClusterResilience(Config(rpc_max_attempts=1))
        for _ in range(10):
            cr.worker_call("http://w", lambda: time.sleep(0.02),
                           track_latency=True)
        assert global_metrics.get("breaker_slow_trips") == 0

    @pytest.mark.timeout(90)
    def test_nemesis_latency_trips_slow_breaker_results_exact(
            self, core, tmp_path):
        nodes = _mk_cluster(core, tmp_path, n=3,
                            breaker_slow_threshold_ms=40,
                            breaker_slow_min_samples=2,
                            breaker_reset_s=5.0)
        try:
            leader = nodes[0]
            resp = _upload_docs(leader.url)
            assert not resp.get("failed")
            want = _oracle(tmp_path, DOCS, QUERIES)
            assert wait_until(lambda: all(
                _parity(_search(leader.url, q), want[q])
                for q in QUERIES), timeout=15)
            victim = leader.registry.get_all_service_addresses()[0]
            global_nemesis.delay(src=[leader.url], dst=[victim],
                                 delay_s=0.08)
            # a few searches feed the EWMA; the slow worker trips and
            # its ownership slice fails over — results stay exact
            for _ in range(4):
                for q in QUERIES:
                    assert _parity(_search(leader.url, q), want[q]), q
            assert global_metrics.get("breaker_slow_trips") >= 1
            assert global_metrics.get("nemesis_delays") > 0
        finally:
            _stop_all(nodes)


# ---------------------------------------------------------------------------
# Reconnect storms: jittered backoff on the coordination client
# ---------------------------------------------------------------------------

class TestReconnectJitter:
    def test_backoff_delays_jittered_bounded_and_distinct(self):
        srv = CoordinationServer(session_timeout_s=10.0).start()
        try:
            c1 = CoordinationClient(srv.address, heartbeat_interval_s=5.0)
            c2 = CoordinationClient(srv.address, heartbeat_interval_s=5.0)
            try:
                a = [c1._reconnect.backoff_delay(3) for _ in range(10)]
                b = [c2._reconnect.backoff_delay(3) for _ in range(10)]
                # exponential base at attempt 3 = 0.05 * 4 = 0.2, ±25%
                for d in a + b:
                    assert 0.14 <= d <= 0.26
                # jitter: the sequences are not constant and the two
                # clients' phases are decorrelated
                assert len(set(a + b)) > 5
            finally:
                c1.close()
                c2.close()
        finally:
            srv.close()

    @pytest.mark.timeout(60)
    def test_flap_reconnects_spread_not_herd(self):
        """Nemesis flap: N partitioned clients accumulate jittered
        backoff sleeps (no fixed 20 Hz beat), and all recover after
        heal."""
        srv = CoordinationServer(session_timeout_s=30.0).start()
        clients = []
        recorded = {}
        try:
            for i in range(4):
                c = CoordinationClient(srv.address,
                                       heartbeat_interval_s=5.0,
                                       failover_deadline_s=0.6,
                                       origin=f"cl{i}:0")
                sleeps = recorded[i] = []

                def rec(d, _sleeps=sleeps):
                    _sleeps.append(d)
                    time.sleep(min(d, 0.02))   # keep the test fast

                c._reconnect._sleep = rec
                clients.append(c)
            global_nemesis.drop(src=[f"cl{i}:0" for i in range(4)],
                                dst=[srv.address])

            def hammer(c):
                try:
                    c.exists("/flap")
                except Exception:
                    pass

            threads = [threading.Thread(target=hammer, args=(c,))
                       for c in clients]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            global_nemesis.heal()
            for c in clients:
                assert c.exists("/flap") is False   # recovered
            for i in range(4):
                assert recorded[i], f"client {i} never backed off"
            # the union of chosen delays is spread, not one fixed beat
            assert len({round(d, 4) for ds in recorded.values()
                        for d in ds}) >= 5
            assert global_metrics.get("coord_reconnect_backoffs") > 0
        finally:
            for c in clients:
                try:
                    c.close()
                except Exception:
                    pass
            srv.close()


# ---------------------------------------------------------------------------
# Placement residue machinery (ghosts / orphans / blanket deletes)
# ---------------------------------------------------------------------------

class TestResidueMachinery:
    def _mapped(self, pm, name, workers):
        with pm.lock:
            pm.replicas[name] = tuple(workers)
            pm._confirmed[name] = set(workers)

    def test_forget_blanket_schedules_every_live_worker(self):
        from tfidf_tpu.cluster.placement import PlacementMap
        pm = PlacementMap(flush_ms=-1)
        self._mapped(pm, "d1", ["w1", "w2"])
        out = pm.forget(["d1"], also={"w1", "w2", "w3"})
        # confirmed holders AND the ghost-hunting blanket (w3)
        assert set(out) == {"w1", "w2", "w3"}
        assert pm.holders_of("d1") == ()
        assert all("d1" in ns for ns in pm.pending_moved().values())

    def test_reconcile_residue_ghost_and_orphan(self):
        from tfidf_tpu.cluster.placement import PlacementMap
        pm = PlacementMap(flush_ms=-1)
        self._mapped(pm, "mapped.txt", ["w1"])
        ghosts, orphans = pm.reconcile_residue(
            "w2", ["mapped.txt", "orphan.txt"], protected=set())
        # w2's copy of a doc mapped to w1 is a ghost: scheduled away
        assert ghosts == ["mapped.txt"]
        assert "mapped.txt" in pm.pending_moved().get("w2", ())
        # a doc mapped nowhere is adopted as a confirmed replica
        assert orphans == ["orphan.txt"]
        assert pm.holders_of("orphan.txt") == ("w2",)
        # deleted-doc residue on a late-coming worker is a ghost, not
        # an adoption (pending deletion anywhere blocks adoption)
        self._mapped(pm, "del.txt", ["w1"])
        pm.forget(["del.txt"], also={"w1"})
        g2, o2 = pm.reconcile_residue("w2", ["del.txt"],
                                      protected=set())
        assert g2 == ["del.txt"] and not o2

    def test_reconcile_residue_skips_inflight_and_protected(self):
        from tfidf_tpu.cluster.placement import PlacementMap
        pm = PlacementMap(flush_ms=-1)
        with pm.lock:
            pm.route_locked("up.txt", ["w1"], {"w1": 0}, None, 1)
        g, o = pm.reconcile_residue(
            "w2", ["up.txt", "mig.txt"], protected={"mig.txt"})
        assert not g and not o   # in-flight legs + migrations are
        # owned by their own machinery

    def test_add_replica_refuses_deleted_and_stray_is_scheduled(self):
        from tfidf_tpu.cluster.placement import PlacementMap
        pm = PlacementMap(flush_ms=-1)
        assert pm.add_replica("gone.txt", "w1") is False
        pm.note_stray("gone.txt", "w1")
        assert "gone.txt" in pm.pending_moved().get("w1", ())
        self._mapped(pm, "live.txt", ["w1"])
        assert pm.add_replica("live.txt", "w2") is True
        assert pm.holders_of("live.txt") == ("w1", "w2")

    def test_unplaced_of(self):
        from tfidf_tpu.cluster.placement import PlacementMap
        pm = PlacementMap(flush_ms=-1)
        self._mapped(pm, "mapped.txt", ["w1"])
        pm.forget(["mapped.txt"], also={"w1"})   # pending delete
        self._mapped(pm, "held.txt", ["w1"])
        got = pm.unplaced_of(
            ["mapped.txt", "held.txt", "lost.txt", "mig.txt"],
            protected={"mig.txt"})
        assert got == ["lost.txt"]


# ---------------------------------------------------------------------------
# Cluster-wide delete (the workload's delete leg)
# ---------------------------------------------------------------------------

class TestLeaderDelete:
    @pytest.mark.timeout(90)
    def test_delete_removes_everywhere_and_is_durable(self, core,
                                                      tmp_path):
        nodes = _mk_cluster(core, tmp_path, n=3)
        try:
            leader = nodes[0]
            resp = _upload_docs(leader.url)
            assert not resp.get("failed")
            assert wait_until(
                lambda: "pt3.txt" in _search(leader.url, "token3"),
                timeout=15)
            out = json.loads(http_post(
                leader.url + "/leader/delete",
                json.dumps({"names": ["pt3.txt"]}).encode()))
            assert out["forgotten"] == 1
            # gone from results immediately and stays gone
            assert "pt3.txt" not in _search(leader.url, "token3")
            remaining = {n: t for n, t in DOCS.items() if n != "pt3.txt"}
            want = _oracle(tmp_path, remaining, QUERIES)
            assert wait_until(lambda: all(
                _parity(_search(leader.url, q), want[q])
                for q in QUERIES), timeout=20, interval=0.2)
        finally:
            _stop_all(nodes)


# ---------------------------------------------------------------------------
# The jepsen-style chaos schedule (slow; make chaos-partition)
# ---------------------------------------------------------------------------

@pytest.mark.slow
class TestChaosPartition:
    @pytest.mark.timeout(420)
    def test_jepsen_schedule_converges_exactly(self, tmp_path):
        """Concurrent upsert/delete/search workload while the nemesis
        (1) deposes the node leader (control-plane cut), (2) splits
        the 3-member coordinator ensemble, (3) one-way isolates a
        worker, and (4) flaps the full mesh — then heals and asserts
        exact single-node-oracle parity, zero acked-write loss, zero
        stale-epoch writes accepted."""
        ports = free_ports(3)
        peers = {f"c{i}": f"127.0.0.1:{p}" for i, p in enumerate(ports)}
        servers = {}
        for i, p in enumerate(ports):
            servers[f"c{i}"] = CoordinationServer(
                host="127.0.0.1", port=p, session_timeout_s=1.0,
                data_dir=str(tmp_path / f"c{i}"), node_id=f"c{i}",
                peers=dict(peers), election_timeout_s=0.4,
                heartbeat_interval_s=0.1, commit_timeout_s=3.0,
                snapshot_every=128).start()
        connect = ",".join(peers.values())
        nodes = []
        stop_flag = threading.Event()
        lock = threading.Lock()
        acked: dict[str, str] = {}      # name -> text (200-acked state)
        ambiguous: set[str] = set()     # failed ops: either outcome ok
        deleted: set[str] = set()
        try:
            def factory():
                return CoordinationClient(connect,
                                          heartbeat_interval_s=0.2,
                                          failover_deadline_s=2.0)

            for i in range(3):
                cfg = Config(
                    documents_path=str(tmp_path / f"ch{i}" / "documents"),
                    index_path=str(tmp_path / f"ch{i}" / "index"),
                    port=0, session_timeout_s=1.0, **{
                        **_CFG, "replication_factor": 3,
                        "rpc_max_attempts": 2,
                        "residue_sweep_ms": 1000.0})
                nodes.append(SearchNode(cfg, coord=factory(),
                                        coord_factory=factory).start())
            assert wait_until(lambda: nodes[0].is_leader(), timeout=20)
            assert wait_until(lambda: len(
                nodes[0].registry.get_all_service_addresses()) == 2,
                timeout=20)

            def leader_url():
                for n in nodes:
                    if n._role == "leader":
                        return n.url
                return nodes[0].url

            # during schedule 1 a deposed-but-undemoted leader can
            # still ACK writes whose placement never reaches the
            # durable map (the known debounce residual) — the fence
            # stops the post-promotion half; the workload quiesces its
            # WRITES for that window (searches keep running) so the
            # final oracle comparison stays exact
            writes_ok = threading.Event()
            writes_ok.set()

            def workload(wid: int) -> None:
                k = 0
                while not stop_flag.is_set():
                    k += 1
                    name = f"w{wid}_{k}.txt"
                    # bucket tokens keep parity-query match sets well
                    # under top_k: per-worker top-k truncation is only
                    # set-stable when the k-boundary is not tied, so
                    # the oracle comparison must never cut a tie
                    text = (f"shared uniq{wid}x{k} cycle{k % 4} "
                            f"bucket{k % 29}")
                    try:
                        if not writes_ok.is_set():
                            _search(leader_url(), "shared")
                            time.sleep(0.05)
                            continue
                        if k % 7 == 6:
                            # idempotent upsert: re-upload one of THIS
                            # thread's acked docs with its own text
                            # (same oracle state; per-doc op order is
                            # sequential because every doc belongs to
                            # exactly one thread — a cross-thread
                            # delete/re-upload race would make the
                            # linearized outcome unknowable)
                            with lock:
                                done = [(n, t) for n, t in acked.items()
                                        if n not in deleted
                                        and n.startswith(f"w{wid}_")]
                            if done:
                                n0, t0 = done[k % len(done)]
                                json.loads(http_post(
                                    leader_url() + "/leader/upload-batch",
                                    json.dumps([{"name": n0,
                                                 "text": t0}]).encode(),
                                    timeout=10.0))
                        elif k % 5 == 4:
                            with lock:
                                cands = [n for n in acked
                                         if n not in deleted
                                         and n.startswith(f"w{wid}_")]
                            if cands:
                                victim = cands[wid % len(cands)]
                                with lock:
                                    ambiguous.add(victim)
                                json.loads(http_post(
                                    leader_url() + "/leader/delete",
                                    json.dumps(
                                        {"names": [victim]}).encode(),
                                    timeout=10.0))
                                with lock:
                                    deleted.add(victim)
                                    ambiguous.discard(victim)
                        else:
                            with lock:
                                ambiguous.add(name)
                            r = json.loads(http_post(
                                leader_url() + "/leader/upload-batch",
                                json.dumps([{"name": name,
                                             "text": text}]).encode(),
                                timeout=10.0))
                            with lock:
                                if name not in r.get("failed", ()):
                                    acked[name] = text
                                ambiguous.discard(name)
                        _search(leader_url(), "shared")
                    except Exception:
                        pass       # failed op: stays ambiguous
                    time.sleep(0.05)

            threads = [threading.Thread(target=workload, args=(i,),
                                        daemon=True) for i in range(3)]
            for t in threads:
                t.start()
            time.sleep(1.0)

            # ---- schedule 1: depose the node leader (control cut) ----
            writes_ok.clear()          # quiesce workload writes (the
            time.sleep(0.3)            # in-flight ones drain)
            old = next(n for n in nodes if n._role == "leader")
            coord_eps = list(peers.values())
            global_nemesis.partition([old.url], coord_eps)
            new = None

            def promoted():
                nonlocal new
                for n in nodes:
                    if n is not old and n._role == "leader":
                        new = n
                        return True
                return False

            assert wait_until(promoted, timeout=30)
            # fence the workers forward, then drive a write through the
            # DEPOSED leader: it must be rejected, never acked
            _upload_docs(new.url, {"fencer.txt": "shared fencer"})
            with lock:
                acked["fencer.txt"] = "shared fencer"
            with pytest.raises(Exception):
                http_post(old.url + "/leader/upload?name=brain.txt",
                          b"splitbrain token", timeout=30.0)
            assert global_metrics.get("fence_rejections") >= 1
            global_nemesis.heal()
            assert wait_until(lambda: len(
                new.registry.get_all_service_addresses()) == 2,
                timeout=40)
            writes_ok.set()            # schedule 1 over: writes resume

            # ---- schedule 2: split the coordinator ensemble ----
            coord_leader = next(
                (nid for nid, s in servers.items()
                 if s.ensemble.is_leader()), None)
            if coord_leader is not None:
                others = [a for nid, a in peers.items()
                          if nid != coord_leader]
                global_nemesis.partition([peers[coord_leader]], others)
                time.sleep(3.0)     # a new coord leader forms; clients
                global_nemesis.heal()   # fail over through the string
                time.sleep(1.0)

            # ---- schedule 3: one-way isolate a worker ----
            cur = next(n for n in nodes if n._role == "leader")
            ws = cur.registry.get_all_service_addresses()
            if ws:
                global_nemesis.one_way(cur.url, ws[0])
                time.sleep(2.0)
                global_nemesis.heal()

            # ---- schedule 4: flap the full mesh ----
            everything = [n.url for n in nodes] + coord_eps
            for _ in range(3):
                global_nemesis.isolate(everything)
                time.sleep(0.3)
                global_nemesis.heal()
                time.sleep(0.3)

            stop_flag.set()
            for t in threads:
                t.join(timeout=15)

            # ---- converge, then verify ----
            def settled_leader():
                live = [n for n in nodes if n._role == "leader"]
                return live[0] if len(live) == 1 else None

            assert wait_until(
                lambda: settled_leader() is not None, timeout=60)
            fin = settled_leader()
            assert wait_until(lambda: len(
                fin.registry.get_all_service_addresses()) == 2,
                timeout=60)

            with lock:
                must_have = {n: t for n, t in acked.items()
                             if n not in deleted and n not in ambiguous}
                must_not = {n for n in deleted if n not in ambiguous}
                amb = set(ambiguous)

            # per-doc presence via each doc's unique token
            def uniq_token(name):
                if not name.startswith("w"):
                    return "fencer"          # the schedule-1 probe doc
                wid, k = name[1:-4].split("_")
                return f"uniq{wid}x{k}"

            def converged():
                try:
                    url = settled_leader().url
                    for n in must_have:
                        if n not in _search(url, uniq_token(n)):
                            return False
                    for n in must_not:
                        if n in _search(url, uniq_token(n)):
                            return False
                    return True
                except Exception:
                    return False

            def forensics():
                out = {"missing": [n for n in must_have
                                   if n not in _search(
                                       fin.url, uniq_token(n))][:10],
                       "resurrected": {}}
                for n in must_not:
                    if n not in _search(fin.url, uniq_token(n)):
                        continue
                    holders = []
                    for nd in nodes:
                        try:
                            hits = json.loads(http_post(
                                nd.url + "/worker/process",
                                uniq_token(n).encode()))
                            if any(h["document"]["name"] == n
                                   for h in hits):
                                holders.append(nd.url)
                        except Exception:
                            pass
                    out["resurrected"][n] = {
                        "engines": holders,
                        "map": fin.placement.holders_of(n),
                        "pending": {w: (n in ns) for w, ns in
                                    fin.placement.pending_moved()
                                    .items()}}
                return out

            assert wait_until(converged, timeout=120,
                              interval=0.5), forensics()
            # zero acked-write loss pinned above; zero stale writes:
            assert _search(fin.url, "splitbrain") == {}

            # exact oracle parity over the discovered final doc set
            final_docs = dict(must_have)
            for name in amb:
                if name in deleted:
                    continue
                hit = _search(fin.url, uniq_token(name))
                if name in hit:
                    wid, k = name[1:-4].split("_")
                    final_docs[name] = (
                        f"shared uniq{wid}x{k} cycle{int(k) % 4} "
                        f"bucket{int(k) % 29}")
            final_docs["fencer.txt"] = "shared fencer"
            queries = ["bucket1", "bucket7", "bucket3 bucket11",
                       "fencer"]
            want = _oracle(tmp_path, final_docs, queries)

            def parity():
                try:
                    url = settled_leader().url
                    return all(_parity(_search(url, q), want[q])
                               for q in queries)
                except Exception:
                    return False

            def diffs():
                out = {}
                for q in queries:
                    got = _search(fin.url, q)
                    w = want[q]
                    if _parity(got, w):
                        continue
                    out[q] = {
                        "sizes": (len(got), len(w)),
                        "extra": sorted(set(got) - set(w))[:6],
                        "missing": sorted(set(w) - set(got))[:6],
                        "score_mismatch": [
                            (k, got[k], w[k]) for k in got
                            if k in w and abs(got[k] - w[k]) >= 1e-4][:6]}
                return out

            assert wait_until(parity, timeout=120, interval=0.5), diffs()
        finally:
            stop_flag.set()
            _stop_all(nodes)
            for s in servers.values():
                try:
                    s.close()
                except Exception:
                    pass

"""Tier-1 interpret-mode kernel parity (ISSUE 15 satellite).

Drives the SAME ``kernel_parity.py`` case machinery the hardware
harness uses, on CPU-scaled shapes in Pallas interpret mode — so every
tier-1 run exercises BOTH A-build variants (v3 single-row; v4 paired
rows incl. the i16 packed sub-variant and the odd-width tail) against
the XLA reduce-fusion oracle plus the v3==v4 bitwise-identity
contract, and a kernel regression fails CI on a CPU box instead of
waiting for the tunneled TPU.
"""

import os
import sys

import numpy as np
import pytest

_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _root not in sys.path:
    sys.path.insert(0, _root)

from kernel_parity import run_case  # noqa: E402
from tfidf_tpu.ops.ell import (_PACKED_VOCAB_MAX,  # noqa: E402
                               _pallas_eligible, _pl_tiles)

# the hardware matrix's eligibility edges at interpret-mode scale:
# small block floor, rows_cap not a multiple of 512, the U1=1024
# boundary, odd widths (v4 tail row), within-row ragged pads, and
# vocabularies on both sides of the i16 packed-compare bound
T1_CASES = [
    dict(rows_cap=256, width=16, n_rows=200, B=64, n_terms=4,
         u_req=256),
    dict(rows_cap=768, width=32, n_rows=700, B=64, n_terms=4,
         u_req=256),
    dict(rows_cap=512, width=24, n_rows=512, B=128, n_terms=4,
         u_req=1024),                                 # U1=1024 boundary
    dict(rows_cap=512, width=33, n_rows=400, B=64, n_terms=4,
         u_req=256),                                  # odd width tail
    dict(rows_cap=512, width=48, n_rows=400, B=64, n_terms=4,
         u_req=256, ragged=True),                     # within-row pads
    dict(rows_cap=512, width=32, n_rows=400, B=64, n_terms=4,
         u_req=256, vocab=20_000),                    # i16 packed
    dict(rows_cap=512, width=31, n_rows=300, B=64, n_terms=4,
         u_req=256, vocab=30_000, ragged=True),       # packed+odd+ragged
    dict(rows_cap=512, width=32, n_rows=400, B=64, n_terms=4,
         u_req=256, vocab=(1 << 15) + 1),             # just past bound
]


@pytest.mark.parametrize("i", range(len(T1_CASES)))
def test_interpret_parity(i):
    rng = np.random.default_rng(100 + i)
    r = run_case(f"t1-case{i}", rng, **T1_CASES[i])
    assert r["ok"], r
    assert r["cross_variant_bitwise_equal"], r


def test_packed_bound_is_the_documented_one():
    """The packed sub-variant arms exactly at vocab_cap <= 2^15 (the
    i16 range incl. the -1 pad sentinel) — T1_CASES straddles it."""
    assert _PACKED_VOCAB_MAX == 1 << 15
    vocabs = [c.get("vocab", 500_000) for c in T1_CASES]
    assert any(v <= _PACKED_VOCAB_MAX for v in vocabs)
    assert any(v > _PACKED_VOCAB_MAX for v in vocabs)


def test_eligibility_envelope_shared_across_variants():
    """A config flip between A-build variants must never change WHICH
    blocks ride the kernel — only how A is built (the gate contract)."""
    for rows_cap in (128, 256, 768, 4096, 4097):
        for B in (64, 2048, 4096):
            for u_cap in (256, 512, 640):
                assert (_pallas_eligible(rows_cap, B, u_cap, "v3")
                        == _pallas_eligible(rows_cap, B, u_cap, "v4")), \
                    (rows_cap, B, u_cap)
    # an unknown variant fails LOUDLY — returning False would silently
    # route the whole engine to the XLA path on a config typo
    with pytest.raises(ValueError, match="kernel_a_build"):
        _pallas_eligible(512, 64, 256, "v9")


def test_ingest_rejects_duplicate_or_unsorted_ids():
    """The layout contract the v4 pair fold relies on (distinct term
    ids per row) is enforced at the ingest seam: a raw-array caller
    passing duplicate or unsorted ids must fail loudly there, not
    score differently on the kernel vs the XLA path."""
    from tfidf_tpu.engine.index import ShardIndex
    from tfidf_tpu.engine.segments import SegmentedIndex
    from tfidf_tpu.models import BM25Model
    from tfidf_tpu.parallel.mesh import make_mesh
    from tfidf_tpu.parallel.mesh_ell_index import MeshEllIndex

    model = BM25Model()
    mesh = make_mesh()
    indexes = [ShardIndex(model), SegmentedIndex(model),
               MeshEllIndex(model, mesh=mesh)]
    for ix in indexes:
        ix.add_document_arrays(
            "ok", np.asarray([1, 5, 9], np.int32),
            np.asarray([1, 1, 1], np.float32), 3.0)
        for bad in ([5, 5], [9, 1]):
            with pytest.raises(ValueError, match="strictly ascending"):
                ix.add_document_arrays(
                    "bad", np.asarray(bad, np.int32),
                    np.asarray([1.0, 1.0], np.float32), 2.0)


def test_v4_tile_schedule_divides_capacities():
    """The v4 schedule (512 tile cap up to B=1024) must keep the grid
    divisibility invariant for every eligible shape — a non-divisor
    tile would silently drop the trailing tile."""
    for rows_cap in (256, 768, 1024, 4096, 65536):
        for B in (64, 512, 1024, 2048):
            for u_cap in (256, 512, 1024, 4096):
                if not _pallas_eligible(rows_cap, B, u_cap, "v4"):
                    continue
                td, tu = _pl_tiles(rows_cap, B, u_cap, "v4")
                assert rows_cap % td == 0 and u_cap % tu == 0, \
                    (rows_cap, B, u_cap, td, tu)

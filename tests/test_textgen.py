"""Realistic-corpus generator properties (VERDICT r3 #3 tooling).

The generator feeds the realistic-text bench; these tests pin the
contract the bench relies on: payload kinds behave as labeled (binary
must 415 through the real ingest path, latin1 must NOT be valid UTF-8,
html must extract to its body text) and the lexicon is real words.
"""

import numpy as np
import pytest

from tfidf_tpu.ops.analyzer import UnsupportedMediaType, extract_text
from tfidf_tpu.utils.textgen import RealisticCorpus, harvest_lexicon


@pytest.fixture(scope="module")
def lexicon():
    words, counts = harvest_lexicon(max_words=5000)
    return words, counts


def test_lexicon_is_ranked_english(lexicon):
    words, counts = lexicon
    assert len(words) >= 1000
    assert all(w.isalpha() and w.islower() for w in words[:100])
    # frequency-ranked: descending counts
    assert all(counts[i] >= counts[i + 1] for i in range(50))
    # the most common English word shows up near the top of any
    # English-prose harvest
    assert "the" in words[:20]


def test_payload_kinds_honor_their_contract(lexicon):
    rng = np.random.default_rng(0)
    gen = RealisticCorpus(rng, lexicon[0])
    seen = set()
    for _ in range(800):
        payload, kind = gen.make_payload(
            40, html_frac=0.2, latin1_frac=0.2, binary_frac=0.1)
        seen.add(kind)
        if kind == "binary":
            with pytest.raises(UnsupportedMediaType):
                extract_text(payload)
        elif kind == "latin1":
            with pytest.raises(UnicodeDecodeError):
                payload.decode("utf-8")
            text = extract_text(payload)
            assert "caf\xe9" in text
        elif kind == "html":
            assert payload.lstrip().lower().startswith(b"<html")
            text = extract_text(payload)
            # brace-bearing substrings cannot come from lexicon words,
            # so this checks style-content stripping without tripping on
            # "margin" legitimately appearing in a harvested lexicon
            assert "<p>" not in text and "p{margin:0}" not in text
            assert len(text.split()) > 5
        else:
            assert extract_text(payload) == payload.decode("utf-8")
    assert seen == {"plain", "html", "latin1", "binary"}


def test_text_shape(lexicon):
    rng = np.random.default_rng(1)
    gen = RealisticCorpus(rng, lexicon[0])
    text = "\n".join(gen.make_text(80) for _ in range(20))
    assert "." in text and "," in text
    assert any(c.isdigit() for c in text)
    assert "'" in text
    assert any(w[0].isupper() for w in text.split())

"""Streaming segment index: incremental commits, tombstones, compaction.

The segmented engine must return the same results as the rebuild engine
for the same corpus (global stats are computed at query time, so no IDF
staleness), with commit cost O(new docs) — old segments are reused
untouched.
"""

import numpy as np
import pytest

from tfidf_tpu.engine.engine import Engine
from tfidf_tpu.utils.config import Config

TEXTS = {
    "a.txt": "the quick brown fox jumps over the lazy dog",
    "b.txt": "a fast brown fox and a quick red fox",
    "c.txt": "lorem ipsum dolor sit amet",
    "d.txt": "the dog sleeps all day long",
    "e.txt": "red dogs chase brown foxes at dawn",
    "f.txt": "ipsum lorem amet dolor",
}


def make_engine(tmp_path, sub, mode, **kw):
    cfg = Config(documents_path=str(tmp_path / sub), index_mode=mode,
                 min_doc_capacity=8, min_nnz_capacity=256,
                 min_vocab_capacity=64, query_batch=4, max_query_terms=8,
                 **kw)
    return Engine(cfg)


QUERIES = ("fox", "brown dog", "lorem ipsum", "red")


def results(engine, queries=QUERIES):
    return [[(h.name, round(h.score, 5)) for h in engine.search(q)]
            for q in queries]


class TestEquivalence:
    def test_incremental_equals_rebuild(self, tmp_path):
        seg = make_engine(tmp_path, "seg", "segments")
        reb = make_engine(tmp_path, "reb", "rebuild")
        items = list(TEXTS.items())
        # segmented: 3 commits of 2 docs each; rebuild: everything at once
        for i in range(0, len(items), 2):
            for name, text in items[i:i + 2]:
                seg.ingest_text(name, text)
            seg.commit()
        for name, text in items:
            reb.ingest_text(name, text)
        reb.commit()
        assert len(seg.index.snapshot.segments) == 3
        assert results(seg) == results(reb)

    def test_single_commit_equivalence(self, tmp_path):
        seg = make_engine(tmp_path, "seg1", "segments")
        reb = make_engine(tmp_path, "reb1", "rebuild")
        for name, text in TEXTS.items():
            seg.ingest_text(name, text)
            reb.ingest_text(name, text)
        seg.commit()
        reb.commit()
        assert results(seg) == results(reb)


class TestIncrementality:
    def test_old_segments_untouched(self, tmp_path):
        e = make_engine(tmp_path, "inc", "segments")
        for name, text in list(TEXTS.items())[:4]:
            e.ingest_text(name, text)
        e.commit()
        first = e.index.snapshot.segments[0]
        e.ingest_text("g.txt", "entirely new content here")
        e.commit()
        segs = e.index.snapshot.segments
        assert len(segs) == 2
        # commit built only the new segment; the old object is reused
        assert segs[0] is first

    def test_empty_commit_is_noop(self, tmp_path):
        e = make_engine(tmp_path, "noop", "segments")
        e.ingest_text("a.txt", "alpha beta")
        e.commit()
        snap = e.index.snapshot
        e.commit()
        assert e.index.snapshot is snap


class TestMutation:
    def test_upsert_replaces(self, tmp_path):
        e = make_engine(tmp_path, "up", "segments")
        e.ingest_text("a.txt", "original walrus content")
        e.commit()
        e.ingest_text("a.txt", "replacement narwhal content")
        e.commit()
        assert [h.name for h in e.search("narwhal")] == ["a.txt"]
        assert e.search("walrus") == []
        assert e.index.num_live_docs == 1

    def test_delete(self, tmp_path):
        e = make_engine(tmp_path, "del", "segments")
        for name, text in list(TEXTS.items())[:3]:
            e.ingest_text(name, text)
        e.commit()
        assert e.delete("b.txt")
        assert not e.delete("b.txt")
        e.commit()
        hits = e.search("fox")
        assert [h.name for h in hits] == ["a.txt"]
        assert e.index.num_live_docs == 2

    def test_delete_pending_doc(self, tmp_path):
        e = make_engine(tmp_path, "delp", "segments")
        e.ingest_text("x.txt", "pending zebra")
        assert e.delete("x.txt")
        e.commit()
        assert e.search("zebra") == []


class TestCompaction:
    def test_compaction_bounds_segments(self, tmp_path):
        e = make_engine(tmp_path, "comp", "segments", max_segments=2)
        for i, (name, text) in enumerate(TEXTS.items()):
            e.ingest_text(name, text)
            e.commit()   # one segment per doc
        assert len(e.index.snapshot.segments) <= 2
        reb = make_engine(tmp_path, "comp_reb", "rebuild")
        for name, text in TEXTS.items():
            reb.ingest_text(name, text)
        reb.commit()
        assert results(e) == results(reb)

    def test_compaction_reclaims_tombstones(self, tmp_path):
        e = make_engine(tmp_path, "reclaim", "segments", max_segments=1)
        e.ingest_text("a.txt", "alpha beta gamma")
        e.commit()
        e.ingest_text("b.txt", "delta epsilon")
        e.delete("a.txt")
        e.commit()   # > max_segments -> compaction drops the tombstone
        segs = e.index.snapshot.segments
        assert len(segs) == 1
        assert segs[0].names == ["b.txt"]
        assert e.search("alpha") == []
        assert [h.name for h in e.search("delta")] == ["b.txt"]


class TestTieredMerging:
    def test_big_segments_not_rewritten(self, tmp_path):
        """Tiered policy: over-cap merging takes the SMALLEST segments;
        an established big segment object survives untouched."""
        e = make_engine(tmp_path, "tier", "segments", max_segments=2)
        for i in range(40):                      # one big segment
            e.ingest_text(f"big{i}.txt", f"common word{i} filler text")
        e.commit()
        big = e.index.snapshot.segments[0]
        for j in range(3):                       # small commits -> merges
            e.ingest_text(f"small{j}.txt", f"tiny doc number{j}")
            e.commit()
        segs = e.index.snapshot.segments
        assert len(segs) <= 2
        assert any(s is big for s in segs), \
            "the big segment must not be rewritten by small merges"
        assert [h.name for h in e.search("number2")] == ["small2.txt"]
        assert e.search("word7")[0].name == "big7.txt"

    def test_background_merge_with_racing_delete(self, tmp_path):
        """A merge above sync_merge_nnz runs off the commit path; a
        delete landing while it builds is re-applied at splice time."""
        e = make_engine(tmp_path, "bg", "segments", max_segments=1,
                        sync_merge_nnz=1)        # force background path
        e.ingest_text("a.txt", "alpha beta gamma")
        e.commit()
        e.ingest_text("b.txt", "delta alpha")
        e.commit()                               # schedules background merge
        idx = e.index
        # racing write while the merge is (or was) in flight
        e.delete("a.txt")
        idx.wait_for_merges(timeout=30)
        e.commit()
        assert len(idx.snapshot.segments) == 1
        assert e.search("alpha") and \
            [h.name for h in e.search("alpha")] == ["b.txt"]
        assert e.search("gamma") == []
        # the index keeps matching a rebuild engine afterwards
        reb = make_engine(tmp_path, "bg_reb", "rebuild")
        reb.ingest_text("b.txt", "delta alpha")
        reb.commit()
        assert results(e) == results(reb)

    def test_background_merge_upsert_away(self, tmp_path):
        """An upsert that moves a doc to pending while its old segment
        merges must not resurrect the old copy."""
        e = make_engine(tmp_path, "bgu", "segments", max_segments=1,
                        sync_merge_nnz=1)
        e.ingest_text("a.txt", "original unique stuff")
        e.commit()
        e.ingest_text("b.txt", "second doc here")
        e.commit()
        e.ingest_text("a.txt", "replacement totally different")
        e.index.wait_for_merges(timeout=30)
        e.commit()
        assert e.search("original") == []
        assert [h.name for h in e.search("replacement")] == ["a.txt"]
        assert [h.name for h in e.search("second")] == ["b.txt"]


class TestCheckpointStreaming:
    def test_checkpoint_roundtrip_segments(self, tmp_path):
        from tfidf_tpu.engine.checkpoint import (load_checkpoint,
                                                 save_checkpoint)
        e = make_engine(tmp_path, "ck", "segments")
        for name, text in TEXTS.items():
            e.ingest_text(name, text)
        e.commit()
        e.delete("c.txt")
        e.commit()
        save_checkpoint(e, str(tmp_path / "ckpt"))
        cfg = e.config
        e2 = load_checkpoint(str(tmp_path / "ckpt"), cfg)
        # the restored index is compacted (tombstoned df dropped), so
        # scores differ slightly from the pre-compaction original —
        # compare result sets/order, not exact scores
        for q in QUERIES:
            assert ([h.name for h in e.search(q)]
                    == [h.name for h in e2.search(q)])


class TestWideDocSpill:
    """Docs with more distinct terms than ell_width_cap must stream via
    the per-segment COO residual (VERDICT r1 #3; Worker.java:190-220
    indexes arbitrarily wide docs)."""

    def _wide_text(self, n_terms: int) -> str:
        # n_terms distinct tokens, some repeated for non-trivial tf
        words = [f"term{i:04d}" for i in range(n_terms)]
        return " ".join(words) + " " + " ".join(words[:7])

    def test_wide_doc_matches_rebuild(self, tmp_path):
        # width cap 16 -> a 1000-distinct-term doc spills heavily
        seg = make_engine(tmp_path, "wseg", "segments", ell_width_cap=16)
        reb = make_engine(tmp_path, "wreb", "rebuild", ell_width_cap=16)
        wide = self._wide_text(1000)
        for e in (seg, reb):
            for name, text in TEXTS.items():
                e.ingest_text(name, text)
            e.ingest_text("wide.txt", wide)
            e.commit()
        qs = QUERIES + ("term0003 term0500 fox",)
        assert results(seg, qs) == results(reb, qs)
        # the wide doc itself must be findable through the residual
        hits = seg.search("term0999")
        assert [h.name for h in hits] == ["wide.txt"]

    def test_failed_or_wide_commit_loses_nothing(self, tmp_path):
        # commit with a wide doc + normal docs: all docs survive, and a
        # subsequent upsert/delete of any of them works (regression for
        # the r1 clear-before-build bug)
        e = make_engine(tmp_path, "wlose", "segments", ell_width_cap=16)
        e.ingest_text("wide.txt", self._wide_text(300))
        e.ingest_text("ok.txt", "plain small document")
        e.commit()
        assert e.index.num_live_docs == 2
        e.ingest_text("ok.txt", "updated small document")
        assert e.delete("wide.txt")
        e.commit()
        assert [h.name for h in e.search("updated")] == ["ok.txt"]
        assert e.search("term0299") == []

    def test_wide_doc_across_compaction(self, tmp_path):
        e = make_engine(tmp_path, "wcomp", "segments", ell_width_cap=16,
                        max_segments=1)
        e.ingest_text("wide.txt", self._wide_text(200))
        e.commit()
        e.ingest_text("x.txt", "xylophone")
        e.commit()   # compaction re-lays-out the wide doc
        assert [h.name for h in e.search("term0150")] == ["wide.txt"]
        assert [h.name for h in e.search("xylophone")] == ["x.txt"]


class TestCosineStreaming:
    """tfidf_cosine in segments mode: norms recomputed at commit from the
    current global df (VERDICT r1 weak #5)."""

    def test_cosine_matches_rebuild(self, tmp_path):
        seg = make_engine(tmp_path, "cseg", "segments",
                          model="tfidf_cosine")
        reb = make_engine(tmp_path, "creb", "rebuild",
                          model="tfidf_cosine")
        items = list(TEXTS.items())
        for i in range(0, len(items), 2):
            for name, text in items[i:i + 2]:
                seg.ingest_text(name, text)
            seg.commit()   # norms must track df across 3 commits
        for name, text in items:
            reb.ingest_text(name, text)
        reb.commit()
        assert results(seg) == results(reb)

    def test_cosine_norms_refresh_after_growth(self, tmp_path):
        e = make_engine(tmp_path, "cgrow", "segments",
                        model="tfidf_cosine")
        e.ingest_text("a.txt", "shared unique")
        e.commit()
        s1 = {h.name: h.score for h in e.search("shared")}
        # adding docs containing "shared" changes its df -> a.txt's norm
        # and score must change (stale per-segment norms would not)
        for i in range(4):
            e.ingest_text(f"x{i}.txt", "shared filler words here")
        e.commit()
        s2 = {h.name: h.score for h in e.search("shared")}
        assert abs(s1["a.txt"] - s2["a.txt"]) > 1e-6

    def test_cosine_wide_doc_spill(self, tmp_path):
        seg = make_engine(tmp_path, "cwide", "segments",
                          model="tfidf_cosine", ell_width_cap=16)
        reb = make_engine(tmp_path, "cwreb", "rebuild",
                          model="tfidf_cosine", ell_width_cap=16)
        wide = " ".join(f"w{i:03d}" for i in range(100))
        for e in (seg, reb):
            e.ingest_text("wide.txt", wide)
            e.ingest_text("a.txt", "w001 w002 and more")
            e.commit()
        qs = ("w001", "w050 w099")
        assert results(seg, qs) == results(reb, qs)


class TestSnapshotIsolation:
    def test_published_snapshot_ignores_later_deletes(self, tmp_path):
        """ADVICE r1: deletes must not flip the live mask of an
        already-published snapshot (its masks are snapshot-owned)."""
        import numpy as np
        e = make_engine(tmp_path, "iso", "segments")
        for name, text in list(TEXTS.items())[:3]:
            e.ingest_text(name, text)
        e.commit()
        snap1 = e.index.snapshot
        mask1 = np.asarray(snap1.views[0].live_mask).copy()
        e.delete("a.txt")
        e.commit()
        snap2 = e.index.snapshot
        # old snapshot's mask unchanged; new snapshot sees the delete
        assert (np.asarray(snap1.views[0].live_mask) == mask1).all()
        assert (np.asarray(snap2.views[0].live_mask).sum()
                == mask1.sum() - 1)

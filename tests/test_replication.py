"""R-way shard replication with failover scatter reads.

The acceptance story: with ``replication_factor=2``, the death of any
single worker — mid-request, unannounced — loses ZERO documents and
double-counts ZERO scores: every response stays in exact merge parity
with a single-node oracle. The pieces under test:

- R-way upload placement + per-query owner assignment (exactly one
  live, breaker-closed replica scores each document);
- within-request failover: a failed owner's ownership slice re-issued
  to surviving replicas;
- hedged duplicate reads (``scatter_hedge_ms``) deduped by owner epoch;
- the durable placement map (znodes through the coordination
  substrate): a NEW leader resumes exact ownership + pending-reconcile
  state (closing the ADVICE r5 leader-failover double-count window);
- the anti-entropy repair loop (restore R after death, trim after
  rejoin);
- scatter deadline propagation (``X-Deadline-Ms`` -> worker 504,
  non-retryable).

The slow chaos jobs (``make chaos-replica``) add real ``kill -9``
subprocess workers under churn and a full-ensemble coordinator SIGKILL.
"""

import json
import threading
import time
import urllib.error
import urllib.request
from concurrent.futures import ThreadPoolExecutor

import pytest

from tfidf_tpu.cluster.coordination import (CoordinationCore,
                                            LocalCoordination)
from tfidf_tpu.cluster.node import SearchNode, http_get, http_post
from tfidf_tpu.cluster.placement import PLACEMENT_STATE, PlacementMap
from tfidf_tpu.cluster.resilience import (RpcStatusError, hedge_laggards,
                                          is_retryable, is_worker_fault)
from tfidf_tpu.engine.engine import Engine
from tfidf_tpu.utils.config import Config
from tfidf_tpu.utils.faults import global_injector
from tfidf_tpu.utils.metrics import global_metrics

from tests.test_cluster import wait_until


@pytest.fixture
def core():
    c = CoordinationCore(session_timeout_s=0.5)
    yield c
    c.close()


DOCS = {f"rp{i}.txt": f"common token{i} word{i % 3} extra{i % 5}"
        for i in range(12)}
QUERIES = ["common", "token3 word0", "word1 extra2", "common token7"]

_CFG = dict(
    top_k=32, min_doc_capacity=64, min_nnz_capacity=1 << 12,
    min_vocab_capacity=1 << 10, query_batch=8, max_query_terms=8,
    rpc_max_attempts=1,            # deterministic: no hidden retries
    breaker_failure_threshold=2, breaker_reset_s=0.4,
    reconcile_sweep_interval_s=0.2, placement_flush_ms=10.0,
    # this suite asserts SCATTER mechanics (failover RPCs, breaker
    # fires, hedges) on repeated identical queries — a leader-side
    # result-cache hit would (correctly) answer without any fan-out
    # and mask exactly what is under test (the cache has its own
    # suite, tests/test_admission.py)
    result_cache_entries=0)


def _node(core, tmp_path, i, port=0, **kw):
    cfg_kw = dict(_CFG)
    cfg_kw.update(kw)
    cfg = Config(
        documents_path=str(tmp_path / f"rp{i}" / "documents"),
        index_path=str(tmp_path / f"rp{i}" / "index"),
        port=port, **cfg_kw)
    return SearchNode(cfg, coord=LocalCoordination(core, 0.1)).start()


def _mk_cluster(core, tmp_path, n=3, **kw):
    nodes = [_node(core, tmp_path, i, **kw) for i in range(n)]
    wait_until(lambda: len(
        nodes[0].registry.get_all_service_addresses()) == n - 1)
    return nodes


def _stop_all(nodes):
    for nd in nodes:
        try:
            nd.stop()
        except Exception:
            pass


def _upload_docs(leader, docs=DOCS):
    batch = [{"name": n, "text": t} for n, t in docs.items()]
    return json.loads(http_post(leader.url + "/leader/upload-batch",
                                json.dumps(batch).encode()))


def _search(leader, q):
    return json.loads(http_post(
        leader.url + "/leader/start", json.dumps({"query": q}).encode()))


def _oracle(tmp_path, docs=DOCS, queries=QUERIES, **cfg_kw):
    """Single-node oracle: one engine holding the FULL corpus, scored
    with the same knobs the cluster nodes use. With full replication
    (R == worker count) every worker's shard statistics equal the
    oracle's, so distributed merge parity is EXACT."""
    kw = {k: v for k, v in _CFG.items()
          if k in ("top_k", "min_doc_capacity", "min_nnz_capacity",
                   "min_vocab_capacity", "query_batch",
                   "max_query_terms")}
    kw.update(cfg_kw)
    cfg = Config(documents_path=str(tmp_path / "oracle" / "documents"),
                 index_path=str(tmp_path / "oracle" / "index"), **kw)
    eng = Engine(cfg)
    for n, t in docs.items():
        eng.ingest_text(n, t)
    eng.commit()
    out = {}
    for q in queries:
        out[q] = {h.name: float(h.score)
                  for h in eng.search(q, k=cfg.top_k)}
    return out


def _assert_parity(got: dict, want: dict, ctx=""):
    assert set(got) == set(want), \
        f"{ctx}: missing={set(want) - set(got)} extra={set(got) - set(want)}"
    for n, s in want.items():
        assert got[n] == pytest.approx(s, rel=1e-5), (ctx, n, got[n], s)


# ---------------------------------------------------------------------------
# Placement map unit tests
# ---------------------------------------------------------------------------

class TestPlacementMap:
    def test_new_name_claims_r_least_loaded(self):
        pm = PlacementMap(flush_ms=-1)
        workers = ["http://a", "http://b", "http://c"]
        sizes = {"http://a": 30, "http://b": 10, "http://c": 20}
        with pm.lock:
            reps, new = pm.route_locked("d.txt", workers, sizes, None, 2)
        assert new and reps == ("http://b", "http://c")

    def test_held_name_routes_to_live_replicas(self):
        pm = PlacementMap(flush_ms=-1)
        workers = ["http://a", "http://b", "http://c"]
        sizes = dict.fromkeys(workers, 0)
        with pm.lock:
            reps, _ = pm.route_locked("d.txt", workers, sizes, None, 2)
        for w in reps:
            pm.leg_success("d.txt", w)
        # one replica left the registry: upserts go to the live one only
        live = [w for w in workers if w != reps[0]]
        with pm.lock:
            reps2, new = pm.route_locked("d.txt", live, sizes, None, 2)
        assert not new and reps2 == (reps[1],)

    def test_failed_leg_drops_unconfirmed_replica_only(self):
        pm = PlacementMap(flush_ms=-1)
        workers = ["http://a", "http://b"]
        with pm.lock:
            reps, _ = pm.route_locked("d.txt", workers,
                                      {w: 0 for w in workers}, None, 2)
        pm.leg_success("d.txt", reps[0])
        pm.leg_failure("d.txt", reps[1])
        assert pm.holders_of("d.txt") == (reps[0],)
        # a later failed UPSERT leg to the confirmed replica keeps it
        with pm.lock:
            pm.route_locked("d.txt", workers, {w: 0 for w in workers},
                            None, 2)
        pm.leg_failure("d.txt", reps[0])
        assert pm.holders_of("d.txt") == (reps[0],)

    def test_all_legs_failed_drops_phantom(self):
        pm = PlacementMap(flush_ms=-1)
        workers = ["http://a", "http://b"]
        with pm.lock:
            reps, _ = pm.route_locked("d.txt", workers,
                                      {w: 0 for w in workers}, None, 2)
        for w in reps:
            pm.leg_failure("d.txt", w)
        assert pm.holders_of("d.txt") == ()

    def test_owner_assignment_one_owner_prefers_closed_breaker(self):
        pm = PlacementMap(flush_ms=-1)
        pm.replicas.update({
            "x": ("http://a", "http://b"),
            "y": ("http://b", "http://a"),
            "z": ("http://c",),
        })
        live = frozenset({"http://a", "http://b"})
        view = pm.owner_assignment(live, frozenset())
        assert view.owner == {"x": "http://a", "y": "http://b"}
        assert view.dark == ("z",)          # no live replica at all
        assert view.replica_workers == live
        # a's breaker opens: ownership shifts to the closed replica
        pm.gen += 1   # breaker state is part of the cache key; gen too
        view2 = pm.owner_assignment(live, frozenset({"http://a"}))
        assert view2.owner == {"x": "http://b", "y": "http://b"}
        # every breaker open: fall back to the first live replica
        view3 = pm.owner_assignment(
            live, frozenset({"http://a", "http://b"}))
        assert view3.owner["x"] == "http://a"

    def test_owner_assignment_cached_until_gen_changes(self):
        pm = PlacementMap(flush_ms=-1)
        pm.replicas["x"] = ("http://a",)
        live = frozenset({"http://a"})
        v1 = pm.owner_assignment(live, frozenset())
        assert pm.owner_assignment(live, frozenset()) is v1
        pm.gen += 1
        assert pm.owner_assignment(live, frozenset()) is not v1

    def test_drop_worker_partitions_kept_and_lost(self):
        pm = PlacementMap(flush_ms=-1)
        pm.replicas.update({"x": ("http://a", "http://b"),
                            "y": ("http://a",)})
        kept, lost = pm.drop_worker("http://a")
        assert kept == ["x"] and lost == ["y"]
        assert pm.holders_of("x") == ("http://b",)
        assert pm.holders_of("y") == ()
        # the dead worker's surviving copy is pending deletion
        assert pm.moved["http://a"] == {"x"}

    def test_moved_never_contains_live_replica_copy(self):
        pm = PlacementMap(flush_ms=-1)
        pm.replicas["x"] = ("http://b",)
        assert pm.note_moved(["x"], "http://b") == 0
        assert pm.note_moved(["x"], "http://a") == 1
        # re-adding the replica clears its pending delete
        pm.add_replica("x", "http://a")
        assert "http://a" not in pm.moved

    def test_under_replicated_and_trim(self):
        pm = PlacementMap(flush_ms=-1)
        live = {"http://a", "http://b", "http://c"}
        pm.replicas.update({"u": ("http://a",),
                            "v": ("http://a", "http://b", "http://c")})
        pm._confirmed.update({"u": {"http://a"},
                              "v": {"http://a", "http://b", "http://c"}})
        under = pm.under_replicated(live, 2)
        assert under == {"u": ("http://a",)}
        trimmed = pm.trim_plan(live, 2)
        assert trimmed == {"http://c": ["v"]}
        assert pm.holders_of("v") == ("http://a", "http://b")
        assert pm.moved["http://c"] == {"v"}

    def test_persist_roundtrip_merges_on_load(self, core):
        coord = LocalCoordination(core, 0.1)
        try:
            pm = PlacementMap(flush_ms=0.0)
            pm.bind_store(lambda: coord)
            pm.set_persist_enabled(True)
            with pm.lock:
                pm.route_locked("x", ["http://a", "http://b"],
                                {"http://a": 0, "http://b": 0}, None, 2)
            pm.leg_success("x", "http://a")
            pm.leg_success("x", "http://b")
            # an unconfirmed tentative claim must NOT be durable
            with pm.lock:
                pm.route_locked("ghost", ["http://a", "http://b"],
                                {"http://a": 0, "http://b": 0}, None, 1)
            pm.note_moved(["x"], "http://dead")
            assert pm.flush()
            raw = json.loads(coord.get_data(PLACEMENT_STATE).decode())
            assert set(raw["replicas"]) == {"x"}
            assert sorted(raw["replicas"]["x"]) == ["http://a",
                                                    "http://b"]
            assert raw["moved"] == {"http://dead": ["x"]}

            pm2 = PlacementMap(flush_ms=0.0)
            pm2.bind_store(lambda: coord)
            pm2.replicas["y"] = ("http://c",)
            assert pm2.load() == 1
            assert sorted(pm2.holders_of("x")) == ["http://a",
                                                   "http://b"]
            assert pm2.holders_of("y") == ("http://c",)   # memory wins
            assert pm2.moved == {"http://dead": {"x"}}
        finally:
            coord.close()


# ---------------------------------------------------------------------------
# Resilience primitives: hedging + deadline classification
# ---------------------------------------------------------------------------

class TestHedgePrimitive:
    def test_only_laggards_get_hedged(self):
        pool = ThreadPoolExecutor(4)
        try:
            slow_gate = threading.Event()
            fast = pool.submit(lambda: "fast")
            slow = pool.submit(lambda: slow_gate.wait(5.0))
            hedged = []
            lag = hedge_laggards({fast: "f", slow: "s"}, 0.05,
                                 hedged.append)
            assert lag == {"s"} and hedged == ["s"]
            slow_gate.set()
        finally:
            pool.shutdown(wait=True)

    def test_disabled_or_empty_is_noop(self):
        assert hedge_laggards({}, 0.05, lambda t: 1 / 0) == set()
        pool = ThreadPoolExecutor(1)
        try:
            fut = pool.submit(lambda: 1)
            assert hedge_laggards({fut: "x"}, 0.0, lambda t: 1 / 0) \
                == set()
        finally:
            pool.shutdown(wait=True)

    def test_raising_callback_is_contained(self):
        pool = ThreadPoolExecutor(1)
        try:
            gate = threading.Event()
            slow = pool.submit(lambda: gate.wait(5.0))

            def boom(tag):
                raise RuntimeError("hedge dispatch exploded")
            lag = hedge_laggards({slow: "s"}, 0.02, boom)
            assert lag == {"s"}
            assert global_metrics.get("hedge_dispatch_failures") >= 1
            gate.set()
        finally:
            pool.shutdown(wait=True)


class TestDeadlineClassification:
    def test_deadline_504_is_non_retryable_and_not_worker_fault(self):
        gw = RpcStatusError("http://w/x", 504)
        dl = RpcStatusError("http://w/x", 504, deadline_exceeded=True)
        assert is_retryable(gw) and not is_retryable(dl)
        assert is_worker_fault(gw) and not is_worker_fault(dl)

    def test_local_deadline_releases_breaker_without_verdict(self):
        """A pre-dispatch DeadlineExpired made NO RPC: it must neither
        close a half-open breaker (no evidence the worker recovered)
        nor count as a failure — and it must free the probe slot."""
        from tfidf_tpu.cluster.resilience import (ClusterResilience,
                                                  DeadlineExpired)
        r = ClusterResilience(Config(
            rpc_max_attempts=1, breaker_failure_threshold=1,
            breaker_reset_s=0.0))
        w = "http://w"
        with pytest.raises(ZeroDivisionError):
            r.worker_call(w, lambda: 1 / 0)        # trips the breaker
        b = r.board.breaker(w)
        assert b.state == "half_open"              # reset_s=0

        def dead():
            raise DeadlineExpired("budget spent before dispatch")
        with pytest.raises(DeadlineExpired):
            r.worker_call(w, dead)                 # consumes the probe
        # NOT closed (would flood a sick worker), NOT re-opened, and
        # the probe slot is free again for a real attempt
        assert b.state == "half_open"
        assert not b.is_open()
        assert r.worker_call(w, lambda: "ok") == "ok"
        assert b.state == "closed"

    def test_worker_refuses_past_deadline_batch(self, core, tmp_path):
        nodes = _mk_cluster(core, tmp_path, n=2)
        try:
            worker = nodes[1]
            # batched scatter endpoint AND the per-query JSON endpoint
            # both honor the propagated budget
            for path, body in (
                    ("/worker/process-batch",
                     {"queries": ["common"], "k": 5}),
                    ("/worker/process", {"query": "common"})):
                req = urllib.request.Request(
                    worker.url + path,
                    data=json.dumps(body).encode(),
                    headers={"Content-Type": "application/json",
                             "X-Deadline-Ms": "0"})
                with pytest.raises(urllib.error.HTTPError) as ei:
                    urllib.request.urlopen(req, timeout=10)
                assert ei.value.code == 504, path
                assert ei.value.headers.get("X-Deadline-Exceeded") == "1"
            assert global_metrics.get("worker_deadline_refusals") >= 2
            # a generous budget (and no header at all) still scores
            req = urllib.request.Request(
                worker.url + "/worker/process",
                data=json.dumps({"query": "common"}).encode(),
                headers={"Content-Type": "application/json",
                         "X-Deadline-Ms": "5000"})
            with urllib.request.urlopen(req, timeout=10) as r:
                assert r.status == 200
        finally:
            _stop_all(nodes)


# ---------------------------------------------------------------------------
# R-way placement + failover scatter reads (in-process cluster)
# ---------------------------------------------------------------------------

class TestReplicatedPlacement:
    def test_uploads_fan_out_r_ways_and_merge_is_single_count(
            self, core, tmp_path):
        """R=2 over 2 workers = full replication: every worker's shard
        statistics equal the single-node oracle's, so the owner-merged
        scatter must match the oracle EXACTLY — any replica
        double-count would show up as a doubled score."""
        nodes = _mk_cluster(core, tmp_path, n=3)
        try:
            leader = nodes[0]
            resp = _upload_docs(leader)
            assert sorted(resp["placed"].values()) == [12, 12]
            workers = set(leader.registry.get_all_service_addresses())
            with leader._placement_lock:
                for name in DOCS:
                    assert set(leader._placement[name]) == workers
            want = _oracle(tmp_path)
            for q in QUERIES:
                _assert_parity(_search(leader, q), want[q], ctx=q)
            assert global_metrics.get("scatter_degraded") == 0
        finally:
            _stop_all(nodes)

    def test_per_file_upload_replies_with_replicas(self, core, tmp_path):
        nodes = _mk_cluster(core, tmp_path, n=3)
        try:
            leader = nodes[0]
            out = leader.leader_upload("solo.txt", b"unique pelican")
            assert len(out["replicas"]) == 2
            assert out["worker"] == out["replicas"][0]
        finally:
            _stop_all(nodes)


class TestFailoverScatter:
    def _kill_data_plane(self, victim):
        """HTTP down, session alive: the registry still lists the
        worker, so recovery/repair cannot help — only the WITHIN-REQUEST
        failover read keeps results complete. The listening socket
        closes AND every kept-alive connection starts aborting (method
        lookup is dynamic, so live keep-alive handler threads die on
        their next request — an in-process stand-in for kill -9's RST)."""
        victim.httpd.shutdown()
        victim.httpd.server_close()
        cls = victim.httpd.RequestHandlerClass

        def dead(handler):
            raise ConnectionResetError("worker killed (test)")
        cls.do_POST = dead
        cls.do_GET = dead

    def test_worker_death_mid_request_loses_nothing(self, core, tmp_path):
        nodes = _mk_cluster(core, tmp_path, n=3)
        try:
            leader = nodes[0]
            _upload_docs(leader)
            want = _oracle(tmp_path)
            for q in QUERIES:
                _assert_parity(_search(leader, q), want[q], ctx=q)

            self._kill_data_plane(nodes[1])
            # every search — including the ones racing the breaker
            # warm-up — returns the COMPLETE result set in exact parity
            before = global_metrics.get("scatter_failovers")
            for _ in range(4):
                for q in QUERIES:
                    _assert_parity(_search(leader, q), want[q], ctx=q)
            assert global_metrics.get("scatter_failovers") > before
            # failover-covered death is NOT a degraded response
            assert global_metrics.get("scatter_degraded") == 0
            snap = json.loads(http_get(leader.url + "/api/metrics"))
            assert snap["scatter_last_dark"] == 0
        finally:
            _stop_all(nodes)

    def test_breaker_open_owner_fails_over_without_rpc(self, core,
                                                      tmp_path):
        nodes = _mk_cluster(core, tmp_path, n=3)
        try:
            leader = nodes[0]
            _upload_docs(leader)
            want = _oracle(tmp_path)
            victim_url = nodes[1].url
            self._kill_data_plane(nodes[1])
            # trip the victim's breaker (threshold=2)
            for _ in range(3):
                _search(leader, "common")
            assert wait_until(
                lambda: leader.resilience.board.is_open(victim_url),
                timeout=5.0)
            # breaker-open owner: the assignment itself avoids the sick
            # worker — full results with NO failover slice needed
            fo = global_metrics.get("scatter_failovers")
            co = global_metrics.get("scatter_circuit_open")
            for q in QUERIES:
                _assert_parity(_search(leader, q), want[q], ctx=q)
            assert global_metrics.get("scatter_circuit_open") > co
            assert global_metrics.get("scatter_failovers") == fo
            assert global_metrics.get("scatter_degraded") == 0
        finally:
            _stop_all(nodes)

    def test_per_query_path_fails_over_too(self, core, tmp_path):
        """The unbounded/parity configs use the per-query JSON fan-out;
        it shares the same owner-merge + failover spine."""
        nodes = _mk_cluster(core, tmp_path, n=3,
                            scatter_micro_batch=False)
        try:
            leader = nodes[0]
            assert leader.scatter_batcher is None
            _upload_docs(leader)
            want = _oracle(tmp_path)
            self._kill_data_plane(nodes[1])
            for q in QUERIES:
                _assert_parity(_search(leader, q), want[q], ctx=q)
            assert global_metrics.get("scatter_failovers") >= 1
        finally:
            _stop_all(nodes)

    def test_single_copy_death_is_still_degraded(self, core, tmp_path):
        """R=1 keeps the honest pre-replication semantics: a dead
        worker's shard is dark and the response says so."""
        nodes = _mk_cluster(core, tmp_path, n=3, replication_factor=1,
                            shard_recovery=False)
        try:
            leader = nodes[0]
            _upload_docs(leader)
            with leader._placement_lock:
                victim_names = {n for n, ws in leader._placement.items()
                                if nodes[1].url in ws}
            assert victim_names
            self._kill_data_plane(nodes[1])
            res = _search(leader, "common")
            assert set(res) == set(DOCS) - victim_names
            assert global_metrics.get("scatter_degraded") == 1
            snap = json.loads(http_get(leader.url + "/api/metrics"))
            assert snap["scatter_last_dark"] >= len(victim_names)
        finally:
            _stop_all(nodes)


class TestHedgedReads:
    def test_hedge_cuts_laggard_tail_and_dedups(self, core, tmp_path):
        nodes = _mk_cluster(core, tmp_path, n=3, scatter_hedge_ms=40.0)
        try:
            leader = nodes[0]
            _upload_docs(leader)
            want = _oracle(tmp_path)
            # warm every worker's compiled path first: a cold-compile
            # first search is a laggard too, and its hedge would land
            # on the artificially slowed victim
            for q in QUERIES:
                _assert_parity(_search(leader, q), want[q], ctx=q)
            # make one worker a pure LAGGARD (healthy, just slow)
            victim = nodes[1]
            orig_batch = victim.engine.search_batch
            orig_arrays = victim.engine.search_batch_arrays

            def slow_arrays(queries, k=None):
                time.sleep(2.0)
                return orig_arrays(queries, k=k)

            def slow_batch(queries, k=None, unbounded=False):
                time.sleep(2.0)
                return orig_batch(queries, k=k, unbounded=unbounded)

            victim.engine.search_batch_arrays = slow_arrays
            victim.engine.search_batch = slow_batch
            t0 = time.monotonic()
            res = _search(leader, "common")
            elapsed = time.monotonic() - t0
            _assert_parity(res, want["common"], ctx="hedged")
            assert elapsed < 1.5, elapsed   # did not pay the 2s tail
            assert global_metrics.get("scatter_hedge_wins") >= 1
            victim.engine.search_batch_arrays = orig_arrays
            victim.engine.search_batch = orig_batch
            # healthy again: the primary answers, hedges stay idle
            wins = global_metrics.get("scatter_hedge_wins")
            _assert_parity(_search(leader, "common"), want["common"])
            assert global_metrics.get("scatter_hedge_wins") == wins
        finally:
            _stop_all(nodes)


# ---------------------------------------------------------------------------
# Durable placement: leader failover resumes ownership + reconciliation
# ---------------------------------------------------------------------------

class TestLeaderFailoverResume:
    def test_new_leader_resumes_pending_reconcile_no_double_count(
            self, core, tmp_path):
        """The ADVICE r5 residual window: `_moved` used to be
        leader-memory-only, so leader failover mid-reconcile forgot
        that a rejoiner still held moved copies — resurrecting the
        sum-merge double count. Now the pending-reconcile state rides
        the durable placement map: the NEW leader excludes the copies
        immediately and its sweep finishes the deletes."""
        nodes = _mk_cluster(core, tmp_path, n=4, replication_factor=1)
        leader = nodes[0]
        try:
            _upload_docs(leader)
            assert set(_search(leader, "common")) == set(DOCS)
            victim = nodes[1]
            victim_port = victim.port
            victim_url = victim.url
            with leader._placement_lock:
                victim_names = {n for n, ws in leader._placement.items()
                                if victim_url in ws}
            assert victim_names
            # kill the victim; the old leader re-places its shard
            victim.httpd.shutdown()
            victim.httpd.server_close()
            core.expire_session(victim.coord.sid)
            assert wait_until(
                lambda: set(_search(leader, "common")) == set(DOCS)
                and victim_url not in {
                    w for ws in leader._placement.values() for w in ws},
                timeout=10.0)

            # the victim's copies are pending reconcile for its future
            # rejoin; that state must be durable in the znode
            def moved_persisted():
                try:
                    raw = json.loads(
                        leader.coord.get_data(PLACEMENT_STATE).decode())
                except Exception:
                    return False
                return set(raw.get("moved", {}).get(victim_url, ())) \
                    == victim_names
            assert wait_until(moved_persisted, timeout=5.0)

            # OLD leader dies with the reconcile still pending (the
            # victim has not rejoined yet)
            leader.stop()
            new_leader = nodes[2]
            assert wait_until(new_leader.is_leader, timeout=5.0)
            # the new leader RESUMES the pending reconcile state from
            # the durable map — the old in-memory-only design lost it
            assert wait_until(
                lambda: set(new_leader._moved.get(victim_url, ()))
                == victim_names, timeout=5.0), (
                dict(new_leader._moved), victim_url, victim_names)

            # NOW the victim rejoins, with its delete RPC broken: the
            # new leader must keep excluding the stale copies
            global_injector.arm("leader.reconcile_rpc", action="raise")
            revived = _node(core, tmp_path, 1, port=victim_port,
                            replication_factor=1)
            nodes.append(revived)
            assert revived.url == victim_url
            assert wait_until(
                lambda: global_injector.fired.get(
                    "leader.reconcile_rpc", 0) >= 1, timeout=5.0)

            # the promoted ex-worker's own shard is re-placed (download
            # probe covers its local docs dir); wait for completeness +
            # stability, then pin scores while the reconcile is pending
            def stable_full():
                a = _search(new_leader, "common")
                return set(a) == set(DOCS) and \
                    a == _search(new_leader, "common")
            assert wait_until(stable_full, timeout=15.0)
            pending_scores = _search(new_leader, "common")
            # the rejoiner's stale copies are flowing and excluded
            assert wait_until(
                lambda: (_search(new_leader, "common"),
                         global_metrics.get(
                             "scatter_hits_excluded"))[1] > 0,
                timeout=8.0)

            # heal the RPC: the NEW leader's sweep converges the delete
            global_injector.disarm("leader.reconcile_rpc")
            assert wait_until(
                lambda: not new_leader._moved.get(victim_url),
                timeout=8.0)
            deleted = json.loads(http_post(
                revived.url + "/worker/delete",
                json.dumps({"names": sorted(victim_names)}).encode()))
            assert deleted["deleted"] == 0   # sweep already deleted them
            # shard compositions did not change between the pending and
            # converged reads — any double count while pending would
            # break this equality
            final = _search(new_leader, "common")
            assert final.keys() == pending_scores.keys()
            for n in final:
                assert final[n] == pytest.approx(pending_scores[n],
                                                 rel=1e-6)
        finally:
            _stop_all(nodes)


# ---------------------------------------------------------------------------
# Anti-entropy repair: restore R after death, trim after rejoin
# ---------------------------------------------------------------------------

class TestReplicationRepair:
    def test_death_restores_replication_factor(self, core, tmp_path):
        nodes = _mk_cluster(core, tmp_path, n=4)   # 3 workers, R=2
        try:
            leader = nodes[0]
            _upload_docs(leader)
            with leader._placement_lock:
                assert all(len(ws) == 2
                           for ws in leader._placement.values())
            victim = nodes[1]
            victim.httpd.shutdown()
            victim.httpd.server_close()
            core.expire_session(victim.coord.sid)
            survivors = {nodes[2].url, nodes[3].url}

            def restored():
                with leader._placement_lock:
                    return all(
                        len(set(ws) & survivors) == 2
                        for ws in leader._placement.values())
            assert wait_until(restored, timeout=10.0)
            assert global_metrics.get("repair_docs_replicated") >= 1
            assert set(_search(leader, "common")) == set(DOCS)
        finally:
            _stop_all(nodes)

    def test_rejoin_trims_and_reconverges(self, core, tmp_path):
        nodes = _mk_cluster(core, tmp_path, n=4)
        try:
            leader = nodes[0]
            _upload_docs(leader)
            victim = nodes[1]
            victim_port = victim.port
            victim_url = victim.url
            victim.httpd.shutdown()
            victim.httpd.server_close()
            core.expire_session(victim.coord.sid)
            survivors = {nodes[2].url, nodes[3].url}

            def restored():
                with leader._placement_lock:
                    return all(
                        len(set(ws) & survivors) == 2
                        for ws in leader._placement.values())
            assert wait_until(restored, timeout=10.0)

            # rejoin: the revived worker's leftover copies are deleted
            # (reconcile) — replication stays at R=2, never 3
            revived = _node(core, tmp_path, 1, port=victim_port)
            nodes.append(revived)

            def reconciled():
                with leader._placement_lock:
                    if leader._moved.get(victim_url):
                        return False
                    return all(len(ws) == 2
                               for ws in leader._placement.values())
            assert wait_until(reconciled, timeout=10.0)
            assert set(_search(leader, "common")) == set(DOCS)
        finally:
            _stop_all(nodes)


# ---------------------------------------------------------------------------
# Full-ensemble coordinator restart (VERDICT r5 Weak #4 tail)
# ---------------------------------------------------------------------------

class TestEnsembleRestartPlacementIntact:
    @pytest.mark.timeout(180)
    def test_kill_all_three_members_cluster_reforms(self, tmp_path):
        """Hard-kill ALL 3 quorum members at once (in-process crash
        simulation: no graceful expiry, recovery purely from WAL +
        snapshots), restart them on the same data dirs, and assert the
        serving nodes re-form the cluster and the durable placement
        map is intact."""
        from tfidf_tpu.cluster.coordination import (CoordinationClient,
                                                    CoordinationServer)
        from tests.test_coordination_durability import (free_ports,
                                                        wait_leader)

        ports = free_ports(3)
        peers = {f"c{i}": f"127.0.0.1:{p}" for i, p in enumerate(ports)}
        connect = ",".join(peers.values())

        def member(i):
            return CoordinationServer(
                host="127.0.0.1", port=ports[i],
                session_timeout_s=30.0,
                data_dir=str(tmp_path / f"c{i}"), node_id=f"c{i}",
                peers=dict(peers), election_timeout_s=0.4,
                heartbeat_interval_s=0.1, commit_timeout_s=3.0,
                snapshot_every=64).start()

        servers = [member(i) for i in range(3)]
        nodes = []
        try:
            # a client's very first mutating op must not race the
            # ensemble's initial election (mutations are not retried
            # through an ambiguous leadership change — by design)
            wait_leader({f"c{i}": s for i, s in enumerate(servers)})

            def factory():
                return CoordinationClient(connect,
                                          heartbeat_interval_s=0.5,
                                          failover_deadline_s=30.0)
            for i in range(3):
                cfg = Config(
                    documents_path=str(tmp_path / f"en{i}" / "documents"),
                    index_path=str(tmp_path / f"en{i}" / "index"),
                    port=0, **_CFG)
                nodes.append(SearchNode(cfg, coord_factory=factory)
                             .start())
            leader = nodes[0]
            assert wait_until(lambda: len(
                leader.registry.get_all_service_addresses()) == 2,
                timeout=30.0)
            _upload_docs(leader)
            assert set(_search(leader, "common")) == set(DOCS)

            def placement_znode():
                # the namespace znode is created empty first; tolerate
                # the window before the first set_data lands
                try:
                    raw = leader.coord.get_data(PLACEMENT_STATE)
                except Exception:
                    return {}
                return json.loads(raw.decode()) if raw else {}
            assert wait_until(
                lambda: len(placement_znode().get("replicas", {}))
                == len(DOCS), timeout=10.0)
            before = placement_znode()

            # SIGKILL-equivalent on the WHOLE ensemble at once
            for s in servers:
                s.kill()
            servers = [member(i) for i in range(3)]
            wait_leader({f"c{i}": s for i, s in enumerate(servers)})

            # serving nodes re-form: same sessions (restored from the
            # WAL with a liveness grace), same registry, working search
            assert wait_until(lambda: len(
                leader.registry.get_all_service_addresses()) == 2,
                timeout=60.0)
            assert wait_until(
                lambda: set(_search(leader, "common")) == set(DOCS),
                timeout=30.0)
            # ...and the placement map survived the quorum's death
            assert placement_znode()["replicas"] == before["replicas"]
            # a fresh client (a NEW leader's view) reads the same map
            probe = factory()
            try:
                raw = json.loads(
                    probe.get_data(PLACEMENT_STATE).decode())
                assert raw["replicas"] == before["replicas"]
            finally:
                probe.close()
        finally:
            _stop_all(nodes)
            for s in servers:
                try:
                    s.close()
                except Exception:
                    pass


# ---------------------------------------------------------------------------
# Chaos (slow): real kill -9 under churn, exact oracle parity throughout
# ---------------------------------------------------------------------------

@pytest.mark.slow
class TestChaosReplica:
    @pytest.mark.timeout(300)
    def test_kill9_worker_mid_workload_exact_parity(self, tmp_path):
        """The acceptance criterion end to end, with a REAL ``kill -9``:
        under a concurrent search workload and membership churn (kill a
        worker, then revive it), every in-flight and subsequent search
        returns the complete result set in exact merge parity with the
        single-node oracle — zero missing documents, zero
        double-counted scores."""
        import os
        import signal
        import socket
        import subprocess
        import sys

        def free_port():
            with socket.socket() as s:
                s.bind(("127.0.0.1", 0))
                return s.getsockname()[1]

        env = os.environ.copy()
        env["TFIDF_JAX_PLATFORM"] = "cpu"
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("XLA_FLAGS", None)
        env.update({
            "TFIDF_REPLICATION_FACTOR": "2",
            "TFIDF_TOP_K": "64",
            "TFIDF_SESSION_TIMEOUT_S": "1.0",
            "TFIDF_HEARTBEAT_INTERVAL_S": "0.2",
            "TFIDF_RECONCILE_SWEEP_INTERVAL_S": "0.5",
            "TFIDF_MIN_DOC_CAPACITY": "64",
            "TFIDF_MIN_NNZ_CAPACITY": "4096",
            "TFIDF_MIN_VOCAB_CAPACITY": "1024",
            "TFIDF_QUERY_BATCH": "8",
            "TFIDF_MAX_QUERY_TERMS": "8",
        })
        coord_port = free_port()
        procs = {}

        def spawn(tag, args):
            p = subprocess.Popen(
                [sys.executable, "-m", "tfidf_tpu", *args],
                env=env, stdout=subprocess.DEVNULL,
                stderr=subprocess.DEVNULL)
            procs[tag] = p
            return p

        def wait_pred(pred, timeout=60.0, interval=0.2):
            deadline = time.monotonic() + timeout
            last = None
            while time.monotonic() < deadline:
                try:
                    if pred():
                        return True
                except Exception as e:
                    last = e
                time.sleep(interval)
            raise AssertionError(f"timed out; last={last!r}")

        def node_args(i, port):
            return ["serve", "--port", str(port), "--host", "127.0.0.1",
                    "--coordinator-address", f"127.0.0.1:{coord_port}",
                    "--documents-path", str(tmp_path / f"ch{i}" / "docs"),
                    "--index-path", str(tmp_path / f"ch{i}" / "index")]

        try:
            spawn("coord", ["coordinator", "--listen",
                            f"127.0.0.1:{coord_port}"])
            wait_pred(lambda: socket.create_connection(
                ("127.0.0.1", coord_port), timeout=1.0).close() or True)
            ports = [free_port() for _ in range(3)]
            urls = [f"http://127.0.0.1:{p}" for p in ports]
            for i, p in enumerate(ports):
                spawn(f"n{i}", node_args(i, p))
                wait_pred(lambda u=urls[i]: http_get(
                    u + "/api/status", timeout=5.0), timeout=120)
            assert http_get(urls[0] + "/api/status") == b"I am the leader"
            wait_pred(lambda: len(json.loads(http_get(
                urls[0] + "/api/services"))) == 2)

            batch = [{"name": n, "text": t} for n, t in DOCS.items()]
            http_post(urls[0] + "/leader/upload-batch",
                      json.dumps(batch).encode())
            want = _oracle(tmp_path, top_k=64)

            def parity_now():
                for q in QUERIES:
                    got = json.loads(http_post(
                        urls[0] + "/leader/start",
                        json.dumps({"query": q}).encode()))
                    _assert_parity(got, want[q], ctx=q)
                return True
            # warm both workers' compiled paths before churning
            wait_pred(parity_now, timeout=120, interval=1.0)

            failures = []
            stop_churn = threading.Event()

            def churn():
                while not stop_churn.is_set():
                    for q in QUERIES:
                        try:
                            got = json.loads(http_post(
                                urls[0] + "/leader/start",
                                json.dumps({"query": q}).encode(),
                                timeout=60.0))
                            _assert_parity(got, want[q], ctx=q)
                        except AssertionError as e:
                            failures.append(e)
                        except Exception as e:
                            # transport-level failure of the LEADER http
                            # front door is a test-env problem; parity
                            # violations are what this chaos run hunts
                            failures.append(
                                AssertionError(f"transport: {e!r}"))

            t = threading.Thread(target=churn, daemon=True)
            t.start()
            time.sleep(1.0)
            # kill -9 one worker mid-workload
            procs["n1"].send_signal(signal.SIGKILL)
            time.sleep(4.0)
            # revive it (same port, same dirs): rejoin churn — trim +
            # re-replication while the workload keeps running
            spawn("n1b", node_args(1, ports[1]))
            wait_pred(lambda: http_get(urls[1] + "/api/status",
                                       timeout=5.0), timeout=120)
            time.sleep(4.0)
            stop_churn.set()
            t.join(timeout=120)
            assert not failures, failures[:3]
            # and the post-churn steady state is still exact
            assert parity_now()
        finally:
            for p in procs.values():
                try:
                    p.kill()
                except Exception:
                    pass
            for p in procs.values():
                try:
                    p.wait(timeout=10)
                except Exception:
                    pass

    @pytest.mark.timeout(300)
    def test_sigkill_full_ensemble_then_serving_resumes(self, tmp_path):
        """Real-process variant of the ensemble-restart test: SIGKILL
        all 3 coordinator subprocesses, restart them on the same data
        dirs, and assert a serving cluster re-forms with the placement
        map intact."""
        import os
        import signal

        from tfidf_tpu.cluster.coordination import CoordinationClient
        from tests.test_coordination_durability import (_spawn_coordinator,
                                                        _wait_http,
                                                        free_ports)

        ports = free_ports(3)
        peers = ",".join(f"c{i}=127.0.0.1:{p}"
                         for i, p in enumerate(ports))
        connect = ",".join(f"127.0.0.1:{p}" for p in ports)
        procs = [
            _spawn_coordinator(p, str(tmp_path / f"c{i}"),
                               node_id=f"c{i}", peers=peers,
                               env={"TFIDF_SESSION_TIMEOUT_S": "30.0"})
            for i, p in enumerate(ports)]
        nodes = []
        try:
            for p in ports:
                _wait_http(p)

            def factory():
                return CoordinationClient(connect,
                                          heartbeat_interval_s=0.5,
                                          failover_deadline_s=30.0)
            for i in range(3):
                cfg = Config(
                    documents_path=str(
                        tmp_path / f"sg{i}" / "documents"),
                    index_path=str(tmp_path / f"sg{i}" / "index"),
                    port=0, **_CFG)
                nodes.append(SearchNode(cfg, coord_factory=factory)
                             .start())
            leader = nodes[0]
            assert wait_until(lambda: len(
                leader.registry.get_all_service_addresses()) == 2,
                timeout=60.0)
            _upload_docs(leader)
            assert set(_search(leader, "common")) == set(DOCS)

            def znode_state():
                # tolerate the empty just-ensured node before the first
                # set_data lands
                try:
                    raw = leader.coord.get_data(PLACEMENT_STATE)
                except Exception:
                    return {}
                return json.loads(raw.decode()) if raw else {}
            assert wait_until(
                lambda: znode_state().get("replicas", {}).keys()
                >= DOCS.keys(), timeout=10.0)

            for p in procs:
                os.kill(p.pid, signal.SIGKILL)
            for p in procs:
                p.wait(timeout=10)
            procs = [
                _spawn_coordinator(p, str(tmp_path / f"c{i}"),
                                   node_id=f"c{i}", peers=peers,
                                   env={"TFIDF_SESSION_TIMEOUT_S":
                                        "30.0"})
                for i, p in enumerate(ports)]
            for p in ports:
                _wait_http(p)
            assert wait_until(lambda: len(
                leader.registry.get_all_service_addresses()) == 2,
                timeout=60.0)
            assert wait_until(
                lambda: set(_search(leader, "common")) == set(DOCS),
                timeout=60.0)
            assert znode_state()["replicas"].keys() >= DOCS.keys()
        finally:
            _stop_all(nodes)
            for p in procs:
                try:
                    p.kill()
                except Exception:
                    pass

"""Cluster tests: election, registry, and the full multi-node HTTP system.

The multi-node behavior the reference only ever validated manually
(SURVEY.md §4: run several instances + curl) is automated here: a 3-node
in-process cluster with a real HTTP data plane, exercising scatter-gather
search, least-loaded upload placement, download probing, leader failover,
and partial-result tolerance.
"""

import json
import time
import urllib.error
import urllib.request

import pytest

from tfidf_tpu.cluster.coordination import CoordinationCore, LocalCoordination
from tfidf_tpu.cluster.election import LeaderElection
from tfidf_tpu.cluster.node import SearchNode, http_get, http_post
from tfidf_tpu.cluster.registry import (ServiceRegistry, read_leader_info)
from tfidf_tpu.utils.config import Config
from tfidf_tpu.utils.faults import global_injector


def wait_until(pred, timeout=5.0, interval=0.01):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return pred()


@pytest.fixture
def core():
    c = CoordinationCore(session_timeout_s=0.5)
    yield c
    c.close()


class Recorder:
    """OnElectionCallback that records role transitions."""

    def __init__(self):
        self.roles = []

    def on_elected_to_be_leader(self):
        self.roles.append("leader")

    def on_worker(self):
        self.roles.append("worker")


class TestElection:
    def test_smallest_wins_and_failover(self, core):
        clients = [LocalCoordination(core, 0.1) for _ in range(3)]
        recs = [Recorder() for _ in range(3)]
        elections = []
        try:
            for c, r in zip(clients, recs):
                e = LeaderElection(c, r)
                e.volunteer_for_leadership()
                e.reelect_leader()
                elections.append(e)
            assert elections[0].is_leader()
            assert not elections[1].is_leader()
            assert recs[0].roles == ["leader"]
            assert recs[1].roles == ["worker"]

            # leader dies → successor (smallest remaining) is promoted
            core.expire_session(clients[0].sid)
            assert wait_until(lambda: recs[1].roles[-1] == "leader")
            assert elections[1].is_leader()
            assert recs[2].roles == ["worker"]   # non-successor undisturbed
        finally:
            for c in clients:
                c.close()

    def test_middle_death_rewires_watch_chain(self, core):
        """When a non-leader dies, its successor re-watches the new
        predecessor without a leadership change (LeaderElection.java:57-86:
        each node watches only its immediate predecessor)."""
        clients = [LocalCoordination(core, 0.1) for _ in range(3)]
        recs = [Recorder() for _ in range(3)]
        elections = []
        try:
            for c, r in zip(clients, recs):
                e = LeaderElection(c, r)
                e.volunteer_for_leadership()
                e.reelect_leader()
                elections.append(e)
            core.expire_session(clients[1].sid)   # middle node dies
            # node 2 re-elects, stays a worker
            assert wait_until(lambda: len(recs[2].roles) == 2)
            assert recs[2].roles == ["worker", "worker"]
            assert elections[0].is_leader()
            # now the old leader dies → node 2 must be promoted (proves the
            # watch was correctly rewired to node 0)
            core.expire_session(clients[0].sid)
            assert wait_until(lambda: recs[2].roles[-1] == "leader")
        finally:
            for c in clients:
                c.close()


class TestRejoin:
    def test_worker_rejoins_after_session_expiry(self, core, tmp_path):
        """A node whose coordination session expires reconnects with a
        fresh session and re-enters the cluster — a capability the
        reference lacks (an expired pod stays out until restarted)."""
        def factory():
            return LocalCoordination(core, 0.1)

        nodes = []
        try:
            for i in range(2):
                cfg = Config(
                    documents_path=str(tmp_path / f"rj{i}" / "docs"),
                    index_path=str(tmp_path / f"rj{i}" / "index"),
                    port=0, min_doc_capacity=64,
                    min_nnz_capacity=1 << 12, min_vocab_capacity=1 << 10,
                    query_batch=4, max_query_terms=8)
                nodes.append(SearchNode(cfg, coord_factory=factory).start())
            leader, worker = nodes
            assert wait_until(lambda: leader.registry
                              .get_all_service_addresses() == [worker.url])
            old_sid = worker.coord.sid
            core.expire_session(old_sid)
            # the worker must come back on a FRESH session and re-register
            assert wait_until(lambda: worker.coord.sid != old_sid,
                              timeout=8.0)
            assert wait_until(lambda: leader.registry
                              .get_all_service_addresses() == [worker.url],
                              timeout=8.0)
        finally:
            for n in nodes:
                try:
                    n.stop()
                except Exception:
                    pass

    def test_leader_info_survives_old_session_expiry(self, core):
        """publish_leader_info must re-own /leader_info: if the new leader
        merely setData'd the old leader's ephemeral node, the address would
        vanish when the old session expires."""
        from tfidf_tpu.cluster.registry import publish_leader_info
        old = LocalCoordination(core, 0.1)
        new = LocalCoordination(core, 0.1)
        try:
            publish_leader_info(old, "http://old")
            publish_leader_info(new, "http://new")
            assert read_leader_info(new) == "http://new"
            core.expire_session(old.sid)
            time.sleep(0.3)   # old session's ephemerals reaped
            assert read_leader_info(new) == "http://new"
        finally:
            old.close()
            new.close()


class TestRegistry:
    def test_register_discover_unregister(self, core):
        a, b = LocalCoordination(core, 0.1), LocalCoordination(core, 0.1)
        try:
            ra, rb = ServiceRegistry(a), ServiceRegistry(b)
            ra.register_to_cluster("http://w0:1")
            rb.register_for_updates()
            assert wait_until(
                lambda: rb.get_all_service_addresses() == ["http://w0:1"])
            ra.unregister_from_cluster()
            assert wait_until(lambda: rb.get_all_service_addresses() == [])
        finally:
            a.close()
            b.close()

    def test_dead_worker_disappears(self, core):
        a, b = LocalCoordination(core, 0.1), LocalCoordination(core, 0.1)
        try:
            ra, rb = ServiceRegistry(a), ServiceRegistry(b)
            ra.register_to_cluster("http://w0:1")
            rb.register_for_updates()
            assert wait_until(
                lambda: rb.get_all_service_addresses() == ["http://w0:1"])
            core.expire_session(a.sid)   # worker crash
            assert wait_until(lambda: rb.get_all_service_addresses() == [])
        finally:
            a.close()
            b.close()


@pytest.fixture
def cluster(core, tmp_path):
    """A 3-node cluster on localhost with a real HTTP data plane."""
    nodes = []
    for i in range(3):
        cfg = Config(
            documents_path=str(tmp_path / f"node{i}" / "documents"),
            index_path=str(tmp_path / f"node{i}" / "index"),
            port=0, result_order="name",
            # single-copy placement: this suite pins the reference's
            # one-copy-per-doc semantics (spread, upsert routing,
            # partial tolerance); R-way placement has its own suite
            replication_factor=1,
            min_doc_capacity=64, min_nnz_capacity=1 << 12,
            min_vocab_capacity=1 << 10, query_batch=4, max_query_terms=8)
        node = SearchNode(cfg, coord=LocalCoordination(core, 0.1))
        node.start()
        nodes.append(node)
    # node 0 is leader (smallest sequence number); 1 and 2 are workers
    wait_until(lambda: len(
        nodes[0].registry.get_all_service_addresses()) == 2)
    yield nodes
    for n in nodes:
        try:
            n.stop()
        except Exception:
            pass


class TestClusterEndToEnd:
    def test_roles_and_status(self, cluster, core):
        leader = cluster[0]
        assert leader.is_leader()
        assert http_get(leader.url + "/api/status") == b"I am the leader"
        assert http_get(cluster[1].url +
                        "/api/status") == b"I am a worker node"
        # leader is not in the worker pool (OnElectionAction.java:30)
        addrs = json.loads(http_get(leader.url + "/api/services"))
        assert sorted(addrs) == sorted([cluster[1].url, cluster[2].url])
        assert read_leader_info(leader.coord) == leader.url

    def test_upload_search_download_cycle(self, cluster):
        leader = cluster[0]
        docs = {
            "a.txt": b"the quick brown fox jumps over the lazy dog",
            "b.txt": b"a fast brown fox and a quick red fox",
            "c.txt": b"lorem ipsum dolor sit amet",
            "d.txt": b"the dog sleeps all day long",
        }
        for name, data in docs.items():
            resp = http_post(leader.url + f"/leader/upload?name={name}",
                             data, content_type="application/octet-stream")
            assert b"uploaded successfully" in resp

        # scatter-gather search, sum-merged, name-ordered (parity mode)
        result = json.loads(http_post(leader.url + "/leader/start",
                                      json.dumps({"query": "fox"}).encode()))
        assert set(result) == {"a.txt", "b.txt"}
        assert list(result) == sorted(result)   # reference TreeMap order
        assert all(v > 0 for v in result.values())
        # b.txt mentions fox twice → higher score
        assert result["b.txt"] > result["a.txt"]

        # download: leader probes workers for the document (Leader.java:127)
        got = http_get(leader.url + "/leader/download?path=c.txt")
        assert got == docs["c.txt"]

        # load balancing spread documents over both workers
        sizes = [int(http_get(w + "/worker/index-size"))
                 for w in json.loads(http_get(leader.url + "/api/services"))]
        assert all(s > 0 for s in sizes)

    def test_concurrent_same_name_uploads_place_once(self, cluster):
        """ADVICE r3 #1: concurrent uploads of the same NEW name must all
        route to ONE worker (tentative claim under the placement lock) —
        without it two handlers both miss the map and place twin copies
        that double-count in the scatter-gather sum-merge."""
        from concurrent.futures import ThreadPoolExecutor

        leader = cluster[0]

        def up(i):
            return http_post(
                leader.url + "/leader/upload?name=same.txt",
                f"unique pelican document copy {i}".encode(),
                content_type="application/octet-stream").decode()

        with ThreadPoolExecutor(8) as ex:
            res = list(ex.map(up, range(16)))
        assert all("uploaded successfully" in r for r in res)
        assert len({r.rsplit(": ", 1)[-1] for r in res}) == 1
        result = json.loads(http_post(
            leader.url + "/leader/start",
            json.dumps({"query": "pelican"}).encode()))
        assert list(result) == ["same.txt"]

    def test_bulk_upload_batch_and_nrt_visibility(self, cluster):
        """Framework addition: /leader/upload-batch places a whole batch
        with one request per worker; deferred (NRT) commits are flushed
        by the next search, so read-your-writes holds end to end."""
        leader = cluster[0]
        docs = [{"name": f"bulk{i}.txt",
                 "text": f"zebra stripe number {i} " + ("grass " * (i % 3))}
                for i in range(20)]
        resp = json.loads(http_post(leader.url + "/leader/upload-batch",
                                    json.dumps(docs).encode()))
        assert sum(resp["placed"].values()) == 20
        assert len(resp["placed"]) == 2          # spread over both workers
        result = json.loads(http_post(leader.url + "/leader/start",
                                      b"zebra"))
        assert len(result) > 0                   # visible without explicit
        names = set(result)                      # commit (NRT flush)
        assert names <= {d["name"] for d in docs}
        # re-upload an existing name: routes to the SAME worker (upsert,
        # not duplicate) — placement map, ADVICE r2
        orig = leader._placement["bulk0.txt"][0]
        one = [{"name": "bulk0.txt", "text": "entirely new content"}]
        resp2 = json.loads(http_post(leader.url + "/leader/upload-batch",
                                     json.dumps(one).encode()))
        assert list(resp2["placed"]) == [orig]
        # a doc the worker refuses (binary-looking text) is reported as
        # skipped, excluded from placed counts and the placement map
        bad = [{"name": "bad.pdf", "text": "%PDF-1.4 but no streams"},
               {"name": "good.txt", "text": "perfectly fine words"}]
        resp3 = json.loads(http_post(leader.url + "/leader/upload-batch",
                                     json.dumps(bad).encode()))
        assert sum(resp3["placed"].values()) == 1
        assert [s["name"] for s in resp3["skipped"]] == ["bad.pdf"]
        assert "bad.pdf" not in leader._placement
        assert "good.txt" in leader._placement

    def test_malformed_batch_rejected_without_state_leak(self, cluster):
        """A doc missing 'name' must 400 BEFORE any routing state is
        touched: a mid-planning KeyError would leak inflight counts and
        claims for already-routed docs, pinning those names to
        never-confirmed placements (code-review r4)."""
        leader = cluster[0]
        bad = [{"name": "leaky.txt", "text": "fine"}, {"text": "no name"}]
        with pytest.raises(urllib.error.HTTPError) as ei:
            http_post(leader.url + "/leader/upload-batch",
                      json.dumps(bad).encode())
        assert ei.value.code == 400
        assert "leaky.txt" not in leader._placement
        assert not any(n == "leaky.txt"
                       for n, _w in leader.placement._inflight)
        # the name is still placeable afterwards
        ok = [{"name": "leaky.txt", "text": "quokka sighting report"}]
        resp = json.loads(http_post(leader.url + "/leader/upload-batch",
                                    json.dumps(ok).encode()))
        assert sum(resp["placed"].values()) == 1
        result = json.loads(http_post(leader.url + "/leader/start",
                                      b"quokka"))
        assert list(result) == ["leaky.txt"]

    def test_settle_failure_cleans_phantom_placement(self, cluster):
        """When EVERY upload leg of a new name fails, the tentative
        placement must not survive: the last failing leg of a
        never-confirmed replica drops the phantom entry, so retries can
        re-place the name anywhere (code-review r4, generalized to
        R-way legs in cluster/placement.py)."""
        leader = cluster[0]
        # registry read BEFORE taking the placement lock: production
        # never nests these, and the lockdep witness holds tests to the
        # same ordering discipline as the code under test
        w = leader.registry.get_all_service_addresses()[0]
        pm = leader.placement
        with leader._placement_lock:
            reps, new = pm.route_locked("ghost.txt", [w], {w: 0},
                                        None, 1)
            assert reps == (w,) and new
            pm._track_leg("ghost.txt", w)   # concurrent sibling leg
        # first leg fails: the sibling is still in flight, keep state
        pm.leg_failure("ghost.txt", w)
        assert "ghost.txt" in leader._placement
        # sibling leg fails last: no leg ever confirmed — drop the
        # phantom placement entirely
        pm.leg_failure("ghost.txt", w)
        assert "ghost.txt" not in leader._placement
        assert not any(n == "ghost.txt" for n, _w in pm._inflight)

    def test_large_download_streams_with_bounded_reads(self, cluster):
        """A big document flows worker -> leader -> client in bounded
        chunks (Leader.java:95-151 FileSystemResource parity): no hop
        buffers the whole file, and the bytes survive the two-hop
        chunked proxy exactly."""
        import hashlib
        import os as _os

        leader, worker = cluster[0], cluster[1]
        # place a ~9MB file directly in a worker's documents dir (upload
        # paths are text-oriented; download must serve any bytes)
        blob = _os.urandom(1 << 20) * 9
        path = worker.engine._safe_doc_path("big.bin")
        _os.makedirs(_os.path.dirname(path), exist_ok=True)
        with open(path, "wb") as f:
            f.write(blob)

        reads = []
        orig = worker.engine.open_document_stream

        def spying(rel):
            got = orig(rel)
            if got is None:
                return None
            stream, size = got

            class Spy:
                def read(self, n=-1):
                    buf = stream.read(n)
                    reads.append(len(buf))
                    return buf

                def close(self):
                    stream.close()
            return Spy(), size

        worker.engine.open_document_stream = spying
        try:
            got = http_get(leader.url + "/leader/download?path=big.bin",
                           timeout=60.0)
        finally:
            worker.engine.open_document_stream = orig
        assert hashlib.sha256(got).hexdigest() == \
            hashlib.sha256(blob).hexdigest()
        # the worker handler pulled bounded chunks, never the whole file
        assert reads and max(reads) <= (1 << 16)

    def test_pdf_upload_extracts_binary_upload_415(self, cluster):
        """Tika-parity contract over HTTP (Worker.java:198-212): a PDF
        becomes searchable text; a raw binary is refused with 415."""
        import urllib.error

        leader = cluster[0]
        pdf_stream = b"BT (uniquepdftoken inside document) Tj ET"
        pdf = (b"%PDF-1.4\nstream\n" + pdf_stream + b"endstream\n%%EOF")
        http_post(leader.url + "/leader/upload?name=doc.pdf", pdf,
                  content_type="application/octet-stream")
        res = json.loads(http_post(leader.url + "/leader/start",
                                   b"uniquepdftoken"))
        assert set(res) == {"doc.pdf"}
        elf = b"\x7fELF\x02\x01\x01" + bytes(64)
        with pytest.raises(urllib.error.HTTPError) as ei:
            http_post(leader.url + "/leader/upload?name=prog.bin", elf,
                      content_type="application/octet-stream")
        assert ei.value.code == 415

    def test_multipart_upload(self, cluster):
        leader = cluster[0]
        boundary = "XbOuNdArYX"
        body = (
            f"--{boundary}\r\n"
            'Content-Disposition: form-data; name="file"; '
            'filename="multi.txt"\r\n'
            "Content-Type: text/plain\r\n\r\n"
            "zebra stripes pattern\r\n"
            f"--{boundary}--\r\n").encode()
        resp = http_post(
            leader.url + "/leader/upload", body,
            content_type=f"multipart/form-data; boundary={boundary}")
        assert b"uploaded successfully" in resp
        result = json.loads(http_post(
            leader.url + "/leader/start",
            json.dumps({"query": "zebra"}).encode()))
        assert "multi.txt" in result

    def test_download_traversal_rejected(self, cluster):
        worker = cluster[1]
        req = urllib.request.Request(
            worker.url + "/worker/download?path=..%2F..%2Fetc%2Fpasswd")
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=5)
        assert ei.value.code == 400

    def test_partial_results_on_worker_failure(self, cluster):
        """Per-worker failure tolerance (Leader.java:67-69): killing one
        worker must not break search; the other shard still answers."""
        leader = cluster[0]
        for name, text in [("x.txt", b"alpha beta"), ("y.txt", b"alpha gq")]:
            http_post(leader.url + f"/leader/upload?name={name}", text,
                      content_type="application/octet-stream")
        cluster[2].httpd.shutdown()   # data plane down, session still alive
        cluster[2].httpd.server_close()   # refuse new connections promptly
        result = json.loads(http_post(
            leader.url + "/leader/start",
            json.dumps({"query": "alpha"}).encode()))
        # at least the surviving worker's shard answered
        assert len(result) >= 1

    def test_leader_failover_end_to_end(self, cluster, core):
        """Kill the leader: a worker is promoted, publishes /leader_info,
        leaves the worker pool, and serves searches."""
        old_leader, w1 = cluster[0], cluster[1]
        http_post(old_leader.url + "/leader/upload?name=z.txt",
                  b"gamma delta", content_type="application/octet-stream")
        core.expire_session(old_leader.coord.sid)
        assert wait_until(lambda: w1.is_leader(), timeout=5.0)
        assert wait_until(
            lambda: read_leader_info(w1.coord) == w1.url, timeout=5.0)
        # new leader left the worker pool; only w2 remains registered
        assert wait_until(lambda: w1.registry.get_all_service_addresses()
                          == [cluster[2].url], timeout=5.0)
        result = json.loads(http_post(
            w1.url + "/leader/start",
            json.dumps({"query": "gamma"}).encode()))
        assert isinstance(result, dict)

    def test_fault_injection_on_scatter(self, cluster):
        """Armed fault point drops every worker RPC → empty results, no
        error (the reference's swallow-and-continue semantics)."""
        leader = cluster[0]
        http_post(leader.url + "/leader/upload?name=f.txt", b"epsilon zeta",
                  content_type="application/octet-stream")
        global_injector.arm("leader.worker_rpc", action="raise")
        try:
            result = json.loads(http_post(
                leader.url + "/leader/start",
                json.dumps({"query": "epsilon"}).encode()))
            assert result == {}
        finally:
            global_injector.disarm("leader.worker_rpc")

    def test_leader_download_traversal_rejected(self, cluster):
        req = urllib.request.Request(
            cluster[0].url + "/leader/download?path=..%2F..%2Fetc%2Fpasswd")
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=5)
        assert ei.value.code == 400

    def test_metrics_exposed(self, cluster):
        leader = cluster[0]
        http_post(leader.url + "/leader/upload?name=m.txt", b"metric text",
                  content_type="application/octet-stream")
        snap = json.loads(http_get(leader.url + "/api/metrics"))
        assert snap.get("uploads_placed", 0) >= 1


class TestBoundedClusterSearch:
    """r2: /worker/process serves exact top-k by default; the reference's
    unbounded ranking (Worker.java:230) is opt-in parity behavior."""

    def _fill(self, leader, n=25):
        for i in range(n):
            http_post(leader.url + f"/leader/upload?name=bulk{i:02d}.txt",
                      b"shared common token plus unique" +
                      str(i).encode() * 2,
                      content_type="application/octet-stream")

    def test_default_returns_top_k(self, cluster):
        leader = cluster[0]
        self._fill(leader)
        res = json.loads(http_post(leader.url + "/leader/start",
                                   b"shared common token"))
        assert 0 < len(res) <= leader.config.top_k

    def test_worker_response_is_bounded(self, cluster):
        leader = cluster[0]
        self._fill(leader)
        for w in leader.registry.get_all_service_addresses():
            hits = json.loads(http_post(w + "/worker/process", b"common"))
            assert len(hits) <= leader.config.top_k

    def test_unbounded_parity_flag(self, core, tmp_path):
        nodes = []
        try:
            for i in range(2):
                cfg = Config(
                    documents_path=str(tmp_path / f"ub{i}" / "documents"),
                    index_path=str(tmp_path / f"ub{i}" / "index"),
                    port=0, unbounded_results=True, top_k=2,
                    min_doc_capacity=64, min_nnz_capacity=1 << 12,
                    min_vocab_capacity=1 << 10, query_batch=4,
                    max_query_terms=8)
                node = SearchNode(cfg, coord=LocalCoordination(core, 0.1))
                node.start()
                nodes.append(node)
            leader = nodes[0]
            wait_until(lambda: len(
                leader.registry.get_all_service_addresses()) == 1)
            for i in range(6):
                http_post(
                    leader.url + f"/leader/upload?name=d{i}.txt",
                    b"same term everywhere",
                    content_type="application/octet-stream")
            res = json.loads(http_post(leader.url + "/leader/start",
                                       b"term"))
            assert len(res) == 6   # all matches, despite top_k=2
        finally:
            for n in nodes:
                try:
                    n.stop()
                except Exception:
                    pass


class TestMeshCluster:
    """End-to-end: cluster nodes serving from the MESH engine — uploads
    commit into ShardedArrays and /leader/start answers through the
    shard_map psum/all_gather step (VERDICT r1 #1 'done' criterion)."""

    def test_leader_search_answers_through_mesh(self, core, tmp_path):
        from tfidf_tpu.parallel.mesh_index import MeshIndex
        nodes = []
        try:
            for i in range(2):
                cfg = Config(
                    documents_path=str(tmp_path / f"mesh{i}" / "documents"),
                    index_path=str(tmp_path / f"mesh{i}" / "index"),
                    port=0, engine_mode="mesh",
                    min_doc_capacity=64, min_nnz_capacity=1 << 12,
                    min_vocab_capacity=1 << 10, query_batch=4,
                    max_query_terms=8)
                node = SearchNode(cfg, coord=LocalCoordination(core, 0.1))
                node.start()
                nodes.append(node)
            leader, worker = nodes
            assert leader.is_leader()
            wait_until(lambda: len(
                leader.registry.get_all_service_addresses()) == 1)
            # the worker's engine really is mesh-backed
            assert isinstance(worker.engine.index, MeshIndex)
            assert worker.engine.index.mesh.devices.size == 8

            docs = {
                "a.txt": b"the quick brown fox jumps over the lazy dog",
                "b.txt": b"a fast brown fox and a quick red fox",
                "c.txt": b"lorem ipsum dolor sit amet",
                "d.txt": b"red dogs chase brown foxes at dawn",
            }
            for name, data in docs.items():
                http_post(leader.url + f"/leader/upload?name={name}", data,
                          content_type="application/octet-stream")
            # NRT commit policy: uploads defer the commit; the next
            # search flushes it (read-your-writes via commit_if_dirty)
            worker.commit_if_dirty()
            # committed into sharded device arrays, spread over the mesh
            snap = worker.engine.index.snapshot
            assert snap is not None and snap.total_live == 4
            counts = [sum(1 for d in sd if d.live)
                      for sd in worker.engine.index._shard_docs]
            assert sum(counts) == 4
            assert sum(1 for c in counts if c > 0) >= 2

            res = json.loads(http_post(leader.url + "/leader/start",
                                       b"brown fox"))
            assert set(res) == {"a.txt", "b.txt", "d.txt"}
            assert res["b.txt"] > res["a.txt"]   # two foxes beat one

            # delete-equivalent: upsert then search through the mesh again
            http_post(leader.url + "/leader/upload?name=a.txt",
                      b"totally different content now",
                      content_type="application/octet-stream")
            res = json.loads(http_post(leader.url + "/leader/start",
                                       b"brown fox"))
            assert set(res) == {"b.txt", "d.txt"}
        finally:
            for n in nodes:
                try:
                    n.stop()
                except Exception:
                    pass


class TestScatterClient:
    """_ScatterClient retry/pruning semantics (code-review r4)."""

    def test_retries_stale_connection_not_timeout(self):
        import http.server
        import socket
        import threading

        from tfidf_tpu.cluster.node import _ScatterClient

        hits = []

        class H(http.server.BaseHTTPRequestHandler):
            def do_POST(self):
                hits.append(self.path)
                n = int(self.headers.get("Content-Length", 0))
                self.rfile.read(n)
                body = b"[]"
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):
                pass

        srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0), H)
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        base = f"http://127.0.0.1:{srv.server_address[1]}"
        c = _ScatterClient()
        try:
            assert c.post(base, "/worker/process", b"{}") == b"[]"
            # server restarts: the cached keep-alive connection is stale;
            # ONE transparent retry on a fresh connection must succeed
            srv.shutdown()
            srv.server_close()
            srv2 = http.server.ThreadingHTTPServer(
                ("127.0.0.1", srv.server_address[1]), H)
            threading.Thread(target=srv2.serve_forever,
                             daemon=True).start()
            assert c.post(base, "/worker/process", b"{}") == b"[]"
            srv2.shutdown()
            srv2.server_close()
        finally:
            pass
        # a connection-refused endpoint exhausts the single retry and
        # raises (never loops)
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            dead = f"http://127.0.0.1:{s.getsockname()[1]}"
        with pytest.raises(Exception):
            c.post(dead, "/worker/process", b"{}")

    def test_prunes_departed_workers(self):
        from tfidf_tpu.cluster.node import _ScatterClient

        c = _ScatterClient()
        c._tls.conns = {"http://old:1": _FakeConn(),
                        "http://live:2": _FakeConn()}
        try:
            c.post("http://live:2", "/x", b"", live={"http://live:2"})
        except Exception:
            pass   # the fake conn fails the request; pruning is the point
        assert "http://old:1" not in c._tls.conns


class _FakeConn:
    closed = False

    def close(self):
        self.closed = True

    def request(self, *a, **kw):
        raise ConnectionResetError("fake")


class TestSizeCacheEviction:
    def test_stale_poll_cannot_resurrect_evicted_worker(self, cluster):
        """A worker evicted from the size cache during a poll must not
        re-enter it from that poll's pre-failure data (code-review r4)."""
        import time as _time

        leader = cluster[0]
        workers = leader.registry.get_all_service_addresses()
        w = workers[0]
        with leader._placement_lock:
            leader._size_cache = (0.0, {})   # force a fresh poll
        leader._ensure_sizes_fresh(workers)
        assert w in leader._size_cache[1]
        # simulate a failure-eviction racing a poll that started earlier
        with leader._placement_lock:
            leader._size_cache[1].pop(w, None)
            leader._evicted[w] = _time.monotonic() + 60.0   # "future"
            leader._size_cache = (0.0, leader._size_cache[1])
        leader._ensure_sizes_fresh(workers)
        assert w not in leader._size_cache[1]
        # once the eviction is old news, the next poll restores it
        with leader._placement_lock:
            leader._evicted[w] = _time.monotonic() - 1.0
            leader._size_cache = (0.0, leader._size_cache[1])
        leader._ensure_sizes_fresh(workers)
        assert w in leader._size_cache[1]

"""Multi-host bootstrap plumbing (jax.distributed over DCN, SURVEY §5.8).

Real multi-host needs multiple machines; what is testable on one CPU host
is the full init path — coordinator service, process handshake, global
device view — with a 1-process "pod", run in a subprocess so the global
distributed state never leaks into this test process.
"""

import socket
import subprocess
import sys
import textwrap

import pytest


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_initialize_multihost_single_process_pod(tmp_path):
    port = _free_port()
    code = textwrap.dedent(f"""
        import os
        os.environ["JAX_PLATFORMS"] = "cpu"
        from tfidf_tpu.parallel.mesh import initialize_multihost, make_mesh
        import jax

        ok = initialize_multihost(
            coordinator_address="127.0.0.1:{port}",
            num_processes=1, process_id=0)
        assert ok, "first call must perform the init"
        assert jax.process_count() == 1
        assert jax.process_index() == 0
        # idempotent: a second call is a no-op
        assert initialize_multihost() is False
        # the mesh builds over the (global) device view post-init
        mesh = make_mesh()
        assert mesh.devices.size == len(jax.devices())
        print("MULTIHOST_OK")
    """)
    out = subprocess.run([sys.executable, "-c", code], capture_output=True,
                         text=True, timeout=120)
    assert "MULTIHOST_OK" in out.stdout, (out.stdout, out.stderr)


@pytest.mark.timeout(300)
def test_multiprocess_mesh_engine_parity(tmp_path):
    """REAL multi-process jax.distributed (VERDICT r4 #3): 2 OS
    processes x 2 virtual CPU devices form ONE global mesh; the mesh
    engine's ingest + commit + search run with the docs axis spanning
    the process boundary (cross-process psum df + top-k all_gather over
    gloo), and every process must produce local-engine-equivalent
    results. The worker body lives in tests/mp_mesh_worker.py."""
    import os

    import jax

    # cross-process collectives on the CPU backend were only implemented
    # in newer jax ("Multiprocess computations aren't implemented on the
    # CPU backend" on 0.4.x) — skip rather than fail where the runtime
    # lacks the capability; real TPU pods are unaffected
    if tuple(int(x) for x in jax.__version__.split(".")[:2]) < (0, 5):
        pytest.skip("multiprocess CPU collectives unsupported on "
                    f"jax {jax.__version__}")

    n = 2
    port = _free_port()
    env = dict(os.environ)
    for k in ("XLA_FLAGS", "JAX_PLATFORMS", "TFIDF_JAX_PLATFORM"):
        env.pop(k, None)
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))
    worker = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "mp_mesh_worker.py")
    procs = [subprocess.Popen(
        [sys.executable, worker, f"127.0.0.1:{port}", str(n), str(i),
         str(tmp_path)], env=env, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True) for i in range(n)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=300)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, (i, out)
        assert f"MP_MESH_OK pid={i} procs=2 devices=4" in out, (i, out)


def test_serve_distributed_flag_plumbs_config():
    from tfidf_tpu.cli import build_parser
    args = build_parser().parse_args(["serve", "--distributed"])
    assert args.distributed is True
    args = build_parser().parse_args(["serve"])
    assert args.distributed is False


def test_config_env_overrides():
    from tfidf_tpu.utils.config import load_config
    cfg = load_config(env={"TFIDF_DISTRIBUTED": "true",
                           "TFIDF_DIST_COORDINATOR": "10.0.0.1:8476",
                           "TFIDF_DIST_NUM_PROCESSES": "4",
                           "TFIDF_DIST_PROCESS_ID": "2"})
    assert cfg.distributed is True
    assert cfg.dist_coordinator == "10.0.0.1:8476"
    assert cfg.dist_num_processes == 4
    assert cfg.dist_process_id == 2

"""Batched scatter-gather serving path (round-5 serving-gap work).

The leader coalesces concurrent ``/leader/start`` queries into one
``/worker/process-batch`` RPC per worker with a packed binary reply
(``cluster/wire.py``); these tests pin the wire format, the endpoint, and
the equivalence of the batched path with the per-query JSON path the
reference defines (``Leader.java:39-92``).
"""

import json
import threading

import pytest

from tfidf_tpu.cluster.coordination import CoordinationCore, LocalCoordination
from tfidf_tpu.cluster.node import SearchNode, http_post
from tfidf_tpu.cluster.wire import pack_hit_lists, unpack_hit_lists
from tfidf_tpu.engine.searcher import SearchHit
from tfidf_tpu.utils.config import Config

from tests.test_cluster import wait_until


class TestWireFormat:
    def test_roundtrip(self):
        lists = [
            [SearchHit("a.txt", 1.5), SearchHit("dir/b.txt", 0.25)],
            [],
            [SearchHit("unicode-ßø𝄞.txt", 3.75)],
            [SearchHit("", 0.0)],
        ]
        got = unpack_hit_lists(pack_hit_lists(lists))
        assert len(got) == len(lists)
        for want, have in zip(lists, got):
            assert [h.name for h in want] == [n for n, _ in have]
            for h, (_, s) in zip(want, have):
                assert s == pytest.approx(h.score, rel=1e-6)

    def test_empty_batch(self):
        assert unpack_hit_lists(pack_hit_lists([])) == []

    def test_corrupt_magic_rejected(self):
        data = bytearray(pack_hit_lists([[SearchHit("x", 1.0)]]))
        data[0] ^= 0xFF
        with pytest.raises(ValueError):
            unpack_hit_lists(bytes(data))

    def test_truncated_rejected(self):
        data = pack_hit_lists([[SearchHit("name.txt", 1.0)]])
        with pytest.raises(ValueError):
            unpack_hit_lists(data[:-3])

    def test_short_buffer_rejected_with_valueerror(self):
        """Buffers shorter than the 8-byte header (or the counts region
        the header promises) must raise ValueError per the wire
        contract — not struct.error (ADVICE r5)."""
        data = pack_hit_lists([[SearchHit("name.txt", 1.0)]])
        for cut in (b"", b"\x31", data[:4], data[:7]):
            with pytest.raises(ValueError):
                unpack_hit_lists(cut)
        # header intact but counts region missing
        with pytest.raises(ValueError):
            unpack_hit_lists(data[:8])


@pytest.fixture
def core():
    c = CoordinationCore(session_timeout_s=0.5)
    yield c
    c.close()


def _mk_cluster(core, tmp_path, n=3, **cfg_kw):
    nodes = []
    for i in range(n):
        cfg = Config(
            documents_path=str(tmp_path / f"sc{i}" / "documents"),
            index_path=str(tmp_path / f"sc{i}" / "index"),
            port=0, min_doc_capacity=64, min_nnz_capacity=1 << 12,
            min_vocab_capacity=1 << 10, query_batch=8, max_query_terms=8,
            # single-copy placement: this suite pins the scatter layer's
            # per-shard tolerance; R-way failover has its own suite
            **{"replication_factor": 1, **cfg_kw})
        node = SearchNode(cfg, coord=LocalCoordination(core, 0.1))
        node.start()
        nodes.append(node)
    wait_until(lambda: len(
        nodes[0].registry.get_all_service_addresses()) == n - 1)
    return nodes


def _stop_all(nodes):
    for nd in nodes:
        try:
            nd.stop()
        except Exception:
            pass


DOCS = {
    "a.txt": b"apple banana cherry apple",
    "b.txt": b"banana date elderberry",
    "c.txt": b"apple fig grape banana banana",
    "d.txt": b"cherry date apple apple apple",
    "e.txt": b"solo unique token here",
}


class TestProcessBatchEndpoint:
    def test_packed_reply_matches_per_query_json(self, core, tmp_path):
        nodes = _mk_cluster(core, tmp_path)
        try:
            leader = nodes[0]
            for name, data in DOCS.items():
                http_post(leader.url + f"/leader/upload?name={name}", data,
                          content_type="application/octet-stream")
            queries = ["apple", "banana date", "nosuchterm", "cherry"]
            for w in leader.registry.get_all_service_addresses():
                packed = http_post(
                    w + "/worker/process-batch",
                    json.dumps({"queries": queries, "k": 10}).encode())
                batch = unpack_hit_lists(packed)
                assert len(batch) == len(queries)
                for q, hits in zip(queries, batch):
                    singles = json.loads(http_post(
                        w + "/worker/process",
                        json.dumps({"query": q}).encode()))
                    assert [(h["document"]["name"],
                             pytest.approx(h["score"], rel=1e-5))
                            for h in singles] == hits
        finally:
            _stop_all(nodes)


class TestScatterBatchedLeader:
    def test_batched_equals_per_query_path(self, core, tmp_path):
        """The coalesced scatter must return exactly what the reference's
        per-query fan-out shape returns, for every query."""
        nodes = _mk_cluster(core, tmp_path, result_order="name")
        try:
            leader = nodes[0]
            for name, data in DOCS.items():
                http_post(leader.url + f"/leader/upload?name={name}", data,
                          content_type="application/octet-stream")
            queries = ["apple", "banana", "apple banana", "date",
                       "nosuchterm", "solo unique"]
            assert leader.scatter_batcher is not None
            batched = {}
            threads = []

            def run(q):
                batched[q] = json.loads(http_post(
                    leader.url + "/leader/start",
                    json.dumps({"query": q}).encode()))

            for q in queries:   # concurrent: exercises real coalescing
                t = threading.Thread(target=run, args=(q,))
                t.start()
                threads.append(t)
            for t in threads:
                t.join()

            # reference-shaped per-query fan-out on the same cluster
            sb, leader.scatter_batcher = leader.scatter_batcher, None
            try:
                for q in queries:
                    want = leader.leader_search(q)
                    have = batched[q]
                    assert list(have) == list(want), q
                    for n in want:
                        assert have[n] == pytest.approx(want[n], rel=1e-5)
            finally:
                leader.scatter_batcher = sb
        finally:
            _stop_all(nodes)

    def test_partial_results_on_worker_death(self, core, tmp_path):
        """A dead worker's shard drops out of the batched scatter
        (partial results, Leader.java:67-69 / ServiceRegistry watch
        semantics), never an error. Session expiry shrinks the registry,
        and the scatter client prunes its idle keep-alive socket.
        Recovery is disabled to isolate the scatter layer's tolerance
        (tests/test_shard_recovery.py covers the re-placement path)."""
        nodes = _mk_cluster(core, tmp_path, shard_recovery=False)
        try:
            leader = nodes[0]
            for name, data in DOCS.items():
                http_post(leader.url + f"/leader/upload?name={name}", data,
                          content_type="application/octet-stream")
            full = json.loads(http_post(leader.url + "/leader/start",
                                        b"apple banana"))
            assert full
            victim = nodes[1]
            victim_names = [n for n, ws in leader._placement.items()
                            if victim.url in ws]
            assert victim_names   # placement spread both workers
            core.expire_session(victim.coord.sid)
            assert wait_until(lambda: leader.registry
                              .get_all_service_addresses()
                              == [nodes[2].url])
            res = json.loads(http_post(leader.url + "/leader/start",
                                       b"apple banana"))
            assert set(res).isdisjoint(victim_names)
            assert set(res) == set(full) - set(victim_names)
        finally:
            _stop_all(nodes)

    def test_unbounded_config_uses_per_query_path(self, core, tmp_path):
        nodes = _mk_cluster(core, tmp_path, n=2, unbounded_results=True)
        try:
            assert nodes[0].scatter_batcher is None
        finally:
            _stop_all(nodes)


class TestNrtCommitBarrier:
    def test_search_waits_for_inflight_commit(self, core, tmp_path):
        """Read-your-writes under concurrency: a search that finds the
        dirty flag already cleared by a sibling must WAIT for that
        sibling's in-flight commit, not serve the pre-upload snapshot
        (the race that surfaced as silently-partial batched scatters)."""
        import time

        cfg = Config(
            documents_path=str(tmp_path / "nrt" / "documents"),
            index_path=str(tmp_path / "nrt" / "index"),
            port=0, micro_batch=False, scatter_micro_batch=False,
            min_doc_capacity=64, min_nnz_capacity=1 << 12,
            min_vocab_capacity=1 << 10, query_batch=4, max_query_terms=8)
        node = SearchNode(cfg, coord=LocalCoordination(core, 0.1))
        node.start()
        try:
            node.engine.ingest_text("n.txt", "needle haystack")
            node.notify_write()
            orig = node.engine.commit
            started = threading.Event()

            def slow_commit():
                started.set()
                time.sleep(0.3)
                orig()

            node.engine.commit = slow_commit
            t = threading.Thread(target=node.worker_search,
                                 args=("needle",))
            t.start()
            assert started.wait(2.0)
            # this search arrives mid-commit with the flag already clear
            hits = node.worker_search("needle")
            t.join()
            assert any(h.name == "n.txt" for h in hits)
        finally:
            node.stop()


class TestCompileFlakeRetry:
    def test_batch_search_retries_once_on_compile_error(self, core,
                                                        tmp_path):
        """A transient remote-compile failure (the tunnel's compile
        helper returns HTTP 500) must not degrade a batch to empty
        results: the pure search retries once."""
        cfg = Config(
            documents_path=str(tmp_path / "cf" / "documents"),
            index_path=str(tmp_path / "cf" / "index"),
            port=0, min_doc_capacity=64, min_nnz_capacity=1 << 12,
            min_vocab_capacity=1 << 10, query_batch=4, max_query_terms=8)
        node = SearchNode(cfg, coord=LocalCoordination(core, 0.1)).start()
        try:
            node.engine.ingest_text("a.txt", "needle body")
            node.engine.commit()
            orig = node.engine.search_batch
            calls = {"n": 0}

            def flaky(queries, k=None, unbounded=False):
                calls["n"] += 1
                if calls["n"] == 1:
                    raise RuntimeError(
                        "INTERNAL: remote_compile: HTTP 500: "
                        "tpu_compile_helper subprocess exit code 1")
                return orig(queries, k=k, unbounded=unbounded)

            node.engine.search_batch = flaky
            hits = node.worker_search_batch(["needle"])
            assert calls["n"] == 2
            assert [h.name for h in hits[0]] == ["a.txt"]

            # non-compile errors propagate immediately (no blind retry)
            calls["n"] = 0

            def broken(queries, k=None, unbounded=False):
                calls["n"] += 1
                raise ValueError("scoring exploded")

            node.engine.search_batch = broken
            with pytest.raises(ValueError):
                node.worker_search_batch(["needle"])
            assert calls["n"] == 1
        finally:
            node.stop()

"""Zero-downtime fleet evolution: wire-protocol versioning, the
version-skew nemesis, traffic capture/replay, and rolling-upgrade
chaos.

Tier-1 pins (fast):

- ``cluster/protover.py`` pure semantics: header parsing (absent /
  malformed -> implicit version 1), the compat window (floor only, no
  ceiling), the outbound stamp.
- The version gate at the handler seam: in-window and future versions
  accepted, below-floor answered with the DISTINCT status 426 +
  ``X-Proto-Rejected: 1`` + a structured body naming both sides'
  versions; ops endpoints ungated; unknown request headers pass
  through (forward compatibility).
- Classification: a proto rejection is never retryable and never a
  worker fault, so rolling-upgrade skew cannot trip breakers.
- The skew nemesis: per-link header masking at the transport seams,
  end-to-end into a raised-floor node.
- Capture/replay: CRC-framed request-log roundtrip, torn-tail
  truncation, entry bound, the admitted-only tap at the front door,
  and replay determinism — the same captured log drives two fresh
  clusters to identical admitted counts and identical results.
- ``cli status``: the per-member proto-version table and the
  mixed-version flag.

Slow (``make chaos-upgrade``): a rolling restart workers -> router ->
leader under live zipfian read load and a write stream, with the
version-skew nemesis, a partition, and a storage fault riding along —
asserting zero acked-write loss, a bounded shed fraction, exact oracle
parity after every step, and that the skew window tripped proto
rejections but never a breaker.
"""

import json
import random
import threading
import time
import urllib.error
import urllib.request

import pytest

from tfidf_tpu.cluster.coordination import CoordinationCore
from tfidf_tpu.cluster.nemesis import NemesisNet, global_nemesis
from tfidf_tpu.cluster.node import http_post
from tfidf_tpu.cluster.protover import (IMPLICIT_VERSION, PROTO_HEADER,
                                        PROTO_REJECTED_HEADER, PROTO_STATUS,
                                        PROTO_VERSION, in_window,
                                        parse_version, proto_headers)
from tfidf_tpu.cluster.resilience import (RpcStatusError, is_proto_rejection,
                                          is_retryable, is_worker_fault)
from tfidf_tpu.utils.metrics import global_metrics
from tfidf_tpu.utils.storage import RequestLog, global_storage

from tests.test_cluster import wait_until
from tests.test_partition import (DOCS, QUERIES, _CFG, _node, _oracle,
                                  _parity, _search, _stop_all, _upload_docs)
from tests.test_router import _mk_router


@pytest.fixture(autouse=True)
def _heal_all():
    """Every test leaves the process-global nemeses healed."""
    yield
    global_nemesis.heal()
    global_storage.heal()


@pytest.fixture
def core():
    c = CoordinationCore(session_timeout_s=0.5)
    yield c
    c.close()


def _raw(url, data=None, headers=None, timeout=10.0):
    """A request OUTSIDE the stamping seams: exactly the wire an
    old (pre-versioning) binary puts on the network."""
    req = urllib.request.Request(url, data=data, headers=headers or {})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, dict(r.headers), r.read()


def _pair(core, tmp_path, base=0, **leader_kw):
    """The smallest cluster that serves uploads: a leader plus one
    registered worker. Returns [leader, worker]."""
    leader = _node(core, tmp_path, base, **leader_kw)
    worker = _node(core, tmp_path, base + 1)
    wait_until(lambda: len(
        leader.registry.get_all_service_addresses()) == 1)
    return [leader, worker]


# ---------------------------------------------------------------------------
# protover pure semantics
# ---------------------------------------------------------------------------

class TestProtoverPure:
    def test_parse_version_absent_is_implicit(self):
        assert parse_version(None) == IMPLICIT_VERSION

    def test_parse_version_values(self):
        assert parse_version("2") == 2
        assert parse_version(" 3 ") == 3
        assert parse_version(str(PROTO_VERSION)) == PROTO_VERSION

    def test_parse_version_malformed_is_implicit(self):
        # garbage never escalates to a rejection the sender cannot
        # act on — malformed headers are the pre-versioning wire
        for bad in ("", "banana", "0", "-4", "2.5"):
            assert parse_version(bad) == IMPLICIT_VERSION, bad

    def test_window_floor_only(self):
        assert in_window(1, 1)
        assert in_window(PROTO_VERSION, 1)
        assert not in_window(1, PROTO_VERSION)
        # deliberately no ceiling: a newer peer is always accepted
        assert in_window(99, PROTO_VERSION)

    def test_outbound_stamp(self):
        assert proto_headers() == {PROTO_HEADER: str(PROTO_VERSION)}


# ---------------------------------------------------------------------------
# the version gate at the handler seam
# ---------------------------------------------------------------------------

class TestVersionGate:
    def test_replies_stamped_and_health_carries_version(self, core,
                                                        tmp_path):
        nd = _node(core, tmp_path, 0)
        try:
            st, hdrs, body = _raw(nd.url + "/api/health")
            assert st == 200
            assert hdrs.get(PROTO_HEADER) == str(PROTO_VERSION)
            h = json.loads(body)
            assert h["proto_version"] == PROTO_VERSION
            assert "role" in h
        finally:
            nd.stop()

    def test_below_floor_rejected_distinctly(self, core, tmp_path):
        nd = _node(core, tmp_path, 0, proto_min_compat=PROTO_VERSION)
        try:
            before = global_metrics.get("proto_rejections")
            with pytest.raises(urllib.error.HTTPError) as ei:
                # no X-Proto-Version header: implicit version 1, which
                # is below this node's floor
                _raw(nd.url + "/leader/start",
                     data=json.dumps({"query": "x"}).encode(),
                     headers={"Content-Type": "application/json"})
            e = ei.value
            assert e.code == PROTO_STATUS
            assert e.headers.get(PROTO_REJECTED_HEADER) == "1"
            detail = json.loads(e.read())
            assert detail["declared"] == IMPLICIT_VERSION
            assert detail["min_compat"] == PROTO_VERSION
            assert detail["server_version"] == PROTO_VERSION
            assert global_metrics.get("proto_rejections") > before
        finally:
            nd.stop()

    def test_in_window_and_future_accepted(self, core, tmp_path):
        nodes = _pair(core, tmp_path,
                      proto_min_compat=PROTO_VERSION)
        try:
            _upload_docs(nodes[0].url, {"a.txt": "alpha beta"})
            for declared in (str(PROTO_VERSION), "99"):
                st, hdrs, body = _raw(
                    nodes[0].url + "/leader/start",
                    data=json.dumps({"query": "alpha"}).encode(),
                    headers={"Content-Type": "application/json",
                             PROTO_HEADER: declared})
                assert st == 200, declared
                assert hdrs.get(PROTO_HEADER) == str(PROTO_VERSION)
                assert "a.txt" in json.loads(body)
        finally:
            _stop_all(nodes)

    def test_ops_endpoints_ungated(self, core, tmp_path):
        # an operator must be able to inspect a node whatever binary
        # they run — /api/* never version-rejects
        nd = _node(core, tmp_path, 0, proto_min_compat=PROTO_VERSION)
        try:
            for path in ("/api/health", "/api/status", "/api/metrics"):
                st, _, _ = _raw(nd.url + path)
                assert st == 200, path
        finally:
            nd.stop()

    def test_unknown_request_headers_pass_through(self, core, tmp_path):
        # forward compatibility: a newer peer only ever ADDS surface;
        # headers this binary has never heard of are ignored, not
        # rejected
        nodes = _pair(core, tmp_path,
                      proto_min_compat=PROTO_VERSION)
        try:
            _upload_docs(nodes[0].url, {"a.txt": "alpha beta"})
            st, _, body = _raw(
                nodes[0].url + "/leader/start",
                data=json.dumps({"query": "alpha"}).encode(),
                headers={"Content-Type": "application/json",
                         PROTO_HEADER: str(PROTO_VERSION),
                         "X-Future-Capability": "1",
                         "X-Another-Unknown": "yes"})
            assert st == 200
            assert "a.txt" in json.loads(body)
        finally:
            _stop_all(nodes)


# ---------------------------------------------------------------------------
# classification: proto rejections never retry, never trip breakers
# ---------------------------------------------------------------------------

class TestProtoClassification:
    def test_rpc_status_error_flag(self):
        e = RpcStatusError("http://w:1", PROTO_STATUS, proto=True)
        assert is_proto_rejection(e)
        assert not is_retryable(e)
        assert not is_worker_fault(e)

    def test_real_wire_rejection_classified(self, core, tmp_path):
        nd = _node(core, tmp_path, 0, proto_min_compat=PROTO_VERSION)
        try:
            with pytest.raises(urllib.error.HTTPError) as ei:
                _raw(nd.url + "/worker/names")
            e = ei.value
            assert is_proto_rejection(e)
            assert not is_retryable(e)
            assert not is_worker_fault(e)
        finally:
            nd.stop()

    def test_other_statuses_not_proto(self):
        assert not is_proto_rejection(RpcStatusError("http://w:1", 500))
        assert not is_proto_rejection(RpcStatusError("http://w:1", 429))


# ---------------------------------------------------------------------------
# the version-skew nemesis
# ---------------------------------------------------------------------------

class TestSkewNemesis:
    def test_filter_headers_masks_per_link(self):
        net = NemesisNet()
        h = {PROTO_HEADER: "2", "X-Other": "kept"}
        # inactive: passthrough
        assert net.filter_headers("http://a:1", "http://b:2", h) == h
        net.skew(src="http://a:1", dst="http://b:2")
        masked = net.filter_headers("http://a:1", "http://b:2", dict(h))
        assert PROTO_HEADER not in masked
        assert masked["X-Other"] == "kept"
        # a different link is untouched
        assert net.filter_headers("http://c:3", "http://b:2", dict(h)) == h
        net.heal()

    def test_filter_headers_case_insensitive(self):
        net = NemesisNet()
        net.skew(dst="http://b:2")
        before = global_metrics.get("nemesis_header_masks")
        masked = net.filter_headers(None, "http://b:2",
                                    {"x-proto-version": "2"})
        assert masked == {}
        assert global_metrics.get("nemesis_header_masks") > before
        net.heal()

    def test_skew_end_to_end(self, core, tmp_path):
        # strip the stamp on every link into a raised-floor node: the
        # node sees an old-binary peer and answers with the distinct
        # rejection, which the classifier refuses to blame on the
        # worker — then heal, and the same call succeeds
        nodes = _pair(core, tmp_path,
                      proto_min_compat=PROTO_VERSION)
        lead = nodes[0]
        try:
            _upload_docs(lead.url, {"a.txt": "alpha beta"})
            global_nemesis.skew(dst=lead.url)
            masks0 = global_metrics.get("nemesis_header_masks")
            with pytest.raises(urllib.error.HTTPError) as ei:
                http_post(lead.url + "/leader/start",
                          json.dumps({"query": "alpha"}).encode(),
                          origin="http://client:0")
            assert ei.value.code == PROTO_STATUS
            assert is_proto_rejection(ei.value)
            assert not is_worker_fault(ei.value)
            assert global_metrics.get("nemesis_header_masks") > masks0
            global_nemesis.heal()
            got = json.loads(http_post(
                lead.url + "/leader/start",
                json.dumps({"query": "alpha"}).encode(),
                origin="http://client:0"))
            assert "a.txt" in got
        finally:
            _stop_all(nodes)


# ---------------------------------------------------------------------------
# traffic capture / replay
# ---------------------------------------------------------------------------

class TestCaptureReplay:
    def test_requestlog_roundtrip(self, tmp_path):
        p = str(tmp_path / "cap" / "requests.log")
        rlog = RequestLog(p)
        assert rlog.record("alpha", "interactive", "c1")
        assert rlog.record("beta gamma", "bulk", "c2")
        assert rlog.record("delta", "interactive")
        rlog.close()
        entries = RequestLog.read(p)
        assert [e["query"] for e in entries] == ["alpha", "beta gamma",
                                                 "delta"]
        assert [e["lane"] for e in entries] == ["interactive", "bulk",
                                                "interactive"]
        assert entries[0]["client"] == "c1"
        ts = [e["t"] for e in entries]
        assert ts == sorted(ts) and ts[0] >= 0.0

    def test_requestlog_torn_tail_truncates_cleanly(self, tmp_path):
        p = str(tmp_path / "requests.log")
        rlog = RequestLog(p)
        rlog.record("alpha", "interactive")
        rlog.record("beta", "interactive")
        rlog.close()
        with open(p, "ab") as f:
            # a torn frame: valid-looking CRC prefix, truncated body
            f.write(b'00000000 {"t":1.0,"query":"tor')
        entries = RequestLog.read(p)
        assert [e["query"] for e in entries] == ["alpha", "beta"]

    def test_requestlog_entry_bound(self, tmp_path):
        p = str(tmp_path / "requests.log")
        rlog = RequestLog(p, max_entries=2)
        assert rlog.record("a", "interactive")
        assert rlog.record("b", "interactive")
        assert not rlog.record("c", "interactive")
        rlog.close()
        assert not rlog.record("d", "interactive")
        assert len(RequestLog.read(p)) == 2

    def test_front_door_tap_captures_admitted_only_fields(self, core,
                                                          tmp_path):
        cap = str(tmp_path / "cap" / "requests.log")
        nodes = _pair(core, tmp_path, replay_capture_path=cap)
        lead = nodes[0]
        try:
            _upload_docs(lead.url, {"a.txt": "alpha beta"})
            http_post(lead.url + "/leader/start",
                      json.dumps({"query": "alpha"}).encode())
            http_post(lead.url + "/leader/start",
                      json.dumps({"query": "beta"}).encode(),
                      headers={"X-Priority": "bulk", "X-Client-Id": "c9"})
        finally:
            _stop_all(nodes)
        entries = RequestLog.read(cap)
        assert [e["query"] for e in entries] == ["alpha", "beta"]
        assert entries[0]["lane"] == "interactive"
        assert entries[1]["lane"] == "bulk"
        assert entries[1]["client"] == "c9"

    @staticmethod
    def _replay(url, entries):
        """Re-drive a captured log through a front door: admitted
        count + per-request results (name -> rounded score)."""
        admitted, results = 0, []
        for e in entries:
            headers = {}
            if e.get("lane") == "bulk":
                headers["X-Priority"] = "bulk"
            if e.get("client"):
                headers["X-Client-Id"] = e["client"]
            try:
                body = http_post(url + "/leader/start",
                                 json.dumps({"query": e["query"]}).encode(),
                                 headers=headers)
                admitted += 1
                results.append({k: round(v, 4)
                                for k, v in json.loads(body).items()})
            except urllib.error.HTTPError:
                results.append(None)
        return admitted, results

    def test_replay_determinism_identical_admitted_counts(self, tmp_path):
        # capture a fixed workload on one cluster, then replay the log
        # into two FRESH clusters over the same corpus: both must admit
        # the same count and return the same results
        queries = QUERIES * 3
        cap = str(tmp_path / "cap" / "requests.log")
        core_a = CoordinationCore(session_timeout_s=0.5)
        cluster_a = _pair(core_a, tmp_path, replay_capture_path=cap)
        try:
            _upload_docs(cluster_a[0].url, DOCS)
            for q in queries:
                http_post(cluster_a[0].url + "/leader/start",
                          json.dumps({"query": q}).encode())
        finally:
            _stop_all(cluster_a)
            core_a.close()
        entries = RequestLog.read(cap)
        assert [e["query"] for e in entries] == queries

        replays = []
        for base in (5, 7):
            c = CoordinationCore(session_timeout_s=0.5)
            fresh = _pair(c, tmp_path, base=base)
            try:
                _upload_docs(fresh[0].url, DOCS)
                replays.append(self._replay(fresh[0].url, entries))
            finally:
                _stop_all(fresh)
                c.close()
        (adm_b, res_b), (adm_c, res_c) = replays
        assert adm_b == adm_c == len(entries)
        assert res_b == res_c


# ---------------------------------------------------------------------------
# cli status: the fleet's version table
# ---------------------------------------------------------------------------

class TestStatusVersions:
    def test_status_reports_proto_versions(self, core, tmp_path, capsys):
        from tests.test_cli import run_cli
        nodes = [_node(core, tmp_path, i) for i in range(2)]
        try:
            wait_until(lambda: len(
                nodes[0].registry.get_all_service_addresses()) == 1)
            rc, out = run_cli(capsys, "status", "--leader", nodes[0].url)
            assert rc == 0
            st = json.loads(out)
            v = st["versions"]
            assert v["proto_versions_seen"] == [PROTO_VERSION]
            assert v["mixed_versions"] is False
            assert len(v["members"]) >= 2
            assert all(m["proto_version"] == PROTO_VERSION
                       for m in v["members"] if m["reachable"])
        finally:
            _stop_all(nodes)


# ---------------------------------------------------------------------------
# rolling-upgrade chaos (make chaos-upgrade)
# ---------------------------------------------------------------------------

@pytest.mark.slow
class TestChaosUpgrade:
    @pytest.mark.timeout(420)
    def test_rolling_upgrade_zero_loss_exact_parity(self, tmp_path):
        """Workers -> router -> leader restart one at a time under live
        zipfian read load and a write stream, with a version-skew
        window, a partition, and a storage fault riding along. The
        fleet must stay exact the whole way: zero acked-write loss,
        bounded shed, oracle parity after every step, and the skew
        window must surface as proto rejections — never as breaker
        trips."""
        core = CoordinationCore(session_timeout_s=1.0)
        kw = dict(replication_factor=3, rpc_max_attempts=2,
                  breaker_failure_threshold=3, breaker_reset_s=0.5)
        # a mixed fleet from the start: node 2 is the "new binary"
        # whose floor already requires the versioned wire
        nodes = [_node(core, tmp_path, i,
                       proto_min_compat=(PROTO_VERSION if i == 2 else 1),
                       **kw)
                 for i in range(3)]
        router = _mk_router(core, **kw)
        front = {"url": router.url}
        stop_evt = threading.Event()
        lock = threading.Lock()
        acked = {}                       # name -> text, confirmed 200
        attempted = {}                   # name -> text, sent at all
        counts = {"ok": 0, "shed": 0, "proto": 0, "err": 0}
        threads = []
        try:
            wait_until(lambda: len(
                nodes[0].registry.get_all_service_addresses()) == 2,
                timeout=20)
            assert wait_until(lambda: any(nd.is_leader() for nd in nodes),
                              timeout=20)
            leader = next(nd for nd in nodes if nd.is_leader())
            # the oracle is over the STATIC corpus only — the write
            # stream uses disjoint tokens, so parity probes are
            # independent of writer progress
            r = _upload_docs(front["url"], DOCS)
            assert r
            # mid-run probes check exact result MEMBERSHIP: the write
            # stream's disjoint tokens never appear in these results,
            # but growing the corpus shifts IDF, so score-exact parity
            # is only well-defined once writes quiesce (checked at the
            # end against an oracle over the resolved corpus)
            want_names = {q: set(o)
                          for q, o in _oracle(tmp_path, DOCS,
                                              QUERIES).items()}

            def settled(q):
                try:
                    return set(_search(front["url"], q)) == want_names[q]
                except Exception:
                    return False

            def assert_parity(step):
                for q in QUERIES:
                    assert wait_until(lambda: settled(q), timeout=30), \
                        f"exact results lost after {step}: {q!r}"

            tokens = ["common", "token1", "token3 word0", "word1",
                      "extra2", "common token7", "word2", "token5"]
            zipf_w = [1.0 / (i + 1) for i in range(len(tokens))]

            def reader(seed):
                rng = random.Random(seed)
                while not stop_evt.is_set():
                    q = rng.choices(tokens, weights=zipf_w)[0]
                    try:
                        http_post(front["url"] + "/leader/start",
                                  json.dumps({"query": q}).encode(),
                                  timeout=5.0)
                        k = "ok"
                    except urllib.error.HTTPError as e:
                        k = ("shed" if e.code == 429 else
                             "proto" if e.code == PROTO_STATUS else "err")
                    except Exception:
                        k = "err"
                    with lock:
                        counts[k] += 1
                    time.sleep(0.01)

            def writer():
                k = 0
                while not stop_evt.is_set() and k < 400:
                    name, text = f"up{k}.txt", f"shared uq{k}tok"
                    k += 1
                    with lock:
                        attempted[name] = text
                    try:
                        http_post(
                            front["url"] + "/leader/upload-batch",
                            json.dumps([{"name": name,
                                         "text": text}]).encode(),
                            timeout=8.0)
                        with lock:
                            acked[name] = text
                    except Exception:
                        pass    # ambiguous: never counted as acked
                    time.sleep(0.05)

            threads = [threading.Thread(target=reader, args=(s,),
                                        daemon=True) for s in (1, 2)]
            threads.append(threading.Thread(target=writer, daemon=True))
            for t in threads:
                t.start()
            time.sleep(2.0)
            assert_parity("warmup")

            # ---- mixed-version window: strip the stamp on every link
            # into the raised-floor node. Its 426s must never look
            # like worker faults, so no breaker may open.
            rej0 = global_metrics.get("proto_rejections")
            masks0 = global_metrics.get("nemesis_header_masks")
            opened0 = global_metrics.get("breaker_opened")
            global_nemesis.skew(dst=nodes[2].url)
            time.sleep(3.0)
            assert_parity("version-skew window")
            global_nemesis.heal()
            assert global_metrics.get("nemesis_header_masks") > masks0
            assert global_metrics.get("proto_rejections") > rej0
            assert global_metrics.get("breaker_opened") == opened0, \
                "a proto rejection tripped a breaker"

            # ---- the rest of the chaos rides along: a brief
            # partition around one replica plus a bounded storage
            # fault under it
            global_storage.arm("fsync_eio",
                               str(tmp_path / "pt1") + "/*", times=2)
            global_nemesis.partition(
                [nodes[1].url],
                [nodes[0].url, nodes[2].url, router.url])
            time.sleep(2.0)
            global_nemesis.heal()
            global_storage.heal()
            assert_parity("partition + storage fault")

            # ---- rolling restart, workers first. Each replacement is
            # the upgraded binary: floor raised to the current wire.
            for i, nd in enumerate(list(nodes)):
                if nd.is_leader():
                    continue
                nd.stop()
                assert_parity(f"worker {i} down")
                nodes[i] = _node(core, tmp_path, i,
                                 proto_min_compat=PROTO_VERSION, **kw)
                assert wait_until(lambda: len(
                    leader.registry.get_all_service_addresses()) == 2,
                    timeout=30)
                assert_parity(f"worker {i} upgraded")

            # ---- router next, surge style (start the upgraded one,
            # move traffic, retire the old) — the front door never
            # goes dark
            new_router = _mk_router(core, proto_min_compat=PROTO_VERSION,
                                    **kw)
            old_router, front["url"] = router, new_router.url
            router = new_router
            old_router.stop()
            assert_parity("router upgraded")

            # ---- leader last: stop it, let the survivors elect, then
            # bring back the upgraded binary
            li = nodes.index(leader)
            leader.stop()
            assert wait_until(
                lambda: any(nd.is_leader()
                            for j, nd in enumerate(nodes) if j != li),
                timeout=30)
            nodes[li] = _node(core, tmp_path, li,
                              proto_min_compat=PROTO_VERSION, **kw)
            leader = next(nd for nd in nodes if nd.is_leader())
            assert wait_until(lambda: len(
                leader.registry.get_all_service_addresses()) == 2,
                timeout=30)
            assert_parity("leader upgraded")

            # ---- quiesce the load and verify the end state
            stop_evt.set()
            for t in threads:
                t.join(timeout=15)

            assert_parity("final")
            # zero acked-write loss: every confirmed write answers by
            # its unique token through the upgraded front door. An
            # AMBIGUOUS write (no ack came back) is resolved by the
            # same probe — present or absent, either is legal, but the
            # oracle corpus must match whichever happened.
            resolved = dict(DOCS)
            missing = []
            for name, text in sorted(attempted.items()):
                tok = text.split()[1]

                def present():
                    try:
                        return name in _search(front["url"], tok)
                    except Exception:
                        return False
                if name in acked:
                    if not wait_until(present, timeout=15):
                        missing.append((name, tok))
                    else:
                        resolved[name] = text
                elif present():
                    resolved[name] = text
            assert not missing, \
                f"acked writes lost across the upgrade: {missing[:5]}"

            # with writes quiesced and the corpus resolved, parity is
            # score-EXACT against a fresh single-node oracle
            final_want = _oracle(tmp_path / "final", resolved, QUERIES)

            def exact(q):
                try:
                    return _parity(_search(front["url"], q),
                                   final_want[q])
                except Exception:
                    return False
            for q in QUERIES:
                assert wait_until(lambda: exact(q), timeout=60), \
                    f"exact score parity lost at the end: {q!r}"

            total = sum(counts.values())
            assert counts["ok"] >= 100, counts
            # bounded shed spike: the rolling restart may shed, but
            # the front door must keep serving
            assert counts["shed"] / max(1, total) <= 0.5, counts
            # readers stamp the current version — the fleet's raised
            # floors never reject them
            assert counts["proto"] == 0, counts

            # the upgrade is complete: the whole fleet (router
            # included) now refuses the pre-versioning wire ...
            with pytest.raises(urllib.error.HTTPError) as ei:
                _raw(front["url"] + "/leader/start",
                     data=json.dumps({"query": "common"}).encode(),
                     headers={"Content-Type": "application/json"})
            assert ei.value.code == PROTO_STATUS
            assert ei.value.headers.get(PROTO_REJECTED_HEADER) == "1"
            # ... while stamped traffic flows
            assert _parity(_search(front["url"], "common"),
                           final_want["common"])
        finally:
            stop_evt.set()
            global_nemesis.heal()
            global_storage.heal()
            _stop_all(nodes)
            for rt in {router}:
                try:
                    rt.stop()
                except Exception:
                    pass
            core.close()

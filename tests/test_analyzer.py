from tfidf_tpu.ops.analyzer import Analyzer, extract_text, make_analyzer


def test_basic_tokens():
    a = Analyzer()
    assert a.tokens("The quick Brown-Fox jumps!") == \
        ["the", "quick", "brown", "fox", "jumps"]


def test_apostrophe_stays_one_token():
    # UAX#29 MidLetter rule, as StandardTokenizer does
    assert Analyzer().tokens("can't won't") == ["can't", "won't"]


def test_numbers_with_separators():
    assert Analyzer().tokens("pi is 3.14 and 1,000 units") == \
        ["pi", "is", "3.14", "and", "1,000", "units"]


def test_no_stopwords_by_default():
    # Lucene 9 StandardAnalyzer() has an EMPTY default stop set
    assert "the" in Analyzer().tokens("the cat")


def test_stopword_filter():
    a = make_analyzer(stopwords=["the", "a"])
    assert a.tokens("the cat sat on a mat") == ["cat", "sat", "on", "mat"]


def test_case_folding_off():
    a = Analyzer(lowercase=False)
    assert a.tokens("Fast Food") == ["Fast", "Food"]


def test_long_token_split_not_dropped():
    a = Analyzer(max_token_length=10)
    toks = a.tokens("x" * 25)
    assert toks == ["x" * 10, "x" * 10, "x" * 5]


def test_counts():
    assert Analyzer().counts("fast food fast") == {"fast": 2, "food": 1}


def test_unicode_tokens():
    assert Analyzer().tokens("café müller") == ["café", "müller"]


def test_extract_utf8():
    assert extract_text("héllo wörld".encode("utf-8")) == "héllo wörld"


def test_extract_latin1_fallback():
    data = "héllo".encode("latin-1")  # invalid as UTF-8
    assert "h" in extract_text(data) and "llo" in extract_text(data)


def test_extract_utf16_bom():
    data = "hello world".encode("utf-16")
    assert extract_text(data) == "hello world"


def test_extract_binary_degrades():
    noise = bytes(range(256)) * 4
    text = extract_text(noise)
    # control bytes become spaces; no exception, tokenizable output
    assert isinstance(text, str)

import pytest

from tfidf_tpu.ops.analyzer import (Analyzer, UnsupportedMediaType,
                                    extract_text, make_analyzer)


def test_basic_tokens():
    a = Analyzer()
    assert a.tokens("The quick Brown-Fox jumps!") == \
        ["the", "quick", "brown", "fox", "jumps"]


def test_apostrophe_stays_one_token():
    # UAX#29 MidLetter rule, as StandardTokenizer does
    assert Analyzer().tokens("can't won't") == ["can't", "won't"]


def test_numbers_with_separators():
    assert Analyzer().tokens("pi is 3.14 and 1,000 units") == \
        ["pi", "is", "3.14", "and", "1,000", "units"]


def test_no_stopwords_by_default():
    # Lucene 9 StandardAnalyzer() has an EMPTY default stop set
    assert "the" in Analyzer().tokens("the cat")


def test_stopword_filter():
    a = make_analyzer(stopwords=["the", "a"])
    assert a.tokens("the cat sat on a mat") == ["cat", "sat", "on", "mat"]


def test_case_folding_off():
    a = Analyzer(lowercase=False)
    assert a.tokens("Fast Food") == ["Fast", "Food"]


def test_long_token_split_not_dropped():
    a = Analyzer(max_token_length=10)
    toks = a.tokens("x" * 25)
    assert toks == ["x" * 10, "x" * 10, "x" * 5]


def test_counts():
    assert Analyzer().counts("fast food fast") == {"fast": 2, "food": 1}


def test_unicode_tokens():
    assert Analyzer().tokens("café müller") == ["café", "müller"]


def test_extract_utf8():
    assert extract_text("héllo wörld".encode("utf-8")) == "héllo wörld"


def test_extract_latin1_fallback():
    data = "héllo".encode("latin-1")  # invalid as UTF-8
    assert "h" in extract_text(data) and "llo" in extract_text(data)


def test_extract_utf16_bom():
    data = "hello world".encode("utf-16")
    assert extract_text(data) == "hello world"


def test_extract_binary_rejected():
    """Undecodable control-heavy blobs are refused, not indexed as
    mojibake (Tika-parity contract, VERDICT r2 #7)."""
    import pytest

    from tfidf_tpu.ops.analyzer import UnsupportedMediaType

    noise = bytes(range(256)) * 4
    with pytest.raises(UnsupportedMediaType):
        extract_text(noise)


def test_extract_known_binary_magics_rejected():
    import pytest

    from tfidf_tpu.ops.analyzer import UnsupportedMediaType

    for blob in (b"\x7fELF\x02\x01\x01" + b"\x00" * 64,
                 b"\x89PNG\r\n\x1a\n" + b"\x00" * 64,
                 b"\xff\xd8\xff\xe0" + b"\x00" * 64,
                 b"\x1f\x8b\x08\x00" + b"\x00" * 64):
        with pytest.raises(UnsupportedMediaType):
            extract_text(blob)


def _tiny_pdf(text: str) -> bytes:
    stream = f"BT /F1 12 Tf ({text}) Tj ET".encode()
    return (b"%PDF-1.4\n1 0 obj\n<< /Length "
            + str(len(stream)).encode()
            + b" >>\nstream\n" + stream + b"endstream\nendobj\n%%EOF\n")


def test_extract_pdf_text():
    out = extract_text(_tiny_pdf("Searchable PDF content"))
    assert "Searchable PDF content" in out


def test_extract_pdf_flate_and_tj_array():
    import zlib
    inner = b"BT [(Hello) -250 (World)] TJ ET"
    stream = zlib.compress(inner)
    pdf = (b"%PDF-1.4\nstream\n" + stream + b"endstream\n%%EOF")
    out = extract_text(pdf)
    assert "Hello" in out and "World" in out


def _cid_pdf(text: str, *, compress_cmap: bool = False,
             literal: bool = False) -> bytes:
    """A CID-encoded PDF: show strings are 2-byte glyph ids, readable
    only through the font's /ToUnicode CMap (the shape Tika handles and
    round 3 refused with 415 — VERDICT r3 #8)."""
    import zlib

    # glyph id = codepoint + 0x100 so raw bytes are NOT latin-1 text
    codes = [ord(c) + 0x100 for c in text]
    pairs = "\n".join(f"<{c:04x}> <{ord(ch):04x}>"
                      for c, ch in zip(codes, text))
    cmap = (b"/CIDInit /ProcSet findresource begin\n"
            b"begincmap\n"
            b"1 begincodespacerange\n<0000> <ffff> endcodespacerange\n"
            + f"{len(codes)} beginbfchar\n{pairs}\nendbfchar\n".encode()
            + b"endcmap\nend\n")
    if compress_cmap:
        cmap = zlib.compress(cmap)
    if literal:
        raw = b"".join(c.to_bytes(2, "big") for c in codes)
        esc = (raw.replace(b"\\", b"\\\\").replace(b"(", b"\\(")
               .replace(b")", b"\\)"))
        content = b"BT /F1 12 Tf (" + esc + b") Tj ET"
    else:
        hexstr = "".join(f"{c:04x}" for c in codes).encode()
        content = b"BT /F1 12 Tf <" + hexstr + b"> Tj ET"
    return (b"%PDF-1.4\n"
            b"1 0 obj\n<< /Type /Font /ToUnicode 2 0 R >>\nendobj\n"
            b"2 0 obj\n<< /Length " + str(len(cmap)).encode()
            + b" >>\nstream\n" + cmap + b"endstream\nendobj\n"
            b"3 0 obj\n<< /Length " + str(len(content)).encode()
            + b" >>\nstream\n" + content + b"endstream\nendobj\n"
            b"%%EOF\n")


def test_extract_pdf_cid_hex_tounicode():
    out = extract_text(_cid_pdf("Hidden cid words"))
    assert "Hidden cid words" in out


def test_extract_pdf_cid_compressed_cmap():
    out = extract_text(_cid_pdf("flate mapped text", compress_cmap=True))
    assert "flate mapped text" in out


def test_extract_pdf_cid_literal_string():
    """CID codes inside a LITERAL (...) Tj string: the bytes decode as
    garbage latin-1 but map cleanly through the CMap — the CMap must
    win."""
    out = extract_text(_cid_pdf("literal cid run", literal=True))
    assert "literal cid run" in out


def test_extract_pdf_cid_bfrange():
    import zlib
    text = "abcdef"
    # one bfrange covering a-f: <0161> <0166> <0061>
    cmap = (b"begincmap\n1 begincodespacerange\n<0000> <ffff> "
            b"endcodespacerange\n1 beginbfrange\n"
            b"<0161> <0166> <0061>\nendbfrange\nendcmap\n")
    codes = [ord(c) + 0x100 for c in text]
    hexstr = "".join(f"{c:04x}" for c in codes).encode()
    content = b"BT <" + hexstr + b"> Tj ET"
    pdf = (b"%PDF-1.4\n"
           b"1 0 obj\n<< /Type /Font /ToUnicode 2 0 R >>\nendobj\n"
           b"2 0 obj\n<< >>\nstream\n" + cmap
           + b"endstream\nendobj\n"
           b"3 0 obj\n<< >>\nstream\n" + content
           + b"endstream\nendobj\n%%EOF\n")
    assert "abcdef" in extract_text(pdf)
    # same but with a compressed content stream
    pdf2 = pdf.replace(b"stream\n" + content,
                       b"stream\n" + zlib.compress(content))
    assert "abcdef" in extract_text(pdf2)


def test_extract_pdf_cid_mixed_bfrange_forms():
    """A bfrange section mixing array-form and consecutive-form entries
    must parse both correctly — stripping only the brackets would leave
    an orphan <lo> <hi> pair that mis-pairs with the next entry
    (code-review r4)."""
    cmap = (b"begincmap\n1 begincodespacerange\n<0000> <ffff> "
            b"endcodespacerange\n2 beginbfrange\n"
            b"<0001> <0003> [<0041> <0042> <0043>]\n"
            b"<0010> <0012> <0061>\n"
            b"endbfrange\nendcmap\n")
    #  codes 1-3 -> ABC (array form); 0x10-0x12 -> abc (consecutive)
    content = b"BT <000100020003> Tj <001000110012> Tj ET"
    pdf = (b"%PDF-1.4\n"
           b"1 0 obj\n<< /Type /Font /ToUnicode 2 0 R >>\nendobj\n"
           b"2 0 obj\n<< >>\nstream\n" + cmap + b"endstream\nendobj\n"
           b"3 0 obj\n<< >>\nstream\n" + content
           + b"endstream\nendobj\n%%EOF\n")
    out = extract_text(pdf)
    assert "ABC" in out and "abc" in out


def test_extract_pdf_mixed_code_width_fonts():
    """A 2-byte CID font and a 1-byte simple-font ToUnicode in one PDF:
    per-width CMap maps keep the 2-byte show strings decoding at the
    right width regardless of CMap parse order (code-review r4)."""
    cmap2 = (b"begincmap\n1 begincodespacerange\n<0000> <ffff> "
             b"endcodespacerange\n2 beginbfchar\n"
             b"<0141> <0058>\n<0142> <0059>\nendbfchar\nendcmap\n")
    cmap1 = (b"begincmap\n1 begincodespacerange\n<00> <ff> "
             b"endcodespacerange\n2 beginbfchar\n"
             b"<41> <0061>\n<42> <0062>\nendbfchar\nendcmap\n")
    content = b"BT <01410142> Tj <4142> Tj ET"
    pdf = (b"%PDF-1.4\n"
           b"1 0 obj\n<< /Type /Font /ToUnicode 3 0 R >>\nendobj\n"
           b"2 0 obj\n<< /Type /Font /ToUnicode 4 0 R >>\nendobj\n"
           b"3 0 obj\n<< >>\nstream\n" + cmap2 + b"endstream\nendobj\n"
           b"4 0 obj\n<< >>\nstream\n" + cmap1 + b"endstream\nendobj\n"
           b"5 0 obj\n<< >>\nstream\n" + content
           + b"endstream\nendobj\n%%EOF\n")
    out = extract_text(pdf)
    # 2-byte codes 0x0141,0x0142 -> XY (not split into 1-byte a,b);
    # 1-byte codes 0x41,0x42 -> ab
    assert "XY" in out and "ab" in out


def test_extract_pdf_unmapped_cids_still_rejected():
    """Hex show strings whose codes have NO ToUnicode coverage must not
    be indexed as glyph-id noise; with no other text the PDF 415s."""
    import pytest

    from tfidf_tpu.ops.analyzer import UnsupportedMediaType

    content = b"BT <0501050205030504> Tj ET"
    pdf = (b"%PDF-1.4\n1 0 obj\n<< /Length "
           + str(len(content)).encode() + b" >>\nstream\n" + content
           + b"endstream\nendobj\n%%EOF\n")
    with pytest.raises(UnsupportedMediaType):
        extract_text(pdf)


def test_extract_pdf_without_text_rejected():
    import pytest

    from tfidf_tpu.ops.analyzer import UnsupportedMediaType

    with pytest.raises(UnsupportedMediaType):
        extract_text(b"%PDF-1.4\nno streams here\n%%EOF")


def test_extract_docx():
    import io
    import zipfile

    buf = io.BytesIO()
    xml = ('<?xml version="1.0"?><w:document><w:body><w:p>'
           '<w:r><w:t>word processor</w:t></w:r>'
           '<w:r><w:t xml:space="preserve"> payload &amp; more</w:t>'
           '</w:r></w:p></w:body></w:document>')
    with zipfile.ZipFile(buf, "w") as z:
        z.writestr("word/document.xml", xml)
    out = extract_text(buf.getvalue())
    assert "word processor" in out and "payload & more" in out


def test_extract_zip_without_docx_rejected():
    import io
    import zipfile

    import pytest

    from tfidf_tpu.ops.analyzer import UnsupportedMediaType

    buf = io.BytesIO()
    with zipfile.ZipFile(buf, "w") as z:
        z.writestr("whatever.bin", b"\x00\x01")
    with pytest.raises(UnsupportedMediaType):
        extract_text(buf.getvalue())


def test_valid_utf8_binary_rejected():
    """NUL-padded archives are valid UTF-8 — the density gate must run
    on every decode branch, not just the latin-1 fallback."""
    import pytest

    from tfidf_tpu.ops.analyzer import UnsupportedMediaType

    tarish = b"some/path\x00" + b"\x00" * 500 + b"0000644\x00ustar"
    with pytest.raises(UnsupportedMediaType):
        extract_text(tarish)
    # lossy client-side decodes surface as U+FFFD runs — same verdict
    with pytest.raises(UnsupportedMediaType):
        extract_text(("�" * 300 + "PNG data").encode("utf-8"))


def test_plain_text_mentioning_html_not_stripped():
    txt = ("wrap the page in an <html> element and a <body> tag; "
           "generics like List<int> must survive too").encode()
    out = extract_text(txt)
    assert "<html>" in out and "List<int>" in out


def test_extract_html():
    html = (b"<!DOCTYPE html><html><head><style>p{color:red}</style>"
            b"<script>var x=1;</script></head>"
            b"<body><p>visible &lt;text&gt; here</p></body></html>")
    out = extract_text(html)
    assert "visible" in out and "<text>" in out
    assert "color" not in out and "var x" not in out


def test_extract_rtf():
    """RTF body text extracts; tables/metadata destinations are
    dropped; \\uN unicode and \\'xx cp1252 escapes decode (Tika
    RTFParser analog, Worker.java:198-212)."""
    rtf = (rb"{\rtf1\ansi{\fonttbl{\f0 Times New Roman;}}"
           rb"{\info{\author Secret Name}}"
           rb"{\*\themedata deadbeef}"
           rb"\f0\fs24 Plain rtf body text\par "
           rb"with \'e9clair and \emdash dashes.\par}")
    out = extract_text(rtf)
    assert "Plain rtf body text" in out
    assert "\xe9clair" in out            # \'e9 -> cp1252 e-acute
    assert "—" in out               # \emdash
    assert "Times" not in out          # fonttbl dropped
    assert "Secret" not in out         # info dropped
    assert "deadbeef" not in out       # \* optional destination dropped


def test_extract_rtf_empty_rejected():
    import pytest

    from tfidf_tpu.ops.analyzer import UnsupportedMediaType

    with pytest.raises(UnsupportedMediaType):
        extract_text(rb"{\rtf1{\fonttbl{\f0 Arial;}}}")


def test_extract_pptx():
    """PPTX slides + notes: DrawingML <a:t> runs, slide order kept
    (ISSUE 3 satellite — closes VERDICT r5 Missing #2's cheap half)."""
    import io
    import zipfile

    buf = io.BytesIO()
    slide1 = ('<p:sld><p:txBody><a:p><a:r><a:t>Quarterly results'
              '</a:t></a:r><a:r><a:t xml:space="preserve"> '
              'Q&amp;A session</a:t></a:r></a:p></p:txBody></p:sld>')
    slide2 = ('<p:sld><a:p><a:r><a:t>second slide body</a:t></a:r>'
              '</a:p></p:sld>')
    notes = ('<p:notes><a:p><a:r><a:t>speaker notes here</a:t></a:r>'
             '</a:p></p:notes>')
    slide10 = ('<p:sld><a:p><a:r><a:t>tenth slide tail</a:t></a:r>'
               '</a:p></p:sld>')
    with zipfile.ZipFile(buf, "w") as z:
        z.writestr("[Content_Types].xml", "<Types/>")
        z.writestr("ppt/slides/slide10.xml", slide10)
        z.writestr("ppt/slides/slide1.xml", slide1)
        z.writestr("ppt/slides/slide2.xml", slide2)
        z.writestr("ppt/notesSlides/notesSlide1.xml", notes)
        z.writestr("ppt/media/image1.png", b"\x89PNG\x00")
    out = extract_text(buf.getvalue())
    assert "Quarterly results" in out and "Q&A session" in out
    assert "second slide body" in out and "speaker notes here" in out
    # NUMERIC slide order (1, 2, 10 — not the lexicographic 1, 10, 2),
    # slide bodies before speaker notes
    assert (out.index("Quarterly results") < out.index("second slide")
            < out.index("tenth slide tail")
            < out.index("speaker notes here"))


def test_extract_pptx_without_text_rejected():
    import io
    import zipfile

    import pytest

    from tfidf_tpu.ops.analyzer import UnsupportedMediaType

    buf = io.BytesIO()
    with zipfile.ZipFile(buf, "w") as z:
        z.writestr("ppt/slides/slide1.xml", "<p:sld></p:sld>")
    with pytest.raises(UnsupportedMediaType):
        extract_text(buf.getvalue())


def test_extract_xlsx_shared_and_inline_strings():
    import io
    import zipfile

    buf = io.BytesIO()
    shared = ('<sst count="2"><si><t>Revenue by region</t></si>'
              '<si><r><t>EMEA&amp;APAC</t></r></si></sst>')
    sheet = ('<worksheet><sheetData>'
             '<row><c r="A1" t="s"><v>0</v></c>'
             '<c r="B1"><v>1234</v></c>'
             '<c r="C1" t="inlineStr"><is><t>inline cell note</t></is>'
             '</c></row></sheetData></worksheet>')
    with zipfile.ZipFile(buf, "w") as z:
        z.writestr("xl/workbook.xml", "<workbook/>")
        z.writestr("xl/sharedStrings.xml", shared)
        z.writestr("xl/worksheets/sheet1.xml", sheet)
    out = extract_text(buf.getvalue())
    assert "Revenue by region" in out and "EMEA&APAC" in out
    assert "inline cell note" in out
    assert "1234" not in out   # numeric cells carry no searchable text


def test_extract_xlsx_numbers_only_rejected():
    """A workbook with no string cells has no searchable text — 415,
    never mojibake/empty indexing."""
    import io
    import zipfile

    import pytest

    from tfidf_tpu.ops.analyzer import UnsupportedMediaType

    buf = io.BytesIO()
    sheet = ('<worksheet><sheetData><row><c r="A1"><v>42</v></c></row>'
             '</sheetData></worksheet>')
    with zipfile.ZipFile(buf, "w") as z:
        z.writestr("xl/workbook.xml", "<workbook/>")
        z.writestr("xl/worksheets/sheet1.xml", sheet)
    with pytest.raises(UnsupportedMediaType):
        extract_text(buf.getvalue())


def test_extract_odt():
    import io
    import zipfile

    buf = io.BytesIO()
    content = (b'<?xml version="1.0"?><office:document-content>'
               b"<office:body><office:text>"
               b"<text:p>Odt paragraph one</text:p>"
               b"<text:p>And&amp;two<text:tab/>tabbed</text:p>"
               b"</office:text></office:body>"
               b"</office:document-content>")
    with zipfile.ZipFile(buf, "w") as z:
        z.writestr("mimetype", "application/vnd.oasis.opendocument.text")
        z.writestr("content.xml", content)
    out = extract_text(buf.getvalue())
    assert "Odt paragraph one" in out
    assert "And&two" in out and "tabbed" in out


def test_zip_without_known_content_rejected():
    import io
    import zipfile

    import pytest

    from tfidf_tpu.ops.analyzer import UnsupportedMediaType

    buf = io.BytesIO()
    with zipfile.ZipFile(buf, "w") as z:
        z.writestr("random.bin", b"\x00" * 64)
    with pytest.raises(UnsupportedMediaType):
        extract_text(buf.getvalue())


def test_rtf_uc_skip_does_not_leak_from_skipped_group():
    out = extract_text(rb"{\rtf1{\info\u233 e}body text}")
    assert "body text" in out


def test_rtf_surrogate_pairs_combine_lone_drop():
    # Word writes non-BMP chars as surrogate-pair \uN escapes
    out = extract_text(rb"{\rtf1 hi \u-10179 ?\u-9089 ? end}")
    assert "\U0001f47f" in out          # combined astral char
    out.encode("utf-8")                 # must be UTF-8-serializable
    out2 = extract_text(rb"{\rtf1 lone \u-10179 ? end}")
    assert "lone" in out2 and "end" in out2
    out2.encode("utf-8")                # lone surrogate dropped


def test_rtf_bin_payload_cannot_corrupt_group_stack():
    payload = bytes([0x7D, 0x7B]) * 5   # braces inside raw binary
    rtf = (rb"{\rtf1{\pict\bin10 " + payload
           + rb"} visible body\par}")
    out = extract_text(rtf)
    assert "visible body" in out
    assert "\x7d\x7b" not in out


def test_empty_odt_rejected():
    import io
    import zipfile

    import pytest

    from tfidf_tpu.ops.analyzer import UnsupportedMediaType

    buf = io.BytesIO()
    with zipfile.ZipFile(buf, "w") as z:
        z.writestr("content.xml",
                   b"<office:body><office:text></office:text>"
                   b"</office:body>")
    with pytest.raises(UnsupportedMediaType):
        extract_text(buf.getvalue())


# ---- legacy .doc (OLE2 / Word 97-2003) extraction (VERDICT r4 #8) ----

def _make_cfb_doc(pieces):
    """Spec-following minimal [MS-CFB]+[MS-DOC] writer: a WordDocument
    stream (regular FAT chain, >4096B) + a 1Table stream holding the CLX
    piece table (mini stream, <4096B). ``pieces`` is a list of
    (text, compressed) tuples."""
    import struct as st

    SEC = 512
    # -- WordDocument stream: FIB + text pieces --
    fib = bytearray(0x600)
    st.pack_into("<H", fib, 0, 0xA5EC)        # wIdent
    st.pack_into("<H", fib, 2, 0x00C1)        # nFib (Word 97)
    st.pack_into("<H", fib, 0x0A, 0x0200)     # fWhichTblStm -> 1Table
    word = bytearray(fib)
    cps = [0]
    pcds = []
    for text, compressed in pieces:
        off = len(word)
        if compressed:
            raw = text.encode("cp1252")
            fc = (off * 2) | 0x40000000
        else:
            raw = text.encode("utf-16-le")
            fc = off
        word.extend(raw)
        cps.append(cps[-1] + len(text))
        pcds.append(st.pack("<HIH", 0, fc, 0))
    # CLX: one Prc block (must be skipped) + Pcdt
    plc = b"".join(st.pack("<I", cp) for cp in cps) + b"".join(pcds)
    clx = b"\x01" + st.pack("<H", 4) + b"\xde\xad\xbe\xef" \
        + b"\x02" + st.pack("<I", len(plc)) + plc
    fc_clx = 16
    table = b"\x00" * fc_clx + clx
    st.pack_into("<I", word, 0x01A2, fc_clx)
    st.pack_into("<I", word, 0x01A6, len(clx))
    while len(word) < 5120:                    # force the regular chain
        word.extend(b"\x00" * 64)
    word = bytes(word[:5120])

    # -- sector layout: 0 FAT, 1 dir, 2 miniFAT, 3..12 WordDocument,
    #    13 mini-stream data --
    n_word_sec = len(word) // SEC
    mini = bytearray(table)
    while len(mini) % SEC:
        mini.append(0)
    fat = [0xFFFFFFFF] * (SEC // 4)
    fat[0] = 0xFFFFFFFD                        # FAT sector marker
    fat[1] = 0xFFFFFFFE                        # directory: 1 sector
    fat[2] = 0xFFFFFFFE                        # miniFAT: 1 sector
    for i in range(n_word_sec):
        fat[3 + i] = 3 + i + 1 if i < n_word_sec - 1 else 0xFFFFFFFE
    fat[3 + n_word_sec] = 0xFFFFFFFE           # mini stream data
    minifat = [0xFFFFFFFF] * (SEC // 4)
    n_mini = -(-len(table) // 64)
    for i in range(n_mini):
        minifat[i] = i + 1 if i < n_mini - 1 else 0xFFFFFFFE

    def dirent(name, etype, start, size, left=-1, right=-1, child=-1):
        e = bytearray(128)
        nm = name.encode("utf-16-le")
        e[:len(nm)] = nm
        st.pack_into("<H", e, 64, len(nm) + 2)
        e[66] = etype
        e[67] = 1                              # black (valid color)
        st.pack_into("<i", e, 68, left)
        st.pack_into("<i", e, 72, right)
        st.pack_into("<i", e, 76, child)
        st.pack_into("<I", e, 116, start)
        st.pack_into("<Q", e, 120, size)
        return bytes(e)

    # root's child tree: WordDocument (entry 1) with 1Table (entry 2)
    # as its right sibling — readers walk the root child tree only
    directory = (dirent("Root Entry", 5, 3 + n_word_sec, len(mini),
                        child=1)
                 + dirent("WordDocument", 2, 3, len(word), right=2)
                 + dirent("1Table", 2, 0, len(table))
                 + bytes(128))

    header = bytearray(SEC)
    header[:8] = b"\xd0\xcf\x11\xe0\xa1\xb1\x1a\xe1"
    st.pack_into("<H", header, 26, 3)          # minor/major version
    st.pack_into("<H", header, 28, 0xFFFE)     # little-endian
    st.pack_into("<H", header, 30, 9)          # sector shift (512)
    st.pack_into("<H", header, 32, 6)          # mini shift (64)
    st.pack_into("<I", header, 44, 1)          # 1 FAT sector
    st.pack_into("<I", header, 48, 1)          # directory start
    st.pack_into("<I", header, 56, 4096)       # mini cutoff
    st.pack_into("<I", header, 60, 2)          # miniFAT start
    st.pack_into("<I", header, 64, 1)          # 1 miniFAT sector
    st.pack_into("<i", header, 68, -2)         # no DIFAT chain
    difat = [0xFFFFFFFF] * 109
    difat[0] = 0
    st.pack_into("<109I", header, 76, *difat)

    import struct as st2
    body = (b"".join(st2.pack("<I", x) for x in fat)
            + directory
            + b"".join(st2.pack("<I", x) for x in minifat)
            + word + bytes(mini))
    return bytes(header) + body


class TestLegacyDoc:
    PIECES = [("Legacy café fast food document. ", True),
              ("Unicode päärt β piece.", False)]

    def test_doc_extracts_both_piece_kinds(self):
        doc = _make_cfb_doc(self.PIECES)
        text = extract_text(doc)
        for word in ("Legacy", "café", "fast", "food",
                     "päärt", "β", "piece"):
            assert word in text, (word, text)

    def test_ole2_without_worddocument_415s(self):
        doc = _make_cfb_doc(self.PIECES)
        # rename the WordDocument stream: same container, not a .doc
        broken = doc.replace("WordDocument".encode("utf-16-le"),
                             "Workbook\x00\x00\x00\x00".encode(
                                 "utf-16-le"))
        with pytest.raises(UnsupportedMediaType):
            extract_text(broken)

    def test_doc_roundtrip_through_upload_and_search(self, tmp_path):
        from tfidf_tpu.engine.engine import Engine
        from tfidf_tpu.utils.config import Config
        e = Engine(Config(documents_path=str(tmp_path / "docs"),
                          min_doc_capacity=8, min_nnz_capacity=256,
                          min_vocab_capacity=64, query_batch=4,
                          max_query_terms=8))
        e.ingest_bytes("legacy.doc", _make_cfb_doc(self.PIECES),
                       save_to_disk=True)
        e.ingest_text("other.txt", "unrelated words only")
        e.commit()
        hits = e.search("fast food")
        assert [h.name for h in hits][:1] == ["legacy.doc"]

from tfidf_tpu.ops.analyzer import Analyzer, extract_text, make_analyzer


def test_basic_tokens():
    a = Analyzer()
    assert a.tokens("The quick Brown-Fox jumps!") == \
        ["the", "quick", "brown", "fox", "jumps"]


def test_apostrophe_stays_one_token():
    # UAX#29 MidLetter rule, as StandardTokenizer does
    assert Analyzer().tokens("can't won't") == ["can't", "won't"]


def test_numbers_with_separators():
    assert Analyzer().tokens("pi is 3.14 and 1,000 units") == \
        ["pi", "is", "3.14", "and", "1,000", "units"]


def test_no_stopwords_by_default():
    # Lucene 9 StandardAnalyzer() has an EMPTY default stop set
    assert "the" in Analyzer().tokens("the cat")


def test_stopword_filter():
    a = make_analyzer(stopwords=["the", "a"])
    assert a.tokens("the cat sat on a mat") == ["cat", "sat", "on", "mat"]


def test_case_folding_off():
    a = Analyzer(lowercase=False)
    assert a.tokens("Fast Food") == ["Fast", "Food"]


def test_long_token_split_not_dropped():
    a = Analyzer(max_token_length=10)
    toks = a.tokens("x" * 25)
    assert toks == ["x" * 10, "x" * 10, "x" * 5]


def test_counts():
    assert Analyzer().counts("fast food fast") == {"fast": 2, "food": 1}


def test_unicode_tokens():
    assert Analyzer().tokens("café müller") == ["café", "müller"]


def test_extract_utf8():
    assert extract_text("héllo wörld".encode("utf-8")) == "héllo wörld"


def test_extract_latin1_fallback():
    data = "héllo".encode("latin-1")  # invalid as UTF-8
    assert "h" in extract_text(data) and "llo" in extract_text(data)


def test_extract_utf16_bom():
    data = "hello world".encode("utf-16")
    assert extract_text(data) == "hello world"


def test_extract_binary_rejected():
    """Undecodable control-heavy blobs are refused, not indexed as
    mojibake (Tika-parity contract, VERDICT r2 #7)."""
    import pytest

    from tfidf_tpu.ops.analyzer import UnsupportedMediaType

    noise = bytes(range(256)) * 4
    with pytest.raises(UnsupportedMediaType):
        extract_text(noise)


def test_extract_known_binary_magics_rejected():
    import pytest

    from tfidf_tpu.ops.analyzer import UnsupportedMediaType

    for blob in (b"\x7fELF\x02\x01\x01" + b"\x00" * 64,
                 b"\x89PNG\r\n\x1a\n" + b"\x00" * 64,
                 b"\xff\xd8\xff\xe0" + b"\x00" * 64,
                 b"\x1f\x8b\x08\x00" + b"\x00" * 64):
        with pytest.raises(UnsupportedMediaType):
            extract_text(blob)


def _tiny_pdf(text: str) -> bytes:
    stream = f"BT /F1 12 Tf ({text}) Tj ET".encode()
    return (b"%PDF-1.4\n1 0 obj\n<< /Length "
            + str(len(stream)).encode()
            + b" >>\nstream\n" + stream + b"endstream\nendobj\n%%EOF\n")


def test_extract_pdf_text():
    out = extract_text(_tiny_pdf("Searchable PDF content"))
    assert "Searchable PDF content" in out


def test_extract_pdf_flate_and_tj_array():
    import zlib
    inner = b"BT [(Hello) -250 (World)] TJ ET"
    stream = zlib.compress(inner)
    pdf = (b"%PDF-1.4\nstream\n" + stream + b"endstream\n%%EOF")
    out = extract_text(pdf)
    assert "Hello" in out and "World" in out


def test_extract_pdf_without_text_rejected():
    import pytest

    from tfidf_tpu.ops.analyzer import UnsupportedMediaType

    with pytest.raises(UnsupportedMediaType):
        extract_text(b"%PDF-1.4\nno streams here\n%%EOF")


def test_extract_docx():
    import io
    import zipfile

    buf = io.BytesIO()
    xml = ('<?xml version="1.0"?><w:document><w:body><w:p>'
           '<w:r><w:t>word processor</w:t></w:r>'
           '<w:r><w:t xml:space="preserve"> payload &amp; more</w:t>'
           '</w:r></w:p></w:body></w:document>')
    with zipfile.ZipFile(buf, "w") as z:
        z.writestr("word/document.xml", xml)
    out = extract_text(buf.getvalue())
    assert "word processor" in out and "payload & more" in out


def test_extract_zip_without_docx_rejected():
    import io
    import zipfile

    import pytest

    from tfidf_tpu.ops.analyzer import UnsupportedMediaType

    buf = io.BytesIO()
    with zipfile.ZipFile(buf, "w") as z:
        z.writestr("whatever.bin", b"\x00\x01")
    with pytest.raises(UnsupportedMediaType):
        extract_text(buf.getvalue())


def test_valid_utf8_binary_rejected():
    """NUL-padded archives are valid UTF-8 — the density gate must run
    on every decode branch, not just the latin-1 fallback."""
    import pytest

    from tfidf_tpu.ops.analyzer import UnsupportedMediaType

    tarish = b"some/path\x00" + b"\x00" * 500 + b"0000644\x00ustar"
    with pytest.raises(UnsupportedMediaType):
        extract_text(tarish)
    # lossy client-side decodes surface as U+FFFD runs — same verdict
    with pytest.raises(UnsupportedMediaType):
        extract_text(("�" * 300 + "PNG data").encode("utf-8"))


def test_plain_text_mentioning_html_not_stripped():
    txt = ("wrap the page in an <html> element and a <body> tag; "
           "generics like List<int> must survive too").encode()
    out = extract_text(txt)
    assert "<html>" in out and "List<int>" in out


def test_extract_html():
    html = (b"<!DOCTYPE html><html><head><style>p{color:red}</style>"
            b"<script>var x=1;</script></head>"
            b"<body><p>visible &lt;text&gt; here</p></body></html>")
    out = extract_text(html)
    assert "visible" in out and "<text>" in out
    assert "color" not in out and "var x" not in out

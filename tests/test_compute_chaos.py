"""Compute-plane chaos suite (ISSUE 20).

Covers the device nemesis at the JAX dispatch seam
(utils/device_nemesis.py), the structured compute-fault classifier
(cluster/resilience.classify_compute_fault), the per-worker
ComputeHealth state machine + host-fallback degraded scoring
(engine/compute_health.py), the OOM batch-backoff ladder, the
poison-query quarantine (cluster/quarantine.py), and the wire surface
they add (X-Compute-Degraded / X-Compute-Fault / X-Poison-Fingerprints
/ X-Poison-Quarantined, /api/ready, /api/quarantine,
/api/device-nemesis).

The load-bearing gate is TestFallbackParity: the host/numpy fallback
must be BIT-identical to the device scoring path (use_pallas=False —
the XLA reference program the kernels are themselves gated against),
across layouts and models.  A fallback that is merely close would turn
"degraded but exact" into a silent correctness lie.

The `make chaos-compute` leg (slow) drives the full live scenario:
zipfian load over a subprocess fleet with an OOM'd worker, a
slow-wedged worker, and a poison query injected mid-run.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from tfidf_tpu.cluster.coordination import CoordinationCore, LocalCoordination
from tfidf_tpu.cluster.node import SearchNode, http_get, http_post
from tfidf_tpu.cluster.quarantine import PoisonQuarantine, poison_fingerprint
from tfidf_tpu.cluster.resilience import (RpcStatusError,
                                          classify_compute_fault,
                                          is_retryable)
from tfidf_tpu.engine.compute_health import (DEGRADED, HEALTHY, SICK,
                                             ComputeHealth,
                                             HostFallbackScorer)
from tfidf_tpu.engine.engine import Engine
from tfidf_tpu.utils.config import Config
from tfidf_tpu.utils.device_nemesis import (DeviceCompileError,
                                            DeviceNemesis, DeviceOOMError,
                                            DevicePoisonedOutput,
                                            DeviceSickError,
                                            DeviceTransientError,
                                            global_device_nemesis)
from tfidf_tpu.utils.metrics import global_metrics


@pytest.fixture(autouse=True)
def _clean_nemesis():
    """Never let an armed rule or sticky sick mode leak across tests —
    the nemesis is process-global by design (the seams consult one
    singleton), so the suite must tear it down the way a chaos run
    does."""
    global_device_nemesis.clear()
    yield
    global_device_nemesis.clear()


CORPUS = {
    "file1.txt": "fast food is fast and cheap",
    "file2.txt": "the cat meowing at night causes trouble",
    "file3.txt": "fast cars go very fast on the road",
    "file4.txt": "cheap food for the cat",
    "file5.txt": "night driving in fast cars",
    "file6.txt": "road food at night is cheap and fast",
}

QUERIES = ["fast food", "cat", "night road", "cheap", "meowing trouble",
           "driving cars fast", "zebra"]


def make_engine(tmp_path, **kw):
    kw.setdefault("use_pallas", False)   # XLA reference path: the
    # program the host mirror is pinned bit-equal to (the Pallas
    # kernels are tolerance-gated against this same reference)
    cfg = Config(documents_path=str(tmp_path / "docs"),
                 index_path=str(tmp_path / "index"),
                 min_nnz_capacity=64, min_doc_capacity=8,
                 min_vocab_capacity=64, query_batch=8,
                 max_query_terms=8, **kw)
    e = Engine(cfg)
    for name, text in CORPUS.items():
        e.ingest_text(name, text)
    e.commit()
    return e


def _post_full(base, path, data, timeout=30.0):
    req = urllib.request.Request(
        base + path, data=data,
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, dict(r.headers), r.read()
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), e.read()


def _get_full(base, path, timeout=30.0):
    try:
        with urllib.request.urlopen(base + path, timeout=timeout) as r:
            return r.status, dict(r.headers), r.read()
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), e.read()


# ---------------------------------------------------------------------------
# device nemesis mechanics
# ---------------------------------------------------------------------------

class TestDeviceNemesis:
    def test_env_format_script_grammar(self):
        n = DeviceNemesis(
            env="score_ell:oom:1.0:min_batch=4,*:delay::delay_s=0.0")
        snap = n.snapshot()
        assert n.armed and not n.sick
        assert [r["kind"] for r in snap["rules"]] == ["oom", "delay"]
        assert snap["rules"][0]["min_batch"] == 4
        assert snap["rules"][1]["site"] == "*"
        # the delay rule sleeps 0s and never raises
        assert n.check("anything") is None
        with pytest.raises(DeviceOOMError):
            n.check("score_ell", batch=4)

    def test_bad_specs_loud(self):
        n = DeviceNemesis(env="")
        with pytest.raises(ValueError):
            n.script("score_ell")              # no kind
        with pytest.raises(ValueError):
            n.script("score_ell:frobnicate")   # unknown kind
        with pytest.raises(ValueError):
            n.script("score_ell:oom:1.0:wat=1")  # unknown option

    def test_glob_sites_and_count_budget(self):
        n = DeviceNemesis(env="")
        n.add_rule("score_*", "transient", count=2)
        with pytest.raises(DeviceTransientError):
            n.check("score_ell")
        with pytest.raises(DeviceTransientError):
            n.check("score_coo")
        # the count budget is spent — the rule goes quiet, not removed
        assert n.check("score_ell") is None
        assert n.snapshot()["rules"][0]["fired"] == 2
        # non-matching site never fired
        n2 = DeviceNemesis(env="")
        n2.add_rule("score_*", "transient")
        assert n2.check("dense") is None

    def test_remove_rule(self):
        n = DeviceNemesis(env="")
        rid = n.add_rule("dense", "compile")
        keep = n.add_rule("dense", "delay", delay_s=0.0)
        assert n.remove_rule(rid) is True
        assert n.remove_rule(rid) is False   # already gone
        assert [r["rid"] for r in n.snapshot()["rules"]] == [keep]
        assert n.check("dense") is None      # compile rule is gone
        assert DeviceNemesis.remove_rule is not None

    def test_sick_is_sticky_until_heal(self):
        n = DeviceNemesis(env="score_ell:sick::count=1")
        with pytest.raises(DeviceSickError):
            n.check("score_ell")
        assert n.sick
        # EVERY seam fails now, count budget notwithstanding
        with pytest.raises(DeviceSickError):
            n.check("dense")
        with pytest.raises(DeviceSickError):
            n.check("upload")
        n.heal()
        assert not n.sick
        assert n.check("dense") is None
        # clear() drops rules AND sick
        n.script("*:sick")
        with pytest.raises(DeviceSickError):
            n.check("score_ell")
        n.clear()
        assert not n.armed and n.check("score_ell") is None

    def test_min_batch_gate(self):
        n = DeviceNemesis(env="")
        n.add_rule("score_ell", "oom", min_batch=8)
        assert n.check("score_ell", batch=4) is None
        with pytest.raises(DeviceOOMError):
            n.check("score_ell", batch=8)

    def test_poison_rule_and_row_targeting(self):
        import jax.numpy as jnp

        from tfidf_tpu.utils.device_nemesis import poison_scores
        n = DeviceNemesis(env="score_ell:poison:1.0:min_uniq=2")
        rule = n.check("score_ell")
        assert rule is not None and rule.kind == "poison"
        scores = jnp.ones((3, 4), jnp.float32)
        weights = jnp.asarray([[1.0, 1.0, 0.0],    # 2 uniq -> poisoned
                               [1.0, 0.0, 0.0],    # 1 uniq -> intact
                               [1.0, 2.0, 3.0]],   # 3 uniq -> poisoned
                              jnp.float32)
        out = np.asarray(poison_scores(scores, weights, rule.min_uniq))
        assert np.isnan(out[0]).all() and np.isnan(out[2]).all()
        assert (out[1] == 1.0).all()
        # min_uniq=0 poisons everything
        out0 = np.asarray(poison_scores(scores, weights, 0))
        assert np.isnan(out0).all()

    def test_fire_emits_metric(self):
        before = global_metrics.snapshot().get("device_nemesis_fired", 0)
        n = DeviceNemesis(env="x:transient")
        with pytest.raises(DeviceTransientError):
            n.check("x")
        assert global_metrics.snapshot()["device_nemesis_fired"] \
            == before + 1


# ---------------------------------------------------------------------------
# structured fault classifier (the string-match retry gate's successor)
# ---------------------------------------------------------------------------

class TestClassifier:
    def test_typed_nemesis_exceptions(self):
        assert classify_compute_fault(DeviceOOMError("x")) == "oom"
        assert classify_compute_fault(DeviceCompileError("x")) == "compile"
        assert classify_compute_fault(
            DeviceTransientError("x")) == "transient"
        assert classify_compute_fault(DeviceSickError("x")) == "transient"
        assert classify_compute_fault(
            DevicePoisonedOutput(("q",))) == "poison"

    def test_xla_runtime_error_message_taxonomy(self):
        # jaxlib buries the class in the message; match by type NAME so
        # the classifier works wherever jaxlib moves the class
        XlaRuntimeError = type("XlaRuntimeError", (Exception,), {})
        assert classify_compute_fault(XlaRuntimeError(
            "RESOURCE_EXHAUSTED: out of memory allocating")) == "oom"
        assert classify_compute_fault(XlaRuntimeError(
            "INTERNAL: remote_compile failed")) == "compile"
        assert classify_compute_fault(XlaRuntimeError(
            "INTERNAL: something else")) == "transient"

    def test_non_device_exceptions_are_none(self):
        assert classify_compute_fault(ValueError("nope")) is None
        assert classify_compute_fault(OSError("disk")) is None

    def test_stamped_rpc_error_carries_worker_verdict(self):
        e = RpcStatusError("http://w/x", 500, compute_fault="oom")
        assert classify_compute_fault(e) == "oom"
        # a compute fault is deterministic on the worker's current
        # state: failover, not retry
        assert not is_retryable(e)
        p = RpcStatusError("http://w/x", 500, compute_fault="poison",
                           poison_fps=("aabbccddeeff",))
        assert classify_compute_fault(p) == "poison"
        assert p.poison_fps == ("aabbccddeeff",)
        assert not is_retryable(p)


# ---------------------------------------------------------------------------
# ComputeHealth state machine
# ---------------------------------------------------------------------------

class FakeClock:
    def __init__(self):
        self.t = 100.0

    def __call__(self):
        return self.t


class TestComputeHealth:
    def test_escalation_and_reset(self):
        h = ComputeHealth(degraded_after=2, sick_after=4)
        assert h.state == HEALTHY
        h.note_fault("transient")
        assert h.state == HEALTHY
        h.note_fault("oom")
        assert h.state == DEGRADED
        h.note_fault("transient")
        assert h.state == DEGRADED
        h.note_fault("transient")
        assert h.state == SICK
        h.note_success()
        assert h.state == HEALTHY and h.consecutive_faults == 0
        snap = h.snapshot()
        assert snap["total_faults"] == 4
        assert snap["faults_by_kind"] == {"transient": 3, "oom": 1}

    def test_poison_never_advances_the_machine(self):
        h = ComputeHealth(degraded_after=1, sick_after=2)
        for _ in range(10):
            h.note_fault("poison")
        assert h.state == HEALTHY
        assert h.snapshot()["total_faults"] == 0

    def test_probe_pacing_rations_one_slot_per_interval(self):
        clk = FakeClock()
        h = ComputeHealth(degraded_after=1, sick_after=2,
                          probe_interval_s=5.0, clock=clk)
        h.note_fault("transient")
        h.note_fault("transient")
        assert h.state == SICK
        # between probes: nobody gets the device
        assert not h.should_try_device()
        clk.t += 5.0
        # exactly ONE caller claims the probe slot per interval
        assert h.should_try_device()
        assert not h.should_try_device()
        assert h.snapshot()["recovery_probes"] == 1
        # a successful probe heals
        h.note_success()
        assert h.state == HEALTHY and h.should_try_device()


# ---------------------------------------------------------------------------
# poison-query quarantine
# ---------------------------------------------------------------------------

class TestPoisonQuarantine:
    def test_replica_distinct_threshold(self):
        q = PoisonQuarantine(after=2)
        fp = poison_fingerprint("bad query")
        # one replica, even repeatedly, is possibly just a sick device
        assert not q.note_fault(fp, "http://w1")
        assert not q.note_fault(fp, "http://w1")
        assert not q.is_quarantined(fp)
        # the second DISTINCT replica is the crossing observation
        assert q.note_fault(fp, "http://w2")
        assert q.is_quarantined(fp)
        # crossing fires once — later blame does not re-announce
        assert not q.note_fault(fp, "http://w3")

    def test_fingerprint_is_query_and_plan_scoped(self):
        assert poison_fingerprint("q", "sparse") \
            != poison_fingerprint("q", "dense")
        assert poison_fingerprint("a") != poison_fingerprint("b")
        assert len(poison_fingerprint("a")) == 12

    def test_ttl_expiry_and_touch_refresh(self):
        clk = FakeClock()
        q = PoisonQuarantine(after=1, ttl_s=10.0, clock=clk)
        fp = poison_fingerprint("doom")
        assert q.note_fault(fp, "w1")
        clk.t += 6.0
        # an admission hit refreshes the verdict (actively re-sent
        # poison must not slip back in by persisting past the TTL)
        assert q.is_quarantined(fp)
        clk.t += 6.0
        assert q.is_quarantined(fp)    # 12s after blame, still warm
        clk.t += 11.0
        assert not q.is_quarantined(fp)   # idle past TTL: expired

    def test_lru_bound(self):
        q = PoisonQuarantine(after=1, max_entries=4)
        fps = [poison_fingerprint(f"q{i}") for i in range(6)]
        for fp in fps:
            q.note_fault(fp, "w1")
        snap = q.snapshot()
        assert snap["tracked"] == 4
        kept = {e["fingerprint"] for e in snap["quarantined"]}
        assert kept == set(fps[2:])    # oldest two evicted

    def test_snapshot_and_clear(self):
        q = PoisonQuarantine(after=2, ttl_s=99.0)
        fp = poison_fingerprint("x")
        q.note_fault(fp, "w1")
        q.note_fault(fp, "w2")
        snap = q.snapshot()
        assert snap["after"] == 2 and snap["tracked"] == 1
        (e,) = snap["quarantined"]
        assert e["fingerprint"] == fp
        assert e["replicas"] == ["w1", "w2"]
        assert q.clear() == 1
        assert q.snapshot()["tracked"] == 0
        assert not q.is_quarantined(fp)


# ---------------------------------------------------------------------------
# host-fallback bit-parity gate
# ---------------------------------------------------------------------------

class TestFallbackParity:
    """The acceptance gate: host scoring bit-compares against the
    device (XLA reference) path — same values, same ids, across
    layouts and models."""

    @pytest.mark.parametrize("layout", ["ell", "coo"])
    @pytest.mark.parametrize("model", ["bm25", "tfidf", "tfidf_cosine"])
    def test_bit_parity_arrays(self, tmp_path, layout, model):
        e = make_engine(tmp_path, scoring_layout=layout, model=model)
        dev_vals, dev_ids, dev_kk, dev_names = \
            e.searcher.search_arrays(QUERIES, k=5)
        fb = HostFallbackScorer(e.searcher)
        h_vals, h_ids, h_kk, h_names = fb.search_arrays(QUERIES, k=5)
        assert h_kk == dev_kk and list(h_names) == list(dev_names)
        # BIT equality, not allclose: the fallback's claim is "exact",
        # and ties must break identically for ids to match
        assert np.asarray(dev_vals).tobytes() == h_vals.tobytes()
        assert np.array_equal(np.asarray(dev_ids), h_ids)

    def test_bit_parity_with_ell_residual_spill(self, tmp_path):
        # a tiny width cap forces long docs to spill into the residual
        # COO pass — the mirror must reproduce BOTH planes bit-exactly
        e = make_engine(tmp_path, scoring_layout="ell", ell_width_cap=4)
        snap = e.index.snapshot
        assert snap.res_tf is not None, "no residual spill — test inert"
        dev = e.searcher.search_arrays(QUERIES, k=5)
        host = HostFallbackScorer(e.searcher).search_arrays(QUERIES, k=5)
        assert np.asarray(dev[0]).tobytes() == host[0].tobytes()
        assert np.array_equal(np.asarray(dev[1]), host[1])

    def test_bit_parity_assembled_hits_and_unbounded(self, tmp_path):
        e = make_engine(tmp_path)
        fb = HostFallbackScorer(e.searcher)
        for unbounded in (False, True):
            dev = e.searcher.search(QUERIES, k=4, unbounded=unbounded)
            host = fb.search(QUERIES, k=4, unbounded=unbounded)
            assert [[(h.name, h.score) for h in hits] for hits in dev] \
                == [[(h.name, h.score) for h in hits] for hits in host]

    def test_mirror_built_once_per_snapshot(self, tmp_path):
        e = make_engine(tmp_path)
        fb = HostFallbackScorer(e.searcher)
        before = global_metrics.snapshot().get(
            "compute_fallback_mirror_builds", 0)
        fb.search(["fast"])
        fb.search(["cat"])
        assert global_metrics.snapshot()[
            "compute_fallback_mirror_builds"] == before + 1
        # a new commit invalidates the mirror
        e.ingest_text("file7.txt", "brand new cheap cars document")
        e.commit()
        fb.search(["cheap"])
        assert global_metrics.snapshot()[
            "compute_fallback_mirror_builds"] == before + 2


# ---------------------------------------------------------------------------
# the engine's compute guard: degradation, ladder, poison honesty
# ---------------------------------------------------------------------------

class TestEngineComputeGuard:
    def test_fault_degrades_to_exact_host_serving(self, tmp_path):
        e = make_engine(tmp_path, compute_sick_after=2,
                        compute_probe_interval_s=3600.0)
        baseline = e.search_batch(QUERIES, k=4)
        assert not e.pop_fallback_served()
        global_device_nemesis.script("score_ell:transient")
        for _ in range(3):
            got = e.search_batch(QUERIES, k=4)
            # exact, not approximate — bit-identical hit lists
            assert [[(h.name, h.score) for h in hs] for hs in got] \
                == [[(h.name, h.score) for h in hs] for hs in baseline]
            assert e.pop_fallback_served()
        stats = e.compute_stats()
        assert stats["state"] == SICK
        assert stats["fallback_available"] is True
        # sick: the device is no longer even tried (probe interval is
        # an hour) — the nemesis would raise if it were
        assert global_metrics.snapshot()["compute_fallback_served"] > 0

    def test_recovery_probe_heals(self, tmp_path):
        e = make_engine(tmp_path, compute_degraded_after=1,
                        compute_sick_after=1,
                        compute_probe_interval_s=0.0)
        baseline = e.search_batch(["fast food"], k=3)
        rid = global_device_nemesis.add_rule("score_ell", "transient")
        e.search_batch(["fast food"], k=3)
        assert e.compute_stats()["state"] == SICK
        assert e.pop_fallback_served()
        # device fixed; the next request claims the probe slot
        # (interval 0), runs the device path, and heals the machine
        global_device_nemesis.remove_rule(rid)
        got = e.search_batch(["fast food"], k=3)
        assert [[(h.name, h.score) for h in hs] for hs in got] \
            == [[(h.name, h.score) for h in hs] for hs in baseline]
        assert not e.pop_fallback_served()
        assert e.compute_stats()["state"] == HEALTHY
        assert e.compute_stats()["recovery_probes"] >= 1

    def test_oom_ladder_retries_smaller_batches(self, tmp_path):
        e = make_engine(tmp_path, oom_backoff_min_batch=1)
        qs = QUERIES + ["food cheap"]          # 8 queries -> cap 8
        baseline = e.search_batch(qs, k=4)
        before = global_metrics.snapshot().get("compute_oom_backoff", 0)
        # OOM fires only at batch cap >= 8: the full batch dies, the
        # B/2 rungs (cap 4) succeed
        global_device_nemesis.script("score_ell:oom:1.0:min_batch=8")
        got = e.search_batch(qs, k=4)
        assert [[(h.name, h.score) for h in hs] for hs in got] \
            == [[(h.name, h.score) for h in hs] for hs in baseline]
        assert global_metrics.snapshot()["compute_oom_backoff"] \
            == before + 1
        # the ladder succeeded on device: no fallback involved, and
        # the recovery reset health
        assert not e.pop_fallback_served()
        assert e.compute_stats()["state"] == HEALTHY

    def test_oom_floor_degrades_to_fallback(self, tmp_path):
        e = make_engine(tmp_path, oom_backoff_min_batch=8)
        qs = QUERIES + ["food cheap"]
        baseline = e.search_batch(qs, k=4)
        # every rung >= the floor OOMs -> the ladder dries out and the
        # host mirror serves
        global_device_nemesis.script("score_ell:oom")
        got = e.search_batch(qs, k=4)
        assert [[(h.name, h.score) for h in hs] for hs in got] \
            == [[(h.name, h.score) for h in hs] for hs in baseline]
        assert e.pop_fallback_served()

    def test_poison_is_never_absorbed(self, tmp_path):
        e = make_engine(tmp_path)
        # rows with >= 4 distinct terms are poisoned; the cohort is not
        global_device_nemesis.script("score_ell:poison:1.0:min_uniq=4")
        poison_q = "fast food cheap night"
        with pytest.raises(DevicePoisonedOutput) as ei:
            e.search_batch(["cat", poison_q], k=4)
        # per-query blame: only the offending row is named
        assert ei.value.queries == (poison_q,)
        # a fallback exists, but poison must surface, not degrade
        assert not e.pop_fallback_served()
        # and the health machine did not move (query problem, not a
        # sick device)
        assert e.compute_stats()["state"] == HEALTHY
        assert global_metrics.snapshot()["compute_poison_outputs"] >= 1
        # innocent queries alone still serve on device
        assert e.search_batch(["cat"], k=4)[0]

    def test_fallback_disabled_faults_surface(self, tmp_path):
        e = make_engine(tmp_path, compute_fallback=False)
        global_device_nemesis.script("score_ell:transient")
        with pytest.raises(DeviceTransientError):
            e.search_batch(["fast"], k=3)
        assert e.compute_stats()["fallback_available"] is False

    def test_dense_plane_poison_detected(self, tmp_path):
        import jax.numpy as jnp

        from tfidf_tpu.ops.dense import dense_scores
        q = jnp.ones((2, 4), jnp.float32)
        emb = jnp.ones((3, 4), jnp.float32)
        n = jnp.int32(3)
        clean = np.asarray(dense_scores(q, emb, n))
        assert np.isfinite(clean).all()
        global_device_nemesis.script("dense:poison")
        assert np.isnan(np.asarray(dense_scores(q, emb, n))).all()


# ---------------------------------------------------------------------------
# ops surface on a live node
# ---------------------------------------------------------------------------

@pytest.fixture
def core():
    c = CoordinationCore(session_timeout_s=0.5)
    yield c
    c.close()


def _node_cfg(tmp_path, tag, **kw):
    return Config(documents_path=str(tmp_path / tag / "docs"),
                  index_path=str(tmp_path / tag / "index"),
                  port=0, min_doc_capacity=64,
                  min_nnz_capacity=1 << 12, min_vocab_capacity=1 << 10,
                  query_batch=8, max_query_terms=8, use_pallas=False,
                  **kw)


class TestOpsSurface:
    def test_ready_health_and_quarantine_endpoints(self, core, tmp_path):
        node = SearchNode(
            _node_cfg(tmp_path, "ops", compute_fallback=False,
                      compute_sick_after=2,
                      compute_probe_interval_s=3600.0),
            coord=LocalCoordination(core, 0.1)).start()
        try:
            # healthy: ready, and /api/health carries the compute block
            st, _, body = _get_full(node.url, "/api/ready")
            assert st == 200 and json.loads(body)["ready"] is True
            h = json.loads(http_get(node.url + "/api/health"))
            assert h["compute"]["state"] == HEALTHY
            assert h["compute"]["fallback_available"] is False
            # sick WITHOUT a fallback: not ready (the k8s
            # readinessProbe takes the pod out of Service endpoints),
            # but /api/health still answers — never a liveness failure
            node.engine.compute.note_fault("transient")
            node.engine.compute.note_fault("transient")
            st, hd, body = _get_full(node.url, "/api/ready")
            assert st == 503
            assert hd.get("Retry-After") == "1"
            assert json.loads(body)["ready"] is False
            assert json.loads(http_get(
                node.url + "/api/health"))["compute"]["state"] == SICK
            # recovery restores readiness
            node.engine.compute.note_success()
            st, _, _b = _get_full(node.url, "/api/ready")
            assert st == 200

            # quarantine: GET snapshot + POST clear
            snap = json.loads(http_get(node.url + "/api/quarantine"))
            assert snap["tracked"] == 0
            fp = poison_fingerprint("doom query")
            node.quarantine.note_fault(fp, "http://w1")
            node.quarantine.note_fault(fp, "http://w2")
            snap = json.loads(http_get(node.url + "/api/quarantine"))
            assert [e["fingerprint"]
                    for e in snap["quarantined"]] == [fp]
            got = json.loads(http_post(node.url + "/api/quarantine",
                                       b"{}"))
            assert got == {"cleared": 1}
        finally:
            node.stop()

    def test_sick_with_fallback_stays_ready(self, core, tmp_path):
        node = SearchNode(
            _node_cfg(tmp_path, "rdy", compute_degraded_after=1,
                      compute_sick_after=1,
                      compute_probe_interval_s=3600.0),
            coord=LocalCoordination(core, 0.1)).start()
        try:
            node.engine.compute.note_fault("oom")
            assert node.engine.compute_stats()["state"] == SICK
            # degraded (host-fallback) serving is slower but exact:
            # the pod must STAY in the Service endpoints
            st, _, body = _get_full(node.url, "/api/ready")
            assert st == 200 and json.loads(body)["ready"] is True
        finally:
            node.stop()

    def test_device_nemesis_endpoint_gated_and_scriptable(
            self, core, tmp_path):
        off = SearchNode(_node_cfg(tmp_path, "off"),
                         coord=LocalCoordination(core, 0.1)).start()
        try:
            st, _, _b = _get_full(off.url, "/api/device-nemesis")
            assert st == 403
            st, _, _b = _post_full(off.url, "/api/device-nemesis",
                                   b'{"script": "score_ell:oom"}')
            assert st == 403
            assert not global_device_nemesis.armed   # gate held
        finally:
            off.stop()
        on = SearchNode(_node_cfg(tmp_path, "on",
                                  device_nemesis_api=True),
                        coord=LocalCoordination(core, 0.1)).start()
        try:
            st, _, body = _post_full(
                on.url, "/api/device-nemesis",
                b'{"script": "score_ell:transient::count=1"}')
            assert st == 200
            got = json.loads(body)
            assert got["armed"] is True and len(got["rules"]) == 1
            snap = json.loads(http_get(on.url + "/api/device-nemesis"))
            assert snap["rules"][0]["site"] == "score_ell"
            st, _, body = _post_full(on.url, "/api/device-nemesis",
                                     b'{"clear": true}')
            assert json.loads(body)["armed"] is False
            assert not global_device_nemesis.armed
        finally:
            on.stop()

    def test_cli_status_and_quarantine_commands(self, core, tmp_path,
                                                capsys):
        from tfidf_tpu.cli import main as cli_main
        node = SearchNode(_node_cfg(tmp_path, "cli"),
                          coord=LocalCoordination(core, 0.1)).start()
        try:
            assert cli_main(["status", "--leader", node.url]) == 0
            out = json.loads(capsys.readouterr().out)
            assert out["compute"]["sick_nodes"] == []
            assert "fallback_served_total" in out["compute"]

            fp = poison_fingerprint("cli doom")
            node.quarantine.note_fault(fp, "w1")
            node.quarantine.note_fault(fp, "w2")
            assert cli_main(["quarantine", node.url]) == 0
            snap = json.loads(capsys.readouterr().out)
            assert [e["fingerprint"]
                    for e in snap["quarantined"]] == [fp]
            assert cli_main(["quarantine", node.url, "--clear"]) == 0
            assert json.loads(capsys.readouterr().out) \
                == {"cleared": 1}
        finally:
            node.stop()


# ---------------------------------------------------------------------------
# cluster end-to-end: degraded stamps + quarantine at the front door
# ---------------------------------------------------------------------------

@pytest.fixture
def compute_cluster(core, tmp_path):
    """Leader + two workers, single-copy placement, tuned for fast
    compute-health transitions."""
    nodes = []
    for i in range(3):
        cfg = _node_cfg(tmp_path, f"cc{i}", replication_factor=1,
                        result_order="name",
                        # no result cache: every request must actually
                        # scatter, or the degraded stamp (a per-scatter
                        # verdict) would vanish behind cache hits
                        result_cache_entries=0,
                        router_cache_entries=0,
                        compute_sick_after=2,
                        compute_probe_interval_s=3600.0,
                        poison_quarantine_after=2)
        node = SearchNode(cfg, coord=LocalCoordination(core, 0.1))
        node.start()
        nodes.append(node)
    deadline = time.monotonic() + 10.0
    while time.monotonic() < deadline and len(
            nodes[0].registry.get_all_service_addresses()) < 2:
        time.sleep(0.02)
    yield nodes
    for n in nodes:
        try:
            n.stop()
        except Exception:
            pass


POISON_Q = "alpha beta"   # 2 distinct terms, present on EVERY shard


class TestClusterComputePlane:
    def _upload(self, leader):
        docs = [{"name": n, "text": t} for n, t in CORPUS.items()]
        http_post(leader.url + "/leader/upload-batch",
                  json.dumps(docs).encode())

    def _upload_poison_corpus(self, leader):
        # every doc carries BOTH poison terms, so every worker's shard
        # vocabulary sees 2 distinct query terms for POISON_Q — the
        # min_uniq row filter must fire on every replica, not just the
        # one that happened to receive the rare terms
        docs = [{"name": f"p{i}.txt", "text": f"alpha beta tok{i}"}
                for i in range(6)]
        http_post(leader.url + "/leader/upload-batch",
                  json.dumps(docs).encode())

    def test_degraded_worker_stamps_end_to_end(self, compute_cluster):
        leader, w1, w2 = compute_cluster
        self._upload(leader)
        st, hd, body = _post_full(leader.url, "/leader/start",
                                  json.dumps({"query": "fast"}).encode())
        assert st == 200 and "X-Compute-Degraded" not in hd
        baseline = json.loads(body)
        assert baseline
        # wedge ONE worker's device sick (direct state injection — the
        # nemesis is process-global and would hit every in-process
        # node): its share now serves from the host mirror
        w1.engine.compute.note_fault("transient")
        w1.engine.compute.note_fault("transient")
        st, hd, body = _post_full(leader.url, "/leader/start",
                                  json.dumps({"query": "fast"}).encode())
        assert st == 200
        assert hd.get("X-Compute-Degraded") == "1"   # one worker
        # exact, not approximate: same merged scores as the baseline
        assert json.loads(body) == baseline
        # the worker recovers -> the stamp disappears
        w1.engine.compute.note_success()
        st, hd, body = _post_full(leader.url, "/leader/start",
                                  json.dumps({"query": "fast"}).encode())
        assert st == 200 and "X-Compute-Degraded" not in hd
        assert json.loads(body) == baseline

    def test_poison_quarantine_front_door_422(self, compute_cluster):
        leader, w1, w2 = compute_cluster
        self._upload_poison_corpus(leader)
        fp = poison_fingerprint(POISON_Q, "sparse")
        # poison rows with >= 2 distinct terms on every worker device
        # (process-global nemesis; the leader scatters, it does not
        # score) — normal 1-term queries are untouched cohorts
        global_device_nemesis.script("score_ell:poison:1.0:min_uniq=2")
        # first send: both workers return 500 + X-Poison-Fingerprints;
        # two DISTINCT replicas blame the fingerprint -> quarantined
        st, hd, body = _post_full(
            leader.url, "/leader/start",
            json.dumps({"query": POISON_Q}).encode())
        snap = json.loads(http_get(leader.url + "/api/quarantine"))
        assert [e["fingerprint"] for e in snap["quarantined"]] == [fp]
        assert len(snap["quarantined"][0]["replicas"]) == 2
        # second send: refused at the front door, no worker touched
        st, hd, body = _post_full(
            leader.url, "/leader/start",
            json.dumps({"query": POISON_Q}).encode())
        assert st == 422
        assert hd.get("X-Poison-Quarantined") == fp
        got = json.loads(body)
        assert got["fingerprint"] == fp and got["retry_after_s"] > 0
        # a 422 is the never-retried application-rejection class
        assert not is_retryable(RpcStatusError("u", 422))
        # poison is a QUERY verdict: innocent queries still serve, on
        # device, from the same workers
        st, hd, body = _post_full(leader.url, "/leader/start",
                                  json.dumps({"query": "tok1"}).encode())
        assert st == 200 and json.loads(body)
        assert "X-Compute-Degraded" not in hd
        assert w1.engine.compute_stats()["state"] == HEALTHY
        assert w2.engine.compute_stats()["state"] == HEALTHY
        # operator override: clear -> admitted again
        global_device_nemesis.clear()
        assert json.loads(http_post(
            leader.url + "/api/quarantine", b"{}"))["cleared"] == 1
        st, _, body = _post_full(
            leader.url, "/leader/start",
            json.dumps({"query": POISON_Q}).encode())
        assert st == 200 and json.loads(body)


# ---------------------------------------------------------------------------
# the live chaos leg: `make chaos-compute`
# ---------------------------------------------------------------------------

@pytest.mark.slow
class TestChaosCompute:
    @pytest.mark.timeout(420)
    def test_oom_wedge_poison_quarantine_recovery(self, tmp_path):
        """``make chaos-compute``: zipfian-ish closed-loop load over a
        subprocess fleet (leader + 3 workers, R=2). Mid-run one worker
        is OOM'd (every dispatch), another is slow-wedged (dispatch
        delay), and a poison query is injected. Every 200 must be
        exact-parity-or-honestly-stamped, no acked write is ever lost,
        the quarantine engages after exactly two distinct replicas
        blame the poison fingerprint (the third poisoned worker is
        never touched by it again), and after the nemeses clear the
        fleet converges back to exact, unmarked device serving."""
        import os
        import signal  # noqa: F401  (parity with sibling chaos jobs)
        import socket
        import subprocess
        import sys

        def free_port():
            with socket.socket() as s:
                s.bind(("127.0.0.1", 0))
                return s.getsockname()[1]

        env = os.environ.copy()
        env["JAX_PLATFORMS"] = "cpu"
        env["TFIDF_JAX_PLATFORM"] = "cpu"
        env.pop("XLA_FLAGS", None)
        env.pop("TFIDF_DEVICE_NEMESIS", None)
        env.update({
            "TFIDF_REPLICATION_FACTOR": "2",
            "TFIDF_TOP_K": "32",
            "TFIDF_USE_PALLAS": "false",
            "TFIDF_SESSION_TIMEOUT_S": "2.0",
            "TFIDF_HEARTBEAT_INTERVAL_S": "0.3",
            "TFIDF_MIN_DOC_CAPACITY": "64",
            "TFIDF_MIN_NNZ_CAPACITY": "4096",
            "TFIDF_MIN_VOCAB_CAPACITY": "1024",
            "TFIDF_QUERY_BATCH": "8",
            "TFIDF_MAX_QUERY_TERMS": "8",
            "TFIDF_DEVICE_NEMESIS_API": "1",
            "TFIDF_COMPUTE_SICK_AFTER": "3",
            "TFIDF_COMPUTE_PROBE_INTERVAL_S": "0.5",
            "TFIDF_POISON_QUARANTINE_AFTER": "2",
            "TFIDF_OOM_BACKOFF_MIN_BATCH": "8",
            # no result caches: every reply must reflect a live
            # scatter, or cache hits would hide the degraded stamps
            # this scenario asserts on
            "TFIDF_RESULT_CACHE_ENTRIES": "0",
            "TFIDF_ROUTER_CACHE_ENTRIES": "0",
        })
        procs = {}

        def spawn(tag, args):
            p = subprocess.Popen(
                [sys.executable, "-m", "tfidf_tpu", *args],
                env=env, stdout=subprocess.DEVNULL,
                stderr=subprocess.DEVNULL)
            procs[tag] = p
            return p

        def wait_pred(pred, timeout=120.0, interval=0.2):
            deadline = time.monotonic() + timeout
            last = None
            while time.monotonic() < deadline:
                try:
                    if pred():
                        return True
                except Exception as e:
                    last = e
                time.sleep(interval)
            raise AssertionError(f"timed out; last={last!r}")

        coord_port = free_port()
        try:
            spawn("coord", ["coordinator", "--listen",
                            f"127.0.0.1:{coord_port}"])
            wait_pred(lambda: socket.create_connection(
                ("127.0.0.1", coord_port), timeout=1.0).close() or True,
                timeout=60)
            nports = [free_port() for _ in range(4)]
            nurls = [f"http://127.0.0.1:{p}" for p in nports]
            for i, p in enumerate(nports):
                spawn(f"n{i}", [
                    "serve", "--port", str(p), "--host", "127.0.0.1",
                    "--coordinator-address", f"127.0.0.1:{coord_port}",
                    "--documents-path", str(tmp_path / f"ch{i}/docs"),
                    "--index-path", str(tmp_path / f"ch{i}/idx")])
                wait_pred(lambda u=nurls[i]: http_get(
                    u + "/api/status", timeout=5.0))
            leader, workers = nurls[0], nurls[1:]
            wait_pred(lambda: len(json.loads(http_get(
                leader + "/api/services"))) == 3)
            # 24 acked writes; every doc carries "common" so one query
            # enumerates the full corpus (the zero-loss witness)
            docs = {f"ch{i}.txt":
                    f"common token{i} word{i % 3} extra{i % 5}"
                    for i in range(24)}
            resp = json.loads(http_post(
                leader + "/leader/upload-batch",
                json.dumps([{"name": n, "text": t}
                            for n, t in docs.items()]).encode()))
            assert sum(resp["placed"].values()) == 48   # 24 docs x R=2

            # the poison query needs >= 6 distinct POSITIVE-WEIGHT
            # terms in EVERY shard's vocabulary (min_uniq is a
            # per-device row filter over weights>0 — "common" has
            # df=N, idf 0, and would not count): with 24 docs over 3
            # workers every shard holds all of word0-2/extra0-4, while
            # the 1-2 term client queries stay far under the filter
            poison_q = "word0 word1 word2 extra0 extra1 extra2"
            qpool = ["common"] + [f"token{i} word{i % 3}"
                                  for i in range(24)]
            # all-workers-ready barrier, then the exact baseline
            baseline = {}
            for q in qpool + [poison_q]:
                st, hd, body = _post_full(
                    leader, "/leader/start",
                    json.dumps({"query": q}).encode())
                assert st == 200 and "X-Scatter-Degraded" not in hd, \
                    (q, st, hd)
                baseline[q] = json.loads(body)
            assert set(baseline["common"]) == set(docs)   # zero loss

            outcomes = {"exact": 0, "compute_degraded": 0,
                        "degraded": 0, "failed": 0}
            olock = threading.Lock()
            errors: list[str] = []
            stop = threading.Event()

            def client(cid):
                import random
                rng = random.Random(cid)
                while not stop.is_set():
                    q = qpool[int(rng.random() ** 2 * len(qpool))]
                    try:
                        st, hd, body = _post_full(
                            leader, "/leader/start",
                            json.dumps({"query": q}).encode(),
                            timeout=30.0)
                    except Exception:
                        st, hd, body = None, {}, b""
                    if st != 200:
                        verdict = "failed"
                    elif json.loads(body) == baseline[q]:
                        verdict = ("compute_degraded"
                                   if "X-Compute-Degraded" in hd
                                   else "exact")
                    elif "X-Scatter-Degraded" in hd \
                            or "X-Compute-Degraded" in hd:
                        verdict = "degraded"   # honest partials only
                    else:
                        errors.append(
                            f"unmarked non-parity 200 for {q!r}")
                        return
                    with olock:
                        outcomes[verdict] += 1

            threads = [threading.Thread(target=client, args=(c,),
                                        daemon=True) for c in range(4)]
            for t in threads:
                t.start()
            time.sleep(2.0)

            # nemesis 1: every dispatch on w0 OOMs (the ladder dries
            # out at the floor) -> host-fallback degraded serving
            http_post(workers[0] + "/api/device-nemesis",
                      json.dumps({"script": "*:oom"}).encode())
            # nemesis 2: w1 is slow-wedged (200ms per dispatch)
            http_post(workers[1] + "/api/device-nemesis",
                      json.dumps(
                          {"script": "*:delay:1.0:delay_s=0.2"}).encode())
            # the sick worker's share starts riding the host mirror
            wait_pred(lambda: json.loads(http_get(
                workers[0] + "/api/health"))["compute"]["state"]
                == "sick", timeout=60)
            time.sleep(3.0)

            # nemesis 3: a poison query. Rows with >= 6 distinct terms
            # NaN on w1 and w2; w0 serves from the host mirror (its
            # device is already sick) and never poisons.
            for w in (workers[1], workers[2]):
                http_post(w + "/api/device-nemesis", json.dumps(
                    {"script":
                     "score_ell:poison:1.0:min_uniq=6"}).encode())
            fp = poison_fingerprint(poison_q, "sparse")

            def quarantined():
                st, hd, _b = _post_full(
                    leader, "/leader/start",
                    json.dumps({"query": poison_q}).encode(),
                    timeout=30.0)
                return st == 422 \
                    and hd.get("X-Poison-Quarantined") == fp
            wait_pred(quarantined, timeout=60, interval=0.5)
            snap = json.loads(http_get(leader + "/api/quarantine"))
            (entry,) = [e for e in snap["quarantined"]
                        if e["fingerprint"] == fp]
            # the quarantine engaged on exactly TWO distinct replicas —
            # the third (sick, host-serving) worker never produced a
            # poison verdict, and no further replica ever will: every
            # later send is a front-door 422
            assert len(entry["replicas"]) == 2
            assert set(entry["replicas"]) <= {workers[1], workers[2]}

            time.sleep(3.0)
            stop.set()
            for t in threads:
                t.join(timeout=30)
            assert not errors, errors[:3]
            assert outcomes["exact"] > 20, outcomes
            # the sick worker's shard kept serving (exact host mirror,
            # honestly stamped) — chaos degraded, never lied
            assert outcomes["compute_degraded"] > 0, outcomes

            # zero acked-write loss THROUGH the chaos: the full-corpus
            # query still returns all 24 names (w0's shard via its
            # mirror, the rest on device)
            st, hd, body = _post_full(
                leader, "/leader/start",
                json.dumps({"query": "common"}).encode(), timeout=30.0)
            assert st == 200 and set(json.loads(body)) == set(docs)

            # recovery: clear every nemesis + the quarantine; the sick
            # device heals via its 0.5s probe, stamps disappear, and
            # replies converge to the exact baseline
            for w in workers:
                http_post(w + "/api/device-nemesis",
                          json.dumps({"clear": True}).encode())
            json.loads(http_post(leader + "/api/quarantine", b"{}"))

            def recovered():
                st, hd, body = _post_full(
                    leader, "/leader/start",
                    json.dumps({"query": "common"}).encode(),
                    timeout=30.0)
                return (st == 200
                        and "X-Compute-Degraded" not in hd
                        and "X-Scatter-Degraded" not in hd
                        and json.loads(body) == baseline["common"])
            wait_pred(recovered, timeout=60, interval=0.5)
            # the poison query is admitted and served again
            st, _, body = _post_full(
                leader, "/leader/start",
                json.dumps({"query": poison_q}).encode(), timeout=30.0)
            assert st == 200 and json.loads(body) == baseline[poison_q]
        finally:
            for p in procs.values():
                try:
                    p.kill()
                except Exception:
                    pass
            for p in procs.values():
                try:
                    p.wait(timeout=10)
                except Exception:
                    pass

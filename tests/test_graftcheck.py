"""graftcheck (ISSUE 4): project-native static analysis + the runtime
lockdep witness.

Three layers:

1. **seeded fixtures** — each analyzer must detect a deliberately
   planted violation (lock-order cycle, RPC under a held lock,
   unregistered fault point, impure jitted function, naked transport
   call) in a tiny synthetic package;
2. **the real tree** — ``run_analyzers`` over this repository must
   produce zero findings beyond the committed allowlist/baseline (the
   CI gate, duplicated here so tier-1 enforces it without the separate
   job), and the lock graph must stay acyclic with the load-bearing
   cross-module edges present;
3. **the witness** — a seeded two-lock inversion must be reported, and
   a real durable-coordinator + registry scenario must yield at least
   one observed multi-lock ordering that the static graph explains.

Plus regression tests for the findings graftcheck surfaced and we
fixed: the registry no longer holds its lock across coordination RPCs,
and the batcher's waits are bounded with shutdown checks.
"""

import os
import threading
import time

import pytest

from tools.graftcheck import core as gc_core
from tools.graftcheck import (jitpurity, lockgraph, registry_drift,
                              resilience, wallclock)
from tools.graftcheck.core import (SourceTree, load_allowlist,
                                   load_baseline, run_analyzers, triage)
from tools.graftcheck.witness import LockdepWitness, _InstrLock

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _mini_tree(tmp_path, files: dict[str, str]) -> SourceTree:
    pkg = tmp_path / gc_core.PACKAGE
    pkg.mkdir(parents=True, exist_ok=True)
    (pkg / "__init__.py").write_text("")
    for rel, src in files.items():
        p = pkg / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        if not (p.parent / "__init__.py").exists():
            (p.parent / "__init__.py").write_text("")
        p.write_text(src)
    return SourceTree(str(tmp_path))


# ---------------------------------------------------------------------------
# 1. seeded fixtures: each analyzer must catch its planted bug
# ---------------------------------------------------------------------------

class TestSeededFixtures:
    def test_detects_lock_order_cycle(self, tmp_path):
        tree = _mini_tree(tmp_path, {"bad.py": '''
import threading

class A:
    def __init__(self):
        self._l1 = threading.Lock()
        self._l2 = threading.Lock()

    def ab(self):
        with self._l1:
            with self._l2:
                pass

    def ba(self):
        with self._l2:
            with self._l1:
                pass
'''})
        found = lockgraph.analyze(tree)
        assert any("cycle" in f.key for f in found), found

    def test_detects_locked_rpc(self, tmp_path):
        tree = _mini_tree(tmp_path, {"bad.py": '''
import threading
import urllib.request

class A:
    def __init__(self):
        self._l = threading.Lock()

    def locked_rpc(self):
        with self._l:
            urllib.request.urlopen("http://example/x")
'''})
        found = lockgraph.analyze(tree)
        assert any(f.key.startswith("lockgraph:blocking:") for f in found)

    def test_detects_transitive_blocking_and_edge(self, tmp_path):
        """Blocking reached THROUGH a resolvable call, plus the
        cross-object lock edge via an annotated attribute."""
        tree = _mini_tree(tmp_path, {"bad.py": '''
import threading
import os

class Store:
    def __init__(self):
        self._mu = threading.Lock()

    def flush(self, fd):
        with self._mu:
            pass

    def sync(self, fd):
        os.fsync(fd)

class A:
    def __init__(self, store: Store):
        self._l = threading.Lock()
        self.store = store

    def locked_sync(self):
        with self._l:
            self.store.sync(1)

    def nested(self):
        with self._l:
            self.store.flush(1)
'''})
        g = lockgraph.build(tree)
        assert any("locked_sync" in f.key and "sync" in f.key
                   for f in g.findings), g.findings
        assert ("bad.A._l", "bad.Store._mu") in g.edge_set()

    def test_detects_indefinite_wait(self, tmp_path):
        tree = _mini_tree(tmp_path, {"bad.py": '''
import threading

def park(ev):
    ev.wait()
'''})
        found = lockgraph.analyze(tree)
        assert any("indefinite-wait" in f.key for f in found)

    def test_detects_impure_jit(self, tmp_path):
        tree = _mini_tree(tmp_path, {"bad.py": '''
import time
import jax

_CACHE = {}

def helper(x):
    time.perf_counter()
    return x

def kernel(x):
    _CACHE["k"] = x
    return helper(x)

kernel_jit = jax.jit(kernel)
'''})
        found = jitpurity.analyze(tree)
        cats = {f.key.split(":")[1] for f in found}
        assert "wall-clock" in cats, found      # via the helper call
        assert "mutable-global" in cats, found  # _CACHE store

    def test_detects_impure_jit_decorator_and_shard_map(self, tmp_path):
        tree = _mini_tree(tmp_path, {"bad.py": '''
import functools
import time
import jax
from tfidf_tpu.compat import shard_map as _shard_map

@functools.partial(jax.jit, static_argnames=("k",))
def decorated(x, k):
    time.time()
    return x

def mapped(x):
    time.monotonic()
    return x

def factory(mesh):
    return _shard_map(mapped, mesh=mesh)
''', "compat.py": "def shard_map(f, **kw):\n    return f\n"})
        found = jitpurity.analyze(tree)
        quals = {f.key.split(":", 2)[2] for f in found}
        assert "bad.decorated" in quals, found
        assert "bad.mapped" in quals, found

    def test_detects_impure_jit_lambda(self, tmp_path):
        tree = _mini_tree(tmp_path, {"bad.py": '''
import time
import jax

fn = jax.jit(lambda x: x + time.time())
'''})
        found = jitpurity.analyze(tree)
        assert any(f.key.split(":")[1] == "wall-clock" for f in found), \
            found

    def test_detects_unregistered_and_stale_fault_points(self, tmp_path):
        tree = _mini_tree(tmp_path, {
            "utils/faults.py": '''
KNOWN_FAULT_POINTS: dict[str, str] = {
    "known.point": "covered",
    "ghost.point": "never fired anywhere",
}

def fault_point(name):
    pass
''',
            "code.py": '''
from tfidf_tpu.utils.faults import fault_point

def f():
    fault_point("known.point")
    fault_point("rogue.point")
'''})
        found = registry_drift.check_fault_points(tree)
        keys = {f.key for f in found}
        assert "registry_drift:faults:unregistered:rogue.point" in keys
        assert "registry_drift:faults:stale:ghost.point" in keys
        assert not any("known.point" in k for k in keys)

    def test_detects_unwrapped_transport(self, tmp_path):
        tree = _mini_tree(tmp_path, {"cluster/rpc.py": '''
import urllib.request

class Node:
    def naked(self, w):
        return urllib.request.urlopen(w + "/worker/thing")

    def wrapped(self, w):
        def rpc():
            return urllib.request.urlopen(w + "/worker/thing")
        return self.resilience.worker_call(w, rpc)

    def wrapped_lambda(self, w):
        return self.resilience.worker_call(
            w, lambda: urllib.request.urlopen(w))
'''})
        found = resilience.analyze(tree)
        quals = {f.key.split(":")[2] for f in found}
        assert "cluster.rpc.naked" in quals, quals
        assert "cluster.rpc.wrapped" not in quals, quals
        assert "cluster.rpc.wrapped_lambda" not in quals, quals

    def test_closure_forwarding_wrapper_recognized(self, tmp_path):
        """The replication spine's indirection: a per-worker RPC
        closure handed to a gatherer that forwards it into worker_call
        (``_gather(..., rpc_one, ...)``) is wrapped; a replica-failover
        RPC that bypasses both is still a finding."""
        tree = _mini_tree(tmp_path, {"cluster/rpc.py": '''
import urllib.request

class Node:
    def _gather(self, queries, rpc_one, deadline):
        def call(addr):
            return self.resilience.worker_call(
                addr, lambda: rpc_one(addr, deadline))
        return [call(w) for w in self.workers]

    def scatter(self, queries):
        def rpc_one(addr, deadline):
            return urllib.request.urlopen(addr + "/worker/process")
        return self._gather(queries, rpc_one, 1.0)

    def naked_failover(self, backup, names):
        def slice_rpc():
            return urllib.request.urlopen(backup + "/worker/slice")
        return slice_rpc()
'''})
        found = resilience.analyze(tree)
        quals = {f.key.split(":")[2] for f in found}
        assert "cluster.rpc.scatter.rpc_one" not in quals, quals
        assert "cluster.rpc.naked_failover.slice_rpc" in quals, quals

    def test_keyword_passed_closure_counts_as_wrapped(self, tmp_path):
        tree = _mini_tree(tmp_path, {"cluster/rpc.py": '''
import urllib.request

class Node:
    def kw_wrapped(self, w):
        def rpc():
            return urllib.request.urlopen(w)
        return self.resilience.worker_call(w, fn=rpc)
'''})
        found = resilience.analyze(tree)
        quals = {f.key.split(":")[2] for f in found}
        assert "cluster.rpc.kw_wrapped.rpc" not in quals, quals

    def test_detects_wallclock_misuse(self, tmp_path):
        tree = _mini_tree(tmp_path, {"mod.py": '''
import time

def f():
    deadline = time.time() + 5
    return deadline

def g():
    while time.time() < 9:
        pass

def h():
    return {"created_at": time.time()}

def ok():
    return time.monotonic() - 1
'''})
        keys = {f.key for f in wallclock.analyze(tree)}
        # direct arithmetic/comparison AND taint-through-a-local both
        # classify as deadline arithmetic; a bare read is a timestamp
        assert "wallclock:mod.f:deadline-arithmetic" in keys
        assert "wallclock:mod.g:deadline-arithmetic" in keys
        assert "wallclock:mod.h:timestamp" in keys
        # time.monotonic is the prescribed fix — never flagged
        assert not any("mod.ok" in k for k in keys)


# ---------------------------------------------------------------------------
# 2. the real tree: the committed pins are the whole story
# ---------------------------------------------------------------------------

class TestRealTree:
    @pytest.fixture(scope="class")
    def graph(self):
        return lockgraph.build(SourceTree(REPO_ROOT))

    def test_no_new_findings(self):
        """The tier-1 copy of the CI gate: everything the analyzers
        surface must be pinned in allowlist.json/baseline.json."""
        findings = run_analyzers(REPO_ROOT)
        new, _pinned, _stale = triage(findings, load_allowlist(),
                                      load_baseline())
        assert not new, "unpinned graftcheck findings:\n" + "\n".join(
            f.render() for f in new)

    def test_allowlist_entries_not_stale(self):
        """Every allowlist entry must still match a live finding —
        fixed code must shed its suppression."""
        live = {f.key for f in run_analyzers(REPO_ROOT)}
        stale = sorted(set(load_allowlist()) - live)
        assert not stale, f"allowlist entries with no finding: {stale}"

    def test_lock_graph_acyclic(self, graph):
        assert not any("cycle" in f.key for f in graph.findings)

    def test_lock_graph_has_load_bearing_edges(self, graph):
        """The orderings the concurrent stack actually depends on must
        be visible to the analyzer — if resolution breaks, the witness
        would start failing on 'unexplained' real edges."""
        edges = graph.edge_set()
        assert ("cluster.ensemble.EnsembleNode._lock",
                "cluster.coordination.CoordinationCore._lock") in edges
        assert ("cluster.coordination.CoordinationCore._lock",
                "cluster.coordination._Session.cond") in edges
        # _placement_lock is an alias of the placement map's own lock
        # (cluster/placement.py) — the resolver sees through it
        assert ("cluster.node.SearchNode._reconcile_serial",
                "cluster.placement.PlacementMap.lock") in edges

    def test_lock_sites_cover_known_locks(self, graph):
        names = set(graph.tree.lock_sites.values())
        assert "cluster.ensemble.EnsembleNode._lock" in names
        assert "engine.pipeline.PipelineExecutor._lock" in names

    def test_pipeline_executor_clean(self, graph):
        """Regression (ISSUE 4 satellite): engine/pipeline.py must stay
        free of blocking-while-locked and indefinite-wait findings —
        all its waits are bounded with shutdown checks."""
        bad = [f for f in graph.findings
               if f.file == "tfidf_tpu/engine/pipeline.py"]
        assert not bad, bad

    def test_batcher_waits_bounded(self, graph):
        """Regression: the Coalescer's indefinite submit/_run waits
        were bounded (timeout audit) — they must not come back."""
        bad = [f for f in graph.findings
               if "cluster.batcher" in f.key
               and "indefinite-wait" in f.key]
        assert not bad, bad

    def test_registry_refresh_not_locked_over_rpc(self, graph):
        """Regression: _update_addresses reads the registry OUTSIDE its
        lock (ticketed install) — the blocking-while-locked finding
        stays gone."""
        bad = [f for f in graph.findings
               if f.key.startswith(
                   "lockgraph:blocking:cluster.registry.")]
        assert not bad, bad

    def test_jit_roots_discovered(self):
        """jitpurity's clean verdict on the real tree only means
        something if its entry-point discovery still finds the real
        jit/shard_map roots — pin a floor so the pass can't silently
        go stale."""
        p = jitpurity._Purity(SourceTree(REPO_ROOT))
        roots = p.roots()
        assert len(roots) >= 10, [r for _, _, r in roots]
        kinds = {r.split("(")[0].split()[0] for _, _, r in roots}
        assert "shard_map" in kinds
        # jax.jit(lambda …) roots must be covered too (the df-update
        # lambda in parallel/mesh_ell_index.py)
        assert any("<lambda" in r for _, _, r in roots), \
            [r for _, _, r in roots]

    def test_registry_drift_fault_points(self):
        """The old one-off anti-stale test, replaced: the drift pass
        checks BOTH directions (source ⊆ registry and registry ⊆
        source) and runs against the real tree."""
        found = registry_drift.check_fault_points(SourceTree(REPO_ROOT))
        assert not found, [f.render() for f in found]

    def test_registry_drift_config_and_metrics(self):
        tree = SourceTree(REPO_ROOT)
        cfg = registry_drift.check_config(tree, REPO_ROOT)
        assert not cfg, [f.render() for f in cfg]
        allow = load_allowlist()
        met = [f for f in registry_drift.check_metrics(tree)
               if f.key not in allow]
        assert not met, [f.render() for f in met]


# ---------------------------------------------------------------------------
# 3. the runtime lockdep witness
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def static_graph():
    return lockgraph.build(SourceTree(REPO_ROOT))


class TestLockdepWitness:
    def test_seeded_inversion_reported(self, static_graph):
        w = LockdepWitness(graph=static_graph)
        a = _InstrLock(w, "fixture.A")
        b = _InstrLock(w, "fixture.B")
        with a:
            with b:
                pass
        with b:
            with a:
                pass
        with pytest.raises(AssertionError, match="inversions"):
            w.check()
        assert ("fixture.B", "fixture.A") in w.inversions \
            or ("fixture.A", "fixture.B") in w.inversions

    def test_consistent_order_passes(self, static_graph):
        w = LockdepWitness(graph=static_graph)
        a = _InstrLock(w, "cluster.ensemble.EnsembleNode._lock")
        b = _InstrLock(w, "cluster.coordination.CoordinationCore._lock")
        with a:
            with b:
                pass
        with a:
            with b:
                pass
        rep = w.check(min_multilock_edges=1)
        assert not rep["inversions"] and not rep["unexplained"]

    def test_edge_missing_from_static_graph_fails(self, static_graph):
        w = LockdepWitness(graph=static_graph)
        a = _InstrLock(w, "cluster.coordination.CoordinationCore._lock")
        b = _InstrLock(w, "cluster.ensemble.EnsembleNode._lock")
        with a:      # reverse of the static ensemble→core ordering
            with b:
                pass
        with pytest.raises(AssertionError, match="missing from the"):
            w.check()

    def test_rlock_reentry_is_not_an_edge(self, static_graph):
        from tools.graftcheck.witness import _InstrRLock
        w = LockdepWitness(graph=static_graph)
        a = _InstrRLock(w, "fixture.R")
        with a:
            with a:
                pass
        assert not w.edges

    @pytest.mark.skipif(
        os.environ.get("GRAFTCHECK_LOCKDEP") == "1",
        reason="session-wide witness already owns the package "
               "namespaces; its end-of-session check covers this")
    def test_real_coordinator_orderings(self, static_graph, tmp_path):
        """Acceptance: the witness observes >= 1 REAL multi-lock
        ordering from a durable coordinator + registry workload and
        confirms every observed edge against the static graph."""
        from tfidf_tpu.cluster.coordination import (CoordinationClient,
                                                    CoordinationServer)
        from tfidf_tpu.cluster.registry import ServiceRegistry

        w = LockdepWitness(graph=static_graph)
        with w:
            srv = CoordinationServer(
                port=0, session_timeout_s=1.0,
                data_dir=str(tmp_path / "coord")).start()
            try:
                cli = CoordinationClient(srv.address)
                reg = ServiceRegistry(cli)
                reg.register_to_cluster("http://127.0.0.1:1")
                assert reg.get_all_service_addresses() \
                    == ["http://127.0.0.1:1"]
                cli.create("/w", b"1")
                cli.delete("/w")
                # force an expiry: _expire_locked fires session conds
                # under the core lock (a real cross-object ordering)
                srv.core.expire_session(cli.sid)
                time.sleep(0.3)
                cli.close()
            finally:
                srv.close()
        rep = w.check(min_multilock_edges=1)
        assert ("cluster.ensemble.EnsembleNode._lock",
                "cluster.coordination.CoordinationCore._lock") \
            in w.multi_lock_edges()


# ---------------------------------------------------------------------------
# regression tests for the fixes graftcheck drove
# ---------------------------------------------------------------------------

class _StallableCoord:
    """Duck-typed coordination fake whose get_children can be stalled —
    the registry must serve cached reads meanwhile."""

    def __init__(self):
        self.gate = threading.Event()
        self.gate.set()
        self.children = ["n_1"]

    def ensure(self, path, data=b""):
        pass

    def get_children(self, path, watcher=None):
        self.gate.wait(5.0)
        return list(self.children)

    def get_data(self, path):
        return b"http://w"


class TestRegistryRefreshRegression:
    def test_cached_reads_not_blocked_by_stalled_refresh(self):
        from tfidf_tpu.cluster.registry import ServiceRegistry

        coord = _StallableCoord()
        reg = ServiceRegistry(coord)
        reg.get_all_service_addresses()          # populate the cache
        coord.gate.clear()                       # stall the NEXT refresh
        t = threading.Thread(target=reg._update_addresses, daemon=True)
        t.start()
        time.sleep(0.05)                         # refresh is now parked
        t0 = time.perf_counter()
        addrs = reg.get_all_service_addresses()
        dt = time.perf_counter() - t0
        coord.gate.set()
        t.join(2.0)
        assert addrs == ["http://w"]
        # pre-fix this blocked for the full stall (coordination RPC
        # under the registry lock); now it's a cache read
        assert dt < 0.5, f"cached read blocked {dt:.2f}s behind refresh"

    def test_stale_refresh_loses_to_newer_install(self):
        from tfidf_tpu.cluster.registry import ServiceRegistry

        coord = _StallableCoord()
        reg = ServiceRegistry(coord)
        reg._update_addresses()
        assert reg.get_all_service_addresses() == ["http://w"]
        # simulate a later-ticketed refresh having already installed:
        # a refresh drawing an OLDER ticket must drop its install (the
        # ordering guarantee the old whole-method lock provided)
        with reg._lock:
            reg._installed_ticket = reg._refresh_ticket + 10
        coord.children = ["n_1", "n_2"]
        reg._update_addresses()
        assert reg.get_all_service_addresses() == ["http://w"]


class TestBatcherShutdownRegression:
    def test_submit_fails_loudly_when_stopped_mid_batch(self):
        """A dispatcher wedged inside batch_fn must not wedge the
        caller forever after stop(): the bounded-slice wait raises."""
        from tfidf_tpu.cluster.batcher import Coalescer

        release = threading.Event()

        def wedged_batch(items):
            release.wait(30.0)
            return items

        c = Coalescer(wedged_batch, linger_s=0.0, pipeline=1,
                      name="wedge")
        got: dict = {}

        def caller():
            try:
                c.submit("x")
                got["r"] = "ok"
            except RuntimeError as e:
                got["r"] = str(e)

        t = threading.Thread(target=caller, daemon=True)
        t.start()
        time.sleep(0.2)          # the batch is now wedged in batch_fn
        c.stop()
        t.join(6.0)
        assert not t.is_alive(), "submit still wedged after stop()"
        assert "stopped" in got["r"]
        release.set()

    @pytest.mark.filterwarnings(
        "ignore::pytest.PytestUnhandledThreadExceptionWarning")
    def test_dispatcher_death_fails_waiters_loudly(self):
        """A BaseException escaping batch_fn kills the dispatcher
        thread — its popped waiters must be failed on the way out, and
        later submits must detect the dead dispatcher instead of
        wedging (code-review finding on the bounded-wait fix)."""
        from tfidf_tpu.cluster.batcher import Coalescer

        def lethal_batch(items):
            raise SystemExit("dispatcher killed")

        c = Coalescer(lethal_batch, linger_s=0.0, pipeline=1,
                      name="lethal")
        with pytest.raises(RuntimeError, match="dispatcher died"):
            c.submit("x")
        # the lone dispatcher is dead now; a fresh submit must fail
        # via the liveness check, not hang
        for t in c._threads:
            t.join(2.0)
        t0 = time.perf_counter()
        with pytest.raises(RuntimeError, match="died|stopped"):
            c.submit("y")
        assert time.perf_counter() - t0 < 10.0

    def test_queued_waiters_failed_on_stop(self):
        from tfidf_tpu.cluster.batcher import Coalescer

        c = Coalescer(lambda items: items, linger_s=0.0, pipeline=1,
                      name="ok")
        assert c.submit("a") == "a"
        c.stop()
        with pytest.raises(RuntimeError):
            c.submit("b")

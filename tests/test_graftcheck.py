"""graftcheck (ISSUE 4): project-native static analysis + the runtime
lockdep witness.

Three layers:

1. **seeded fixtures** — each analyzer must detect a deliberately
   planted violation (lock-order cycle, RPC under a held lock,
   unregistered fault point, impure jitted function, naked transport
   call) in a tiny synthetic package;
2. **the real tree** — ``run_analyzers`` over this repository must
   produce zero findings beyond the committed allowlist/baseline (the
   CI gate, duplicated here so tier-1 enforces it without the separate
   job), and the lock graph must stay acyclic with the load-bearing
   cross-module edges present;
3. **the witness** — a seeded two-lock inversion must be reported, and
   a real durable-coordinator + registry scenario must yield at least
   one observed multi-lock ordering that the static graph explains.

Plus regression tests for the findings graftcheck surfaced and we
fixed: the registry no longer holds its lock across coordination RPCs,
and the batcher's waits are bounded with shutdown checks.
"""

import json
import os
import threading
import time
import urllib.error
import urllib.request

import pytest

from tools.graftcheck import core as gc_core
from tools.graftcheck import (deadsymbols, jitpurity, lockgraph, protocol,
                              registry_drift, resilience, wallclock)
from tools.graftcheck.core import (SourceTree, load_allowlist,
                                   load_baseline, run_analyzers, triage)
from tools.graftcheck.protocol_witness import ProtocolWitness
from tools.graftcheck.witness import LockdepWitness, _InstrLock

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _mini_tree(tmp_path, files: dict[str, str]) -> SourceTree:
    pkg = tmp_path / gc_core.PACKAGE
    pkg.mkdir(parents=True, exist_ok=True)
    (pkg / "__init__.py").write_text("")
    for rel, src in files.items():
        p = pkg / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        if not (p.parent / "__init__.py").exists():
            (p.parent / "__init__.py").write_text("")
        p.write_text(src)
    return SourceTree(str(tmp_path))


# ---------------------------------------------------------------------------
# 1. seeded fixtures: each analyzer must catch its planted bug
# ---------------------------------------------------------------------------

class TestSeededFixtures:
    def test_detects_lock_order_cycle(self, tmp_path):
        tree = _mini_tree(tmp_path, {"bad.py": '''
import threading

class A:
    def __init__(self):
        self._l1 = threading.Lock()
        self._l2 = threading.Lock()

    def ab(self):
        with self._l1:
            with self._l2:
                pass

    def ba(self):
        with self._l2:
            with self._l1:
                pass
'''})
        found = lockgraph.analyze(tree)
        assert any("cycle" in f.key for f in found), found

    def test_detects_locked_rpc(self, tmp_path):
        tree = _mini_tree(tmp_path, {"bad.py": '''
import threading
import urllib.request

class A:
    def __init__(self):
        self._l = threading.Lock()

    def locked_rpc(self):
        with self._l:
            urllib.request.urlopen("http://example/x")
'''})
        found = lockgraph.analyze(tree)
        assert any(f.key.startswith("lockgraph:blocking:") for f in found)

    def test_detects_transitive_blocking_and_edge(self, tmp_path):
        """Blocking reached THROUGH a resolvable call, plus the
        cross-object lock edge via an annotated attribute."""
        tree = _mini_tree(tmp_path, {"bad.py": '''
import threading
import os

class Store:
    def __init__(self):
        self._mu = threading.Lock()

    def flush(self, fd):
        with self._mu:
            pass

    def sync(self, fd):
        os.fsync(fd)

class A:
    def __init__(self, store: Store):
        self._l = threading.Lock()
        self.store = store

    def locked_sync(self):
        with self._l:
            self.store.sync(1)

    def nested(self):
        with self._l:
            self.store.flush(1)
'''})
        g = lockgraph.build(tree)
        assert any("locked_sync" in f.key and "sync" in f.key
                   for f in g.findings), g.findings
        assert ("bad.A._l", "bad.Store._mu") in g.edge_set()

    def test_detects_indefinite_wait(self, tmp_path):
        tree = _mini_tree(tmp_path, {"bad.py": '''
import threading

def park(ev):
    ev.wait()
'''})
        found = lockgraph.analyze(tree)
        assert any("indefinite-wait" in f.key for f in found)

    def test_detects_impure_jit(self, tmp_path):
        tree = _mini_tree(tmp_path, {"bad.py": '''
import time
import jax

_CACHE = {}

def helper(x):
    time.perf_counter()
    return x

def kernel(x):
    _CACHE["k"] = x
    return helper(x)

kernel_jit = jax.jit(kernel)
'''})
        found = jitpurity.analyze(tree)
        cats = {f.key.split(":")[1] for f in found}
        assert "wall-clock" in cats, found      # via the helper call
        assert "mutable-global" in cats, found  # _CACHE store

    def test_detects_impure_jit_decorator_and_shard_map(self, tmp_path):
        tree = _mini_tree(tmp_path, {"bad.py": '''
import functools
import time
import jax
from tfidf_tpu.compat import shard_map as _shard_map

@functools.partial(jax.jit, static_argnames=("k",))
def decorated(x, k):
    time.time()
    return x

def mapped(x):
    time.monotonic()
    return x

def factory(mesh):
    return _shard_map(mapped, mesh=mesh)
''', "compat.py": "def shard_map(f, **kw):\n    return f\n"})
        found = jitpurity.analyze(tree)
        quals = {f.key.split(":", 2)[2] for f in found}
        assert "bad.decorated" in quals, found
        assert "bad.mapped" in quals, found

    def test_detects_impure_jit_lambda(self, tmp_path):
        tree = _mini_tree(tmp_path, {"bad.py": '''
import time
import jax

fn = jax.jit(lambda x: x + time.time())
'''})
        found = jitpurity.analyze(tree)
        assert any(f.key.split(":")[1] == "wall-clock" for f in found), \
            found

    def test_detects_unregistered_and_stale_fault_points(self, tmp_path):
        tree = _mini_tree(tmp_path, {
            "utils/faults.py": '''
KNOWN_FAULT_POINTS: dict[str, str] = {
    "known.point": "covered",
    "ghost.point": "never fired anywhere",
}

def fault_point(name):
    pass
''',
            "code.py": '''
from tfidf_tpu.utils.faults import fault_point

def f():
    fault_point("known.point")
    fault_point("rogue.point")
'''})
        found = registry_drift.check_fault_points(tree)
        keys = {f.key for f in found}
        assert "registry_drift:faults:unregistered:rogue.point" in keys
        assert "registry_drift:faults:stale:ghost.point" in keys
        assert not any("known.point" in k for k in keys)

    def test_detects_storage_seam_violations(self, tmp_path):
        """The storageseam pass: raw write-mode open / np.savez /
        os.replace are findings EVERYWHERE — the seam module included
        (its own primitives are explicit allowlist pins, not a silent
        skip; the blanket skip used to hide any new durable-write
        class that happened to live there) — while read-mode opens
        are not."""
        from tools.graftcheck import storageseam
        tree = _mini_tree(tmp_path, {
            "utils/storage.py": '''
import os

def write_bytes(path, data):
    with open(path, "wb") as f:   # flagged too: pinned, not skipped
        f.write(data)
''',
            "engine/rogue.py": '''
import os
import numpy as np


class Saver:
    def save(self, path, data, arrays):
        with open(path + ".tmp", "wb") as f:
            f.write(data)
        np.savez(path + ".npz", **arrays)
        os.replace(path + ".tmp", path)

    def load(self, path):
        with open(path, "rb") as f:   # read-mode: not a finding
            return f.read()
'''})
        keys = {f.key for f in storageseam.analyze(tree)}
        assert "storageseam:raw-io:engine.rogue.Saver.save:open:wb" \
            in keys
        assert "storageseam:raw-io:engine.rogue.Saver.save:savez" \
            in keys
        assert "storageseam:raw-io:engine.rogue.Saver.save:replace" \
            in keys
        assert not any("Saver.load" in k for k in keys)
        # the seam module is scanned like everything else now: its own
        # write primitive surfaces as an explicit (allowlist-pinned)
        # finding rather than vanishing behind a module-wide skip
        assert "storageseam:raw-io:utils.storage.write_bytes:open:wb" \
            in keys

    def test_storage_seam_clean_on_real_tree(self):
        """Every raw-IO site in the real tree is either migrated onto
        the seam or pinned in the allowlist with a justification —
        exactly the CI gate."""
        from tools.graftcheck import storageseam
        allow = load_allowlist()
        found = storageseam.analyze(SourceTree(REPO_ROOT))
        new = [f.render() for f in found if f.key not in allow]
        assert not new, new

    def test_detects_unwrapped_transport(self, tmp_path):
        tree = _mini_tree(tmp_path, {"cluster/rpc.py": '''
import urllib.request

class Node:
    def naked(self, w):
        return urllib.request.urlopen(w + "/worker/thing")

    def wrapped(self, w):
        def rpc():
            return urllib.request.urlopen(w + "/worker/thing")
        return self.resilience.worker_call(w, rpc)

    def wrapped_lambda(self, w):
        return self.resilience.worker_call(
            w, lambda: urllib.request.urlopen(w))
'''})
        found = resilience.analyze(tree)
        quals = {f.key.split(":")[2] for f in found}
        assert "cluster.rpc.naked" in quals, quals
        assert "cluster.rpc.wrapped" not in quals, quals
        assert "cluster.rpc.wrapped_lambda" not in quals, quals

    def test_closure_forwarding_wrapper_recognized(self, tmp_path):
        """The replication spine's indirection: a per-worker RPC
        closure handed to a gatherer that forwards it into worker_call
        (``_gather(..., rpc_one, ...)``) is wrapped; a replica-failover
        RPC that bypasses both is still a finding."""
        tree = _mini_tree(tmp_path, {"cluster/rpc.py": '''
import urllib.request

class Node:
    def _gather(self, queries, rpc_one, deadline):
        def call(addr):
            return self.resilience.worker_call(
                addr, lambda: rpc_one(addr, deadline))
        return [call(w) for w in self.workers]

    def scatter(self, queries):
        def rpc_one(addr, deadline):
            return urllib.request.urlopen(addr + "/worker/process")
        return self._gather(queries, rpc_one, 1.0)

    def naked_failover(self, backup, names):
        def slice_rpc():
            return urllib.request.urlopen(backup + "/worker/slice")
        return slice_rpc()
'''})
        found = resilience.analyze(tree)
        quals = {f.key.split(":")[2] for f in found}
        assert "cluster.rpc.scatter.rpc_one" not in quals, quals
        assert "cluster.rpc.naked_failover.slice_rpc" in quals, quals

    def test_keyword_passed_closure_counts_as_wrapped(self, tmp_path):
        tree = _mini_tree(tmp_path, {"cluster/rpc.py": '''
import urllib.request

class Node:
    def kw_wrapped(self, w):
        def rpc():
            return urllib.request.urlopen(w)
        return self.resilience.worker_call(w, fn=rpc)
'''})
        found = resilience.analyze(tree)
        quals = {f.key.split(":")[2] for f in found}
        assert "cluster.rpc.kw_wrapped.rpc" not in quals, quals

    def test_detects_wallclock_misuse(self, tmp_path):
        tree = _mini_tree(tmp_path, {"mod.py": '''
import time

def f():
    deadline = time.time() + 5
    return deadline

def g():
    while time.time() < 9:
        pass

def h():
    return {"created_at": time.time()}

def ok():
    return time.monotonic() - 1
'''})
        keys = {f.key for f in wallclock.analyze(tree)}
        # direct arithmetic/comparison AND taint-through-a-local both
        # classify as deadline arithmetic; a bare read is a timestamp
        assert "wallclock:mod.f:deadline-arithmetic" in keys
        assert "wallclock:mod.g:deadline-arithmetic" in keys
        assert "wallclock:mod.h:timestamp" in keys
        # time.monotonic is the prescribed fix — never flagged
        assert not any("mod.ok" in k for k in keys)


# ---------------------------------------------------------------------------
# 2. the real tree: the committed pins are the whole story
# ---------------------------------------------------------------------------

class TestRealTree:
    @pytest.fixture(scope="class")
    def graph(self):
        return lockgraph.build(SourceTree(REPO_ROOT))

    def test_no_new_findings(self):
        """The tier-1 copy of the CI gate: everything the analyzers
        surface must be pinned in allowlist.json/baseline.json."""
        findings = run_analyzers(REPO_ROOT)
        new, _pinned, _stale = triage(findings, load_allowlist(),
                                      load_baseline())
        assert not new, "unpinned graftcheck findings:\n" + "\n".join(
            f.render() for f in new)

    def test_allowlist_entries_not_stale(self):
        """Every allowlist entry must still match a live finding —
        fixed code must shed its suppression."""
        live = {f.key for f in run_analyzers(REPO_ROOT)}
        stale = sorted(set(load_allowlist()) - live)
        assert not stale, f"allowlist entries with no finding: {stale}"

    def test_lock_graph_acyclic(self, graph):
        assert not any("cycle" in f.key for f in graph.findings)

    def test_lock_graph_has_load_bearing_edges(self, graph):
        """The orderings the concurrent stack actually depends on must
        be visible to the analyzer — if resolution breaks, the witness
        would start failing on 'unexplained' real edges."""
        edges = graph.edge_set()
        assert ("cluster.ensemble.EnsembleNode._lock",
                "cluster.coordination.CoordinationCore._lock") in edges
        assert ("cluster.coordination.CoordinationCore._lock",
                "cluster.coordination._Session.cond") in edges
        # _placement_lock is an alias of the placement map's own lock
        # (cluster/placement.py) — the resolver sees through it
        assert ("cluster.node.SearchNode._reconcile_serial",
                "cluster.placement.PlacementMap.lock") in edges

    def test_lock_sites_cover_known_locks(self, graph):
        names = set(graph.tree.lock_sites.values())
        assert "cluster.ensemble.EnsembleNode._lock" in names
        assert "engine.pipeline.PipelineExecutor._lock" in names

    def test_pipeline_executor_clean(self, graph):
        """Regression (ISSUE 4 satellite): engine/pipeline.py must stay
        free of blocking-while-locked and indefinite-wait findings —
        all its waits are bounded with shutdown checks."""
        bad = [f for f in graph.findings
               if f.file == "tfidf_tpu/engine/pipeline.py"]
        assert not bad, bad

    def test_batcher_waits_bounded(self, graph):
        """Regression: the Coalescer's indefinite submit/_run waits
        were bounded (timeout audit) — they must not come back."""
        bad = [f for f in graph.findings
               if "cluster.batcher" in f.key
               and "indefinite-wait" in f.key]
        assert not bad, bad

    def test_registry_refresh_not_locked_over_rpc(self, graph):
        """Regression: _update_addresses reads the registry OUTSIDE its
        lock (ticketed install) — the blocking-while-locked finding
        stays gone."""
        bad = [f for f in graph.findings
               if f.key.startswith(
                   "lockgraph:blocking:cluster.registry.")]
        assert not bad, bad

    def test_jit_roots_discovered(self):
        """jitpurity's clean verdict on the real tree only means
        something if its entry-point discovery still finds the real
        jit/shard_map roots — pin a floor so the pass can't silently
        go stale."""
        p = jitpurity._Purity(SourceTree(REPO_ROOT))
        roots = p.roots()
        assert len(roots) >= 10, [r for _, _, r in roots]
        kinds = {r.split("(")[0].split()[0] for _, _, r in roots}
        assert "shard_map" in kinds
        # jax.jit(lambda …) roots must be covered too (the df-update
        # lambda in parallel/mesh_ell_index.py)
        assert any("<lambda" in r for _, _, r in roots), \
            [r for _, _, r in roots]

    def test_registry_drift_fault_points(self):
        """The old one-off anti-stale test, replaced: the drift pass
        checks BOTH directions (source ⊆ registry and registry ⊆
        source) and runs against the real tree."""
        found = registry_drift.check_fault_points(SourceTree(REPO_ROOT))
        assert not found, [f.render() for f in found]

    def test_registry_drift_config_and_metrics(self):
        tree = SourceTree(REPO_ROOT)
        cfg = registry_drift.check_config(tree, REPO_ROOT)
        assert not cfg, [f.render() for f in cfg]
        allow = load_allowlist()
        met = [f for f in registry_drift.check_metrics(tree)
               if f.key not in allow]
        assert not met, [f.render() for f in met]


# ---------------------------------------------------------------------------
# 3. the runtime lockdep witness
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def static_graph():
    return lockgraph.build(SourceTree(REPO_ROOT))


class TestLockdepWitness:
    def test_seeded_inversion_reported(self, static_graph):
        w = LockdepWitness(graph=static_graph)
        a = _InstrLock(w, "fixture.A")
        b = _InstrLock(w, "fixture.B")
        with a:
            with b:
                pass
        with b:
            with a:
                pass
        with pytest.raises(AssertionError, match="inversions"):
            w.check()
        assert ("fixture.B", "fixture.A") in w.inversions \
            or ("fixture.A", "fixture.B") in w.inversions

    def test_consistent_order_passes(self, static_graph):
        w = LockdepWitness(graph=static_graph)
        a = _InstrLock(w, "cluster.ensemble.EnsembleNode._lock")
        b = _InstrLock(w, "cluster.coordination.CoordinationCore._lock")
        with a:
            with b:
                pass
        with a:
            with b:
                pass
        rep = w.check(min_multilock_edges=1)
        assert not rep["inversions"] and not rep["unexplained"]

    def test_edge_missing_from_static_graph_fails(self, static_graph):
        w = LockdepWitness(graph=static_graph)
        a = _InstrLock(w, "cluster.coordination.CoordinationCore._lock")
        b = _InstrLock(w, "cluster.ensemble.EnsembleNode._lock")
        with a:      # reverse of the static ensemble→core ordering
            with b:
                pass
        with pytest.raises(AssertionError, match="missing from the"):
            w.check()

    def test_rlock_reentry_is_not_an_edge(self, static_graph):
        from tools.graftcheck.witness import _InstrRLock
        w = LockdepWitness(graph=static_graph)
        a = _InstrRLock(w, "fixture.R")
        with a:
            with a:
                pass
        assert not w.edges

    @pytest.mark.skipif(
        os.environ.get("GRAFTCHECK_LOCKDEP") == "1",
        reason="session-wide witness already owns the package "
               "namespaces; its end-of-session check covers this")
    def test_real_coordinator_orderings(self, static_graph, tmp_path):
        """Acceptance: the witness observes >= 1 REAL multi-lock
        ordering from a durable coordinator + registry workload and
        confirms every observed edge against the static graph."""
        from tfidf_tpu.cluster.coordination import (CoordinationClient,
                                                    CoordinationServer)
        from tfidf_tpu.cluster.registry import ServiceRegistry

        w = LockdepWitness(graph=static_graph)
        with w:
            srv = CoordinationServer(
                port=0, session_timeout_s=1.0,
                data_dir=str(tmp_path / "coord")).start()
            try:
                cli = CoordinationClient(srv.address)
                reg = ServiceRegistry(cli)
                reg.register_to_cluster("http://127.0.0.1:1")
                assert reg.get_all_service_addresses() \
                    == ["http://127.0.0.1:1"]
                cli.create("/w", b"1")
                cli.delete("/w")
                # force an expiry: _expire_locked fires session conds
                # under the core lock (a real cross-object ordering)
                srv.core.expire_session(cli.sid)
                time.sleep(0.3)
                cli.close()
            finally:
                srv.close()
        rep = w.check(min_multilock_edges=1)
        assert ("cluster.ensemble.EnsembleNode._lock",
                "cluster.coordination.CoordinationCore._lock") \
            in w.multi_lock_edges()


# ---------------------------------------------------------------------------
# regression tests for the fixes graftcheck drove
# ---------------------------------------------------------------------------

class _StallableCoord:
    """Duck-typed coordination fake whose get_children can be stalled —
    the registry must serve cached reads meanwhile."""

    def __init__(self):
        self.gate = threading.Event()
        self.gate.set()
        self.children = ["n_1"]

    def ensure(self, path, data=b""):
        pass

    def get_children(self, path, watcher=None):
        self.gate.wait(5.0)
        return list(self.children)

    def get_data(self, path):
        return b"http://w"


class TestRegistryRefreshRegression:
    def test_cached_reads_not_blocked_by_stalled_refresh(self):
        from tfidf_tpu.cluster.registry import ServiceRegistry

        coord = _StallableCoord()
        reg = ServiceRegistry(coord)
        reg.get_all_service_addresses()          # populate the cache
        coord.gate.clear()                       # stall the NEXT refresh
        t = threading.Thread(target=reg._update_addresses, daemon=True)
        t.start()
        time.sleep(0.05)                         # refresh is now parked
        t0 = time.perf_counter()
        addrs = reg.get_all_service_addresses()
        dt = time.perf_counter() - t0
        coord.gate.set()
        t.join(2.0)
        assert addrs == ["http://w"]
        # pre-fix this blocked for the full stall (coordination RPC
        # under the registry lock); now it's a cache read
        assert dt < 0.5, f"cached read blocked {dt:.2f}s behind refresh"

    def test_stale_refresh_loses_to_newer_install(self):
        from tfidf_tpu.cluster.registry import ServiceRegistry

        coord = _StallableCoord()
        reg = ServiceRegistry(coord)
        reg._update_addresses()
        assert reg.get_all_service_addresses() == ["http://w"]
        # simulate a later-ticketed refresh having already installed:
        # a refresh drawing an OLDER ticket must drop its install (the
        # ordering guarantee the old whole-method lock provided)
        with reg._lock:
            reg._installed_ticket = reg._refresh_ticket + 10
        coord.children = ["n_1", "n_2"]
        reg._update_addresses()
        assert reg.get_all_service_addresses() == ["http://w"]


class TestBatcherShutdownRegression:
    def test_submit_fails_loudly_when_stopped_mid_batch(self):
        """A dispatcher wedged inside batch_fn must not wedge the
        caller forever after stop(): the bounded-slice wait raises."""
        from tfidf_tpu.cluster.batcher import Coalescer

        release = threading.Event()

        def wedged_batch(items):
            release.wait(30.0)
            return items

        c = Coalescer(wedged_batch, linger_s=0.0, pipeline=1,
                      name="wedge")
        got: dict = {}

        def caller():
            try:
                c.submit("x")
                got["r"] = "ok"
            except RuntimeError as e:
                got["r"] = str(e)

        t = threading.Thread(target=caller, daemon=True)
        t.start()
        time.sleep(0.2)          # the batch is now wedged in batch_fn
        c.stop()
        t.join(6.0)
        assert not t.is_alive(), "submit still wedged after stop()"
        assert "stopped" in got["r"]
        release.set()

    @pytest.mark.filterwarnings(
        "ignore::pytest.PytestUnhandledThreadExceptionWarning")
    def test_dispatcher_death_fails_waiters_loudly(self):
        """A BaseException escaping batch_fn kills the dispatcher
        thread — its popped waiters must be failed on the way out, and
        later submits must detect the dead dispatcher instead of
        wedging (code-review finding on the bounded-wait fix)."""
        from tfidf_tpu.cluster.batcher import Coalescer

        def lethal_batch(items):
            raise SystemExit("dispatcher killed")

        c = Coalescer(lethal_batch, linger_s=0.0, pipeline=1,
                      name="lethal")
        with pytest.raises(RuntimeError, match="dispatcher died"):
            c.submit("x")
        # the lone dispatcher is dead now; a fresh submit must fail
        # via the liveness check, not hang
        for t in c._threads:
            t.join(2.0)
        t0 = time.perf_counter()
        with pytest.raises(RuntimeError, match="died|stopped"):
            c.submit("y")
        assert time.perf_counter() - t0 < 10.0

    def test_queued_waiters_failed_on_stop(self):
        from tfidf_tpu.cluster.batcher import Coalescer

        c = Coalescer(lambda items: items, linger_s=0.0, pipeline=1,
                      name="ok")
        assert c.submit("a") == "a"
        c.stop()
        with pytest.raises(RuntimeError):
            c.submit("b")


# ---------------------------------------------------------------------------
# 4. the wire-contract analyzer family (protocol) — seeded violations
# ---------------------------------------------------------------------------

class TestProtocolSeeded:
    def test_detects_endpoint_drift_both_ways(self, tmp_path):
        """Served-but-never-called AND called-but-never-served."""
        tree = _mini_tree(tmp_path, {"cluster/h.py": '''
import urllib.parse
from http.server import BaseHTTPRequestHandler

class H(BaseHTTPRequestHandler):
    def do_POST(self):
        u = urllib.parse.urlparse(self.path)
        if u.path == "/worker/served":
            pass
''', "cluster/c.py": '''
def call(post, w):
    post(w + "/worker/phantom")
    post(w + "/worker/served")

def orphan(post, w):
    post(w + "/worker/ghost")
'''})
        keys = {f.key for f in protocol.check_endpoints(tree)}
        assert "protocol:endpoint:unserved:/worker/phantom" in keys
        assert "protocol:endpoint:unserved:/worker/ghost" in keys
        assert not any("/worker/served" in k for k in keys), keys

    def test_detects_uncalled_endpoint(self, tmp_path):
        tree = _mini_tree(tmp_path, {"cluster/h.py": '''
import urllib.parse
from http.server import BaseHTTPRequestHandler

class H(BaseHTTPRequestHandler):
    def do_GET(self):
        u = urllib.parse.urlparse(self.path)
        if u.path == "/api/nobody-calls-me":
            pass
'''})
        keys = {f.key for f in protocol.check_endpoints(tree)}
        assert "protocol:endpoint:uncalled:/api/nobody-calls-me" in keys

    def test_detects_missing_fence_stamp(self, tmp_path):
        """A mutating worker RPC without the epoch stamp is exactly the
        deposed-leader write the fence exists to reject."""
        tree = _mini_tree(tmp_path, {"cluster/rpc.py": '''
class Leader:
    def good(self, w, http_post):
        http_post(w + "/worker/delete", b"{}",
                  headers=self._epoch_headers())

    def bad(self, w, http_post):
        http_post(w + "/worker/delete", b"{}",
                  headers={"Content-Type": "application/json"})

    def bad_upload(self, w, http_post):
        http_post(w + "/worker/upload?name=a", b"data")
'''})
        keys = {f.key for f in protocol.check_fence_stamps(tree)}
        assert ("protocol:header:unfenced-mutation:cluster.rpc.bad:"
                "/worker/delete") in keys
        assert ("protocol:header:unfenced-mutation:cluster.rpc."
                "bad_upload:/worker/upload") in keys
        assert not any(":cluster.rpc.good:" in k for k in keys), keys

    def test_detects_missing_deadline_stamp(self, tmp_path):
        tree = _mini_tree(tmp_path, {"cluster/rpc.py": '''
class Plane:
    def ok(self, w, body, remaining):
        return self._scatter.post(
            w, "/worker/process-batch", body,
            headers={"X-Deadline-Ms": str(remaining)})

    def undeadlined(self, w, body):
        return self._scatter.post(w, "/worker/process-batch", body)
'''})
        keys = {f.key for f in protocol.check_deadline_stamps(tree)}
        assert ("protocol:header:undeadlined-scatter:"
                "cluster.rpc.undeadlined") in keys
        assert not any("cluster.rpc.ok" in k for k in keys), keys

    def test_detects_unstamped_429_and_bypass_send(self, tmp_path):
        tree = _mini_tree(tmp_path, {"cluster/h.py": '''
from http.server import BaseHTTPRequestHandler

class _HttpHandlerBase(BaseHTTPRequestHandler):
    def _send(self, code, body, headers=None):
        self.send_response(code)
        self.send_header("X-Trace-Id", "tid")

class H(_HttpHandlerBase):
    def do_POST(self):
        self._send(429, b"overloaded")

    def naked(self):
        self.send_response(200)
'''})
        shed = {f.key for f in protocol.check_shed_headers(tree)}
        assert ("protocol:header:shed-missing-retry-after:"
                "cluster.h.H.do_POST:429") in shed
        disc = {f.key for f in protocol.check_send_discipline(tree)}
        assert "protocol:header:bypass-send:cluster.h.H.naked" in disc
        # _send itself stamps the trace header and is never flagged
        assert not any("_send" in k and "bypass" in k for k in disc)

    def test_stamped_429_passes(self, tmp_path):
        tree = _mini_tree(tmp_path, {"cluster/h.py": '''
from http.server import BaseHTTPRequestHandler

class _HttpHandlerBase(BaseHTTPRequestHandler):
    def _send(self, code, body, headers=None):
        self.send_response(code)
        self.send_header("X-Trace-Id", "tid")

class H(_HttpHandlerBase):
    def do_POST(self):
        self._send(429, b"overloaded",
                   headers={"Retry-After": "1", "X-Shed-Reason": "x"})
'''})
        assert not protocol.check_shed_headers(tree)

    def test_detects_unclassified_status(self, tmp_path):
        """A status code the README wire table never reviewed fails —
        and a 4xx smuggled into _TRANSIENT_STATUSES (it would be
        silently retried) fails too."""
        (tmp_path / "README.md").write_text(
            "## Wire contract\n\n"
            "| endpoint | methods | lane | headers | statuses |\n"
            "|---|---|---|---|---|\n"
            "| `/worker/x` | POST | — | — | 200, 410 |\n")
        tree = _mini_tree(tmp_path, {
            "cluster/resilience.py":
                "_TRANSIENT_STATUSES = frozenset({404, 503})\n"
                "_SHED_STATUS = 429\n_FENCE_STATUS = 403\n",
            "cluster/fencing.py": "FENCE_STATUS = 403\n",
            "cluster/h.py": '''
from http.server import BaseHTTPRequestHandler

class H(BaseHTTPRequestHandler):
    def _send(self, code, body):
        self.send_response(code)

    def do_POST(self):
        self._send(200, b"ok")
        self._send(507, b"weird")
'''})
        keys = {f.key for f in protocol.check_statuses(tree,
                                                       str(tmp_path))}
        assert "protocol:status:unknown:507" in keys
        assert "protocol:status:transient-4xx:404" in keys
        assert "protocol:status:readme-stale:410" in keys
        assert not any(":200" in k for k in keys), keys

    def test_detects_fence_status_mismatch(self, tmp_path):
        (tmp_path / "README.md").write_text(
            "## Wire contract\n\n| e | m | l | h | s |\n|---|---|---|"
            "---|---|\n| `/worker/x` | POST | — | — | 200 |\n")
        tree = _mini_tree(tmp_path, {
            "cluster/resilience.py":
                "_TRANSIENT_STATUSES = frozenset({503})\n"
                "_FENCE_STATUS = 403\n",
            "cluster/fencing.py": "FENCE_STATUS = 409\n",
            "cluster/h.py": '''
from http.server import BaseHTTPRequestHandler

class H(BaseHTTPRequestHandler):
    def do_POST(self):
        self._send(200, b"ok")

    def _send(self, code, body):
        self.send_response(code)
'''})
        keys = {f.key for f in protocol.check_statuses(tree,
                                                       str(tmp_path))}
        assert "protocol:status:fence-mismatch" in keys

    def test_detects_version_surface_drift(self, tmp_path):
        """The version pass (PR 16): an unversioned wire-table row, a
        declared-version mismatch, a stale fingerprint pin, and a
        proto-status disagreement between protover.py and
        resilience.py are each findings; a consistent tree is clean.
        Mini trees opt in by including cluster/protover.py."""
        files = {
            "cluster/protover.py":
                "PROTO_VERSION = 2\nPROTO_STATUS = 426\n",
            "cluster/resilience.py":
                "_TRANSIENT_STATUSES = frozenset({503})\n"
                "_FENCE_STATUS = 403\n_PROTO_STATUS = 426\n",
            "cluster/h.py": '''
from http.server import BaseHTTPRequestHandler

class H(BaseHTTPRequestHandler):
    def _send(self, code, body):
        self.send_response(code)

    def do_POST(self):
        if self.path == "/worker/x":
            self._send(200, b"ok")
'''}
        tree = _mini_tree(tmp_path, files)
        fp = protocol.contract_fingerprint(tree)
        # consistent README: version declared, row windowed, fp pinned
        (tmp_path / "README.md").write_text(
            "## Wire contract\n\n"
            "| endpoint | methods | since | statuses |\n"
            "|---|---|---|---|\n"
            "| `/worker/x` | POST | 1– | 200 |\n\n"
            "## Versioning\n\nCurrent wire version: **2**.\n"
            f"Contract fingerprint: `{fp}`.\n")
        assert not protocol.check_version_surface(tree, str(tmp_path))
        # seed each violation in turn
        (tmp_path / "README.md").write_text(
            "## Wire contract\n\n"
            "| endpoint | methods | since | statuses |\n"
            "|---|---|---|---|\n"
            "| `/worker/x` | POST | — | 200 |\n"
            "| `/worker/y` | POST | 3– | 200 |\n\n"
            "## Versioning\n\nCurrent wire version: **1**.\n"
            "Contract fingerprint: `000000000000`.\n")
        keys = {f.key
                for f in protocol.check_version_surface(tree,
                                                        str(tmp_path))}
        assert "protocol:version:row-unversioned:/worker/x" in keys
        assert "protocol:version:row-future:/worker/y" in keys
        assert "protocol:version:declared-mismatch" in keys
        assert "protocol:version:fingerprint-drift" in keys
        # proto-status disagreement (the fence-mismatch analog)
        files["cluster/resilience.py"] = (
            "_TRANSIENT_STATUSES = frozenset({503})\n"
            "_FENCE_STATUS = 403\n_PROTO_STATUS = 410\n")
        tree2 = _mini_tree(tmp_path, files)
        keys2 = {f.key
                 for f in protocol.check_version_surface(tree2,
                                                         str(tmp_path))}
        assert "protocol:version:proto-status-mismatch" in keys2
        # trees without protover.py (all pre-PR-16 fixtures) are exempt
        del files["cluster/protover.py"]
        (tmp_path / gc_core.PACKAGE / "cluster" / "protover.py").unlink()
        tree3 = _mini_tree(tmp_path, files)
        assert not protocol.check_version_surface(tree3, str(tmp_path))

    def test_additive_surface_requires_bump_and_repin(self, tmp_path):
        """The PR 17 review loop: growing the wire surface (a new
        served route, the hybrid ``mode`` story) moves the fingerprint,
        so the OLD pin fails until the change is reviewed — version
        bumped, new row windowed at the new version, fingerprint
        re-pinned. The reviewed tree is clean; a row windowed BEYOND
        the declared version stays a finding."""
        files = {
            "cluster/protover.py":
                "PROTO_VERSION = 2\nPROTO_STATUS = 426\n",
            "cluster/resilience.py":
                "_TRANSIENT_STATUSES = frozenset({503})\n"
                "_FENCE_STATUS = 403\n_PROTO_STATUS = 426\n",
            "cluster/h.py": '''
from http.server import BaseHTTPRequestHandler

class H(BaseHTTPRequestHandler):
    def _send(self, code, body):
        self.send_response(code)

    def do_POST(self):
        if self.path == "/worker/x":
            self._send(200, b"ok")
'''}
        v2_fp = protocol.contract_fingerprint(_mini_tree(tmp_path,
                                                         files))
        # the surface grows: a second route appears (additive, like
        # the staged-mode plan) but the README still pins the v2 world
        files["cluster/h.py"] = files["cluster/h.py"].replace(
            '            self._send(200, b"ok")',
            '            self._send(200, b"ok")\n'
            '        if self.path == "/worker/staged":\n'
            '            self._send(200, b"ok")')
        tree = _mini_tree(tmp_path, files)
        assert protocol.contract_fingerprint(tree) != v2_fp
        (tmp_path / "README.md").write_text(
            "## Wire contract\n\n"
            "| endpoint | methods | since | statuses |\n"
            "|---|---|---|---|\n"
            "| `/worker/x` | POST | 1– | 200 |\n"
            "| `/worker/staged` | POST | 3– | 200 |\n\n"
            "## Versioning\n\nCurrent wire version: **2**.\n"
            f"Contract fingerprint: `{v2_fp}`.\n")
        keys = {f.key
                for f in protocol.check_version_surface(tree,
                                                        str(tmp_path))}
        assert "protocol:version:fingerprint-drift" in keys
        assert "protocol:version:row-future:/worker/staged" in keys
        # the review: bump the version, keep the 3– window, re-pin
        files["cluster/protover.py"] = (
            "PROTO_VERSION = 3\nPROTO_STATUS = 426\n")
        tree = _mini_tree(tmp_path, files)
        (tmp_path / "README.md").write_text(
            "## Wire contract\n\n"
            "| endpoint | methods | since | statuses |\n"
            "|---|---|---|---|\n"
            "| `/worker/x` | POST | 1– | 200 |\n"
            "| `/worker/staged` | POST | 3– | 200 |\n\n"
            "## Versioning\n\nCurrent wire version: **3**.\n"
            f"Contract fingerprint: "
            f"`{protocol.contract_fingerprint(tree)}`.\n")
        assert not protocol.check_version_surface(tree, str(tmp_path))

    def test_detects_raw_transport_bypass(self, tmp_path):
        """A raw transport outside the nemesis+trace seams is the
        'same shared seams' invariant breaking."""
        tree = _mini_tree(tmp_path, {"cluster/t.py": '''
import urllib.request

def naked(url):
    return urllib.request.urlopen(url)

def seam(url, origin):
    global_nemesis.check_send(origin, url)
    req = urllib.request.Request(url, headers=propagation_headers())
    return urllib.request.urlopen(req)
'''})
        keys = {f.key for f in protocol.check_seams(tree)}
        assert "protocol:seam:no-nemesis:cluster.t.naked" in keys
        assert "protocol:seam:no-trace:cluster.t.naked" in keys
        assert not any("cluster.t.seam" in k for k in keys), keys

    def test_detects_dead_symbol(self, tmp_path):
        tree = _mini_tree(tmp_path, {"m.py": '''
def used():
    return 1

def dead_helper():
    return 2

class C:
    def dead_method(self):
        pass

    def live_method(self):
        return used()

entry = used

def driver(c: C):
    return c.live_method()
'''})
        keys = {f.key for f in deadsymbols.analyze(tree, str(tmp_path))}
        assert "deadsymbols:unreferenced:m.dead_helper" in keys
        assert "deadsymbols:unreferenced:m.C.dead_method" in keys
        assert not any("live_method" in k or ":m.used" in k
                       for k in keys), keys


# ---------------------------------------------------------------------------
# 5. protocol — the real tree
# ---------------------------------------------------------------------------

class TestProtocolRealTree:
    @pytest.fixture(scope="class")
    def tree(self):
        return SourceTree(REPO_ROOT)

    def test_route_extraction_floor(self, tree):
        """The clean verdict only means something if the extraction
        still sees the real surface — pin a floor (jit_roots
        precedent)."""
        routes = protocol.served_routes(tree)
        exact = {r.path for r in routes if not r.prefix}
        assert len(exact) >= 25, sorted(exact)
        assert {"/leader/start", "/worker/process-batch",
                "/worker/upload", "/rpc", "/events"} <= exact
        assert "/api/trace/" in {r.path for r in routes if r.prefix}

    def test_header_site_floors(self, tree):
        """Zero fence/deadline findings must mean 'every site is
        stamped', not 'extraction went stale'."""
        assert len(protocol.mutating_rpc_sites(tree)) >= 6
        assert len(protocol.scatter_rpc_sites(tree)) >= 3

    def test_status_contract_pinned(self, tree):
        c = protocol.build_contract(REPO_ROOT, tree)
        assert c.statuses == {200, 400, 403, 404, 409, 415, 421, 422,
                              426, 429, 500, 503, 504, 507}

    def test_protocol_clean_on_real_tree(self, tree):
        allow = load_allowlist()
        found = [f for f in protocol.analyze(tree, REPO_ROOT)
                 if f.key not in allow]
        assert not found, [f.render() for f in found]

    def test_dead_symbols_clean_on_real_tree(self, tree):
        allow = load_allowlist()
        found = [f for f in deadsymbols.analyze(tree, REPO_ROOT)
                 if f.key not in allow]
        assert not found, [f.render() for f in found]


# ---------------------------------------------------------------------------
# 6. the runtime protocol witness
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def wire_contract():
    return protocol.build_contract(REPO_ROOT)


class TestProtocolWitnessSeeded:
    def test_unexplained_exchange_fails(self, wire_contract):
        w = ProtocolWitness(contract=wire_contract)
        w.observe("front", "POST", "/worker/zap", 200)
        with pytest.raises(AssertionError, match="not explained"):
            w.check()

    def test_unknown_path_404_is_contractual(self, wire_contract):
        """404 IS the contract's answer for an unknown path."""
        w = ProtocolWitness(contract=wire_contract)
        w.observe("front", "GET", "/worker/zap", 404)
        w.check()

    def test_unreviewed_status_fails(self, wire_contract):
        w = ProtocolWitness(contract=wire_contract)
        # 511 is in no table row and no classifier — truly unreviewed
        # (507 graduated into the contract with the ENOSPC work)
        w.observe("front", "POST", "/worker/process-batch", 511)
        with pytest.raises(AssertionError, match="reviewed"):
            w.check()

    def test_shed_without_retry_after_fails(self, wire_contract):
        w = ProtocolWitness(contract=wire_contract)
        w.observe("front", "POST", "/leader/start", 429,
                  ["X-Shed-Reason", "Connection"])
        with pytest.raises(AssertionError, match="Retry-After"):
            w.check()

    def test_read_without_route_stamp_fails(self, wire_contract):
        """The PR 11 catch (cache hits losing their route stamp),
        enforced at runtime."""
        w = ProtocolWitness(contract=wire_contract)
        w.observe("front", "POST", "/leader/start", 200,
                  ["X-Trace-Id"])
        with pytest.raises(AssertionError, match="route stamp"):
            w.check()

    def test_traced_worker_reply_must_echo_trace(self, wire_contract):
        w = ProtocolWitness(contract=wire_contract)
        w.observe("front", "POST", "/worker/process-batch", 200,
                  [], traced_request=True)
        with pytest.raises(AssertionError, match="lost X-Trace-Id"):
            w.check()

    def test_unexercised_contract_fails(self, wire_contract):
        """Lockdep-style mutual validation: statically-claimed surface
        the run never exercised fails the witness."""
        w = ProtocolWitness(contract=wire_contract)
        w.observe("front", "POST", "/leader/start", 200,
                  ["X-Trace-Id", "X-Route-Generation", "X-Route-Epoch",
                   "X-Proto-Version"])
        w.check(require_exercised={"/leader/start"})
        with pytest.raises(AssertionError, match="never exercised"):
            w.check(require_exercised={"/leader/start",
                                       "/worker/process-batch"})

    def test_vacuous_run_fails(self, wire_contract):
        w = ProtocolWitness(contract=wire_contract)
        with pytest.raises(AssertionError, match="not seeing"):
            w.check(min_exchanges=1)


class TestProtocolWitnessLive:
    def test_real_node_exchanges_explained_and_traced(self, tmp_path,
                                                      wire_contract):
        """Acceptance: the witness observes a REAL node's exchanges and
        explains every one — and the traced worker reply carries
        X-Trace-Id (the fix the protocol passes surfaced: worker-plane
        replies used to be emitted after the propagated span closed,
        so a leader-traced scatter's answer was never stamped)."""
        from tests.test_cluster import wait_until
        from tfidf_tpu.cluster.coordination import (CoordinationCore,
                                                    LocalCoordination)
        from tfidf_tpu.cluster.node import SearchNode
        from tfidf_tpu.utils.config import Config

        cfg = Config(documents_path=str(tmp_path / "documents"),
                     index_path=str(tmp_path / "index"), port=0,
                     min_doc_capacity=64, min_nnz_capacity=1 << 12,
                     min_vocab_capacity=1 << 10, query_batch=4,
                     max_query_terms=8)
        core = CoordinationCore(session_timeout_s=1.0)
        w = ProtocolWitness(contract=wire_contract)
        with w:
            node = SearchNode(cfg,
                              coord=LocalCoordination(core, 0.1)).start()
            try:
                wait_until(lambda: node.is_leader(), timeout=5.0)
                r = urllib.request.urlopen(urllib.request.Request(
                    node.url + "/worker/upload?name=d.txt",
                    data=b"shared token body",
                    headers={"Content-Type":
                             "application/octet-stream"}))
                assert r.status == 200
                # front-door read: route stamp + trace id on the reply
                r = urllib.request.urlopen(urllib.request.Request(
                    node.url + "/leader/start", data=b"token",
                    headers={"Content-Type": "text/plain"}))
                assert r.status == 200
                assert r.headers.get("X-Route-Generation") is not None
                assert r.headers.get("X-Trace-Id")
                # leader-traced worker RPC: the reply must echo the
                # propagated trace id (emitted INSIDE the worker span)
                req = urllib.request.Request(
                    node.url + "/worker/process-batch",
                    data=json.dumps({"queries": ["token"],
                                     "k": 3}).encode(),
                    headers={"Content-Type": "application/json",
                             "X-Trace-Id": "deadbeefdeadbeef",
                             "X-Span-Id": "cafe0123"})
                r = urllib.request.urlopen(req)
                assert r.status == 200
                assert r.headers.get("X-Trace-Id") \
                    == "deadbeefdeadbeef", dict(r.headers)
            finally:
                node.stop()
                core.close()
        rep = w.check(require_exercised={"/leader/start",
                                         "/worker/process-batch"},
                      min_exchanges=3)
        assert any("/worker/process-batch" in k and "(traced)" in k
                   for k in rep["exchanges"]), rep


# ---------------------------------------------------------------------------
# regression tests for the real findings the protocol passes surfaced
# ---------------------------------------------------------------------------

class TestProtocolRegressions:
    def test_coordination_ops_served_on_rpc_only(self):
        """Endpoint-drift fix: the coordination server used to dispatch
        the op switch on ANY posted path (the /rpc the client calls was
        called-but-never-served); unknown paths must 404 now."""
        from tfidf_tpu.cluster.coordination import CoordinationServer

        srv = CoordinationServer(port=0).start()
        try:
            body = json.dumps({"op": "new_session"}).encode()
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(urllib.request.Request(
                    f"http://{srv.address}/definitely-not-rpc",
                    data=body,
                    headers={"Content-Type": "application/json"}))
            assert ei.value.code == 404
            r = urllib.request.urlopen(urllib.request.Request(
                f"http://{srv.address}/rpc", data=body,
                headers={"Content-Type": "application/json"}))
            assert json.loads(r.read())["session"] > 0
        finally:
            srv.close()

    def test_download_probe_behind_nemesis_seam(self):
        """Seam-coverage fix: the download probes used to call urlopen
        raw — a scripted partition could never cut the download path.
        http_get_stream must honor an armed drop rule."""
        from tfidf_tpu.cluster.nemesis import (NemesisPartitioned,
                                               global_nemesis)
        from tfidf_tpu.cluster.node import http_get_stream

        global_nemesis.drop(src="http://leader:1",
                            dst="http://worker:2")
        try:
            with pytest.raises(NemesisPartitioned):
                http_get_stream(
                    "http://worker:2/worker/download?path=x",
                    origin="http://leader:1")
        finally:
            global_nemesis.heal()

    def test_download_probe_propagates_trace(self, tmp_path):
        """Seam-coverage fix, trace half: a download probe dispatched
        inside an active span must carry X-Trace-Id (the probe hop
        used to drop out of the request story)."""
        from http.server import BaseHTTPRequestHandler, HTTPServer

        from tfidf_tpu.cluster.node import http_get_stream
        from tfidf_tpu.utils.tracing import global_tracer

        seen = {}

        class Probe(BaseHTTPRequestHandler):
            def do_GET(self):
                seen["trace"] = self.headers.get("X-Trace-Id")
                body = b"doc"
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, fmt, *args):
                pass

        srv = HTTPServer(("127.0.0.1", 0), Probe)
        t = threading.Thread(target=srv.serve_forever, daemon=True)
        t.start()
        try:
            url = (f"http://127.0.0.1:{srv.server_address[1]}"
                   f"/worker/download?path=x")
            with global_tracer.span("leader.download") as sp:
                resp = http_get_stream(url, timeout=5.0)
                assert resp.read() == b"doc"
                resp.close()
            assert seen["trace"] == sp.trace_id
        finally:
            srv.shutdown()
            srv.server_close()

"""Distributed scoring over an 8-virtual-device CPU mesh.

Validates the collectives (psum global IDF, terms-axis score reduce,
all_gather top-k merge) against the single-device kernel and the numpy
oracle — the multi-worker behavior the reference only ever tested manually
(SURVEY.md §4).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tests.oracle import bm25_scores, df_of, random_corpus
from tfidf_tpu.ops.csr import build_coo
from tfidf_tpu.ops.scoring import make_query_batch
from tfidf_tpu.parallel.mesh import default_mesh_shape, make_mesh
from tfidf_tpu.parallel.sharded import (build_sharded_arrays, global_stats,
                                        make_sharded_search,
                                        shard_documents)


def _shard(rng, n_docs=50, vocab=40):
    docs, lengths = random_corpus(rng, n_docs=n_docs, vocab=vocab)
    s = build_coo(docs, 64, min_nnz_cap=256, min_doc_cap=16)
    s.doc_len[:n_docs] = lengths
    return docs, lengths, s


def _queries(qs, max_terms=8):
    B = len(qs)
    qt = np.zeros((B, max_terms), np.int32)
    qw = np.zeros((B, max_terms), np.float32)
    for i, q in enumerate(qs):
        for j, (t, w) in enumerate(sorted(q.items())):
            qt[i, j] = t
            qw[i, j] = w
    return make_query_batch(qt, qw, min_slots=8)


def test_mesh_shapes():
    assert default_mesh_shape(8) == (4, 2)
    assert default_mesh_shape(4) == (4, 1)
    assert default_mesh_shape(1) == (1, 1)
    mesh = make_mesh((4, 2))
    assert mesh.shape == {"docs": 4, "terms": 2}
    with pytest.raises(ValueError):
        make_mesh((3, 2))


@pytest.mark.parametrize("shape", [(8, 1), (4, 2), (2, 4), (1, 8)])
def test_sharded_search_matches_oracle(rng, shape):
    docs, lengths, shard = _shard(rng)
    mesh = make_mesh(shape)
    arrays = build_sharded_arrays(shard, mesh, min_chunk_cap=64)
    queries = [{1: 1.0, 2: 2.0}, {7: 1.0}, {0: 1.0, 13: 3.0}]
    qb = _queries(queries)
    search = make_sharded_search(mesh, k=10, model="bm25", chunk=64)
    vals, gids = search(arrays, qb)
    vals, gids = np.asarray(vals), np.asarray(gids)

    assign = shard_documents(len(docs), shape[0])
    # map (shard, local) -> global doc
    local_of = {}
    counters = [0] * shape[0]
    for g, s in enumerate(assign):
        local_of[(int(s), counters[s])] = g
        counters[s] += 1
    for i, q in enumerate(queries):
        want = np.asarray(bm25_scores(docs, lengths, q))
        order = np.argsort(-want, kind="stable")
        k_pos = int((want > 0).sum())
        got_scores = vals[i]
        np.testing.assert_allclose(
            np.sort(got_scores[:min(10, k_pos)])[::-1],
            np.sort(want[order[:min(10, k_pos)]])[::-1], rtol=1e-4)
        # ids decode to the right documents
        for v, gid in zip(vals[i], gids[i]):
            if not np.isfinite(v) or v <= 0:
                continue
            s, local = divmod(int(gid), arrays.doc_cap)
            g = local_of[(s, local)]
            np.testing.assert_allclose(v, want[g], rtol=1e-4, atol=1e-6)


def test_global_stats(rng):
    docs, lengths, shard = _shard(rng)
    mesh = make_mesh((4, 2))
    arrays = build_sharded_arrays(shard, mesh, min_chunk_cap=64)
    n, avgdl = global_stats(arrays)
    assert int(n) == len(docs)
    np.testing.assert_allclose(float(avgdl), np.mean(lengths), rtol=1e-5)


def test_parity_mode_uses_local_stats(rng):
    """global_idf=False must reproduce per-worker scoring: each docs-shard
    scores with its own df/N/avgdl, like independent Lucene workers."""
    docs, lengths, shard = _shard(rng, n_docs=24)
    D = 4
    mesh = make_mesh((D, 2))
    arrays = build_sharded_arrays(shard, mesh, min_chunk_cap=64)
    q = {1: 1.0, 3: 1.0}
    qb = _queries([q])
    search = make_sharded_search(mesh, k=24, model="bm25",
                                 global_idf=False, chunk=64)
    vals, gids = search(arrays, qb)
    vals, gids = np.asarray(vals)[0], np.asarray(gids)[0]

    assign = shard_documents(len(docs), D)
    got = {}
    for v, gid in zip(vals, gids):
        if np.isfinite(v) and v > 0:
            got[int(gid)] = float(v)
    # oracle: score each shard independently
    counters = [0] * D
    for g, s in enumerate(assign):
        local = counters[int(s)]
        counters[int(s)] += 1
        sdocs = [d for d2, d in enumerate(docs) if assign[d2] == s]
        slens = [l for d2, l in enumerate(lengths) if assign[d2] == s]
        want = bm25_scores(sdocs, slens, q)
        # position of g within its shard == local
        gid = int(s) * arrays.doc_cap + local
        if want[local] > 0:
            np.testing.assert_allclose(got[gid], want[local],
                                       rtol=1e-4, atol=1e-6)


def test_eight_device_cpu_mesh_available():
    assert len(jax.devices()) == 8


def test_sharded_cosine_model(rng):
    from tests.oracle import tfidf_scores
    docs, lengths, shard = _shard(rng, n_docs=30)
    mesh = make_mesh((4, 2))
    arrays = build_sharded_arrays(shard, mesh, min_chunk_cap=64)
    q = {1: 1.0, 3: 2.0}
    qb = _queries([q])
    search = make_sharded_search(mesh, k=10, model="tfidf_cosine", chunk=64)
    vals, gids = search(arrays, qb)
    want = np.asarray(tfidf_scores(docs, q, cosine=True))
    top = np.sort(want[want > 0])[::-1][:10]
    got = np.asarray(vals)[0]
    got = got[np.isfinite(got) & (got > 0)]
    np.testing.assert_allclose(np.sort(got)[::-1], top, rtol=1e-4)


def test_sharded_ingest_then_search(rng):
    """On-device index growth: append new docs, global IDF/avgdl shift, and
    search must match the oracle over the combined corpus."""
    from tfidf_tpu.parallel.sharded import build_ingest_batch, make_sharded_ingest

    docs, lengths, shard = _shard(rng, n_docs=20, vocab=30)
    D, T = 4, 2
    mesh = make_mesh((D, T))
    arrays = build_sharded_arrays(shard, mesh, min_chunk_cap=256)
    ingest = make_sharded_ingest(mesh)

    new_docs, new_lengths = random_corpus(rng, n_docs=8, vocab=30)
    assign = shard_documents(len(docs), D)
    n_live_before = [int((assign == s).sum()) for s in range(D)]
    # place new docs round-robin too (continuing the pattern)
    per_shard_docs = [[] for _ in range(D)]
    per_shard_lens = [[] for _ in range(D)]
    placement = []
    for i, (d_counts, dl) in enumerate(zip(new_docs, new_lengths)):
        s = i % D
        placement.append((s, n_live_before[s] + len(per_shard_docs[s])))
        per_shard_docs[s].append(d_counts)
        per_shard_lens[s].append(dl)
    batch = build_ingest_batch(mesh, arrays, per_shard_docs, per_shard_lens,
                               64)
    arrays2 = ingest(arrays, *batch)

    # combined-corpus oracle
    all_docs = docs + new_docs
    all_lens = lengths + new_lengths
    q = {1: 1.0, 3: 2.0}
    qb = _queries([q])
    search = make_sharded_search(mesh, k=15, model="bm25", chunk=64)
    vals, gids = search(arrays2, qb)
    want = np.asarray(bm25_scores(all_docs, all_lens, q))

    # build global-id map: old docs then new placements
    local_of = {}
    counters = [0] * D
    for g, s in enumerate(assign):
        local_of[(int(s), counters[s])] = g
        counters[s] += 1
    for i, (s, local) in enumerate(placement):
        local_of[(s, local)] = len(docs) + i

    n_pos = int((want > 0).sum())
    kk = min(15, n_pos)
    np.testing.assert_allclose(
        np.sort(np.asarray(vals)[0, :kk])[::-1],
        np.sort(want[np.argsort(-want)[:kk]])[::-1], rtol=1e-4)
    for v, gid in zip(np.asarray(vals)[0], np.asarray(gids)[0]):
        if np.isfinite(v) and v > 0:
            s, local = divmod(int(gid), arrays.doc_cap)
            np.testing.assert_allclose(v, want[local_of[(s, local)]],
                                       rtol=1e-4, atol=1e-6)
    # new docs are actually findable
    assert int(np.asarray(arrays2.n_live).sum()) == len(all_docs)

import numpy as np
import pytest

from tfidf_tpu.models.base import get_model
from tfidf_tpu.models.bm25 import (BM25Model, byte4_to_int, int_to_byte4,
                                   quantize_length, quantize_lengths)


def test_byte4_roundtrip_small_exact():
    # SmallFloat byte4 represents small ints exactly (the free values)
    for i in range(40):
        assert byte4_to_int(int_to_byte4(i)) == i


def test_byte4_monotone():
    prev = -1
    for i in range(0, 100000, 7):
        enc = int_to_byte4(i)
        assert 0 <= enc <= 255
        dec = byte4_to_int(enc)
        assert dec <= i          # truncation, never rounds up
        assert dec >= prev
        prev = dec


def test_byte4_idempotent():
    for i in [0, 1, 39, 40, 100, 1000, 123456, 10**9]:
        q = quantize_length(i)
        assert quantize_length(q) == q


def test_quantize_lengths_vectorized_matches_scalar():
    vals = np.array([0, 1, 5, 39, 40, 41, 100, 999, 12345, 10**6])
    vec = quantize_lengths(vals)
    for v, q in zip(vals, vec):
        assert q == quantize_length(int(v))


def test_bm25_parity_transform():
    m = BM25Model(lucene_parity=True)
    out = m.transform_doc_len(np.array([100.0, 3.0], np.float32))
    assert out.dtype == np.float32
    assert out[1] == 3.0
    assert out[0] <= 100.0
    m2 = BM25Model(lucene_parity=False)
    np.testing.assert_array_equal(
        m2.transform_doc_len(np.array([100.0])), [100.0])


def test_get_model():
    assert get_model("bm25").kind == "bm25"
    assert get_model("tfidf").kind == "tfidf"
    assert get_model("tfidf_cosine").needs_norms
    assert not get_model("bm25").needs_norms
    with pytest.raises(ValueError):
        get_model("nope")


def test_query_weights_multiplicity():
    m = get_model("bm25")
    assert m.query_weights({3: 2, 5: 1}) == {3: 2.0, 5: 1.0}

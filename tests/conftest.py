"""Test env: force the CPU backend with 8 virtual devices BEFORE jax loads,
so mesh/sharding tests exercise real collectives without TPU hardware
(SURVEY.md §4's prescribed strategy)."""

import os

# Force CPU even when the ambient env selects a TPU platform (e.g. axon):
# tests must not occupy the real chip and need 8 virtual devices. The env
# vars alone are not enough here because a sitecustomize may import jax at
# interpreter startup (latching JAX_PLATFORMS) — jax.config.update still
# works as long as no backend has been initialized yet.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    # older jax (< 0.4.34-ish) has no jax_num_cpu_devices option; the
    # XLA_FLAGS host-platform-device-count export above already covers it
    # as long as no backend initialized yet — never fail collection here
    pass

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(0)


@pytest.fixture(autouse=True)
def _reset_faults_and_metrics():
    from tfidf_tpu.utils.faults import global_injector
    from tfidf_tpu.utils.metrics import global_metrics
    yield
    global_injector.disarm()
    global_injector.fired.clear()
    global_metrics.reset()

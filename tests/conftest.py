"""Test env: force the CPU backend with 8 virtual devices BEFORE jax loads,
so mesh/sharding tests exercise real collectives without TPU hardware
(SURVEY.md §4's prescribed strategy)."""

import os

# Force CPU even when the ambient env selects a TPU platform (e.g. axon):
# tests must not occupy the real chip and need 8 virtual devices. The env
# vars alone are not enough here because a sitecustomize may import jax at
# interpreter startup (latching JAX_PLATFORMS) — jax.config.update still
# works as long as no backend has been initialized yet.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    # older jax (< 0.4.34-ish) has no jax_num_cpu_devices option; the
    # XLA_FLAGS host-platform-device-count export above already covers it
    # as long as no backend initialized yet — never fail collection here
    pass

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(0)


@pytest.fixture(autouse=True)
def _reset_faults_and_metrics():
    from tfidf_tpu.utils.faults import global_injector
    from tfidf_tpu.utils.metrics import global_metrics
    from tfidf_tpu.utils.storage import global_storage
    yield
    global_injector.disarm()
    global_injector.fired.clear()
    global_storage.heal()
    global_storage.fired.clear()
    global_metrics.reset()


@pytest.fixture(scope="session", autouse=True)
def _lockdep_witness():
    """GRAFTCHECK_LOCKDEP=1 runs the WHOLE selected suite under the
    instrumented Lock (tools/graftcheck/witness.py): every lock the
    package constructs during the run is order-tracked, and at session
    end the observed acquisition orders must contain zero inversions
    and nothing the static lock graph cannot explain. The CI graftcheck
    job runs the chaos/resilience suites this way; plain runs are
    untouched (raw threading primitives)."""
    if os.environ.get("GRAFTCHECK_LOCKDEP") != "1":
        yield
        return
    import sys
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    # make sure every package module exists BEFORE install: the witness
    # patches already-imported module namespaces only
    import tfidf_tpu.cli  # noqa: F401
    import tfidf_tpu.cluster.node  # noqa: F401
    import tfidf_tpu.engine.pipeline  # noqa: F401
    import tfidf_tpu.parallel.mesh  # noqa: F401
    from tools.graftcheck.witness import LockdepWitness
    w = LockdepWitness()
    w.install()
    yield
    w.uninstall()
    # min_multilock_edges=1: a witness that observed NOTHING is a
    # broken witness (proxy bypassed, install ordering drifted), not a
    # clean run — the gate must fail vacuous passes
    rep = w.check(min_multilock_edges=1)
    print(f"\nlockdep witness: {len(rep['observed_edges'])} multi-lock "
          f"ordering(s) observed, 0 inversions, all statically "
          f"explained")


@pytest.fixture(scope="session", autouse=True)
def _protocol_witness():
    """GRAFTCHECK_PROTOCOL=1 runs the selected suite with the handler
    classes instrumented (tools/graftcheck/protocol_witness.py): every
    real HTTP exchange is recorded, and at session end each one must be
    explained by the statically computed wire contract (routes,
    statuses, required stamps) while the core scatter/mutation surface
    must actually have been exercised. `make protocol-witness` runs the
    router + partition suites this way; plain runs are untouched."""
    if os.environ.get("GRAFTCHECK_PROTOCOL") != "1":
        yield
        return
    import sys
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from tools.graftcheck.protocol_witness import (CORE_EXERCISED,
                                                   ProtocolWitness)
    w = ProtocolWitness()
    w.install()
    yield
    w.uninstall()
    rep = w.check(require_exercised=CORE_EXERCISED, min_exchanges=50)
    print(f"\nprotocol witness: "
          f"{sum(w.exchanges.values())} exchange(s) across "
          f"{len(rep['paths'])} endpoint(s) observed, all explained "
          f"by the static wire contract")


@pytest.fixture(scope="session", autouse=True)
def _device_witness():
    """GRAFTCHECK_DEVICE=1 runs the selected suite under the device
    witness (tools/graftcheck/device_witness.py): XLA compile events
    are counted and the ``np`` binding in every package module records
    d2h fetches of device arrays — at session end every observed
    transfer site must be explained by the static devicecheck cone
    (the named fetch stage or an allowlisted-with-reason site). The
    per-test compile churn of a suite is expected, so the suite-wide
    gate checks transfers only; the steady-state zero-recompile gate
    is the dedicated test in tests/test_devicecheck.py.
    GRAFTCHECK_DEVICE_MIN floors the observation count (vacuous-pass
    guard: `make device-witness` sets it, single-suite debugging runs
    need not). Plain runs are untouched (raw numpy)."""
    if os.environ.get("GRAFTCHECK_DEVICE") != "1":
        yield
        return
    import sys
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    # the witness patches already-imported module namespaces only
    import tfidf_tpu.engine.pipeline  # noqa: F401
    import tfidf_tpu.engine.searcher  # noqa: F401
    import tfidf_tpu.engine.tiering  # noqa: F401
    from tools.graftcheck.device_witness import DeviceWitness
    w = DeviceWitness()
    w.install()
    yield
    w.uninstall()
    w.check(min_observations=int(
        os.environ.get("GRAFTCHECK_DEVICE_MIN", "0")))
    print("\n" + w.report() + "\n  all transfer sites statically "
          "explained")

"""Deployment artifacts stay structurally valid (VERDICT r1 #7).

No kubectl/docker in CI, so these are structural dry-runs: the manifest
must parse and carry the reference layout's load-bearing pieces
(3 replicas, pod anti-affinity, Downward-API pod IP, coordinator service,
volumes — reference README.MD:49-108), and the Dockerfile must install
the package and run the node entrypoint.
"""

import os

import pytest

yaml = pytest.importorskip("yaml")

HERE = os.path.dirname(os.path.abspath(__file__))
ROOT = os.path.join(HERE, "..")


def test_k8s_manifest_structure():
    with open(os.path.join(ROOT, "deploy", "k8s.yaml")) as f:
        docs = [d for d in yaml.safe_load_all(f) if d]
    kinds = sorted(d["kind"] for d in docs)
    assert kinds == ["Deployment", "Deployment",
                     "HorizontalPodAutoscaler",
                     "HorizontalPodAutoscaler",
                     "Namespace",
                     "PodDisruptionBudget", "PodDisruptionBudget",
                     "PodDisruptionBudget",
                     "Service", "Service", "Service",
                     "Service", "StatefulSet"]
    deployments = {d["metadata"]["name"]: d for d in docs
                   if d["kind"] == "Deployment"}
    assert set(deployments) == {"tfidf-node", "tfidf-router"}

    node = deployments["tfidf-node"]["spec"]
    assert node["replicas"] == 3
    pod = node["template"]["spec"]
    anti = pod["affinity"]["podAntiAffinity"]
    rule = anti["requiredDuringSchedulingIgnoredDuringExecution"][0]
    assert rule["topologyKey"] == "kubernetes.io/hostname"

    env = {e["name"]: e for e in pod["containers"][0]["env"]}
    # Downward-API pod IP, like the reference's POD_IP
    assert env["TFIDF_HOST"]["valueFrom"]["fieldRef"][
        "fieldPath"] == "status.podIP"
    # ensemble connect string: all three stable member DNS names
    connect = env["TFIDF_COORDINATOR_ADDRESS"]["value"]
    members = connect.split(",")
    assert len(members) == 3
    for i, m in enumerate(members):
        assert m == (f"tfidf-coordinator-{i}"
                     f".tfidf-coordinator-peers:2181")
    # every env var must be a real Config field
    from tfidf_tpu.utils.config import Config
    fields = {f.upper() for f in Config.__dataclass_fields__}
    for name in env:
        assert name.startswith("TFIDF_")
        assert name[len("TFIDF_"):] in fields, name
    # the dense plane is an explicit per-fleet capacity decision, not
    # an inherited default (off => dense/hybrid 400 loudly)
    assert env["TFIDF_EMBEDDING_ENABLED"]["value"] == "true"
    assert env["TFIDF_EMBEDDING_MODEL"]["value"] == "hash"

    mounts = {m["name"]: m["mountPath"]
              for m in pod["containers"][0]["volumeMounts"]}
    assert mounts == {"documents": "/app/documents", "index": "/app/index"}
    vols = {v["name"] for v in pod["volumes"]}
    assert vols == {"documents", "index"}

    # readiness is COMPUTE readiness (ISSUE 20): the probe must hit
    # /api/ready — a sick device with no host fallback takes the pod
    # out of Service endpoints; degraded (host-mirror) serving and a
    # merely-sick-but-falling-back device stay Ready. Any drift back
    # to /api/status would silently keep unqueryable pods in rotation.
    probe = pod["containers"][0]["readinessProbe"]["httpGet"]
    assert probe["path"] == "/api/ready"
    assert probe["port"] == 8085


def test_k8s_autopilot_enabled_with_clamps():
    """The manifest ships the SLO autopilot, not hand-tuned constants:
    the guessed TFIDF_SCATTER_HEDGE_MS=250 is gone (the hedge delay is
    derived from the observed scatter p95 on whatever hardware the
    pods land on), replaced by autopilot enablement plus a
    conservative clamp envelope and the operator-owned p99 SLO."""
    with open(os.path.join(ROOT, "deploy", "k8s.yaml")) as f:
        docs = [d for d in yaml.safe_load_all(f) if d]
    node = next(d for d in docs if d["kind"] == "Deployment")
    pod = node["spec"]["template"]["spec"]
    env = {e["name"]: e.get("value")
           for e in pod["containers"][0]["env"]}
    # the hand-tuned constant must NOT come back
    assert "TFIDF_SCATTER_HEDGE_MS" not in env
    assert env["TFIDF_AUTOPILOT_ENABLED"] == "true"
    # conservative clamp envelope: floor < ceiling, both positive
    floor = float(env["TFIDF_AUTOPILOT_HEDGE_FLOOR_MS"])
    ceil = float(env["TFIDF_AUTOPILOT_HEDGE_CEILING_MS"])
    assert 0 < floor < ceil
    assert float(env["TFIDF_AUTOPILOT_P99_SLO_MS"]) > 0
    # every autopilot env var is a real Config field (the generic
    # env-override loop must be able to load each one)
    from tfidf_tpu.utils.config import Config
    fields = {f.upper() for f in Config.__dataclass_fields__}
    for name in env:
        if name.startswith("TFIDF_AUTOPILOT"):
            assert name[len("TFIDF_"):] in fields, name


def test_k8s_coordinator_ensemble():
    """The coordination substrate deploys as a 3-member quorum ensemble:
    StatefulSet + headless peer service + PVC-backed --data-dir (the
    round-5 VERDICT's single-replica in-memory coordinator gap)."""
    with open(os.path.join(ROOT, "deploy", "k8s.yaml")) as f:
        docs = [d for d in yaml.safe_load_all(f) if d]
    sts = [d for d in docs if d["kind"] == "StatefulSet"]
    assert len(sts) == 1 and sts[0]["metadata"]["name"] == (
        "tfidf-coordinator")
    spec = sts[0]["spec"]
    assert spec["replicas"] == 3
    # headless peer service for stable per-member DNS names
    headless = [d for d in docs if d["kind"] == "Service"
                and d["metadata"]["name"] == spec["serviceName"]]
    assert headless and headless[0]["spec"].get("clusterIP") == "None"

    pod = spec["template"]["spec"]
    anti = pod["affinity"]["podAntiAffinity"]
    rule = anti["requiredDuringSchedulingIgnoredDuringExecution"][0]
    assert rule["topologyKey"] == "kubernetes.io/hostname"

    args = " ".join(pod["containers"][0]["args"])
    assert "--data-dir /data" in args
    assert "--node-id" in args
    for i in range(3):
        assert (f"tfidf-coordinator-{i}=tfidf-coordinator-{i}"
                f".tfidf-coordinator-peers:2181") in args

    # WAL + snapshots live on a PVC, not pod-ephemeral storage
    pvcs = {t["metadata"]["name"]: t
            for t in spec["volumeClaimTemplates"]}
    assert "data" in pvcs
    mounts = {m["name"]: m["mountPath"]
              for m in pod["containers"][0]["volumeMounts"]}
    assert mounts["data"] == "/data"


def test_k8s_hpa_autoscaling():
    """The worker autoscaling story (ROADMAP item 1's HPA pairing):
    the search-node Deployment scales on the serving-pressure gauges
    /api/metrics already emits, and every metric the HPA keys on must
    correspond to a gauge actually emitted somewhere in the tree —
    a renamed gauge must fail here, not silently stop scaling."""
    with open(os.path.join(ROOT, "deploy", "k8s.yaml")) as f:
        docs = [d for d in yaml.safe_load_all(f) if d]
    hpas = {d["spec"]["scaleTargetRef"]["name"]: d for d in docs
            if d["kind"] == "HorizontalPodAutoscaler"}
    assert set(hpas) == {"tfidf-node", "tfidf-router"}
    spec = hpas["tfidf-node"]["spec"]
    ref = spec["scaleTargetRef"]
    assert ref["kind"] == "Deployment" and ref["name"] == "tfidf-node"
    # the HPA floor matches the Deployment's replica count
    node = next(d for d in docs if d["kind"] == "Deployment"
                and d["metadata"]["name"] == "tfidf-node")
    assert spec["minReplicas"] == node["spec"]["replicas"]
    assert spec["maxReplicas"] > spec["minReplicas"]

    names = {m["pods"]["metric"]["name"] for m in spec["metrics"]
             if m["type"] == "Pods"}
    assert names == {"tfidf_last_scatter_queue_depth",
                     "tfidf_index_size_bytes"}
    # each adapter-exported series (tfidf_<gauge>) maps to a gauge the
    # code emits: index_size_bytes is a literal set_gauge name, the
    # queue-depth gauge is the coalescer's f"last_{name}_queue_depth"
    # with the scatter batcher named "scatter"
    src = ""
    pkg = os.path.join(ROOT, "tfidf_tpu")
    for dirpath, _dirs, files in os.walk(pkg):
        for fn in files:
            if fn.endswith(".py"):
                with open(os.path.join(dirpath, fn),
                          encoding="utf-8") as f:
                    src += f.read()
    assert '"index_size_bytes"' in src
    assert '_queue_depth"' in src
    assert 'name="scatter"' in src

    # graceful scale-down: a long stabilization window so operators can
    # drain workers before pods disappear
    assert spec["behavior"]["scaleDown"][
        "stabilizationWindowSeconds"] >= 300


def test_k8s_router_tier():
    """The scale-out query plane ships as a STATELESS router tier
    (README "Scale-out query plane"): a Deployment with no volumes
    (nothing to lose — scale-down just deletes pods), its own Service,
    and an autoscaling/v2 HPA keyed on the per-router queue-depth
    gauge the router's scatter coalescer actually emits."""
    with open(os.path.join(ROOT, "deploy", "k8s.yaml")) as f:
        docs = [d for d in yaml.safe_load_all(f) if d]
    router = next(d for d in docs if d["kind"] == "Deployment"
                  and d["metadata"]["name"] == "tfidf-router")
    spec = router["spec"]
    assert spec["replicas"] >= 2
    pod = spec["template"]["spec"]
    c = pod["containers"][0]
    assert c["args"] == ["router"]
    # stateless: no volumes, no PVCs — a router holds nothing durable
    assert "volumes" not in pod
    assert "volumeMounts" not in c
    env = {e["name"]: e for e in c["env"]}
    # same coordination connect string as the nodes
    assert env["TFIDF_COORDINATOR_ADDRESS"]["value"].count(",") == 2
    assert env["TFIDF_HOST"]["valueFrom"]["fieldRef"][
        "fieldPath"] == "status.podIP"
    # every TFIDF_ env var (except the JAX platform pin, which is a
    # CLI-level override, not a Config field) must be a real Config
    # field the generic env loop can load
    from tfidf_tpu.utils.config import Config
    fields = {f.upper() for f in Config.__dataclass_fields__}
    for name in env:
        if name == "TFIDF_JAX_PLATFORM":
            continue
        assert name.startswith("TFIDF_")
        assert name[len("TFIDF_"):] in fields, name
    # scraped like the nodes (the HPA's custom metric comes from here)
    ann = spec["template"]["metadata"]["annotations"]
    assert ann["prometheus.io/scrape"] == "true"
    assert ann["prometheus.io/path"] == "/metrics"

    # the router Service fronts the tier
    svc = [d for d in docs if d["kind"] == "Service"
           and d["metadata"]["name"] == "tfidf-router"]
    assert svc and svc[0]["spec"]["selector"] == {"app": "tfidf-router"}

    # the router HPA scales on the per-router coalescer gauge — and
    # that gauge name must map to what the code emits: the coalescer's
    # f"last_{name}_queue_depth" with the router batcher named
    # "router_scatter"
    hpa = next(d for d in docs if d["kind"] == "HorizontalPodAutoscaler"
               and d["spec"]["scaleTargetRef"]["name"] == "tfidf-router")
    spec = hpa["spec"]
    assert spec["minReplicas"] == router["spec"]["replicas"]
    assert spec["maxReplicas"] > spec["minReplicas"]
    names = {m["pods"]["metric"]["name"] for m in spec["metrics"]
             if m["type"] == "Pods"}
    assert names == {"tfidf_last_router_scatter_queue_depth"}
    with open(os.path.join(ROOT, "tfidf_tpu", "cluster",
                           "router.py"), encoding="utf-8") as f:
        src = f.read()
    assert 'name="router_scatter"' in src
    assert '_queue_depth' in src


def test_k8s_rolling_upgrade_budget():
    """Zero-downtime fleet evolution (README "Versioning &
    zero-downtime upgrades"): both Deployments roll one pod at a time
    (maxUnavailable: 1 — the order chaos-upgrade rehearses) and every
    tier carries a PodDisruptionBudget so voluntary drains obey the
    same rule. The coordinator budget must preserve quorum (2 of 3);
    the node budget must never leave fewer standing than the
    replication factor the manifest itself configures."""
    with open(os.path.join(ROOT, "deploy", "k8s.yaml")) as f:
        docs = [d for d in yaml.safe_load_all(f) if d]
    deployments = {d["metadata"]["name"]: d for d in docs
                   if d["kind"] == "Deployment"}
    for name, dep in deployments.items():
        strat = dep["spec"]["strategy"]
        assert strat["type"] == "RollingUpdate", name
        assert strat["rollingUpdate"]["maxUnavailable"] == 1, name

    pdbs = {d["metadata"]["name"]: d["spec"] for d in docs
            if d["kind"] == "PodDisruptionBudget"}
    assert set(pdbs) == {"tfidf-coordinator", "tfidf-node",
                         "tfidf-router"}
    # each budget selects its own tier's pods
    for name, spec in pdbs.items():
        assert spec["selector"]["matchLabels"] == {"app": name}, name

    # coordinator: majority of the 3-member ensemble must stand
    sts = next(d for d in docs if d["kind"] == "StatefulSet")
    assert pdbs["tfidf-coordinator"]["minAvailable"] >= (
        sts["spec"]["replicas"] // 2 + 1)

    # nodes: never fewer standing than the replication factor
    node = deployments["tfidf-node"]
    env = {e["name"]: e.get("value")
           for e in node["spec"]["template"]["spec"]["containers"][0][
               "env"]}
    rf = int(env["TFIDF_REPLICATION_FACTOR"])
    assert pdbs["tfidf-node"]["minAvailable"] >= rf
    # and the budget is satisfiable: minAvailable < replicas, or no
    # voluntary disruption is ever allowed and drains wedge forever
    assert pdbs["tfidf-node"]["minAvailable"] < node["spec"]["replicas"]

    # routers: the front door never drains empty
    assert pdbs["tfidf-router"]["minAvailable"] >= 1
    router = deployments["tfidf-router"]
    assert pdbs["tfidf-router"]["minAvailable"] < router["spec"][
        "replicas"]


def test_dockerfile_structure():
    with open(os.path.join(ROOT, "Dockerfile")) as f:
        content = f.read()
    assert "COPY tfidf_tpu" in content
    assert 'ENTRYPOINT ["python", "-m", "tfidf_tpu"]' in content
    assert "EXPOSE 8085" in content
    # env defaults must be real Config fields
    from tfidf_tpu.utils.config import Config
    fields = {f.upper() for f in Config.__dataclass_fields__}
    for line in content.splitlines():
        line = line.strip().lstrip("ENV").strip()
        if line.startswith("TFIDF_"):
            name = line.split("=")[0]
            assert name[len("TFIDF_"):] in fields, name

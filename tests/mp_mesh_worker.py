"""Subprocess body for the REAL multi-process ``jax.distributed`` test.

Each OS process owns 2 virtual CPU devices; ``jax.distributed.initialize``
joins them into one global device view, and the mesh engine runs ingest +
commit + search over a mesh that SPANS the process boundary — the psum of
document frequencies and the top-k all_gather cross processes over the
gloo collective backend, which is exactly the SPMD shape a DCN-connected
TPU pod runs (SURVEY.md §5.8). Every process executes the identical
program on identical inputs and must get the identical (and
local-engine-equivalent) results.

Invoked by tests/test_multihost.py and probe_multihost.py; not a test
module itself.
"""

from __future__ import annotations

import os
import sys

TEXTS = {
    "a.txt": "the quick brown fox jumps over the lazy dog",
    "b.txt": "a fast brown fox and a quick red fox",
    "c.txt": "lorem ipsum dolor sit amet",
    "d.txt": "the dog sleeps all day long",
    "e.txt": "red dogs chase brown foxes at dawn",
    "f.txt": "ipsum lorem amet dolor",
    "g.txt": "quick quick quick brown brown dog",
    "h.txt": "foxes and dogs and foxes again",
    "i.txt": "dawn chorus over the lazy meadow",
    "j.txt": "meadow fox naps in the red dawn",
}

QUERIES = ("fox", "brown dog", "lorem ipsum", "red dawn", "meadow",
           "nosuchterm")


def results(engine):
    return [sorted(((h.name, round(h.score, 4))
                    for h in engine.search(q)),
                   key=lambda nv: (-nv[1], nv[0])) for q in QUERIES]


def main() -> None:
    coord, n, pid, tmp = (sys.argv[1], int(sys.argv[2]), int(sys.argv[3]),
                          sys.argv[4])
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    import jax

    from tfidf_tpu.parallel.mesh import initialize_multihost, make_mesh
    assert initialize_multihost(coord, num_processes=n, process_id=pid)
    assert jax.process_count() == n, jax.process_count()
    assert jax.process_index() == pid
    n_dev = len(jax.devices())
    assert n_dev == 2 * n, (n_dev, n)
    assert len(jax.local_devices()) == 2

    from tfidf_tpu.engine.engine import Engine
    from tfidf_tpu.utils.config import Config

    def cfg(sub: str, mode: str, layout: str = "coo") -> Config:
        return Config(documents_path=os.path.join(tmp, f"{sub}{pid}"),
                      engine_mode=mode, mesh_layout=layout,
                      min_doc_capacity=8, min_nnz_capacity=256,
                      min_vocab_capacity=64, query_batch=4,
                      max_query_terms=8)

    local = Engine(cfg("l", "local"))
    # COO layout, all devices on the docs axis (spans both processes)
    mesh_coo = Engine(cfg("mc", "mesh", "coo"),
                      mesh=make_mesh((n_dev, 1)))
    # ELL layout on a (docs, terms) grid: the docs axis crosses the
    # process boundary, terms stays intra-process — the DCN/ICI split
    mesh_ell = Engine(cfg("me", "mesh", "ell"),
                      mesh=make_mesh((n_dev // 2, 2)))
    for e in (local, mesh_coo, mesh_ell):
        for name, text in TEXTS.items():
            e.ingest_text(name, text)
        e.commit()
    want = results(local)
    for label, e in (("coo", mesh_coo), ("ell", mesh_ell)):
        got = results(e)
        assert got == want, (label, got, want)
    # incremental path: append after the first commit, cross-process df
    # must update (psum) and the new doc must be searchable everywhere
    for label, e in (("coo", mesh_coo), ("ell", mesh_ell),
                     ("local", local)):
        e.ingest_text("k.txt", "zebra fox dawn")
        e.commit()
    want2 = results(local)
    for label, e in (("coo", mesh_coo), ("ell", mesh_ell)):
        got2 = results(e)
        assert got2 == want2, (label, got2, want2)
    print(f"MP_MESH_OK pid={pid} procs={jax.process_count()} "
          f"devices={n_dev}", flush=True)


if __name__ == "__main__":
    main()

"""CLI tests: local ingest/search commands and the serve loop's wiring.

Covers the single-binary surface (the reference's fat-jar role): ingest a
directory, search it, checkpoint round-trip through flags, and client
commands against an in-process cluster node.
"""

import json
import os
import threading
import time

import pytest

from tfidf_tpu.cli import build_parser, main


@pytest.fixture
def corpus(tmp_path):
    d = tmp_path / "docs"
    d.mkdir()
    (d / "a.txt").write_text("the quick brown fox")
    (d / "b.txt").write_text("lazy dogs sleep all day")
    return str(d)


def run_cli(capsys, *argv):
    rc = main(list(argv))
    out = capsys.readouterr().out.strip()
    return rc, out


class TestLocalCommands:
    def test_ingest_then_search(self, tmp_path, corpus, capsys):
        rc, out = run_cli(capsys, "ingest", corpus,
                          "--documents-path", corpus)
        assert rc == 0
        assert json.loads(out)["docs"] == 2

        rc, out = run_cli(capsys, "search", "fox",
                          "--documents-path", corpus)
        assert rc == 0
        res = json.loads(out)
        assert res["query"] == "fox"
        assert [h["name"] for h in res["hits"]] == ["a.txt"]

    def test_checkpoint_flags(self, tmp_path, corpus, capsys):
        ckpt = str(tmp_path / "ckpt")
        rc, out = run_cli(capsys, "ingest", corpus,
                          "--documents-path", corpus,
                          "--checkpoint", ckpt)
        assert rc == 0 and os.path.exists(ckpt)
        rc, out = run_cli(capsys, "search", "dogs",
                          "--checkpoint", ckpt)
        hits = json.loads(out)["hits"]
        assert [h["name"] for h in hits] == ["b.txt"]

    def test_config_file_and_flags(self, tmp_path, corpus, capsys):
        cfg = tmp_path / "cfg.json"
        cfg.write_text(json.dumps({"model": "tfidf",
                                   "documents_path": corpus}))
        rc, out = run_cli(capsys, "--config", str(cfg), "search", "fox")
        assert rc == 0
        assert json.loads(out)["hits"][0]["name"] == "a.txt"

    def test_parser_rejects_unknown(self):
        with pytest.raises(SystemExit):
            build_parser().parse_args(["frobnicate"])


class TestClusterClientCommands:
    def test_upload_query_status(self, tmp_path, corpus, capsys):
        from tfidf_tpu.cluster.coordination import (CoordinationCore,
                                                    LocalCoordination)
        from tfidf_tpu.cluster.node import SearchNode
        from tfidf_tpu.utils.config import Config

        core = CoordinationCore(session_timeout_s=2.0)
        nodes = []
        try:
            for i in range(2):
                c = Config(
                    documents_path=str(tmp_path / f"n{i}" / "docs"),
                    index_path=str(tmp_path / f"n{i}" / "idx"),
                    port=0, min_doc_capacity=8, min_nnz_capacity=256,
                    min_vocab_capacity=64, query_batch=4,
                    max_query_terms=8)
                nodes.append(SearchNode(
                    c, coord=LocalCoordination(core, 0.3)).start())
            leader = nodes[0]
            deadline = time.monotonic() + 5
            while (not leader.registry.get_all_service_addresses()
                   and time.monotonic() < deadline):
                time.sleep(0.05)

            f = tmp_path / "up.txt"
            f.write_text("zebra crossing stripes")
            rc, out = run_cli(capsys, "upload", str(f),
                              "--leader", leader.url)
            assert rc == 0 and "uploaded" in out

            # filenames with spaces must be URL-encoded by the client
            g = tmp_path / "my doc.txt"
            g.write_text("quagga herds")
            rc, out = run_cli(capsys, "upload", str(g),
                              "--leader", leader.url)
            assert rc == 0 and "uploaded" in out
            rc, out = run_cli(capsys, "query", "quagga",
                              "--leader", leader.url)
            assert "my doc.txt" in json.loads(out)

            rc, out = run_cli(capsys, "query", "zebra",
                              "--leader", leader.url)
            assert rc == 0
            assert "up.txt" in json.loads(out)

            rc, out = run_cli(capsys, "status", "--leader", leader.url)
            st = json.loads(out)
            assert st["status"] == "I am the leader"
            assert st["services"] == [nodes[1].url]
            # failure-semantics summary: the healthy cluster reports a
            # non-degraded last scatter and no open breakers
            assert st["degraded"]["last_scatter_degraded"] is False
            assert st["degraded"]["circuit_open_workers"] == []

            # bulk: a directory of text files in one batched request
            bdir = tmp_path / "bulk"
            bdir.mkdir()
            for i in range(5):
                (bdir / f"b{i}.txt").write_text(f"okapi spots item{i}")
            rc, out = run_cli(capsys, "upload", str(bdir),
                              "--leader", leader.url, "--batch")
            assert rc == 0 and "5 files uploaded" in out
            rc, out = run_cli(capsys, "query", "item3",
                              "--leader", leader.url)
            assert "b3.txt" in json.loads(out)
        finally:
            for n in nodes:
                try:
                    n.stop()
                except Exception:
                    pass
            core.close()

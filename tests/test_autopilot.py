"""Closed-loop SLO autopilot (cluster/autopilot.py).

Controller math is tested in ISOLATION against synthetic sensor feeds
(step / ramp / noise), because a control loop's failure modes —
oscillation, overshoot, runaway — are properties of the math, not of
the cluster around it: hysteresis dead bands, clamp floors/ceilings,
damped steps, direction confirmation, the kill-switch revert, and the
decision-ring bound all get deterministic pins here. The PINNED
DAMPING TEST is the acceptance artifact: under a step-change sensor
feed the applied adjustments never alternate sign within a
constant-target phase (zero oscillation), while still converging to
within the hysteresis band of the target.

Integration tests run a real in-process node: live histogram
observations drive real knob movement, the decision audit is exported
via ``GET /api/autopilot`` and the CLI, a ``tfidf_autopilot_*`` gauge
exists per managed knob, the sweep that changes a knob carries a
``knob_adjusted`` span event, and the runtime kill switch (``POST
/api/autopilot``) restores exact static config.

The slow chaos job (``make chaos-autopilot``) runs a step-change
zipfian closed loop against a real 3-process cluster with a mid-run
worker ``kill -9``: the autopilot converges without oscillation and
admitted-interactive p99 stays bounded.
"""

import json
import threading
import time
import urllib.error

import numpy as np
import pytest

from tfidf_tpu.cluster.admission import AdmissionController
from tfidf_tpu.cluster.autopilot import (Autopilot, CounterWindow,
                                         HedgeController, HistWindow,
                                         LingerController,
                                         SlowTripController,
                                         WatermarkController,
                                         delta_quantile)
from tfidf_tpu.cluster.batcher import Coalescer
from tfidf_tpu.cluster.coordination import (CoordinationCore,
                                            LocalCoordination)
from tfidf_tpu.cluster.node import SearchNode, http_get, http_post
from tfidf_tpu.cluster.resilience import ClusterResilience
from tfidf_tpu.utils.config import Config
from tfidf_tpu.utils.metrics import BUCKET_BOUNDS_S, global_metrics
from tfidf_tpu.utils.tracing import global_tracer

from tests.test_cluster import wait_until


# ---------------------------------------------------------------------------
# windowed-sensor plumbing
# ---------------------------------------------------------------------------

class TestWindows:
    def test_delta_quantile_oracle_vs_numpy(self):
        """The window-quantile estimate stays within one bucket ratio
        of the true order statistic on uniform and bimodal samples
        (the order statistic, not numpy's default linear
        interpolation: at a bimodal gap the interpolated value lies in
        empty space no sample occupies, which no histogram — or
        serving SLO — should report)."""
        rng = np.random.default_rng(7)
        for samples in (
                rng.uniform(0.001, 0.2, size=2000),
                np.concatenate([rng.normal(0.004, 0.0005, 1000),
                                rng.normal(0.3, 0.02, 1000)]).clip(1e-4)):
            counts = [0] * (len(BUCKET_BOUNDS_S) + 1)
            import bisect
            for s in samples:
                counts[bisect.bisect_left(BUCKET_BOUNDS_S, s)] += 1
            srt = np.sort(samples)
            for q in (0.5, 0.95, 0.99):
                est = delta_quantile(counts, q)
                true = float(srt[int(np.ceil(q * len(srt))) - 1])
                assert est == pytest.approx(true, rel=0.25), (q, est,
                                                              true)

    def test_delta_quantile_empty(self):
        assert delta_quantile([0] * (len(BUCKET_BOUNDS_S) + 1),
                              0.95) is None

    def test_hist_window_returns_only_the_delta(self):
        name = "ap_test_hist_window"
        w = HistWindow(name)
        global_metrics.observe(name, 0.010)
        counts, n = w.advance()
        assert n == 1 and sum(counts) == 1
        # no new samples -> empty window, NOT the cumulative history
        counts, n = w.advance()
        assert n == 0 and sum(counts) == 0
        for _ in range(5):
            global_metrics.observe(name, 0.100)
        counts, n = w.advance()
        assert n == 5 and sum(counts) == 5
        assert delta_quantile(counts, 0.5) == pytest.approx(0.1,
                                                            rel=0.25)

    def test_counter_window(self):
        name = "ap_test_counter_window"
        w = CounterWindow(name)
        global_metrics.inc(name, 3)
        assert w.advance() == 3
        assert w.advance() == 0
        global_metrics.inc(name, 2)
        assert w.advance() == 2


# ---------------------------------------------------------------------------
# controller laws (pure sense() math)
# ---------------------------------------------------------------------------

def _cfg(**kw) -> Config:
    kw.setdefault("autopilot_enabled", True)
    kw.setdefault("autopilot_min_window", 16)
    return Config(**kw)


def _frame(**kw) -> dict:
    f = {"scatter_p95_ms": 0.0, "scatter_n": 0,
         "leader_p99_ms": 0.0, "leader_n": 0,
         "batches": 0.0, "items": 0.0, "sheds": 0.0, "depth": 0.0,
         "max_batch": 128, "worker_ewmas": {}}
    f.update(kw)
    return f


class TestControllerLaws:
    def test_hedge_tracks_p95_plus_epsilon(self):
        c = HedgeController(_cfg(), read=lambda: 0.0,
                            write=lambda v: None)
        assert c.sense(_frame(scatter_p95_ms=80.0, scatter_n=100),
                       0.0)[0] == pytest.approx(90.0)

    def test_hedge_holds_below_min_window(self):
        c = HedgeController(_cfg(), read=lambda: 0.0,
                            write=lambda v: None)
        assert c.sense(_frame(scatter_p95_ms=80.0, scatter_n=3),
                       0.0) is None

    def test_hedge_parks_at_ceiling_under_saturation(self):
        """The Tail-at-Scale caveat: a hedge duplicates load, so while
        queries are queueing (no spare capacity) the controller steers
        the hedge delay to its ceiling instead of the p95 — in-budget
        tail-trimming stops exactly when it would amplify overload.
        Parking is immediate; UNparking is sticky (CALM_SWEEPS
        pressure-free windows), so a flapping saturation edge cannot
        cycle the knob."""
        c = HedgeController(_cfg(), read=lambda: 90.0,
                            write=lambda v: None)
        t, inp = c.sense(_frame(scatter_p95_ms=80.0, scatter_n=100,
                                depth=5.0), 90.0)
        assert t == c.ceiling and inp["parked"] == 1
        # pressure gone: HOLDS through the calm requirement first
        calm = _frame(scatter_p95_ms=80.0, scatter_n=100, depth=0.0)
        for _ in range(HedgeController.CALM_SWEEPS - 1):
            assert c.sense(calm, 90.0) is None
        # sustained calm: back to tracking the tail
        t, _ = c.sense(calm, 90.0)
        assert t == pytest.approx(90.0)
        # one pressure blip re-arms the full calm requirement
        c.sense(_frame(scatter_n=100, depth=2.0), 90.0)
        assert c.sense(calm, 90.0) is None

    def test_watermark_shrinks_over_slo_grows_only_when_shedding(self):
        cfg = _cfg(autopilot_p99_slo_ms=500.0,
                   admission_queue_high_water=100)

        def fresh():
            return WatermarkController(cfg, read=lambda: 100.0,
                                       write=lambda v: None)
        # p99 at 2x the SLO: the tolerated queue halves
        t, _ = fresh().sense(_frame(leader_p99_ms=1000.0,
                                    leader_n=100), 100.0)
        assert t == pytest.approx(50.0)
        # p99 comfortably inside the SLO but sheds happened: grow
        t, _ = fresh().sense(_frame(leader_p99_ms=250.0, leader_n=100,
                                    sheds=5), 100.0)
        assert t == pytest.approx(200.0)
        # in budget, no sheds: nothing to learn
        assert fresh().sense(_frame(leader_p99_ms=250.0,
                                    leader_n=100), 100.0) is None
        # near the SLO (inside the grow guard), even with sheds: hold
        assert fresh().sense(_frame(leader_p99_ms=450.0, leader_n=100,
                                    sheds=5), 100.0) is None

    def test_watermark_peak_hold_blocks_regrow_mid_overload(self):
        """The latency signal is PEAK-HELD over recent windows: under
        zipfian traffic most windows are cache-hit-dominated and calm,
        and one calm window mid-overload must not regrow the watermark
        (re-opening the queue while the tail burns). Growth needs the
        peak itself calm — sustained relief across the hold depth."""
        cfg = _cfg(autopilot_p99_slo_ms=500.0,
                   admission_queue_high_water=100)
        c = WatermarkController(cfg, read=lambda: 100.0,
                                write=lambda v: None)
        t, _ = c.sense(_frame(leader_p99_ms=1000.0, leader_n=100),
                       100.0)
        assert t < 100.0
        # a calm window with sheds right after the bad one: the peak
        # still remembers 1000ms — keep shrinking, never grow
        t, inp = c.sense(_frame(leader_p99_ms=200.0, leader_n=100,
                                sheds=5), 100.0)
        assert inp["peak_p99_ms"] == 1000.0 and t < 100.0
        # after PEAK_WINDOWS calm windows the peak decays: now grow
        for _ in range(WatermarkController.PEAK_WINDOWS):
            out = c.sense(_frame(leader_p99_ms=200.0, leader_n=100,
                                 sheds=5), 100.0)
        t, inp = out
        assert inp["peak_p99_ms"] == 200.0 and t > 100.0

    def test_linger_widens_on_unfilled_pressure_narrows_on_full(self):
        c = LingerController(_cfg(), read=lambda: 8.0,
                             write=lambda v: None)
        # unfilled batches while queries queue: widen
        t, inp = c.sense(_frame(batches=10, items=128, max_batch=64,
                                depth=4.0), 8.0)
        assert t > 8.0 and inp["fill"] == pytest.approx(0.2)
        # unfilled but NO queued pressure: hold (light traffic is not
        # a reason to tax every query's latency ceiling)
        assert c.sense(_frame(batches=10, items=128, max_batch=64,
                              depth=0.0), 8.0) is None
        # batches essentially full: the wait buys nothing, narrow
        t, _ = c.sense(_frame(batches=10, items=608, max_batch=64,
                              depth=4.0), 8.0)
        assert t < 8.0

    def test_slow_trip_needs_two_peers_and_tracks_median(self):
        cfg = _cfg(autopilot_slow_spread_mult=4.0,
                   breaker_slow_min_samples=5)
        c = SlowTripController(cfg, read=lambda: 0.0,
                               write=lambda v: None)
        assert c.sense(_frame(worker_ewmas={"w0": (0.050, 10)}),
                       0.0) is None
        # under-sampled workers are ignored
        assert c.sense(_frame(worker_ewmas={"w0": (0.050, 10),
                                            "w1": (9.0, 2)}),
                       0.0) is None
        t, inp = c.sense(_frame(worker_ewmas={
            "w0": (0.040, 10), "w1": (0.060, 10),
            "w2": (0.050, 10)}), 0.0)
        assert t == pytest.approx(200.0)   # 4 x 50ms median
        assert inp["workers"] == 3


# ---------------------------------------------------------------------------
# the shared discipline: hysteresis / confirmation / damping / clamps
# ---------------------------------------------------------------------------

class _FakeNode:
    """The minimum surface Autopilot needs — real admission controller
    and resilience bundle (the write targets), no HTTP anywhere."""

    def __init__(self, cfg: Config) -> None:
        self.config = cfg
        self.hedge_ms = float(cfg.scatter_hedge_ms)
        self.admission = AdmissionController(cfg, depth_fn=lambda: 0.0)
        self.resilience = ClusterResilience(cfg)
        self.scatter_batcher = None


def _autopilot(**cfg_kw) -> tuple[Autopilot, _FakeNode]:
    cfg = _cfg(**cfg_kw)
    node = _FakeNode(cfg)
    return Autopilot(node), node


def _drive(ap: Autopilot, frames: list[dict]) -> list[list[dict]]:
    """Run one control pass per synthetic frame; returns the applied
    decisions of each pass."""
    feed = iter(frames)
    ap._frame = lambda: next(feed)
    return [ap.run_once() for _ in frames]


def _applied_dirs(ap: Autopilot, knob: str) -> list[int]:
    return [d["direction"] for d in ap.decisions(10_000)
            if d["knob"] == knob and d["applied"]
            and d["reason"] == "adjusted"]


class TestDiscipline:
    def test_hysteresis_dead_band_holds(self):
        ap, node = _autopilot(scatter_hedge_ms=100.0,
                              autopilot_hysteresis=0.15)
        # target 110 is within 15% of current 100: no movement, ever
        _drive(ap, [_frame(scatter_p95_ms=100.0, scatter_n=100)] * 6)
        assert node.hedge_ms == 100.0
        assert all(d["reason"] == "hold:in_band"
                   for d in ap.decisions(100)
                   if d["knob"] == "scatter_hedge_ms")

    def test_direction_confirmation_delays_first_move(self):
        ap, node = _autopilot(scatter_hedge_ms=20.0,
                              autopilot_confirm=2)
        frames = [_frame(scatter_p95_ms=200.0, scatter_n=100)] * 2
        applied = _drive(ap, frames)
        assert applied[0] == []          # sweep 1: confirmation only
        assert len(applied[1]) == 1      # sweep 2: the move lands
        # damped: half of the (210 - 20) error, not the full jump
        assert node.hedge_ms == pytest.approx(115.0)

    def test_damped_convergence_into_band(self):
        ap, node = _autopilot(scatter_hedge_ms=20.0,
                              autopilot_hysteresis=0.15,
                              autopilot_step=0.5)
        _drive(ap, [_frame(scatter_p95_ms=200.0, scatter_n=100)] * 12)
        target = 210.0
        assert abs(target - node.hedge_ms) <= 0.15 * target
        # geometric approach never overshoots the target
        assert node.hedge_ms <= target

    def test_clamps_pin_floor_and_ceiling(self):
        ap, node = _autopilot(scatter_hedge_ms=100.0,
                              autopilot_hedge_floor_ms=50.0,
                              autopilot_hedge_ceiling_ms=300.0)
        _drive(ap, [_frame(scatter_p95_ms=10_000.0,
                           scatter_n=100)] * 20)
        # the knob may NEVER exceed the ceiling, and settles within
        # one hysteresis band of it (the band is relative to current)
        assert 300.0 * 0.85 <= node.hedge_ms <= 300.0
        _drive(ap, [_frame(scatter_p95_ms=0.1, scatter_n=100)] * 20)
        assert 50.0 <= node.hedge_ms <= 50.0 / 0.85

    def test_pinned_damping_no_oscillation_under_step_change(self):
        """THE acceptance pin: a step-change sensor feed (20ms -> 200ms
        -> back to 20ms scatter p95) produces zero sign-alternating
        adjustments within each constant-target phase — the knob walks
        monotonically to each new target and stops inside the
        hysteresis band. Direction changes happen exactly at the two
        genuine target steps, never inside a phase."""
        ap, node = _autopilot(scatter_hedge_ms=25.0,
                              autopilot_hysteresis=0.15,
                              autopilot_step=0.5, autopilot_confirm=2)
        lo = [_frame(scatter_p95_ms=20.0, scatter_n=100)] * 14
        hi = [_frame(scatter_p95_ms=200.0, scatter_n=100)] * 14
        _drive(ap, lo + hi + lo)
        dirs = _applied_dirs(ap, "scatter_hedge_ms")
        assert dirs, "the step change must move the knob"
        flips = sum(1 for a, b in zip(dirs, dirs[1:]) if a != b)
        # two genuine target steps -> at most two direction changes,
        # and NO A/B/A flapping beyond them
        assert flips <= 2, dirs
        # converged back into the band around the low target (the
        # band is relative to the current knob value)
        assert abs(30.0 - node.hedge_ms) <= 0.15 * node.hedge_ms + 0.01

    def test_noise_inside_band_never_moves_the_knob(self):
        ap, node = _autopilot(scatter_hedge_ms=100.0,
                              autopilot_hysteresis=0.15)
        rng = np.random.default_rng(3)
        frames = [_frame(scatter_p95_ms=float(90.0 + rng.uniform(-8, 8)),
                         scatter_n=100) for _ in range(20)]
        _drive(ap, frames)
        assert node.hedge_ms == 100.0

    def test_alternating_noise_beyond_band_blocked_by_confirmation(self):
        """A sensor flapping hard (target far above, then far below,
        every sweep) proposes a new direction each pass — confirmation
        (2 consecutive sweeps) means NOTHING is ever applied: the
        flap cannot reach the knob."""
        ap, node = _autopilot(scatter_hedge_ms=100.0,
                              autopilot_confirm=2)
        frames = []
        for i in range(20):
            p95 = 300.0 if i % 2 == 0 else 20.0
            frames.append(_frame(scatter_p95_ms=p95, scatter_n=100))
        applied = _drive(ap, frames)
        assert all(a == [] for a in applied)
        assert node.hedge_ms == 100.0

    def test_reversal_guard_blocks_marginal_undo(self):
        """After an applied adjustment, undoing it demands an error
        beyond TWICE the hysteresis band: noise that barely clears the
        band cannot walk the knob back, while a genuine step (error >>
        band) reverses after the usual confirmation."""
        ap, node = _autopilot(scatter_hedge_ms=20.0,
                              autopilot_hysteresis=0.15)
        # walk the knob up and let it settle near 210
        _drive(ap, [_frame(scatter_p95_ms=200.0, scatter_n=100)] * 10)
        settled = node.hedge_ms
        assert settled > 150.0
        # a marginal pull-down: ~25% below current clears the band
        # (15%) but not the reversal guard (30%) — never applied
        marginal = settled * 0.75 - 10.0   # target = p95 + 10
        _drive(ap, [_frame(scatter_p95_ms=marginal,
                           scatter_n=100)] * 6)
        assert node.hedge_ms == settled
        assert any(d["reason"] == "hold:reversal_guard"
                   for d in ap.decisions(200))
        # a genuine collapse reverses (error >> 2x band)
        _drive(ap, [_frame(scatter_p95_ms=20.0, scatter_n=100)] * 10)
        assert node.hedge_ms < settled

    def test_raw_agreement_gates_smoothed_drift(self):
        """Target smoothing must not let an alternating sensor sneak
        its MEAN past confirmation: each confirming sweep's raw sample
        must itself point beyond the band in the same direction."""
        ap, _node = _autopilot(scatter_hedge_ms=100.0)
        frames = []
        for i in range(12):
            p95 = 290.0 if i % 2 == 0 else 10.0   # mean well above
            frames.append(_frame(scatter_p95_ms=p95, scatter_n=100))
        applied = _drive(ap, frames)
        assert all(a == [] for a in applied)
        assert any(d["reason"] == "hold:noisy"
                   for d in ap.decisions(200))

    def test_ramp_tracks_monotonically(self):
        ap, node = _autopilot(scatter_hedge_ms=20.0)
        frames = [_frame(scatter_p95_ms=30.0 + 12.0 * i, scatter_n=100)
                  for i in range(16)]
        _drive(ap, frames)
        dirs = _applied_dirs(ap, "scatter_hedge_ms")
        assert dirs and all(d == 1 for d in dirs)
        assert node.hedge_ms > 20.0

    def test_watermark_integer_and_critical_ratio_preserved(self):
        ap, node = _autopilot(admission_queue_high_water=100,
                              admission_queue_critical=400,
                              autopilot_p99_slo_ms=500.0)
        _drive(ap, [_frame(leader_p99_ms=2000.0, leader_n=100)] * 8)
        hw = node.admission.high_water
        assert isinstance(hw, int) and hw < 100
        assert node.admission.critical == max(hw * 4, hw + 1)

    def test_integral_knob_never_deadlocks_on_quantization(self):
        """The minimum-step rule: an integer knob whose damped
        fractional step rounds back onto itself (high_water 4, shrink
        ratio 0.83 -> 3.67 -> rounds to 4) must still move one unit
        toward the target — otherwise the controller silently loses
        authority exactly at small watermarks, where interactive
        shedding is decided."""
        ap, node = _autopilot(admission_queue_high_water=4,
                              admission_queue_critical=16,
                              autopilot_queue_floor=2,
                              autopilot_p99_slo_ms=500.0)
        # peak p99 at 600ms: ratio 0.83 — fractional step would stall
        _drive(ap, [_frame(leader_p99_ms=600.0, leader_n=100)] * 6)
        assert node.admission.high_water == 2   # walked 4 -> 3 -> 2
        assert node.admission.critical == 8

    def test_no_signal_decisions_not_recorded(self):
        ap, _node = _autopilot()
        _drive(ap, [_frame()] * 5)   # idle cluster: nothing to decide
        assert [d for d in ap.decisions(100)
                if d["reason"].startswith("hold:confirm")] == []
        assert all(d["reason"] == "bootstrap:arm_ewma_collection"
                   for d in ap.decisions(100))


# ---------------------------------------------------------------------------
# kill switch + decision ring
# ---------------------------------------------------------------------------

class TestKillSwitchAndRing:
    def test_kill_switch_reverts_every_knob_to_static(self):
        ap, node = _autopilot(scatter_hedge_ms=30.0,
                              admission_queue_high_water=128,
                              admission_queue_critical=512,
                              breaker_slow_threshold_ms=0.0)
        # bootstrap armed EWMA collection (slow threshold = ceiling)
        assert node.resilience.slow_threshold_s > 0
        # move every knob off its static value
        _drive(ap, [_frame(scatter_p95_ms=500.0, scatter_n=100,
                           leader_p99_ms=3000.0, leader_n=100,
                           worker_ewmas={"w0": (0.040, 10),
                                         "w1": (0.060, 10)})] * 6)
        assert node.hedge_ms != 30.0
        assert node.admission.high_water != 128
        snap = ap.set_enabled(False)
        # EXACT static config, instantly, for every managed knob
        assert node.hedge_ms == 30.0
        assert node.admission.high_water == 128
        assert node.admission.critical == 512
        assert node.resilience.slow_threshold_s == 0.0
        assert snap["enabled"] is False
        for k, v in snap["knobs"].items():
            assert v["current"] == v["static"], k
        # the loop is OFF: run_once is a no-op
        ap._frame = lambda: _frame(scatter_p95_ms=500.0, scatter_n=100)
        assert ap.run_once() == []
        assert node.hedge_ms == 30.0
        # the reverts are audited
        reverts = [d for d in ap.decisions(100)
                   if d["reason"] == "revert:kill_switch"]
        assert {d["knob"] for d in reverts} >= {
            "scatter_hedge_ms", "admission_queue_high_water",
            "breaker_slow_threshold_ms"}

    def test_kill_switch_restores_critical_exactly_despite_ratio(self):
        """The critical watermark is re-derived through a float ratio
        while steering, but the kill switch must restore BOTH static
        values verbatim — int(c/h*h) truncation (7/61 -> 60) must
        never survive a revert."""
        ap, node = _autopilot(admission_queue_high_water=7,
                              admission_queue_critical=61,
                              autopilot_p99_slo_ms=500.0,
                              autopilot_queue_floor=2)
        _drive(ap, [_frame(leader_p99_ms=2000.0, leader_n=100)] * 6)
        assert node.admission.high_water != 7
        ap.set_enabled(False)
        assert node.admission.high_water == 7
        assert node.admission.critical == 61

    def test_no_signal_sweep_breaks_confirmation_streak(self):
        """'autopilot_confirm CONSECUTIVE sweeps' means consecutive: a
        proposal from before a traffic gap (no-signal windows) must
        not combine with one fresh noisy window into a move."""
        ap, node = _autopilot(scatter_hedge_ms=20.0,
                              autopilot_confirm=2)
        applied = _drive(ap, [
            _frame(scatter_p95_ms=200.0, scatter_n=100),  # confirm 1
            _frame(scatter_n=0),                          # traffic gap
            _frame(scatter_p95_ms=200.0, scatter_n=100),  # confirm 1!
        ])
        assert applied == [[], [], []]
        assert node.hedge_ms == 20.0

    def test_reenable_restarts_from_static_with_fresh_windows(self):
        ap, node = _autopilot(scatter_hedge_ms=30.0)
        _drive(ap, [_frame(scatter_p95_ms=500.0, scatter_n=100)] * 4)
        ap.set_enabled(False)
        ap.set_enabled(True)
        assert ap.enabled and node.hedge_ms == 30.0
        # no stale trend: the first post-enable sweep must re-confirm
        ap._frame = lambda: _frame(scatter_p95_ms=500.0, scatter_n=100)
        assert ap.run_once() == []   # confirmation sweep, no move yet

    def test_reenable_clears_peak_hold_and_calm_state(self):
        """Subclass sensor memory must not survive a disable/enable
        cycle: a 900ms peak from the pre-disable overload would make
        the first post-enable calm window propose shrinking the
        watermark on a healthy cluster; a pre-disable pressure window
        would keep the hedge park-stuck through the calm gate."""
        ap, _node = _autopilot(admission_queue_high_water=100,
                               admission_queue_critical=400,
                               autopilot_p99_slo_ms=500.0)
        wm = next(c for c in ap.controllers
                  if c.knob == "admission_queue_high_water")
        hg = next(c for c in ap.controllers
                  if c.knob == "scatter_hedge_ms")
        _drive(ap, [_frame(leader_p99_ms=900.0, leader_n=100,
                           depth=3.0, scatter_n=100)] * 2)
        assert len(wm._recent_p99) > 0 and hg._calm == 0
        ap.set_enabled(False)
        ap.set_enabled(True)
        assert len(wm._recent_p99) == 0
        assert hg._calm == hg.CALM_SWEEPS
        # first post-enable calm window: peak is THIS window only —
        # p99 at 200ms proposes no shrink from the stale 900ms era
        out = wm.sense(_frame(leader_p99_ms=200.0, leader_n=100),
                       100.0)
        assert out is None   # in budget, no sheds: nothing to learn

    def test_decision_ring_is_bounded(self):
        ap, _node = _autopilot(autopilot_ring=16)
        for i in range(100):
            ap._record(knob="k", current=0, target=1, new=None,
                       direction=0, applied=False, reason="hold:test",
                       inputs={})
        recs = ap.decisions(10_000)
        assert len(recs) == 16
        # the ring keeps the NEWEST records
        assert recs[-1]["seq"] > 100 - 16
        assert ap.decisions(4) == recs[-4:]
        assert ap.decisions(0) == []


# ---------------------------------------------------------------------------
# integration: a real node, live sensors, HTTP export, CLI, gauges
# ---------------------------------------------------------------------------

@pytest.fixture
def core():
    c = CoordinationCore(session_timeout_s=0.5)
    yield c
    c.close()


_NODE_CFG = dict(
    top_k=16, min_doc_capacity=64, min_nnz_capacity=1 << 12,
    min_vocab_capacity=1 << 10, query_batch=8, max_query_terms=8,
    rpc_max_attempts=1, reconcile_sweep_interval_s=0.2,
    autopilot_enabled=True, autopilot_min_window=8,
    autopilot_interval_ms=50.0)


def _mk_node(core, tmp_path, **kw):
    cfg_kw = dict(_NODE_CFG)
    cfg_kw.update(kw)
    cfg = Config(documents_path=str(tmp_path / "ap" / "documents"),
                 index_path=str(tmp_path / "ap" / "index"),
                 port=0, **cfg_kw)
    return SearchNode(cfg, coord=LocalCoordination(core, 0.1)).start()


class TestNodeIntegration:
    def test_live_histograms_drive_hedge_with_span_and_gauges(
            self, core, tmp_path):
        node = _mk_node(core, tmp_path, scatter_hedge_ms=0.0)
        try:
            ap = node.autopilot
            # feed the REAL sensor pipeline: scatter-leg latencies into
            # the global histogram, one window per control pass
            for _ in range(3):
                for _ in range(40):
                    global_metrics.observe("scatter_rpc", 0.050)
                ap.run_once()
            assert node.hedge_ms > 0.0, \
                "hedge must track the observed scatter p95"
            # within the band of p95 + epsilon (~60ms) after 3 passes,
            # or at least moving toward it
            assert 5.0 <= node.hedge_ms <= 2000.0
            # tfidf_autopilot_* gauge per managed knob
            prom = global_metrics.render_prometheus()
            assert "tfidf_autopilot_scatter_hedge_ms " in prom
            assert "tfidf_autopilot_scatter_hedge_ms_floor " in prom
            assert "tfidf_autopilot_scatter_hedge_ms_ceiling " in prom
            assert "tfidf_autopilot_scatter_hedge_ms_direction " in prom
            assert "tfidf_autopilot_active " in prom
            # the sweep that changed a knob is traced with one
            # knob_adjusted event per change
            spans = [s for s in global_tracer.recent(200)
                     if s["name"] == "autopilot.sweep"]
            assert spans
            events = [e for s in spans for e in s["events"]
                      if e["name"] == "knob_adjusted"]
            assert any(e["attrs"]["knob"] == "scatter_hedge_ms"
                       and "scatter_p95_ms" in e["attrs"]
                       for e in events)
        finally:
            node.stop()

    def test_api_autopilot_get_and_post_kill_switch(self, core,
                                                    tmp_path):
        node = _mk_node(core, tmp_path, scatter_hedge_ms=40.0)
        try:
            ap = node.autopilot
            for _ in range(3):
                for _ in range(40):
                    global_metrics.observe("scatter_rpc", 0.200)
                ap.run_once()
            assert node.hedge_ms != 40.0
            got = json.loads(http_get(node.url
                                      + "/api/autopilot?recent=5"))
            snap = got["autopilot"]
            assert snap["enabled"] is True
            assert "scatter_hedge_ms" in snap["knobs"]
            k = snap["knobs"]["scatter_hedge_ms"]
            assert k["static"] == 40.0 and k["current"] != 40.0
            assert k["adjustments"] >= 1
            assert 0 < len(got["decisions"]) <= 5
            d = got["decisions"][-1]
            assert {"seq", "ts", "knob", "reason",
                    "inputs"} <= set(d)
            # the runtime kill switch over HTTP
            resp = json.loads(http_post(
                node.url + "/api/autopilot",
                json.dumps({"enabled": False}).encode()))
            assert resp["autopilot"]["enabled"] is False
            assert node.hedge_ms == 40.0
            # malformed body is a 400, not a toggle
            with pytest.raises(urllib.error.HTTPError) as ei:
                http_post(node.url + "/api/autopilot",
                          json.dumps({"enabled": "yes"}).encode())
            assert ei.value.code == 400
        finally:
            node.stop()

    def test_cli_status_block_and_autopilot_subcommand(self, core,
                                                      tmp_path,
                                                      capsys):
        from tfidf_tpu.cli import main as cli_main
        node = _mk_node(core, tmp_path)
        try:
            for _ in range(3):
                for _ in range(40):
                    global_metrics.observe("scatter_rpc", 0.100)
                node.autopilot.run_once()
            assert cli_main(["status", "--leader", node.url]) == 0
            out = json.loads(capsys.readouterr().out)
            assert out["autopilot"]["enabled"] is True
            assert "scatter_hedge_ms" in out["autopilot"]["knobs"]
            kb = out["autopilot"]["knobs"]["scatter_hedge_ms"]
            assert {"current", "static", "adjustments"} <= set(kb)
            assert out["autopilot"]["last_decision_age_s"] is not None
            # the dedicated subcommand renders the audit trail
            assert cli_main(["autopilot", "--leader", node.url]) == 0
            txt = capsys.readouterr().out
            assert "autopilot ENABLED" in txt
            assert "scatter_hedge_ms" in txt
            assert "decision(s):" in txt
            # kill switch via the CLI
            assert cli_main(["autopilot", "--leader", node.url,
                             "--disable"]) == 0
            txt = capsys.readouterr().out
            assert "autopilot disabled" in txt
            assert node.autopilot.enabled is False
        finally:
            node.stop()

    def test_static_config_when_disabled(self, core, tmp_path):
        """autopilot_enabled=False (the default) = exact legacy
        behavior: no knob ever moves, no sweep ever runs."""
        node = _mk_node(core, tmp_path, autopilot_enabled=False,
                        scatter_hedge_ms=70.0,
                        breaker_slow_threshold_ms=0.0)
        try:
            for _ in range(40):
                global_metrics.observe("scatter_rpc", 0.300)
            node.autopilot.maybe_run()
            assert node.autopilot.run_once() == []
            assert node.hedge_ms == 70.0
            assert node.resilience.slow_threshold_s == 0.0
            assert global_metrics.get("autopilot_active") == 0.0
        finally:
            node.stop()


# ---------------------------------------------------------------------------
# chaos (slow): step-change zipfian closed loop + mid-run worker kill -9
# ---------------------------------------------------------------------------

@pytest.mark.slow
class TestChaosAutopilot:
    @pytest.mark.timeout(300)
    def test_step_change_converges_without_oscillation(self, tmp_path):
        """``make chaos-autopilot``: a real 3-process cluster under the
        zipfian closed loop, load stepped 1x -> 2x with a worker
        ``kill -9`` mid-2x. The autopilot (enabled, fast cadence) must
        make adjustments, never flap (at most one direction change per
        knob beyond the genuine load step), keep admitted-interactive
        p99 bounded, and revert exactly to static config on the kill
        switch."""
        import os
        import random as _random
        import signal
        import socket
        import subprocess
        import sys

        def free_port():
            with socket.socket() as s:
                s.bind(("127.0.0.1", 0))
                return s.getsockname()[1]

        env = os.environ.copy()
        env["TFIDF_JAX_PLATFORM"] = "cpu"
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("XLA_FLAGS", None)
        env.update({
            "TFIDF_REPLICATION_FACTOR": "2",
            "TFIDF_TOP_K": "64",
            "TFIDF_SESSION_TIMEOUT_S": "1.0",
            "TFIDF_HEARTBEAT_INTERVAL_S": "0.2",
            "TFIDF_RECONCILE_SWEEP_INTERVAL_S": "0.25",
            "TFIDF_MIN_DOC_CAPACITY": "64",
            "TFIDF_MIN_NNZ_CAPACITY": "4096",
            "TFIDF_MIN_VOCAB_CAPACITY": "1024",
            "TFIDF_QUERY_BATCH": "4",
            "TFIDF_MAX_QUERY_TERMS": "8",
            # overload mechanics (as in chaos-overload): small scatter
            # batches leave a queue behind, LOW starting watermarks the
            # controller may rescale
            "TFIDF_SCATTER_BATCH": "2",
            "TFIDF_SCATTER_PIPELINE": "1",
            "TFIDF_ADMISSION_QUEUE_HIGH_WATER": "2",
            "TFIDF_ADMISSION_QUEUE_CRITICAL": "8",
            "TFIDF_RESULT_CACHE_ENTRIES": "256",
            # the autopilot under test: fast cadence, small windows
            "TFIDF_AUTOPILOT_ENABLED": "true",
            "TFIDF_AUTOPILOT_INTERVAL_MS": "500",
            "TFIDF_AUTOPILOT_MIN_WINDOW": "8",
            "TFIDF_AUTOPILOT_P99_SLO_MS": "400",
        })
        coord_port = free_port()
        procs = {}

        def spawn(tag, args):
            p = subprocess.Popen(
                [sys.executable, "-m", "tfidf_tpu", *args],
                env=env, stdout=subprocess.DEVNULL,
                stderr=subprocess.DEVNULL)
            procs[tag] = p
            return p

        def wait_pred(pred, timeout=60.0, interval=0.2):
            deadline = time.monotonic() + timeout
            last = None
            while time.monotonic() < deadline:
                try:
                    if pred():
                        return True
                except Exception as e:
                    last = e
                time.sleep(interval)
            raise AssertionError(f"timed out; last={last!r}")

        docs = {f"ap{i}.txt": f"common token{i} word{i % 3} "
                              f"extra{i % 5}" for i in range(12)}
        try:
            spawn("coord", ["coordinator", "--listen",
                            f"127.0.0.1:{coord_port}"])
            wait_pred(lambda: socket.create_connection(
                ("127.0.0.1", coord_port), timeout=1.0).close()
                or True)
            ports = [free_port() for _ in range(3)]
            urls = [f"http://127.0.0.1:{p}" for p in ports]
            for i, p in enumerate(ports):
                spawn(f"n{i}", [
                    "serve", "--port", str(p), "--host", "127.0.0.1",
                    "--coordinator-address",
                    f"127.0.0.1:{coord_port}",
                    "--documents-path",
                    str(tmp_path / f"ap{i}" / "docs"),
                    "--index-path",
                    str(tmp_path / f"ap{i}" / "index")])
                wait_pred(lambda u=urls[i]: http_get(
                    u + "/api/status", timeout=5.0), timeout=120)
            leader = urls[0]
            wait_pred(lambda: len(json.loads(http_get(
                leader + "/api/services"))) == 2)
            http_post(leader + "/leader/upload-batch",
                      json.dumps([{"name": n, "text": t}
                                  for n, t in docs.items()]).encode())
            wait_pred(lambda: json.loads(http_post(
                leader + "/leader/start",
                json.dumps({"query": "common"}).encode())),
                timeout=120, interval=1.0)

            qpool = [f"token{i} word{j}" for i in range(12)
                     for j in range(3)] + ["common"]
            rng = _random.Random(11)
            weights = [1.0 / (i + 1) ** 1.1 for i in range(len(qpool))]
            zipf = rng.choices(qpool, weights=weights, k=4000)
            nonce = [0]
            idx = [0]
            lock = threading.Lock()

            def run_phase(n_clients, seconds, mid_phase=None):
                lats, sheds, errors = [], [0], []
                stop_at = time.monotonic() + seconds

                def client(cid):
                    while time.monotonic() < stop_at:
                        with lock:
                            q = zipf[idx[0] % len(zipf)]
                            idx[0] += 1
                            if idx[0] % 5 < 2:
                                nonce[0] += 1
                                q = f"{q} zzuniq{nonce[0]}"
                        t0 = time.monotonic()
                        try:
                            http_post(
                                leader + "/leader/start",
                                json.dumps({"query": q}).encode(),
                                headers={"X-Client-Id": f"c{cid}"},
                                timeout=30.0)
                            with lock:
                                lats.append(time.monotonic() - t0)
                        except urllib.error.HTTPError as e:
                            if e.code == 429:
                                with lock:
                                    sheds[0] += 1
                                time.sleep(min(float(e.headers.get(
                                    "Retry-After", 0.05)), 0.5))
                            else:
                                errors.append(e)
                                return
                        except Exception as e:
                            errors.append(e)
                            return

                threads = [threading.Thread(target=client, args=(i,),
                                            daemon=True)
                           for i in range(n_clients)]
                for t in threads:
                    t.start()
                if mid_phase is not None:
                    time.sleep(seconds / 2)
                    mid_phase()
                for t in threads:
                    t.join(timeout=seconds + 60)
                assert not errors, errors[:3]
                lats.sort()
                return {"n": len(lats), "sheds": sheds[0],
                        "p99": lats[int(len(lats) * 0.99)]
                        if lats else 0.0}

            one_x = run_phase(4, 10.0)
            assert one_x["n"] > 0

            def kill_worker():
                victim = procs.pop("n2")
                os.kill(victim.pid, signal.SIGKILL)
                victim.wait(timeout=10)

            two_x = run_phase(12, 16.0, mid_phase=kill_worker)
            assert two_x["n"] > 0
            # admitted-interactive p99 stays bounded through the step
            # change AND the kill (CI-generous 4x; the committed
            # BENCH_r06 artifact holds the quiet-hardware 1.5x bar)
            assert two_x["p99"] <= max(4.0 * one_x["p99"], 2.0), \
                (one_x, two_x)

            got = json.loads(http_get(
                leader + "/api/autopilot?recent=256"))
            snap = got["autopilot"]
            assert snap["enabled"] is True
            # the loop actually steered something under the step change
            total_adjust = sum(v["adjustments"]
                               for v in snap["knobs"].values())
            assert total_adjust >= 1, snap
            # convergence without oscillation: per knob, applied
            # adjustments may change direction only at genuine
            # load-state transitions — the 1x->2x step, the post-kill
            # settle, and (for the hedge) a park/unpark mode switch
            # at a saturation boundary. A/B/A/B flapping would rack
            # up far more than this bound.
            by_knob = {}
            for d in got["decisions"]:
                if d.get("applied") and d["reason"] == "adjusted":
                    by_knob.setdefault(d["knob"], []).append(
                        d["direction"])
            for knob, dirs in by_knob.items():
                flips = sum(1 for a, b in zip(dirs, dirs[1:])
                            if a != b)
                assert flips <= 3, (knob, dirs)
            # every knob inside its clamps
            for k, v in snap["knobs"].items():
                assert v["floor"] <= v["current"] <= v["ceiling"], (
                    k, v)
            # kill switch restores exact static config, live
            resp = json.loads(http_post(
                leader + "/api/autopilot",
                json.dumps({"enabled": False}).encode()))
            for k, v in resp["autopilot"]["knobs"].items():
                assert v["current"] == v["static"], (k, v)
        finally:
            for p in procs.values():
                try:
                    p.kill()
                except Exception:
                    pass
            for p in procs.values():
                try:
                    p.wait(timeout=10)
                except Exception:
                    pass

"""Independent Lucene 9 BM25 golden generator for the reference corpus.

Implements the Java system's scoring stack from the Lucene specification —
deliberately WITHOUT importing any tfidf_tpu code, so it can serve as the
golden oracle the engine's ``lucene_parity=True`` mode is checked against
(the correctness bar of BASELINE.md: identical results vs the Java/Lucene
baseline; reference path: ``Worker.java:222-241`` scoring +
``Leader.java:39-92`` merge).

Pieces, each per the documented Lucene 9 behavior:

* StandardAnalyzer: Unicode word-break tokenization (alphanumeric runs for
  this ASCII corpus) + lowercase, no stopwords (Lucene 9 default).
* Norm encoding: document length round-trips through
  ``SmallFloat.intToByte4``/``byte4ToInt`` — a lossy 4-mantissa-bit code —
  before entering the BM25 length normalization.
* BM25Similarity (k1=1.2, b=0.75), Lucene 8+ form without the (k1+1)
  numerator: ``idf * tf / (tf + k1 * (1 - b + b * dl_q / avgdl))`` with
  ``idf = ln(1 + (N - df + 0.5) / (df + 0.5))``; ``avgdl`` from EXACT
  lengths (sumTotalTermFreq / docCount), ``dl_q`` the quantized length.
* Per-shard statistics: each worker scores against its local df/N
  (cross-shard IDF is never globalized in the reference).
* Leader merge: sum scores per doc name, order alphabetically
  (``Leader.java:73-91``).
"""

from __future__ import annotations

import math
import re

K1 = 1.2
B = 0.75

_TOKEN = re.compile(r"[0-9a-z]+")


def analyze(text: str) -> list[str]:
    return _TOKEN.findall(text.lower())


# SmallFloat byte-4 codec, from the org.apache.lucene.util.SmallFloat
# spec: values 0..39 exact, then 3 mantissa bits + exponent.

def _long_to_int4(i: int) -> int:
    num_bits = i.bit_length()
    if num_bits < 4:
        return i
    shift = num_bits - 4
    return ((i >> shift) & 0x07) | ((shift + 1) << 3)


def _int4_to_long(i: int) -> int:
    bits = i & 0x07
    shift = (i >> 3) - 1
    return bits if shift == -1 else (bits | 0x08) << shift


_FREE = 255 - _long_to_int4(2**31 - 1)


def quantize_dl(dl: int) -> int:
    b = dl if dl < _FREE else _FREE + _long_to_int4(dl - _FREE)
    return b if b < _FREE else _FREE + _int4_to_long(b - _FREE)


class LuceneShard:
    """One worker's Lucene index (local statistics)."""

    def __init__(self, docs: dict[str, str]) -> None:
        self.tf: dict[str, dict[str, int]] = {}
        self.dl: dict[str, int] = {}
        for name, text in docs.items():
            toks = analyze(text)
            counts: dict[str, int] = {}
            for t in toks:
                counts[t] = counts.get(t, 0) + 1
            self.tf[name] = counts
            self.dl[name] = len(toks)
        self.n = len(docs)
        self.avgdl = (sum(self.dl.values()) / self.n) if self.n else 1.0
        self.df: dict[str, int] = {}
        for counts in self.tf.values():
            for t in counts:
                self.df[t] = self.df.get(t, 0) + 1

    def idf(self, t: str) -> float:
        df = self.df.get(t, 0)
        return math.log(1.0 + (self.n - df + 0.5) / (df + 0.5))

    def search(self, query: str) -> dict[str, float]:
        """Unbounded search (``Integer.MAX_VALUE``): every doc matching at
        least one query term, with its BM25 score."""
        q_terms = analyze(query)
        out: dict[str, float] = {}
        for name, counts in self.tf.items():
            s = 0.0
            hit = False
            for t in q_terms:
                tf = counts.get(t, 0)
                if tf == 0:
                    continue
                hit = True
                dl_q = float(quantize_dl(self.dl[name]))
                norm = K1 * (1.0 - B + B * dl_q / self.avgdl)
                s += self.idf(t) * tf / (tf + norm)
            if hit:
                out[name] = s
        return out


def leader_search(shards: list[LuceneShard], query: str
                  ) -> dict[str, float]:
    """Scatter-gather: sum-merge per name, alphabetical order."""
    merged: dict[str, float] = {}
    for shard in shards:
        for name, score in shard.search(query).items():
            merged[name] = merged.get(name, 0.0) + score
    return dict(sorted(merged.items()))


QUERIES = [
    "fast food",
    "cat meowing",
    "kheder",
    "wireless earbuds",
    "helo",
    "best wireless earbuds 2024",
    "night causes",
    "food",
]


def generate(corpus_dir: str) -> dict:
    import json
    import os

    docs = {}
    for fn in sorted(os.listdir(corpus_dir)):
        path = os.path.join(corpus_dir, fn)
        if fn.endswith(".txt") and os.path.isfile(path):
            with open(path, encoding="utf-8") as f:
                docs[fn] = f.read()
    names = sorted(docs)
    # two shard layouts: everything on one worker, and the 2-worker split
    # the reference would produce with files alternating by upload order
    one = [LuceneShard(docs)]
    w0 = LuceneShard({n: docs[n] for n in names[0::2]})
    w1 = LuceneShard({n: docs[n] for n in names[1::2]})
    goldens = {
        "queries": QUERIES,
        "single_worker": {q: leader_search(one, q) for q in QUERIES},
        "two_workers": {q: leader_search([w0, w1], q) for q in QUERIES},
        "two_worker_split": {"w0": names[0::2], "w1": names[1::2]},
    }
    return goldens


if __name__ == "__main__":
    import json
    import os
    import sys

    here = os.path.dirname(os.path.abspath(__file__))
    corpus = sys.argv[1] if len(sys.argv) > 1 else os.path.join(
        here, "..", "demo", "corpus")
    out = os.path.join(here, "data", "lucene_goldens.json")
    with open(out, "w", encoding="utf-8") as f:
        json.dump(generate(corpus), f, indent=1, sort_keys=True)
    print(f"wrote {out}")

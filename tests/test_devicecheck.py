"""Device-hygiene analyzer + runtime device witness (ISSUE 19).

Style of tests/test_graftcheck.py: seeded mini-trees that each new
static pass MUST catch (a clean verdict is only trustworthy if the
planted bug trips it), extraction floors against vacuous staleness,
real-tree gates pinning the reviewed state, and runtime witness tests —
including the steady-state serving gate: after warmup, a fixed-shape
search loop must trigger ZERO XLA recompiles, and every device->host
transfer the witness observes must be explained by the static cone.
"""

from __future__ import annotations

import os
import sys
import types

import pytest

from tools.graftcheck import core as gc_core
from tools.graftcheck import devicecheck
from tools.graftcheck.core import SourceTree, load_allowlist

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _mini_tree(tmp_path, files: dict[str, str]) -> SourceTree:
    pkg = tmp_path / gc_core.PACKAGE
    pkg.mkdir(parents=True, exist_ok=True)
    (pkg / "__init__.py").write_text("")
    for rel, src in files.items():
        p = pkg / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        if not (p.parent / "__init__.py").exists():
            (p.parent / "__init__.py").write_text("")
        p.write_text(src)
    return SourceTree(str(tmp_path))


def _keys(findings) -> set[str]:
    return {f.key for f in findings}


# ---------------------------------------------------------------------------
# 1. seeded fixtures: each pass must catch its planted bug
# ---------------------------------------------------------------------------

class TestSeededCacheDiscipline:
    def test_uncached_jit_creation(self, tmp_path):
        tree = _mini_tree(tmp_path, {"bad.py": '''
import jax

def hot_path(xs):
    f = jax.jit(lambda x: x * 2)      # fresh trace EVERY call
    return f(xs)
'''})
        keys = _keys(devicecheck.analyze(tree))
        assert "devicecheck:jit-uncached:bad.hot_path" in keys

    def test_memoized_jit_is_clean(self, tmp_path):
        tree = _mini_tree(tmp_path, {"ok.py": '''
import jax
from tfidf_tpu.caps import next_capacity

class Applier:
    def __init__(self):
        self._fns = {}

    def apply(self, df, uniq):
        cap = next_capacity(int(uniq.shape[0]), 256)
        fn = self._fns.get(cap)
        if fn is None:
            fn = jax.jit(lambda d, i: d.at[i].add(1.0))
            self._fns[cap] = fn
        return fn(df, uniq)
''', "caps.py": '''
def next_capacity(n, minimum):
    cap = minimum
    while cap < n:
        cap *= 2
    return cap
'''})
        keys = _keys(devicecheck.analyze(tree))
        assert not any(k.startswith("devicecheck:jit-") for k in keys)

    def test_unstable_cache_key(self, tmp_path):
        # same memo-store shape, but keyed on the RAW corpus size: every
        # doc count mints a new executable — the compile-storm bug
        tree = _mini_tree(tmp_path, {"bad.py": '''
import jax

class Applier:
    def __init__(self):
        self._fns = {}

    def apply(self, df, uniq):
        n = int(uniq.shape[0])            # corpus-dependent, unbucketed
        fn = self._fns.get(n)
        if fn is None:
            fn = jax.jit(lambda d, i: d.at[i].add(1.0))
            self._fns[n] = fn
        return fn(df, uniq)
'''})
        keys = _keys(devicecheck.analyze(tree))
        assert "devicecheck:jit-unstable-key:bad.Applier.apply" in keys

    def test_corpus_value_into_static_arg(self, tmp_path):
        tree = _mini_tree(tmp_path, {"ops.py": '''
import functools
import jax

@functools.partial(jax.jit, static_argnames=("k",))
def topk(scores, *, k):
    return jax.lax.top_k(scores, k)
''', "bad.py": '''
from tfidf_tpu.ops import topk

class Searcher:
    def dispatch(self, scores, snap):
        return topk(scores, k=snap.n_docs)   # recompiles as corpus grows
'''})
        keys = _keys(devicecheck.analyze(tree))
        assert ("devicecheck:jit-corpus-static:bad.Searcher.dispatch:"
                "topk.k" in keys)

    def test_min_bounded_static_arg_is_clean(self, tmp_path):
        # min(k, corpus) is capacity-class: at most k distinct values,
        # stabilizing once the corpus outgrows k — the established idiom
        tree = _mini_tree(tmp_path, {"ops.py": '''
import functools
import jax

@functools.partial(jax.jit, static_argnames=("k",))
def topk(scores, *, k):
    return jax.lax.top_k(scores, k)
''', "ok.py": '''
from tfidf_tpu.ops import topk

class Searcher:
    def dispatch(self, scores, snap, k):
        kk = min(k, snap.n_docs)
        return topk(scores, k=kk)
'''})
        keys = _keys(devicecheck.analyze(tree))
        assert not any("jit-corpus-static" in k for k in keys)

    def test_factory_return_is_a_seam(self, tmp_path):
        tree = _mini_tree(tmp_path, {"ok.py": '''
import jax

def make_search(mesh, k):
    def step(q, emb):
        return jax.lax.top_k(q @ emb.T, k)
    return jax.jit(step)
'''})
        keys = _keys(devicecheck.analyze(tree))
        assert not any(k.startswith("devicecheck:jit-uncached")
                       for k in keys)


class TestSeededTransferHygiene:
    # the cone-root machinery is driven with a synthetic root list so
    # the fixture is self-contained (the real CONE_ROOTS name real
    # modules, which a mini-tree does not carry)

    def _analyze(self, tree, roots):
        dc = devicecheck._DeviceCheck(tree, cone_roots=roots)
        dc.check_transfers()
        return _keys(dc.findings)

    def test_item_in_dispatch_cone(self, tmp_path):
        tree = _mini_tree(tmp_path, {"srv.py": '''
import jax
import jax.numpy as jnp
import numpy as np

@jax.jit
def score(q):
    return q * 2.0

class Searcher:
    def _dispatch_chunk(self, q, k):
        scores = score(q)
        best = scores.max()
        if float(best) <= 0.0:            # blocking d2h mid-dispatch
            return None
        n = scores.shape[0]
        lead = scores[0].item()           # and another one
        host = np.asarray(scores)         # and a full fetch
        return host, lead, n
'''})
        keys = self._analyze(tree, ("srv.Searcher._dispatch_chunk",))
        qual = "srv.Searcher._dispatch_chunk"
        assert f"devicecheck:transfer:{qual}:float" in keys
        assert f"devicecheck:transfer:{qual}:item" in keys
        assert f"devicecheck:transfer:{qual}:asarray" in keys

    def test_annotated_device_attr_sync(self, tmp_path):
        # the shape of the real finding this PR fixed: float() on a
        # dataclass field annotated jax.Array, reached via the
        # annotated snap parameter
        tree = _mini_tree(tmp_path, {"snapmod.py": '''
import jax
from dataclasses import dataclass

@dataclass
class Snap:
    n_docs: jax.Array
    version: int = 0
''', "srv.py": '''
from tfidf_tpu.snapmod import Snap

class Searcher:
    def _dispatch_chunk(self, snap: Snap, k):
        n = float(snap.n_docs)            # per-dispatch device sync
        return n * k
'''})
        keys = self._analyze(tree, ("srv.Searcher._dispatch_chunk",))
        assert ("devicecheck:transfer:srv.Searcher._dispatch_chunk:"
                "float" in keys)

    def test_fetch_stage_is_exempt(self, tmp_path):
        tree = _mini_tree(tmp_path, {"ops/topk.py": '''
import numpy as np
import jax

@jax.jit
def packed(q):
    return q

def fetch_packed(arr):
    dev = packed(arr)
    return np.asarray(dev)                # THE one sanctioned d2h
'''})
        keys = self._analyze(tree, ("ops.topk.fetch_packed",))
        assert not any("transfer" in k for k in keys)

    def test_missing_cone_root_is_a_finding(self, tmp_path):
        # module exists but the named method is gone: a rename must
        # update CONE_ROOTS, not silently shrink the cone
        tree = _mini_tree(tmp_path, {"srv.py": '''
class Searcher:
    def renamed(self):
        pass
'''})
        keys = self._analyze(tree, ("srv.Searcher._dispatch_chunk",))
        assert ("devicecheck:cone-root-missing:"
                "srv.Searcher._dispatch_chunk" in keys)


class TestSeededDonation:
    def test_missing_donation_candidate(self, tmp_path):
        tree = _mini_tree(tmp_path, {"df.py": '''
import jax

class Applier:
    def __init__(self):
        self._fns = {}

    def apply(self, df):
        fn = self._fns.get(df.shape[0])
        if fn is None:
            fn = jax.jit(lambda d: d + 1.0)   # no donate_argnums
            self._fns[df.shape[0]] = fn
        return fn(df)

class Index:
    def __init__(self):
        self._df = None
        self._app = Applier()

    def commit(self):
        new = self._app.apply(self._df)   # self._df dead after this…
        self._df = new                    # …rebound here
        return new
'''})
        keys = _keys(devicecheck.analyze(tree))
        assert "devicecheck:donation:df.Index.commit:apply" in keys

    def test_donated_seam_is_clean(self, tmp_path):
        tree = _mini_tree(tmp_path, {"df.py": '''
import jax

class Applier:
    def __init__(self):
        self._fns = {}

    def apply(self, df):
        fn = self._fns.get(df.shape[0])
        if fn is None:
            fn = jax.jit(lambda d: d + 1.0, donate_argnums=0)
            self._fns[df.shape[0]] = fn
        return fn(df)

class Index:
    def __init__(self):
        self._df = None
        self._app = Applier()

    def commit(self):
        new = self._app.apply(self._df)
        self._df = new
        return new
'''})
        keys = _keys(devicecheck.analyze(tree))
        assert not any(k.startswith("devicecheck:donation") for k in keys)


# ---------------------------------------------------------------------------
# 2. extraction floors: clean verdicts must not go vacuously stale
# ---------------------------------------------------------------------------

class TestExtractionFloors:
    @pytest.fixture(scope="class")
    def tree(self):
        return SourceTree(REPO_ROOT)

    def test_jit_roots_discovered(self, tree):
        roots = devicecheck.jit_roots(tree)
        # 31 at pin time (19 jit + 12 shard_map): dense plane, ELL
        # kernels, topk family, dfdelta, mesh factories
        assert len(roots) >= 25
        kinds = {r.kind for r in roots}
        assert "shard_map" in kinds and "jit" in kinds

    def test_module_entries_and_static_names(self, tree):
        roots = devicecheck.jit_roots(tree)
        entries = {f"{r.mi.name}.{r.bound}" for r in roots if r.bound}
        assert len(entries) >= 8
        assert "ops.topk.packed_topk_chunked" in entries
        by_name = {f"{r.mi.name}.{r.bound}": r for r in roots if r.bound}
        # static_argnames extraction: the (capacity, k, chunk) pattern
        assert "k" in by_name["ops.topk.packed_topk_chunked"].static_names
        assert "chunk" in by_name["ops.dense._packed_dense_topk_jit"] \
            .static_names

    def test_scoped_creations_classified(self, tree):
        # the per-capacity dfdelta cache and the mesh factories are
        # function-scoped jit creations — the seam classifier must see
        # them (and, per the real-tree gate, accept every one)
        roots = devicecheck.jit_roots(tree)
        scoped = [r for r in roots if r.scope is not None]
        assert len(scoped) >= 10
        quals = {r.scope.qual for r in scoped}
        assert "ops.dfdelta.DfDeltaApplier.apply" in quals

    def test_cone_covers_the_serving_paths(self, tree):
        dc = devicecheck._DeviceCheck(tree)
        cone = dc.cone()
        assert not any(f.key.startswith("devicecheck:cone-root-missing")
                       for f in dc.findings), [f.key for f in dc.findings]
        assert len(cone) >= 40     # 91 at pin time: closed call graph
        assert "engine.searcher.Searcher._dispatch_tiered" in cone
        assert "engine.tiering.TierManager._build_device" in cone

    def test_device_attr_annotations_extracted(self, tree):
        dc = devicecheck._DeviceCheck(tree)
        # the annotation-driven taint that caught the fixed finding
        assert "n_docs" in dc._device_attrs[
            "engine.segments.SegmentedSnapshot"]
        assert "df" in dc._device_attrs["engine.index.Snapshot"]


# ---------------------------------------------------------------------------
# 3. real tree: the reviewed state, pinned
# ---------------------------------------------------------------------------

class TestRealTree:
    @pytest.fixture(scope="class")
    def findings(self):
        return devicecheck.analyze(SourceTree(REPO_ROOT))

    def test_no_unpinned_findings(self, findings):
        allowlist = load_allowlist()
        new = [f for f in findings if f.key not in allowlist]
        assert not new, "unreviewed device-hygiene finding(s):\n" + \
            "\n".join(f.render() for f in new)

    def test_fixed_dispatch_sync_stays_fixed(self, findings):
        """Regression pin for the real finding this PR fixed: the tiered
        dispatch read float(snap.n_docs)/float(snap.avgdl) — a blocking
        d2h sync per dispatched chunk — now served by the host mirrors
        stamped at commit (SegmentedSnapshot.n_docs_f/avgdl_f)."""
        assert ("devicecheck:transfer:engine.searcher.Searcher."
                "_dispatch_tiered:float" not in _keys(findings))

    def test_host_mirrors_match_device_scalars(self):
        """The fix is only sound if the mirrors equal the device
        scalars they replace."""
        import numpy as np

        from tfidf_tpu.engine.segments import SegmentedIndex
        from tfidf_tpu.models.bm25 import BM25Model

        idx = SegmentedIndex(BM25Model())
        rng = np.random.default_rng(0)
        for d in range(20):
            ids = rng.choice(100, size=5, replace=False).astype(np.int64)
            idx.add_document(f"d{d}", {int(t): 1 + int(t) % 3
                                       for t in ids})
        idx.commit(vocab_cap=128)
        snap = idx.snapshot
        assert snap.n_docs_f == float(np.asarray(snap.n_docs))
        assert snap.avgdl_f == pytest.approx(
            float(np.asarray(snap.avgdl)))

    def test_tiered_dispatch_has_reviewed_asarray_pin(self, findings):
        """The tiered host-merge d2h is intentional (the method IS its
        own fetch stage) — it must stay VISIBLE as an allowlisted
        finding, not vanish from the analyzer."""
        key = ("devicecheck:transfer:engine.searcher.Searcher."
               "_dispatch_tiered:asarray")
        assert key in _keys(findings)
        assert key in load_allowlist()

    def test_donation_pins_carry_reasons(self, findings):
        allowlist = load_allowlist()
        donation = [f.key for f in findings
                    if f.key.startswith("devicecheck:donation:")]
        assert donation, "donation audit found nothing on the real " \
            "tree — the committed-df seams should be candidates"
        for k in donation:
            assert len(allowlist.get(k, "")) > 40, \
                f"donation finding {k} lacks a reviewed reason"


# ---------------------------------------------------------------------------
# 4. runtime device witness
# ---------------------------------------------------------------------------

_OWNS_NS = os.environ.get("GRAFTCHECK_DEVICE") == "1"


def _fixture_module(name: str, source: str):
    """A throwaway tfidf_tpu submodule the witness will instrument."""
    import numpy as np
    mod = types.ModuleType(f"{gc_core.PACKAGE}.{name}")
    mod.__dict__["np"] = np
    exec(compile(source, f"<{name}>", "exec"), mod.__dict__)
    sys.modules[mod.__name__] = mod
    return mod


@pytest.mark.skipif(_OWNS_NS, reason="session device witness owns the "
                    "package namespaces; nested install would fight it")
class TestDeviceWitness:
    def test_unexplained_transfer_fails(self, tmp_path):
        import jax.numpy as jnp

        from tools.graftcheck.device_witness import DeviceWitness
        mod = _fixture_module("zz_dw_fixture", """
def leaky_dispatch(x):
    return np.asarray(x)          # d2h outside any explained site
""")
        try:
            w = DeviceWitness(explained=set()).install()
            try:
                mod.leaky_dispatch(jnp.ones(4))
            finally:
                w.uninstall()
            assert w.observed, "proxy recorded nothing"
            with pytest.raises(AssertionError,
                               match="did not explain"):
                w.check()
        finally:
            sys.modules.pop(mod.__name__, None)

    def test_explained_transfer_passes(self, tmp_path):
        import jax.numpy as jnp

        from tools.graftcheck.device_witness import DeviceWitness
        mod = _fixture_module("zz_dw_fixture2", """
def fetch_stage(x):
    return np.asarray(x)
""")
        try:
            w = DeviceWitness(
                explained={("zz_dw_fixture2", "fetch_stage")}).install()
            try:
                mod.fetch_stage(jnp.ones(4))
            finally:
                w.uninstall()
            w.check(min_observations=1)   # observed AND explained
        finally:
            sys.modules.pop(mod.__name__, None)

    def test_host_arrays_not_recorded(self, tmp_path):
        import numpy as np

        from tools.graftcheck.device_witness import DeviceWitness
        mod = _fixture_module("zz_dw_fixture3", """
def host_only(x):
    return np.asarray(x)
""")
        try:
            w = DeviceWitness(explained=set()).install()
            try:
                mod.host_only(np.ones(4))
            finally:
                w.uninstall()
            assert not w.observed
            w.check()
        finally:
            sys.modules.pop(mod.__name__, None)

    def test_vacuous_run_fails_floor(self):
        from tools.graftcheck.device_witness import DeviceWitness
        w = DeviceWitness(explained=set()).install()
        w.uninstall()
        with pytest.raises(AssertionError, match="vacuous"):
            w.check(min_observations=1)

    def test_post_warmup_recompile_detected(self):
        import jax
        import jax.numpy as jnp

        from tools.graftcheck.device_witness import DeviceWitness
        f = jax.jit(lambda x: x * 2.0 + 1.0)
        w = DeviceWitness(explained=set()).install()
        try:
            f(jnp.ones(8))                   # warmup compile
            w.end_warmup()
            f(jnp.ones(8))                   # cache hit: no event
            w.check(max_post_warmup_compiles=0)
            f(jnp.ones(16))                  # NEW shape: recompile
            with pytest.raises(AssertionError, match="post-warmup"):
                w.check(max_post_warmup_compiles=0)
        finally:
            w.uninstall()


class TestSteadyStateServing:
    def test_zero_recompiles_after_warmup(self, tmp_path):
        """The PAPER §7 claim the analyzer exists to guard, measured:
        after two warmup batches (compile + u_cap ratchet), a
        steady-state stream of same-bucket batches must re-enter XLA
        compilation exactly zero times."""
        import numpy as np

        from tfidf_tpu.engine.engine import Engine
        from tfidf_tpu.utils.config import Config
        from tools.graftcheck.device_witness import (
            DeviceWitness, compile_count, ensure_compile_listener)

        ensure_compile_listener()
        cfg = Config(documents_path=str(tmp_path / "docs"),
                     index_path=str(tmp_path / "index"),
                     min_nnz_capacity=256, min_doc_capacity=64,
                     min_vocab_capacity=64)
        eng = Engine(cfg)
        rng = np.random.default_rng(7)
        vocab = [f"t{i}" for i in range(50)]
        for d in range(48):
            words = rng.choice(vocab, size=12)
            eng.ingest_text(f"doc{d}", " ".join(words))
        eng.commit()

        def batch(seed):
            r = np.random.default_rng(seed)
            return [" ".join(r.choice(vocab, size=3, replace=False))
                    for _ in range(8)]

        w = DeviceWitness(explained=set())
        # no install(): compile counting needs no namespace swap, and
        # the session witness may own the proxies already
        eng.search_batch(batch(0), k=5)      # warmup: compiles
        eng.search_batch(batch(1), k=5)      # warmup: ratchets floors
        w.end_warmup()
        for i in range(2, 8):
            eng.search_batch(batch(i), k=5)  # steady state
        assert w.post_warmup_compiles() == 0, (
            f"{w.post_warmup_compiles()} recompile(s) in steady-state "
            f"serving (total this process: {compile_count()})")

"""Observability: distributed tracing, histogram metrics, Prometheus.

The acceptance story (ISSUE 10): a kill-a-worker-mid-scatter request's
trace, fetched via ``GET /api/trace/<id>``, reconstructs the whole
story — the scatter span, per-worker child spans, the failover re-issue
span, and resilience span events; ``/api/metrics?format=prometheus``
parses under a strict text-format checker whose histogram series agree
with the JSON snapshot's live percentiles; histogram quantiles track
``numpy.percentile`` within bucket resolution across adversarial
distributions; and counter/gauge name collisions fail loudly instead of
silently shadowing.
"""

import json
import logging as _pylogging
import math
import re
import time
import urllib.request

import numpy as np
import pytest

from tfidf_tpu.cluster.batcher import Coalescer
from tfidf_tpu.cluster.coordination import CoordinationCore
from tfidf_tpu.cluster.node import http_get
from tfidf_tpu.utils.logging import get_logger
from tfidf_tpu.utils.metrics import (_BUCKET_RATIO, MetricKindError,
                                     Metrics, global_metrics)
from tfidf_tpu.utils.tracing import (TRACE_HEADER, global_tracer,
                                     propagation_headers,
                                     render_trace_tree, span_event,
                                     to_chrome_trace, trace_phase)

from tests.test_replication import (QUERIES, _assert_parity,
                                    _mk_cluster, _oracle, _search,
                                    _stop_all, _upload_docs)


@pytest.fixture
def core():
    c = CoordinationCore(session_timeout_s=0.5)
    yield c
    c.close()


@pytest.fixture(autouse=True)
def _reset_tracer():
    global_tracer.configure(max_spans=4096, sample_rate=1.0)
    global_tracer.clear()
    yield
    global_tracer.configure(max_spans=4096, sample_rate=1.0)
    global_tracer.clear()


# ---------------------------------------------------------------------------
# Histogram quantiles vs numpy.percentile (oracle)
# ---------------------------------------------------------------------------

# one bucket ratio each way covers the estimate's construction error;
# numpy's linear interpolation can land at a bucket edge, so allow two
_QTOL = _BUCKET_RATIO ** 2


def _assert_close_quantile(got_s: float, want_s: float, ctx=""):
    assert want_s / _QTOL <= got_s <= want_s * _QTOL, \
        (ctx, got_s, want_s)


class TestHistogramQuantiles:
    def _check(self, samples, qs=(0.5, 0.95, 0.99), ctx=""):
        m = Metrics()
        for s in samples:
            m.observe("lat", float(s))
        for q in qs:
            want = float(np.percentile(samples, q * 100))
            got = m.quantile("lat", q)
            _assert_close_quantile(got, want, ctx=f"{ctx} q={q}")

    def test_uniform(self, rng):
        self._check(rng.uniform(0.001, 0.2, size=5000), ctx="uniform")

    def test_bimodal(self, rng):
        # fast-path/slow-path serving mix: the mean is meaningless,
        # the p99 sits in the far mode — exactly what buckets must see
        fast = rng.normal(0.002, 0.0003, size=4000).clip(1e-4)
        slow = rng.normal(0.5, 0.05, size=300).clip(1e-4)
        self._check(np.concatenate([fast, slow]), ctx="bimodal")

    def test_heavy_tail(self, rng):
        self._check(rng.lognormal(mean=-5.0, sigma=1.5, size=8000),
                    ctx="lognormal")

    def test_single_sample_is_exact(self):
        m = Metrics()
        m.observe("lat", 0.0421)
        for q in (0.0, 0.5, 0.99, 1.0):
            assert m.quantile("lat", q) == pytest.approx(0.0421)

    def test_extremes_clamp_to_observed(self, rng):
        m = Metrics()
        xs = rng.uniform(0.001, 1.0, size=100)
        for x in xs:
            m.observe("lat", float(x))
        assert m.quantile("lat", 0.0) == pytest.approx(xs.min())
        assert m.quantile("lat", 1.0) == pytest.approx(xs.max())

    def test_overflow_bucket_uses_max(self):
        m = Metrics()
        m.observe("lat", 500.0)   # beyond the last finite bound
        m.observe("lat", 600.0)
        assert m.quantile("lat", 0.99) == pytest.approx(600.0)

    def test_snapshot_percentile_keys(self):
        m = Metrics()
        for i in range(100):
            m.observe("lat", 0.01 * (i + 1))
        snap = m.snapshot()
        for k in ("lat_p50_ms", "lat_p95_ms", "lat_p99_ms"):
            assert k in snap
        assert snap["lat_p50_ms"] <= snap["lat_p95_ms"] \
            <= snap["lat_p99_ms"]
        assert m.quantile("nothing", 0.5) is None


# ---------------------------------------------------------------------------
# Counter/gauge namespaces: collisions fail loudly
# ---------------------------------------------------------------------------

class TestMetricKindCollision:
    def test_gauge_then_counter_raises(self):
        m = Metrics()
        m.set_gauge("depth", 3)
        with pytest.raises(MetricKindError):
            m.inc("depth")

    def test_counter_then_gauge_raises(self):
        m = Metrics()
        m.inc("requests")
        with pytest.raises(MetricKindError):
            m.set_gauge("requests", 1.0)

    def test_real_tree_has_no_collision(self, core, tmp_path):
        """The global registry builds up a real serving run's metrics
        without any emit-side guard firing (the guard would raise into
        the serving path) — pinned by the cluster test below actually
        running; here just assert the registry stayed consistent."""
        snap = global_metrics.snapshot()
        assert isinstance(snap, dict)


# ---------------------------------------------------------------------------
# Prometheus text exposition: strict checker
# ---------------------------------------------------------------------------

_NAME_RE = r"[a-zA-Z_:][a-zA-Z0-9_:]*"
_SAMPLE_RE = re.compile(
    rf"^({_NAME_RE})(?:\{{le=\"([^\"]+)\"\}})? "
    r"(-?(?:[0-9.]+(?:[eE][-+]?[0-9]+)?|\+Inf|NaN))$")
_TYPE_RE = re.compile(rf"^# TYPE ({_NAME_RE}) (counter|gauge|histogram)$")


def parse_prometheus_strict(text: str) -> dict:
    """Strict text-format checker: every line is a TYPE declaration or
    a sample; every sample's metric was declared; histogram series are
    cumulative with a ``+Inf`` bucket equal to ``_count``; returns
    {metric: {"type": ..., "samples": [(labels_le, value)], ...}}."""
    metrics: dict = {}
    declared: dict[str, str] = {}
    for line in text.strip().splitlines():
        tm = _TYPE_RE.match(line)
        if tm:
            name, kind = tm.groups()
            assert name not in declared, f"duplicate TYPE for {name}"
            declared[name] = kind
            metrics[name] = {"type": kind, "samples": []}
            continue
        sm = _SAMPLE_RE.match(line)
        assert sm, f"unparseable exposition line: {line!r}"
        name, le, value = sm.groups()
        base = name
        for suf in ("_bucket", "_sum", "_count"):
            if name.endswith(suf) and name[: -len(suf)] in declared \
                    and declared[name[: -len(suf)]] == "histogram":
                base = name[: -len(suf)]
                break
        assert base in declared, f"sample before TYPE: {line!r}"
        metrics[base]["samples"].append((name, le, float(value)
                                         if value != "+Inf"
                                         else math.inf))
    # histogram invariants
    for name, m in metrics.items():
        if m["type"] != "histogram":
            continue
        buckets = [(le, v) for n, le, v in m["samples"]
                   if n == f"{name}_bucket"]
        counts = [v for n, _le, v in m["samples"]
                  if n == f"{name}_count"]
        assert buckets and len(counts) == 1, name
        vals = [v for _le, v in buckets]
        assert vals == sorted(vals), f"{name} buckets not cumulative"
        assert buckets[-1][0] == "+Inf", f"{name} missing +Inf bucket"
        assert buckets[-1][1] == counts[0], \
            f"{name} +Inf bucket != _count"
    return metrics


def _p_from_buckets(buckets: list[tuple[str, float]], q: float) -> float:
    """Replicate the quantile estimate from exposition buckets (the
    operator's histogram_quantile()): geometric interpolation."""
    n = buckets[-1][1]
    target = max(1, math.ceil(q * n))
    prev_cum, prev_bound = 0.0, None
    for le, cum in buckets:
        if cum >= target:
            hi = float(le) if le != "+Inf" else float(buckets[-2][0])
            lo = (float(prev_bound) if prev_bound not in (None, "+Inf")
                  else hi / _BUCKET_RATIO)
            frac = (target - prev_cum) / (cum - prev_cum)
            return lo * (hi / lo) ** frac
        prev_cum, prev_bound = cum, le
    raise AssertionError("empty histogram")


class TestPrometheusExposition:
    def test_render_parses_and_is_consistent(self, rng):
        m = Metrics()
        m.inc("uploads_placed", 7)
        m.set_gauge("queue depth/now", 3.5)   # name needs sanitizing
        for x in rng.lognormal(-4.0, 1.0, size=2000):
            m.observe("scatter_rpc", float(x))
        parsed = parse_prometheus_strict(m.render_prometheus())
        assert parsed["tfidf_uploads_placed_total"]["type"] == "counter"
        assert parsed["tfidf_uploads_placed_total"]["samples"][0][2] == 7
        # sanitized gauge name, distinct from any counter name
        assert "tfidf_queue_depth_now" in parsed
        h = parsed["tfidf_scatter_rpc_seconds"]
        assert h["type"] == "histogram"
        # the exposition's histogram reproduces the JSON snapshot's p99
        # within bucket resolution (the estimate may clamp to observed
        # extremes, which buckets alone cannot)
        buckets = [(le, v) for n, le, v in h["samples"]
                   if n == "tfidf_scatter_rpc_seconds_bucket"]
        want = m.snapshot()["scatter_rpc_p99_ms"] / 1e3
        _assert_close_quantile(_p_from_buckets(buckets, 0.99), want,
                               ctx="prom p99")
        # _sum agrees with the JSON running sum
        s = [v for n, _le, v in h["samples"]
             if n == "tfidf_scatter_rpc_seconds_sum"][0]
        assert s == pytest.approx(m.snapshot()["scatter_rpc_sum_ms"]
                                  / 1e3, rel=1e-6)

    def test_namespaces_stay_distinct_in_exposition(self):
        m = Metrics()
        m.inc("served")
        m.set_gauge("depth", 1.0)
        text = m.render_prometheus()
        assert "tfidf_served_total" in text
        assert re.search(r"^tfidf_depth 1$", text, re.M)


# ---------------------------------------------------------------------------
# Tracing unit tests
# ---------------------------------------------------------------------------

class TestTracingUnit:
    def test_span_nesting_and_events(self):
        with global_tracer.span("outer") as outer:
            assert propagation_headers()[TRACE_HEADER] == outer.trace_id
            span_event("hello", n=1)
            with global_tracer.span("inner",
                                    parent=outer) as inner:
                assert inner.trace_id == outer.trace_id
                assert inner.parent_id == outer.span_id
        assert propagation_headers() == {}
        spans = global_tracer.get_trace(outer.trace_id)
        assert [s["name"] for s in spans] == ["outer", "inner"]
        assert spans[0]["events"][0]["name"] == "hello"

    def test_trace_phase_folds_into_active_span(self):
        with global_tracer.span("req") as sp:
            with trace_phase("unittest_phase"):
                pass
        evs = [e["name"] for e in sp.to_dict()["events"]]
        assert "phase.unittest_phase" in evs
        assert global_metrics.get("phase_unittest_phase_count", 0) == 0
        assert global_metrics.snapshot()["phase_unittest_phase_count"] \
            == 1

    def test_ring_is_bounded(self):
        global_tracer.configure(max_spans=32)
        for i in range(200):
            with global_tracer.span(f"s{i}"):
                pass
        assert len(global_tracer.recent(1000)) == 32

    def test_sampling_zero_records_nothing_but_keeps_ids(self):
        global_tracer.configure(sample_rate=0.0)
        with global_tracer.span("unsampled") as sp:
            assert sp.trace_id           # id still minted (log joining)
            sp.event("dropped")
            assert propagation_headers() == {}  # unsampled: no headers
        assert global_tracer.recent(10) == []
        assert not sp.events

    def test_coalescer_links_batch_and_requests_both_ways(self):
        co = Coalescer(lambda items: [x * 2 for x in items],
                       max_batch=4, linger_s=0.0, pipeline=1,
                       name="obs")
        try:
            with global_tracer.span("request") as req:
                assert co.submit(21) == 42
            batch = [s for s in global_tracer.recent(50)
                     if s["name"] == "obs.batch"]
            assert batch, "no batch span recorded"
            b = batch[0]
            # batch links request; request links batch (walkable both
            # directions across the coalescing boundary)
            assert {l["trace_id"] for l in b["links"]} == {req.trace_id}
            reqd = [s for s in global_tracer.recent(50)
                    if s["name"] == "request"][0]
            assert {l["trace_id"] for l in reqd["links"]} \
                == {b["trace_id"]}
            # link-following trace fetch pulls the other trace in
            got = {s["name"]
                   for s in global_tracer.get_trace(req.trace_id)}
            assert {"request", "obs.batch"} <= got
        finally:
            co.stop()

    def test_event_cap_keeps_newest(self):
        from tfidf_tpu.utils.tracing import Span
        with global_tracer.span("stormy") as sp:
            for i in range(Span._MAX_EVENTS + 50):
                sp.event("retry", i=i)
            sp.event("scatter.health", degraded=0)
        evs = sp.to_dict()["events"]
        assert len(evs) == Span._MAX_EVENTS
        # the late decisive event survives the storm; the OLDEST
        # retries are what got dropped
        assert evs[-1]["name"] == "scatter.health"
        assert evs[0]["attrs"]["i"] > 0

    def test_remote_header_respects_sampling_off(self):
        """A client-supplied X-Trace-Id must not buy recording back in
        when the operator turned tracing off (trace_sample_rate=0) —
        untrusted headers would otherwise control ring retention."""
        from tfidf_tpu.utils.tracing import remote_context
        global_tracer.configure(sample_rate=0.0)
        for trusted in (True, False):
            ctx = remote_context("deadbeefdeadbeef", "cafe0123",
                                 trusted=trusted)
            assert ctx is not None and ctx.sampled is False
            with global_tracer.span("worker.process", parent=ctx):
                pass
        assert global_tracer.recent(10) == []
        # untrusted front-door headers under PARTIAL sampling face the
        # local draw like any root — at a 1e-9 rate a client id cannot
        # buy its way to 100% recording (trusted internal propagation
        # stays sampled: the decision was made at the root)
        global_tracer.configure(sample_rate=1e-9)
        draws = [remote_context("deadbeefdeadbeef", "cafe0123",
                                trusted=False).sampled
                 for _ in range(64)]
        assert not any(draws)
        assert remote_context("deadbeefdeadbeef", "cafe0123",
                              trusted=True).sampled is True
        global_tracer.configure(sample_rate=1.0)
        assert remote_context("deadbeefdeadbeef", "cafe0123",
                              trusted=False).sampled is True
        assert remote_context(None, None) is None
        # untrusted ids must match the hex grammar — a hostile header
        # cannot inject arbitrary bytes into the ring / log stream /
        # reply headers (malformed falls back to a fresh root)
        for bad in ("x shed=0 lane=interactive", "A" * 70, "short",
                    "DEADBEEFDEADBEEF", "deadbeef" * 9):
            assert remote_context(bad, None, trusted=False) is None
        assert remote_context("deadbeefdeadbeef", "zz zz",
                              trusted=False) is None
        # the trusted (internal) continuation validates too: the
        # worker endpoints share the public listener, so a hostile
        # header can arrive on either path
        assert remote_context("anything-goes", None,
                              trusted=True) is None
        assert remote_context("deadbeefdeadbeef", None,
                              trusted=True) is not None

    def test_cli_trace_merges_linked_trace_from_worker_rings(
            self, monkeypatch, capsys):
        """Multi-process contract: worker-side continuations live under
        the BATCH trace id in the worker's OWN ring — the CLI's by-id
        fan-out must re-query nodes with the linked trace ids, or the
        timeline silently omits every worker span."""
        import tfidf_tpu.cluster.node as node_mod
        from tfidf_tpu.cli import main as cli_main
        req = {"trace_id": "req1", "span_id": "r1", "parent_id": None,
               "name": "leader.search", "start_s": 1.0,
               "duration_ms": 5.0, "attrs": {}, "events": [],
               "links": [{"trace_id": "batch1", "span_id": "b1"}]}
        # the batch absorbed a SIBLING request too: one-hop link
        # following must not drag it into req1's timeline
        batch = {"trace_id": "batch1", "span_id": "b1",
                 "parent_id": None, "name": "scatter.batch",
                 "start_s": 1.1, "duration_ms": 4.0, "attrs": {},
                 "events": [], "links": [{"trace_id": "req1",
                                          "span_id": "r1"},
                                         {"trace_id": "sibling",
                                          "span_id": "s1"}]}
        sib = {"trace_id": "sibling", "span_id": "s1",
               "parent_id": None, "name": "leader.search",
               "start_s": 1.0, "duration_ms": 5.0,
               "attrs": {"query": "other users secret"},
               "events": [], "links": [{"trace_id": "batch1",
                                        "span_id": "b1"}]}
        wspan = {"trace_id": "batch1", "span_id": "w1",
                 "parent_id": "b1", "name": "worker.process_batch",
                 "start_s": 1.2, "duration_ms": 2.0, "attrs": {},
                 "events": [], "links": []}
        rings = {  # per-node rings, disjoint like real processes
            "http://leader:1": {"req1": [req, batch],
                                "batch1": [req, batch, sib],
                                "sibling": [sib, batch]},
            "http://worker:2": {"batch1": [wspan]},
        }

        def fake_http_get(url, timeout=10.0, origin=None):
            base, _, path = url.partition("/api/")
            if path == "services":
                return json.dumps(["http://worker:2"]).encode()
            tid = path[len("trace/"):]
            return json.dumps(
                {"spans": rings.get(base, {}).get(tid, [])}).encode()

        monkeypatch.setattr(node_mod, "http_get", fake_http_get)
        assert cli_main(["trace", "req1", "--leader",
                         "http://leader:1"]) == 0
        out = capsys.readouterr().out
        assert "worker.process_batch" in out, out
        assert "leader.search" in out and "scatter.batch" in out
        # one hop only: the sibling request the batch also absorbed
        # stays out of this request's timeline
        assert "secret" not in out

    def test_batch_span_inherits_sampling_never_rerolls(self):
        """A batch span exists only because its linked requests won the
        sampling draw — it must inherit that verdict, not re-roll it
        (an independent draw drops a sampled request's whole scatter
        sub-trace with probability 1 - sample_rate). Proven at the
        adversarial extreme: rate 0 with a force-sampled request."""
        global_tracer.configure(sample_rate=0.0)
        co = Coalescer(lambda items: list(items), max_batch=4,
                       linger_s=0.0, pipeline=1, name="obs3")
        try:
            with global_tracer.span("req", sampled=True):
                co.submit("x")
            batch = [s for s in global_tracer.recent(50)
                     if s["name"] == "obs3.batch"]
            assert batch, \
                "batch span re-rolled sampling and was dropped"
        finally:
            co.stop()

    def test_untraced_submit_creates_no_batch_span(self):
        co = Coalescer(lambda items: list(items), max_batch=4,
                       linger_s=0.0, pipeline=1, name="obs2")
        try:
            co.submit("x")
            assert [s for s in global_tracer.recent(50)
                    if s["name"] == "obs2.batch"] == []
        finally:
            co.stop()

    def test_log_records_carry_trace_id(self):
        records = []

        class _Capture(_pylogging.Handler):
            def emit(self, record):
                records.append(record)

        logger = _pylogging.getLogger("tfidf_tpu")
        h = _Capture()
        logger.addHandler(h)
        try:
            log = get_logger("unittest")
            with global_tracer.span("traced") as sp:
                log.warning("inside", foo=1)
            log.warning("outside", foo=2)
        finally:
            logger.removeHandler(h)
        inside = next(r for r in records if "inside" in r.getMessage())
        outside = next(r for r in records
                       if "outside" in r.getMessage())
        assert inside.kv.get("trace") == sp.trace_id
        assert "trace" not in outside.kv

    def test_fault_fire_emits_span_event(self):
        from tfidf_tpu.utils.faults import (FaultInjected,
                                            global_injector)
        global_injector.arm("leader.sweep", action="raise", times=1)
        with global_tracer.span("chaos") as sp:
            with pytest.raises(FaultInjected):
                global_injector.check("leader.sweep")
        evs = [e for e in sp.to_dict()["events"]
               if e["name"] == "fault_injected"]
        assert evs and evs[0]["attrs"]["point"] == "leader.sweep"

    def test_chrome_export_and_render(self):
        with global_tracer.span("root") as root:
            span_event("tick", ms=1)
            with global_tracer.span("child", parent=root):
                pass
        spans = global_tracer.get_trace(root.trace_id)
        chrome = to_chrome_trace(spans)
        assert {e["ph"] for e in chrome["traceEvents"]} == {"X", "i"}
        tree = render_trace_tree(spans)
        assert "root" in tree and "child" in tree and "· tick" in tree
        assert render_trace_tree([]) == "(no spans)"


# ---------------------------------------------------------------------------
# Chaos-trace integration: the story reconstructs from the trace
# ---------------------------------------------------------------------------

def _search_traced(leader, q: str) -> tuple[dict, str]:
    """POST /leader/start returning (result, trace id) — the reply
    header contract every traced response carries."""
    req = urllib.request.Request(
        leader.url + "/leader/start",
        data=json.dumps({"query": q}).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=60) as r:
        return json.loads(r.read()), r.headers.get(TRACE_HEADER)


def _kill_data_plane(victim):
    """HTTP down, session alive (see tests/test_replication.py): only
    the WITHIN-REQUEST failover read keeps results complete."""
    victim.httpd.shutdown()
    victim.httpd.server_close()
    cls = victim.httpd.RequestHandlerClass

    def dead(handler):
        raise ConnectionResetError("worker killed (test)")
    cls.do_POST = dead
    cls.do_GET = dead


def _fetch_trace(leader, tid: str) -> list[dict]:
    return json.loads(http_get(
        leader.url + f"/api/trace/{tid}"))["spans"]


def _owning_worker(leader, nodes):
    """A worker node that OWNS at least one document under the current
    assignment — killing a non-owner exercises no failover slice (the
    owner assignment already avoids it), so victim choice must follow
    ownership, not list position."""
    live = frozenset(leader.registry.get_all_service_addresses())
    view = leader.placement.owner_assignment(live, frozenset())
    owners = set(view.owner.values())
    return next(nd for nd in nodes[1:] if nd.url in owners)


class TestChaosTrace:
    def test_worker_kill_mid_scatter_trace_reconstructs_story(
            self, core, tmp_path):
        """The acceptance criterion: kill a worker's data plane, search,
        fetch the trace by the reply's X-Trace-Id — it must contain the
        scatter (batch) span, per-worker child spans including the
        failed one, the failover re-issue slice parented under the
        scatter span, and the health annotation."""
        nodes = _mk_cluster(core, tmp_path, n=3)
        try:
            leader = nodes[0]
            _upload_docs(leader)
            want = _oracle(tmp_path)
            for q in QUERIES:
                _assert_parity(_search(leader, q), want[q], ctx=q)
            _kill_data_plane(_owning_worker(leader, nodes))
            story = None
            for _ in range(6):   # ownership decides which search pays
                res, tid = _search_traced(leader, "common")
                _assert_parity(res, want["common"], ctx="killed")
                assert tid
                time.sleep(0.1)   # worker-side spans finish async
                spans = _fetch_trace(leader, tid)
                if any(s["name"] == "scatter.slice" for s in spans):
                    story = spans
                    break
            assert story is not None, \
                "no search produced a failover slice"
            by_name: dict[str, list] = {}
            for s in story:
                by_name.setdefault(s["name"], []).append(s)
            # the request span, linked (not parented) to the batch
            req = by_name["leader.search"][0]
            batch = by_name["scatter.batch"][0]
            assert {l["trace_id"] for l in req["links"]} \
                == {batch["trace_id"]}
            assert req["trace_id"] != batch["trace_id"]
            # per-worker child spans PARENTED under the scatter span,
            # one of them errored (the killed worker)
            workers = by_name["scatter.worker"]
            assert len(workers) == 2
            assert all(w["parent_id"] == batch["span_id"]
                       for w in workers)
            assert any("error" in w["attrs"] for w in workers)
            # the failover re-issue, parented correctly, slice-typed
            sl = by_name["scatter.slice"][0]
            assert sl["parent_id"] == batch["span_id"]
            assert sl["attrs"]["kind"] == "failover"
            assert sl["attrs"]["names"] >= 1
            # the degraded flag annotated on the scatter span (failover
            # fully covered the death, so degraded=0 and failovers>0)
            health = [e for e in batch["events"]
                      if e["name"] == "scatter.health"]
            assert health
            assert health[0]["attrs"]["degraded"] == 0
            assert health[0]["attrs"]["failovers"] >= 1
            # the worker-side span of the surviving replica carries the
            # engine's phase events (the request timeline reaches into
            # the engine)
            wspans = by_name.get("worker.process_batch", ())
            assert any(
                any(e["name"].startswith("phase.")
                    for e in w["events"]) for w in wspans)
        finally:
            _stop_all(nodes)

    def test_hedge_win_visible_in_trace(self, core, tmp_path):
        nodes = _mk_cluster(core, tmp_path, n=3, scatter_hedge_ms=40.0)
        try:
            leader = nodes[0]
            _upload_docs(leader)
            want = _oracle(tmp_path)
            for q in QUERIES:   # warm compiled paths first
                _assert_parity(_search(leader, q), want[q], ctx=q)
            victim = _owning_worker(leader, nodes)
            orig_batch = victim.engine.search_batch
            orig_arrays = victim.engine.search_batch_arrays

            def slow_arrays(queries, k=None):
                time.sleep(2.0)
                return orig_arrays(queries, k=k)

            def slow_batch(queries, k=None, unbounded=False):
                time.sleep(2.0)
                return orig_batch(queries, k=k, unbounded=unbounded)

            victim.engine.search_batch_arrays = slow_arrays
            victim.engine.search_batch = slow_batch
            res, tid = _search_traced(leader, "common")
            _assert_parity(res, want["common"], ctx="hedged")
            victim.engine.search_batch_arrays = orig_arrays
            victim.engine.search_batch = orig_batch
            assert global_metrics.get("scatter_hedge_wins") >= 1
            spans = _fetch_trace(leader, tid)
            batch = next(s for s in spans
                         if s["name"] == "scatter.batch")
            evs = {e["name"] for e in batch["events"]}
            assert "hedge_dispatched" in evs
            assert "hedge_win" in evs
            hedges = [s for s in spans if s["name"] == "scatter.slice"
                      and s["attrs"].get("kind") == "hedge"]
            assert hedges
            assert all(h["parent_id"] == batch["span_id"]
                       for h in hedges)
        finally:
            _stop_all(nodes)

    def test_prometheus_endpoint_matches_json_snapshot(self, core,
                                                       tmp_path):
        """Integration half of the exposition contract: the leader's
        /api/metrics?format=prometheus parses strictly and its
        leader_search histogram p99 agrees with the JSON snapshot's
        leader_search_p99_ms."""
        nodes = _mk_cluster(core, tmp_path, n=3)
        try:
            leader = nodes[0]
            _upload_docs(leader)
            for _ in range(3):
                for q in QUERIES:
                    _search(leader, q)
            text = http_get(
                leader.url + "/api/metrics?format=prometheus").decode()
            parsed = parse_prometheus_strict(text)
            alias = http_get(leader.url + "/metrics").decode()
            parse_prometheus_strict(alias)
            h = parsed["tfidf_leader_search_seconds"]
            buckets = [(le, v) for n, le, v in h["samples"]
                       if n == "tfidf_leader_search_seconds_bucket"]
            snap = json.loads(http_get(leader.url + "/api/metrics"))
            want = snap["leader_search_p99_ms"] / 1e3
            got = _p_from_buckets(buckets, 0.99)
            # clamping to observed extremes can only tighten the JSON
            # estimate relative to the raw bucket read
            _assert_close_quantile(got, want, ctx="live prom p99")
            assert snap["leader_search_count"] \
                == [v for n, _le, v in h["samples"]
                    if n == "tfidf_leader_search_seconds_count"][0]
        finally:
            _stop_all(nodes)

    def test_slow_query_log_counts_and_keys_by_trace(self, core,
                                                     tmp_path):
        nodes = _mk_cluster(core, tmp_path, n=3,
                            trace_slow_query_ms=0.0001)
        try:
            leader = nodes[0]
            _upload_docs(leader)
            before = global_metrics.get("slow_queries")
            _res, tid = _search_traced(leader, "common")
            assert tid
            assert global_metrics.get("slow_queries") > before
        finally:
            _stop_all(nodes)

    def test_cli_trace_renders_timeline(self, core, tmp_path,
                                        capsys):
        from tfidf_tpu.cli import main as cli_main
        nodes = _mk_cluster(core, tmp_path, n=3)
        try:
            leader = nodes[0]
            _upload_docs(leader)
            _res, tid = _search_traced(leader, "common")
            time.sleep(0.1)
            assert cli_main(["trace", tid, "--leader",
                             leader.url]) == 0
            out = capsys.readouterr().out
            assert "leader.search" in out
            # entry via a WORKER url works too: /api/leader names the
            # leader (it left /api/services on promotion), so the
            # fan-out still reaches the ring that holds the request
            worker_url = nodes[1].url
            got = json.loads(http_get(worker_url + "/api/leader"))
            assert got["leader"] == leader.url
            assert cli_main(["trace", tid, "--leader",
                             worker_url]) == 0
            assert "leader.search" in capsys.readouterr().out
            # recent mode also renders
            assert cli_main(["trace", "--leader", leader.url,
                             "--recent", "50"]) == 0
        finally:
            _stop_all(nodes)

    def test_every_leader_response_carries_trace_id(self, core,
                                                    tmp_path):
        """The documented contract: ANY /leader/* reply's X-Trace-Id
        keys `tfidf_tpu trace` — uploads, deletes, and 429 sheds
        included, not just /leader/start."""
        import urllib.error
        nodes = _mk_cluster(core, tmp_path, n=3,
                            admission_rate_qps=1e-9)
        try:
            leader = nodes[0]
            # burst floors at ONE token per client bucket: distinct
            # client ids admit each mutating request once
            body = json.dumps([{"name": "t.txt",
                                "text": "hello"}]).encode()
            req = urllib.request.Request(
                leader.url + "/leader/upload-batch", data=body,
                headers={"Content-Type": "application/json",
                         "X-Client-Id": "obs-a"})
            with urllib.request.urlopen(req, timeout=30) as r:
                assert r.headers.get(TRACE_HEADER)
            req = urllib.request.Request(
                leader.url + "/leader/delete",
                data=json.dumps({"names": ["gone.txt"]}).encode(),
                headers={"Content-Type": "application/json",
                         "X-Client-Id": "obs-b"})
            with urllib.request.urlopen(req, timeout=30) as r:
                assert r.headers.get(TRACE_HEADER)
            # client obs-a's bucket is spent (rate ~0): its next
            # request sheds — and the 429 still carries the trace id
            # of the span minted at the admission point
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(urllib.request.Request(
                    leader.url + "/leader/start",
                    data=json.dumps({"query": "x"}).encode(),
                    headers={"Content-Type": "application/json",
                             "X-Client-Id": "obs-a"}),
                    timeout=30)
            assert ei.value.code == 429
            assert ei.value.headers.get(TRACE_HEADER)
            # /leader/download too — both the 404 reply and a real
            # streamed 200 carry the trace id (streams bypass _send)
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(urllib.request.Request(
                    leader.url + "/leader/download?path=absent.txt",
                    headers={"X-Client-Id": "obs-c"}), timeout=30)
            assert ei.value.code == 404
            assert ei.value.headers.get(TRACE_HEADER)
            # a handler FAILURE (500) keeps the contract too — the
            # span contextvar is gone by the outer except, but the
            # remembered span still keys the reply
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(urllib.request.Request(
                    leader.url + "/leader/delete",
                    data=b"{not json",
                    headers={"Content-Type": "application/json",
                             "X-Client-Id": "obs-e"}), timeout=30)
            assert ei.value.code == 500
            assert ei.value.headers.get(TRACE_HEADER)
            with urllib.request.urlopen(urllib.request.Request(
                    leader.url + "/leader/download?path=t.txt",
                    headers={"X-Client-Id": "obs-d"}),
                    timeout=30) as r:
                assert r.headers.get(TRACE_HEADER)
                assert r.read() == b"hello"
        finally:
            _stop_all(nodes)

    def test_recent_zero_returns_nothing(self, core, tmp_path):
        nodes = _mk_cluster(core, tmp_path, n=3)
        try:
            leader = nodes[0]
            _upload_docs(leader)
            _search(leader, "common")
            got = json.loads(http_get(
                leader.url + "/api/trace?recent=0"))
            assert got["spans"] == []
            assert global_tracer.recent(0) == []
            assert global_tracer.recent(-5) == []
        finally:
            _stop_all(nodes)

    def test_chrome_export_endpoint(self, core, tmp_path):
        nodes = _mk_cluster(core, tmp_path, n=3)
        try:
            leader = nodes[0]
            _upload_docs(leader)
            _res, tid = _search_traced(leader, "common")
            time.sleep(0.1)
            chrome = json.loads(http_get(
                leader.url + f"/api/trace/{tid}?format=chrome"))
            assert chrome["traceEvents"]
            assert any(e["name"] == "leader.search"
                       for e in chrome["traceEvents"])
        finally:
            _stop_all(nodes)

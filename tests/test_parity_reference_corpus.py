"""End-to-end Lucene parity on the reference's own corpus.

The goldens (tests/data/lucene_goldens.json) are produced by
``tests/lucene_golden.py`` — an independent Lucene-9-BM25 implementation
written from the Lucene spec, never touching tfidf_tpu code. These tests
lock the whole parity chain: StandardAnalyzer tokenization, SmallFloat
norm quantization, per-shard (non-global) IDF, unbounded results, and the
leader's sum-merge + alphabetical ordering (``Worker.java:222-241``,
``Leader.java:39-92``). Corpus: the 8 files the reference ships at
``TF-IDF-System-Core/src/main/resources/documents/`` (checked in at
``demo/corpus``).
"""

import json
import os

import pytest

from tfidf_tpu.engine.engine import Engine
from tfidf_tpu.utils.config import Config

HERE = os.path.dirname(os.path.abspath(__file__))
CORPUS = os.path.join(HERE, "..", "demo", "corpus")
GOLDENS = os.path.join(HERE, "data", "lucene_goldens.json")

ATOL = 1e-5


@pytest.fixture(scope="module")
def goldens():
    with open(GOLDENS, encoding="utf-8") as f:
        return json.load(f)


def parity_config(**kw) -> Config:
    return Config(model="bm25", lucene_parity=True, result_order="name",
                  unbounded_results=True,
                  min_doc_capacity=8, min_nnz_capacity=256,
                  min_vocab_capacity=64, query_batch=4, max_query_terms=8,
                  **kw)


def load_corpus() -> dict[str, bytes]:
    docs = {}
    for fn in sorted(os.listdir(CORPUS)):
        if fn.endswith(".txt"):
            with open(os.path.join(CORPUS, fn), "rb") as f:
                docs[fn] = f.read()
    return docs


def assert_matches(result: list, expected: dict[str, float]):
    assert [h.name for h in result] == sorted(expected), (
        [h.name for h in result], sorted(expected))
    for h in result:
        assert abs(h.score - expected[h.name]) < ATOL, (
            h.name, h.score, expected[h.name])


def test_goldens_are_fresh(goldens):
    """The checked-in fixture must match what the generator produces from
    the checked-in corpus (guards against silent corpus/fixture drift)."""
    from tests.lucene_golden import generate
    assert generate(CORPUS) == goldens


def test_single_worker_parity(tmp_path, goldens):
    e = Engine(parity_config(documents_path=str(tmp_path / "docs")))
    for name, data in load_corpus().items():
        e.ingest_bytes(name, data)
    e.commit()
    for q in goldens["queries"]:
        hits = e.search(q, unbounded=True)
        assert_matches(hits, goldens["single_worker"][q])


def test_two_worker_cluster_parity(tmp_path, goldens):
    """Two real engines holding the golden split, merged the way the
    leader merges (sum per name, alphabetical)."""
    split = goldens["two_worker_split"]
    corpus = load_corpus()
    merged_expected = goldens["two_workers"]
    engines = []
    for w in ("w0", "w1"):
        e = Engine(parity_config(documents_path=str(tmp_path / w)))
        for name in split[w]:
            e.ingest_bytes(name, corpus[name])
        e.commit()
        engines.append(e)
    for q in goldens["queries"]:
        merged: dict[str, float] = {}
        for e in engines:
            for h in e.search(q, unbounded=True):
                merged[h.name] = merged.get(h.name, 0.0) + h.score
        expected = merged_expected[q]
        assert sorted(merged) == sorted(expected)
        for name, score in merged.items():
            assert abs(score - expected[name]) < ATOL, (name, score,
                                                        expected[name])


def test_segments_mode_parity(tmp_path, goldens):
    """Streaming-segment layout scores identically (one commit per pair
    of files, so multiple segments exist)."""
    e = Engine(parity_config(documents_path=str(tmp_path / "docs"),
                             index_mode="segments"))
    items = list(load_corpus().items())
    for i in range(0, len(items), 2):
        for name, data in items[i:i + 2]:
            e.ingest_bytes(name, data)
        e.commit()
    for q in goldens["queries"]:
        hits = e.search(q, unbounded=True)
        assert_matches(hits, goldens["single_worker"][q])


def test_mesh_local_stats_parity(tmp_path, goldens):
    """Mesh engine in parity mode (global_idf=False): every docs-shard
    scores against local statistics, like each Java worker. With the
    corpus round-robined over 8 shards the result is the 8-'worker'
    analog — verified against a golden computed per-shard."""
    from tests.lucene_golden import LuceneShard, analyze, leader_search

    e = Engine(parity_config(documents_path=str(tmp_path / "docs"),
                             engine_mode="mesh"))
    corpus = load_corpus()
    names = sorted(corpus)
    for name in names:
        e.ingest_bytes(name, corpus[name])
    e.commit()
    D = e.index.D
    # reproduce the engine's round-robin placement per shard
    placement = [[] for _ in range(D)]
    for i, name in enumerate(names):
        placement[i % D].append(name)
    shards = [LuceneShard({n: corpus[n].decode() for n in group})
              for group in placement if group]
    for q in goldens["queries"]:
        expected = leader_search(shards, q)
        hits = e.search(q, unbounded=True)
        assert_matches(hits, expected)

"""Coordination substrate tests: znode semantics, sessions, watches.

Covers the four ZooKeeper primitives the reference relies on (SURVEY.md §2):
persistent/ephemeral/ephemeral-sequential nodes, data payloads, one-shot
watches, and session-timeout liveness — over both the in-process and the
HTTP transports.
"""

import time

import pytest

from tfidf_tpu.cluster.coordination import (
    CHILDREN_CHANGED, EPHEMERAL, EPHEMERAL_SEQUENTIAL, NODE_DELETED,
    CoordinationCore, CoordinationServer, CoordinationClient,
    LocalCoordination, NodeExistsError, NoNodeError)


def wait_until(pred, timeout=5.0, interval=0.01):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return pred()


@pytest.fixture
def core():
    c = CoordinationCore(session_timeout_s=0.5)
    yield c
    c.close()


@pytest.fixture
def local(core):
    clients = []

    def make():
        cl = LocalCoordination(core, heartbeat_interval_s=0.1)
        clients.append(cl)
        return cl

    yield make
    for cl in clients:
        cl.close()


class TestTree:
    def test_create_get_set_delete(self, local):
        c = local()
        c.create("/a", b"hello")
        assert c.exists("/a")
        assert c.get_data("/a") == b"hello"
        c.set_data("/a", b"world")
        assert c.get_data("/a") == b"world"
        c.delete("/a")
        assert not c.exists("/a")

    def test_duplicate_create_raises(self, local):
        c = local()
        c.create("/a")
        with pytest.raises(NodeExistsError):
            c.create("/a")
        c.ensure("/a")   # create-if-absent does not raise

    def test_missing_parent_and_node(self, local):
        c = local()
        with pytest.raises(NoNodeError):
            c.create("/a/b")
        with pytest.raises(NoNodeError):
            c.get_data("/nope")
        with pytest.raises(NoNodeError):
            c.delete("/nope")

    def test_sequential_naming(self, local):
        """EPHEMERAL_SEQUENTIAL appends a monotonically increasing zero-
        padded counter, like ZooKeeper's c_0000000000 naming that the
        election sorts on (LeaderElection.java:60-63)."""
        c = local()
        c.create("/election")
        p0 = c.create("/election/c_", mode=EPHEMERAL_SEQUENTIAL)
        p1 = c.create("/election/c_", mode=EPHEMERAL_SEQUENTIAL)
        assert p0 == "/election/c_0000000000"
        assert p1 == "/election/c_0000000001"
        assert c.get_children("/election") == ["c_0000000000",
                                               "c_0000000001"]

    def test_children_sorted(self, local):
        c = local()
        c.create("/r")
        for name in ["b", "a", "c"]:
            c.create(f"/r/{name}")
        assert c.get_children("/r") == ["a", "b", "c"]


class TestSessions:
    def test_ephemeral_vanishes_on_close(self, local):
        c1, c2 = local(), local()
        c1.create("/svc")
        c1.create("/svc/n_", b"addr", mode=EPHEMERAL_SEQUENTIAL)
        assert c2.get_children("/svc") != []
        c1.close()
        assert wait_until(lambda: c2.get_children("/svc") == [])

    def test_session_timeout_expires_ephemerals(self, core, local):
        """A node that stops heartbeating is declared dead after the
        session timeout — the reference's failure detector
        (ZookeeperConfig.java:17, 3000ms; scaled down here)."""
        c1, c2 = local(), local()
        c1.create("/svc")
        c1.create("/svc/n_", b"x", mode=EPHEMERAL)
        # simulate a partitioned/crashed node: stop heartbeats
        c1._closed.set()
        assert wait_until(lambda: c2.get_children("/svc") == [],
                          timeout=3.0)

    def test_forced_expiry_fault_injection(self, core, local):
        c1, c2 = local(), local()
        c1.create("/svc")
        c1.create("/svc/e", b"x", mode=EPHEMERAL)
        core.expire_session(c1.sid)
        assert wait_until(lambda: not c2.exists("/svc/e"))

    def test_persistent_survives_session(self, local):
        c1, c2 = local(), local()
        c1.create("/keep", b"data")
        c1.close()
        time.sleep(0.1)
        assert c2.get_data("/keep") == b"data"


class TestWatches:
    def test_deletion_watch_fires_once(self, local):
        c1, c2 = local(), local()
        c1.create("/t")
        events = []
        assert c1.exists("/t", watcher=events.append)
        c2.delete("/t")
        assert wait_until(lambda: len(events) == 1)
        assert events[0].type == NODE_DELETED
        assert events[0].path == "/t"
        # one-shot: recreating and deleting again fires nothing new
        c2.create("/t")
        c2.delete("/t")
        time.sleep(0.2)
        assert len(events) == 1

    def test_children_watch(self, local):
        c1, c2 = local(), local()
        c1.create("/r")
        events = []
        c1.get_children("/r", watcher=events.append)
        c2.create("/r/x")
        assert wait_until(lambda: len(events) == 1)
        assert events[0].type == CHILDREN_CHANGED

    def test_watch_rearm_pattern(self, local):
        """The registry's pattern: refresh + re-arm inside the callback
        (ServiceRegistry.java:91-122)."""
        c1, c2 = local(), local()
        c1.create("/r")
        seen = []

        def on_change(ev):
            seen.append(c1.get_children("/r", watcher=on_change))

        c1.get_children("/r", watcher=on_change)
        c2.create("/r/a")
        assert wait_until(lambda: len(seen) >= 1)
        c2.create("/r/b")
        assert wait_until(lambda: any("b" in s for s in seen))


class TestHTTPTransport:
    def test_full_stack_over_http(self):
        # generous timeout: under full-suite load (JAX compiles hogging the
        # GIL) heartbeat threads can stall well past a sub-second deadline
        server = CoordinationServer(session_timeout_s=3.0).start()
        try:
            c1 = CoordinationClient(server.address,
                                    heartbeat_interval_s=0.2)
            c2 = CoordinationClient(server.address,
                                    heartbeat_interval_s=0.2)
            c1.create("/svc")
            path = c1.create("/svc/n_", b"http://w0",
                             mode=EPHEMERAL_SEQUENTIAL)
            assert path == "/svc/n_0000000000"
            assert c2.get_data(path) == b"http://w0"

            events = []
            c2.get_children("/svc", watcher=events.append)
            c1.close()   # session close → ephemeral gone → watch fires
            assert wait_until(lambda: len(events) >= 1, timeout=5.0)
            assert c2.get_children("/svc") == []
            c2.close()
        finally:
            server.close()

    def test_http_errors_map_to_exceptions(self):
        server = CoordinationServer(session_timeout_s=5.0).start()
        try:
            c = CoordinationClient(server.address, heartbeat_interval_s=0.5)
            c.create("/a")
            with pytest.raises(NodeExistsError):
                c.create("/a")
            with pytest.raises(NoNodeError):
                c.get_data("/missing")
            c.close()
        finally:
            server.close()

"""Shard recovery on worker loss (VERDICT r4 #4 / SURVEY §5.3).

The reference's story: a dead worker's shard is unsearchable until the pod
restarts and re-walks its volume (``Worker.java:77-94``). Here the leader
re-places the lost shard's documents onto survivors from its durable
store, and reconciles a rejoining worker by deleting the moved copies."""

import json
import time

import pytest

from tfidf_tpu.cluster.coordination import CoordinationCore, LocalCoordination
from tfidf_tpu.cluster.node import SearchNode, http_get, http_post
from tfidf_tpu.utils.config import Config

from tests.test_cluster import wait_until


@pytest.fixture
def core():
    c = CoordinationCore(session_timeout_s=0.5)
    yield c
    c.close()


def _node(core, tmp_path, i, port=0):
    # replication_factor=1: this suite covers the SINGLE-COPY recovery
    # machinery (re-placement from the durable store); the replicated
    # failover path is tests/test_replication.py
    cfg = Config(
        documents_path=str(tmp_path / f"sr{i}" / "documents"),
        index_path=str(tmp_path / f"sr{i}" / "index"),
        port=port, top_k=32, replication_factor=1,
        min_doc_capacity=64, min_nnz_capacity=1 << 12,
        min_vocab_capacity=1 << 10, query_batch=8, max_query_terms=8)
    return SearchNode(cfg, coord=LocalCoordination(core, 0.1)).start()


DOCS = {f"r{i}.txt": f"common token{i} word{i % 3}" for i in range(12)}


def _search_names(leader, q, k=32):
    res = json.loads(http_post(
        leader.url + "/leader/start",
        json.dumps({"query": q}).encode()))
    return set(res), res


def test_worker_loss_replaces_shard_and_rejoin_reconciles(core, tmp_path):
    nodes = [_node(core, tmp_path, i) for i in range(3)]
    leader = nodes[0]
    try:
        wait_until(lambda: len(
            leader.registry.get_all_service_addresses()) == 2)
        # mixed upload paths: bulk (text) + per-file
        batch = [{"name": n, "text": t} for n, t in list(DOCS.items())[:8]]
        http_post(leader.url + "/leader/upload-batch",
                  json.dumps(batch).encode())
        for n, t in list(DOCS.items())[8:]:
            http_post(leader.url + f"/leader/upload?name={n}", t.encode(),
                      content_type="application/octet-stream")
        names0, _ = _search_names(leader, "common")
        assert names0 == set(DOCS)

        victim = nodes[1]
        victim_port = victim.port
        victim_names = {n for n, ws in leader._placement.items()
                        if victim.url in ws}
        assert victim_names   # placement spread over both workers
        survivor_names = set(DOCS) - victim_names

        # kill the victim: HTTP down + session expired
        victim.httpd.shutdown()
        victim.httpd.server_close()
        core.expire_session(victim.coord.sid)
        assert wait_until(lambda: leader.registry
                          .get_all_service_addresses()
                          == [nodes[2].url], timeout=5.0)
        # recovery re-places the lost shard onto the survivor
        assert wait_until(
            lambda: _search_names(leader, "common")[0] == set(DOCS),
            timeout=10.0), _search_names(leader, "common")[0]
        # search convergence races the recovery's final metric bump by a
        # hair (the counter lands after the last re-placement batch) —
        # poll instead of reading once
        def metrics():
            return json.loads(http_get(leader.url + "/api/metrics"))
        assert wait_until(
            lambda: metrics().get("shard_recoveries", 0) >= 1, timeout=5.0)
        assert metrics().get("shard_docs_replaced", 0) >= len(victim_names)
        # placement now maps every doc to the survivor
        with leader._placement_lock:
            holders = {w for n in DOCS for w in leader._placement[n]}
        assert holders == {nodes[2].url}
        want_scores = _search_names(leader, "common")[1]

        # the victim POD restarts: same URL, same docs dir (its old
        # shard files are still there), boot re-walk re-indexes them
        revived = _node(core, tmp_path, 1, port=victim_port)
        nodes.append(revived)
        assert revived.url == victim.url
        assert wait_until(lambda: sorted(
            leader.registry.get_all_service_addresses())
            == sorted([nodes[2].url, revived.url]), timeout=5.0)
        # reconciliation deletes the moved docs from the rejoiner: the
        # sum-merge must NOT double-count (scores converge back)
        def reconciled():
            names, scores = _search_names(leader, "common")
            return names == set(DOCS) and all(
                abs(scores[n] - want_scores[n]) < 1e-6 for n in DOCS)
        assert wait_until(reconciled, timeout=10.0), \
            (_search_names(leader, "common")[1], want_scores)
    finally:
        for n in nodes:
            try:
                n.stop()
            except Exception:
                pass


def test_recovery_disabled_keeps_reference_behavior(core, tmp_path):
    cfgs = []
    nodes = []
    try:
        for i in range(3):
            cfg = Config(
                documents_path=str(tmp_path / f"nr{i}" / "documents"),
                index_path=str(tmp_path / f"nr{i}" / "index"),
                port=0, shard_recovery=False, top_k=32,
                replication_factor=1,
                min_doc_capacity=64, min_nnz_capacity=1 << 12,
                min_vocab_capacity=1 << 10, query_batch=8,
                max_query_terms=8)
            cfgs.append(cfg)
            nodes.append(SearchNode(
                cfg, coord=LocalCoordination(core, 0.1)).start())
        leader = nodes[0]
        wait_until(lambda: len(
            leader.registry.get_all_service_addresses()) == 2)
        for n, t in DOCS.items():
            http_post(leader.url + f"/leader/upload?name={n}", t.encode(),
                      content_type="application/octet-stream")
        victim = nodes[1]
        victim_names = {n for n, ws in leader._placement.items()
                        if victim.url in ws}
        core.expire_session(victim.coord.sid)
        assert wait_until(lambda: leader.registry
                          .get_all_service_addresses()
                          == [nodes[2].url], timeout=5.0)
        time.sleep(0.5)
        names, _ = _search_names(leader, "common")
        # the lost shard stays dark (Worker.java:77-94 semantics)
        assert names == set(DOCS) - victim_names
    finally:
        for n in nodes:
            try:
                n.stop()
            except Exception:
                pass

"""Independent numpy reference implementations (golden oracles).

Deliberately written as naive per-document loops — structurally unlike the
chunked/segment-sum device kernels they validate — so a shared bug is
unlikely. BM25 follows Lucene 9 BM25Similarity (idf = ln(1+(N-df+0.5)/
(df+0.5)), no (k1+1) numerator); TF-IDF follows the smoothed-idf scheme
documented in tfidf_tpu.ops.scoring.
"""

from __future__ import annotations

import math


def df_of(docs: list[dict[int, int]]) -> dict[int, int]:
    df: dict[int, int] = {}
    for d in docs:
        for t in d:
            df[t] = df.get(t, 0) + 1
    return df


def bm25_scores(docs: list[dict[int, int]], lengths: list[float],
                query: dict[int, float], *, k1: float = 1.2,
                b: float = 0.75, n_docs: float | None = None,
                df: dict[int, int] | None = None,
                avgdl: float | None = None) -> list[float]:
    n = float(len(docs) if n_docs is None else n_docs)
    df = df_of(docs) if df is None else df
    if avgdl is None:
        avgdl = sum(lengths) / max(len(lengths), 1)
    out = []
    for d, dl in zip(docs, lengths):
        s = 0.0
        for t, qw in query.items():
            tf = d.get(t, 0)
            if tf == 0:
                continue
            idf = math.log(1.0 + (n - df.get(t, 0) + 0.5)
                           / (df.get(t, 0) + 0.5))
            s += qw * idf * tf / (tf + k1 * (1 - b + b * dl / avgdl))
        out.append(s)
    return out


def tfidf_scores(docs: list[dict[int, int]], query: dict[int, float],
                 *, n_docs: float | None = None,
                 df: dict[int, int] | None = None,
                 cosine: bool = False) -> list[float]:
    n = float(len(docs) if n_docs is None else n_docs)
    df = df_of(docs) if df is None else df

    def idf(t: int) -> float:
        return math.log((1.0 + n) / (1.0 + df.get(t, 0))) + 1.0

    out = []
    for d in docs:
        s = sum(qw * d.get(t, 0) * idf(t) for t, qw in query.items())
        if cosine:
            norm = math.sqrt(sum((tf * idf(t)) ** 2 for t, tf in d.items()))
            s = s / norm if norm > 0 else 0.0
        out.append(s)
    return out


def random_corpus(rng, n_docs: int, vocab: int, max_len: int = 60,
                  zipf_a: float = 1.3) -> tuple[list[dict[int, int]],
                                                list[float]]:
    """Zipfian synthetic corpus: returns (term->tf maps, analyzed lengths)."""
    docs, lengths = [], []
    for _ in range(n_docs):
        length = int(rng.integers(1, max_len))
        terms = rng.zipf(zipf_a, size=length) % vocab
        counts: dict[int, int] = {}
        for t in terms:
            counts[int(t)] = counts.get(int(t), 0) + 1
        docs.append(counts)
        lengths.append(float(length))
    return docs, lengths

import numpy as np

from tfidf_tpu.ops.csr import (CooShard, build_coo, merge_coo, next_capacity,
                               widen_vocab)


def test_next_capacity():
    assert next_capacity(0, 16) == 16
    assert next_capacity(16, 16) == 16
    assert next_capacity(17, 16) == 32
    assert next_capacity(1000, 16) == 1024


def test_build_coo_contents():
    docs = [{1: 2, 3: 1}, {}, {3: 4}]
    s = build_coo(docs, vocab_cap=8, min_nnz_cap=4, min_doc_cap=4)
    assert s.nnz == 3 and s.num_docs == 3
    assert s.tf[:3].tolist() == [2.0, 1.0, 4.0]
    assert s.term[:3].tolist() == [1, 3, 3]
    assert s.doc[:3].tolist() == [0, 0, 2]
    assert s.doc_len[:3].tolist() == [3.0, 0.0, 4.0]
    assert s.df.tolist() == [0, 1, 0, 2, 0, 0, 0, 0]
    # padding is inert: zero tf beyond nnz
    assert s.tf[3:].sum() == 0


def test_row_sorted():
    docs = [{i: 1, i + 1: 2} for i in range(10)]
    s = build_coo(docs, vocab_cap=16, min_nnz_cap=4, min_doc_cap=4)
    rows = s.doc[:s.nnz]
    assert (np.diff(rows) >= 0).all()


def test_merge_coo():
    a = build_coo([{0: 1}, {1: 2}], vocab_cap=4, min_nnz_cap=4, min_doc_cap=4)
    b = build_coo([{1: 3}], vocab_cap=4, min_nnz_cap=4, min_doc_cap=4)
    m = merge_coo([a, b], vocab_cap=4, min_nnz_cap=4, min_doc_cap=4)
    assert m.nnz == 3 and m.num_docs == 3
    assert m.doc[:3].tolist() == [0, 1, 2]   # renumbered
    assert m.df.tolist() == [1, 2, 0, 0]
    assert m.doc_len[:3].tolist() == [1.0, 2.0, 3.0]


def test_widen_vocab():
    a = build_coo([{0: 1}], vocab_cap=4, min_nnz_cap=4, min_doc_cap=4)
    w = widen_vocab(a, 16)
    assert w.vocab_cap == 16 and w.df[:4].tolist() == a.df.tolist()
    assert widen_vocab(a, 2) is a


def test_size_bytes_positive():
    a = build_coo([{0: 1}], vocab_cap=4, min_nnz_cap=4, min_doc_cap=4)
    assert a.size_bytes() > 0

"""Micro-batching of concurrent queries into one device batch."""

import threading
import time

import pytest

from tfidf_tpu.cluster.batcher import QueryBatcher
from tfidf_tpu.engine.engine import Engine
from tfidf_tpu.utils.config import Config

TEXTS = {
    "a.txt": "the quick brown fox",
    "b.txt": "lazy dog sleeps",
    "c.txt": "brown dog barks at the fox",
}


class RecordingEngine:
    """search_batch stub that records batch sizes and echoes queries."""

    def __init__(self, delay_s: float = 0.0):
        self.batches = []
        self.delay_s = delay_s

    def search_batch(self, queries, k=None, unbounded=False):
        self.batches.append(len(queries))
        if self.delay_s:
            time.sleep(self.delay_s)
        return [[(q, k, unbounded)] for q in queries]


@pytest.fixture
def engine(tmp_path):
    cfg = Config(documents_path=str(tmp_path / "docs"),
                 min_doc_capacity=8, min_nnz_capacity=256,
                 min_vocab_capacity=64, query_batch=8, max_query_terms=8)
    e = Engine(cfg)
    for name, text in TEXTS.items():
        e.ingest_text(name, text)
    e.commit()
    return e


def test_single_query_passthrough(engine):
    b = QueryBatcher(engine, max_batch=8, linger_s=0.0)
    try:
        hits = b.search("fox")
        assert sorted(h.name for h in hits) == ["a.txt", "c.txt"]
    finally:
        b.stop()


def test_concurrent_queries_all_correct(engine):
    b = QueryBatcher(engine, max_batch=4, linger_s=0.02)
    results = {}
    try:
        def one(q):
            results[q] = b.search(q)

        threads = [threading.Thread(target=one, args=(q,))
                   for q in ("fox", "dog", "brown", "lazy", "barks")]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=10)
        assert sorted(h.name for h in results["fox"]) == ["a.txt", "c.txt"]
        assert sorted(h.name for h in results["lazy"]) == ["b.txt"]
        assert sorted(h.name for h in results["dog"]) == ["b.txt", "c.txt"]
    finally:
        b.stop()


def test_batches_actually_group():
    eng = RecordingEngine(delay_s=0.05)   # slow step -> queue piles up
    b = QueryBatcher(eng, max_batch=8, linger_s=0.02)
    try:
        threads = [threading.Thread(target=b.search, args=(f"q{i}",))
                   for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=10)
        assert sum(eng.batches) == 8
        assert max(eng.batches) >= 2, eng.batches
    finally:
        b.stop()


def test_mixed_parameters_split_into_groups():
    eng = RecordingEngine(delay_s=0.05)
    b = QueryBatcher(eng, max_batch=8, linger_s=0.02)
    out = {}
    try:
        def one(q, unbounded):
            out[q] = b.search(q, unbounded=unbounded)

        threads = [threading.Thread(target=one, args=(f"q{i}", i % 2 == 0))
                   for i in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=10)
        # every caller got ITS parameters back, not its batchmates'
        for q, hits in out.items():
            qq, k, unb = hits[0]
            assert qq == q
            assert unb == (int(q[1]) % 2 == 0)
    finally:
        b.stop()


def test_error_propagates_to_all_waiters():
    class Boom:
        def search_batch(self, queries, k=None, unbounded=False):
            raise ValueError("scoring exploded")

    b = QueryBatcher(Boom(), max_batch=4, linger_s=0.0)
    try:
        with pytest.raises(ValueError, match="scoring exploded"):
            b.search("anything")
    finally:
        b.stop()


def test_stop_fails_pending_not_hangs():
    class Slow:
        def search_batch(self, queries, k=None, unbounded=False):
            time.sleep(0.2)
            return [[] for _ in queries]

    b = QueryBatcher(Slow(), max_batch=1, linger_s=0.0)
    errs = []

    def one():
        try:
            b.search("q")
        except RuntimeError as e:
            errs.append(e)

    threads = [threading.Thread(target=one) for _ in range(3)]
    for t in threads:
        t.start()
    time.sleep(0.05)
    b.stop()
    for t in threads:
        t.join(timeout=5)
        assert not t.is_alive()


def test_pipeline_threads_concurrent_and_stop_clean(engine):
    """pipeline=2: concurrent queries still all answer correctly, and
    stop() terminates BOTH scorer threads (a _take_batch clearing _wake
    after stop() set it would park sibling threads forever —
    code-review r4)."""
    b = QueryBatcher(engine, max_batch=4, linger_s=0.002, pipeline=2)
    results = {}

    def run(q):
        results[q] = b.search(q)

    threads = [threading.Thread(target=run, args=(f"fox t{i}",))
               for i in range(12)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=10)
    assert len(results) == 12
    t0 = time.monotonic()
    b.stop()
    # a parked thread makes stop() eat the full 2s join timeout PER
    # thread (4s at pipeline=2); stay below that signature with slack
    # for CPU-contended CI hosts
    assert time.monotonic() - t0 < 3.5, "stop() stalled on parked thread"
    for t in b._threads:
        t.join(timeout=1.0)
        assert not t.is_alive(), "batcher thread leaked after stop()"

"""O(batch) commit stats: the incremental df/N/avgdl contract.

Two pins per index family (ISSUE 15 tentpole b):

* **witness**: steady-state commits never invoke the O(corpus) full
  stat recompute — the ``df_full_recomputes`` counter moves only on
  the documented exceptional paths (first commit / vocab growth /
  mesh rebuild / the ``df_incremental=false`` control path);
* **exact parity**: after randomized upsert → delete → merge → commit
  sequences, the incrementally maintained device df and the N/avgdl
  scalars equal a full recompute BIT-EXACTLY (df counts are integer-
  valued f32 adds — the same anti-entropy style the placement map
  uses: incremental state must always be reconcilable with a scratch
  rebuild).
"""

import numpy as np
import pytest

from tfidf_tpu.engine.engine import Engine
from tfidf_tpu.utils.config import Config

# a fixed word pool keeps the vocabulary (and its power-of-two
# capacity bucket) stable, so no commit takes the vocab-growth resync
WORDS = [f"w{i}" for i in range(48)]


def make_engine(tmp_path, sub, mode, **kw):
    cfg = Config(documents_path=str(tmp_path / sub),
                 engine_mode="mesh" if mode == "mesh" else "local",
                 index_mode="segments" if mode == "segments"
                 else "rebuild",
                 min_doc_capacity=8, min_nnz_capacity=256,
                 min_vocab_capacity=64, query_batch=4,
                 max_query_terms=8, **kw)
    return Engine(cfg)


def rand_text(rng, n_lo=3, n_hi=12):
    n = int(rng.integers(n_lo, n_hi))
    return " ".join(WORDS[i] for i in rng.integers(0, len(WORDS), n))


def seg_oracle(index, vocab_cap):
    """Full recompute over the segment set (tombstone-inclusive df and
    totals — the exact semantics of the old per-commit pass)."""
    with index._write_lock:
        return index._stats_scratch_locked(vocab_cap)


def assert_segment_stats_exact(engine):
    index = engine.index
    snap = index.snapshot
    vocab_cap = snap.df.shape[0]
    df_o, count_o, len_o, live_o = seg_oracle(index, vocab_cap)
    np.testing.assert_array_equal(np.asarray(snap.df), df_o)
    assert float(np.asarray(snap.n_docs)) == float(count_o)
    expect_avgdl = np.float32(len_o / count_o if count_o else 1.0)
    assert float(np.asarray(snap.avgdl)) == pytest.approx(
        float(expect_avgdl), rel=1e-6)
    assert index._live_total == live_o


class TestSegmentsWitness:
    def test_steady_commits_never_full_recompute(self, tmp_path):
        e = make_engine(tmp_path, "w", "segments")
        rng = np.random.default_rng(0)
        for i in range(4):
            e.ingest_text(f"d{i}.txt", rand_text(rng))
        e.commit()
        assert e.index.df_full_recomputes == 1   # first commit only
        base = e.index.df_full_recomputes
        # appends, upserts, deletes — all steady-state
        for round_ in range(5):
            e.ingest_text(f"n{round_}.txt", rand_text(rng))
            e.ingest_text("d0.txt", rand_text(rng))      # upsert
            e.commit()
            assert_segment_stats_exact(e)
        e.delete("d1.txt")
        e.commit()
        assert_segment_stats_exact(e)
        assert e.index.df_full_recomputes == base, \
            "a steady-state commit took the O(corpus) recompute path"

    def test_vocab_growth_takes_the_resync(self, tmp_path):
        e = make_engine(tmp_path, "vg", "segments")
        e.ingest_text("a.txt", "w0 w1 w2")
        e.commit()
        base = e.index.df_full_recomputes
        # push the vocabulary over the 64-term capacity bucket
        e.ingest_text("big.txt", " ".join(f"x{i}" for i in range(80)))
        e.commit()
        assert e.index.df_full_recomputes == base + 1
        assert_segment_stats_exact(e)

    def test_control_path_counts_every_commit(self, tmp_path):
        e = make_engine(tmp_path, "ctl", "segments",
                        df_incremental=False)
        rng = np.random.default_rng(1)
        for i in range(3):
            e.ingest_text(f"d{i}.txt", rand_text(rng))
            e.commit()
        assert e.index.df_full_recomputes == 3
        assert_segment_stats_exact(e)


class TestSegmentsRandomized:
    @pytest.mark.parametrize("seed", [0, 7])
    def test_upsert_delete_merge_commit_parity(self, tmp_path, seed):
        """max_segments=2 forces inline merges nearly every commit, so
        the splice-delta bookkeeping is exercised alongside appends,
        upserts, and tombstones — df/N/avgdl must stay bit-exact vs
        the scratch recompute, with the witness frozen after setup."""
        e = make_engine(tmp_path, f"rz{seed}", "segments",
                        max_segments=2)
        rng = np.random.default_rng(seed)
        alive = set()
        for i in range(4):
            name = f"d{i}.txt"
            e.ingest_text(name, rand_text(rng))
            alive.add(name)
        e.commit()
        base = e.index.df_full_recomputes
        next_id = 4
        for _round in range(12):
            op = rng.integers(0, 3)
            if op == 0 or not alive:                    # add
                name = f"d{next_id}.txt"
                next_id += 1
                e.ingest_text(name, rand_text(rng))
                alive.add(name)
            elif op == 1:                               # upsert
                name = sorted(alive)[int(rng.integers(0, len(alive)))]
                e.ingest_text(name, rand_text(rng))
            else:                                       # delete
                name = sorted(alive)[int(rng.integers(0, len(alive)))]
                assert e.delete(name)
                alive.discard(name)
            e.commit()
            assert_segment_stats_exact(e)
        assert e.index.df_full_recomputes == base
        assert e.index.snapshot.version >= 12
        # merges actually happened (the point of max_segments=2)
        assert len(e.index.snapshot.segments) <= 3
        # end-to-end: equal results vs a fresh rebuild engine over the
        # surviving corpus (IDF from merged segments must not drift)
        if alive:
            reb = make_engine(tmp_path, f"rzr{seed}", "rebuild")
            with e.index._write_lock:
                live_docs = {d.name: d for d in
                             e.index._live_entries_locked()}
            for name in sorted(alive):
                d = live_docs[name]
                reb.index.add_document_arrays(
                    name, d.term_ids, d.tfs, d.length)
            # share the vocabulary mapping (ids must agree)
            reb.vocab = e.vocab
            reb.searcher.vocab = e.vocab
            reb.commit()
            q = WORDS[3] + " " + WORDS[11]
            got = [(h.name, round(h.score, 5)) for h in e.search(q)]
            want = [(h.name, round(h.score, 5)) for h in reb.search(q)]
            assert got == want

    def test_cosine_commits_still_exact(self, tmp_path):
        """The cosine model reads the CURRENT dense df host-side for
        norms — the incremental path must hand it the same df the
        device sees."""
        e = make_engine(tmp_path, "cos", "segments",
                        model="tfidf_cosine")
        rng = np.random.default_rng(3)
        for i in range(4):
            e.ingest_text(f"d{i}.txt", rand_text(rng))
        e.commit()
        e.ingest_text("d9.txt", rand_text(rng))
        e.commit()
        assert_segment_stats_exact(e)
        assert any(e.search(WORDS[5]) for _ in [0])    # serves


def mesh_stats_exact(engine):
    index = engine.index
    cap = engine.vocab.capacity()
    inc = index._live_stats(cap)
    scr = index._live_stats_scratch(cap)
    assert inc[1] == scr[1]
    assert abs(inc[2] - scr[2]) < 1e-6
    np.testing.assert_array_equal(inc[0], scr[0])
    snap = index.snapshot
    if snap is not None and not index._df_delta.journal:
        np.testing.assert_array_equal(
            np.asarray(snap.df_g)[:scr[0].shape[0]], scr[0])


class TestMeshWitness:
    def test_steady_append_commits_never_recompute(self, tmp_path):
        e = make_engine(tmp_path, "mw", "mesh")
        rng = np.random.default_rng(5)
        for i in range(6):
            e.ingest_text(f"d{i}.txt", rand_text(rng))
        e.commit()
        # the first commit is a rebuild (base construction) — the one
        # sanctioned O(corpus) resync
        assert e.index.df_full_recomputes == e.index.rebuilds == 1
        for round_ in range(3):
            e.ingest_text(f"n{round_}.txt", rand_text(rng))
            e.ingest_text("d0.txt", rand_text(rng))      # upsert
            e.commit()
            mesh_stats_exact(e)
        e.delete("d1.txt")
        e.commit()
        mesh_stats_exact(e)
        # witness only ever tracks rebuilds, never steady commits
        assert e.index.df_full_recomputes == e.index.rebuilds

    def test_control_path_counts_every_commit(self, tmp_path):
        e = make_engine(tmp_path, "mc", "mesh", df_incremental=False)
        rng = np.random.default_rng(6)
        for i in range(4):
            e.ingest_text(f"d{i}.txt", rand_text(rng))
        e.commit()
        e.ingest_text("x.txt", rand_text(rng))
        e.commit()
        # rebuild resync + one control recompute PER commit
        assert e.index.df_full_recomputes >= 3
        mesh_stats_exact(e)
        # control and incremental engines agree end to end
        e2 = make_engine(tmp_path, "mi", "mesh")
        rng = np.random.default_rng(6)
        for i in range(4):
            e2.ingest_text(f"d{i}.txt", rand_text(rng))
        e2.commit()
        e2.ingest_text("x.txt", rand_text(rng))
        e2.commit()
        q = WORDS[2] + " " + WORDS[9]
        got = [(h.name, round(h.score, 5)) for h in e.search(q)]
        want = [(h.name, round(h.score, 5)) for h in e2.search(q)]
        assert got == want

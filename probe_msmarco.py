"""Config-4 at FULL scale: stream 8.8M MS-MARCO-shaped passages.

BASELINE.md config 4 names the 8.8M-passage corpus; bench.py streams 1M
(kept there for runtime). This probe runs the full count once and
records sustained docs/s + commit percentiles + device residency, so
the scale claim is measured, not extrapolated:

    python probe_msmarco.py          # ~25 min on the tunneled v5e

Passages are shorter than the north-star docs (avg ~55 terms — MS MARCO
passages average ~56 words), vocab 500k.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                      os.path.join(os.path.dirname(__file__), ".jax_cache"))

from bench import NS_VOCAB, make_doc_arrays, make_queries  # noqa: E402

N_DOCS = int(os.environ.get("PROBE_DOCS", 8_800_000))
AVG_LEN = 55
COMMIT_EVERY = 50_000
GEN_CHUNK = 1_000_000


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def main():
    from tfidf_tpu.engine import Engine
    from tfidf_tpu.utils.config import Config

    rng = np.random.default_rng(0)
    # query_batch 16: at 8.8M docs the padded score space is ~11M
    # columns, and depth-2 pipelining keeps up to THREE chunks in
    # flight (dispatch-then-drain = depth+1, see
    # searcher._run_pipelined); three [B, 11M] f32 score buffers at
    # B=64 overflow 16GB HBM alongside the resident postings (two
    # already tipped it over by 240MB) — B=16 leaves ~2GB slack
    engine = Engine(Config(
        index_mode="segments", query_batch=16,
        merge_upload_pace=float(os.environ.get("PROBE_PACE", "1.0"))))
    t0 = time.perf_counter()
    for i in range(NS_VOCAB):
        engine.vocab.add(f"t{i}")
    log(f"[vocab] {time.perf_counter()-t0:.0f}s")

    add = engine.index.add_document_arrays
    commit_ms = []          # (ms, merge_was_inflight)
    done = 0
    t_start = time.perf_counter()
    gen_s = 0.0
    while done < N_DOCS:
        n = min(GEN_CHUNK, N_DOCS - done)
        g0 = time.perf_counter()
        offsets, ids, tfs, lengths = make_doc_arrays(
            rng, n, NS_VOCAB, AVG_LEN)
        gen_s += time.perf_counter() - g0
        for i in range(n):
            lo, hi = offsets[i], offsets[i + 1]
            add(f"d{done + i}", ids[lo:hi], tfs[lo:hi],
                float(lengths[i]))
            if (done + i + 1) % COMMIT_EVERY == 0:
                inflight = engine.index._merge_future is not None
                c0 = time.perf_counter()
                engine.commit()
                commit_ms.append(((time.perf_counter() - c0) * 1e3,
                                  inflight))
        done += n
        log(f"[st] {done}/{N_DOCS} docs "
            f"({done/(time.perf_counter()-t_start-gen_s):.0f} docs/s "
            f"excl. corpus gen)")
    total_s = time.perf_counter() - t_start - gen_s
    engine.commit()
    q0 = time.perf_counter()
    for _ in range(32):
        engine.index.wait_for_merges()
        engine.commit()
        if len(engine.index._segments) <= engine.config.max_segments \
                and engine.index._merge_future is None:
            break
    quiesce_s = time.perf_counter() - q0
    # the FIRST commit pays one-time warmup (first big numpy pass +
    # first device transfers); report it separately so the steady-state
    # split isolates the merge-contention question
    first_ms = commit_ms[0][0] if commit_ms else 0.0
    steady = commit_ms[1:]
    cm = np.asarray([m for m, _f in steady] or [0.0])
    cm_merge = np.asarray([m for m, f in steady if f] or [0.0])
    cm_alone = np.asarray([m for m, f in steady if not f] or [0.0])
    # END-OF-RUN SEARCH GATE (ROADMAP item 7 hygiene): a run whose
    # full-scale search fails must FAIL — loudly, without touching the
    # committed artifact. MSMARCO_SCALE.json carried an unevidenced
    # `search_ok: false` from r5 to r13 precisely because this gate
    # used to record its own failure into the artifact and exit 0; an
    # artifact that silently documents a broken run is a bench bug
    # (bench.py --kernel applies the same assert-before-emit
    # discipline). The tunnel's remote-compile flake still gets one
    # retry; a second failure aborts the probe with a nonzero exit.
    queries = make_queries(rng, NS_VOCAB, 32)
    try:
        hits = engine.search_batch(queries, k=10)
    except Exception as e:
        if "compile" not in repr(e).lower():
            raise
        log(f"[st] search compile flake, retrying once: {e!r}")
        time.sleep(5.0)
        hits = engine.search_batch(queries, k=10)
    if not any(hits):
        sys.exit("[st] FULL-SCALE SEARCH GATE FAILED: no hits at "
                 f"{N_DOCS} docs — refusing to emit an artifact for a "
                 "run that cannot answer queries")
    search_ok = True
    from tfidf_tpu.utils.metrics import global_metrics
    snap = global_metrics.snapshot()
    out = {
        "n_docs": N_DOCS,
        "streaming_dps": round(done / total_s, 1),
        "commit_ms_p50": round(float(np.percentile(cm, 50)), 1),
        "commit_ms_p99": round(float(np.percentile(cm, 99)), 1),
        "commit_ms_max": round(float(cm.max()), 1),
        # the attribution split (VERDICT r3 #4): commits that overlapped
        # a background merge vs commits that ran alone — with paced
        # merge uploads both tails should be bounded
        "commit_first_warmup_ms": round(float(first_ms), 1),
        "commits_with_merge_inflight": int((np.asarray(
            [f for _m, f in steady])).sum()) if steady else 0,
        "commit_merge_inflight_ms_p99": round(float(
            np.percentile(cm_merge, 99)), 1),
        "commit_merge_inflight_ms_max": round(float(cm_merge.max()), 1),
        "commit_alone_ms_p99": round(float(
            np.percentile(cm_alone, 99)), 1),
        "commit_alone_ms_max": round(float(cm_alone.max()), 1),
        "merge_upload_pace": engine.config.merge_upload_pace,
        "merge_build_mean_ms": round(snap.get(
            "merge_build_mean_ms", 0.0), 1),
        "quiesce_s": round(quiesce_s, 1),
        "segments": len(engine.index.snapshot.segments),
        "nnz_live": int(engine.index.nnz_live),
        "search_ok": search_ok,
    }
    log(f"[done] {json.dumps(out)}")
    if N_DOCS >= 8_000_000:
        # only FULL runs update the committed artifact (bracketing runs
        # at smaller N_DOCS print their JSON for the caller to merge),
        # and the update PRESERVES context keys a human merged in
        # (multi-run history, attribution notes) rather than clobbering
        path = os.path.join(os.path.dirname(__file__),
                            "MSMARCO_SCALE.json")
        prior: dict = {}
        try:
            with open(path) as f:
                prior = json.load(f)
        except Exception:
            prior = {}
        out.update({k: v for k, v in prior.items() if k not in out})
        with open(path, "w") as f:
            json.dump(out, f, indent=1)
    print(json.dumps(out))


if __name__ == "__main__":
    main()

"""Search pipeline depth on small corpora (VERDICT r3 weak #3).

At 18k docs the device step is a few ms while the device->host fetch
RTT over the tunnel is tens of ms, so one-deep pipelining caps
throughput near one chunk per RTT. This probe measures QPS vs
``search_pipeline_depth`` at the config-1 shape to pick the default
and document the small-corpus story.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                      os.path.join(os.path.dirname(__file__), ".jax_cache"))

from bench import (C1_AVG_LEN, C1_DOCS, C1_VOCAB, TOP_K,  # noqa: E402
                   make_doc_arrays, make_queries)

BATCH = 1024
BATCHES = 8


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def main():
    from tfidf_tpu.engine import Engine
    from tfidf_tpu.utils.config import Config

    rng = np.random.default_rng(0)
    offsets, ids, tfs, lengths = make_doc_arrays(rng, C1_DOCS, C1_VOCAB,
                                                 C1_AVG_LEN)
    queries = make_queries(rng, C1_VOCAB, BATCH * (BATCHES + 2))
    out = {}
    for depth in (1, 2, 3, 4, 6):
        engine = Engine(Config(query_batch=BATCH,
                               search_pipeline_depth=depth))
        for i in range(C1_VOCAB):
            engine.vocab.add(f"t{i}")
        add = engine.index.add_document_arrays
        for i in range(C1_DOCS):
            lo, hi = offsets[i], offsets[i + 1]
            add(f"d{i}", ids[lo:hi], tfs[lo:hi], float(lengths[i]))
        engine.commit()
        engine.search_batch(queries[:BATCH], k=TOP_K)
        engine.search_batch(queries[BATCH:2 * BATCH], k=TOP_K)
        timed = queries[2 * BATCH:(BATCHES + 2) * BATCH]
        best = 0.0
        for _ in range(3):
            t0 = time.perf_counter()
            engine.search_batch(timed, k=TOP_K)
            best = max(best, len(timed) / (time.perf_counter() - t0))
        log(f"[pipe] depth={depth}: {best:.0f} q/s (best of 3)")
        out[str(depth)] = round(best, 1)
        del engine
    print(json.dumps(out))


if __name__ == "__main__":
    main()

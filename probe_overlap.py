"""Can device->host fetches overlap device compute on this runtime?

VERDICT r5 Weak #3: PERF.md attributed the distributed serving wall to
"fetches from concurrent scatter batches do not overlap", but the claim
was asserted, not isolated. This probe settles it either way with two
experiments, and commits the artifact (``PROBE_OVERLAP.json``):

1. **Device experiment** — two INDEPENDENTLY FETCHABLE device programs
   (disjoint inputs, disjoint outputs). Measured three ways, medians
   over ``iters``:

   * ``serial``: dispatch A, fetch A, dispatch B, fetch B — the shape
     the pre-round-6 worker data plane produced under concurrent
     scatter RPCs (each handler drained its own fetch before the next
     dispatch ran);
   * ``double_buffered``: dispatch A, dispatch B, fetch A, fetch B —
     program B computes while A's result crosses the link;
   * ``threaded``: two threads each dispatch+fetch their own program —
     can the runtime overlap two in-flight transfers at all?

   ``overlap_ratio = serial / overlapped``: ~2.0 means fetch fully
   hides under compute (the wall was software — the round-6 pipeline
   executor recovers the loss); ~1.0 means the runtime serializes the
   transfers (the wall is the tunnel) — either answer converts the
   PERF.md assertion into evidence.

2. **Executor experiment** — the actual ``PipelineExecutor`` over a
   fake 2-stage workload with known costs (dispatch = compute_s,
   fetch = rtt_s, both pure sleeps, no device needed): steady-state
   pipelined time should approach ``max(compute, rtt)`` per chunk vs
   ``compute + rtt`` serial. Also asserts, deterministically (an event
   handshake, no timing), that a fetch really was in flight while a
   later chunk dispatched. This half runs in tier-1 on CPU
   (``tests/test_pipeline.py``) so the overlap machinery is exercised
   on every push.

Run ``make probe-overlap`` (or ``python probe_overlap.py``). NOTE: the
committed artifact records whatever backend the run found — on a
CPU-only host the device experiment measures shared-memory "transfers"
(near-free, ratios ~1.0 by construction); the verdict about the TPU
tunnel requires running this against the tunnel and committing that
artifact.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                      os.path.join(os.path.dirname(__file__), ".jax_cache"))

ARTIFACT = os.path.join(os.path.dirname(__file__), "PROBE_OVERLAP.json")


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


# --------------------------------------------------------------------------
# experiment 2: the executor itself, fake workload (tier-1-safe)
# --------------------------------------------------------------------------

def executor_workload(n_chunks: int = 8, compute_s: float = 0.015,
                      rtt_s: float = 0.015, depth: int = 2) -> dict:
    """Drive :class:`PipelineExecutor` with a synthetic 2-program-shaped
    workload: dispatch costs ``compute_s`` (serialized, like a device
    queue), fetch costs ``rtt_s`` (the d2h link). Returns timings for a
    serial loop vs the pipelined executor, plus a DETERMINISTIC overlap
    witness: chunk 0's fetch blocks until chunk 1's dispatch has
    started, which can only complete if dispatch and fetch genuinely
    run concurrently (a serialized pipeline deadlocks into the timeout
    and fails the handshake)."""
    from tfidf_tpu.engine.pipeline import PipelineExecutor

    def make_stages(record):
        def dispatch(i):
            time.sleep(compute_s)
            record.append(("d", i))
            return (i,)

        def fetch(i):
            time.sleep(rtt_s)
            record.append(("f", i))
            return i * i

        return dispatch, fetch

    # serial baseline: the pre-round-6 shape (drain before next dispatch)
    rec_serial: list = []
    dispatch, fetch = make_stages(rec_serial)
    t0 = time.perf_counter()
    serial_out = [fetch(*dispatch(i)) for i in range(n_chunks)]
    serial_s = time.perf_counter() - t0

    # pipelined through the executor
    rec_pipe: list = []
    dispatch, fetch = make_stages(rec_pipe)
    ex = PipelineExecutor(depth=depth, name="probe")
    t0 = time.perf_counter()
    futures = [ex.submit(lambda i=i: dispatch(i), fetch)
               for i in range(n_chunks)]
    pipe_out = [f.result() for f in futures]
    pipelined_s = time.perf_counter() - t0

    # deterministic overlap witness (event handshake, no timing)
    started_d1 = threading.Event()
    witnessed = threading.Event()

    def d(i):
        if i == 1:
            started_d1.set()
        return (i,)

    def f(i):
        if i == 0 and started_d1.wait(timeout=5.0):
            witnessed.set()
        return i

    ws = [ex.submit(lambda i=i: d(i), f) for i in range(2)]
    for w in ws:
        w.result()
    ex.stop()

    return {
        "n_chunks": n_chunks,
        "compute_ms": compute_s * 1e3, "rtt_ms": rtt_s * 1e3,
        "depth": depth,
        "serial_s": round(serial_s, 4),
        "pipelined_s": round(pipelined_s, 4),
        "speedup": round(serial_s / pipelined_s, 3),
        "ideal_speedup": round((compute_s + rtt_s)
                               / max(compute_s, rtt_s), 3),
        "results_ok": serial_out == pipe_out
        == [i * i for i in range(n_chunks)],
        "fetch_order_fifo": [i for s, i in rec_pipe if s == "f"]
        == list(range(n_chunks)),
        "overlap_witnessed": witnessed.is_set(),
    }


# --------------------------------------------------------------------------
# experiment 1: two independently fetchable device programs
# --------------------------------------------------------------------------

def device_overlap(n: int = 2048, iters: int = 10) -> dict:
    """Two disjoint jitted programs; measure serial vs double-buffered
    vs threaded dispatch+fetch (medians)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    @jax.jit
    def prog(x):
        return x @ x          # [n, n] result: the fetch moves n*n*4 bytes

    key = jax.random.PRNGKey(0)
    x1 = jax.random.normal(key, (n, n), jnp.float32)
    x2 = jax.random.normal(jax.random.PRNGKey(1), (n, n), jnp.float32)
    # warm compiles + one fetch each
    np.asarray(prog(x1)).sum()
    np.asarray(prog(x2)).sum()

    def median(run):
        ts = []
        for _ in range(iters):
            t0 = time.perf_counter()
            run()
            ts.append(time.perf_counter() - t0)
        ts.sort()
        return ts[len(ts) // 2]

    t_compute = median(lambda: (prog(x1).block_until_ready(),
                                prog(x2).block_until_ready()))

    def serial():
        np.asarray(prog(x1))
        np.asarray(prog(x2))

    def double_buffered():
        r1 = prog(x1)
        r2 = prog(x2)
        np.asarray(r1)
        np.asarray(r2)

    def threaded():
        outs = [None, None]

        def one(i, x):
            outs[i] = np.asarray(prog(x))

        ts = [threading.Thread(target=one, args=(i, x))
              for i, x in enumerate((x1, x2))]
        for t in ts:
            t.start()
        for t in ts:
            t.join()

    t_serial = median(serial)
    t_double = median(double_buffered)
    t_threaded = median(threaded)
    dev = jax.devices()[0]
    return {
        "backend": dev.platform, "device": str(dev),
        "n": n, "iters": iters,
        "compute_only_ms": round(t_compute * 1e3, 2),
        "serial_ms": round(t_serial * 1e3, 2),
        "double_buffered_ms": round(t_double * 1e3, 2),
        "threaded_ms": round(t_threaded * 1e3, 2),
        "overlap_ratio_double_buffered": round(t_serial / t_double, 3),
        "overlap_ratio_threaded": round(t_serial / t_threaded, 3),
    }


def main() -> None:
    log("[overlap] executor experiment (fake workload)...")
    executor_workload(n_chunks=2)   # warm thread startup out of the timing
    exec_res = executor_workload(n_chunks=12)
    log(f"[overlap] executor: serial {exec_res['serial_s']}s vs "
        f"pipelined {exec_res['pipelined_s']}s "
        f"(speedup {exec_res['speedup']}x of ideal "
        f"{exec_res['ideal_speedup']}x), overlap_witnessed="
        f"{exec_res['overlap_witnessed']}")
    log("[overlap] device experiment (two independent programs)...")
    dev_res = device_overlap()
    log(f"[overlap] device [{dev_res['backend']}]: serial "
        f"{dev_res['serial_ms']}ms, double-buffered "
        f"{dev_res['double_buffered_ms']}ms (ratio "
        f"{dev_res['overlap_ratio_double_buffered']}), threaded "
        f"{dev_res['threaded_ms']}ms (ratio "
        f"{dev_res['overlap_ratio_threaded']})")
    ratio = max(dev_res["overlap_ratio_double_buffered"],
                dev_res["overlap_ratio_threaded"])
    if dev_res["backend"] != "tpu":
        conclusion = (
            "methodology + CPU control run: transfers on this backend "
            "are shared-memory (near-free), so ratios ~1.0 are expected "
            "and say nothing about the tunnel — run on the TPU tunnel "
            "for the serving-path verdict")
    elif ratio >= 1.3:
        conclusion = ("fetches OVERLAP compute on this runtime: the r5 "
                      "wall was software; the pipeline executor "
                      "recovers it")
    else:
        conclusion = ("fetches SERIALIZE on this runtime: the wall is "
                      "the tunnel, qps ceiling ~= batch/fetch_RTT")
    result = {"experiment": "scatter-batch fetch/compute overlap",
              "device": dev_res, "executor": exec_res,
              "conclusion": conclusion}
    with open(ARTIFACT, "w", encoding="utf-8") as f:
        json.dump(result, f, indent=1)
        f.write("\n")
        f.flush()
        os.fsync(f.fileno())
    log(f"[overlap] artifact written: {ARTIFACT}")
    print(json.dumps({"overlap_ratio": ratio,
                      "backend": dev_res["backend"],
                      "executor_speedup": exec_res["speedup"],
                      "overlap_witnessed":
                      exec_res["overlap_witnessed"]}))


if __name__ == "__main__":
    main()

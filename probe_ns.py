"""North-star steady-state probe: 1M docs / 500k vocab on the chip.

Measures engine.search_batch q/s at several batch sizes using DISTINCT
query sets per timed batch (the serving pattern), after the u-floor
warmup. The ≥50x target needs ~1970 q/s against torch-CSR's 39.4.
"""

from __future__ import annotations

import os
import sys
import time

import numpy as np

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                      os.path.join(os.path.dirname(__file__), ".jax_cache"))
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "0")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0")

from bench import (NS_AVG_LEN, NS_DOCS, NS_VOCAB, make_doc_arrays,  # noqa: E402
                   make_queries)

N_DOCS = int(os.environ.get("PROBE_DOCS", NS_DOCS))


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def main():
    from tfidf_tpu.engine import Engine
    from tfidf_tpu.utils.config import Config

    rng = np.random.default_rng(0)
    t0 = time.perf_counter()
    offsets, ids, tfs, lengths = make_doc_arrays(
        rng, N_DOCS, NS_VOCAB, NS_AVG_LEN)
    log(f"[gen] {N_DOCS} docs nnz={ids.shape[0]} "
        f"{time.perf_counter()-t0:.0f}s")

    engine = Engine(Config(query_batch=4096))
    for i in range(NS_VOCAB):
        engine.vocab.add(f"t{i}")
    add = engine.index.add_document_arrays
    t0 = time.perf_counter()
    for i in range(N_DOCS):
        lo, hi = offsets[i], offsets[i + 1]
        add(f"d{i}", ids[lo:hi], tfs[lo:hi], float(lengths[i]))
    log(f"[ingest] {time.perf_counter()-t0:.0f}s")
    t0 = time.perf_counter()
    engine.commit()
    log(f"[commit] {time.perf_counter()-t0:.0f}s")
    snap = engine.index.snapshot
    log(f"[ell] blocks={[i.shape for i in snap.ell_impacts]}")

    queries = make_queries(rng, NS_VOCAB, 6 * 4096)

    if os.environ.get("PROBE_PIECES"):
        import functools
        import jax
        from tfidf_tpu.engine.searcher import vectorize_queries
        from tfidf_tpu.ops.ell import score_ell_with_residual
        from tfidf_tpu.ops.topk import packed_topk, unpack_topk

        kw = engine.model.score_kwargs()
        B = int(os.environ.get("PROBE_B", 512))
        qb, _ = vectorize_queries(
            queries[:B], engine.analyzer, engine.vocab, engine.model,
            batch_cap=B, max_terms=32)
        log(f"[pieces] B={B} uniq={int(qb.n_uniq)} "
            f"u_cap={qb.uniq.shape[0]}")
        fn = jax.jit(functools.partial(
            score_ell_with_residual, use_pallas=True, **kw))

        def scores_only():
            s = fn(snap.ell_impacts, snap.ell_terms, snap.ell_live,
                   snap.res_tf, snap.res_term, snap.res_doc,
                   snap.doc_len, snap.df, qb, snap.n_docs, snap.avgdl,
                   snap.doc_norms)
            np.asarray(s[:1, :8])
            return s

        def timeit(f, n=3):
            f()
            t0 = time.perf_counter()
            for _ in range(n):
                f()
            return (time.perf_counter() - t0) / n

        dt = timeit(scores_only)
        log(f"[pieces] scores+fetch8: {dt*1e3:.0f}ms")
        s = scores_only()

        def topk_and_fetch():
            unpack_topk(packed_topk(s, snap.num_docs, k=10))
        dt = timeit(topk_and_fetch)
        log(f"[pieces] topk+packed fetch: {dt*1e3:.0f}ms")

        def fetch8():
            np.asarray(s[:1, :8])
        dt = timeit(fetch8)
        log(f"[pieces] bare fetch of 8 floats: {dt*1e3:.0f}ms")
        return

    for B in (512, 1024):
        # warmup: 2 distinct batches (ratchets u_floor, compiles once)
        engine.searcher.query_batch = B
        engine.search_batch(queries[:B], k=10)
        engine.search_batch(queries[B:2 * B], k=10)
        # one call over 4 chunks: the searcher pipelines internally
        t0 = time.perf_counter()
        engine.search_batch(queries[2 * B:6 * B], k=10)
        dt = time.perf_counter() - t0
        log(f"[B={B}] {4*B} q in {dt:.2f}s -> {4*B/dt:.0f} q/s "
            f"pipelined ({dt/4*1e3:.0f} ms/chunk, u_floor="
            f"{engine.searcher._u_floor})")


if __name__ == "__main__":
    main()

# tfidf_tpu node image — the single-binary deployment surface
# (the analog of the reference's fat-jar image,
# TF-IDF-System-Core/Dockerfile:1-9: one image, every node runs it,
# role decided at runtime by leader election).
#
# For TPU nodes, build FROM a JAX TPU base instead (e.g. a
# python:3.11 image + `pip install 'jax[tpu]'`) and schedule onto
# TPU node pools; the CPU base below runs the full system (engine,
# cluster, coordination) on any k8s cluster.

FROM python:3.11-slim

# native toolchain for the C++ ingest fast path (tfidf_tpu/native);
# the engine falls back to pure Python when no compiler is present,
# so this layer is an optimization, not a requirement
RUN apt-get update \
    && apt-get install -y --no-install-recommends g++ \
    && rm -rf /var/lib/apt/lists/*

WORKDIR /app

COPY pyproject.toml README.md ./
COPY tfidf_tpu ./tfidf_tpu
RUN pip install --no-cache-dir "jax[cpu]" numpy && \
    pip install --no-cache-dir --no-deps .

# documents + index live on volumes (reference: /app/documents,
# /app/lucene-index — README.MD:93-107)
ENV TFIDF_DOCUMENTS_PATH=/app/documents \
    TFIDF_INDEX_PATH=/app/index \
    TFIDF_PORT=8085
VOLUME ["/app/documents", "/app/index"]

EXPOSE 8085

ENTRYPOINT ["python", "-m", "tfidf_tpu"]
CMD ["serve"]

"""Registry-drift enforcement: the single pass that keeps every
declared surface honest against the source.

Three sub-checks (generalizing PR 1's one-off anti-stale test):

1. **fault points** — every ``fault_point("…")`` /
   ``global_injector.check("…")`` call site must be covered by
   ``KNOWN_FAULT_POINTS`` (f-string sites by their static prefix +
   ``*``), AND every registry entry must match at least one call site
   (a removed point must leave the registry too).
2. **config** — every ``Config`` dataclass field must be mentioned in
   the README (the operator-facing contract), and ``load_config`` must
   still carry the generic ``TFIDF_<UPPER>`` env-override loop so every
   field stays overridable without per-field plumbing.
3. **metrics** — every metric name the code READS
   (``global_metrics.get("…")``, the CLI's snapshot lookups) must be
   EMITTED somewhere (``inc``/``observe``/``set_gauge``; f-string
   emissions match by pattern; ``observe`` names also cover their
   snapshot-derived ``_count``/``_mean_ms``/``_p50_ms``/… suffixes).
4. **fault-trace coupling** — ``FaultInjector.check`` must emit a
   trace span event on every FIRE (``span_event("fault_injected", …)``
   in utils/faults.py): because every ``fault_point()``/``check()``
   site routes through that one method, fault injection is visible in
   traces by construction — and this check fails if the emission is
   ever refactored away.

Everything is read via AST — ``KNOWN_FAULT_POINTS`` and the Config
fields are parsed out of their literals, never imported.
"""

from __future__ import annotations

import ast
import os
import re

from tools.graftcheck.core import Finding, SourceTree, _dotted

_TIMING_SUFFIXES = ("_count", "_mean_ms", "_min_ms", "_max_ms", "_sum_ms",
                    "_p50_ms", "_p95_ms", "_p99_ms")


# ---------------------------------------------------------------------------
# shared literal / f-string extraction
# ---------------------------------------------------------------------------

def _str_or_prefix(node: ast.expr) -> tuple[str, bool] | None:
    """(text, is_prefix) for a string literal or an f-string whose
    leading part is literal; None otherwise."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value, False
    if isinstance(node, ast.JoinedStr):
        if node.values and isinstance(node.values[0], ast.Constant) \
                and isinstance(node.values[0].value, str):
            return node.values[0].value, True
        return "", True
    return None


# ---------------------------------------------------------------------------
# 1. fault points
# ---------------------------------------------------------------------------

def _known_fault_points(tree: SourceTree) -> dict[str, int]:
    """Parse KNOWN_FAULT_POINTS keys (and the dict's line) from
    utils/faults.py without importing it."""
    mi = tree.modules["utils.faults"]
    for node in mi.tree.body:
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AnnAssign):
            targets = [node.target]
        for t in targets:
            if isinstance(t, ast.Name) and t.id == "KNOWN_FAULT_POINTS" \
                    and isinstance(node.value, ast.Dict):
                return {k.value: k.lineno for k in node.value.keys
                        if isinstance(k, ast.Constant)}
    return {}


def _fault_sites(tree: SourceTree) -> dict[str, tuple[str, int]]:
    """point (literal, or prefix + '*') -> one (file, line) site."""
    out: dict[str, tuple[str, int]] = {}
    for mi in tree.modules.values():
        for node in ast.walk(mi.tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            d = _dotted(node.func) or ""
            leaf = d.split(".")[-1]
            # `_observe` is CircuitBreaker's swallow-the-raise forwarder
            # to global_injector.check — its literal-arg call sites are
            # fault points too (the old grep-based test missed them).
            # `device_guard` is the compute-plane injector's dispatch
            # seam (utils/device_nemesis.py): its sites register under
            # the `device.` namespace — one registry covers both
            # injectors, so chaos configs validate device rules too.
            if not (leaf in ("fault_point", "_observe", "device_guard")
                    or (leaf == "check"
                        and "injector" in d.split(".")[0])):
                continue
            got = _str_or_prefix(node.args[0])
            if got is None:
                continue
            text, is_prefix = got
            point = text.split("{")[0] + "*" if is_prefix else text
            if leaf == "device_guard":
                point = "device." + point
            out.setdefault(point, (mi.relpath, node.lineno))
    return out


def _covered(point: str, registry: dict[str, int]) -> bool:
    if point in registry:
        return True
    return any(k.endswith("*") and point.rstrip("*").startswith(k[:-1])
               for k in registry)


def check_fault_points(tree: SourceTree) -> list[Finding]:
    registry = _known_fault_points(tree)
    sites = _fault_sites(tree)
    out: list[Finding] = []
    if not registry or not sites:
        out.append(Finding(
            "registry_drift", "registry_drift:faults:extraction-empty",
            "fault-point extraction found nothing — the pass went stale",
            "tfidf_tpu/utils/faults.py", 1))
        return out
    for point, (f, ln) in sorted(sites.items()):
        if not _covered(point, registry):
            out.append(Finding(
                "registry_drift",
                f"registry_drift:faults:unregistered:{point}",
                f"fault point {point!r} is not in KNOWN_FAULT_POINTS "
                f"(chaos configs validate against the registry)", f, ln))
    for point, ln in sorted(registry.items()):
        key = point.rstrip("*")
        hit = any(site == point
                  or (point.endswith("*")
                      and site.rstrip("*").startswith(key))
                  for site in sites)
        if not hit:
            out.append(Finding(
                "registry_drift",
                f"registry_drift:faults:stale:{point}",
                f"KNOWN_FAULT_POINTS entry {point!r} matches no "
                f"fault_point()/check() call site — stale registry entry",
                "tfidf_tpu/utils/faults.py", ln))
    return out


# ---------------------------------------------------------------------------
# 2. config fields
# ---------------------------------------------------------------------------

def _config_fields(tree: SourceTree) -> dict[str, int]:
    ci = tree.modules["utils.config"].classes.get("Config")
    if ci is None:
        return {}
    return {n.target.id: n.lineno for n in ci.node.body
            if isinstance(n, ast.AnnAssign)
            and isinstance(n.target, ast.Name)}


def check_config(tree: SourceTree, root: str) -> list[Finding]:
    out: list[Finding] = []
    fields = _config_fields(tree)
    if not fields:
        out.append(Finding(
            "registry_drift", "registry_drift:config:extraction-empty",
            "no Config fields found — the pass went stale",
            "tfidf_tpu/utils/config.py", 1))
        return out
    readme_path = os.path.join(root, "README.md")
    readme = ""
    if os.path.exists(readme_path):
        with open(readme_path, encoding="utf-8") as f:
            readme = f.read()
    for name, ln in sorted(fields.items()):
        if not re.search(rf"\b{re.escape(name)}\b", readme):
            out.append(Finding(
                "registry_drift",
                f"registry_drift:config:readme-missing:{name}",
                f"Config field {name!r} has no README mention (every "
                f"field is operator-facing via TFIDF_{name.upper()})",
                "tfidf_tpu/utils/config.py", ln))
    # the generic env-override loop must survive refactors: without it,
    # fields silently stop being TFIDF_* overridable
    cfg_src = tree.modules["utils.config"].source
    if "_ENV_PREFIX + f_.name.upper()" not in cfg_src:
        out.append(Finding(
            "registry_drift", "registry_drift:config:env-loop-missing",
            "load_config no longer derives TFIDF_* overrides "
            "generically from dataclasses.fields(Config) — per-field "
            "env plumbing drifts; restore the generic loop",
            "tfidf_tpu/utils/config.py", 1))
    return out


# ---------------------------------------------------------------------------
# 3. metrics
# ---------------------------------------------------------------------------

_EMIT_METHODS = {"inc", "observe", "set_gauge"}


def _metric_emissions(tree: SourceTree
                      ) -> tuple[set[str], list[str], set[str]]:
    """(literal names, prefix patterns from f-strings, observe names)."""
    literals: set[str] = set()
    prefixes: list[str] = []
    observed: set[str] = set()
    for mi in tree.modules.values():
        # local aliases: g = global_metrics.set_gauge; g("name", …)
        aliases: set[str] = set()
        for node in ast.walk(mi.tree):
            if isinstance(node, ast.Assign) \
                    and isinstance(node.value, ast.Attribute) \
                    and node.value.attr in _EMIT_METHODS:
                d = _dotted(node.value.value) or ""
                if "metrics" in d:
                    aliases.update(t.id for t in node.targets
                                   if isinstance(t, ast.Name))
        for node in ast.walk(mi.tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            is_emit = False
            method = ""
            if isinstance(node.func, ast.Attribute) \
                    and node.func.attr in _EMIT_METHODS:
                d = _dotted(node.func.value) or ""
                if "metrics" in d or d == "self":
                    is_emit = True
                    method = node.func.attr
            elif isinstance(node.func, ast.Name) \
                    and node.func.id in aliases:
                is_emit = True
            if not is_emit:
                continue
            got = _str_or_prefix(node.args[0])
            if got is None:
                continue
            text, is_prefix = got
            if is_prefix:
                prefixes.append(text)
            else:
                literals.add(text)
                if method == "observe":
                    observed.add(text)
    return literals, prefixes, observed


def _metric_reads(tree: SourceTree) -> dict[str, tuple[str, int]]:
    out: dict[str, tuple[str, int]] = {}
    for mi in tree.modules.values():
        for node in ast.walk(mi.tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            if not (isinstance(node.func, ast.Attribute)
                    and node.func.attr == "get"):
                continue
            d = _dotted(node.func.value) or ""
            # global_metrics.get(...) anywhere; `metrics.get(...)` on
            # the CLI's fetched /api/metrics snapshot
            if not (d == "global_metrics"
                    or (d == "metrics" and mi.name == "cli")):
                continue
            got = _str_or_prefix(node.args[0])
            if got is None or got[1]:
                continue
            out.setdefault(got[0], (mi.relpath, node.lineno))
    return out


def check_metrics(tree: SourceTree) -> list[Finding]:
    literals, prefixes, observed = _metric_emissions(tree)
    reads = _metric_reads(tree)
    out: list[Finding] = []
    if not literals:
        out.append(Finding(
            "registry_drift", "registry_drift:metrics:extraction-empty",
            "metric-emission extraction found nothing — pass went stale",
            "tfidf_tpu/utils/metrics.py", 1))
        return out

    def emitted(name: str) -> bool:
        if name in literals:
            return True
        # snapshot-derived timing keys come from observe() names; an
        # f-string emission covers anything sharing its literal prefix
        for suf in _TIMING_SUFFIXES:
            if name.endswith(suf) and name[: -len(suf)] in (
                    literals | observed):
                return True
        return any(p and name.startswith(p) for p in prefixes)

    for name, (f, ln) in sorted(reads.items()):
        if not emitted(name):
            out.append(Finding(
                "registry_drift",
                f"registry_drift:metrics:never-emitted:{name}",
                f"metric {name!r} is read but never emitted by any "
                f"inc/observe/set_gauge in the tree", f, ln))
    return out


# ---------------------------------------------------------------------------
# 4. fault-point -> trace-event coupling
# ---------------------------------------------------------------------------

def check_fault_trace(tree: SourceTree) -> list[Finding]:
    """Every fault FIRE must land a span event. All fault_point()/
    check() call sites route through ``FaultInjector.check`` (the
    fault-points sub-check above keeps that registry honest), so one
    structural guarantee suffices: the check method's fire path must
    call ``span_event("fault_injected", …)``. A chaos run's trace then
    shows exactly where each injected failure entered the request —
    by construction, for every present and future fault point."""
    mi = tree.modules["utils.faults"]
    fn = next(
        (n for cls in ast.walk(mi.tree)
         if isinstance(cls, ast.ClassDef) and cls.name == "FaultInjector"
         for n in cls.body
         if isinstance(n, ast.FunctionDef) and n.name == "check"), None)
    if fn is None:
        return [Finding(
            "registry_drift", "registry_drift:faults:no-check-method",
            "FaultInjector.check not found — the fault-trace pass went "
            "stale", "tfidf_tpu/utils/faults.py", 1)]
    for node in ast.walk(fn):
        if (isinstance(node, ast.Call) and node.args
                and (_dotted(node.func) or "").split(".")[-1]
                == "span_event"
                and isinstance(node.args[0], ast.Constant)
                and node.args[0].value == "fault_injected"):
            return []
    return [Finding(
        "registry_drift", "registry_drift:faults:fire-not-traced",
        "FaultInjector.check no longer emits the 'fault_injected' span "
        "event on fire — fault injection must stay visible in traces "
        "by construction (every fault_point() site routes through "
        "this method)", mi.relpath, fn.lineno)]


def analyze(tree: SourceTree, root: str) -> list[Finding]:
    return (check_fault_points(tree) + check_config(tree, root)
            + check_metrics(tree) + check_fault_trace(tree))

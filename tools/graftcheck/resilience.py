"""Resilience-coverage analysis: no naked leader→worker RPCs.

Every leader→worker RPC must flow through
``ClusterResilience.worker_call`` (breaker + bounded retry) — a new raw
``urlopen`` / ``http_post`` / ``http_get`` / ``_ScatterClient.post`` /
``_post_json`` call in ``cluster/`` that is NOT wrapped is a finding.

A raw transport call counts as wrapped when it sits lexically inside a
closure handed to ``worker_call``: a ``lambda`` argument of a
``worker_call(...)`` call, or a nested ``def`` whose name appears as a
``worker_call`` argument in the same enclosing function. Subsystems
with their own failure discipline (the coordination client's
connect-string failover, Raft replication's term-checked resend loop,
heartbeats) are pinned in ``allowlist.json`` with reasons — new call
sites in them still surface here first.
"""

from __future__ import annotations

import ast

from tools.graftcheck.core import Finding, SourceTree, _dotted

_RAW_TRANSPORTS = {"urlopen", "http_post", "http_get", "_post_json"}
_RAW_METHODS = {"post"}         # self._scatter.post
_WRAPPER = "worker_call"


def _transport_call(node: ast.Call) -> str | None:
    d = _dotted(node.func)
    if d is None:
        return None
    leaf = d.split(".")[-1]
    if leaf in _RAW_TRANSPORTS:
        return leaf
    if leaf in _RAW_METHODS and "_scatter" in d:
        return d
    return None


def _wrapped_names(func: ast.AST) -> set[str]:
    """Names of nested defs passed to worker_call within ``func``."""
    out: set[str] = set()
    for node in ast.walk(func):
        if isinstance(node, ast.Call):
            d = _dotted(node.func) or ""
            if d.split(".")[-1] == _WRAPPER:
                for a in node.args:
                    if isinstance(a, ast.Name):
                        out.add(a.id)
    return out


def _lambda_wrapped(module: ast.Module) -> set[ast.AST]:
    """All nodes inside lambdas that are worker_call arguments."""
    covered: set[ast.AST] = set()
    for node in ast.walk(module):
        if isinstance(node, ast.Call):
            d = _dotted(node.func) or ""
            if d.split(".")[-1] == _WRAPPER:
                for a in node.args:
                    if isinstance(a, ast.Lambda):
                        covered.update(ast.walk(a))
    return covered


def analyze(tree: SourceTree) -> list[Finding]:
    out: list[Finding] = []
    for mi in tree.modules.values():
        if not mi.name.startswith("cluster."):
            continue
        lambda_cov = _lambda_wrapped(mi.tree)
        # map: every FunctionDef node -> its enclosing chain of defs
        chains: dict[ast.AST, list[ast.FunctionDef]] = {}

        def index(node: ast.AST, chain: list[ast.FunctionDef]) -> None:
            if isinstance(node, ast.FunctionDef):
                chain = chain + [node]
            for child in ast.iter_child_nodes(node):
                chains[child] = chain
                index(child, chain)

        chains[mi.tree] = []
        index(mi.tree, [])
        for node in ast.walk(mi.tree):
            if not isinstance(node, ast.Call):
                continue
            transport = _transport_call(node)
            if transport is None:
                continue
            if node in lambda_cov:
                continue
            chain = chains.get(node, [])
            covered = False
            qual_parts = [f.name for f in chain]
            if chain:
                inner = chain[-1]
                for encl in chain[:-1]:
                    if inner.name in _wrapped_names(encl):
                        covered = True
                        break
            if covered:
                continue
            qual = f"{mi.name}." + ".".join(qual_parts or ["<module>"])
            out.append(Finding(
                "resilience",
                f"resilience:unwrapped:{qual}:{transport}",
                f"raw transport call {transport!r} in {qual} does not "
                f"flow through ClusterResilience.worker_call "
                f"(no breaker, no bounded retry)",
                mi.relpath, node.lineno))
    return out

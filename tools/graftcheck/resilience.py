"""Resilience-coverage analysis: no naked leader→worker RPCs.

Every leader→worker RPC must flow through
``ClusterResilience.worker_call`` (breaker + bounded retry) — a new raw
``urlopen`` / ``http_post`` / ``http_get`` / ``_ScatterClient.post`` /
``_post_json`` call in ``cluster/`` that is NOT wrapped is a finding.

A raw transport call counts as wrapped when it sits lexically inside a
closure handed to ``worker_call``: a ``lambda`` argument of a
``worker_call(...)`` call, or a nested ``def`` whose name appears as a
``worker_call`` argument (positional or keyword, directly or invoked
inside a ``worker_call`` lambda) in the same enclosing function.

The replication spine adds one indirection: ``_gather_merge(queries,
rpc_one, ...)`` receives the per-worker RPC closure and forwards it
into ``worker_call`` itself. The pass derives such **closure-forwarding
wrappers** structurally — a function is a wrapper when one of its own
PARAMETERS is invoked inside a ``worker_call`` closure — and then
treats closures passed to a known wrapper as wrapped too. A
replica-failover RPC that bypasses both (a naked transport call in a
closure nobody forwards to ``worker_call``) is still a finding.

Subsystems with their own failure discipline (the coordination client's
connect-string failover, Raft replication's term-checked resend loop,
heartbeats) are pinned in ``allowlist.json`` with reasons — new call
sites in them still surface here first.
"""

from __future__ import annotations

import ast

from tools.graftcheck.core import Finding, SourceTree, _dotted

_RAW_TRANSPORTS = {"urlopen", "http_post", "http_get", "http_get_stream",
                   "_post_json"}
_RAW_METHODS = {"post"}         # self._scatter.post
_WRAPPER = "worker_call"


def _transport_call(node: ast.Call) -> str | None:
    d = _dotted(node.func)
    if d is None:
        return None
    leaf = d.split(".")[-1]
    if leaf in _RAW_TRANSPORTS:
        return leaf
    if leaf in _RAW_METHODS and "_scatter" in d:
        return d
    return None


def _call_args(node: ast.Call):
    """Positional + keyword argument value nodes."""
    return list(node.args) + [kw.value for kw in node.keywords]


def _forwarding_wrappers(tree: SourceTree) -> set[str]:
    """Leaf names of functions that forward one of their own PARAMETERS
    into ``worker_call`` (directly, or invoked inside a ``worker_call``
    lambda) — e.g. ``_gather_merge(self, queries, rpc_one, ...)`` with
    ``worker_call(addr, lambda: rpc_one(...))`` in its body. Closures
    handed to these are breaker-gated by construction."""
    out: set[str] = set()
    for mi in tree.modules.values():
        if not mi.name.startswith("cluster."):
            continue
        for node in ast.walk(mi.tree):
            if not isinstance(node, ast.FunctionDef):
                continue
            params = {a.arg for a in node.args.args
                      + node.args.kwonlyargs}
            for call in ast.walk(node):
                if not isinstance(call, ast.Call):
                    continue
                d = _dotted(call.func) or ""
                if d.split(".")[-1] != _WRAPPER:
                    continue
                for a in _call_args(call):
                    if isinstance(a, ast.Name) and a.id in params:
                        out.add(node.name)
                    elif isinstance(a, ast.Lambda):
                        for c in ast.walk(a):
                            if isinstance(c, ast.Call) \
                                    and isinstance(c.func, ast.Name) \
                                    and c.func.id in params:
                                out.add(node.name)
    return out


def _wrapped_names(func: ast.AST, wrappers: frozenset[str]) -> set[str]:
    """Names of nested defs passed to worker_call (or to a known
    closure-forwarding wrapper) within ``func`` — positional or
    keyword, directly or invoked inside a worker_call lambda."""
    out: set[str] = set()
    for node in ast.walk(func):
        if isinstance(node, ast.Call):
            d = _dotted(node.func) or ""
            if d.split(".")[-1] not in ({_WRAPPER} | wrappers):
                continue
            for a in _call_args(node):
                if isinstance(a, ast.Name):
                    out.add(a.id)
                elif isinstance(a, ast.Lambda):
                    for c in ast.walk(a):
                        if isinstance(c, ast.Call) \
                                and isinstance(c.func, ast.Name):
                            out.add(c.func.id)
    return out


def _lambda_wrapped(module: ast.Module,
                    wrappers: frozenset[str]) -> set[ast.AST]:
    """All nodes inside lambdas that are worker_call (or known-wrapper)
    arguments."""
    covered: set[ast.AST] = set()
    for node in ast.walk(module):
        if isinstance(node, ast.Call):
            d = _dotted(node.func) or ""
            if d.split(".")[-1] in ({_WRAPPER} | wrappers):
                for a in _call_args(node):
                    if isinstance(a, ast.Lambda):
                        covered.update(ast.walk(a))
    return covered


def analyze(tree: SourceTree) -> list[Finding]:
    out: list[Finding] = []
    wrappers = frozenset(_forwarding_wrappers(tree))
    for mi in tree.modules.values():
        if not mi.name.startswith("cluster."):
            continue
        lambda_cov = _lambda_wrapped(mi.tree, wrappers)
        # map: every FunctionDef node -> its enclosing chain of defs
        chains: dict[ast.AST, list[ast.FunctionDef]] = {}

        def index(node: ast.AST, chain: list[ast.FunctionDef]) -> None:
            if isinstance(node, ast.FunctionDef):
                chain = chain + [node]
            for child in ast.iter_child_nodes(node):
                chains[child] = chain
                index(child, chain)

        chains[mi.tree] = []
        index(mi.tree, [])
        for node in ast.walk(mi.tree):
            if not isinstance(node, ast.Call):
                continue
            transport = _transport_call(node)
            if transport is None:
                continue
            if node in lambda_cov:
                continue
            chain = chains.get(node, [])
            covered = False
            qual_parts = [f.name for f in chain]
            if chain:
                inner = chain[-1]
                for encl in chain[:-1]:
                    if inner.name in _wrapped_names(encl, wrappers):
                        covered = True
                        break
            if covered:
                continue
            qual = f"{mi.name}." + ".".join(qual_parts or ["<module>"])
            out.append(Finding(
                "resilience",
                f"resilience:unwrapped:{qual}:{transport}",
                f"raw transport call {transport!r} in {qual} does not "
                f"flow through ClusterResilience.worker_call "
                f"(no breaker, no bounded retry)",
                mi.relpath, node.lineno))
    return out

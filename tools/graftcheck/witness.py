"""Runtime lockdep witness — the dynamic half of the lock-graph check.

The static lock graph (:mod:`tools.graftcheck.lockgraph`) is an
over-approximation built by resolution rules that can miss paths; a
runtime trace alone sees only the schedules that happened to run. Each
side validates the other:

- the witness instruments every ``threading.Lock``/``RLock``/
  ``Condition`` the *package* constructs while installed, records the
  actually-observed acquisition orders per thread, and
- :meth:`LockdepWitness.check` fails on a real **inversion** (both
  ``A→B`` and ``B→A`` observed — a schedule away from deadlock) and on
  any observed edge the static graph cannot explain (``A→B`` observed
  but ``B`` unreachable from ``A`` statically — the analyzer's
  resolution has a hole that must be fixed, not ignored).

Locks are named by their creation site: the static pass records every
``threading.Lock()`` call's (file, line) together with the lock's
graph name, and the instrumented constructor looks the caller's frame
up in that map — no cooperation from the instrumented code needed.

Scope: ``install()`` swaps a proxy ``threading`` module into every
already-imported ``tfidf_tpu`` module's namespace, so only locks the
package creates *after* install are instrumented (import-time
singletons like the metrics lock stay raw — they are leaf locks the
static graph already covers). TEST-ONLY by design: nothing under
``tfidf_tpu/`` imports this module, production paths always run raw
``threading`` primitives (see PERF.md).
"""

from __future__ import annotations

import os
import sys
import threading as _real_threading

from tools.graftcheck.core import SourceTree
from tools.graftcheck.lockgraph import LockGraph, build

_REPO_ROOT = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))


def _site_name(site_map: dict[tuple[str, int], str], depth: int = 2) -> str:
    f = sys._getframe(depth)
    path = f.f_code.co_filename.replace(os.sep, "/")
    idx = path.rfind("tfidf_tpu/")
    rel = path[idx:] if idx >= 0 else path
    return site_map.get((rel, f.f_lineno), f"{rel}:{f.f_lineno}")


class _InstrLock:
    """Delegating wrapper over a real lock primitive that reports
    acquisition/release to the witness. ``_depth`` tracks reentrancy
    (mutated only by the owning thread) so an RLock's re-acquire adds
    no ordering edges."""

    _factory = staticmethod(_real_threading.Lock)

    def __init__(self, witness: "LockdepWitness", name: str) -> None:
        self._w = witness
        self.name = name
        self._inner = self._factory()
        self._depth = 0

    def acquire(self, blocking: bool = True, timeout: float = -1):
        got = self._inner.acquire(blocking, timeout)
        if got:
            if self._depth == 0:
                self._w._on_acquire(self)
            self._depth += 1
        return got

    def release(self) -> None:
        self._depth -= 1
        if self._depth == 0:
            self._w._on_release(self)
        self._inner.release()

    def locked(self) -> bool:
        return self._inner.locked()

    __enter__ = acquire

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:
        return f"<witness lock {self.name}>"


class _InstrRLock(_InstrLock):
    _factory = staticmethod(_real_threading.RLock)

    # Condition(instrumented_rlock) support: the default Condition glue
    # only handles plain locks; an RLock must expose the save/restore
    # protocol — and OUR versions must keep the held-stack honest when
    # wait() fully releases and later re-acquires.

    def _release_save(self):
        depth, self._depth = self._depth, 0
        self._w._on_release(self)
        return self._inner._release_save(), depth

    def _acquire_restore(self, state) -> None:
        inner_state, depth = state
        self._inner._acquire_restore(inner_state)
        self._w._on_acquire(self)
        self._depth = depth

    def _is_owned(self) -> bool:
        return self._inner._is_owned()


class _ThreadingProxy:
    """A stand-in for the ``threading`` module inside package
    namespaces: Lock/RLock/Condition are instrumented, everything else
    delegates to the real module."""

    def __init__(self, witness: "LockdepWitness") -> None:
        self._w = witness

    def __getattr__(self, name: str):
        return getattr(_real_threading, name)

    def Lock(self):
        return _InstrLock(self._w, _site_name(self._w.site_map))

    def RLock(self):
        return _InstrRLock(self._w, _site_name(self._w.site_map))

    def Condition(self, lock=None):
        if lock is None:
            lock = _InstrRLock(self._w, _site_name(self._w.site_map))
        return _real_threading.Condition(lock)


class LockdepWitness:
    """Record real lock-acquisition orders and check them against the
    statically computed graph. Use as a context manager::

        with LockdepWitness() as w:
            ... drive the cluster ...
        w.check(min_multilock_edges=1)
    """

    def __init__(self, root: str = _REPO_ROOT,
                 graph: LockGraph | None = None) -> None:
        self.graph = graph or build(SourceTree(root))
        self.site_map = dict(self.graph.tree.lock_sites)
        self._tls = _real_threading.local()
        self._mu = _real_threading.Lock()   # guards edges/inversions
        # (outer_name, inner_name) -> observation count
        self.edges: dict[tuple[str, str], int] = {}
        self.inversions: list[tuple[str, str]] = []
        self._saved: dict[str, object] = {}
        self._installed = False

    # ---- bookkeeping (called from instrumented locks) ----

    def _stack(self) -> list:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def _on_acquire(self, lock: _InstrLock) -> None:
        st = self._stack()
        new_edges = []
        for held in st:
            if held.name != lock.name:
                new_edges.append((held.name, lock.name))
        st.append(lock)
        if not new_edges:
            return
        with self._mu:
            for e in new_edges:
                first = e not in self.edges
                self.edges[e] = self.edges.get(e, 0) + 1
                rev = (e[1], e[0])
                if first and rev in self.edges:
                    self.inversions.append(e)

    def _on_release(self, lock: _InstrLock) -> None:
        st = self._stack()
        for i in range(len(st) - 1, -1, -1):
            if st[i] is lock:
                del st[i]
                return

    # ---- install / uninstall ----

    def install(self) -> "LockdepWitness":
        """Swap the proxy ``threading`` into every imported tfidf_tpu
        module namespace. Locks constructed from here on are
        instrumented; pre-existing locks stay raw."""
        assert not self._installed
        proxy = _ThreadingProxy(self)
        for name, mod in list(sys.modules.items()):
            if mod is None or not (name == "tfidf_tpu"
                                   or name.startswith("tfidf_tpu.")):
                continue
            if mod.__dict__.get("threading") is _real_threading:
                self._saved[name] = mod.__dict__["threading"]
                mod.__dict__["threading"] = proxy
        self._installed = True
        return self

    def uninstall(self) -> None:
        for name, orig in self._saved.items():
            mod = sys.modules.get(name)
            if mod is not None:
                mod.__dict__["threading"] = orig
        self._saved.clear()
        self._installed = False

    def __enter__(self) -> "LockdepWitness":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.uninstall()

    # ---- verdict ----

    def multi_lock_edges(self) -> set[tuple[str, str]]:
        return set(self.edges)

    def unexplained_edges(self) -> list[tuple[str, str]]:
        """Observed orderings the static graph cannot explain (no
        static path outer→inner)."""
        return sorted(e for e in self.edges
                      if not self.graph.reachable(*e))

    def report(self) -> dict:
        return {
            "observed_edges": {f"{a} -> {b}": n
                               for (a, b), n in sorted(self.edges.items())},
            "inversions": [f"{a} -> {b} (reverse also observed)"
                           for a, b in self.inversions],
            "unexplained": [f"{a} -> {b}"
                            for a, b in self.unexplained_edges()],
        }

    def check(self, min_multilock_edges: int = 0) -> dict:
        """Raise AssertionError on any inversion or statically
        unexplained edge; optionally require that at least
        ``min_multilock_edges`` real multi-lock orderings were seen
        (guards against the witness silently observing nothing)."""
        rep = self.report()
        problems = []
        if self.inversions:
            problems.append(f"lock-order inversions: {rep['inversions']}")
        if rep["unexplained"]:
            problems.append(
                "orderings missing from the static lock graph "
                f"(fix the analyzer or the code): {rep['unexplained']}")
        if len(self.edges) < min_multilock_edges:
            problems.append(
                f"witness observed {len(self.edges)} multi-lock "
                f"ordering(s), expected >= {min_multilock_edges} — "
                f"instrumentation is not seeing the real workload")
        if problems:
            raise AssertionError("lockdep witness failed:\n  "
                                 + "\n  ".join(problems)
                                 + f"\n  report: {rep}")
        return rep
